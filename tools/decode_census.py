#!/usr/bin/env python
"""Decode census over Windows PE images (VERDICT r3 item 3).

Sweeps the function bodies (.pdata ranges) of 64-bit PE files through the
framework decoder and reports the undecodable fraction plus a histogram
of what's missing — the data that drives ISA-coverage priorities.

Usage: python tools/decode_census.py [PE paths...]
Defaults to the MSVC-compiled DLLs shipped inside the PyOpenGL wheel —
the only real Windows binaries guaranteed present on a dev box with this
repo's Python environment.
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from wtf_tpu.utils.pe import decode_census, load_pe  # noqa: E402

_DEFAULTS = [
    "/opt/venv/lib/python3.12/site-packages/OpenGL/DLLS/gle64.vc14.dll",
    "/opt/venv/lib/python3.12/site-packages/OpenGL/DLLS/freeglut64.vc14.dll",
    "/opt/venv/lib/python3.12/site-packages/OpenGL/DLLS/gle64.vc10.dll",
]


def main(argv):
    paths = argv[1:] or [p for p in _DEFAULTS if Path(p).exists()]
    if not paths:
        print("no PE files found; pass paths explicitly", file=sys.stderr)
        return 1
    for path in paths:
        pe = load_pe(path)
        if pe.machine != 0x8664:
            print(f"{Path(path).name}: skipped (not x86-64)")
            continue
        print(json.dumps(decode_census(pe)))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
