"""Regenerate the hand-assembled import stubs embedded in
wtf_tpu/harness/demo_pe.py (_STUBS).

Run from the repo root: python tools/gen_pe_stubs.py
Requires the test assembler helper (gas + objcopy).
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tests"))

from asmhelper import assemble  # noqa: E402

from wtf_tpu.harness.demo_pe import HEAP_STATE  # noqa: E402

STUBS = {
    # zero-return: the whole GL/GLU/kernel32/CRT surface
    "ret0": "xor eax, eax\nret",
    # sin/cos/atan2/acos: deterministic 0.0 (values don't matter to the
    # fuzzer; determinism and finiteness do)
    "fpzero": "xorps xmm0, xmm0\nret",
    # sqrt: the real thing (SSE2)
    "sqrt": "sqrtsd xmm0, xmm0\nret",
    # malloc(rcx) -> rax: 16-byte-aligned bump allocator over the HEAP
    # arena; the bump pointer lives at HEAP_STATE so overlay reset
    # rewinds the heap on restore
    "malloc": f"""
        mov r10, {HEAP_STATE}
        mov rax, [r10]
        lea rcx, [rcx + 15]
        and rcx, -16
        lea rdx, [rax + rcx]
        mov [r10], rdx
        ret
    """,
    # realloc(rcx=old, rdx=size): bump-alloc + copy `size` bytes from the
    # old block (reads stay inside the mapped arena; realloc(NULL) works)
    "realloc": f"""
        mov r10, {HEAP_STATE}
        mov rax, [r10]
        lea r8, [rdx + 15]
        and r8, -16
        lea r9, [rax + r8]
        mov [r10], r9
        mov r9, rdi
        mov r11, rsi
        mov rdi, rax
        mov rsi, rcx
        mov rcx, rdx
        test rsi, rsi
        jz done
        rep movsb
    done:
        mov rdi, r9
        mov rsi, r11
        ret
    """,
    # memset(rcx=dst, dl=val, r8=count) -> dst
    "memset": """
        mov r9, rdi
        mov r10, rcx
        mov rdi, rcx
        movzx eax, dl
        mov rcx, r8
        rep stosb
        mov rax, r10
        mov rdi, r9
        ret
    """,
}


def main() -> None:
    for name, asm in STUBS.items():
        code = assemble(asm)
        print(f'    "{name}": bytes.fromhex("{code.hex()}"),')


if __name__ == "__main__":
    main()
