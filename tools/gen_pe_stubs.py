"""Regenerate the hand-assembled import stubs embedded in
wtf_tpu/harness/demo_pe.py (_STUBS).

Run from the repo root: python tools/gen_pe_stubs.py
Requires the test assembler helper (gas + objcopy).
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tests"))

from asmhelper import assemble  # noqa: E402

from wtf_tpu.harness.demo_pe import (  # noqa: E402
    HEAP_BASE, HEAP_PAGES, HEAP_STATE,
)

HEAP_END = HEAP_BASE + HEAP_PAGES * 0x1000

STUBS = {
    # zero-return: the whole GL/GLU/kernel32/CRT surface
    "ret0": "xor eax, eax\nret",
    # sin/cos/atan2/acos: deterministic 0.0 (values don't matter to the
    # fuzzer; determinism and finiteness do)
    "fpzero": "xorps xmm0, xmm0\nret",
    # sqrt: the real thing (SSE2)
    "sqrt": "sqrtsd xmm0, xmm0\nret",
    # malloc(rcx) -> rax: 16-byte-aligned bump allocator over the HEAP
    # arena; the bump pointer lives at HEAP_STATE so overlay reset
    # rewinds the heap on restore.  BOUNDED to the arena: the RAW size is
    # checked against the arena size FIRST (so sizes like -1 cannot wrap
    # through the +15 alignment into a tiny allocation), then the bumped
    # end against HEAP_END — out-of-arena requests return NULL like a
    # real allocator under pressure, so huge mangled sizes surface as the
    # TARGET's NULL handling, not as harness-arena overruns misattributed
    # to gle64 (ADVICE r5).
    "malloc": f"""
        mov r10, {HEAP_STATE}
        mov rax, [r10]
        mov r11, {HEAP_END - HEAP_BASE}
        cmp rcx, r11
        ja fail
        lea rcx, [rcx + 15]
        and rcx, -16
        lea rdx, [rax + rcx]
        mov r11, {HEAP_END}
        cmp rdx, r11
        ja fail
        mov [r10], rdx
        ret
    fail:
        xor eax, eax
        ret
    """,
    # realloc(rcx=old, rdx=size): bump-alloc + copy `size` bytes from the
    # old block (reads stay inside the mapped arena; realloc(NULL) works).
    # Same raw-size + arena bounds as malloc: past-the-end growth returns
    # NULL and leaves the bump pointer (and the old block) untouched.
    "realloc": f"""
        mov r10, {HEAP_STATE}
        mov rax, [r10]
        mov r11, {HEAP_END - HEAP_BASE}
        cmp rdx, r11
        ja rfail
        lea r8, [rdx + 15]
        and r8, -16
        lea r9, [rax + r8]
        mov r11, {HEAP_END}
        cmp r9, r11
        ja rfail
        mov [r10], r9
        mov r9, rdi
        mov r11, rsi
        mov rdi, rax
        mov rsi, rcx
        mov rcx, rdx
        test rsi, rsi
        jz done
        rep movsb
    done:
        mov rdi, r9
        mov rsi, r11
        ret
    rfail:
        xor eax, eax
        ret
    """,
    # memset(rcx=dst, dl=val, r8=count) -> dst
    "memset": """
        mov r9, rdi
        mov r10, rcx
        mov rdi, rcx
        movzx eax, dl
        mov rcx, r8
        rep stosb
        mov rax, r10
        mov rdi, r9
        ret
    """,
}


def main() -> None:
    for name, asm in STUBS.items():
        code = assemble(asm)
        print(f'    "{name}": bytes.fromhex("{code.hex()}"),')


if __name__ == "__main__":
    main()
