#!/usr/bin/env python
"""Noise-aware perf-regression guard over the BENCH_r*.json trajectory.

The repo's bench rounds (BENCH_r01..rNN, PERF.md) are recorded on
cpu-shares-throttled CI containers where two back-to-back runs of the
SAME tree differ by ~25% (BENCH_r06's note documents 14.1s vs 18.3s for
chunk512) — a naive "slower than last round" gate would flap.  This
guard compares a fresh `python bench.py` JSON (or any BENCH_r file)
against the newest comparable checked-in round with that variance made
explicit:

  - a metric REGRESSES only when it worsens past the noise band
    (default ±25%); inside the band it's OK, past the band the *other*
    way it's an improvement
  - the overall verdict fails only on >= 2 regressed metrics, or one
    metric past the SQUARED band (beyond two stacked noise intervals —
    not explainable as container luck), or any exact-metric increase
  - deterministic metrics (the XLA kernel-count budget) get NO noise
    band: any increase is a real step-graph regression (same ratchet
    as `wtf-tpu lint --rebaseline`)

Usage:
  python tools/bench_guard.py <fresh.json>      # vs newest BENCH_r*
  python tools/bench_guard.py <fresh.json> --baseline BENCH_r07.json
  python tools/bench_guard.py --self-test       # guard logic on r06/r07
  options: --noise 0.25  --json

Exit 0 = no regression (or self-test pass), 1 = regression, 2 = usage /
no comparable metrics.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# direction per comparable metric; exact metrics ratchet with no band
LOWER_BETTER = {
    "micro.chunk512_wall_s",
    "micro.chunk_dispatch_floor_s",
    "megachunk.host_share_of_wall",
}
HIGHER_BETTER = {
    "micro.branchy_instr_per_s",
    "headline.execs_per_s",
    "fused.occupancy",
    "megachunk.execs_per_s",
    "devmut.device_execs_per_s",
    "fused_mega.kernel_reduction",
}
# counter-derived at equal seeds (fused_mega) or census pins: any
# increase is a real step-graph/dispatch regression, no noise excuse
EXACT = {"budget.xla_step_total", "budget.mega_window_total",
         "fused_mega.window_kernels",
         # jaxpr host-transfer census (wtf-tpu lint transfer family):
         # a +1 on any program is a hidden device->host sync in the
         # zero-host steady state — deterministic, zero noise excuse
         "transfer.megachunk_window_fused", "transfer.devmut_generate",
         "transfer.device_insert", "transfer.decode_service",
         "transfer.total"}

_CENSUS_KEYS = ("megachunk_window_fused", "devmut_generate",
                "device_insert", "decode_service", "total")

_MICRO_KEYS = ("branchy_instr_per_s", "chunk512_wall_s",
               "chunk_dispatch_floor_s")


def _num(value):
    return value if isinstance(value, (int, float)) \
        and not isinstance(value, bool) else None


def extract(doc: dict) -> dict:
    """Comparable {metric: value} rows from any bench shape the repo has
    produced: a raw bench.py line, the r02-r05 harness wrapper
    ({"parsed": ...}), or the hand-structured r06+ rounds."""
    out = {}
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    micro = doc.get("microbench") or \
        (doc.get("micro_compare") or {}).get("current") or {}
    for key in _MICRO_KEYS:
        value = _num(micro.get(key))
        if value is not None:
            out[f"micro.{key}"] = value
    if str(doc.get("unit", "")) == "execs/s":
        value = _num(doc.get("value"))
        if value is not None:
            out["headline.execs_per_s"] = value
    fused = doc.get("fused_compare") or {}
    occ = _num((fused.get("fused") or fused.get("fused_on") or {})
               .get("fused_occupancy"))
    if occ is not None:
        out["fused.occupancy"] = occ
    mega = (doc.get("megachunk_host_share") or {}).get("megachunk") or {}
    for src, dst in (("execs_per_s", "megachunk.execs_per_s"),
                     ("host_share_of_wall",
                      "megachunk.host_share_of_wall")):
        value = _num(mega.get(src))
        if value is not None:
            out[dst] = value
    devmut = (doc.get("devmut_ab") or {}).get("device") or {}
    value = _num(devmut.get("execs_per_s"))
    if value is not None:
        out["devmut.device_execs_per_s"] = value
    fm = doc.get("fused_mega") or {}
    value = _num((fm.get("fused") or {}).get("window_kernels"))
    if value is not None:
        out["fused_mega.window_kernels"] = value
    value = _num(fm.get("kernel_reduction"))
    if value is not None:
        out["fused_mega.kernel_reduction"] = value
    budget = doc.get("kernel_budget") or {}
    for src, dst in (("xla_step_total", "budget.xla_step_total"),
                     ("mega_window_total", "budget.mega_window_total")):
        value = _num(budget.get(src))
        if value is not None:
            out[dst] = value
    # host-transfer census rows: present in `wtf-tpu lint --json` output
    # (transfer family) and in bench rounds that embed it
    census = doc.get("transfer_census") or {}
    for src in _CENSUS_KEYS:
        value = _num(census.get(src))
        if value is not None:
            out[f"transfer.{src}"] = value
    return out


def compare(baseline: dict, fresh: dict, noise: float = 0.25) -> dict:
    """Per-metric verdicts over the shared keys + the overall verdict."""
    rows = {}
    regressed = []
    hard = []
    for name in sorted(set(baseline) & set(fresh)):
        base, cur = baseline[name], fresh[name]
        if name in EXACT:
            verdict = "regressed" if cur > base else (
                "improved" if cur < base else "ok")
            if verdict == "regressed":
                regressed.append(name)
                hard.append(name)  # deterministic: no noise excuse
            rows[name] = {"baseline": base, "current": cur,
                          "verdict": verdict, "exact": True}
            continue
        ratio = cur / base if base else float("inf")
        worse = ratio > 1.0 + noise if name in LOWER_BETTER \
            else ratio < 1.0 - noise
        better = ratio < 1.0 - noise if name in LOWER_BETTER \
            else ratio > 1.0 + noise
        far = ratio > (1.0 + noise) ** 2 if name in LOWER_BETTER \
            else ratio < (1.0 - noise) ** 2
        verdict = "regressed" if worse else (
            "improved" if better else "ok")
        if worse:
            regressed.append(name)
            if far:
                hard.append(name)
        rows[name] = {"baseline": base, "current": cur,
                      "ratio": round(ratio, 4), "verdict": verdict}
    fail = len(regressed) >= 2 or bool(hard)
    return {"noise": noise, "metrics": rows, "regressed": regressed,
            "hard_regressions": hard, "compared": len(rows),
            "fail": fail}


def trajectory(baseline_path=None):
    """(path, comparable rows) of the chosen baseline round: explicit
    --baseline, else the newest BENCH_r* that yields >= 1 row."""
    if baseline_path is not None:
        path = Path(baseline_path)
        return path, extract(json.loads(path.read_text()))
    rounds = sorted(
        REPO.glob("BENCH_r*.json"),
        key=lambda p: int(re.sub(r"\D", "", p.stem) or 0), reverse=True)
    for path in rounds:
        rows = extract(json.loads(path.read_text()))
        if rows:
            return path, rows
    return None, {}


def self_test(noise: float) -> dict:
    """The guard's own invariants, on the checked-in r06/r07 pair:
    extraction finds the known metric rows, the real r06->r07 movement
    produces no hard regression, and a synthetic 2x worsening of every
    shared metric IS flagged."""
    r06 = extract(json.loads((REPO / "BENCH_r06.json").read_text()))
    r07 = extract(json.loads((REPO / "BENCH_r07.json").read_text()))
    assert {"micro.branchy_instr_per_s", "micro.chunk512_wall_s",
            "fused.occupancy",
            "devmut.device_execs_per_s"} <= set(r06), \
        f"r06 extraction incomplete: {sorted(r06)}"
    assert {"fused.occupancy", "megachunk.execs_per_s",
            "megachunk.host_share_of_wall",
            "budget.xla_step_total"} <= set(r07), \
        f"r07 extraction incomplete: {sorted(r07)}"
    real = compare(r06, r07, noise)
    assert real["compared"] >= 1, "r06/r07 share no comparable metric"
    assert not real["hard_regressions"], \
        (f"checked-in trajectory reads as a hard regression: "
         f"{real['hard_regressions']} — the guard would flap on CI")
    # r08's fused-megachunk shape: the exact window-kernel ratchet rows
    # extract, and the checked-in r07->r08 step compares clean
    r08 = extract(json.loads((REPO / "BENCH_r08.json").read_text()))
    assert {"fused_mega.window_kernels", "fused_mega.kernel_reduction",
            "budget.mega_window_total",
            "budget.xla_step_total"} <= set(r08), \
        f"r08 extraction incomplete: {sorted(r08)}"
    real8 = compare(r07, r08, noise)
    assert real8["compared"] >= 1, "r07/r08 share no comparable metric"
    assert not real8["fail"], \
        (f"checked-in r07->r08 step reads as a regression: "
         f"{real8['regressed']}")
    bad = {}
    for name, value in r07.items():
        if name in EXACT:
            bad[name] = value + 1
        elif name in LOWER_BETTER:
            bad[name] = value * 2.0
        else:
            bad[name] = value / 2.0
    synthetic = compare(r07, bad, noise)
    assert synthetic["fail"], "synthetic 2x regression was NOT flagged"
    assert set(synthetic["regressed"]) == set(bad), \
        f"synthetic regression missed: {synthetic['regressed']}"
    # the window-kernel ratchet: ONE extra kernel in the fused window
    # must fail the guard outright (exact rows have no noise band)
    crept = dict(r08)
    crept["fused_mega.window_kernels"] += 1
    ratchet = compare(r08, crept, noise)
    assert ratchet["fail"] and \
        "fused_mega.window_kernels" in ratchet["hard_regressions"], \
        "a +1 window-kernel creep was NOT flagged as a hard regression"
    # the transfer-census ratchet: rows extract from lint-shaped docs
    # and a single extra host transfer is a hard regression
    lint_doc = {"transfer_census": {
        "megachunk_window_fused": 5, "devmut_generate": 2,
        "device_insert": 0, "decode_service": 0, "total": 7}}
    census = extract(lint_doc)
    assert {"transfer.megachunk_window_fused", "transfer.total"} <= \
        set(census), f"census extraction incomplete: {sorted(census)}"
    leaked = dict(census)
    leaked["transfer.megachunk_window_fused"] += 1
    leaked["transfer.total"] += 1
    tguard = compare(census, leaked, noise)
    assert tguard["fail"] and \
        "transfer.total" in tguard["hard_regressions"], \
        "a +1 host-transfer creep was NOT flagged as a hard regression"
    return {"real": real, "synthetic_flagged": synthetic["regressed"]}


def _print_report(report: dict, base_path, fresh_path) -> None:
    print(f"bench-guard: {fresh_path} vs {base_path} "
          f"(noise band ±{report['noise'] * 100:.0f}%)")
    for name, row in report["metrics"].items():
        ratio = f" ({row['ratio']}x)" if "ratio" in row else " (exact)"
        print(f"  {row['verdict']:<10} {name:<32} "
              f"{row['baseline']} -> {row['current']}{ratio}")
    if report["fail"]:
        print(f"bench-guard FAIL: {len(report['regressed'])} "
              f"regressed ({', '.join(report['regressed'])}; "
              f"hard: {', '.join(report['hard_regressions']) or '-'})")
    else:
        print(f"bench-guard OK: {report['compared']} metric(s) "
              f"compared, {len(report['regressed'])} inside-band "
              f"regression(s)")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="bench_guard", description=__doc__.splitlines()[0])
    parser.add_argument("fresh", nargs="?", type=Path,
                        help="fresh bench.py JSON (file with the "
                             "bench line or a BENCH_r-shaped doc)")
    parser.add_argument("--baseline", type=Path, default=None)
    parser.add_argument("--noise", type=float, default=0.25)
    parser.add_argument("--self-test", action="store_true")
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)

    if args.self_test:
        result = self_test(args.noise)
        if args.json:
            print(json.dumps(result))
        else:
            print(f"bench-guard self-test PASS "
                  f"({result['real']['compared']} r06/r07 metric(s) "
                  f"compared clean; synthetic regression flagged on "
                  f"{len(result['synthetic_flagged'])} metric(s))")
        return 0
    if args.fresh is None:
        parser.print_usage(sys.stderr)
        return 2
    text = args.fresh.read_text()
    try:
        fresh_doc = json.loads(text)
    except ValueError:
        # bench.py streams log lines before the one JSON line: take the
        # last parseable line (same posture as the r02-r05 harness)
        fresh_doc = None
        for line in reversed(text.splitlines()):
            try:
                fresh_doc = json.loads(line)
                break
            except ValueError:
                continue
        if fresh_doc is None:
            print(f"bench-guard: no JSON in {args.fresh}",
                  file=sys.stderr)
            return 2
    fresh = extract(fresh_doc)
    base_path, baseline = trajectory(args.baseline)
    if not fresh or not baseline:
        print("bench-guard: no comparable metrics "
              f"(fresh: {sorted(fresh)}, baseline: {sorted(baseline)})",
              file=sys.stderr)
        return 2
    report = compare(baseline, fresh, args.noise)
    if not report["compared"]:
        print("bench-guard: fresh and baseline share no metric",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({"baseline": str(base_path), **report}))
    else:
        _print_report(report, base_path, args.fresh)
    return 1 if report["fail"] else 0


if __name__ == "__main__":
    sys.exit(main())
