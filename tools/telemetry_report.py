#!/usr/bin/env python
"""Summarize a telemetry events.jsonl (the --telemetry-dir stream).

Answers the operator questions the raw stream buries:
  - where did the wall-clock go?  per-phase span totals (top-level phases
    accounted against wall-clock, nested phases shown as a breakdown)
  - how fast was it?  testcases/s from the campaign counters
  - why did lanes leave the device?  fallback rate per opclass
  - what did the device itself count?  instructions retired / memory
    faults / decode misses from the in-graph counter block
  - what happened?  event census (crashes, new coverage, errors)

Usage: python tools/telemetry_report.py <events.jsonl | telemetry dir> [--json]

Exit status 1 when the file holds no usable records.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from wtf_tpu.telemetry.events import read_events  # noqa: E402
from wtf_tpu.telemetry.spans import DEVICE_SPAN_LEAVES  # noqa: E402,F401


def wall_breakdown(phase_seconds: dict) -> dict:
    """Host-busy vs device-busy split of the top-level phases: for each
    phase, device seconds = the fenced device spans nested under it,
    host seconds = the remainder.  This is what makes the devmut
    double-buffer claim measurable from an events.jsonl — with
    mutate-on-device, `mutate.host_seconds` collapses to dispatch
    overhead and the generation wait shows under mutate/device."""
    top = {name: secs for name, secs in phase_seconds.items()
           if "/" not in name}
    device_by_top: dict = {}
    for path, secs in phase_seconds.items():
        parts = path.split("/")
        if len(parts) > 1 and parts[-1] in DEVICE_SPAN_LEAVES:
            device_by_top[parts[0]] = device_by_top.get(parts[0], 0.0) + secs
    by_phase = {}
    host_total = device_total = 0.0
    for name, secs in sorted(top.items(), key=lambda kv: -kv[1]):
        dev = min(device_by_top.get(name, 0.0), secs)
        by_phase[name] = {"seconds": round(secs, 4),
                          "device_seconds": round(dev, 4),
                          "host_seconds": round(secs - dev, 4)}
        host_total += secs - dev
        device_total += dev
    return {
        "host_busy_seconds": round(host_total, 4),
        "device_busy_seconds": round(device_total, 4),
        "by_phase": by_phase,
    }


def summarize(path) -> dict:
    """Machine-readable summary of one events.jsonl."""
    path = Path(path)
    if path.is_dir():
        path = path / "events.jsonl"
    records = list(read_events(path))
    if not records:
        return {"error": f"no records in {path}"}

    # EventLog appends, so re-running with the same --telemetry-dir stacks
    # runs in one file.  Metrics dumps are per-run (the registry is fresh
    # each invocation), so summarize the LATEST run: slice at its
    # run-start, or wall-clock/rates would span the gap between runs.
    starts = [i for i, r in enumerate(records) if r["type"] == "run-start"]
    runs_in_file = max(len(starts), 1)
    if starts:
        records = records[starts[-1]:]

    first_ts = records[0]["ts"]
    last_ts = records[-1]["ts"]
    wall = max(last_ts - first_ts, 0.0)

    # the freshest full metrics dump (run-end normally; the last
    # heartbeat when the run was killed)
    metrics = {}
    for rec in reversed(records):
        if "metrics" in rec:
            metrics = rec["metrics"]
            break

    by_type: dict = {}
    crashes: dict = {}
    errors = []
    compiles_by_shape: dict = {}
    checkpoint_bytes: list = []
    checkpoint_secs: list = []
    for rec in records:
        by_type[rec["type"]] = by_type.get(rec["type"], 0) + 1
        if rec["type"] == "crash" and rec.get("name"):
            crashes[rec["name"]] = crashes.get(rec["name"], 0) + 1
        elif rec["type"] == "error":
            errors.append({k: rec.get(k) for k in ("kind", "detail")})
        elif rec["type"] == "checkpoint":
            checkpoint_bytes.append(rec.get("bytes", 0))
            checkpoint_secs.append(rec.get("seconds", 0.0))
        elif rec["type"] == "compile":
            # one executor "shape" = the compile event's own payload
            # (chunk_steps/donate/kind/...) minus the stream bookkeeping
            shape = ",".join(
                f"{k}={rec[k]}" for k in sorted(rec)
                if k not in ("ts", "seq", "type"))
            compiles_by_shape[shape] = compiles_by_shape.get(shape, 0) + 1
    # >1 compile for one executor shape means the jit cache churned (a
    # weak-type/python-scalar signature split, or an executor rebuilt
    # past the process-global dispatch dedup) — wall-clock silently lost
    compile_shape_churn = {shape: n for shape, n in compiles_by_shape.items()
                           if n > 1}

    phase_seconds = metrics.get("phase.seconds", {}) or {}
    if not isinstance(phase_seconds, dict):
        phase_seconds = {}
    top = {name: secs for name, secs in phase_seconds.items()
           if "/" not in name}
    top_total = sum(top.values())
    phases = {
        name: {"seconds": round(secs, 4),
               "share_of_wall": round(secs / wall, 4) if wall else None}
        for name, secs in sorted(top.items(), key=lambda kv: -kv[1])
    }
    nested = {name: round(secs, 4)
              for name, secs in sorted(phase_seconds.items())
              if "/" in name}
    breakdown = wall_breakdown(phase_seconds)

    # mesh campaigns (wtf_tpu/meshrun): per-shard device counters next to
    # the merged view — the operator's straggling/cold-chip check is
    # "do the shards sum to the merged counter, and are they balanced"
    mesh = None
    if metrics.get("mesh.devices"):
        shard_instr = metrics.get("device.shard_instructions", {})
        if not isinstance(shard_instr, dict):
            shard_instr = {}
        per_shard = dict(sorted(shard_instr.items(),
                                key=lambda kv: int(kv[0])))
        mesh = {
            "devices": metrics.get("mesh.devices"),
            "lanes_per_shard": metrics.get("mesh.lanes_per_shard"),
            "shard_instructions": per_shard,
            "shard_instructions_sum": sum(per_shard.values()),
            "merged_instructions": metrics.get("device.instructions", 0),
        }

    # triage (wtf_tpu/triage): candidate volume and what it bought —
    # dispatches per minimization, bytes removed, minset before/after.
    # None when the run did no triage work.
    triage = None
    tri_signals = {
        "candidates": metrics.get("triage.candidates", 0) or 0,
        "dispatches": metrics.get("triage.dispatches", 0) or 0,
        "crashes_replayed": metrics.get("triage.crashes", 0) or 0,
        "minimizations": metrics.get("triage.minimizations", 0) or 0,
        "minimize_rounds": metrics.get("triage.minimize_rounds", 0) or 0,
        "bytes_removed": metrics.get("triage.bytes_removed", 0) or 0,
        "minset_before": metrics.get("triage.minset_before", 0) or 0,
        "minset_after": metrics.get("triage.minset_after", 0) or 0,
        "captures": metrics.get("triage.captures", 0) or 0,
    }
    if any(tri_signals.values()):
        triage = dict(tri_signals)
        if tri_signals["minimizations"]:
            triage["dispatches_per_minimization"] = round(
                tri_signals["dispatches"] / tri_signals["minimizations"], 2)
        if wall and tri_signals["candidates"]:
            triage["candidates_per_s"] = round(
                tri_signals["candidates"] / wall, 2)

    # multi-tenant campaigns (wtf_tpu/tenancy): one block per tenant off
    # the `tenant.<name>.*` namespaces — execs/s, new-coverage, crash
    # buckets (from tenant-tagged crash events), lane-seconds — plus the
    # scheduler's round/preemption census.  None when the run had no
    # tenants.
    tenants = None
    tenant_names = sorted({name.split(".")[1] for name in metrics
                           if name.startswith("tenant.")
                           and len(name.split(".")) >= 3})
    if tenant_names:
        buckets_by_tenant: dict = {}
        for rec in records:
            if rec["type"] == "crash" and rec.get("tenant"):
                b = buckets_by_tenant.setdefault(rec["tenant"], {})
                key = rec.get("bucket") or rec.get("name") or "?"
                b[key] = b.get(key, 0) + 1
        per_tenant = {}
        for name in tenant_names:
            def tm(field, default=0):
                return metrics.get(f"tenant.{name}.{field}", default) or 0

            execs = tm("testcases")
            per_tenant[name] = {
                "testcases": execs,
                "testcases_per_s": (round(execs / wall, 2)
                                    if wall else None),
                "crashes": tm("crashes"),
                "crash_buckets": dict(sorted(
                    buckets_by_tenant.get(name, {}).items())),
                "new_coverage": tm("new_coverage"),
                "timeouts": tm("timeouts"),
                "batches": tm("batches"),
                "lane_seconds": round(tm("lane_ms") / 1000.0, 3),
                "checkpoints": tm("checkpoints"),
                "resumes": tm("resumes"),
            }
        tenants = {"by_tenant": per_tenant}
        sched = {key: metrics.get(f"sched.{key}", 0) or 0
                 for key in ("rounds", "placements", "preemptions",
                             "completions")}
        if any(sched.values()):
            tenants["sched"] = sched

    # resilience (fault-tolerance tier): reconnect/reclaim/resume
    # activity + checkpoint cadence and cost.  None when the run had no
    # fault-tolerance signal at all — quiet campaigns stay quiet.
    resilience = None
    res_signals = {
        "retries": metrics.get("dist.retries", 0) or 0,
        "reconnects": by_type.get("reconnect", 0),
        "reclaimed_testcases": metrics.get("dist.reclaimed", 0) or 0,
        "resumes": metrics.get("campaign.resumes", 0) or 0,
        "checkpoints": metrics.get("campaign.checkpoints", 0) or 0,
        "drains": by_type.get("drain", 0),
    }
    if any(res_signals.values()) or checkpoint_bytes:
        phase_secs = metrics.get("phase.seconds", {}) or {}
        resilience = dict(res_signals)
        resilience["checkpoint_seconds_total"] = round(
            phase_secs.get("checkpoint", 0.0)
            if isinstance(phase_secs, dict) else 0.0, 4)
        if checkpoint_bytes:
            resilience["checkpoint_last_bytes"] = checkpoint_bytes[-1]
            resilience["checkpoint_mean_seconds"] = round(
                sum(checkpoint_secs) / len(checkpoint_secs), 4)

    # device resilience (wtf_tpu/supervise): what the self-healing
    # runtime did — watchdog fires, device errors, backend rebuilds,
    # batch replays, ladder movement, quarantined lanes — plus what the
    # always-on machinery cost (snapshot + integrity + recovery span
    # seconds against wall).  None when the run was unsupervised.
    device_res = None
    sup_signals = {
        "supervised_dispatches": metrics.get("supervise.dispatches", 0) or 0,
        "watchdog_fires": metrics.get("supervise.watchdog_fires", 0) or 0,
        "device_errors": metrics.get("supervise.device_errors", 0) or 0,
        "rebuilds": metrics.get("supervise.rebuilds", 0) or 0,
        "batch_retries": metrics.get("supervise.batch_retries", 0) or 0,
        "degradations": metrics.get("supervise.degradations", 0) or 0,
        "promotions": metrics.get("supervise.promotions", 0) or 0,
        "poisoned_lanes": metrics.get("supervise.poisoned_lanes", 0) or 0,
        "quarantined_total": metrics.get("device.quarantined", 0) or 0,
        "integrity_checks": metrics.get("supervise.integrity_checks",
                                        0) or 0,
    }
    if any(sup_signals.values()):
        device_res = dict(sup_signals)
        # gauges: final rung index (0 = full speed) and lanes still
        # quarantined at dump time (vs the lifetime quarantined_total)
        device_res["final_rung"] = metrics.get("supervise.rung", 0) or 0
        device_res["quarantined_now"] = metrics.get(
            "supervise.quarantined_lanes", 0) or 0
        # supervisor cost: snapshot/integrity/recover spans wherever they
        # nest in the phase tree.  overhead_share folds in only the
        # steady-state legs (snapshot + integrity); recovery seconds are
        # fault-path work and reported separately.
        sup_leaves = {"integrity": 0.0, "supervise-snapshot": 0.0,
                      "supervise-recover": 0.0}
        for span_path, secs in phase_seconds.items():
            leaf = span_path.split("/")[-1]
            if leaf in sup_leaves:
                sup_leaves[leaf] += secs
        device_res["integrity_seconds"] = round(sup_leaves["integrity"], 4)
        device_res["snapshot_seconds"] = round(
            sup_leaves["supervise-snapshot"], 4)
        device_res["recover_seconds"] = round(
            sup_leaves["supervise-recover"], 4)
        steady = sup_leaves["integrity"] + sup_leaves["supervise-snapshot"]
        device_res["overhead_share"] = (round(steady / wall, 4)
                                        if wall else None)

    # fleet (distribution tier): streaming-delta wire savings, store
    # dedup activity, crash bucket-dedup rate, elastic reshards.  None
    # when the run produced no fleet signal.
    fleet = None
    delta_bytes = metrics.get("dist.cov_bytes_delta", 0) or 0
    bitmap_bytes = metrics.get("dist.cov_bytes_bitmap", 0) or 0
    fleet_signals = {
        "delta_frames": metrics.get("fleet.delta_frames", 0) or 0,
        "full_resyncs": metrics.get("fleet.full_resyncs", 0) or 0,
        "cursor_resumes": metrics.get("fleet.cursor_resumes", 0) or 0,
        "coverage_writes": metrics.get("fleet.coverage_writes", 0) or 0,
        "store_puts": metrics.get("fleet.store_puts", 0) or 0,
        "store_dedup_hits": metrics.get("fleet.store_dedup", 0) or 0,
        "bucket_dedup_hits": metrics.get("fleet.bucket_dedup", 0) or 0,
        "reshards": metrics.get("campaign.reshards", 0) or 0,
    }
    if any(fleet_signals.values()) or delta_bytes:
        fleet = dict(fleet_signals)
        fleet["cov_bytes_delta"] = delta_bytes
        fleet["cov_bytes_bitmap_equiv"] = bitmap_bytes
        fleet["cov_bytes_saved"] = max(bitmap_bytes - delta_bytes, 0)
        fleet["delta_ratio"] = (round(bitmap_bytes / delta_bytes, 1)
                                if delta_bytes else None)
        crashes_seen = ((metrics.get("campaign.crashes", 0) or 0)
                        or fleet["bucket_dedup_hits"])
        fleet["bucket_dedup_rate"] = (
            round(fleet["bucket_dedup_hits"] / crashes_seen, 4)
            if crashes_seen else None)

    # device-resident decode (interp/devdec): the zero-host steady
    # state.  published = entries the in-graph decoder committed,
    # cross-checked entry-by-entry against the host oracle at harvest
    # (mismatches MUST read 0 — any other value is a decoder bug, not
    # noise).  zero-host windows are megachunk windows that completed
    # without a single host decode service; their mean length in batches
    # says how long the device runs untouched.  harvest_overlap_share is
    # the fraction of windows whose successor was speculatively
    # prelaunched AND adopted, i.e. readback hidden behind execution.
    # None when the run never exercised device decode.
    devdecode = None
    dd_signals = {
        "published": metrics.get("devdec.published", 0) or 0,
        "serviced_lanes": metrics.get("devdec.serviced_lanes", 0) or 0,
        "parked_lanes": metrics.get("devdec.parked_lanes", 0) or 0,
        "service_rounds": metrics.get("devdec.service_rounds", 0) or 0,
        "zero_host_windows": metrics.get("devdec.zero_host_windows",
                                         0) or 0,
        "zero_host_batches": metrics.get("devdec.zero_host_batches",
                                         0) or 0,
    }
    if any(dd_signals.values()):
        devdecode = dict(dd_signals)
        devdecode["crosscheck_mismatches"] = metrics.get(
            "devdec.crosscheck_mismatches", 0) or 0
        # host decode services that still happened (parked encodings
        # serviced in-order by the authoritative host decoder); 0 is the
        # acceptance target for the demo workloads
        devdecode["host_decode_services"] = metrics.get(
            "runner.decodes", 0) or 0
        devdecode["zero_host_mean_batches"] = (
            round(devdecode["zero_host_batches"]
                  / devdecode["zero_host_windows"], 1)
            if devdecode["zero_host_windows"] else None)
        mega_windows = metrics.get("megachunk.windows", 0) or 0
        devdecode["windows"] = mega_windows
        devdecode["prelaunched"] = metrics.get("megachunk.prelaunched",
                                               0) or 0
        devdecode["prelaunch_hits"] = metrics.get(
            "megachunk.prelaunch_hits", 0) or 0
        devdecode["prelaunch_dropped"] = metrics.get(
            "megachunk.prelaunch_dropped", 0) or 0
        devdecode["harvest_overlap_share"] = (
            round(devdecode["prelaunch_hits"] / mega_windows, 4)
            if mega_windows else None)
        # the PR-14 steady-state headline, as one number (also live on
        # the heartbeat line as `zh:` and in `wtf-tpu status`)
        devdecode["zero_host_window_rate"] = (
            round(devdecode["zero_host_windows"] / mega_windows, 4)
            if mega_windows else None)

    testcases = metrics.get("campaign.testcases", 0) or 0
    fallbacks = metrics.get("runner.fallbacks_by_opclass", {})
    if not isinstance(fallbacks, dict):
        fallbacks = {}
    # without a testcase counter (run-subcommand streams) the values are
    # raw counts, and fallback_rate_unit says so — never pass counts off
    # as per-testcase rates
    fallback_rate_unit = "per-testcase" if testcases else "raw-count"
    fallback_rate = {
        opclass: round(count / testcases, 4) if testcases else count
        for opclass, count in sorted(fallbacks.items(), key=lambda kv: -kv[1])
    }

    return {
        "path": str(path),
        "records": len(records),
        "runs_in_file": runs_in_file,
        "events_by_type": by_type,
        "wall_seconds": round(wall, 3),
        "phases": phases,
        "phase_accounted_frac": round(top_total / wall, 4) if wall else None,
        "nested_phases": nested,
        "wall_breakdown": breakdown,
        "testcases": testcases,
        "testcases_per_s": round(testcases / wall, 2) if wall else None,
        "compiles": {"total": sum(compiles_by_shape.values()),
                     "by_shape": dict(sorted(compiles_by_shape.items()))},
        "compile_shape_churn": dict(sorted(compile_shape_churn.items())),
        "crashes": metrics.get("campaign.crashes", 0),
        "crash_names": crashes,
        "new_coverage": metrics.get("campaign.new_coverage", 0),
        "fallbacks": metrics.get("runner.fallbacks", 0),
        "fallback_rate_unit": fallback_rate_unit,
        "fallback_rate_per_opclass": fallback_rate,
        "device": {
            "instructions": metrics.get("device.instructions", 0),
            "mem_faults": metrics.get("device.mem_faults", 0),
            "decode_misses": metrics.get("device.decode_misses", 0),
            "fused_steps": metrics.get("device.fused_steps", 0),
            # fraction of retired instructions executed inside the fused
            # Pallas kernel (interp/pstep.py); null when the fast path
            # never ran, so 0% occupancy can't be confused with "off".
            # "ran" is detected by the pallas-step span, not the counter:
            # a fused campaign whose every lane parks each round must
            # read as the actionable 0.0, not as null
            "fused_occupancy": (
                round(metrics.get("device.fused_steps", 0)
                      / metrics["device.instructions"], 4)
                if metrics.get("device.instructions")
                and (metrics.get("device.fused_steps", 0) > 0
                     or any(path.split("/")[-1] == "pallas-step"
                            for path in (metrics.get("phase.seconds")
                                         or {})))
                else None),
            # WHY lanes left the kernel (interp/pstep.py park split):
            # subset = cold opclass / armed bp / SMC-risk code window,
            # mem = failing/unwritable walk or overlay-slot exhaustion
            # mid-window — one opaque number used to hide the reason
            "fused_park_subset": metrics.get("device.fused_park_subset",
                                             0),
            "fused_park_mem": metrics.get("device.fused_park_mem", 0),
            # fused megachunk windows (fuzz/megachunk.py fused=True):
            # in-window quiesce dispatch split — Pallas kernel rounds vs
            # XLA ladder/resume sweeps — and the machine/overlay bytes
            # donation kept from copying through the kernel per dispatch
            "fused_window_rounds": metrics.get(
                "device.fused_window_rounds", 0),
            "fused_window_xla_steps": metrics.get(
                "device.fused_window_xla_steps", 0),
            "fused_window_share": (
                round(metrics.get("device.fused_window_rounds", 0)
                      / (metrics.get("device.fused_window_rounds", 0)
                         + metrics.get("device.fused_window_xla_steps",
                                       0)), 4)
                if metrics.get("device.fused_window_rounds", 0) else None),
            "fused_window_bytes_saved": metrics.get(
                "device.fused_window_bytes_saved", 0),
        },
        "mesh": mesh,
        "triage": triage,
        "tenants": tenants,
        "resilience": resilience,
        "device_resilience": device_res,
        "fleet": fleet,
        "device_decode": devdecode,
        "errors": errors,
    }


def _print_human(s: dict) -> None:
    extra = (f" (latest of {s['runs_in_file']} runs in file)"
             if s["runs_in_file"] > 1 else "")
    print(f"{s['path']}: {s['records']} records over "
          f"{s['wall_seconds']}s{extra}")
    print(f"events: " + ", ".join(
        f"{t}={n}" for t, n in sorted(s["events_by_type"].items())))
    if s["phases"]:
        acct = s["phase_accounted_frac"]
        print(f"phases (top-level, "
              f"{acct * 100:.1f}% of wall accounted):" if acct is not None
              else "phases:")
        for name, d in s["phases"].items():
            share = (f" ({d['share_of_wall'] * 100:5.1f}%)"
                     if d["share_of_wall"] is not None else "")
            print(f"  {name:<16} {d['seconds']:>10.3f}s{share}")
        for name, secs in s["nested_phases"].items():
            print(f"    {name:<24} {secs:>8.3f}s")
    wb = s.get("wall_breakdown") or {}
    if wb.get("by_phase"):
        print(f"host-busy vs device-busy: "
              f"{wb['host_busy_seconds']}s host / "
              f"{wb['device_busy_seconds']}s device")
        for name, d in wb["by_phase"].items():
            print(f"  {name:<16} host {d['host_seconds']:>9.3f}s  "
                  f"device {d['device_seconds']:>9.3f}s")
    print(f"testcases: {s['testcases']}"
          + (f" ({s['testcases_per_s']}/s)" if s["testcases_per_s"] else ""))
    if s["compiles"]["total"]:
        print(f"compiles: {s['compiles']['total']} executor(s)")
        for shape, n in s["compiles"]["by_shape"].items():
            print(f"  {shape} x{n}")
        for shape, n in s["compile_shape_churn"].items():
            print(f"  warning: shape-churn — {shape} compiled {n}x "
                  f"(expected 1 per executor shape)")
    print(f"crashes: {s['crashes']} new-coverage: {s['new_coverage']}")
    if s["crash_names"]:
        for name, n in sorted(s["crash_names"].items()):
            print(f"  {name} x{n}")
    if s["fallback_rate_per_opclass"]:
        label = ("fallback rate per opclass (fallbacks/testcase):"
                 if s["fallback_rate_unit"] == "per-testcase"
                 else "fallbacks per opclass (raw counts — no testcase "
                      "counter in this stream):")
        print(label)
        for opclass, rate in s["fallback_rate_per_opclass"].items():
            print(f"  {opclass:<12} {rate}")
    dev = s["device"]
    fused = (f" fused_steps={dev['fused_steps']}"
             f" (occupancy {dev['fused_occupancy'] * 100:.1f}%; parks "
             f"subset={dev.get('fused_park_subset', 0)} "
             f"mem={dev.get('fused_park_mem', 0)})"
             if dev.get("fused_occupancy") is not None else "")
    print(f"device counters: instructions={dev['instructions']} "
          f"mem_faults={dev['mem_faults']} "
          f"decode_misses={dev['decode_misses']}{fused}")
    if dev.get("fused_window_share") is not None:
        total = (dev["fused_window_rounds"]
                 + dev["fused_window_xla_steps"])
        print(f"  fused windows: {dev['fused_window_share'] * 100:.1f}% "
              f"of {total} quiesce dispatches in-kernel "
              f"({dev['fused_window_rounds']} pallas, "
              f"{dev['fused_window_xla_steps']} ladder sweeps)")
        saved = dev.get("fused_window_bytes_saved", 0)
        if saved:
            print(f"  donation: {saved / (1 << 20):.1f} MiB "
                  f"copy-through saved "
                  f"({saved // max(dev['fused_window_rounds'], 1)} "
                  f"B/dispatch)")
    mesh = s.get("mesh")
    if mesh:
        print(f"mesh: {mesh['devices']} devices x "
              f"{mesh['lanes_per_shard']} lanes/shard")
        shards = mesh["shard_instructions"]
        if shards:
            per = " ".join(f"{k}={v}" for k, v in shards.items())
            agree = ("" if mesh["shard_instructions_sum"]
                     == mesh["merged_instructions"]
                     else f" (merged view {mesh['merged_instructions']} "
                          "DISAGREES)")
            print(f"  per-shard instructions: {per} "
                  f"(sum {mesh['shard_instructions_sum']}{agree})")
    tri = s.get("triage")
    if tri:
        per_min = (f" ({tri['dispatches_per_minimization']} "
                   "dispatches/minimization)"
                   if "dispatches_per_minimization" in tri else "")
        rate = (f" ({tri['candidates_per_s']}/s)"
                if "candidates_per_s" in tri else "")
        print(f"triage: candidates={tri['candidates']}{rate} "
              f"dispatches={tri['dispatches']}{per_min} "
              f"crashes={tri['crashes_replayed']}")
        if tri["minimizations"]:
            print(f"  minimize: {tri['minimizations']} run(s), "
                  f"{tri['minimize_rounds']} rounds, "
                  f"{tri['bytes_removed']} bytes removed")
        if tri["minset_before"]:
            print(f"  distill: minset {tri['minset_before']} -> "
                  f"{tri['minset_after']} seeds")
        if tri["captures"]:
            print(f"  vbreak: {tri['captures']} captures")
    ten = s.get("tenants")
    if ten:
        sched = ten.get("sched")
        line = "tenants:"
        if sched:
            line += (f" (sched: {sched['rounds']} rounds, "
                     f"{sched['placements']} placements, "
                     f"{sched['preemptions']} preemptions, "
                     f"{sched['completions']} completions)")
        print(line)
        for name, d in ten["by_tenant"].items():
            rate = (f" ({d['testcases_per_s']}/s)"
                    if d["testcases_per_s"] else "")
            print(f"  {name:<12} execs={d['testcases']}{rate} "
                  f"newcov={d['new_coverage']} "
                  f"crashes={d['crashes']} "
                  f"buckets={len(d['crash_buckets'])} "
                  f"batches={d['batches']} "
                  f"lane-s={d['lane_seconds']} "
                  f"ckpt={d['checkpoints']}/{d['resumes']}")
    res = s.get("resilience")
    if res:
        ckpt = (f", checkpoints={res['checkpoints']} "
                f"({res['checkpoint_seconds_total']}s total"
                + (f", last {res['checkpoint_last_bytes']}B, "
                   f"mean {res['checkpoint_mean_seconds']}s"
                   if "checkpoint_last_bytes" in res else "") + ")")
        print(f"resilience: retries={res['retries']} "
              f"reconnects={res['reconnects']} "
              f"reclaimed={res['reclaimed_testcases']} "
              f"resumes={res['resumes']} drains={res['drains']}{ckpt}")
    dres = s.get("device_resilience")
    if dres:
        share = (f"{dres['overhead_share'] * 100:.2f}%"
                 if dres.get("overhead_share") is not None else "n/a")
        print(f"device resilience: rung={dres['final_rung']} "
              f"watchdog={dres['watchdog_fires']} "
              f"errors={dres['device_errors']} "
              f"rebuilds={dres['rebuilds']} "
              f"retries={dres['batch_retries']} "
              f"ladder={dres['degradations']}v/{dres['promotions']}^ "
              f"quarantined={dres['quarantined_now']} "
              f"(lifetime {dres['quarantined_total']}, "
              f"poison events {dres['poisoned_lanes']})")
        print(f"  supervisor cost: {share} of wall steady-state "
              f"(integrity {dres['integrity_seconds']}s over "
              f"{dres['integrity_checks']} checks, "
              f"snapshot {dres['snapshot_seconds']}s) "
              f"+ recovery {dres['recover_seconds']}s")
    flt = s.get("fleet")
    if flt:
        ratio = (f"{flt['delta_ratio']}x"
                 if flt.get("delta_ratio") is not None else "n/a")
        print(f"fleet: delta-frames={flt['delta_frames']} "
              f"cov-bytes saved={flt['cov_bytes_saved']} "
              f"(delta {ratio} smaller, "
              f"full-resyncs={flt['full_resyncs']}, "
              f"cursor-resumes={flt['cursor_resumes']}) "
              f"store puts={flt['store_puts']} "
              f"dedup={flt['store_dedup_hits']} "
              f"bucket-dedup={flt['bucket_dedup_hits']} "
              f"reshards={flt['reshards']}")
    ddc = s.get("device_decode")
    if ddc:
        check = ("clean" if ddc["crosscheck_mismatches"] == 0
                 else f"{ddc['crosscheck_mismatches']} MISMATCHES")
        mean = (f", mean {ddc['zero_host_mean_batches']} batches"
                if ddc.get("zero_host_mean_batches") is not None else "")
        rate = (f" ({ddc['zero_host_window_rate'] * 100:.0f}% zero-host)"
                if ddc.get("zero_host_window_rate") is not None else "")
        overlap = (f"{ddc['harvest_overlap_share'] * 100:.1f}%"
                   if ddc.get("harvest_overlap_share") is not None
                   else "n/a")
        print(f"device decode: published={ddc['published']} "
              f"(cross-check {check}) "
              f"serviced={ddc['serviced_lanes']} "
              f"parked={ddc['parked_lanes']} "
              f"rounds={ddc['service_rounds']} "
              f"host-services={ddc['host_decode_services']}")
        print(f"  zero-host windows: {ddc['zero_host_windows']}"
              f"/{ddc['windows']}{rate}{mean}; harvest overlap {overlap} "
              f"(prelaunched {ddc['prelaunched']}, "
              f"adopted {ddc['prelaunch_hits']}, "
              f"dropped {ddc['prelaunch_dropped']})")
    for err in s["errors"]:
        print(f"error: {err['kind']}: {err['detail']}")


def main(argv) -> int:
    args = [a for a in argv if not a.startswith("--")]
    if not args:
        print(__doc__.strip(), file=sys.stderr)
        return 1
    summary = summarize(args[0])
    if "error" in summary:
        print(summary["error"], file=sys.stderr)
        return 1
    if "--json" in argv:
        print(json.dumps(summary))
    else:
        _print_human(summary)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main(sys.argv[1:]))
    except BrokenPipeError:
        # `... | head` closed the pipe: normal operator usage, not an error
        sys.exit(0)
