"""Hot-path ablation harness: time one interpreter chunk on the real chip
across configs to localize the per-step cost (overlay probes vs uop-table
gathers vs lane scaling).  Not part of the framework — a measurement tool.

Usage: python ablate.py [config ...]; no args = all configs.
"""

import json
import sys
import time

import numpy as np

CONFIGS = {
    "base":      dict(n_lanes=1024, overlay_slots=128, uop_capacity=1 << 14),
    "slots16":   dict(n_lanes=1024, overlay_slots=16,  uop_capacity=1 << 14),
    "cap2k":     dict(n_lanes=1024, overlay_slots=128, uop_capacity=1 << 11),
    "lanes256":  dict(n_lanes=256,  overlay_slots=128, uop_capacity=1 << 14),
    "lanes4096": dict(n_lanes=4096, overlay_slots=128, uop_capacity=1 << 14),
}


def measure(name, cfg, chunk=512):
    import jax.numpy as jnp

    from wtf_tpu.harness import demo_tlv
    from wtf_tpu.interp.runner import Runner, warm_decode_cache

    snapshot = demo_tlv.build_snapshot()
    r = Runner(snapshot, chunk_steps=chunk, **cfg)
    payload = b"\x01\x08AAAAAAAA" * 200  # long branchy run: fills the chunk
    warm_decode_cache(r, demo_tlv.TARGET, payload)
    view = r.view()
    for lane in range(cfg["n_lanes"]):
        view.virt_write(lane, demo_tlv.INPUT_GVA, payload)
        view.r["gpr"][lane, 2] = np.uint64(len(payload))
    r.push(view)
    tab = r.cache.device()
    rc = r._run_chunk
    t0 = time.time()
    m = rc(tab, r.physmem.image, r.machine, jnp.uint64(1 << 40))
    m.status.block_until_ready()
    compile_s = time.time() - t0
    ic0 = np.asarray(m.icount).copy()
    t0 = time.time()
    m2 = rc(tab, r.physmem.image, m, jnp.uint64(1 << 40))
    m2.status.block_until_ready()
    dt = time.time() - t0
    instr = int((np.asarray(m2.icount) - ic0).sum())
    print(json.dumps({
        "config": name, **cfg, "chunk": chunk,
        "compile_s": round(compile_s, 1),
        "chunk_wall_s": round(dt, 4),
        "per_step_ms": round(dt / chunk * 1e3, 3),
        "instr_per_s": round(instr / dt, 1),
    }), flush=True)


if __name__ == "__main__":
    import faulthandler

    faulthandler.dump_traceback_later(
        int(__import__("os").environ.get("ABLATE_WATCHDOG", "240")), exit=True)
    names = sys.argv[1:] or list(CONFIGS)
    for n in names:
        measure(n, CONFIGS[n])
        faulthandler.cancel_dump_traceback_later()
        faulthandler.dump_traceback_later(
            int(__import__("os").environ.get("ABLATE_WATCHDOG", "240")),
            exit=True)
