"""Hot-path ablation harness: time one interpreter chunk on the real chip
across configs to localize the per-step cost (overlay probes vs uop-table
gathers vs lane scaling).  Not part of the framework — a measurement tool.

Usage: python ablate.py [config ...]; no args = all configs.
"""

import json
import sys
import time

import numpy as np

CONFIGS = {
    "base":      dict(n_lanes=1024, overlay_slots=128, uop_capacity=1 << 14),
    "slots16":   dict(n_lanes=1024, overlay_slots=16,  uop_capacity=1 << 14),
    "cap2k":     dict(n_lanes=1024, overlay_slots=128, uop_capacity=1 << 11),
    "lanes256":  dict(n_lanes=256,  overlay_slots=128, uop_capacity=1 << 14),
    "lanes4096": dict(n_lanes=4096, overlay_slots=128, uop_capacity=1 << 14),
}


def measure(name, cfg, chunk=512):
    # warm-runner + chunk-timing recipe shared with bench._microbench and
    # the linter (wtf_tpu/analysis/trace.py)
    from wtf_tpu.analysis.trace import build_tlv_runner, timed_chunk

    # long branchy run: fills the chunk
    r = build_tlv_runner(chunk_steps=chunk,
                         payload=b"\x01\x08AAAAAAAA" * 200, **cfg)
    t = timed_chunk(r)
    import jax

    print(json.dumps({
        "config": name, **cfg, "chunk": chunk,
        "platform": jax.devices()[0].platform,
        "compile_s": round(t["compile_s"], 1),
        "chunk_wall_s": round(t["warm_wall_s"], 4),
        "per_step_ms": round(t["warm_wall_s"] / chunk * 1e3, 3),
        "instr_per_s": round(t["instr"] / t["warm_wall_s"], 1),
    }), flush=True)


def fused_ab(n_lanes, limit, chunk, payload):
    """Fused-vs-XLA A/B core, shared by `ablate.py fused` and
    `bench.py --fused-compare`: the same warmed demo_tlv batch driven
    through Runner.run() with fused_step off vs on.  Returns
    {"off": col, "on": col} with cold wall, warm wall, instr/s, and (on)
    the kernel occupancy — both occupancy terms come from the device
    counter block (CTR_INSTR == icount by invariant), so the ratio is
    exactly retired-in-kernel / retired.  The `on` column also carries
    the park-reason split (fused_park_subset vs fused_park_mem): WHY
    lanes left the kernel, not just how often."""
    from wtf_tpu.analysis.trace import build_tlv_runner, insert_payload
    from wtf_tpu.interp.machine import (
        CTR_FUSED, CTR_INSTR, CTR_PARK_MEM, CTR_PARK_SUBSET,
    )

    cols = {}
    for mode in ("off", "on"):
        r = build_tlv_runner(n_lanes=n_lanes, chunk_steps=chunk,
                             payload=payload, limit=limit, fused_step=mode)
        t0 = time.time()
        r.run()                       # cold pass: compiles + decode fill
        cold_s = time.time() - t0
        r.restore()
        insert_payload(r, payload)
        t0 = time.time()
        r.run()
        warm_s = time.time() - t0
        ctr = r.device_counters()
        instr = int(ctr[:, CTR_INSTR].sum(dtype=np.uint64))
        col = {"compile_plus_run_s": round(cold_s, 3),
               "warm_wall_s": round(warm_s, 4),
               "instr_per_s": round(instr / warm_s, 1)}
        if mode == "on":
            fused = int(ctr[:, CTR_FUSED].sum(dtype=np.uint64))
            col["fused_occupancy"] = round(fused / max(instr, 1), 4)
            col["fused_park_subset"] = int(
                ctr[:, CTR_PARK_SUBSET].sum(dtype=np.uint64))
            col["fused_park_mem"] = int(
                ctr[:, CTR_PARK_MEM].sum(dtype=np.uint64))
        cols[mode] = col
    return cols


def measure_fused(n_lanes=None, limit=None, chunk=512):
    """Fused-Pallas-ladder A/B (ISSUE 4), reporting warm wall, instr/s,
    and the kernel occupancy.  On a real TPU this times the actual Mosaic
    kernel at campaign scale (1024 lanes); elsewhere the kernel runs
    under Pallas interpret mode — grid-point-by-grid-point emulation — so
    the default run scales down to stay minutes-scale, and jax builds
    without pallas support skip with a reason instead of aborting the
    remaining default configs."""
    import jax

    from wtf_tpu.interp.pstep import fused_available

    on_tpu = jax.default_backend() == "tpu"
    if n_lanes is None:
        n_lanes = 1024 if on_tpu else 64
    if limit is None:
        limit = 20_000 if on_tpu else 5_000
    report = {"config": "fused", "n_lanes": n_lanes, "limit": limit,
              "chunk": chunk, "platform": jax.devices()[0].platform}
    if not fused_available():
        report["skipped"] = "this jax build cannot run pallas kernels"
        print(json.dumps(report), flush=True)
        return
    cols = fused_ab(n_lanes, limit, chunk, b"\x01\x08AAAAAAAA" * 200)
    report["fused_off"] = cols["off"]
    report["fused_on"] = cols["on"]
    print(json.dumps(report), flush=True)


def measure_devmut(n_lanes=None, limit=100_000, seconds=10.0):
    """Host-mangle vs device-mangle A/B at matched lane counts (ISSUE 6):
    the same demo_tlv campaign driven through FuzzLoop with the best
    host engine vs the devmangle engine (wtf_tpu/devmut), reporting
    execs/s plus the mutate-phase split — total mutate seconds, the
    fenced device wait under mutate/device, and the residual HOST share,
    which is the number the device engine exists to collapse.  On the
    CPU stand-in the generation kernel competes with the interpreter for
    the same core, so execs/s parity is the expectation there; the
    mutate host-share collapse is the measured claim."""
    import jax

    from wtf_tpu.analysis.trace import build_tlv_campaign

    if n_lanes is None:
        n_lanes = 1024 if jax.default_backend() == "tpu" else 64
    cols = {}
    for mode, engine in (("host", "mangle"), ("device", "devmangle")):
        loop = build_tlv_campaign(n_lanes=n_lanes, mutator=engine,
                                  limit=limit, chunk_steps=512,
                                  overlay_slots=32)
        loop.run_one_batch()   # warmup: XLA compiles + decode servicing
        loop.run_one_batch()
        spans = loop.registry.spans
        c0 = loop.stats.testcases
        m0 = spans.seconds("mutate")
        d0 = spans.seconds("mutate/device")
        t0 = time.time()
        while time.time() - t0 < seconds:
            loop.run_one_batch()
        dt = time.time() - t0
        mutate_s = spans.seconds("mutate") - m0
        mutate_dev_s = spans.seconds("mutate/device") - d0
        cols[mode] = {
            "execs_per_s": round((loop.stats.testcases - c0) / dt, 2),
            "mutate_s": round(mutate_s, 4),
            "mutate_device_s": round(mutate_dev_s, 4),
            "mutate_host_s": round(mutate_s - mutate_dev_s, 4),
            "mutate_share_of_wall": round(mutate_s / dt, 4),
        }
    print(json.dumps({
        "config": "devmut", "n_lanes": n_lanes, "limit": limit,
        "platform": __import__("jax").devices()[0].platform,
        "host": cols["host"], "device": cols["device"],
    }), flush=True)


def measure_megachunk(n_lanes=None, limit=100_000, seconds=10.0,
                      window=16, warm_batches=16):
    """Megachunk host-share A/B (ISSUE 14): the same devmangle demo_tlv
    campaign through the batch-at-a-time device loop vs one-dispatch
    multi-batch windows (wtf_tpu/fuzz/megachunk), reporting execs/s and
    the fenced host/device wall split telemetry_report uses — host
    share of campaign wall = 1 - device-span seconds / wall.  The
    megachunk claim is that per-batch host work collapses to the status
    pull + harvest (<5% on the CPU stand-in; the acceptance metric).

    Fairness note: both modes warm to the SAME campaign maturity
    (`warm_batches` completed batches, not N loop calls — one megachunk
    call is up to `window` batches), because equal seeds only mean equal
    work at equal batch indices; demo_tlv testcases get deeper as the
    corpus matures.  In find-heavy stretches the window legitimately
    degrades toward one batch per dispatch (the find-stop rule IS the
    bit-exactness contract), so the measured host share is the honest
    blended number, not a best case."""
    import jax

    from wtf_tpu.analysis.trace import build_tlv_campaign
    from wtf_tpu.telemetry.spans import DEVICE_SPAN_LEAVES

    if n_lanes is None:
        n_lanes = 1024 if jax.default_backend() == "tpu" else 64
    cols = {}
    for mode, mega in (("batch", 0), ("megachunk", window)):
        loop = build_tlv_campaign(n_lanes=n_lanes, mutator="devmangle",
                                  limit=limit, chunk_steps=512,
                                  overlay_slots=32, megachunk=mega)
        # warmup: XLA compiles + decode servicing + equal maturity
        while loop.stats.testcases < warm_batches * n_lanes:
            loop.run_one_batch()
        children = loop.registry.counter("phase.seconds").children

        def dev_seconds():
            return sum(c.value for path, c in children.items()
                       if path.split("/")[-1] in DEVICE_SPAN_LEAVES)

        c0 = loop.stats.testcases
        d0 = dev_seconds()
        t0 = time.time()
        while time.time() - t0 < seconds:
            loop.run_one_batch()
        dt = time.time() - t0
        dev_s = dev_seconds() - d0
        cols[mode] = {
            "execs_per_s": round((loop.stats.testcases - c0) / dt, 2),
            "batches": int((loop.stats.testcases - c0) / n_lanes),
            "device_s": round(dev_s, 4),
            "host_s": round(max(dt - dev_s, 0.0), 4),
            "host_share_of_wall": round(max(dt - dev_s, 0.0) / dt, 4),
        }
    print(json.dumps({
        "config": "megachunk", "n_lanes": n_lanes, "limit": limit,
        "window": window, "warm_batches": warm_batches,
        "platform": jax.devices()[0].platform,
        "batch_at_a_time": cols["batch"], "megachunk": cols["megachunk"],
    }), flush=True)
    return cols


def measure_fused_mega(n_lanes=8, limit=20_000, window=3, batches=32,
                       seed=0x5EED):
    """Fused-window vs ladder-window A/B (the PR-19 tentpole): the same
    equal-seed devmangle demo_tlv campaign through megachunk windows
    whose quiesce body is the XLA ladder vs the Pallas fused kernel +
    bounded-resume leg.  Reports the WINDOW KERNEL COUNT each way — the
    ladder pays one full step-graph sweep (budgets.json `xla_step` total
    kernels) per in-window round, the fused body pays ONE pallas dispatch
    per round plus a short resume sweep — the donated bytes that stop
    copying through the kernel each dispatch, and the bit-identity
    verdict (coverage/edge bytes, corpus digests, crash buckets).  The
    kernel-count collapse is deterministic (counter-derived at equal
    seeds), so bench_guard treats it as an exact ratchet, not a noisy
    wall-clock number."""
    import jax

    from wtf_tpu.analysis.rules import BUDGET_ENTRY, load_budgets
    from wtf_tpu.analysis.trace import build_tlv_campaign
    from wtf_tpu.interp.pstep import fused_available
    from wtf_tpu.utils.hashing import hex_digest

    report = {"config": "fused-mega", "n_lanes": n_lanes, "limit": limit,
              "window": window, "batches": batches,
              "platform": jax.devices()[0].platform}
    if not fused_available():
        report["skipped"] = "this jax build cannot run pallas kernels"
        print(json.dumps(report), flush=True)
        return report
    # kernels per XLA ladder sweep: the checked-in step-graph pin
    per_sweep = int(load_budgets()[BUDGET_ENTRY]["total"])
    cols, fps = {}, {}
    for mode in ("ladder", "fused"):
        loop = build_tlv_campaign(
            n_lanes=n_lanes, mutator="devmangle", limit=limit,
            chunk_steps=128, overlay_slots=16, megachunk=window,
            seed=seed, fused_step="on" if mode == "fused" else "off")
        t0 = time.time()
        loop.fuzz(n_lanes * batches)
        dt = time.time() - t0
        reg = loop.registry
        sweeps = int(reg.counter("device.fused_window_xla_steps").value)
        rounds = int(reg.counter("device.fused_window_rounds").value)
        col = {
            "wall_s": round(dt, 2),
            "execs_per_s": round(loop.stats.testcases / dt, 2),
            "windows": int(reg.counter("megachunk.windows").value),
            "xla_sweeps": sweeps,
            "pallas_dispatches": rounds,
            "window_kernels": rounds + sweeps * per_sweep,
        }
        if mode == "fused":
            saved = int(
                reg.counter("device.fused_window_bytes_saved").value)
            col["bytes_saved"] = saved
            col["bytes_saved_per_dispatch"] = saved // max(rounds, 1)
        cols[mode] = col
        cov, edge = loop.backend.coverage_state()
        fps[mode] = {
            "cov": hex_digest(cov.tobytes()),
            "edge": hex_digest(edge.tobytes()),
            "cov_bits": loop._coverage(),
            "corpus": [hex_digest(d) for d in loop.corpus],
            "buckets": sorted(loop.crash_buckets),
            "testcases": loop.stats.testcases,
        }
    report["ladder"] = cols["ladder"]
    report["fused"] = cols["fused"]
    report["kernels_per_sweep"] = per_sweep
    report["kernel_reduction"] = round(
        cols["ladder"]["window_kernels"] /
        max(cols["fused"]["window_kernels"], 1), 2)
    report["bit_identical"] = fps["ladder"] == fps["fused"]
    print(json.dumps(report), flush=True)
    return report


def measure_decode(n_lanes=None, limit=100_000, seconds=10.0, window=16):
    """Device-decode A/B (the zero-host-steady-state tentpole): the
    same devmangle megachunk campaign host-serviced vs with
    `--device-decode` (wtf_tpu/interp/devdec), both from a COLD decode
    cache — the cold-start service storm is exactly the host cost the
    in-graph decoder removes.  Reports execs/s, the fenced host/device
    wall split, the host decode-service count (the A column's cost, the
    B column's zero), zero-host window share, and the pipelined-harvest
    overlap share (prelaunch adoptions / windows)."""
    import jax

    from wtf_tpu.analysis.trace import build_tlv_campaign
    from wtf_tpu.telemetry.spans import DEVICE_SPAN_LEAVES

    if n_lanes is None:
        n_lanes = 1024 if jax.default_backend() == "tpu" else 64
    cols = {}
    for mode, dd in (("host", False), ("device", True)):
        loop = build_tlv_campaign(n_lanes=n_lanes, mutator="devmangle",
                                  limit=limit, chunk_steps=512,
                                  overlay_slots=32, megachunk=window,
                                  device_decode=dd)
        def dev_seconds():
            # re-resolve: the per-leaf children only materialize once
            # their spans first fire (this A/B starts cold on purpose)
            children = loop.registry.counter("phase.seconds").children
            return sum(c.value for path, c in children.items()
                       if path.split("/")[-1] in DEVICE_SPAN_LEAVES)

        t0 = time.time()
        while time.time() - t0 < seconds:
            loop.run_one_batch()
        dt = time.time() - t0
        dev_s = dev_seconds()
        reg = loop.registry
        windows = reg.counter("megachunk.windows").value
        col = {
            "execs_per_s": round(loop.stats.testcases / dt, 2),
            "host_decode_services": loop.backend.runner.stats["decodes"],
            "device_s": round(dev_s, 4),
            "host_s": round(max(dt - dev_s, 0.0), 4),
            "host_share_of_wall": round(max(dt - dev_s, 0.0) / dt, 4),
            "windows": int(windows),
        }
        if dd:
            col["device_published"] = int(
                reg.counter("devdec.published").value)
            col["crosscheck_mismatches"] = int(
                reg.counter("devdec.crosscheck_mismatches").value)
            col["zero_host_windows"] = int(
                reg.counter("devdec.zero_host_windows").value)
            col["zero_host_batches"] = int(
                reg.counter("devdec.zero_host_batches").value)
            col["prelaunch_hits"] = int(
                reg.counter("megachunk.prelaunch_hits").value)
            col["harvest_overlap_share"] = round(
                col["prelaunch_hits"] / max(windows, 1), 4)
        cols[mode] = col
    print(json.dumps({
        "config": "decode", "n_lanes": n_lanes, "limit": limit,
        "window": window, "platform": jax.devices()[0].platform,
        "host_serviced": cols["host"], "device_decode": cols["device"],
    }), flush=True)
    return cols


def measure_lanes_ramp(seconds=None, limit=20_000):
    """The chips x lanes ramp (ROADMAP item 1 / ISSUE 7): devmangle
    campaigns through the meshrun driver at lanes x mesh-shard
    combinations, reporting execs/s and cov/edge bits at equal wall per
    cell — the scaling curve behind the SNIPPETS north-star chase
    (>=1000x bochscpu exec/s on a v5e-8 at equal edge coverage).

    On a real TPU the ramp runs lanes256..lanes4096 over 1 chip vs the
    whole mesh; on the forced-8-device CPU stand-in (MULTICHIP_r06) it
    scales down — there the claim is scaling MECHANICS (one process,
    one SPMD program, coverage merged on-chip), not throughput: all
    eight "chips" share the same cores, so execs/s parity with the
    single-device cell is the expectation, not a speedup."""
    import jax

    from wtf_tpu.analysis.trace import build_tlv_campaign

    on_tpu = jax.default_backend() == "tpu"
    if seconds is None:
        seconds = 10.0 if on_tpu else 4.0
    n_dev = len(jax.devices())
    lanes_list = (256, 1024, 4096) if on_tpu else (64, 256)
    shards_list = [1] + ([n_dev] if n_dev > 1 else [])
    cells = []
    for n_lanes in lanes_list:
        for shards in shards_list:
            if n_lanes % shards:
                continue
            loop = build_tlv_campaign(
                n_lanes=n_lanes, mutator="devmangle", limit=limit,
                chunk_steps=128, overlay_slots=16,
                mesh_devices=shards if shards > 1 else None)
            loop.run_one_batch()   # warmup: compiles + decode servicing
            c0 = loop.stats.testcases
            t0 = time.time()
            while time.time() - t0 < seconds:
                loop.run_one_batch()
            dt = time.time() - t0
            agg_edge = np.asarray(loop.backend._agg_edge)
            cell = {
                "lanes": n_lanes, "shards": shards,
                "execs_per_s": round((loop.stats.testcases - c0) / dt, 2),
                "cov_bits": loop._coverage(),
                "edge_bits": int(np.unpackbits(
                    agg_edge.view("uint8")).sum()),
                "testcases": loop.stats.testcases,
            }
            cells.append(cell)
            print(json.dumps({"config": "lanes-ramp", **cell}), flush=True)
    print(json.dumps({
        "config": "lanes-ramp-summary", "limit": limit,
        "seconds_per_cell": seconds, "devices": n_dev,
        "platform": jax.devices()[0].platform, "cells": cells,
    }), flush=True)
    return cells


def measure_tenants_ramp(seconds=None, limit=50_000, lanes_per_tenant=None):
    """The tenant-mix ramp (wtf_tpu/tenancy): MultiTenantLoop campaigns
    at 1..N co-resident tenants on one batch, equal wall per cell —
    execs/s, per-tenant coverage, and the per-tenant-merge overhead
    (the mixed batch runs T prefix-credit merges against the solo
    batch's one).  Emits a JSON table like `ablate.py lanes`.  demo_pe
    joins the 3-tenant cell only where its census DLL is present."""
    import jax

    from wtf_tpu.harness import demo_pe
    from wtf_tpu.harness.targets import Targets, load_builtin_targets
    from wtf_tpu.tenancy.backend import TenantSpec, create_tenancy_backend
    from wtf_tpu.tenancy.loop import MultiTenantLoop, TenantRuntime

    on_tpu = jax.default_backend() == "tpu"
    if seconds is None:
        seconds = 10.0 if on_tpu else 4.0
    if lanes_per_tenant is None:
        lanes_per_tenant = 256 if on_tpu else 16
    load_builtin_targets()
    targets = Targets.instance()
    rows = [("t0", "demo_tlv", "tlv", b"\x01\x04AAAA\x02\x08BBBBBBBB"),
            ("t1", "demo_kernel", "mangle", b"hello-world-123")]
    if demo_pe.available():
        rows.append(("t2", "demo_pe", "auto", demo_pe.BENIGN
                     if hasattr(demo_pe, "BENIGN") else b"\x00" * 16))
    cells = []
    for n_tenants in range(1, len(rows) + 1):
        cfg = rows[:n_tenants]
        specs = [TenantSpec(n, targets.get(t), targets.get(t).snapshot(),
                            lanes_per_tenant)
                 for n, t, _m, _s in cfg]
        backend = create_tenancy_backend(
            specs, lanes_per_tenant * n_tenants, limit=limit,
            chunk_steps=128, overlay_slots=16)
        backend.initialize()
        for i, s in enumerate(specs):
            with backend.tenant_context(i):
                s.target.init(backend)
        runtimes, lane_lo = [], 0
        for i, (n, _t, m, seed_data) in enumerate(cfg):
            rt = TenantRuntime(specs[i], seed=0x7E0 + i, runs=1 << 30,
                               mutator_name=m, max_len=256,
                               lane_lo=lane_lo)
            rt.corpus.add(seed_data)
            runtimes.append(rt)
            lane_lo += lanes_per_tenant
        loop = MultiTenantLoop(backend, runtimes, stats_every=1e9)
        loop.run_one_batch()   # warmup: compiles + decode servicing
        c0 = loop.stats.testcases
        t0 = time.time()
        while time.time() - t0 < seconds:
            loop.run_one_batch()
        dt = time.time() - t0
        cell = {
            "tenants": n_tenants,
            "lanes": lanes_per_tenant * n_tenants,
            "execs_per_s": round((loop.stats.testcases - c0) / dt, 2),
            "per_tenant": {
                rt.name: {
                    "execs": int(rt.stats["testcases"]),
                    "cov_bits": int(np.unpackbits(np.asarray(
                        backend.tenant_coverage_state(i)[0]).view(
                            "uint8")).sum()),
                    "new_coverage": int(rt.stats["new_coverage"]),
                }
                for i, rt in enumerate(runtimes)
            },
            # the per-tenant merge cost: T prefix-credit merges per
            # batch in the mixed cell vs the solo cell's one
            "cov_readback_s": round(
                loop.registry.spans.seconds("execute/cov-readback"), 4),
        }
        cells.append(cell)
        print(json.dumps({"config": "tenants-ramp", **cell}), flush=True)
    print(json.dumps({
        "config": "tenants-ramp-summary", "limit": limit,
        "seconds_per_cell": seconds,
        "platform": jax.devices()[0].platform, "cells": cells,
    }), flush=True)
    return cells


def measure_fleet(clients=None, runs_per_client=40, seed=0xF1EE7):
    """Fleet-tier client-count ramp (wtf_tpu/fleet/soak): the same
    deterministic soak workload at growing fan-out, measuring reactor
    throughput (results/s) and the delta-vs-whole-bitmap coverage wire
    ratio at each cell.  Paste the summary as FLEET_rNN.json."""
    import logging
    import tempfile

    logging.getLogger("wtf_tpu").setLevel(logging.ERROR)
    from wtf_tpu.fleet.soak import run_soak

    cells = []
    for n in (clients or (16, 64, 256)):
        with tempfile.TemporaryDirectory() as tmp:
            report = run_soak(tmp, clients=n,
                              runs_per_client=runs_per_client,
                              seed=seed, threads=min(16, max(n // 8, 1)),
                              min_ratio=1.0)
        cell = {k: report[k] for k in (
            "clients", "runs", "accounted", "wall_s", "results_per_s",
            "coverage", "retries", "reclaimed", "delta_cov_bytes",
            "bitmap_equiv_bytes", "delta_ratio", "full_resyncs")}
        cells.append(cell)
        print(json.dumps({"config": "fleet-ramp", **cell}), flush=True)
    print(json.dumps({
        "config": "fleet-ramp-summary",
        "runs_per_client": runs_per_client, "seed": seed,
        "cells": cells,
    }), flush=True)
    return cells


def measure_deep(n_lanes=1024, limit=10_000_000, seconds=30.0):
    """BASELINE-config-3-shaped end-to-end number (the same workload
    bench.py reports in its `deep` extras): mangle campaign on demo_spin
    with a 10M-instruction budget; prints execs/s + instr/s."""
    import random
    import struct

    import jax

    from wtf_tpu.backend import create_backend
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.loop import FuzzLoop
    from wtf_tpu.fuzz.native_mutator import best_mangle_mutator
    from wtf_tpu.harness import demo_spin

    backend = create_backend("tpu", demo_spin.build_snapshot(),
                             n_lanes=n_lanes, limit=limit, chunk_steps=512,
                             overlay_slots=16)
    backend.initialize()
    demo_spin.TARGET.init(backend)
    rng = random.Random(0xD33B)
    corpus = Corpus(rng=rng)
    corpus.add(struct.pack("<I", min(limit // demo_spin.INSNS_PER_ITER,
                                     0xFFFF_FFFF)))
    loop = FuzzLoop(backend, demo_spin.TARGET,
                    best_mangle_mutator(rng, max_len=4), corpus)
    loop.run_one_batch()  # warmup
    i0, c0 = backend.stats["instructions"], loop.stats.testcases
    t0 = time.time()
    while time.time() - t0 < seconds:
        loop.run_one_batch()
    dt = time.time() - t0
    print(json.dumps({
        "config": "deep", "n_lanes": n_lanes, "limit": limit,
        "platform": jax.devices()[0].platform,
        "execs_per_s": round((loop.stats.testcases - c0) / dt, 2),
        "instr_per_s": round((backend.stats["instructions"] - i0) / dt, 1),
    }), flush=True)


if __name__ == "__main__":
    import faulthandler

    faulthandler.dump_traceback_later(
        int(__import__("os").environ.get("ABLATE_WATCHDOG", "240")), exit=True)
    names = sys.argv[1:] or list(CONFIGS) + ["deep", "fused", "devmut",
                                             "megachunk", "fused-mega",
                                             "decode", "lanes", "tenants",
                                             "fleet"]
    for n in names:
        if n == "deep":
            measure_deep()
        elif n == "fused":
            measure_fused()
        elif n == "devmut":
            measure_devmut()
        elif n == "megachunk":
            measure_megachunk()
        elif n == "fused-mega":
            measure_fused_mega()
        elif n == "decode":
            measure_decode()
        elif n == "lanes":
            measure_lanes_ramp()
        elif n == "tenants":
            measure_tenants_ramp()
        elif n == "fleet":
            measure_fleet()
        else:
            measure(n, CONFIGS[n])
        faulthandler.cancel_dump_traceback_later()
        faulthandler.dump_traceback_later(
            int(__import__("os").environ.get("ABLATE_WATCHDOG", "240")),
            exit=True)
