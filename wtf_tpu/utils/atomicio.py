"""Atomic file writes: the ONE torn-file-proof persistence helper.

A campaign killed mid-write must never leave a half-written coverage
file, crash testcase, corpus entry, or checkpoint behind — every
persistence path that survives a restart routes through here
(dist/server coverage + crash saves, fuzz/corpus outputs, the
wtf_tpu/resume checkpoints).  The recipe is the classic
tmp + fsync + rename: `os.replace` is atomic on POSIX, so readers see
either the old file or the complete new one, never a torn middle.

Chaos seam: `wtf_tpu/testing/faultinject` installs `_WRITE_FAULT` to
inject deterministic ENOSPC/OSError failures at the write boundary —
the recovery paths above are exercised against *this* function, not a
mock of it.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Callable, Optional

# fault-injection hook (wtf_tpu/testing/faultinject): called with the
# destination path before any byte is written; may raise OSError
_WRITE_FAULT: Optional[Callable] = None


def atomic_write_bytes(path, data: bytes, fsync: bool = True) -> None:
    """Write `data` to `path` atomically (tmp + fsync + rename).  On any
    failure the destination is untouched and the tmp file is removed."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    if _WRITE_FAULT is not None:
        _WRITE_FAULT(path)
    tmp = path.with_name(f".{path.name}.tmp.{os.getpid()}")
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
            if fsync:
                fh.flush()
                os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        tmp.unlink(missing_ok=True)
        raise
    if fsync:
        _fsync_dir(path.parent)


def atomic_write_text(path, text: str, fsync: bool = True) -> None:
    atomic_write_bytes(path, text.encode("utf-8"), fsync=fsync)


def _fsync_dir(directory: Path) -> None:
    """Best-effort directory fsync so the rename itself is durable (a
    power cut after the file fsync but before the dirent lands would
    otherwise resurrect the old file)."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return  # e.g. platforms that can't open directories
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)
