"""Minimal PE (Windows image) reader: sections, .text bytes, and the
.pdata function table.

Purpose (VERDICT r3 item 3): the product domain is Windows snapshots, so
decoder coverage must be measured against real Windows-PE codegen, not
Linux ELFs.  `function_ranges` uses the x64 exception directory
(.pdata RUNTIME_FUNCTION entries: begin/end RVAs) so the decode census
sweeps actual function bodies instead of jump tables and padding —
the same ground truth a disassembler would use.

Only what the census and symbol tooling need is implemented: 64-bit
images (machine 0x8664), section headers, and .pdata.  The reference gets
module metadata from the debugger's symbol machinery instead
(debugger.h); parsing the on-disk PE keeps this framework usable where
no Windows host ever enters the loop.
"""

from __future__ import annotations

import dataclasses
import struct
from pathlib import Path
from typing import Dict, List, Tuple

PE32PLUS_MACHINE_AMD64 = 0x8664


class PeError(ValueError):
    pass


@dataclasses.dataclass
class Section:
    name: str
    vaddr: int      # RVA
    vsize: int
    raw_off: int
    raw_size: int
    characteristics: int

    @property
    def executable(self) -> bool:
        return bool(self.characteristics & 0x2000_0000)


@dataclasses.dataclass
class PeImage:
    path: Path
    machine: int
    image_base: int
    sections: List[Section]
    _data: bytes

    def section(self, name: str) -> Section:
        for s in self.sections:
            if s.name == name:
                return s
        raise PeError(f"{self.path.name}: no section {name!r}")

    def section_bytes(self, name: str) -> bytes:
        s = self.section(name)
        raw = self._data[s.raw_off:s.raw_off + min(s.raw_size, s.vsize)]
        return raw

    def rva_bytes(self, rva: int, size: int) -> bytes:
        for s in self.sections:
            if s.vaddr <= rva < s.vaddr + max(s.vsize, s.raw_size):
                off = s.raw_off + (rva - s.vaddr)
                return self._data[off:off + size]
        raise PeError(f"rva {rva:#x} outside every section")

    def data_directory(self, index: int) -> Tuple[int, int]:
        """(rva, size) of optional-header data directory `index`
        (0 = exports, 1 = imports, 12 = IAT)."""
        data = self._data
        (pe_off,) = struct.unpack_from("<I", data, 0x3C)
        (magic,) = struct.unpack_from("<H", data, pe_off + 24)
        if magic != 0x20B:
            raise PeError(f"{self.path.name}: not PE32+")
        return struct.unpack_from("<II", data, pe_off + 24 + 112 + index * 8)

    def exports(self) -> Dict[str, int]:
        """name -> RVA from the export directory."""
        erva, esize = self.data_directory(0)
        if erva == 0:
            return {}
        exp = self.rva_bytes(erva, 40)
        addr_rva, names_rva, ord_rva = struct.unpack_from("<III", exp, 28)
        (nnames,) = struct.unpack_from("<I", exp, 24)
        out: Dict[str, int] = {}
        for i in range(nnames):
            (nrva,) = struct.unpack_from(
                "<I", self.rva_bytes(names_rva + 4 * i, 4))
            name = self.rva_bytes(nrva, 256).split(b"\x00")[0].decode(
                "latin-1")
            (ordinal,) = struct.unpack_from(
                "<H", self.rva_bytes(ord_rva + 2 * i, 2))
            (frva,) = struct.unpack_from(
                "<I", self.rva_bytes(addr_rva + 4 * ordinal, 4))
            out[name] = frva
        return out

    def mapped_image(self) -> bytes:
        """The image laid out as the loader would map it at image_base:
        headers + sections at their RVAs, zero-filled virtual slack."""
        end = max(s.vaddr + max(s.vsize, s.raw_size) for s in self.sections)
        end = (end + 0xFFF) & ~0xFFF
        img = bytearray(end)
        hdr = min(0x1000, len(self._data))
        img[:hdr] = self._data[:hdr]
        for s in self.sections:
            raw = self._data[s.raw_off:s.raw_off + min(s.raw_size, s.vsize)]
            img[s.vaddr:s.vaddr + len(raw)] = raw
        return bytes(img)

    def function_ranges(self) -> List[Tuple[int, int]]:
        """(begin, end) RVA pairs from the .pdata RUNTIME_FUNCTION table
        (x64 SEH unwind directory) — every non-leaf function the compiler
        emitted.  Sorted, overlap-merged."""
        try:
            pdata = self.section_bytes(".pdata")
        except PeError:
            return []
        ranges = []
        for off in range(0, len(pdata) - 11, 12):
            begin, end, _unwind = struct.unpack_from("<III", pdata, off)
            if begin == 0 or end <= begin:
                continue
            ranges.append((begin, end))
        ranges.sort()
        merged: List[Tuple[int, int]] = []
        for begin, end in ranges:
            if merged and begin <= merged[-1][1]:
                merged[-1] = (merged[-1][0], max(merged[-1][1], end))
            else:
                merged.append((begin, end))
        return merged


def load_pe(path) -> PeImage:
    path = Path(path)
    data = path.read_bytes()
    if len(data) < 0x40 or data[:2] != b"MZ":
        raise PeError(f"{path.name}: not a PE (no MZ)")
    (pe_off,) = struct.unpack_from("<I", data, 0x3C)
    if data[pe_off:pe_off + 4] != b"PE\x00\x00":
        raise PeError(f"{path.name}: bad PE signature")
    machine, nsections = struct.unpack_from("<HH", data, pe_off + 4)
    (opt_size,) = struct.unpack_from("<H", data, pe_off + 20)
    (magic,) = struct.unpack_from("<H", data, pe_off + 24)
    image_base = 0
    if magic == 0x20B:  # PE32+
        (image_base,) = struct.unpack_from("<Q", data, pe_off + 24 + 24)
    sections = []
    sect0 = pe_off + 24 + opt_size
    for i in range(nsections):
        off = sect0 + i * 40
        name = data[off:off + 8].rstrip(b"\x00").decode("latin-1")
        vsize, vaddr, raw_size, raw_off = struct.unpack_from(
            "<IIII", data, off + 8)
        (characteristics,) = struct.unpack_from("<I", data, off + 36)
        sections.append(Section(name, vaddr, vsize, raw_off, raw_size,
                                characteristics))
    return PeImage(path=path, machine=machine, image_base=image_base,
                   sections=sections, _data=data)


def decode_census(pe: PeImage, max_bytes: int = 0) -> Dict:
    """Linear-sweep the image's function bodies (from .pdata) through the
    framework decoder; returns totals + a histogram of the first bytes of
    undecodable sequences (what to implement next, by measured weight)."""
    from collections import Counter

    from wtf_tpu.cpu.decoder import decode
    from wtf_tpu.cpu.uops import OPC_INVALID

    text = pe.section(".text")
    blob = pe.section_bytes(".text")
    ranges = pe.function_ranges()
    if not ranges:  # no unwind info: whole section (less accurate)
        ranges = [(text.vaddr, text.vaddr + len(blob))]
    total_instr = 0
    bad_instr = 0
    bad_bytes = 0
    swept = 0
    unknown = Counter()
    for begin, end in ranges:
        pos = begin - text.vaddr
        stop = min(end - text.vaddr, len(blob))
        while pos < stop:
            window = blob[pos:pos + 15]
            if len(window) < 15:
                window = window + b"\x90" * (15 - len(window))
            uop = decode(window, pos)
            total_instr += 1
            swept += max(uop.length, 1)
            if uop.opc == OPC_INVALID:
                bad_instr += 1
                bad_bytes += 1
                unknown[window[:3].hex()] += 1
                pos += 1  # resync byte-wise, like the round-3 ELF census
            else:
                pos += uop.length
            if max_bytes and swept >= max_bytes:
                break
        if max_bytes and swept >= max_bytes:
            break
    return {
        "image": pe.path.name,
        "functions": len(ranges),
        "bytes_swept": swept,
        "instructions": total_instr,
        "undecodable_instr": bad_instr,
        "undecodable_bytes": bad_bytes,
        "undecodable_pct": round(100.0 * bad_bytes / max(swept, 1), 4),
        "top_unknown": unknown.most_common(20),
    }
