"""Coverage-file (.cov) ingestion.

The reference consumes JSON files produced by IDA/Binja/Ghidra scripts
(scripts/gen_coveragefile_*.py) with shape {"name": str, "addresses": [int]},
where addresses are module-relative or absolute basic-block starts
(utils.cc:314-379 ParseCovFiles).  Used to pre-register coverage breakpoints
for backends without per-instruction visibility; for the TPU interpreter
backend they instead seed the known-coverage sets so parity comparisons work.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, Iterable, Set


def parse_cov_files(cov_dir) -> Set[int]:
    """Parse every .cov JSON file in a directory into a set of GVAs."""
    addresses: Set[int] = set()
    cov_dir = Path(cov_dir)
    if not cov_dir.is_dir():
        return addresses
    for path in sorted(cov_dir.glob("*.cov")):
        data = json.loads(path.read_text())
        for addr in data.get("addresses", []):
            if isinstance(addr, str):
                addr = int(addr, 0)
            addresses.add(int(addr))
    return addresses
