"""Hashing utilities: testcase digests and coverage hashes.

The reference names corpus/crash files by BLAKE3 hex digest
(src/wtf/utils.cc:279-300) and hashes coverage edges with splitmix64
(src/wtf/bochscpu_backend.cc:699-728).  We use blake2b (CPython's native C
implementation) for file digests — the digest choice is an internal detail,
not a wire contract — and reimplement splitmix64 both host-side (here) and
device-side (wtf_tpu/interp/coverage math) so hashes agree bit-for-bit.
"""

from __future__ import annotations

import hashlib

MASK64 = (1 << 64) - 1


def hex_digest(data: bytes) -> str:
    """Stable content digest used for corpus/crash filenames."""
    return hashlib.blake2b(data, digest_size=32).hexdigest()


def splitmix64(x: int) -> int:
    """Full splitmix64 step (increment + finalizer).  Used for internal hash
    tables (decode-cache probing); NOT the edge hash — the reference's edge
    mix skips the additive increment (see mix64)."""
    x = (x + 0x9E3779B97F4A7C15) & MASK64
    return mix64(x)


def mix64(z: int) -> int:
    """splitmix64's mixing steps only (no increment) — bit-for-bit the chain
    the reference's RecordEdge applies to RIP
    (src/wtf/bochscpu_backend.cc:699-728).  Must match the device-side
    version in wtf_tpu/interp/step.py exactly."""
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & MASK64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & MASK64
    return (z ^ (z >> 31)) & MASK64


def edge_hash(rip: int, next_rip: int) -> int:
    """Edge identity: mix64(rip) xor next_rip — bit-for-bit the reference's
    RecordEdge (bochscpu_backend.cc:699-724)."""
    return (mix64(rip) ^ next_rip) & MASK64
