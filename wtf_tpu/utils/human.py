"""Human-readable formatting for stats lines.

Equivalent of reference src/wtf/human.{h,cc} (BytesToHuman / NumberToHuman /
SecondsToHuman) used by server/client status lines.
"""

from __future__ import annotations

_BYTE_UNITS = ["b", "kb", "mb", "gb", "tb"]
_NUM_UNITS = ["", "k", "m", "g", "t"]


def _scale(value: float, units, base: float) -> str:
    for unit in units[:-1]:
        if abs(value) < base:
            return f"{value:.1f}{unit}"
        value /= base
    return f"{value:.1f}{units[-1]}"


def bytes_to_human(n: float) -> str:
    return _scale(float(n), _BYTE_UNITS, 1024.0)


def number_to_human(n: float) -> str:
    return _scale(float(n), _NUM_UNITS, 1000.0)


def seconds_to_human(seconds: float) -> str:
    seconds = float(seconds)
    if seconds < 60:
        return f"{seconds:.1f}s"
    minutes, seconds = divmod(seconds, 60)
    if minutes < 60:
        return f"{int(minutes)}min{int(seconds)}s"
    hours, minutes = divmod(minutes, 60)
    if hours < 24:
        return f"{int(hours)}hr{int(minutes)}min"
    days, hours = divmod(hours, 24)
    return f"{int(days)}d{int(hours)}hr"
