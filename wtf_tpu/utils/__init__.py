from wtf_tpu.utils.human import bytes_to_human, number_to_human, seconds_to_human
from wtf_tpu.utils.hashing import hex_digest, splitmix64
from wtf_tpu.utils.covfiles import parse_cov_files
