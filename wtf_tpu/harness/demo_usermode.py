"""Demo target: user-mode guest with a real IDT — guard-page stack growth
and SEH dispatch, the two behaviors every actual Windows user-mode
snapshot depends on.

Role in the reference's capability set: a user-mode target under the
reference runs with the guest kernel IN the snapshot, so a #PF is serviced
by the guest (bochs emulates the IDT walk; KVM/WHV inject the event —
bochscpu_backend.cc:917-999, whv_backend.cc:1218-1247).  That is what
makes (a) thread stacks grow through guard-page faults instead of
false-crashing and (b) unhandled exceptions travel kernel->user into
`ntdll!RtlDispatchException` where the crash-detection hooks parse the
EXCEPTION_RECORD (crash_detection_umode.cc:53-129).  This synthetic guest
reproduces both flows end to end against this framework's host-side
exception delivery (cpu/interrupts.py).

Guest layout:
  user  @ 0x15000000 (CPL3, cs=0x33): dispatch on input byte 0:
    cmd 1 (len>=2): touch N = byte1&0xF pages below rsp -> each lands in
          the unmapped guard region, #PF(CPL3 write), kernel handler maps
          the page by writing the PTE through a kernel window, iretq,
          store retries and succeeds: the stack GROWS.
    cmd 2: read 0xDEAD0000 -> non-growable #PF: kernel builds an
          EXCEPTION_RECORD64 (code 0xC0000005, info = [write?, cr2]) at
          XRECORD, points the iretq frame at user_dispatch and returns —
          the KiUserExceptionDispatcher/RtlDispatchException-analog, where
          setup_usermode_crash_detection's hook names the crash.
    cmd 3: div by zero -> #DE via IDT gate 0 -> same dispatch with
          code 0xC0000094.
    cmd 4: grow the stack through the faulting PUSH itself.
    cmd 5: read a NON-canonical address -> #GP (vector 13, not #PF — the
          delivery layer routes by canonicality) -> dispatched as an A/V
          with no faulting address, like KiGeneralProtectionFault.
  kernel @ 0xFFFF800000410000: #PF handler (gate 14) + #DE handler
          (gate 0), entered through a real 64-bit interrupt-gate IDT with
          a CPL3->0 stack switch via TSS.RSP0.
  KPTWIN @ 0xFFFF800000400000: kernel-mode alias of the page-table page
          covering the user stack region (patched post-build), so the
          handler can map guard pages with one PTE store.

The grown pages map to the low frames 1..0xF — inside the dump's frame
range (the device image rejects stores past it) but absent from the dump
itself, so physmem reads them as zeros and every write lands in the
per-lane overlay: Restore() undoes the growth for free.

Assembled with binutils (Intel syntax); bytes embedded, sources kept in
_USER_ASM/_KERN_ASM for regeneration (tests/test_usermode.py re-assembles
and checks the hex stays in sync when binutils is available).

Testcase ABI (insert_testcase): rsi = user buffer GVA, rdx = length.
"""

from __future__ import annotations

import struct

from wtf_tpu.core.cpustate import GlobalSeg, Seg
from wtf_tpu.core.results import Ok
from wtf_tpu.harness import crash_detection
from wtf_tpu.harness.targets import Target
from wtf_tpu.snapshot.loader import Snapshot
from wtf_tpu.snapshot.synthetic import SyntheticSnapshotBuilder

USER_CODE = 0x0000_1500_0000
FINISH_GVA = USER_CODE + 148        # `finish` label
USER_DISPATCH = USER_CODE + 150     # `user_dispatch` label
USER_BUF = 0x0000_2100_0000
XRECORD = 0x0000_2200_0000          # kernel-built EXCEPTION_RECORD64
MAX_INPUT = 0x1000

STACK_TOP = 0x0000_3000_0000        # top page mapped; below it: guard
STACK_LO = 0x0000_2FFF_0000         # growable region floor
GROW_FRAME_BASE = 0x1               # pfn of the first grown stack frame

KPTWIN = 0xFFFF_8000_0040_0000      # alias of the stack-region PT page
KERN_CODE = 0xFFFF_8000_0041_0000
_GP_HANDLER_OFF = 170               # `gp_handler` label
_DE_HANDLER_OFF = 251               # `de_handler` label
KSTACK_PAGE = 0xFFFF_8000_0042_0000
KSTACK_TOP = KSTACK_PAGE + 0xF80    # TSS.RSP0
KIDT = 0xFFFF_8000_0043_0000
KTSS = 0xFFFF_8000_0044_0000

_USER_ASM = """
user_entry:
    cmp rdx, 1 ; jb finish
    movzx rax, byte ptr [rsi]
    cmp al, 1 ; je u_grow
    cmp al, 2 ; je u_wild
    cmp al, 3 ; je u_div
    cmp al, 4 ; je u_push
    cmp al, 5 ; je u_noncanon
    jmp finish
u_grow:
    cmp rdx, 2 ; jb finish
    movzx rcx, byte ptr [rsi+1]
    and rcx, 0xF ; jz finish
    mov rbx, rsp
grow_loop:
    sub rbx, 0x1000
    mov [rbx], rcx                  # guard-page write -> #PF -> growth
    dec rcx ; jnz grow_loop
    jmp finish
u_wild:
    mov rax, 0xDEAD0000
    mov rax, [rax]                  # unmapped read -> SEH dispatch
    jmp finish
u_div:
    xor edx, edx ; mov eax, 1 ; xor ecx, ecx
    div ecx                         # #DE via IDT gate 0
    jmp finish
u_push:
    cmp rdx, 2 ; jb finish
    movzx rcx, byte ptr [rsi+1]
    and rcx, 0xF ; jz finish
push_loop:
    sub rsp, 0xFF8
    push rcx                        # the PUSH itself faults mid-insn:
    dec rcx ; jnz push_loop         # must retry with rsp NOT yet moved
    jmp finish
u_noncanon:
    mov rax, 0x800000000000
    mov rax, [rax]                  # non-canonical -> #GP via gate 13
    jmp finish
finish:
    nop ; hlt
user_dispatch:                      # RtlDispatchException analog (hooked)
    nop ; hlt
"""

_USER_CODE = bytes.fromhex(
    "4883fa010f828a000000480fb6063c0174123c0274333c03743e3c0474473c05"
    "7463eb704883fa02726a480fb64e014883e10f745f4889e34881eb0010000048"
    "890b48ffc975f1eb4b48b80000adde00000000488b00eb3c31d2b80100000031"
    "c9f7f1eb2f4883fa027229480fb64e014883e10f741e4881ecf80f00005148ff"
    "c975f3eb0f48b80000000000800000488b00eb0090f490f4"
)

_KERN_ASM = """
pf_handler:                         # IDT gate 14 (interrupt gate)
    push rax ; push rbx ; push rcx
    mov rax, cr2
    mov rbx, 0x2FFF0000             # STACK_LO
    cmp rax, rbx ; jb seh
    mov rbx, 0x30000000             # STACK_TOP
    cmp rax, rbx ; jae seh
    # growable: map frame GROW_FRAME_BASE+(idx-0x1F0) at the faulting page
    mov rbx, rax ; shr rbx, 12 ; and rbx, 0x1FF
    lea rcx, [rbx - 0x1EF]          # + GROW_FRAME_BASE - 0x1F0
    shl rcx, 12 ; or rcx, 7         # P|W|U
    mov rax, 0xFFFF800000400000     # KPTWIN (stack PT alias)
    mov [rax + rbx*8], rcx
    pop rcx ; pop rbx ; pop rax
    add rsp, 8                      # drop error code
    iretq                           # faulting store retries, now mapped
seh:
    # build EXCEPTION_RECORD64 at XRECORD and dispatch to user
    mov rbx, 0x22000000             # XRECORD
    mov dword ptr [rbx], 0xC0000005 # ExceptionCode = ACCESS_VIOLATION
    mov dword ptr [rbx+4], 0        # ExceptionFlags
    mov qword ptr [rbx+8], 0        # nested record
    mov rcx, [rsp+32]               # interrupted rip (3 saves + err)
    mov [rbx+16], rcx               # ExceptionAddress
    mov dword ptr [rbx+24], 2       # NumberParameters
    mov rcx, [rsp+24] ; shr rcx, 1 ; and rcx, 1
    mov [rbx+32], rcx               # info[0]: 0=read 1=write (err.W)
    mov rax, cr2
    mov [rbx+40], rax               # info[1]: faulting VA
    mov rcx, rbx                    # rcx = &record (dispatch ABI)
    mov rax, 0x15000096             # USER_DISPATCH
    mov [rsp+32], rax               # iretq frame rip -> dispatcher
    add rsp, 32                     # drop saves + error code
    iretq
gp_handler:                         # IDT gate 13 (#GP, error code)
    mov rbx, 0x22000000
    mov dword ptr [rbx], 0xC0000005 # Windows: #GP surfaces as an A/V
    mov dword ptr [rbx+4], 0
    mov qword ptr [rbx+8], 0
    mov rcx, [rsp+8]                # rip (past the error code)
    mov [rbx+16], rcx
    mov dword ptr [rbx+24], 2
    mov qword ptr [rbx+32], 0       # read
    mov qword ptr [rbx+40], 0       # no faulting address for #GP
    mov rcx, rbx
    mov rax, 0x15000096             # USER_DISPATCH
    mov [rsp+8], rax
    add rsp, 8                      # drop error code
    iretq
de_handler:                         # IDT gate 0 (no error code)
    mov rbx, 0x22000000
    mov dword ptr [rbx], 0xC0000094 # INT_DIVIDE_BY_ZERO
    mov dword ptr [rbx+4], 0
    mov qword ptr [rbx+8], 0
    mov rcx, [rsp]                  # interrupted rip
    mov [rbx+16], rcx
    mov dword ptr [rbx+24], 0
    mov rcx, rbx
    mov rax, 0x15000096             # USER_DISPATCH
    mov [rsp], rax
    iretq
"""

_KERN_CODE = bytes.fromhex(
    "5053510f20d048c7c30000ff2f4839d8724048c7c3000000304839d873344889"
    "c348c1eb0c4881e3ff010000488d8b11feffff48c1e10c4883c90748b8000040"
    "000080ffff48890cd8595b584883c40848cf48c7c300000022c703050000c0c7"
    "43040000000048c7430800000000488b4c242048894b10c7431802000000488b"
    "4c241848d1e94883e10148894b200f20d0488943284889d948c7c09600001548"
    "894424204883c42048cf48c7c300000022c703050000c0c743040000000048c7"
    "430800000000488b4c240848894b10c743180200000048c743200000000048c7"
    "4328000000004889d948c7c09600001548894424084883c40848cf48c7c30000"
    "0022c703940000c0c743040000000048c7430800000000488b0c2448894b10c7"
    "4318000000004889d948c7c0960000154889042448cf"
)


def _idt_gate(handler: int, selector: int = 0x10, gate_type: int = 0xE,
              ist: int = 0, dpl: int = 0) -> bytes:
    """One 16-byte long-mode gate descriptor (SDM Vol 3A 6.14.1)."""
    return struct.pack(
        "<HHBBHII",
        handler & 0xFFFF, selector, ist & 7,
        0x80 | (dpl << 5) | gate_type,
        (handler >> 16) & 0xFFFF, (handler >> 32) & 0xFFFFFFFF, 0)


def _walk_to_pt(pages: dict, cr3: int, gva: int) -> tuple:
    """Host-side 3-level descent to (pt_pfn, pte_index) for a GVA in the
    freshly built snapshot pages."""
    table_pfn = cr3 >> 12
    for shift in (39, 30, 21):
        idx = (gva >> shift) & 0x1FF
        entry = struct.unpack_from("<Q", pages[table_pfn], idx * 8)[0]
        assert entry & 1, f"level {shift} not present for {gva:#x}"
        table_pfn = (entry >> 12) & ((1 << 40) - 1)
    return table_pfn, (gva >> 12) & 0x1FF


def build_snapshot() -> Snapshot:
    b = SyntheticSnapshotBuilder()
    b.write(USER_CODE, _USER_CODE)
    b.write(KERN_CODE, _KERN_CODE)
    b.map(USER_BUF, MAX_INPUT)
    b.map(XRECORD, 0x1000)
    b.map(STACK_TOP - 0x1000, 0x1000)   # stack top page; guard below
    b.map(KSTACK_PAGE, 0x1000)
    b.map(KPTWIN, 0x1000)               # placeholder; PTE patched below

    idt = bytearray(0x1000)
    idt[0:16] = _idt_gate(KERN_CODE + _DE_HANDLER_OFF)   # #DE
    idt[13 * 16:14 * 16] = _idt_gate(KERN_CODE + _GP_HANDLER_OFF)  # #GP
    idt[14 * 16:15 * 16] = _idt_gate(KERN_CODE)          # #PF
    b.write(KIDT, bytes(idt))

    tss = bytearray(0x68)
    struct.pack_into("<Q", tss, 4, KSTACK_TOP)           # RSP0
    struct.pack_into("<H", tss, 0x66, 0x68)              # IOPB = limit
    b.write(KTSS, bytes(tss))

    pages, cpu = b.build(rip=USER_CODE, rsp=STACK_TOP - 0x10)
    cpu.rsi = USER_BUF
    cpu.rdx = 0
    cpu.idtr = GlobalSeg(base=KIDT, limit=0xFFF)
    cpu.tr = Seg(present=True, selector=0x40, base=KTSS, limit=0x67,
                 attr=0x8B)

    # Alias KPTWIN onto the PT page that maps the user stack region, so
    # the kernel handler can install guard-page PTEs with a plain store.
    stack_pt_pfn, _ = _walk_to_pt(pages, cpu.cr3, STACK_LO)
    win_pt_pfn, win_idx = _walk_to_pt(pages, cpu.cr3, KPTWIN)
    pt_page = bytearray(pages[win_pt_pfn])
    struct.pack_into("<Q", pt_page, win_idx * 8, (stack_pt_pfn << 12) | 0x3)
    pages[win_pt_pfn] = bytes(pt_page)

    return Snapshot.from_pages(
        pages, cpu, symbols={
            "user!entry": USER_CODE,
            "user!finish": FINISH_GVA,
            "ntdll!RtlDispatchException": USER_DISPATCH,
        })


def _init(backend) -> bool:
    backend.set_breakpoint(FINISH_GVA, lambda b: b.stop(Ok()))
    crash_detection.setup_usermode_crash_detection(backend)
    return True


def _insert_testcase(backend, data: bytes) -> bool:
    data = data[:MAX_INPUT]
    backend.virt_write(USER_BUF, data)
    backend.set_reg(6, USER_BUF)    # rsi
    backend.set_reg(2, len(data))   # rdx
    return True


TARGET = Target(
    name="demo_usermode",
    init=_init,
    insert_testcase=_insert_testcase,
    snapshot=build_snapshot,
)
