"""Demo target: synthetic ring-0 syscall handler with planted kernel bugs.

Role of the reference's HEVD kernel target (fuzzer_hevd.cc + the hevd
crash-dump snapshot): an end-to-end kernel-mode campaign exercising the
privilege-boundary machinery — syscall via IA32_LSTAR, swapgs, kernel
stack switch, high-half (canonical negative) addresses, sysret — plus the
kernel crash-detection hook set (harness/crash_detection.py).

Guest layout:
  user  @ 0x14000000:        syscall ; nop(FINISH bp) ; hlt
  kernel @ 0xffff8000_00200000 (LSTAR): swapgs, stack switch, dispatch on
  the first input byte:
    cmd 1: benign byte-sum loop
    cmd 2 (len>=16): load bugcheck code+arg from input, jmp bugcheck
           routine -> the nt!KeBugCheck2-analog bp names the crash
    cmd 3: copy len-1 bytes into a 32-byte kernel buffer sitting at the
           end of a mapped page -> OOB kernel WRITE into the guard page
    cmd 4 (len>=9): jmp to an attacker-controlled address -> EXEC fault
  then swapgs ; sysretq back to user FINISH.

Testcase ABI (insert_testcase): rsi = user buffer GVA, rdx = length.

Assembled with binutils at build time; bytes embedded (source in
_KERN_ASM / _USER_ASM for regeneration).
"""

from __future__ import annotations

from wtf_tpu.core.results import Ok
from wtf_tpu.harness import crash_detection
from wtf_tpu.harness.targets import Target
from wtf_tpu.snapshot.loader import Snapshot
from wtf_tpu.snapshot.synthetic import SyntheticSnapshotBuilder

USER_CODE = 0x0000_1400_0000
FINISH_GVA = USER_CODE + 2          # the nop after syscall
USER_BUF = 0x0000_2000_0000
MAX_INPUT = 0x1000

KERN_CODE = 0xFFFF_8000_0020_0000
KBUF_PAGE = 0xFFFF_8000_0020_2000   # 32-byte buffer at page end; next
KBUF = KBUF_PAGE + 0xFE0            #   page is unmapped (kernel guard)
KSTACK_PAGE = 0xFFFF_8000_0021_0000
KSTACK_TOP = KSTACK_PAGE + 0xFF0
KGS_PAGE = 0xFFFF_8000_0022_0000    # kernel_gs_base target of swapgs
_BUGCHECK_OFF = 155                 # k_bugcheck label offset in _KERN_CODE

_USER_ASM = "syscall ; nop ; hlt"
_USER_CODE = bytes.fromhex("0f0590f4")

_KERN_ASM = """
    swapgs ; mov r13, rsp ; mov rsp, KSTACK_TOP
    cmp rdx, 1 ; jb kout
    movzx rax, byte ptr [rsi]
    cmp al, 1 ; je k_sum ; cmp al, 2 ; je k_bug
    cmp al, 3 ; je k_copy ; cmp al, 4 ; je k_exec ; jmp kout
k_sum:
    xor rbx, rbx ; lea r8, [rsi+1] ; mov r12, rdx ; dec r12
k_sum_loop:
    test r12, r12 ; jz kout
    movzx rax, byte ptr [r8] ; add rbx, rax ; inc r8 ; dec r12
    jmp k_sum_loop
k_bug:
    cmp rdx, 16 ; jb kout
    mov ecx, dword ptr [rsi+1] ; mov rdx, qword ptr [rsi+5]
    jmp k_bugcheck
k_copy:
    lea r8, [rsi+1] ; mov r9, KBUF ; mov r12, rdx ; dec r12
k_copy_loop:
    test r12, r12 ; jz kout
    mov al, byte ptr [r8] ; mov byte ptr [r9], al
    inc r8 ; inc r9 ; dec r12 ; jmp k_copy_loop
k_exec:
    cmp rdx, 9 ; jb kout
    mov rax, qword ptr [rsi+1] ; jmp rax
kout:
    mov rsp, r13 ; swapgs ; sysretq
k_bugcheck:
    nop ; hlt
"""

_KERN_CODE = bytes.fromhex(
    "0f01f84989e548bcf00f21000080ffff4883fa01727c480fb6063c01740e3c02"
    "742b3c0374363c04745ceb664831db4c8d46014989d449ffcc4d85e47454490f"
    "b6004801c349ffc049ffccebec4883fa10723f8b4e01488b5605eb3f4c8d4601"
    "49b9e02f20000080ffff4989d449ffcc4d85e4741d418a0041880149ffc049ff"
    "c149ffccebea4883fa097206488b4601ffe04c89ec0f01f8480f0790f4"
)


def build_snapshot() -> Snapshot:
    b = SyntheticSnapshotBuilder()
    b.write(USER_CODE, _USER_CODE)
    b.write(KERN_CODE, _KERN_CODE)
    b.map(USER_BUF, MAX_INPUT)
    b.map(KBUF_PAGE, 0x1000)        # exactly one page: guard after KBUF
    b.map(KSTACK_PAGE, 0x1000)
    b.map(KGS_PAGE, 0x1000)
    pages, cpu = b.build(rip=USER_CODE, rsp=0)
    cpu.rsi = USER_BUF
    cpu.rdx = 0
    # privilege-boundary machinery (the state bdump captures from MSRs)
    cpu.lstar = KERN_CODE
    cpu.sfmask = 0x300              # mask TF|IF on syscall entry
    cpu.gs_base = 0                 # user gs
    cpu.kernel_gs_base = KGS_PAGE   # swapped in by swapgs
    return Snapshot.from_pages(
        pages, cpu, symbols={
            "user!entry": USER_CODE,
            "user!finish": FINISH_GVA,
            "kernel!entry": KERN_CODE,
            "nt!KeBugCheck2": KERN_CODE + _BUGCHECK_OFF,
        })


def _init(backend) -> bool:
    backend.set_breakpoint(FINISH_GVA, lambda b: b.stop(Ok()))
    crash_detection.setup_kernel_crash_detection(backend)
    return True


def _insert_testcase(backend, data: bytes) -> bool:
    data = data[:MAX_INPUT]
    backend.virt_write(USER_BUF, data)
    backend.set_reg(6, USER_BUF)    # rsi
    backend.set_reg(2, len(data))   # rdx
    return True


TARGET = Target(
    name="demo_kernel",
    init=_init,
    insert_testcase=_insert_testcase,
    snapshot=build_snapshot,
)
