"""Guest filesystem emulation: fake file I/O syscalls without a disk.

Reference design (src/wtf/fshooks.cc:115-929, guestfile.h:22-106,
fshandle_table.{h,cc}, handle_table.h:56-141, restorable.h): breakpoints
on the Nt* file syscalls parse guest arguments (OBJECT_ATTRIBUTES /
UNICODE_STRING), consult a table of host-backed GuestFile streams keyed
by filename, hand out fake handles counting down from 0x7ffffffe, fake
the whole syscall with SimulateReturnFromFunction, and roll every bit of
it back per testcase via the Restorable save/restore pair — so file
content mutations, cursors, and open handles are deterministic across
runs.

Batch semantics (a delta from the single-VM reference): every LANE is an
independent guest, so file content, cursors, and handle tables are kept
per lane (`backend.current_lane`), cloned lazily from the init-time
template and discarded wholesale on restore().

Hooked symbols (registered when present in the snapshot's symbol store,
like the reference's Windows-image hooks):
  ntdll!NtCreateFile, nt!NtOpenFile  -> open known files / not-found
  ntdll!NtReadFile                   -> stream read + IO_STATUS_BLOCK
  ntdll!NtWriteFile                  -> stream write + IO_STATUS_BLOCK
  ntdll!NtClose                      -> release the fake handle
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

from wtf_tpu.core import nt

SYM_NTCREATEFILE = "ntdll!NtCreateFile"
SYM_NTOPENFILE = "nt!NtOpenFile"
SYM_NTREADFILE = "ntdll!NtReadFile"
SYM_NTWRITEFILE = "ntdll!NtWriteFile"
SYM_NTCLOSE = "ntdll!NtClose"

# Fake handles count DOWN from here; the range below 0x7ffffffe avoids
# colliding with the pseudo-handles (-1/-2/...) and any real low handles
# the snapshot may hold (reference handle_table.h:56-141).
HANDLE_BASE = 0x7FFF_FFFE

# A guest write can place the file pointer anywhere; bound host memory.
MAX_FILE_SIZE = 16 * 1024 * 1024

# LARGE_INTEGER ByteOffset sentinels (wdm.h semantics)
_OFFSET_USE_CURSOR = 0xFFFF_FFFF_FFFF_FFFE   # FILE_USE_FILE_POINTER_POSITION
_OFFSET_APPEND = 0xFFFF_FFFF_FFFF_FFFF       # FILE_WRITE_TO_END_OF_FILE


def _leaf(name: str) -> str:
    return name.replace("/", "\\").rsplit("\\", 1)[-1]


class Restorable:
    """save() at harness-init time; restore() per testcase
    (reference restorable.h:4-7)."""

    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError


class GuestFile(Restorable):
    """One host-backed guest file stream (guestfile.h:22-106): content +
    cursor + existence, snapshot/rollback semantics."""

    def __init__(self, name: str, data: bytes = b"", exists: bool = True):
        self.name = name
        self.data = bytearray(data)
        self.cursor = 0
        self.exists = exists
        self.delete_on_close = False
        self._saved = None

    def clone(self) -> "GuestFile":
        c = GuestFile(self.name, bytes(self.data), self.exists)
        c.cursor = self.cursor
        c.delete_on_close = self.delete_on_close
        return c

    def save(self) -> None:
        self._saved = (bytes(self.data), self.cursor, self.exists,
                       self.delete_on_close)

    def restore(self) -> None:
        if self._saved is not None:
            data, cursor, exists, doc = self._saved
            self.data = bytearray(data)
            self.cursor = cursor
            self.exists = exists
            self.delete_on_close = doc

    def read(self, size: int, offset: Optional[int] = None) -> bytes:
        pos = self.cursor if offset is None else offset
        out = bytes(self.data[pos:pos + size])
        self.cursor = pos + len(out)
        return out

    def write(self, data: bytes, offset: Optional[int] = None) -> int:
        pos = self.cursor if offset is None else offset
        end = pos + len(data)
        if end > MAX_FILE_SIZE:
            raise ValueError("write beyond MAX_FILE_SIZE")
        if end > len(self.data):
            self.data.extend(b"\x00" * (end - len(self.data)))
        self.data[pos:end] = data
        self.cursor = end
        return len(data)


class HandleTable(Restorable):
    """handle -> GuestFile map with fake-handle allocation
    (handle_table.h:56-141)."""

    def __init__(self):
        self._next = HANDLE_BASE
        self._handles: Dict[int, GuestFile] = {}
        self._saved = None

    def allocate(self, obj: GuestFile) -> int:
        handle = self._next
        self._next -= 2  # stay even-ish like real handles
        self._handles[handle] = obj
        return handle

    def get(self, handle: int) -> Optional[GuestFile]:
        return self._handles.get(handle)

    def close(self, handle: int) -> bool:
        return self._handles.pop(handle, None) is not None

    def save(self) -> None:
        self._saved = (self._next, dict(self._handles))

    def restore(self) -> None:
        if self._saved is not None:
            self._next, handles = self._saved
            self._handles = dict(handles)


class FsHandleTable(Restorable):
    """filename -> GuestFile registry + unknown-file policy
    (fshandle_table.h:70-113).  Filenames are matched on the final path
    component as well, so guests opening '\\??\\C:\\x\\in.txt' find a file
    mapped as 'in.txt'; the blacklist applies with the same leaf-name
    rule so path variants cannot bypass it."""

    def __init__(self):
        self.files: Dict[str, GuestFile] = {}
        self.blacklist: set = set()
        # policy for files never mapped: called with the name, returns a
        # GuestFile or None (=> STATUS_OBJECT_NAME_NOT_FOUND)
        self.unknown_file_handler: Optional[
            Callable[[str], Optional[GuestFile]]] = None

    def map_existing_guest_file(self, name: str,
                                data: bytes = b"") -> GuestFile:
        f = GuestFile(name, data, exists=True)
        self.files[name] = f
        return f

    def map_nonexisting_guest_file(self, name: str) -> GuestFile:
        f = GuestFile(name, exists=False)
        self.files[name] = f
        return f

    def blacklist_file(self, name: str) -> None:
        self.blacklist.add(name)

    def _is_blacklisted(self, name: str) -> bool:
        if name in self.blacklist:
            return True
        leaf = _leaf(name)
        return any(_leaf(b) == leaf for b in self.blacklist)

    def lookup(self, name: str) -> Optional[GuestFile]:
        if self._is_blacklisted(name):
            return None
        f = self.files.get(name)
        if f is not None:
            return f
        leaf = _leaf(name)
        for key, f in self.files.items():
            if _leaf(key) == leaf:
                return f
        if self.unknown_file_handler is not None:
            return self.unknown_file_handler(name)
        return None

    def clone(self) -> "FsHandleTable":
        c = FsHandleTable()
        c.files = {k: f.clone() for k, f in self.files.items()}
        c.blacklist = self.blacklist        # policy: shared, not state
        c.unknown_file_handler = self.unknown_file_handler
        return c

    def save(self) -> None:
        for f in self.files.values():
            f.save()

    def restore(self) -> None:
        for f in self.files.values():
            f.restore()


class GuestFs:
    """The hook set + its restorable per-lane state; one per target.

    `fs` is the init-time TEMPLATE: targets map files into it once.  Each
    lane gets a lazy clone (files + fresh handle table) the first time it
    is touched; restore() drops all lane clones, so every testcase starts
    from the template — the Restorable contract batched."""

    def __init__(self):
        self.fs = FsHandleTable()
        self.stats = {"opens": 0, "reads": 0, "writes": 0, "closes": 0,
                      "not_found": 0, "faults": 0}
        self._lanes: Dict[int, Tuple[FsHandleTable, HandleTable]] = {}

    # -- per-lane state ----------------------------------------------------
    def lane_state(self, lane: int) -> Tuple[FsHandleTable, HandleTable]:
        state = self._lanes.get(lane)
        if state is None:
            state = (self.fs.clone(), HandleTable())
            self._lanes[lane] = state
        return state

    def lane_file(self, backend, name: str) -> GuestFile:
        """The named file as `backend`'s current lane sees it (targets use
        this in insert_testcase to plant per-lane file content)."""
        fs, _ = self.lane_state(backend.current_lane)
        return fs.files[name]

    # -- Restorable plumbing (call from target init/restore) --------------
    def save(self) -> None:
        self.fs.save()

    def restore(self) -> None:
        self.fs.restore()
        self._lanes.clear()

    # -- hook installation -------------------------------------------------
    def install(self, backend) -> None:
        hooks = {
            SYM_NTCREATEFILE: self._on_create_file,
            SYM_NTOPENFILE: self._on_create_file,  # same arg shape
            SYM_NTREADFILE: self._on_read_file,
            SYM_NTWRITEFILE: self._on_write_file,
            SYM_NTCLOSE: self._on_close,
        }
        for name, handler in hooks.items():
            backend.set_breakpoint_if_symbol(name, self._guard(handler))

    def _guard(self, handler):
        """guard_guest_faults (base.py) semantics plus a stats counter: a
        guest-controlled bad pointer in a syscall argument fails the
        TESTCASE, not the campaign."""
        from wtf_tpu.cpu.emu import MemFault
        from wtf_tpu.interp.runner import HostFault

        def with_stats(b):
            try:
                handler(b)
            except (MemFault, HostFault) as e:
                self.stats["faults"] += 1
                kind = "write" if getattr(e, "write", False) else "read"
                b.save_crash(getattr(e, "gva", 0), kind)
        return with_stats

    # -- syscall fakes (fshooks.cc:115-929) --------------------------------
    def _object_name(self, b, objattr_ptr: int) -> str:
        raw = b.virt_read(objattr_ptr, nt.ObjectAttributes.SIZE)
        attrs = nt.ObjectAttributes.parse(raw)
        if attrs.object_name_ptr == 0:
            return ""
        return nt.read_unicode_string(b.virt_read, attrs.object_name_ptr)

    def _on_create_file(self, b) -> None:
        """NtCreateFile(FileHandle*, DesiredAccess, ObjectAttributes*,
        IoStatusBlock*, ...) — open a known file or fail not-found."""
        fs, handles = self.lane_state(b.current_lane)
        handle_ptr = b.get_arg(0)
        objattr_ptr = b.get_arg(2)
        iosb_ptr = b.get_arg(3)
        name = self._object_name(b, objattr_ptr)
        f = fs.lookup(name)
        if f is None or not f.exists:
            self.stats["not_found"] += 1
            b.simulate_return_from_function(nt.STATUS_OBJECT_NAME_NOT_FOUND)
            return
        self.stats["opens"] += 1
        handle = handles.allocate(f)
        b.virt_write_u64(handle_ptr, handle)
        if iosb_ptr:
            b.virt_write(iosb_ptr, nt.IoStatusBlock(
                status=nt.STATUS_SUCCESS, information=1).pack())  # FILE_OPENED
        b.simulate_return_from_function(nt.STATUS_SUCCESS)

    def _read_write_args(self, b):
        """NtReadFile/NtWriteFile(Handle, Event, ApcRoutine, ApcContext,
        IoStatusBlock*, Buffer, Length, ByteOffset*, Key)."""
        handle = b.get_arg(0)
        iosb_ptr = b.get_arg(4)
        buffer = b.get_arg(5)
        length = b.get_arg(6)
        offset_ptr = b.get_arg(7)
        offset = None
        if offset_ptr:
            off = b.virt_read_u64(offset_ptr)
            if off == _OFFSET_APPEND:
                offset = -1          # resolved against the file below
            elif off != _OFFSET_USE_CURSOR:
                offset = off
        return handle, iosb_ptr, buffer, length, offset

    def _on_read_file(self, b) -> None:
        fs, handles = self.lane_state(b.current_lane)
        handle, iosb_ptr, buffer, length, offset = self._read_write_args(b)
        f = handles.get(handle)
        if f is None:
            b.simulate_return_from_function(nt.STATUS_INVALID_HANDLE)
            return
        if offset is not None and (offset < 0 or offset > MAX_FILE_SIZE):
            b.simulate_return_from_function(nt.STATUS_INVALID_PARAMETER)
            return
        data = f.read(length, offset)
        # a zero-length read at a valid position is SUCCESS (Information=0)
        # on real NT; END_OF_FILE only when bytes were wanted and none left
        status = (nt.STATUS_SUCCESS if data or length == 0
                  else nt.STATUS_END_OF_FILE)
        if data:
            b.virt_write(buffer, data)
        if iosb_ptr:
            b.virt_write(iosb_ptr, nt.IoStatusBlock(
                status=status, information=len(data)).pack())
        self.stats["reads"] += 1
        b.simulate_return_from_function(status)

    def _on_write_file(self, b) -> None:
        fs, handles = self.lane_state(b.current_lane)
        handle, iosb_ptr, buffer, length, offset = self._read_write_args(b)
        f = handles.get(handle)
        if f is None:
            b.simulate_return_from_function(nt.STATUS_INVALID_HANDLE)
            return
        if offset == -1:
            offset = len(f.data)     # FILE_WRITE_TO_END_OF_FILE
        if (length > MAX_FILE_SIZE
                or (offset is not None
                    and not 0 <= offset <= MAX_FILE_SIZE - length)):
            b.simulate_return_from_function(nt.STATUS_INVALID_PARAMETER)
            return
        try:
            written = f.write(b.virt_read(buffer, length), offset)
        except ValueError:           # cursor-relative write past the cap
            b.simulate_return_from_function(nt.STATUS_INVALID_PARAMETER)
            return
        if iosb_ptr:
            b.virt_write(iosb_ptr, nt.IoStatusBlock(
                status=nt.STATUS_SUCCESS, information=written).pack())
        self.stats["writes"] += 1
        b.simulate_return_from_function(nt.STATUS_SUCCESS)

    def _on_close(self, b) -> None:
        _, handles = self.lane_state(b.current_lane)
        handle = b.get_arg(0)
        ok = handles.close(handle)
        self.stats["closes"] += 1
        b.simulate_return_from_function(
            nt.STATUS_SUCCESS if ok else nt.STATUS_INVALID_HANDLE)
