"""Crash-detection hook sets: turn guest failure sites into named crashes.

Adaptation of the reference's detection layer
(crash_detection_umode.cc:20-167 + the hevd harness's kernel hooks,
fuzzer_hevd.cc:114-139) to this framework's symbol-driven breakpoints:

  setup_kernel_crash_detection    bugcheck-analog routine -> named crash
                                  with the bugcheck code + args (the
                                  nt!KeBugCheck2 hook, fuzzer_hevd.cc:114)
  setup_usermode_crash_detection  exception-dispatch-analog routine ->
                                  parse the guest EXCEPTION_RECORD, filter
                                  debug-print/C++ exceptions, refine A/V
                                  into read/write/execute, stop w/ named
                                  crash (RtlDispatchException hook,
                                  crash_detection_umode.cc:53-129);
                                  plus stack-cookie (KiRaiseSecurityCheck-
                                  Failure :141) and verifier (:154) analogs

Symbols are looked up in the backend's snapshot symbol store; hooks for
absent symbols are skipped (the reference behaves the same on targets
without app verifier loaded).

Crash naming convention shared with the backends' intrinsic detections:
  crash-bugcheck-<code>-<arg0>   kernel bugcheck
  crash-<read|write|execute>-<addr>   access violation (refined)
  crash-<pretty>-<addr>          other exception codes (nt.py names)
"""

from __future__ import annotations

from wtf_tpu.backend.base import guard_guest_faults
from wtf_tpu.core import nt
from wtf_tpu.core.results import Crash, Timedout

# Symbol names the hook sets look for (targets alias their own routines
# to these in their symbol stores, like real snapshots carry the Windows
# names the reference hooks).
SYM_BUGCHECK = "nt!KeBugCheck2"
SYM_DISPATCH_EXCEPTION = "ntdll!RtlDispatchException"
SYM_SECURITY_CHECK = "ntdll!KiRaiseSecurityCheckFailure"
SYM_VERIFIER_STOP = "verifier!VerifierStopMessage"
SYM_PERF_INTERRUPT = "hal!HalpPerfInterrupt"


def setup_kernel_crash_detection(backend) -> None:
    """Kernel-mode hook set (the hevd harness's detections)."""

    def on_bugcheck(b) -> None:
        # Windows x64 ABI: rcx = bugcheck code, rdx/r8/r9 = args
        # (fuzzer_hevd.cc:114-128 formats the same tuple)
        code = b.get_reg(1) & 0xFFFFFFFF       # rcx
        arg0 = b.get_reg(2)                    # rdx
        b.stop(Crash(f"crash-bugcheck-{code:#x}-{arg0:#x}"))

    backend.set_breakpoint_if_symbol(SYM_BUGCHECK, on_bugcheck)
    backend.set_breakpoint_if_symbol(SYM_PERF_INTERRUPT,
                                     lambda b: b.stop(Timedout()))


def setup_usermode_crash_detection(backend) -> None:
    """User-mode hook set (SetupUsermodeCrashDetectionHooks)."""

    def on_dispatch_exception(b) -> None:
        # rcx = &EXCEPTION_RECORD (crash_detection_umode.cc:53)
        record_ptr = b.get_reg(1)
        raw = b.virt_read(record_ptr, nt.ExceptionRecord.SIZE)
        record = nt.ExceptionRecord.parse(raw)
        # C++ throws and debug prints are not bugs; let the guest's own
        # handler run them (crash_detection_umode.cc:76-100)
        if record.code in (nt.DBG_PRINTEXCEPTION_C,
                           nt.DBG_PRINTEXCEPTION_WIDE_C,
                           nt.CPP_EH_EXCEPTION):
            return
        if record.code == nt.EXCEPTION_ACCESS_VIOLATION:
            kind = record.av_kind() or "av"
            addr = record.parameters[1] if len(record.parameters) > 1 else 0
            b.save_crash(addr, kind)
            return
        b.save_crash(record.address, nt.exception_code_to_str(record.code))

    def on_security_check(b) -> None:
        # stack cookie failure == __fastfail -> stack-buffer-overrun
        # (crash_detection_umode.cc:141-152)
        b.save_crash(b.get_rip(), "stack-buffer-overrun")

    def on_verifier_stop(b) -> None:
        b.save_crash(b.get_rip(), "heap-corruption")

    # the record pointer is guest-controlled: a corrupt rcx names a crash
    # instead of escaping the dispatch (guard_guest_faults)
    backend.set_breakpoint_if_symbol(
        SYM_DISPATCH_EXCEPTION, guard_guest_faults(on_dispatch_exception))
    backend.set_breakpoint_if_symbol(SYM_SECURITY_CHECK, on_security_check)
    backend.set_breakpoint_if_symbol(SYM_VERIFIER_STOP, on_verifier_stop)
