"""Demo target: deep-execution workload (BASELINE config 5's shape).

The reference's deep-kernel campaigns run 100M+ instructions per
testcase (--limit up to ~1.5B on KVM, README.md:307).  This target's
guest spins a hash loop for u32(payload[0:4]) iterations (~8
instructions each), so testcases dial in execution depth directly —
the workload that exposes chunk-servicing overhead and validates the
runner's adaptive chunk growth.
"""

from __future__ import annotations

from wtf_tpu.core.results import Ok
from wtf_tpu.harness.targets import Target
from wtf_tpu.snapshot.loader import Snapshot
from wtf_tpu.snapshot.synthetic import SyntheticSnapshotBuilder

CODE_GVA = 0x1400_0000
FINISH_GVA = 0x1400_2000
INPUT_GVA = 0x2000_0000
STACK_TOP = 0x0000_7FFF_F000
INSNS_PER_ITER = 8  # test/bench helpers size --limit with this

_GUEST_CODE = bytes.fromhex(
    "4883fa04721c8b064831db4885c074124801c34889d948c1e90d4831cb48ffc8"
    "ebe9c3"
)


def build_snapshot() -> Snapshot:
    b = SyntheticSnapshotBuilder()
    b.write(CODE_GVA, _GUEST_CODE)
    b.write(FINISH_GVA, b"\x90\xf4")
    b.map(INPUT_GVA, 0x1000)
    b.map(STACK_TOP - 0x2000, 0x3000)
    rsp = STACK_TOP - 0x1000
    b.write(rsp, FINISH_GVA.to_bytes(8, "little"), map_if_needed=False)
    pages, cpu = b.build(rip=CODE_GVA, rsp=rsp)
    cpu.rsi = INPUT_GVA
    cpu.rdx = 0
    return Snapshot.from_pages(
        pages, cpu, symbols={
            "spin!entry": CODE_GVA,
            "spin!finish": FINISH_GVA,
        })


def _init(backend) -> bool:
    backend.set_breakpoint(FINISH_GVA, lambda b: b.stop(Ok()))
    return True


def _insert_testcase(backend, data: bytes) -> bool:
    data = data[:0x1000]
    backend.virt_write(INPUT_GVA, data)
    backend.set_reg(6, INPUT_GVA)
    backend.set_reg(2, len(data))
    return True


TARGET = Target(
    name="demo_spin",
    init=_init,
    insert_testcase=_insert_testcase,
    snapshot=build_snapshot,
)
