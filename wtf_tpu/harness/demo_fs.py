"""Demo target: user-mode guest that does real file I/O via the faked
Nt* syscalls (the guest-fs emulation proof).

The guest opens '\\??\\C:\\fuzz\\input.txt' with NtCreateFile (parsing
OBJECT_ATTRIBUTES/UNICODE_STRING planted in its data pages), NtReadFile's
16 bytes into a buffer, copies the first qword to an output slot, and
NtCloses the handle.  All three syscalls are stub routines (nop;hlt)
whose entry breakpoints the GuestFs hook set services entirely host-side
(SimulateReturnFromFunction), exactly like the reference fakes
ntdll!NtCreateFile & co in breakpoint handlers (fshooks.cc:115-929).

The fuzzing surface: insert_testcase REPLACES THE FILE CONTENT — the
testcase travels into the guest through the faked filesystem, the
standard wtf pattern for file-parsing targets.
"""

from __future__ import annotations

import struct

from wtf_tpu.core.results import Ok
from wtf_tpu.harness import guestfs
from wtf_tpu.harness.targets import Target
from wtf_tpu.snapshot.loader import Snapshot
from wtf_tpu.snapshot.synthetic import SyntheticSnapshotBuilder

# All absolute addresses fit in 31 bits: the guest loads them with
# sign-extended imm32 movs (mov r64, imm32).
CODE_GVA = 0x1400_0000
NTCREATE = 0x1500_0000
NTREAD = 0x1500_1000
NTCLOSE = 0x1500_2000
DATA = 0x2100_0000
HSLOT = DATA
IOSB = DATA + 0x10
OBJATTR = DATA + 0x40
UNICODE = DATA + 0x80
NAMEBUF = DATA + 0xC0
RBUF = DATA + 0x100
OUTSLOT = DATA + 0x200
STACK_TOP = 0x0000_7FFF_F000
FILE_NAME = "\\??\\C:\\fuzz\\input.txt"
_FINISH_OFF = 167

_GUEST_CODE = bytes.fromhex(
    "4883ec5848c7c10000002148c7c28900120049c7c04000002149c7c110000021"
    "48c7c000000015ffd085c0757a48c7c000000021488b084831d24d31c04d31c9"
    "48c7c010000021488944242048c7c000010021488944242848c7442430100000"
    "0048c74424380000000048c74424400000000048c7c000100015ffd085c07527"
    "48c7c000010021488b1848c7c00002002148891848c7c000000021488b0848c7"
    "c000200015ffd090f4"
)

FINISH_GVA = CODE_GVA + _FINISH_OFF

# One GuestFs per initialized backend (differential runs init several
# backends in one process; each keeps its own hook state).  restore()
# has no backend argument in the Target contract — like the reference's
# global fshooks state — so it rolls every registered instance back.
_FS_BY_BACKEND = {}
_FS: guestfs.GuestFs = None  # most recent (test/inspection convenience)


def build_snapshot() -> Snapshot:
    b = SyntheticSnapshotBuilder()
    b.write(CODE_GVA, _GUEST_CODE)
    for stub in (NTCREATE, NTREAD, NTCLOSE):
        b.write(stub, b"\x90\xf4")  # nop ; hlt — hook fires pre-execution
    b.map(DATA, 0x1000)
    # OBJECT_ATTRIBUTES {Length, Root, &UNICODE_STRING, Attributes, 0, 0}
    b.write(OBJATTR, struct.pack("<QQQQQQ", 0x30, 0, UNICODE, 0x40, 0, 0))
    name16 = FILE_NAME.encode("utf-16-le")
    b.write(UNICODE, struct.pack("<HHIQ", len(name16), len(name16), 0,
                                 NAMEBUF))
    b.write(NAMEBUF, name16)
    b.map(STACK_TOP - 0x4000, 0x5000)
    rsp = STACK_TOP - 0x1000
    pages, cpu = b.build(rip=CODE_GVA, rsp=rsp)
    return Snapshot.from_pages(
        pages, cpu, symbols={
            "fsdemo!entry": CODE_GVA,
            "fsdemo!finish": FINISH_GVA,
            guestfs.SYM_NTCREATEFILE: NTCREATE,
            guestfs.SYM_NTREADFILE: NTREAD,
            guestfs.SYM_NTCLOSE: NTCLOSE,
        })


def _init(backend) -> bool:
    global _FS
    fs = guestfs.GuestFs()
    fs.fs.map_existing_guest_file(FILE_NAME, b"default contents")
    fs.install(backend)
    fs.save()
    _FS_BY_BACKEND[id(backend)] = fs
    _FS = fs
    backend.set_breakpoint(FINISH_GVA, lambda b: b.stop(Ok()))
    return True


def _insert_testcase(backend, data: bytes) -> bool:
    # the testcase IS the file content (file-format fuzzing shape),
    # planted into THIS backend's view of THIS lane's file
    fs = _FS_BY_BACKEND[id(backend)]
    f = fs.lane_file(backend, FILE_NAME)
    f.data = bytearray(data)
    f.cursor = 0
    return True


def _restore() -> bool:
    for fs in _FS_BY_BACKEND.values():
        fs.restore()
    return True


TARGET = Target(
    name="demo_fs",
    init=_init,
    insert_testcase=_insert_testcase,
    restore=_restore,
    snapshot=build_snapshot,
)
