"""Target registry: how fuzzing campaigns plug user harness code in.

Mirror of the reference's `Target_t` (src/wtf/targets.h:14-48): a named
bundle of callbacks —

  init(backend)                  one-time setup after backend init: register
                                 breakpoints, patch guest code, map files
                                 (e.g. fuzzer_hevd.cc:61-142)
  insert_testcase(backend, data) write one testcase into guest memory /
                                 registers (fuzzer_hevd.cc:20-59); called
                                 per lane on the batch backend
  restore()                      roll back harness-side state per testcase
                                 (fs handle tables etc.)
  create_mutator(rng, max_len)   optional structure-aware mutator
                                 (fuzzer_tlv_server.cc:204-365); None =
                                 campaign default (honggfuzz-style mangle)
  snapshot()                     optional snapshot factory for self-
                                 contained synthetic targets (the reference
                                 loads user-supplied crash dumps instead,
                                 wtf.cc:127-129)
  device_insert                  optional DeviceInsertSpec: the declarative
                                 equivalent of insert_testcase for the
                                 device-resident mutation path (wtf_tpu/
                                 devmut) — where the bytes land and which
                                 registers carry pointer/length, so the
                                 whole insertion can be one in-graph
                                 overlay/register update per batch

Constructing a Target self-registers it (reference targets.cc:11-22); the
CLI looks targets up by --name (wtf.cc:378-383).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional


@dataclasses.dataclass(frozen=True)
class DeviceInsertSpec:
    """Declarative testcase-insertion contract for targets whose
    insert_testcase is "write the bytes at a fixed GVA, put the pointer
    and length in registers" (the fuzzer_hevd.cc:20-59 shape).  The
    devmangle path (wtf_tpu/devmut) uses it to fuse insertion into the
    device program; the imperative insert_testcase remains the host
    path's contract and MUST stay semantically equivalent."""

    gva: int                 # where testcase bytes land (page-aligned)
    max_len: int             # region capacity in bytes
    len_gpr: int = 2         # GPR index receiving the byte length (rdx)
    ptr_gpr: int = 6         # GPR index receiving the buffer GVA (rsi)
    # Declarative stop breakpoint (the megachunk path, fuzz/megachunk.py):
    # when set, the target PROMISES its init() arms exactly
    # `set_breakpoint(finish_gva, lambda b: b.stop(Ok()))` at this rip,
    # so the in-graph window may rewrite BREAKPOINT@finish_gva -> OK
    # without a host round-trip.  Targets with richer handlers leave it
    # None; their batches fall back to host breakpoint dispatch.
    finish_gva: Optional[int] = None


@dataclasses.dataclass
class Target:
    name: str
    init: Callable = lambda backend: True
    insert_testcase: Callable = lambda backend, data: True
    restore: Callable = lambda: True
    create_mutator: Optional[Callable] = None
    snapshot: Optional[Callable] = None
    device_insert: Optional[DeviceInsertSpec] = None

    def __post_init__(self):
        Targets.instance().register(self)


class Targets:
    """Singleton registry (reference Targets_t, targets.cc:11-22)."""

    _instance: Optional["Targets"] = None

    def __init__(self):
        self._targets: Dict[str, Target] = {}

    @classmethod
    def instance(cls) -> "Targets":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def register(self, target: Target) -> None:
        if target.name in self._targets:
            raise ValueError(f"target {target.name!r} already registered")
        self._targets[target.name] = target

    def get(self, name: str) -> Target:
        target = self._targets.get(name)
        if target is None:
            raise KeyError(
                f"unknown target {name!r}; known: {sorted(self._targets)}")
        return target

    def names(self):
        return sorted(self._targets)


def register_target(**kwargs) -> Target:
    return Target(**kwargs)


def load_builtin_targets() -> None:
    """Import the in-tree demo target modules so their self-registration
    runs (the reference compiles fuzzer_*.cc into the binary; our
    equivalent is importing the harness modules)."""
    from wtf_tpu.harness import (  # noqa: F401
        demo_fs, demo_ioctl, demo_kernel, demo_maze, demo_pe, demo_spin,
        demo_tlv, demo_usermode,
    )
