"""Target/harness layer: the user extension surface (SURVEY.md §2.4).

  targets.py    - Target descriptor + self-registering singleton registry
                  (reference src/wtf/targets.h:14-48)
  crash_detection.py - user-mode crash-detection breakpoint set
                  (reference src/wtf/crash_detection_umode.cc:20-167)
  demo_tlv.py   - synthetic TLV-parser demo target with a planted stack
                  overflow (role of the reference's tlv_server demo,
                  src/tlv_server/tlv_server.cc + fuzzer_tlv_server.cc)
  demo_maze.py  - coverage-maze demo target: nested input checks that only
                  coverage-guided mutation can walk through
  demo_pe.py    - REAL Windows machine code: maps an MSVC-built DLL
                  (gle64.vc14.dll) loader-style with synthetic import
                  stubs and fuzzes an actual export (the reference's
                  real-snapshot posture, README.md:27-33)
"""

from wtf_tpu.harness.targets import Target, Targets, register_target  # noqa: F401
