"""Demo target: synthetic TLV parser with a planted stack overflow.

Plays the role of the reference's tlv_server demo (a deliberately vulnerable
TLV heap server snapshot fuzzed by fuzzer_tlv_server.cc).  The reference
ships Windows crash-dump snapshots of its demo programs; we synthesize the
equivalent: a long-mode guest whose code is a hand-written TLV parser with
the classic bug.

Guest ABI (set by insert_testcase, mirroring fuzzer_hevd.cc:20-59's
register+buffer insertion):
  rsi = input buffer GVA, rdx = input length
  records: { type:u8, len:u8, payload[len] }
    type 1: sum payload bytes into rbx
    type 2: len>=8 -> store first qword at [r15] (scratch page)
    type 3: copy payload into an 8-byte stack buffer  <-- NO length check:
            len > ~24 smashes the saved return address; `ret` then jumps
            to attacker bytes -> fetch fault -> Crash (the detection path
            a real campaign exercises)
  returns (ret) to FINISH_GVA where init() plants the stop breakpoint -> Ok

Assembled with binutils at build time; bytes embedded so runtime needs no
toolchain (source in _GUEST_ASM for auditability/regeneration).
"""

from __future__ import annotations

from wtf_tpu.core.results import Ok
from wtf_tpu.harness.targets import DeviceInsertSpec, Target
from wtf_tpu.snapshot.loader import Snapshot
from wtf_tpu.snapshot.synthetic import SyntheticSnapshotBuilder

CODE_GVA = 0x0001_4000_0000
FINISH_GVA = 0x0001_4000_2000
INPUT_GVA = 0x0002_0000_0000
SCRATCH_GVA = 0x0002_0000_4000
STACK_TOP = 0x0000_7FFF_F000
MAX_INPUT = 0x1000

_GUEST_ASM = """
    push rbp ; mov rbp, rsp ; sub rsp, 0x40
    mov r8, rsi ; lea r9, [rsi + rdx] ; xor rbx, rbx
next_record:
    cmp r8, r9 ; jae done
    lea r10, [r8+2] ; cmp r10, r9 ; ja done
    movzx rax, byte ptr [r8] ; movzx rcx, byte ptr [r8+1]
    lea r8, [r8+2] ; lea r10, [r8+rcx] ; cmp r10, r9 ; ja done
    cmp al, 1 ; je t_sum ; cmp al, 2 ; je t_store ; cmp al, 3 ; je t_copy
    mov r8, r10 ; jmp next_record
t_sum:
    test rcx, rcx ; jz sum_done
    movzx rax, byte ptr [r8] ; add rbx, rax ; inc r8 ; dec rcx ; jmp t_sum
sum_done: jmp next_record
t_store:
    cmp rcx, 8 ; jb store_skip
    mov rax, [r8] ; mov [r15], rax
store_skip: mov r8, r10 ; jmp next_record
t_copy:
    lea r11, [rbp-0x10]
copy_loop:
    test rcx, rcx ; jz copy_done
    mov al, byte ptr [r8] ; mov byte ptr [r11], al
    inc r8 ; inc r11 ; dec rcx ; jmp copy_loop
copy_done: jmp next_record
done:
    mov rax, rbx ; mov rsp, rbp ; pop rbp ; ret
"""

_GUEST_CODE = bytes.fromhex(
    "554889e54883ec404989f04c8d0c164831db4d39c873734d8d50024d39ca776a"
    "490fb600490fb648014d8d40024d8d14084d39ca77543c01740d3c02741f3c03"
    "742c4d89d0ebcb4885c9740f490fb6004801c349ffc048ffc9ebecebb54883f9"
    "087206498b004989074d89d0eba44c8d5df04885c97411418a0041880349ffc0"
    "49ffc348ffc9ebeaeb884889d84889ec5dc3"
)


def build_snapshot() -> Snapshot:
    """Synthesize the snapshot: parser entered as if just called, return
    address pointing at FINISH_GVA (so `ret` = end of testcase)."""
    b = SyntheticSnapshotBuilder()
    b.write(CODE_GVA, _GUEST_CODE)
    b.write(FINISH_GVA, b"\x90\xf4")          # nop; hlt (never reached: bp)
    b.map(INPUT_GVA, MAX_INPUT)
    b.map(SCRATCH_GVA, 0x1000)
    b.map(STACK_TOP - 0x8000, 0x9000)
    rsp = STACK_TOP - 0x1000
    b.write(rsp, FINISH_GVA.to_bytes(8, "little"), map_if_needed=False)
    pages, cpu = b.build(rip=CODE_GVA, rsp=rsp)
    cpu.rsi = INPUT_GVA
    cpu.rdx = 0
    cpu.r15 = SCRATCH_GVA
    return Snapshot.from_pages(
        pages, cpu, symbols={
            "tlv!parse": CODE_GVA,
            "tlv!finish": FINISH_GVA,
        })


def _init(backend) -> bool:
    # stop bp where `ret` lands (reference: bp after the DeviceIoControl
    # call site, fuzzer_hevd.cc:66-74)
    backend.set_breakpoint(
        FINISH_GVA, lambda b: b.stop(Ok()))
    return True


def _insert_testcase(backend, data: bytes) -> bool:
    data = data[:MAX_INPUT]
    backend.virt_write(INPUT_GVA, data)
    backend.set_reg(6, INPUT_GVA)        # rsi
    backend.set_reg(2, len(data))        # rdx
    return True


def _create_mutator(rng, max_len: int):
    from wtf_tpu.fuzz.mutator import TlvStructureMutator

    return TlvStructureMutator(rng, max_len)


TARGET = Target(
    name="demo_tlv",
    init=_init,
    insert_testcase=_insert_testcase,
    create_mutator=_create_mutator,
    snapshot=build_snapshot,
    # declarative twin of _insert_testcase for the device-resident
    # mutation path: bytes at INPUT_GVA, pointer in rsi (6), len in rdx
    # (2); finish_gva is the stop bp _init plants (stop(Ok()) exactly),
    # which lets the megachunk window retire clean lanes in-graph
    device_insert=DeviceInsertSpec(gva=INPUT_GVA, max_len=MAX_INPUT,
                                   len_gpr=2, ptr_gpr=6,
                                   finish_gva=FINISH_GVA),
)
