"""Demo target: generic IOCTL-style in-place rewriting (fuzzer_ioctl role).

The reference's fuzzer_ioctl.cc fuzzes any NtDeviceIoControlFile snapshot
by rewriting IoControlCode / InputBuffer / InputLength in place
(fuzzer_ioctl.cc:25-135), pushing the payload against the end of the
snapshot buffer so OOB reads fault immediately (page-heap idiom, :82-89),
and planting its stop breakpoint DYNAMICALLY on the saved return address
instead of a fixed symbol (:144-173).  This target reproduces all three
idioms on a synthetic dispatcher snapshot:

  guest ABI at snapshot time (an ioctl dispatch about to run):
    ecx = IoControlCode, rdx = InputBuffer, r8 = InputLength
    handlers: 0x222007 byte-sum (benign), 0x222003 trusts a u16 length
    field at buf[0] and copies that many bytes -> OOB READ past the
    page-end-placed buffer

  testcase format: u32 IoControlCode | payload  (insert_testcase
  rewrites registers + places payload at the end of the input page)
"""

from __future__ import annotations

import struct

from wtf_tpu.core.results import Ok
from wtf_tpu.harness.targets import Target
from wtf_tpu.snapshot.loader import Snapshot
from wtf_tpu.snapshot.synthetic import SyntheticSnapshotBuilder

CODE_GVA = 0x1400_0000
EXIT_GVA = 0x1400_2000      # where the snapshot's saved return address points
INPUT_PAGE = 0x2000_0000    # one page; payload pushed against its end
SCRATCH = 0x2200_0000
STACK_TOP = 0x0000_7FFF_F000
IOCTL_SUM = 0x222007
IOCTL_PARSE = 0x222003

_GUEST_CODE = bytes.fromhex(
    "81f903202200742781f9072022007402eb484831c04989d14d89c24d85d2743a"
    "490fb6194801d849ffc149ffcaebec4983f80272254c0fb7124c8d4a0249c7c3"
    "000000224d85d27411418a0141880349ffc149ffc349ffcaebeac3"
)


def build_snapshot() -> Snapshot:
    b = SyntheticSnapshotBuilder()
    b.write(CODE_GVA, _GUEST_CODE)
    b.write(EXIT_GVA, b"\x90\xf4")      # nop ; hlt (bp planted at init)
    b.map(INPUT_PAGE, 0x1000)           # guard page follows (unmapped)
    b.map(SCRATCH, 0x1000)
    b.map(STACK_TOP - 0x4000, 0x5000)
    rsp = STACK_TOP - 0x1000
    b.write(rsp, EXIT_GVA.to_bytes(8, "little"), map_if_needed=False)
    pages, cpu = b.build(rip=CODE_GVA, rsp=rsp)
    cpu.rcx = IOCTL_SUM
    cpu.rdx = INPUT_PAGE
    cpu.r8 = 0
    return Snapshot.from_pages(
        pages, cpu, symbols={
            "ioctl!dispatch": CODE_GVA,
            # note: no exit symbol on purpose — init() discovers it
        })


def _init(backend) -> bool:
    # dynamic exit breakpoint: read the snapshot's saved return address
    # off the stack (fuzzer_ioctl.cc:144-173's first-return-address idiom)
    ret_addr = int.from_bytes(backend.virt_read(backend.get_reg(4), 8),
                              "little")
    backend.set_breakpoint(ret_addr, lambda b: b.stop(Ok()))
    return True


def _insert_testcase(backend, data: bytes) -> bool:
    if len(data) < 4:
        data = data.ljust(4, b"\x00")
    (code,) = struct.unpack_from("<I", data, 0)
    payload = data[4:4 + 0xF00]
    # page-heap placement: payload ends exactly at the page boundary so
    # one byte of OOB read faults (fuzzer_ioctl.cc:82-89)
    addr = INPUT_PAGE + 0x1000 - len(payload)
    if payload:
        backend.virt_write(addr, payload)
    backend.set_reg(1, code)            # rcx = IoControlCode
    backend.set_reg(2, addr)            # rdx = InputBuffer
    backend.set_reg(8, len(payload))    # r8  = InputLength
    return True


TARGET = Target(
    name="demo_ioctl",
    init=_init,
    insert_testcase=_insert_testcase,
    snapshot=build_snapshot,
)
