"""Demo target: a 4-stage input maze only coverage guidance walks through.

Each correct input byte unlocks a new basic block (new coverage -> corpus
entry -> mutation base), the standard demonstration that the
coverage->corpus->mutate feedback loop works end-to-end; random fuzzing of
the 2^32 input space essentially never finds the final int3 crash, the
guided loop finds it in seconds.  Role model: the reference's hevd demo
campaign walkthrough (README.md:34-110).

Guest ABI: rsi = buffer, rdx = length; "wtf!" -> int3 (Crash).
"""

from __future__ import annotations

from wtf_tpu.core.results import Ok
from wtf_tpu.harness.targets import Target
from wtf_tpu.snapshot.loader import Snapshot
from wtf_tpu.snapshot.synthetic import SyntheticSnapshotBuilder

CODE_GVA = 0x0001_5000_0000
FINISH_GVA = 0x0001_5000_2000
INPUT_GVA = 0x0002_1000_0000
STACK_TOP = 0x0000_7FFF_F000
MAX_INPUT = 0x100

# cmp rdx,4 / jb out ; buf[0]=='w' ... buf[3]=='!' -> int3 ; out: ret
_GUEST_CODE = bytes.fromhex(
    "4883fa0472388a063c77753248c7c3010000008a46013c74752448c7c3020000"
    "008a46023c66751648c7c3030000008a46033c21750848c7c304000000ccc3"
)


def build_snapshot() -> Snapshot:
    b = SyntheticSnapshotBuilder()
    b.write(CODE_GVA, _GUEST_CODE)
    b.write(FINISH_GVA, b"\x90\xf4")
    b.map(INPUT_GVA, MAX_INPUT)
    b.map(STACK_TOP - 0x4000, 0x5000)
    rsp = STACK_TOP - 0x1000
    b.write(rsp, FINISH_GVA.to_bytes(8, "little"), map_if_needed=False)
    pages, cpu = b.build(rip=CODE_GVA, rsp=rsp)
    cpu.rsi = INPUT_GVA
    cpu.rdx = 0
    return Snapshot.from_pages(
        pages, cpu, symbols={
            "maze!entry": CODE_GVA,
            "maze!finish": FINISH_GVA,
        })


def _init(backend) -> bool:
    backend.set_breakpoint(FINISH_GVA, lambda b: b.stop(Ok()))
    return True


def _insert_testcase(backend, data: bytes) -> bool:
    data = data[:MAX_INPUT]
    backend.virt_write(INPUT_GVA, data)
    backend.set_reg(6, INPUT_GVA)
    backend.set_reg(2, len(data))
    return True


TARGET = Target(
    name="demo_maze",
    init=_init,
    insert_testcase=_insert_testcase,
    snapshot=build_snapshot,
)
