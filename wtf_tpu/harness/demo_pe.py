"""Real-Windows-machine-code target: fuzz an exported function of an
actual MSVC-built DLL (VERDICT r4 item 3).

The reference ships snapshots of real programs and fuzzes them through
their harness modules (reference README.md:27-33; the tlv_server demo's
source is src/tlv_server/tlv_server.cc).  No Windows box exists in this
environment, so instead of a bdump capture this target builds the
snapshot the way the LOADER would: `utils/pe.py` maps a census-verified
MSVC PE (`gle64.vc14.dll`, the GLE extrusion library that ships inside
PyOpenGL) at its preferred base, fills its IAT with synthetic import
stubs (bump-allocator malloc/realloc, rep-stosb memset, sqrtsd sqrt,
zero-return for the GL/kernel32 surface — the guest-environment-faking
role the reference's fshooks layer plays for file I/O), and snapshots
the machine about to call a real export.

Default export: `glePolyCylinder(int npoints, gleDouble points[][3],
float colors[][3], gleDouble radius)` — real MSVC codegen with an
attacker-controlled element COUNT walking an attacker-placed array.
The testcase supplies fewer points than it claims and the points buffer
sits against the end of its mapping (the page-heap idiom the reference
demos use, fuzzer_ioctl.cc:82-89), so an over-count walks off the page
inside genuine `gle64` code and surfaces as an access violation.

  testcase format: u32 npoints | f64 radius | point data (24 B each)

Both engines run the same image; the decode census (README table) says
0.02% of this DLL's .text is undecodable, and the device step executes
its SSE/SSE2 floating point natively.

Limitation: the CRT math imports sin/cos/atan2/acos are zero-returning
stubs, so exports whose control flow branches on transcendental results
explore a distorted input space (every such call sees 0.0) — pick
exports that don't, or supply real implementations, when that matters.
"""

from __future__ import annotations

import struct
from pathlib import Path
from typing import Optional

from wtf_tpu.core.results import Ok
from wtf_tpu.fuzz.mutator import Mutator
from wtf_tpu.harness.targets import Target
from wtf_tpu.snapshot.loader import Snapshot
from wtf_tpu.snapshot.synthetic import SyntheticSnapshotBuilder
from wtf_tpu.utils.pe import PeImage, load_pe

DEFAULT_DLL = Path(
    "/opt/venv/lib/python3.12/site-packages/OpenGL/DLLS/gle64.vc14.dll")
DEFAULT_EXPORT = "glePolyCylinder"

EXIT_GVA = 0x1400_0000      # magic return address; bp -> Ok
STUB_GVA = 0x2100_0000      # synthetic import stubs
HEAP_BASE = 0x2200_0000     # bump-allocator arena (16 pages)
HEAP_PAGES = 16
HEAP_STATE = 0x2300_0000    # u64: current bump pointer (stub ABI)
POINTS_BASE = 0x2400_0000   # testcase point data; guard page follows
POINTS_PAGES = 2
STACK_TOP = 0x0000_7FFF_F000

# Hand-assembled stubs (source in tools/gen_pe_stubs.py); HEAP_STATE and
# the HEAP_END arena bound are baked into the malloc/realloc immediates.
# The RAW size is bounded by the arena size before 16-byte alignment (so
# sizes like -1 can't wrap through the +15 into a tiny allocation), then
# the bumped end by HEAP_END — out-of-arena requests return NULL, so
# allocation-heavy mangled inputs exercise the DLL's NULL-handling
# instead of crashing on harness-arena overruns that would be
# misattributed to gle64 (ADVICE r5).
_STUBS = {
    "ret0": bytes.fromhex("31c0c3"),
    "fpzero": bytes.fromhex("0f57c0c3"),
    "sqrt": bytes.fromhex("f20f51c0c3"),
    "malloc": bytes.fromhex(
        "49c7c200000023498b0249c7c3000001004c39d9771c488d490f4883e1f048"
        "8d140849c7c3000001224c39da7704498912c331c0c3"),
    "realloc": bytes.fromhex(
        "49c7c200000023498b0249c7c3000001004c39da77384c8d420f4983e0f04e"
        "8d0c0049c7c3000001224d39d977204d890a4989f94989f34889c74889ce48"
        "89d14885f67402f3a44c89cf4c89dec331c0c3"),
    "memset": bytes.fromhex("4989f94989ca4889cf0fb6c24c89c1f3aa4c89d04c89cfc3"),
}

# import name -> stub kind; anything unlisted gets the zero-return stub
_STUB_FOR = {
    "malloc": "malloc",
    "realloc": "realloc",
    "memset": "memset",
    "sqrt": "sqrt",
    "sin": "fpzero",
    "cos": "fpzero",
    "atan2": "fpzero",
    "acos": "fpzero",
}


def _iter_imports(pe: PeImage):
    """Yield (name, iat_slot_rva) for every import thunk."""
    irva, _ = pe.data_directory(1)
    if irva == 0:
        return
    off = 0
    while True:
        ent = pe.rva_bytes(irva + off, 20)
        ilt, _ts, _fc, _name_rva, iat_rva = struct.unpack("<IIIII", ent)
        if ilt == 0 and iat_rva == 0:
            return
        j = 0
        while True:
            (thunk,) = struct.unpack("<Q", pe.rva_bytes(ilt + j * 8, 8))
            if thunk == 0:
                break
            if thunk >> 63:
                name = f"ordinal_{thunk & 0xFFFF}"
            else:
                name = pe.rva_bytes((thunk & 0x7FFFFFFF) + 2, 256).split(
                    b"\x00")[0].decode("latin-1")
            yield name, iat_rva + j * 8
            j += 1
        off += 20


def build_snapshot(dll_path=DEFAULT_DLL,
                   export: str = DEFAULT_EXPORT) -> Snapshot:
    pe = load_pe(dll_path)
    exports = pe.exports()
    if export not in exports:
        raise ValueError(f"{Path(dll_path).name} does not export {export!r}; "
                         f"has {sorted(exports)}")
    base = pe.image_base

    # lay the image out as the loader would and resolve the IAT onto the
    # synthetic stubs
    image = bytearray(pe.mapped_image())
    stub_addr = {}
    pos = 0
    blob = bytearray()
    for kind, code in _STUBS.items():
        stub_addr[kind] = STUB_GVA + pos
        blob += code + b"\xcc" * (16 - len(code) % 16)
        pos = len(blob)
    for name, slot_rva in _iter_imports(pe):
        kind = _STUB_FOR.get(name, "ret0")
        struct.pack_into("<Q", image, slot_rva, stub_addr[kind])

    b = SyntheticSnapshotBuilder()
    b.write(base, bytes(image))
    b.write(STUB_GVA, bytes(blob))
    b.map(HEAP_BASE, HEAP_PAGES * 0x1000)
    b.write(HEAP_STATE, HEAP_BASE.to_bytes(8, "little"))
    b.map(POINTS_BASE, POINTS_PAGES * 0x1000)   # guard page follows
    b.write(EXIT_GVA, b"\x90\xf4")              # nop; hlt (bp at init)
    b.map(STACK_TOP - 0x8000, 0x9000)
    rsp = STACK_TOP - 0x1000
    b.write(rsp, EXIT_GVA.to_bytes(8, "little"), map_if_needed=False)
    pages, cpu = b.build(rip=base + exports[export], rsp=rsp)
    name = Path(dll_path).name.split(".")[0]
    symbols = {f"{name}!{exp}": base + rva for exp, rva in exports.items()}
    symbols[f"{name}!__exit_magic"] = EXIT_GVA
    return Snapshot.from_pages(pages, cpu, symbols=symbols)


def _init(backend) -> bool:
    backend.set_breakpoint_by_symbol("gle64!__exit_magic",
                                     lambda b: b.stop(Ok()))
    return True


POINTS_CAP = (POINTS_PAGES * 0x1000) // 24 * 24  # whole 24-byte elements


def _insert_testcase(backend, data: bytes) -> bool:
    if len(data) < 12:
        data = data.ljust(12, b"\x00")
    (npoints,) = struct.unpack_from("<I", data, 0)
    (radius_bits,) = struct.unpack_from("<Q", data, 4)
    pts = data[12:12 + POINTS_CAP]
    # page-heap placement: the LAST supplied byte sits at the end of the
    # mapping, so reading element `len(pts)//24` faults
    addr = POINTS_BASE + POINTS_PAGES * 0x1000 - max(len(pts), 24)
    if pts:
        backend.virt_write(addr, pts)
    backend.set_reg(1, npoints)        # rcx: attacker-claimed count
    backend.set_reg(2, addr)           # rdx: gleDouble point_array[][3]
    backend.set_reg(8, 0)              # r8:  color_array = NULL
    backend.set_xmm(3, radius_bits)    # xmm3: gleDouble radius
    return True


class PeStructureMutator(Mutator):
    """Structure-aware mutator for the demo_pe testcase format
    {npoints:u32, radius:f64, points:f64[3][]} — the custom-mutator role
    the reference demonstrates on its tlv_server (CustomMutator_t,
    fuzzer_tlv_server.cc:204-365), here driving REAL MSVC code:
    count lies (the OOB trigger), adversarial FP values for the radius
    and coordinates (NaN payloads, infinities, denormals — the device
    FP path's divert stress), and element-level add/dup/delete."""

    # adversarial f64 bit patterns (denormals exercise the oracle divert)
    _SPECIALS = (0x0000000000000001, 0x000FFFFFFFFFFFFF,  # denormals
                 0x7FF0000000000000, 0xFFF0000000000000,  # +/-inf
                 0x7FF8000000001234, 0x7FF0000000000BAD,  # qnan/snan
                 0x8000000000000000, 0x3FF0000000000000,  # -0, 1.0
                 0x7FEFFFFFFFFFFFFF, 0x0010000000000000)  # max, min-normal

    def __init__(self, rng, max_len: int = 0x400):
        self.rng = rng
        self.max_len = max_len

    def get_new_testcase(self, corpus) -> bytes:
        rng = self.rng
        base = corpus.pick() if corpus is not None else None
        if not base or len(base) < 12:
            base = struct.pack("<Id", 2, 1.0) + struct.pack(
                "<6d", *(rng.uniform(-8, 8) for _ in range(6)))
        (npoints,) = struct.unpack_from("<I", base, 0)
        (radius,) = struct.unpack_from("<Q", base, 4)
        pts = bytearray(base[12:12 + POINTS_CAP])
        n_elem = len(pts) // 24
        for _ in range(rng.randrange(1, 4)):
            op = rng.randrange(6)
            if op == 0:    # count lies: boundary / overclaim / huge
                npoints = rng.choice(
                    (0, 1, n_elem, n_elem + 1, n_elem + rng.randrange(64),
                     0x7FFFFFFF, rng.getrandbits(32)))
            elif op == 1:  # adversarial radius
                radius = rng.choice(self._SPECIALS) ^ rng.getrandbits(2)
            elif op == 2 and n_elem:  # poison one coordinate
                off = rng.randrange(n_elem * 3) * 8
                struct.pack_into(
                    "<Q", pts, off,
                    rng.choice(self._SPECIALS) ^ rng.getrandbits(2))
            elif op == 3 and len(pts) + 24 <= POINTS_CAP:  # append element
                pts += struct.pack(
                    "<3d", *(rng.uniform(-100, 100) for _ in range(3)))
                n_elem += 1
            elif op == 4 and n_elem > 1:  # delete element
                k = rng.randrange(n_elem) * 24
                del pts[k:k + 24]
                n_elem -= 1
            else:          # raw byte flip inside the coordinates
                if pts:
                    pts[rng.randrange(len(pts))] ^= 1 << rng.randrange(8)
        out = struct.pack("<I", npoints & 0xFFFFFFFF) + struct.pack(
            "<Q", radius) + bytes(pts)
        return out[:self.max_len]


def _create_mutator(rng, max_len: int):
    return PeStructureMutator(rng, max_len)


TARGET = Target(
    name="demo_pe",
    init=_init,
    insert_testcase=_insert_testcase,
    create_mutator=_create_mutator,
    snapshot=build_snapshot,
)


def available() -> bool:
    """The census DLL ships with PyOpenGL; gate tests on its presence."""
    return DEFAULT_DLL.exists()
