"""Versioned campaign checkpoints: atomic save, torn-file-safe load.

What a checkpoint carries (the minimal resumable state around the
persistent device loop — Concordia's shape, PAPERS.md):

  corpus     manifest of content digests in insertion order; the bytes
             live content-addressed under <dir>/corpus/<digest> (so
             repeated checkpoints re-write nothing that already exists,
             and the checkpoint is self-contained even when the campaign
             has no outputs/ dir)
  coverage   the backend's aggregate cov/edge bitmaps
  decode     the runner's decode cache in insertion order — coverage-
             bitmap bit i IS cache entry index i, so restored bitmaps
             are meaningless without identical indices
  mutator    engine state: cross-over seed for host engines; for devmut
             the engine seed, batch cursor, both slab views and the
             pending-batch flag (the prelaunched batch is REGENERATED on
             resume from the slab view it originally sampled)
  rng        the shared campaign random.Random state
  stats      campaign/backend/device/devmut/runner counters (telemetry
             continuity; campaign.testcases also drives the run budget)

File format: `checkpoint.json` = {"format", "version", "digest",
"payload"} where `payload` is the state as ONE canonical JSON string and
`digest` is its blake2b hex — a torn or bit-rotted file fails the digest
check instead of resuming silently wrong.  Writes go tmp+fsync+rename
(utils/atomicio) with the previous checkpoint rotated to `.prev`, and
the loader falls back to `.prev` when the newest file is torn.
"""

from __future__ import annotations

import base64
import json
import logging
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from wtf_tpu.utils.atomicio import atomic_write_text
from wtf_tpu.utils.hashing import hex_digest

log = logging.getLogger(__name__)

CKPT_VERSION = 1
CKPT_NAME = "checkpoint.json"
CKPT_FORMAT = "wtf-tpu-campaign-checkpoint"

# the resumable counter namespaces (Registry.counters_state)
COUNTER_PREFIXES = ("campaign.", "backend.", "device.", "devmut.",
                    "runner.", "dist.")


class CheckpointError(RuntimeError):
    """Unusable checkpoint: torn, version-mismatched, or inconsistent
    with the campaign it is being restored into."""


# ---------------------------------------------------------------------------
# JSON transport for binary state (numpy arrays, raw bytes)
# ---------------------------------------------------------------------------

def _jsonify(obj):
    if isinstance(obj, dict):
        return {str(k): _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonify(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return {"__nd__": base64.b64encode(obj.tobytes()).decode(),
                "dtype": str(obj.dtype), "shape": list(obj.shape)}
    if isinstance(obj, (bytes, bytearray)):
        return {"__bytes__": base64.b64encode(bytes(obj)).decode()}
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    return obj


def _unjsonify(obj):
    if isinstance(obj, dict):
        if "__nd__" in obj:
            raw = base64.b64decode(obj["__nd__"])
            return np.frombuffer(raw, dtype=np.dtype(obj["dtype"])).reshape(
                obj["shape"]).copy()
        if "__bytes__" in obj:
            return base64.b64decode(obj["__bytes__"])
        return {k: _unjsonify(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unjsonify(v) for v in obj]
    return obj


def _rng_state(rng) -> Optional[list]:
    if rng is None:
        return None
    version, internal, gauss = rng.getstate()
    return [version, list(internal), gauss]


def _set_rng_state(rng, state) -> None:
    if rng is None or state is None:
        return
    version, internal, gauss = state
    rng.setstate((version, tuple(internal), gauss))


# ---------------------------------------------------------------------------
# save
# ---------------------------------------------------------------------------

def _campaign_state(loop) -> dict:
    backend = loop.backend
    runner = getattr(backend, "runner", None)
    if runner is None or not hasattr(backend, "coverage_state"):
        raise CheckpointError(
            "checkpoint/resume needs the batched tpu backend "
            "(--backend=tpu); this backend has no device state to "
            "checkpoint")
    cov, edge = backend.coverage_state()
    mutator = loop.mutator
    mut_rng = getattr(mutator, "rng", None)
    corpus_rng = getattr(loop.corpus, "rng", None)
    return {
        "config": {
            "target": getattr(loop.target, "name", None),
            "lanes": getattr(backend, "n_lanes", None),
            "mutator": type(mutator).__name__,
            "mesh_devices": getattr(getattr(backend, "mesh", None),
                                    "size", None),
        },
        "batches": loop.batches_done,
        "stats": loop.registry.counters_state(COUNTER_PREFIXES),
        "crash_names": sorted(loop.crash_names),
        # triage-grade dedup keys (wtf_tpu/triage/bucket.py): without
        # them a resumed campaign would re-announce known buckets as new
        "crash_buckets": sorted(loop.crash_buckets),
        "requeue": [data.hex() for data in loop._requeue],
        "requeue_digests": sorted(loop._requeue_digests),
        "rng": {
            "corpus": _rng_state(corpus_rng),
            # most drivers share ONE campaign rng between corpus and
            # mutator; serialize the mutator's only when distinct
            "mutator": ("shared" if mut_rng is corpus_rng
                        else _rng_state(mut_rng)),
        },
        "mutator": mutator.checkpoint_state(),
        "coverage": {"cov": cov, "edge": edge},
        "runner": runner.checkpoint_state(),
        "corpus_manifest": [hex_digest(data) for data in loop.corpus],
    }


def write_checkpoint(state: dict, directory, corpus_items) -> dict:
    """The atomic persistence tail shared by whole-campaign checkpoints
    and per-tenant checkpoints (wtf_tpu/tenancy/state.py): content-
    addressed corpus blobs (only new content costs a write), then the
    digest-embedded doc written tmp+fsync+rename with one `.prev`
    generation kept for torn-file fallback."""
    directory = Path(directory)
    blob_dir = directory / "corpus"
    blob_dir.mkdir(parents=True, exist_ok=True)
    from wtf_tpu.utils.atomicio import atomic_write_bytes

    for digest, data in zip(state["corpus_manifest"], corpus_items):
        path = blob_dir / digest
        if not path.exists():
            atomic_write_bytes(path, data)
    payload = json.dumps(_jsonify(state), sort_keys=True)
    doc = json.dumps({
        "format": CKPT_FORMAT,
        "version": CKPT_VERSION,
        "digest": hex_digest(payload.encode()),
        "payload": payload,
    })
    path = directory / CKPT_NAME
    prev = directory / (CKPT_NAME + ".prev")
    if path.exists():
        path.replace(prev)  # keep one generation for torn-file fallback
    atomic_write_text(path, doc)
    return {"path": str(path), "bytes": len(doc),
            "batches": state.get("batches", 0)}


def save_campaign(loop, directory) -> dict:
    """Checkpoint `loop` into `directory` (created on demand).  Returns
    {"path", "bytes", "batches"}.  Atomic: a kill at any point leaves
    either the previous checkpoint, the new one, or the previous one
    under `.prev` with the new one complete — never a torn file that
    loads."""
    state = _campaign_state(loop)
    return write_checkpoint(state, directory, list(loop.corpus))


# ---------------------------------------------------------------------------
# load + restore
# ---------------------------------------------------------------------------

def _load_one(path: Path) -> dict:
    doc = json.loads(path.read_text(encoding="utf-8"))
    if doc.get("format") != CKPT_FORMAT:
        raise CheckpointError(f"{path}: not a campaign checkpoint")
    if doc.get("version") != CKPT_VERSION:
        raise CheckpointError(
            f"{path}: checkpoint version {doc.get('version')} "
            f"(this build reads {CKPT_VERSION})")
    payload = doc.get("payload", "")
    if hex_digest(payload.encode()) != doc.get("digest"):
        raise CheckpointError(f"{path}: digest mismatch (torn write?)")
    return _unjsonify(json.loads(payload))


def load_campaign(directory) -> Tuple[dict, bool]:
    """Load the newest usable checkpoint from `directory`.  Returns
    (state, fell_back) — fell_back is True when the newest file was torn
    and `.prev` was used.  Raises CheckpointError when neither loads."""
    directory = Path(directory)
    path = directory / CKPT_NAME
    prev = directory / (CKPT_NAME + ".prev")
    errors = []
    for candidate, fell_back in ((path, False), (prev, True)):
        if not candidate.exists():
            errors.append(f"{candidate}: missing")
            continue
        try:
            state = _load_one(candidate)
        except (CheckpointError, json.JSONDecodeError, OSError) as e:
            errors.append(str(e))
            log.warning("checkpoint unusable: %s", e)
            continue
        if fell_back:
            log.warning("newest checkpoint torn; resuming from %s "
                        "(one checkpoint interval of work re-executes)",
                        candidate)
        return state, fell_back
    raise CheckpointError(
        "no usable checkpoint in " + str(directory) + ": "
        + "; ".join(errors))


def _check_config(loop, state) -> None:
    cfg = state.get("config", {})
    checks = (
        ("target", getattr(loop.target, "name", None)),
        ("lanes", getattr(loop.backend, "n_lanes", None)),
        ("mutator", type(loop.mutator).__name__),
    )
    for key, current in checks:
        saved = cfg.get(key)
        if saved is not None and current is not None and saved != current:
            raise CheckpointError(
                f"checkpoint {key}={saved!r} but this campaign has "
                f"{key}={current!r} — resume needs the same target, "
                f"lane count, and mutation engine (mesh layout may "
                f"differ; streams are shard-count invariant)")


def restore_corpus(corpus, state, directory) -> None:
    """Rebuild the host corpus in manifest order from the checkpoint's
    content-addressed blobs, verifying each digest (a corrupt blob would
    silently fork the mutation stream)."""
    blob_dir = Path(directory) / "corpus"
    corpus.clear()
    for digest in state.get("corpus_manifest", []):
        path = blob_dir / digest
        try:
            data = path.read_bytes()
        except OSError as e:
            raise CheckpointError(f"corpus blob missing: {e}") from e
        if hex_digest(data) != digest:
            raise CheckpointError(
                f"corpus blob {digest[:16]}… fails its digest "
                "(torn write?)")
        corpus.add_digested(data, digest)


def restore_campaign(loop, state, directory) -> int:
    """Install a load_campaign() state into a freshly-built FuzzLoop
    (backend initialized, target init done, inputs possibly preloaded —
    preloads are discarded wholesale).  Returns the batch index the
    campaign resumes after."""
    _check_config(loop, state)
    restore_corpus(loop.corpus, state, directory)
    rng = state.get("rng", {})
    _set_rng_state(getattr(loop.corpus, "rng", None), rng.get("corpus"))
    mut_state = rng.get("mutator")
    if mut_state != "shared":
        _set_rng_state(getattr(loop.mutator, "rng", None), mut_state)
    loop.crash_names = set(state.get("crash_names", []))
    loop.crash_buckets = set(state.get("crash_buckets", []))
    loop._requeue = [bytes.fromhex(h) for h in state.get("requeue", [])]
    loop._requeue_digests = set(state.get("requeue_digests", []))
    runner = getattr(loop.backend, "runner", None)
    if runner is None:
        raise CheckpointError(
            "resume needs the batched tpu backend (--backend=tpu)")
    runner.restore_state(state.get("runner", {}))
    coverage = state.get("coverage", {})
    loop.backend.restore_coverage_state(coverage["cov"], coverage["edge"])
    # mutator last-but-one: devmut regeneration dispatches device work
    # whose stat side effects the counter restore below then overwrites
    loop.mutator.restore_state(state.get("mutator", {}))
    loop.registry.restore_counters(state.get("stats", {}))
    loop.batches_done = int(state.get("batches", 0))
    loop.registry.counter("campaign.resumes").inc()
    loop.events.emit("resume", batch=loop.batches_done,
                     testcases=loop.stats.testcases,
                     corpus=len(loop.corpus),
                     directory=str(directory))
    return loop.batches_done
