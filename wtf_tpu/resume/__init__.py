"""Crash-safe campaign checkpoint/resume (the fault-tolerance tier).

A campaign killed at any batch boundary and restarted with `--resume`
must be bit-identical — coverage, crash set, corpus, devmut byte
streams — to the uninterrupted run (the same parity bar as the mesh
driver).  `checkpoint.py` holds the format and the save/restore logic;
the state seams live with their owners (Runner.checkpoint_state,
TpuBackend.coverage_state, DeviceCorpus/DevMangleMutator checkpoint
methods, Registry.counters_state).
"""

from wtf_tpu.resume.checkpoint import (  # noqa: F401
    CKPT_NAME, CKPT_VERSION, CheckpointError, load_campaign,
    restore_campaign, save_campaign,
)
