"""TenancyBackend: many campaigns behind one batched backend.

A tenant table (TenantSpec per campaign: name, target, snapshot, lane
quota) turns the TpuBackend/MeshBackend into a SERVING backend: lane
ranges belong to tenants, one `run_batch_tenants` dispatch executes a
heterogeneous batch through the ONE compiled step ladder, and the
coverage merge splits into per-tenant bit-planes by lane-ID masks —
each tenant's new-coverage credit is computed against ITS aggregate
with the prefix scan restricted to ITS lanes, so a tenant's results are
bit-identical to the same campaign run alone (tests/test_tenancy.py).

Breakpoints key by (tenant, gva): `tenant_context(t)` scopes a target's
init-time registrations (and the backend's symbol store) to its lanes,
and dispatch routes by the faulting lane's tenant — two base images
sharing a virtual address never see each other's handlers (the decode
cache already splits the entries by the same tag).
"""

from __future__ import annotations

import dataclasses
from contextlib import contextmanager
from functools import reduce
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from wtf_tpu.backend.tpu import TpuBackend
from wtf_tpu.core.results import Crash, StatusCode, TestcaseResult
from wtf_tpu.meshrun.backend import MeshBackend


@dataclasses.dataclass
class TenantSpec:
    """One tenant's placement row (what build_batch_state consumes)."""

    name: str
    target: object            # harness.targets.Target
    snapshot: object          # snapshot.loader.Snapshot
    lanes: int                # lane quota (== solo campaign lane count)


class _TenancyMixin:
    """The multi-tenant deltas over TpuBackend/MeshBackend — everything
    rides the existing seams (`tenants=` runner kwarg, `_merge`,
    `_bp_handler`, `_finish_batch`)."""

    def _init_tenancy(self, specs: Sequence[TenantSpec]) -> None:
        if not specs:
            raise ValueError("tenancy backend needs at least one tenant")
        self.tenant_specs = list(specs)
        quotas = [int(s.lanes) for s in specs]
        if sum(quotas) > self.n_lanes:
            raise ValueError(
                f"tenant quotas {quotas} exceed the {self.n_lanes}-lane "
                "batch")
        self._lane_lo = np.cumsum([0] + quotas)[:-1]
        self._quotas = quotas
        self._init_tenant = 0
        # trailing lanes beyond the placed quotas idle (status OK at
        # insert); build_batch_state pads them from tenant 0's snapshot
        self._runner_kwargs = dict(self._runner_kwargs,
                                   tenants=list(specs))
        self._agg_cov_t: List = []
        self._agg_edge_t: List = []
        self._new_words_t: List = []
        self._active_mask: Optional[np.ndarray] = None

    # -- placement helpers -------------------------------------------------
    def lane_range(self, t: int) -> range:
        lo = int(self._lane_lo[t])
        return range(lo, lo + self._quotas[t])

    def lane_mask(self, t: int) -> np.ndarray:
        mask = np.zeros(self.n_lanes, dtype=bool)
        mask[self.lane_range(t).start:self.lane_range(t).stop] = True
        return mask

    @contextmanager
    def tenant_context(self, t: int):
        """Scope breakpoint registration + the symbol store to tenant t
        (target.init time, and handler dispatch)."""
        old_t, old_sym = self._init_tenant, self.symbols
        self._init_tenant = t
        self.symbols = self.tenant_specs[t].snapshot.symbols
        try:
            yield
        finally:
            self._init_tenant, self.symbols = old_t, old_sym

    # -- overridden seams --------------------------------------------------
    def initialize(self) -> None:
        super().initialize()
        self._lane_masks = [self.lane_mask(t)
                            for t in range(len(self.tenant_specs))]
        cov0, edge0 = self._zero_aggs()
        self._agg_cov_t = [cov0 for _ in self.tenant_specs]
        self._agg_edge_t = [edge0 for _ in self.tenant_specs]
        self._new_words_t = [None for _ in self.tenant_specs]
        self.registry.gauge("tenancy.tenants").set(len(self.tenant_specs))

    def _zero_aggs(self):
        return (jnp.zeros_like(self._agg_cov),
                jnp.zeros_like(self._agg_edge))

    def set_breakpoint(self, gva: int, handler) -> None:
        self.breakpoints[(self._init_tenant, gva)] = handler
        self.runner.cache.set_breakpoint(gva, tenant=self._init_tenant)

    def _bp_handler(self, lane: int, rip: int):
        return self.breakpoints.get((self.runner.tenant_of(lane), rip))

    def _dispatch_bp(self, runner, view, lane: int) -> None:
        # handlers run under their tenant's symbol scope
        with self.tenant_context(runner.tenant_of(lane)):
            super()._dispatch_bp(runner, view, lane)

    def _finish_batch(self, statuses, n_active: int) -> None:
        """Per-tenant prefix-credit merges by lane-ID mask: tenant t's
        aggregate only sees its own lanes, and a lane is credited new
        coverage only for bits new to ITS tenant — the isolation rule
        that makes mixed-batch results bit-identical to solo runs."""
        runner = self.runner
        with self.registry.spans.span("cov-readback") as sp:
            m = runner.machine
            # run_batch_tenants leaves the per-lane active mask (lane
            # ranges, not a prefix); prefix-count callers (the inherited
            # run_batch paths) fall back to the classic arange rule
            mask = self._active_mask
            self._active_mask = None
            lane_ok = (np.arange(self.n_lanes) < n_active
                       if mask is None else mask)
            base = ((statuses != int(StatusCode.TIMEDOUT))
                    & (statuses != int(StatusCode.OVERLAY_FULL))
                    & lane_ok)
            new_lane = np.zeros(self.n_lanes, dtype=bool)
            for t in range(len(self.tenant_specs)):
                inc = jnp.asarray(base & self._lane_masks[t])
                (self._agg_cov_t[t], self._agg_edge_t[t], nl,
                 nw) = self._merge(self._agg_cov_t[t], self._agg_edge_t[t],
                                   m.cov, m.edge, inc)
                self._new_words_t[t] = np.asarray(nw)
                new_lane |= np.asarray(nl)
            self._new_lane = new_lane
            # global roll-up (heartbeat coverage display, minset compat)
            self._agg_cov = reduce(jnp.bitwise_or, self._agg_cov_t)
            self._agg_edge = reduce(jnp.bitwise_or, self._agg_edge_t)
            self._last_new_words = reduce(
                np.bitwise_or, [w for w in self._new_words_t
                                if w is not None])
            self.stats["batches"] += 1
            self.stats["testcases"] += n_active
            self.stats["instructions"] += int(
                np.asarray(m.icount)[lane_ok].sum())
            runner.fold_device_counters()
            sp.fence(self._agg_cov)

    # -- heterogeneous batch execution ------------------------------------
    def run_batch_tenants(self, plans) -> List[TestcaseResult]:
        """One mixed batch: `plans[t]` is either ("host", [bytes...]) —
        at most quota testcases inserted through tenant t's
        insert_testcase — or ("device", mutator) with a bound
        tenant-scoped devmangle engine whose take_batch() already ran.
        Unfilled/unplaced lanes idle.  Returns per-lane results."""
        runner = self.runner
        runner.limit = self.limit
        self._lane_results = {}
        spans = self.registry.spans
        active = np.zeros(self.n_lanes, dtype=bool)
        device_plans = []
        with spans.span("insert"):
            view = self._ensure_view()
            for t, plan in enumerate(plans):
                kind, payload = plan
                lo = int(self._lane_lo[t])
                if kind == "host":
                    if len(payload) > self._quotas[t]:
                        raise ValueError(
                            f"tenant {self.tenant_specs[t].name!r} plan "
                            f"has {len(payload)} testcases for "
                            f"{self._quotas[t]} lanes")
                    with self.tenant_context(t):
                        for i, data in enumerate(payload):
                            with self._bound(view, lo + i):
                                self.tenant_specs[t].target.insert_testcase(
                                    self, data)
                    active[lo:lo + len(payload)] = True
                elif kind == "device":
                    device_plans.append((t, payload))
                    active[lo:lo + self._quotas[t]] = True
                else:
                    raise ValueError(f"unknown plan kind {kind!r}")
            for lane in np.nonzero(~active)[0]:
                view.set_status(int(lane), StatusCode.OK)
            runner.push(view)
            self._view = None
            for t, mutator in device_plans:
                with spans.span("device") as sp:
                    words, lens = mutator.current_batch()
                    lo = int(self._lane_lo[t])
                    q = self._quotas[t]
                    full_w = jnp.zeros((self.n_lanes, words.shape[1]),
                                       jnp.uint32).at[lo:lo + q].set(words)
                    full_l = jnp.zeros((self.n_lanes,),
                                       jnp.int32).at[lo:lo + q].set(lens)
                    spec = mutator.spec
                    runner.device_insert(
                        full_w, full_l, mutator.pfns, spec.gva,
                        spec.len_gpr, spec.ptr_gpr,
                        active=self._lane_masks[t])
                    sp.fence(runner.machine.status)
        statuses = runner.run(bp_handler=self._dispatch_bp)
        self._active_mask = active
        self._finish_batch(statuses, int(active.sum()))
        return [self._map_result(lane, statuses[lane])
                for lane in range(self.n_lanes)]

    # -- per-tenant checkpoint seams (wtf_tpu/tenancy/state.py) ------------
    def tenant_coverage_state(self, t: int):
        return (np.asarray(jax.device_get(self._agg_cov_t[t])),
                np.asarray(jax.device_get(self._agg_edge_t[t])))

    def restore_tenant_coverage(self, t: int, cov: np.ndarray,
                                edge: np.ndarray) -> None:
        self._agg_cov_t[t] = self._place_agg(jnp.asarray(cov))
        self._agg_edge_t[t] = self._place_agg(jnp.asarray(edge))
        self._agg_cov = reduce(jnp.bitwise_or, self._agg_cov_t)
        self._agg_edge = reduce(jnp.bitwise_or, self._agg_edge_t)

    def _place_agg(self, arr):
        return arr

    def tenant_coverage_rips(self, t: int) -> set:
        cov = np.asarray(jax.device_get(self._agg_cov_t[t]))
        return set(self.runner.cache.rips_of_bits(cov))

    def print_run_stats(self) -> None:
        super().print_run_stats()
        parts = ", ".join(
            f"{s.name}={q}" for s, q in zip(self.tenant_specs,
                                            self._quotas))
        print(f"[tpu] tenants: {parts} (lanes {self.n_lanes})")


class TenancyBackend(_TenancyMixin, TpuBackend):
    """Single-device multi-tenant batch."""

    def __init__(self, specs: Sequence[TenantSpec], n_lanes: int,
                 **kwargs):
        super().__init__(specs[0].snapshot, n_lanes=n_lanes, **kwargs)
        self._init_tenancy(specs)


class TenancyMeshBackend(_TenancyMixin, MeshBackend):
    """Mesh-sharded multi-tenant batch: lane quotas need not align to
    shard boundaries — the per-tenant merge masks are lane-sharded data,
    and the mesh merge's all_gather already carries the cross-shard
    exclusive prefix."""

    def __init__(self, specs: Sequence[TenantSpec], n_lanes: int,
                 mesh_devices: Optional[int] = None, **kwargs):
        super().__init__(specs[0].snapshot, n_lanes=n_lanes,
                         mesh_devices=mesh_devices, **kwargs)
        self._init_tenancy(specs)

    def _place_agg(self, arr):
        from wtf_tpu.meshrun.mesh import replicated_sharding

        return jax.device_put(arr, replicated_sharding(self.mesh))


def create_tenancy_backend(specs: Sequence[TenantSpec], n_lanes: int,
                           mesh_devices: Optional[int] = None,
                           **kwargs):
    if mesh_devices is not None:
        return TenancyMeshBackend(specs, n_lanes,
                                  mesh_devices=mesh_devices, **kwargs)
    return TenancyBackend(specs, n_lanes, **kwargs)
