"""Multi-tenant campaign scheduling over one device batch.

The production story of ROADMAP item 3: one mesh serving *different*
customers' campaigns concurrently, the same shape as multi-tenant
inference serving — heterogeneous requests batched into one compiled
program, jobs placed onto accelerator slices, preemption via durable
state (the PR-8 checkpoint format is already placement-free).

Two tiers:

  heterogeneous batch axis (image.py + the interp/mem seams)
      per-lane base-image ids index a STACKED image table — every
      tenant's snapshot packed into one page store with one padded
      pfn->slot row per tenant — and the decode cache keys entries by
      (tenant, rip), so demo_tlv + demo_kernel + demo_pe lanes share
      ONE run_batch dispatch and ONE compiled step ladder.  Tenant
      identity is pure DATA (the `MemImage.tenant` lane selector):
      the compiled program depends only on shapes, so any tenant mix
      at a given lane count runs the same program bytes (pinned by
      the lint's budget family).

  scheduler tier (sched.py / loop.py / state.py / backend.py)
      campaigns as jobs (`wtf-tpu sched` + jobs.json) placed onto lane
      ranges of a (possibly mesh-sharded) batch, with priorities and
      lane quotas; preemption checkpoints a tenant at a batch boundary
      (reusing wtf_tpu/resume's format per tenant, coverage bit-planes
      remapped to tenant-local entry indices so they are placement-
      free), hands its lanes to another job, and resumes later
      bit-identically.  Telemetry lands under per-tenant
      `tenant.<name>.*` namespaces with tenant-tagged JSONL events.
"""

from wtf_tpu.tenancy.image import (  # noqa: F401
    BatchState, build_batch_state, stack_images,
)
from wtf_tpu.tenancy.backend import (  # noqa: F401
    TenancyBackend, TenancyMeshBackend, create_tenancy_backend,
)
from wtf_tpu.tenancy.loop import MultiTenantLoop, TenantRuntime  # noqa: F401
from wtf_tpu.tenancy.sched import Job, Scheduler, load_jobs  # noqa: F401
