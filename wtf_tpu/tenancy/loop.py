"""MultiTenantLoop: N campaigns, one batch, per-tenant everything.

Each TenantRuntime is a mini fuzz campaign — its own corpus, mutation
engine (host mangle/byte/tlv or a tenant-scoped devmangle), RNG, crash
dirs, stats and checkpoint cadence — sharing ONE TenancyBackend batch.
Per batch the loop gathers every active tenant's insert plan, executes
them in one `run_batch_tenants` dispatch, and harvests each tenant's
lanes in lane order against its own aggregates, so every per-tenant
decision (mutation draws, corpus insertion order, new-coverage credit,
crash bucketing, devmut lane seeds) is a function of the tenant's OWN
stream and relative lane index — the isolation contract that makes a
lane-subset campaign bit-identical to the same campaign run alone.

Telemetry: per-tenant counters live under `tenant.<name>.*` (execs,
crashes, new-coverage, lane-milliseconds), tenant-tagged JSONL events
segment the shared events.jsonl per tenant (tools/telemetry_report.py
groups them), and the classic `campaign.*` namespace aggregates across
tenants so the heartbeat line keeps its shape.
"""

from __future__ import annotations

import random
import time
from pathlib import Path
from typing import Dict, List, Optional

from wtf_tpu.core.results import (
    Cr3Change, Crash, OverlayFull, TestcaseResult, Timedout,
)
from wtf_tpu.devmut.mutator import DevMangleMutator
from wtf_tpu.fuzz.corpus import Corpus
from wtf_tpu.fuzz.loop import CampaignStats
from wtf_tpu import telemetry
from wtf_tpu.telemetry import Registry, StatsDict
from wtf_tpu.utils.hashing import hex_digest


class TenantStats:
    """`tenant.<name>.*` counters with the CampaignStats accounting
    rule (one shared account() path per result class)."""

    FIELDS = ("testcases", "crashes", "timeouts", "cr3s",
              "overlay_fulls", "new_coverage", "lane_ms", "batches")

    def __init__(self, registry: Registry, name: str):
        self.d = StatsDict(registry, f"tenant.{name}", fields=self.FIELDS)

    def __getitem__(self, key):
        return self.d[key]

    def __setitem__(self, key, value):
        self.d[key] = value

    def account(self, result: TestcaseResult) -> bool:
        self.d["testcases"] += 1
        if isinstance(result, Timedout):
            self.d["timeouts"] += 1
        elif isinstance(result, Cr3Change):
            self.d["cr3s"] += 1
        elif isinstance(result, OverlayFull):
            self.d["overlay_fulls"] += 1
        elif isinstance(result, Crash):
            self.d["crashes"] += 1
            return True
        return False


class TenantDevMutator(DevMangleMutator):
    """Devmangle scoped to one tenant's lane range: quota-sized batches
    on the tenant's own corpus slab and seed stream (relative lane
    indices — bit-exact with the same campaign run alone), generation
    through the plain engine (the byte stream is placement- and
    shard-count-invariant by the per-lane program)."""

    def __init__(self, seed: int, max_len: int, name: str, lane_lo: int,
                 quota: int, **kwargs):
        super().__init__(seed, max_len, **kwargs)
        self.tenant_name = name
        self.lane_lo = lane_lo
        self.quota = quota

    def bind(self, backend, target, registry: Optional[Registry] = None,
             events=None) -> None:
        super().bind(backend, target, registry=registry, events=events)
        # tenant deltas over the campaign bind: quota-sized batches,
        # stats under tenant.<name>.devmut, and the input-region pfns
        # re-translated through the TENANT's own page tables (any lane
        # of its range — the snapshot mapping is per-tenant static)
        self.stats = StatsDict(
            self.registry, f"tenant.{self.tenant_name}.devmut",
            fields=("batches", "generated", "fetched", "corpus_syncs"),
            gauges=("corpus_slots",))
        self.n_lanes = self.quota
        page = 4096
        view = self.runner.view()
        self.pfns = [
            view.translate(self.lane_lo, self.spec.gva + i * page) >> 12
            for i in range(len(self.pfns))]

    def generate(self, rounds: int, data, lens, cumw, seeds):
        import jax.numpy as jnp

        from wtf_tpu.devmut.engine import make_generate

        return make_generate(rounds)(data, lens, cumw, jnp.asarray(seeds))


class TenantRuntime:
    """One campaign-as-job bound to a lane range of the shared batch."""

    def __init__(self, spec, seed: int, runs: int, mutator_name: str,
                 max_len: int, lane_lo: int,
                 crashes_dir: Optional[Path] = None,
                 checkpoint_dir: Optional[Path] = None,
                 checkpoint_every: int = 0,
                 registry: Optional[Registry] = None, events=None,
                 store=None):
        self.spec = spec
        self.name = spec.name
        self.target = spec.target
        self.quota = int(spec.lanes)
        self.lane_lo = lane_lo
        self.seed = seed
        self.runs = runs
        self.mutator_name = mutator_name
        self.max_len = max_len
        self.registry = registry if registry is not None else Registry()
        self.events = events if events is not None else telemetry.NULL
        self.rng = random.Random(seed or None)
        # per-tenant store namespace (wtf_tpu/fleet/store): a root
        # FleetStore hands each tenant its own `tenant-<name>` corpus +
        # crash space — shared fanout layout, zero shared state
        self.store = (store.namespace(f"tenant-{spec.name}")
                      if store is not None else None)
        self.corpus = Corpus(rng=self.rng, store=self.store)
        self.crashes_dir = Path(crashes_dir) if crashes_dir else None
        if self.crashes_dir:
            self.crashes_dir.mkdir(parents=True, exist_ok=True)
        self.checkpoint_dir = (Path(checkpoint_dir) if checkpoint_dir
                               else None)
        self.checkpoint_every = checkpoint_every
        self.stats = TenantStats(self.registry, self.name)
        self.crash_names: set = set()
        self.crash_buckets: set = set()
        self.requeue: List[bytes] = []
        self.requeue_digests: set = set()
        self.batches_done = 0
        if mutator_name == "devmangle":
            self.mutator = TenantDevMutator(
                seed=self.rng.getrandbits(64), max_len=max_len,
                name=self.name, lane_lo=lane_lo, quota=self.quota)
            self.device = True
        else:
            from wtf_tpu.fuzz.mutator import create_mutator

            if mutator_name == "auto":
                from wtf_tpu.fuzz.native_mutator import best_mangle_mutator

                self.mutator = (spec.target.create_mutator(
                    self.rng, max_len)
                    if spec.target.create_mutator is not None
                    else best_mangle_mutator(self.rng, max_len))
            else:
                self.mutator = create_mutator(mutator_name, self.rng,
                                              max_len)
            self.device = False

    @property
    def done(self) -> bool:
        return self.runs > 0 and self.stats["testcases"] >= self.runs

    def seed_corpus(self, inputs_dir) -> None:
        if inputs_dir and Path(inputs_dir).is_dir():
            from wtf_tpu.fuzz.corpus import seed_paths

            for _p, digest, data in seed_paths([inputs_dir],
                                               with_data=True):
                self.corpus.add_digested(data, digest)


class MultiTenantLoop:
    """Drive every active tenant one batch at a time on a shared
    TenancyBackend."""

    def __init__(self, backend, runtimes: List[TenantRuntime],
                 registry: Optional[Registry] = None, events=None,
                 stats_every: float = 10.0):
        self.backend = backend
        self.tenants = runtimes
        self.registry, self.events = telemetry.resolve(
            backend, registry, events)
        self.stats = CampaignStats(self.registry)  # cross-tenant roll-up
        self.stats_every = stats_every
        for t, rt in enumerate(runtimes):
            rt.registry = self.registry
            rt.events = self.events
            rt.stats = TenantStats(self.registry, rt.name)
            if rt.device:
                rt.mutator.bind(backend, rt.target,
                                registry=self.registry,
                                events=self.events)
                rt.mutator.seed_from(rt.corpus)

    # -- per-batch ---------------------------------------------------------
    def _plan(self, rt: TenantRuntime):
        if rt.done:
            return ("host", [])
        if rt.device:
            rt.mutator.take_batch()
            return ("device", rt.mutator)
        requeued = rt.requeue[:rt.quota]
        rt.requeue = rt.requeue[len(requeued):]
        fresh = rt.quota - len(requeued)
        testcases = requeued + [rt.mutator.get_new_testcase(rt.corpus)
                                for _ in range(fresh)]
        return ("host", testcases)

    def _save_crash(self, rt: TenantRuntime, data: bytes, result: Crash,
                    bucket: Optional[str]) -> None:
        name = result.name or f"crash-{hex_digest(data)[:16]}"
        bucket = bucket or name
        new = bucket not in rt.crash_buckets
        rt.crash_buckets.add(bucket)
        rt.crash_names.add(name)
        if rt.crashes_dir:
            from wtf_tpu.utils.atomicio import atomic_write_bytes

            try:
                if rt.store is not None:
                    digest, _ = rt.store.put(data, kind="crash",
                                             name=name, bucket=bucket)
                    if rt.store.has(digest):
                        rt.store.link_into(rt.crashes_dir, digest,
                                           name=name)
                else:
                    atomic_write_bytes(rt.crashes_dir / name, data)
            except OSError as e:
                import logging

                logging.getLogger(__name__).warning(
                    "crash save failed for %r (%s): %s", name, rt.name, e)
                self.events.emit("error", kind="crash-save", name=name,
                                 tenant=rt.name, detail=str(e))
        self.events.emit("crash", tenant=rt.name, name=name,
                         size=len(data), new=new, bucket=bucket)

    def _harvest_tenant(self, t: int, rt: TenantRuntime, plan,
                        results) -> int:
        from wtf_tpu.triage.bucket import bucket_of

        kind, payload = plan
        lo = rt.lane_lo
        crashes = 0
        timeouts_before = rt.stats["timeouts"]
        if kind == "device":
            rt.mutator.prelaunch()
            wanted = [rel for rel in range(rt.quota)
                      if self.backend.lane_found_new_coverage(lo + rel)
                      or isinstance(results[lo + rel], Crash)]
            datas = rt.mutator.fetch(wanted)
            lanes = [(rel, datas.get(rel, b"")) for rel in range(rt.quota)]
            requeue = False
        else:
            lanes = list(enumerate(payload))
            requeue = True
        for rel, data in lanes:
            lane = lo + rel
            result = results[lane]
            self.stats.account(result)
            if rt.stats.account(result):
                crashes += 1
                self._save_crash(rt, data, result,
                                 bucket_of(self.backend, lane, result))
            elif requeue and isinstance(result, OverlayFull):
                digest = hex_digest(data)
                if digest not in rt.requeue_digests:
                    rt.requeue_digests.add(digest)
                    rt.requeue.append(data)
            if self.backend.lane_found_new_coverage(lane):
                rt.stats["new_coverage"] += 1
                self.stats.new_coverage += 1
                if rt.corpus.add(data):
                    rt.mutator.on_new_coverage(data)
                    self.events.emit("new-coverage", tenant=rt.name,
                                     digest=hex_digest(data),
                                     size=len(data))
        timeouts = rt.stats["timeouts"] - timeouts_before
        if timeouts:
            self.events.emit("timeout", tenant=rt.name, count=timeouts)
        return crashes

    def run_one_batch(self) -> int:
        spans = self.registry.spans
        t0 = time.time()
        active = [t for t, rt in enumerate(self.tenants) if not rt.done]
        with spans.span("mutate"):
            plans = [self._plan(rt) for rt in self.tenants]
        with spans.span("execute"):
            results = self.backend.run_batch_tenants(plans)
        crashes = 0
        with spans.span("harvest"):
            for t in active:
                rt = self.tenants[t]
                crashes += self._harvest_tenant(t, rt, plans[t], results)
                rt.batches_done += 1
        with spans.span("restore"):
            for t in active:
                with self.backend.tenant_context(t):
                    self.tenants[t].target.restore()
            self.backend.restore()
        wall_ms = int((time.time() - t0) * 1000)
        for t in active:
            rt = self.tenants[t]
            rt.stats["lane_ms"] += wall_ms * rt.quota
            rt.stats["batches"] += 1
        self._maybe_checkpoint()
        self.stats.maybe_heartbeat(
            self.events, self.registry,
            lambda: self.stats.line(
                sum(len(rt.corpus) for rt in self.tenants)),
            every=self.stats_every, print_stats=True)
        return crashes

    def _maybe_checkpoint(self) -> None:
        from wtf_tpu.tenancy.state import save_tenant

        for t, rt in enumerate(self.tenants):
            if not (rt.checkpoint_dir and rt.checkpoint_every):
                continue
            if rt.done or rt.batches_done == 0 \
                    or rt.batches_done % rt.checkpoint_every:
                continue
            self.checkpoint_tenant(t)

    def checkpoint_tenant(self, t: int) -> Optional[dict]:
        """Checkpoint one tenant now (cadence hits and the scheduler's
        preemption both land here).  Best-effort like the campaign
        checkpoint: a full disk degrades with a warning, never aborts."""
        from wtf_tpu.tenancy.state import save_tenant

        rt = self.tenants[t]
        if rt.checkpoint_dir is None:
            return None
        try:
            info = save_tenant(self.backend, rt, t, rt.checkpoint_dir)
        except OSError as e:
            import logging

            logging.getLogger(__name__).warning(
                "tenant %s checkpoint failed at batch %d: %s",
                rt.name, rt.batches_done, e)
            self.events.emit("error", kind="checkpoint-write",
                             tenant=rt.name, batch=rt.batches_done,
                             detail=str(e))
            return None
        self.registry.counter(f"tenant.{rt.name}.checkpoints").inc()
        self.events.emit("checkpoint", tenant=rt.name,
                         batch=rt.batches_done, bytes=info["bytes"],
                         path=info["path"])
        return info

    def resume_tenant(self, t: int) -> Optional[int]:
        """Restore tenant t from its checkpoint dir when one exists."""
        from wtf_tpu.resume.checkpoint import CKPT_NAME
        from wtf_tpu.tenancy.state import restore_tenant

        rt = self.tenants[t]
        if (rt.checkpoint_dir is None
                or not (rt.checkpoint_dir / CKPT_NAME).exists()):
            return None
        return restore_tenant(self.backend, rt, t, rt.checkpoint_dir)

    def run(self, max_batches: int = 1 << 20) -> Dict[str, TenantStats]:
        """Run until every tenant's testcase budget is met."""
        for _ in range(max_batches):
            if all(rt.done for rt in self.tenants):
                break
            self.run_one_batch()
        return {rt.name: rt.stats for rt in self.tenants}
