"""The stacked image table: many snapshots behind one dispatch operand.

Every tenant's `PhysMem` packs into ONE device image:

  pages        all tenants' present pages concatenated behind the shared
               zero page (slot 0), total row count padded to a power of
               two (the same shape-polymorphism-by-padding policy as
               PhysMem.from_pages);
  frame_table  one pfn->slot row per tenant, padded to a COMMON page
               span (the max of the tenants' spans) — absent/padded pfns
               resolve to slot 0, the shared zero page, preserving the
               reference's zero-fill semantics per tenant;
  tenant       the per-lane row selector (int32[L]) — which base image a
               lane interprets against.

Heterogeneity is thereby pure DATA: the compiled step ladder sees one
pages array, one [T, span] table and one selector vector, so any tenant
mix at a given lane count runs the SAME program bytes (the lint budget
family pins this, analysis/rules.py tenancy rules).

`build_batch_state` also concatenates per-tenant Machine batches (each
lane initialized from its tenant's CpuState — per-lane cr3/rip/MSRs are
already per-lane state, so the heterogeneous machine needs no new
fields) and returns the host-side routing tables the Runner's servicing
loop uses (per-lane PhysMem / CpuState).
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from wtf_tpu.interp.machine import Machine, machine_init
from wtf_tpu.mem.physmem import MemImage, PAGE_WORDS, _next_pow2

MAX_TENANTS = 1 << 15  # the tag_key tenant field (bits 48..62)


def stack_images(physmems: Sequence) -> MemImage:
    """Pack tenants' PhysMems into one stacked MemImage (tenant=None —
    the caller attaches the per-lane selector)."""
    if not physmems:
        raise ValueError("stack_images needs at least one tenant image")
    if len(physmems) > MAX_TENANTS:
        raise ValueError(f"{len(physmems)} tenants exceed the "
                         f"{MAX_TENANTS} tag-key limit")
    span = max(pm.image.frame_table.shape[-1] for pm in physmems)
    tables = np.zeros((len(physmems), span), dtype=np.int32)
    bodies: List[np.ndarray] = []
    cur = 1  # slot 0 stays the shared zero page
    for t, pm in enumerate(physmems):
        pages_np = np.asarray(pm.image.pages)          # [slots_t, PW]
        body = pages_np[1:]                            # drop its zero page
        tbl = np.asarray(pm.image.frame_table)[0]      # [span_t]
        tables[t, :tbl.shape[0]] = np.where(tbl != 0, tbl + (cur - 1), 0)
        bodies.append(body)
        cur += body.shape[0]
    total = _next_pow2(cur)
    stacked = np.zeros((total, PAGE_WORDS), dtype=np.uint64)
    pos = 1
    for body in bodies:
        stacked[pos:pos + body.shape[0]] = body
        pos += body.shape[0]
    return MemImage(pages=jnp.asarray(stacked),
                    frame_table=jnp.asarray(tables))


def _concat_machines(machines: Sequence[Machine]) -> Machine:
    if len(machines) == 1:
        return machines[0]
    return jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *machines)


@dataclasses.dataclass
class BatchState:
    """What a heterogeneous Runner dispatches and routes with."""

    image: MemImage            # stacked, tenant selector populated
    machine: Machine           # per-tenant lane blocks concatenated
    template: Machine          # pristine restore template, same layout
    tenant_of_lane: np.ndarray  # int32[L]
    physmems: List            # per-tenant PhysMem (host reads)
    cpus: List                # per-tenant CpuState (oracle / delivery)


def build_batch_state(tenants: Sequence, n_lanes: int, uop_capacity: int,
                      overlay_slots: int, edge_bits: int) -> BatchState:
    """Build the heterogeneous batch from a tenant table.

    `tenants` is a sequence of objects with `.snapshot` (a loaded
    Snapshot) and `.lanes` (the tenant's lane quota); lane ranges are
    assigned in table order and must tile the batch exactly (the
    scheduler's placement pads quotas to fill)."""
    quotas = [int(t.lanes) for t in tenants]
    if any(q <= 0 for q in quotas):
        raise ValueError(f"tenant lane quotas must be positive: {quotas}")
    if sum(quotas) > n_lanes:
        raise ValueError(
            f"tenant quotas {quotas} sum to {sum(quotas)} but the batch "
            f"has only {n_lanes} lanes")
    physmems = [t.snapshot.physmem for t in tenants]
    cpus = [t.snapshot.cpu for t in tenants]
    image = stack_images(physmems)
    tenant_of_lane = np.repeat(
        np.arange(len(tenants), dtype=np.int32), quotas)
    machines, templates = [], []
    for t, q in zip(tenants, quotas):
        machines.append(machine_init(
            t.snapshot.cpu, q, uop_capacity, overlay_slots, edge_bits))
        templates.append(machine_init(
            t.snapshot.cpu, q, uop_capacity, overlay_slots=0,
            edge_bits=edge_bits))
    pad = n_lanes - sum(quotas)
    if pad:
        # unplaced trailing lanes idle (the backend marks them OK before
        # every run); they carry tenant 0's state so no extra image rows
        tenant_of_lane = np.concatenate(
            [tenant_of_lane, np.zeros(pad, dtype=np.int32)])
        machines.append(machine_init(
            tenants[0].snapshot.cpu, pad, uop_capacity, overlay_slots,
            edge_bits))
        templates.append(machine_init(
            tenants[0].snapshot.cpu, pad, uop_capacity, overlay_slots=0,
            edge_bits=edge_bits))
    return BatchState(
        image=image._replace(tenant=jnp.asarray(tenant_of_lane)),
        machine=_concat_machines(machines),
        template=_concat_machines(templates),
        tenant_of_lane=tenant_of_lane,
        physmems=physmems,
        cpus=cpus,
    )
