"""Scheduler tier: campaigns as JOBS over the multi-tenant batch.

The serving-system half of wtf_tpu/tenancy (`wtf-tpu sched`): a jobs
table (jobs.json or programmatic `Job`s) is placed onto the lane budget
of one (possibly mesh-sharded) device batch by priority and lane quota,
runs in quantum-sized rounds, and is preempted through the per-tenant
checkpoint (state.py) — the exact contract of multi-tenant inference
serving with persistent device programs (PAPERS.md: Concordia):

  placement   first-fit by (priority desc, least-recently-run, submit
              order) until the lane budget is spent.  Each distinct
              placement is a fresh stacked image table + backend (an
              UNCHANGED placement stays live across rounds — no rebuild,
              no checkpoint restore, so a solo job compiles once); all
              per-job state crossing placements travels via the
              placement-free tenant checkpoint.
  quantum     each round runs at most `quantum` batches, then every
              still-unfinished placed job checkpoints at the batch
              boundary.  When jobs are waiting, that checkpoint IS the
              preemption: the next round's placement hands the lanes to
              the waiting job, and the preempted one resumes later
              bit-identically (tests/test_tenancy.py preemption sweep).
  completion  a job is done when its testcase budget (`runs`) is met —
              counters restore with the checkpoint, so budgets span
              preemptions.

Telemetry: `sched.*` counters (rounds, placements, preemptions,
completions) + `sched-round`/`sched-preempt`/`sched-complete` JSONL
events alongside the per-tenant `tenant.<name>.*` namespaces the loop
maintains.
"""

from __future__ import annotations

import dataclasses
import json
import re
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from wtf_tpu import telemetry
from wtf_tpu.telemetry import Registry

DEFAULT_MAX_LEN = 1 << 20

# job names key `tenant.<name>.*` counters (dots are the namespace
# separator) and name dirs under --workdir (separators would escape it)
_NAME_RE = re.compile(r"^[A-Za-z0-9_-]+$")


@dataclasses.dataclass
class Job:
    """One campaign-as-job row of the jobs table."""

    name: str                  # tenant id (unique; names dirs + counters)
    target: str                # registered target name (--name equivalent)
    lanes: int                 # lane quota per placement
    runs: int                  # testcase budget (job done when met)
    priority: int = 0          # higher places first
    seed: int = 0
    mutator: str = "auto"
    max_len: int = DEFAULT_MAX_LEN
    inputs: Optional[str] = None    # seed corpus dir
    checkpoint_every: int = 0       # extra cadence inside a quantum
    # -- runtime state (scheduler-owned) --------------------------------
    done: bool = False
    seq: int = 0               # submit order (placement tiebreak)
    last_round: int = -1       # most recent round placed (round-robin)
    batches_done: int = 0
    testcases: int = 0
    crashes: int = 0
    preemptions: int = 0


def load_jobs(path) -> List[Job]:
    """Parse a jobs.json: either {"jobs": [...]} or a bare list of job
    objects.  Field names match Job; unknown keys are an error (a typoed
    "lanes" must not silently fall back)."""
    doc = json.loads(Path(path).read_text())
    rows = doc.get("jobs") if isinstance(doc, dict) else doc
    if not isinstance(rows, list) or not rows:
        raise ValueError(f"{path}: expected a non-empty job list "
                         '(either {"jobs": [...]} or a bare list)')
    fields = {f.name for f in dataclasses.fields(Job)}
    config_fields = fields - {"done", "seq", "last_round", "batches_done",
                              "testcases", "crashes", "preemptions"}
    jobs = []
    for i, row in enumerate(rows):
        unknown = set(row) - config_fields
        if unknown:
            raise ValueError(
                f"{path}: job {i} has unknown fields {sorted(unknown)} "
                f"(known: {sorted(config_fields)})")
        missing = {"name", "target", "lanes", "runs"} - set(row)
        if missing:
            raise ValueError(
                f"{path}: job {i} is missing {sorted(missing)}")
        jobs.append(Job(seq=i, **row))
    names = [j.name for j in jobs]
    if len(set(names)) != len(names):
        raise ValueError(f"{path}: duplicate job names in {names}")
    for job in jobs:
        if job.lanes <= 0 or job.runs <= 0:
            raise ValueError(
                f"{path}: job {job.name!r} needs lanes > 0 and runs > 0")
        if not _NAME_RE.match(job.name):
            raise ValueError(
                f"{path}: job name {job.name!r} must match "
                "[A-Za-z0-9_-]+ — it keys tenant.<name>.* counters "
                "(dots are the namespace separator) and names a "
                "directory under --workdir")
    return jobs


class Scheduler:
    """Drive a jobs table to completion over one shared lane budget."""

    def __init__(self, jobs: Sequence[Job], n_lanes: int, workdir,
                 limit: int = 0, quantum: int = 4,
                 mesh_devices: Optional[int] = None,
                 registry: Optional[Registry] = None, events=None,
                 backend_tuning: Optional[dict] = None,
                 stats_every: float = 10.0, store=None):
        if not jobs:
            raise ValueError("scheduler needs at least one job")
        for job in jobs:
            if job.lanes > n_lanes:
                raise ValueError(
                    f"job {job.name!r} wants {job.lanes} lanes but the "
                    f"batch has {n_lanes} — no placement can ever fit it")
            if not _NAME_RE.match(job.name):
                raise ValueError(
                    f"job name {job.name!r} must match [A-Za-z0-9_-]+ "
                    "(telemetry namespace key and workdir subdirectory)")
        self.jobs = list(jobs)
        self.n_lanes = n_lanes
        self.workdir = Path(workdir)
        self.limit = limit
        self.quantum = max(int(quantum), 1)
        self.mesh_devices = mesh_devices
        self.registry, self.events = telemetry.resolve(
            None, registry, events)
        self.backend_tuning = dict(backend_tuning or {})
        self.stats_every = stats_every
        # root content-addressed store (wtf_tpu/fleet/store): each job
        # gets its own tenant-<name> namespace carved out at placement
        self.store = store
        self._snapshots: Dict[str, object] = {}  # target name -> Snapshot
        # live placement carried across rounds: when _place() returns
        # the same job set, the backend/loop are reused instead of a
        # checkpoint-restore round trip (a solo job compiles ONCE)
        self._live: Optional[tuple] = None  # (names, backend, runtimes,
        #                                      loop)
        self.rounds = 0

    # -- placement ---------------------------------------------------------
    def _place(self) -> List[Job]:
        """First-fit into the lane budget by (priority desc, least-
        recently-run, submit order).  The least-recently-run key is what
        turns the quantum checkpoint into preemptive round-robin within
        a priority class."""
        order = sorted((j for j in self.jobs if not j.done),
                       key=lambda j: (-j.priority, j.last_round, j.seq))
        placed, free = [], self.n_lanes
        for job in order:
            if job.lanes <= free:
                placed.append(job)
                free -= job.lanes
        return placed

    def _snapshot_for(self, target) -> object:
        """One snapshot per target per scheduler (the base image is
        immutable; re-loading per round would only slow placement)."""
        snap = self._snapshots.get(target.name)
        if snap is None:
            if target.snapshot is None:
                raise ValueError(
                    f"target {target.name!r} has no snapshot factory — "
                    "sched jobs need self-contained targets")
            snap = target.snapshot()
            self._snapshots[target.name] = snap
        return snap

    # -- one scheduling round ---------------------------------------------
    def _build_placement(self, placed: List[Job]):
        """Fresh stacked image + backend + runtimes for a placement;
        every job resumes from its checkpoint when one exists."""
        from wtf_tpu.harness.targets import Targets
        from wtf_tpu.tenancy.backend import TenantSpec, \
            create_tenancy_backend
        from wtf_tpu.tenancy.loop import MultiTenantLoop, TenantRuntime

        targets = Targets.instance()
        specs = [TenantSpec(name=job.name, target=targets.get(job.target),
                            snapshot=self._snapshot_for(
                                targets.get(job.target)),
                            lanes=job.lanes)
                 for job in placed]
        backend = create_tenancy_backend(
            specs, self.n_lanes, mesh_devices=self.mesh_devices,
            limit=self.limit, registry=self.registry, events=self.events,
            **self.backend_tuning)
        with self.registry.spans.span("sched-place"):
            backend.initialize()
            for t, spec in enumerate(specs):
                with backend.tenant_context(t):
                    spec.target.init(backend)
        runtimes = []
        for t, (job, spec) in enumerate(zip(placed, specs)):
            jobdir = self.workdir / job.name
            rt = TenantRuntime(
                spec, seed=job.seed, runs=job.runs,
                mutator_name=job.mutator, max_len=job.max_len,
                lane_lo=int(backend._lane_lo[t]),
                crashes_dir=jobdir / "crashes",
                checkpoint_dir=jobdir / "checkpoint",
                checkpoint_every=job.checkpoint_every,
                registry=self.registry, events=self.events,
                store=self.store)
            rt.seed_corpus(job.inputs)
            runtimes.append(rt)
        loop = MultiTenantLoop(backend, runtimes, registry=self.registry,
                               events=self.events,
                               stats_every=self.stats_every)
        for t, job in enumerate(placed):
            resumed = loop.resume_tenant(t)
            if resumed is not None:
                print(f"[sched] {job.name}: resumed at batch {resumed}")
        self.registry.counter("sched.builds").inc()
        return backend, runtimes, loop

    def _run_round(self, placed: List[Job]) -> None:
        names = tuple(j.name for j in placed)
        if self._live is not None and self._live[0] == names:
            # same placement as last round and state is live: keep the
            # backend/loop (no re-upload, no checkpoint restore)
            backend, runtimes, loop = self._live[1:]
        else:
            self._live = None  # release the old device state first
            backend, runtimes, loop = self._build_placement(placed)
            self._live = (names, backend, runtimes, loop)
        self.events.emit("sched-round", round=self.rounds,
                         placed=[j.name for j in placed],
                         lanes=[j.lanes for j in placed])
        batches = 0
        while batches < self.quantum and not all(rt.done
                                                 for rt in runtimes):
            loop.run_one_batch()
            batches += 1
        waiting = [j.name for j in self.jobs
                   if not j.done and j not in placed]
        for t, (job, rt) in enumerate(zip(placed, runtimes)):
            job.last_round = self.rounds
            job.batches_done = rt.batches_done
            job.testcases = int(rt.stats["testcases"])
            job.crashes = int(rt.stats["crashes"])
            # quantum boundary: persist so the NEXT placement (which may
            # not include this job) resumes bit-identically; for a DONE
            # job this is the final results checkpoint (corpus manifest,
            # coverage, crash buckets survive the scheduler exit)
            loop.checkpoint_tenant(t)
            if rt.done:
                job.done = True
                self.registry.counter("sched.completions").inc()
                self.events.emit("sched-complete", tenant=job.name,
                                 testcases=job.testcases,
                                 batches=job.batches_done)
                print(f"[sched] {job.name}: done "
                      f"({job.testcases} testcases, "
                      f"{job.crashes} crashes)")
                continue
            if waiting:
                job.preemptions += 1
                self.registry.counter("sched.preemptions").inc()
                self.events.emit("sched-preempt", tenant=job.name,
                                 batch=rt.batches_done,
                                 waiting=waiting)
                print(f"[sched] {job.name}: preempted at batch "
                      f"{rt.batches_done} (waiting: "
                      f"{', '.join(waiting)})")
        self.registry.counter("sched.rounds").inc()
        self.registry.counter("sched.placements").inc(len(placed))

    # -- driver ------------------------------------------------------------
    def run(self, max_rounds: int = 1 << 12) -> Dict[str, dict]:
        """Round-robin the jobs table until every job's budget is met
        (or max_rounds).  Returns {job name: summary dict}."""
        t0 = time.time()
        while not all(j.done for j in self.jobs):
            if self.rounds >= max_rounds:
                break
            placed = self._place()
            if not placed:
                break  # unreachable: every job fits alone (ctor check)
            self._run_round(placed)
            self.rounds += 1
        self.registry.gauge("sched.wall_seconds").set(
            round(time.time() - t0, 3))
        return {
            job.name: {
                "done": job.done,
                "testcases": job.testcases,
                "crashes": job.crashes,
                "batches": job.batches_done,
                "preemptions": job.preemptions,
            }
            for job in self.jobs
        }
