"""Per-tenant checkpoints: the scheduler's preemption primitive.

A tenant checkpoint reuses the PR-8 campaign-checkpoint format
(wtf_tpu/resume: digest-embedded atomic doc, content-addressed corpus
blobs, `.prev` fallback) with two placement-freeing twists:

  decode cache   only the TENANT's entries are persisted, untagged and
                 in insertion order — a resumed placement re-tags them
                 with whatever tenant index the scheduler assigns next;
  coverage       the tenant's cov bit-plane is REMAPPED from global
                 decode-cache entry indices to tenant-local positions
                 (bit j = the tenant's j-th entry).  Within-tenant
                 insertion order is placement-invariant (lane order is
                 preserved inside a tenant's range), so the local plane
                 equals what a solo run of the campaign would hold —
                 restore scatters it back through the indices the new
                 placement's cache assigns.  Edge planes are hash-
                 indexed and travel as-is.

Checkpoint tenant A at a batch boundary, hand its lanes to tenant B,
resume A later — bit-identically (tests/test_tenancy.py preemption
sweep; the acceptance drill rides `wtf-tpu sched`).
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Sequence, Tuple

import numpy as np

from wtf_tpu.resume.checkpoint import (
    CheckpointError, _rng_state, _set_rng_state, load_campaign,
    restore_corpus, write_checkpoint,
)
from wtf_tpu.utils.hashing import hex_digest

TENANT_COUNTER_KINDS = ("", ".devmut")  # tenant.<name>[kind].* namespaces


def extract_bits(words: np.ndarray, idxs: Sequence[int]) -> np.ndarray:
    """Global bit-plane -> tenant-local plane: local bit j is global bit
    idxs[j]."""
    out = np.zeros((max(len(idxs), 1) + 31) // 32, dtype=np.uint32)
    for j, i in enumerate(idxs):
        if (int(words[i >> 5]) >> (i & 31)) & 1:
            out[j >> 5] |= np.uint32(1 << (j & 31))
    return out


def scatter_bits(local: np.ndarray, idxs: Sequence[int],
                 n_words: int) -> np.ndarray:
    """Tenant-local plane -> global bit-plane under a new index map."""
    out = np.zeros(n_words, dtype=np.uint32)
    for j, i in enumerate(idxs):
        if (int(local[j >> 5]) >> (j & 31)) & 1:
            out[i >> 5] |= np.uint32(1 << (i & 31))
    return out


def _tenant_prefixes(name: str) -> Tuple[str, ...]:
    return tuple(f"tenant.{name}{kind}." for kind in TENANT_COUNTER_KINDS)


def save_tenant(backend, rt, t: int, directory) -> dict:
    """Checkpoint tenant `rt` (TenantRuntime at table index `t`) into
    `directory`.  Call at a batch boundary (machine freshly restored)."""
    runner = backend.runner
    cov, edge = backend.tenant_coverage_state(t)
    entries = runner.cache.tenant_entries(t)
    idxs = [e[0] for e in entries]
    mut_rng = getattr(rt.mutator, "rng", None)
    state = {
        "config": {
            "kind": "tenant",
            "target": rt.target.name,
            "lanes": rt.quota,
            "mutator": type(rt.mutator).__name__,
        },
        "batches": rt.batches_done,
        "stats": rt.registry.counters_state(_tenant_prefixes(rt.name)),
        "crash_names": sorted(rt.crash_names),
        "crash_buckets": sorted(rt.crash_buckets),
        "requeue": [data.hex() for data in rt.requeue],
        "requeue_digests": sorted(rt.requeue_digests),
        "rng": {
            "corpus": _rng_state(rt.rng),
            "mutator": ("shared" if mut_rng is rt.rng
                        else _rng_state(mut_rng)),
        },
        "mutator": rt.mutator.checkpoint_state(),
        "coverage": {"cov": extract_bits(cov, idxs), "edge": edge},
        "runner": {
            "cache": [(rip, raw, p0, p1)
                      for (_i, rip, raw, p0, p1) in entries],
            "smc_updates": [[r, n]
                            for (tt, r), n in runner._smc_updates.items()
                            if tt == t],
        },
        "corpus_manifest": [hex_digest(data) for data in rt.corpus],
    }
    return write_checkpoint(state, directory, list(rt.corpus))


def restore_tenant(backend, rt, t: int, directory) -> int:
    """Install a tenant checkpoint into a freshly-placed runtime (backend
    initialized, target init done).  Returns the batch index the tenant
    resumes after."""
    state, _fell_back = load_campaign(directory)
    cfg = state.get("config", {})
    checks = (("target", rt.target.name), ("lanes", rt.quota),
              ("mutator", type(rt.mutator).__name__))
    for key, current in checks:
        saved = cfg.get(key)
        if saved is not None and saved != current:
            raise CheckpointError(
                f"tenant checkpoint {key}={saved!r} but this placement "
                f"has {key}={current!r} — resume needs the same target, "
                "lane quota, and mutation engine (lane RANGE and mesh "
                "layout may differ; state is placement-free)")
    restore_corpus(rt.corpus, state, directory)
    rng = state.get("rng", {})
    _set_rng_state(rt.rng, rng.get("corpus"))
    mut_state = rng.get("mutator")
    if mut_state != "shared":
        _set_rng_state(getattr(rt.mutator, "rng", None), mut_state)
    rt.crash_names = set(state.get("crash_names", []))
    rt.crash_buckets = set(state.get("crash_buckets", []))
    rt.requeue = [bytes.fromhex(h) for h in state.get("requeue", [])]
    rt.requeue_digests = set(state.get("requeue_digests", []))
    runner = backend.runner
    # re-tag the tenant's decode entries under the NEW placement index
    # and record the global indices they land at — the coverage remap
    from wtf_tpu.cpu.decoder import decode

    saved_cache = state.get("runner", {}).get("cache", [])
    idxs: List[int] = []
    for rip, raw, p0, p1 in saved_cache:
        idxs.append(runner.cache.add(int(rip), decode(raw, int(rip)),
                                     int(p0), int(p1), tenant=t))
    coverage = state.get("coverage", {})
    n_words = backend.tenant_coverage_state(t)[0].shape[0]
    backend.restore_tenant_coverage(
        t, scatter_bits(coverage["cov"], idxs, n_words),
        np.asarray(coverage["edge"]))
    for r, n in state.get("runner", {}).get("smc_updates", []):
        runner._smc_updates[(t, int(r))] = int(n)
    rt.mutator.restore_state(state.get("mutator", {}))
    rt.registry.restore_counters(state.get("stats", {}))
    rt.batches_done = int(state.get("batches", 0))
    rt.registry.counter(f"tenant.{rt.name}.resumes").inc()
    rt.events.emit("tenant-resume", tenant=rt.name,
                   batch=rt.batches_done, corpus=len(rt.corpus),
                   directory=str(Path(directory)))
    return rt.batches_done
