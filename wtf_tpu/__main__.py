"""`python -m wtf_tpu` -> CLI (wtf_tpu/cli.py)."""

import sys

from wtf_tpu.cli import main

sys.exit(main())
