"""`python -m wtf_tpu` -> CLI (wtf_tpu/cli.py)."""

from wtf_tpu.cli import console_main

console_main()
