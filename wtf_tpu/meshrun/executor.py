"""shard_map-wrapped device executors: the mesh dispatch units.

Where the single-device runner dispatches `make_run_chunk` (a global
while_loop over the whole batch), the mesh runner dispatches these: the
SAME chunk body runs per shard under `shard_map`, so

  * the while-loop's "any lane still RUNNING" condition is shard-LOCAL —
    shards early-exit independently instead of paying a cross-device
    all-reduce per loop iteration;
  * machine state never crosses the interconnect (every per-lane op is
    shard-local by construction — the lint's `mesh` family pins the
    compiled program to zero gather-class collectives);
  * the chunk program ends with the shard-local u32 OR + [words, 32]
    boolean all-reduce of the cov/edge bitmaps, so the host reads back
    ONE merged bitmap per chunk instead of gathering [lanes, words]
    planes — the only cross-chip traffic of the hot loop.

The fused Pallas kernel (interp/pstep.py) wraps the same way: the kernel
grid runs over the shard's local lanes, and its XLA resume leg doubles
as the merged-coverage producer.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from wtf_tpu.interp.step import make_run_chunk
from wtf_tpu.mem.physmem import MemImage
from wtf_tpu.meshrun.mesh import LANE_AXIS
from wtf_tpu.meshrun.reduce import bitplane_or

# pages/frame table replicated on every chip; the per-lane tenant
# selector (wtf_tpu/tenancy) shards with the lane axis.  Prefix specs
# match images with tenant=None too (the empty subtree takes no spec).
IMAGE_SPEC = MemImage(pages=P(), frame_table=P(), tenant=P(LANE_AXIS))

_MESH_CHUNK_CACHE: dict = {}
_MESH_FUSED_CACHE: dict = {}


def _chunk_with_coverage(body):
    """Wrap a machine->machine chunk body so the program also emits the
    cross-shard merged cov/edge bitmaps (shard-local OR, then one
    boolean all-reduce over the concatenated planes)."""

    def local(tab, image, machine, limit):
        m = body(tab, image, machine, limit)
        loc = jnp.bitwise_or.reduce(
            jnp.concatenate([m.cov, m.edge], axis=1), axis=0)
        merged = bitplane_or(loc, LANE_AXIS)
        wc = m.cov.shape[1]
        return m, merged[:wc], merged[wc:]

    return local


def make_mesh_chunk(n_steps: int, mesh, donate: Optional[bool] = None,
                    jit: bool = True):
    """Build (or fetch) the mesh chunk executor:
    (tab, image, machine, limit) -> (machine', merged_cov, merged_edge)
    with tab/image replicated, machine lane-sharded, merged replicated.

    Same memoization/donation policy as step.make_run_chunk (donation is
    unsound on the XLA CPU backend — see that docstring).  jit=False
    returns the undecorated shard_map callable, a fresh closure per call
    — the static analyzer's trace probe, exactly like make_run_chunk's."""
    if donate is None:
        donate = jax.default_backend() != "cpu"
    key = (n_steps, mesh, donate)
    if jit:
        cached = _MESH_CHUNK_CACHE.get(key)
        if cached is not None:
            return cached
    body = make_run_chunk(n_steps, donate=donate, jit=False)
    fn = shard_map(
        _chunk_with_coverage(body), mesh=mesh,
        in_specs=(P(), IMAGE_SPEC, P(LANE_AXIS), P()),
        out_specs=(P(LANE_AXIS), P(), P()),
        check_rep=False)
    if not jit:
        return fn
    fn = jax.jit(fn, donate_argnums=(2,) if donate else ())
    _MESH_CHUNK_CACHE[key] = fn
    return fn


def make_mesh_fused(k_steps: int, mesh):
    """The fused Pallas kernel (interp/pstep.py) per shard: the pallas
    grid spans the shard's LOCAL lanes (the kernel reads its lane count
    from the block it is handed), machine stays lane-sharded, and no
    collective is emitted — parked lanes are resumed by the mesh resume
    leg, which also carries the merged-coverage all-reduce."""
    key = (k_steps, mesh)
    cached = _MESH_FUSED_CACHE.get(key)
    if cached is not None:
        return cached
    from wtf_tpu.interp.pstep import make_run_fused

    run_fused = make_run_fused(k_steps)
    fn = jax.jit(shard_map(
        lambda tab, image, machine, limit: run_fused(
            tab, image, machine, limit),
        mesh=mesh,
        in_specs=(P(), IMAGE_SPEC, P(LANE_AXIS), P()),
        out_specs=P(LANE_AXIS),
        check_rep=False))
    _MESH_FUSED_CACHE[key] = fn
    return fn


def make_mesh_resume(n_steps: int, mesh, donate: Optional[bool] = None):
    """The fused ladder's XLA resume leg per shard (see pstep.
    make_run_resume for the park/hold/release contract), extended like
    make_mesh_chunk to emit the merged cov/edge bitmaps — the fused
    mesh round's one collective rides here."""
    if donate is None:
        donate = jax.default_backend() != "cpu"
    key = ("resume", n_steps, mesh, donate)
    cached = _MESH_CHUNK_CACHE.get(key)
    if cached is not None:
        return cached
    from wtf_tpu.interp.pstep import make_run_resume

    # the memoized single-device executor is jitted; tracing through it
    # inside shard_map inlines the program, donation stays on the outer
    run_resume = make_run_resume(n_steps, donate=False)
    fn = jax.jit(shard_map(
        _chunk_with_coverage(run_resume), mesh=mesh,
        in_specs=(P(), IMAGE_SPEC, P(LANE_AXIS), P()),
        out_specs=(P(LANE_AXIS), P(), P()),
        check_rep=False), donate_argnums=(2,) if donate else ())
    _MESH_CHUNK_CACHE[key] = fn
    return fn
