"""MeshBackend: a whole device mesh behind the one-logical-backend seam.

`FuzzLoop`, `BatchClient`, the campaign/fuzz CLI drivers and every target
module see an ordinary batched backend whose lane count happens to be
`lanes_per_chip x chips` — the reference's process-per-core fan-out
(README.md:34-110) collapsed into one process driving one SPMD program.

Deltas against the plain TpuBackend, all behind existing seams:

  * the runner is a MeshRunner (machine lane-sharded, image/uop table
    replicated, shard_map executors);
  * the batch coverage merge is the shard-aware variant of the SAME
    prefix-credit core (meshrun/reduce.make_mesh_merge) with aggregates
    replicated on every chip — per-batch interconnect bytes are the
    [shards, words] union gather, nothing else;
  * `mesh.devices` / `mesh.lanes_per_shard` gauges join the telemetry
    registry, and the per-shard `device.shard_instructions` counters
    (MeshRunner.fold_device_counters) feed tools/telemetry_report.py's
    mesh section.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from wtf_tpu.backend.tpu import TpuBackend
from wtf_tpu.meshrun.mesh import make_mesh, replicated_sharding
from wtf_tpu.meshrun.reduce import make_mesh_merge
from wtf_tpu.meshrun.runner import MeshRunner


class MeshBackend(TpuBackend):
    """TpuBackend whose batch spans a lane mesh (CLI: --mesh-devices)."""

    def __init__(self, snapshot, n_lanes: int = 64,
                 mesh_devices: Optional[int] = None, **kwargs):
        super().__init__(snapshot, n_lanes=n_lanes, **kwargs)
        # 0 / None = every device jax can see (the CLI's "auto")
        self._mesh_devices = mesh_devices or None
        self.mesh = None

    def initialize(self) -> None:
        self.mesh = make_mesh(self._mesh_devices)
        self.runner = MeshRunner(self.snapshot, self.n_lanes,
                                 mesh=self.mesh, registry=self.registry,
                                 events=self.events,
                                 supervisor=self.supervisor,
                                 **self._runner_kwargs)
        m = self.runner.machine
        rep = replicated_sharding(self.mesh)
        # aggregates live replicated on every chip, so the merge's only
        # cross-shard traffic is the per-shard union gather
        self._agg_cov = jax.device_put(
            jnp.zeros(m.cov.shape[1:], m.cov.dtype), rep)
        self._agg_edge = jax.device_put(
            jnp.zeros(m.edge.shape[1:], m.edge.dtype), rep)
        self._merge = make_mesh_merge(self.mesh)
        self.registry.gauge("mesh.devices").set(self.mesh.size)
        self.registry.gauge("mesh.lanes_per_shard").set(
            self.n_lanes // self.mesh.size)

    def restore_coverage_state(self, cov, edge) -> None:
        """Checkpointed aggregates re-enter REPLICATED (the mesh merge's
        placement contract) — a bare jnp.asarray would leave them on one
        device and force a reshard inside every batch merge."""
        rep = replicated_sharding(self.mesh)
        self._agg_cov = jax.device_put(jnp.asarray(cov), rep)
        self._agg_edge = jax.device_put(jnp.asarray(edge), rep)
        # same prelaunch-drop contract as the base restore: a window
        # dispatched pre-restore must never be adopted post-restore
        self._mega_inflight = None

    def print_run_stats(self) -> None:
        super().print_run_stats()
        print(f"[tpu] mesh: {self.mesh.size} devices x "
              f"{self.n_lanes // self.mesh.size} lanes/shard "
              f"({self.mesh.devices.flat[0].platform})")
