"""MeshRunner: the Runner with its lane axis sharded over a device mesh.

One MeshRunner == one snapshot loaded on EVERY chip of the mesh == N
total lanes, `n_lanes / mesh.size` per chip.  The host servicing loop,
decode cache, oracle fallback, breakpoint dispatch and telemetry are the
base Runner's, unchanged — the subclass only re-points the device
dispatch surface:

  * machine + template lane-sharded, snapshot image + uop table
    replicated (meshrun/mesh.py placement);
  * chunks run through the shard_map executors (meshrun/executor.py):
    shard-local while loops, zero resharding of machine state, and the
    merged cov/edge bitmaps produced on-chip by the chunk's single
    boolean all-reduce — `merged_coverage()` reads them back without
    ever gathering the [lanes, words] planes;
  * host pushes (servicing writes) re-place the updated leaves with the
    lane sharding so the next dispatch never pays an implicit reshard;
  * the devmut generator runs per shard under shard_map with the corpus
    slab replicated and the lane-seed stream sharded — the same program
    per lane as single-device, so the byte streams are bit-exact against
    hostref.lane_seeds (pinned by tests/test_meshrun.py);
  * device counters fold per shard (`device.shard_instructions{i}`)
    on top of the merged `device.*` view.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from wtf_tpu.interp.machine import CTR_INSTR
from wtf_tpu.interp.runner import Runner
from wtf_tpu.meshrun.executor import (
    make_mesh_chunk, make_mesh_fused, make_mesh_resume,
)
from wtf_tpu.meshrun.mesh import (
    LANE_AXIS, lane_sharding, make_mesh, replicate, replicated_sharding,
    shard_machine,
)

_MESH_GEN_CACHE: dict = {}


def _mesh_generate(rounds: int, mesh):
    """The devmut batch generator per shard: slab replicated, seeds
    lane-sharded, output words/lens lane-sharded.  Same per-lane program
    as engine.make_generate, so the stream is bit-exact."""
    key = (rounds, mesh)
    cached = _MESH_GEN_CACHE.get(key)
    if cached is not None:
        return cached
    from wtf_tpu.devmut.engine import generate

    fn = jax.jit(shard_map(
        partial(generate, rounds=rounds), mesh=mesh,
        in_specs=(P(), P(), P(), P(LANE_AXIS)),
        out_specs=(P(LANE_AXIS), P(LANE_AXIS)),
        check_rep=False))
    _MESH_GEN_CACHE[key] = fn
    return fn


class MeshRunner(Runner):
    """Runner whose batch spans a `jax.sharding.Mesh` over the lane axis."""

    def __init__(self, snapshot, n_lanes: int, mesh=None,
                 mesh_devices: Optional[int] = None, **kwargs):
        self.mesh = mesh if mesh is not None else make_mesh(mesh_devices)
        if n_lanes % self.mesh.size:
            raise ValueError(
                f"n_lanes={n_lanes} does not divide over the "
                f"{self.mesh.size}-device mesh — the lane axis shards "
                f"evenly (lanes_per_chip x chips)")
        super().__init__(snapshot, n_lanes, **kwargs)
        # distinguishes mesh executors in the process-global compile-event
        # dedup (same chunk size, different program)
        self.exec_sig = ("mesh", self.mesh.size)
        self.machine = shard_machine(self.machine, self.mesh)
        self.template = shard_machine(self.template, self.mesh)
        # pages + frame table replicated; the per-lane tenant selector
        # (wtf_tpu/tenancy heterogeneous batches) shards with the lanes
        tenant = self.image.tenant
        self.image = replicate(self.image._replace(tenant=None), self.mesh)
        if tenant is not None:
            self.image = self.image._replace(
                tenant=jax.device_put(tenant, lane_sharding(self.mesh)))
        self._tab_src = None
        self._tab_repl = None
        self._slab_src = None
        self._slab_repl = None
        self._merged_cov = None
        self._merged_edge = None

    @property
    def lanes_per_shard(self) -> int:
        return self.n_lanes // self.mesh.size

    # -- dispatch surface (the only seams the base Runner goes through) ----
    def device_tab(self):
        tab = self.cache.device()
        if tab is not self._tab_src:  # cache.device() memoizes when clean
            self._tab_src = tab
            self._tab_repl = replicate(tab, self.mesh)
        return self._tab_repl

    def _chunk_callable(self, n_steps: int):
        fn = make_mesh_chunk(n_steps, self.mesh, donate=self._donate)

        def dispatch(tab, image, machine, limit):
            machine, self._merged_cov, self._merged_edge = fn(
                tab, image, machine, limit)
            return machine

        return dispatch

    def _fused_callables(self):
        fused = make_mesh_fused(self.fused_k, self.mesh)
        resume = make_mesh_resume(self.fused_resume_steps, self.mesh,
                                  donate=self._donate)

        def dispatch_resume(tab, image, machine, limit):
            machine, self._merged_cov, self._merged_edge = resume(
                tab, image, machine, limit)
            return machine

        return fused, dispatch_resume

    def megachunk_callable(self, max_batches: int, n_pages: int,
                           len_gpr: int, ptr_gpr: int, rounds: int):
        """The megachunk window per shard (fuzz/megachunk.py mesh
        variant): slabs/seeds arrive pre-placed through the driver's
        megachunk_operands; outputs keep canonical shardings."""
        from wtf_tpu.fuzz.megachunk import make_mesh_megachunk

        return make_mesh_megachunk(max_batches, n_pages, len_gpr,
                                   ptr_gpr, rounds,
                                   deliver=self.deliver_exceptions,
                                   mesh=self.mesh,
                                   devdec=self.device_decode,
                                   fused=bool(self.fused_enabled),
                                   fused_k=self.fused_k,
                                   fused_resume_steps=(
                                       self.fused_resume_steps),
                                   donate=self._donate)

    def megachunk_place(self, slab_first, slab_rest, seeds):
        """Place one window's operands: slabs replicated (version-
        tracked like devmut_generate's), the seed stream lane-sharded."""
        rep = replicated_sharding(self.mesh)
        if slab_rest[0] is not self._slab_src:
            self._slab_src = slab_rest[0]
            self._slab_repl = tuple(
                jax.device_put(a, rep) for a in slab_rest)
        rest = self._slab_repl
        first = (rest if slab_first[0] is slab_rest[0]
                 else tuple(jax.device_put(a, rep) for a in slab_first))
        seeds = jax.device_put(jnp.asarray(seeds),
                               jax.sharding.NamedSharding(
                                   self.mesh, P(None, LANE_AXIS)))
        return first, rest, seeds

    # -- host write seams: keep the canonical sharding -----------------------
    def push(self, view) -> None:
        super().push(view)
        # servicing replaced register leaves with host-built arrays;
        # re-place them so the next dispatch sees the canonical lane
        # sharding instead of paying an implicit reshard per chunk
        self.machine = shard_machine(self.machine, self.mesh)

    def device_insert(self, *args, **kwargs) -> None:
        super().device_insert(*args, **kwargs)
        self.machine = shard_machine(self.machine, self.mesh)

    def restore(self) -> None:
        super().restore()
        self.machine = shard_machine(self.machine, self.mesh)
        self._merged_cov = None
        self._merged_edge = None

    # -- on-chip merged coverage ---------------------------------------------
    def merged_coverage(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """(cov, edge) bitmaps OR-merged across ALL lanes of ALL shards,
        as of the last dispatched chunk — produced in-graph by the chunk's
        boolean all-reduce, so reading them costs two [words] transfers,
        never a [lanes, words] gather.  None before the first chunk of a
        run (or right after restore)."""
        if self._merged_cov is None:
            return None
        return (np.asarray(jax.device_get(self._merged_cov)),
                np.asarray(jax.device_get(self._merged_edge)))

    # -- devmut seam ---------------------------------------------------------
    def devmut_generate(self, rounds, data, lens, cumw, seeds):
        # replicate the corpus slab once per slab VERSION, not per batch
        # (DeviceCorpus.arrays memoizes between dirty uploads, so object
        # identity tracks the version — same scheme as device_tab): the
        # point of the device engine is that the stream stays resident,
        # not re-broadcast [slots, words] to every chip each batch
        if data is not self._slab_src:
            rep = replicated_sharding(self.mesh)
            self._slab_src = data
            self._slab_repl = (jax.device_put(data, rep),
                               jax.device_put(lens, rep),
                               jax.device_put(cumw, rep))
        data_r, lens_r, cumw_r = self._slab_repl
        return _mesh_generate(rounds, self.mesh)(
            data_r, lens_r, cumw_r,
            jax.device_put(jnp.asarray(seeds), lane_sharding(self.mesh)))

    # -- telemetry -----------------------------------------------------------
    def fold_device_counters(self) -> np.ndarray:
        """Merged `device.*` fold (base class) plus the per-shard view:
        `device.shard_instructions{<shard>}` — the counters a mesh
        operator reads to spot a cold or straggling chip.  Shard i owns
        lanes [i*L/S, (i+1)*L/S)."""
        ctr = super().fold_device_counters()
        shards = self.mesh.size
        per = ctr.reshape(shards, self.n_lanes // shards,
                          ctr.shape[1]).sum(axis=1, dtype=np.uint64)
        by_shard = self.registry.counter("device.shard_instructions")
        for s in range(shards):
            by_shard.labels(str(s)).inc(int(per[s, CTR_INSTR]))
        return ctr
