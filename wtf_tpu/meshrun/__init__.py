"""meshrun: a jax.sharding.Mesh over the lane axis as one logical backend.

The reference wtf scales by one fuzzer process per core aggregating
coverage over TCP (SURVEY.md §2.7); the TPU-native answer makes
lanes-per-chip x chips the headline axis.  Machine state is SoA with a
leading lane axis, so the whole campaign loop shards as data
parallelism:

  mesh.py      mesh construction + pytree placement (lanes split,
               image/uop-table replicated, multi-host init)
  reduce.py    the ONE shard-aware coverage OR-reduce family (chunk
               bitmaps, batch merge with reference set-union credit)
  executor.py  shard_map chunk / fused-step / resume executors — the
               compiled chunk carries exactly one cross-device
               collective, the coverage all-reduce (pinned statically
               by `wtf-tpu lint`'s mesh family)
  runner.py    MeshRunner: the host servicing loop over a sharded batch
  backend.py   MeshBackend: the one-logical-backend seam the fuzz loop,
               dist clients and CLI drive (`campaign --mesh-devices N`)

Imports resolve lazily (PEP 562) so `wtf_tpu.backend` can pull the
shared coverage merge without importing the runner stack.
"""

from __future__ import annotations

_EXPORTS = {
    "LANE_AXIS": "mesh",
    "make_mesh": "mesh",
    "init_multihost": "mesh",
    "lane_sharding": "mesh",
    "replicated_sharding": "mesh",
    "shard_machine": "mesh",
    "replicate": "mesh",
    "or_reduce_lanes": "reduce",
    "merged_coverage": "reduce",
    "merge_coverage": "reduce",
    "make_mesh_merge": "reduce",
    "make_mesh_chunk": "executor",
    "make_mesh_fused": "executor",
    "make_mesh_resume": "executor",
    "MeshRunner": "runner",
    "MeshBackend": "backend",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f"{__name__}.{module}"), name)
