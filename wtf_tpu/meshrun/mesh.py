"""Mesh construction and pytree placement over the lane axis.

Design (SURVEY.md §2.7.3): the fuzzer's only parallel axis is *testcases*
(lanes) — the analog of data parallelism.  Machine state is SoA arrays with
a leading lane axis, so sharding is one PartitionSpec over that axis; the
snapshot image and uop table are replicated (every chip interprets against
the same read-only memory image); coverage aggregation is an OR-reduce over
the lane axis whose only cross-chip leg is a small boolean all-reduce
(meshrun/reduce.py).

Multi-host: the same mesh spans processes (jax distributed runtime); the
corpus/crash plane stays host-side and distributes over the reference's TCP
protocol (dist/), which needs no device awareness.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

LANE_AXIS = "lanes"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D lane mesh over the first `n_devices` local devices (None or
    0 = every device jax can see)."""
    devices = jax.devices()
    if n_devices:
        if n_devices > len(devices):
            raise ValueError(
                f"mesh wants {n_devices} devices but jax sees only "
                f"{len(devices)} ({devices[0].platform})")
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (LANE_AXIS,))


def init_multihost(coordinator: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> Mesh:
    """Multi-host campaign entry point: join the jax distributed runtime
    (DCN coordination; args default from the cluster environment) and
    return the global lane mesh over every chip of every host.

    This replaces the reference's process-per-core fan-out INSIDE the
    pod: one mesh, lanes sharded across all chips, coverage OR-reduce
    riding ICI within hosts and DCN across (XLA picks the collectives).
    Across independent pods, the TCP master/node plane (wtf_tpu.dist)
    still applies unchanged — a whole pod is one BatchClient."""
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    try:  # jax >= 0.5 exposes is_initialized; older builds don't
        already = jax.distributed.is_initialized()
    except AttributeError:
        from jax._src.distributed import global_state

        already = global_state.client is not None
    if not already:
        jax.distributed.initialize(**kwargs)  # raises on a bad coordinator
    return make_mesh()


def lane_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis-split placement for per-lane arrays."""
    return NamedSharding(mesh, P(LANE_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Every-device-holds-it placement (image, uop table, aggregates)."""
    return NamedSharding(mesh, P())


def _is_multiprocess(mesh: Mesh) -> bool:
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def _place(leaf, sharding, mesh: Mesh):
    """device_put within one process; across processes every host holds
    the same global value (machines broadcast from one snapshot, images
    and uop tables are replicated by construction), so each process
    donates its addressable shards of that value via the callback form."""
    if not _is_multiprocess(mesh):
        return jax.device_put(leaf, sharding)
    arr = np.asarray(leaf)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])


def shard_machine(machine, mesh: Mesh):
    """Place every per-lane leaf with its leading axis split over the mesh.

    n_lanes must divide by mesh size.  Returns the same pytree with
    device-sharded arrays; everything downstream (run_chunk, coverage
    merge) is shape-identical, so jit compiles SPMD executables with XLA
    inserting the cross-chip collectives.  On a multi-host mesh every
    process must call this with the SAME host value (true for machines
    built from one snapshot) and the array becomes global."""
    sharding = lane_sharding(mesh)
    return jax.tree.map(lambda leaf: _place(leaf, sharding, mesh), machine)


def replicate(tree, mesh: Mesh):
    """Replicate snapshot image / uop table on every mesh device."""
    sharding = replicated_sharding(mesh)
    return jax.tree.map(lambda leaf: _place(leaf, sharding, mesh), tree)
