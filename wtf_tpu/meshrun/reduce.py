"""Coverage reduction: the ONE shard-aware OR-reduce family.

Every coverage union in the tree funnels through here — the parallel
`merged_coverage` helper, the batched backend's aggregate merge, and the
mesh backend's cross-shard merge — so the OR-reduce exists once:

  or_reduce_lanes    grouped shard-local OR + boolean bit-plane reduce;
                     the formulation that partitions cleanly when the
                     lane axis spans devices (XLA has no u32 bitwise-or
                     cross-device reduction, booleans it can all-reduce)
  merge_coverage     the reference master's sequential set-union merge
                     (server.h:816-854): union + per-lane new-coverage
                     credit via an exclusive prefix OR — single-device
  make_mesh_merge    the same semantics over a sharded lane axis:
                     shard-local prefix via the SAME core, one all_gather
                     of the tiny per-shard unions for the cross-shard
                     exclusive prefix (S x words — the only bytes that
                     cross the interconnect per batch merge)

The per-CHUNK merged-bitmap readback lives in meshrun/executor.py (it is
fused into the chunk program so the whole chunk carries exactly one
collective); it shares `bitplane_or` below.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from wtf_tpu.meshrun.mesh import LANE_AXIS


def or_reduce_lanes(words, groups: Optional[int] = None):
    """OR-reduce u32 bitmaps over the (possibly sharded) lane axis.

    XLA's cross-device reduction set covers sum/min/max but not u32
    bitwise-or, so a plain `bitwise_or.reduce` over a sharded axis fails
    to partition.  Split the reduction instead: the expensive [L, W] part
    is a shard-local bitwise OR (no collective, no expansion), and only
    the small [g, W, 32] per-bit view crosses devices via `jnp.any`'s
    boolean all-reduce.

    The group count must be a multiple of the lane-mesh size or the
    "local" OR itself crosses shards; callers that hold the mesh pass
    `groups` (merged_coverage's static arg).  The default — the largest
    power-of-two divisor of n_lanes, capped at 256 — stays shard-local
    for any power-of-two mesh up to 256 devices."""
    n = words.shape[0]
    g = groups if groups else min(n & -n, 256)
    grouped = words.reshape(g, n // g, -1)
    local = jnp.bitwise_or.reduce(grouped, axis=1)        # [g, W]
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = jnp.any((local[..., None] >> shifts) & jnp.uint32(1) != 0,
                   axis=0)                                # [W, 32]
    return jnp.sum(bits.astype(jnp.uint32) << shifts, axis=-1)


@partial(jax.jit, static_argnames=("groups",))
def merged_coverage(machine, groups: Optional[int] = None):
    """Batch-wide coverage union: OR-reduce the per-lane cov/edge bitmaps
    over the lane axis.  Under a sharded lane axis this lowers to an
    all-reduce over ICI — the device-side replacement for the reference
    master's set-union merge (server.h:816-854).

    Pass `groups` = a multiple of the lane-mesh device count (e.g.
    `mesh.size`) on meshes wider than 256 or with non-power-of-two
    device counts; see `or_reduce_lanes`."""
    return (or_reduce_lanes(machine.cov, groups),
            or_reduce_lanes(machine.edge, groups))


def bitplane_or(words, axis_name: str):
    """Cross-shard bitwise OR of a [W] u32 bitmap via the boolean
    bit-plane all-reduce: expand to the [W, 32] 0/1 plane, pmax across
    the named axis (max of 0/1 == OR), repack.  ONE collective."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (words[:, None] >> shifts) & jnp.uint32(1)     # [W, 32]
    merged = lax.pmax(bits, axis_name)
    return jnp.sum(merged << shifts, axis=-1, dtype=jnp.uint32)


def _prefix_scan(x_in, prev):
    """(inclusive cumulative OR, exclusive-prefix OR seeded with `prev`)
    over the lane axis — the scan both the batch merge (`_merge_core`)
    and the triage attribution (`first_hit_credit`) are built on."""
    cum = lax.associative_scan(jnp.bitwise_or, x_in, axis=0)
    before = jnp.concatenate([prev[None], prev | cum[:-1]], axis=0)
    return cum, before


def _merge_core(agg_cov, agg_edge, cov_in, edge_in, prev_cov, prev_edge):
    """Prefix-credit merge of one contiguous lane block, given the OR of
    every EARLIER lane (`prev_*` — zeros for lane block 0 / the
    single-device path; the lower shards' union on a mesh).

    Per-lane new-coverage credit follows the reference master's
    *sequential* set-union merge: a lane counts as new only for bits not
    in the aggregate AND not already contributed by any earlier lane.
    Without this, every lane finding the same new edge enters the corpus,
    polluting it with coverage-duplicate testcases and measurably
    diluting guided search.  Returns (block cov union, block edge union,
    new_lane flags for the block)."""
    cum_cov, before_cov = _prefix_scan(cov_in, prev_cov)
    cum_edge, before_edge = _prefix_scan(edge_in, prev_edge)
    new_lane = (
        jnp.any((cov_in & ~agg_cov[None] & ~before_cov) != 0, axis=1)
        | jnp.any((edge_in & ~agg_edge[None] & ~before_edge) != 0, axis=1))
    return cum_cov[-1], cum_edge[-1], new_lane


@jax.jit
def merge_coverage(agg_cov, agg_edge, cov, edge, include):
    """Single-device batch merge: OR lane bitmaps (where `include`) into
    the aggregates; returns (agg_cov', agg_edge', new_lane, new_cov_words).
    The mesh path (make_mesh_merge) runs the same `_merge_core` per shard."""
    inc = include[:, None]
    cov_in = jnp.where(inc, cov, 0)
    edge_in = jnp.where(inc, edge, 0)
    zc = jnp.zeros_like(agg_cov)
    ze = jnp.zeros_like(agg_edge)
    cov_union, edge_union, new_lane = _merge_core(
        agg_cov, agg_edge, cov_in, edge_in, zc, ze)
    new_cov_words = cov_union & ~agg_cov
    return (agg_cov | cov_union, agg_edge | edge_union,
            new_lane & include, new_cov_words)


@jax.jit
def first_hit_credit(agg_cov, agg_edge, cov, edge, include):
    """Exact per-lane coverage attribution under replay order — the
    device half of wtf_tpu/triage's corpus distillation.

    Runs the SAME exclusive-prefix scan as `_merge_core` but keeps the
    whole per-lane credit PLANES instead of collapsing them to flags:
    lane i is credited exactly the cov/edge bits it is FIRST to set —
    not in `agg_*` (earlier batches) and not contributed by any earlier
    lane of this batch.  Excluded lanes (`include` false: timeouts,
    overlay-full) contribute and receive nothing, matching the batch
    merge's revocation rule.

    Returns (credit_cov [L, Wc], credit_edge [L, We], agg_cov', agg_edge')
    — summing each lane's credit popcount over a whole corpus sweep gives
    the exact-attribution ledger, and OR-ing the credits reproduces the
    aggregate delta (the host-recount differential tests/test_triage.py
    pins)."""
    inc = include[:, None]
    cov_in = jnp.where(inc, cov, 0)
    edge_in = jnp.where(inc, edge, 0)
    cum_cov, before_cov = _prefix_scan(cov_in, agg_cov)
    cum_edge, before_edge = _prefix_scan(edge_in, agg_edge)
    credit_cov = cov_in & ~before_cov
    credit_edge = edge_in & ~before_edge
    return (credit_cov, credit_edge,
            agg_cov | cum_cov[-1], agg_edge | cum_edge[-1])


_MESH_MERGE_CACHE: dict = {}


def mesh_merge_local(agg_cov, agg_edge, cov, edge, include,
                     axis_name: str = LANE_AXIS):
    """The per-shard body of the mesh batch merge — module-level so the
    megachunk program (wtf_tpu/fuzz/megachunk.py) can inline the SAME
    merge inside its per-batch loop: shard-local prefix credit via
    `_merge_core`, one all_gather of the tiny per-shard unions for the
    cross-shard exclusive prefix.  Bit-identical to `merge_coverage`
    for any lane order."""
    inc = include[:, None]
    cov_in = jnp.where(inc, cov, 0)
    edge_in = jnp.where(inc, edge, 0)
    wc = cov.shape[1]
    zc = jnp.zeros_like(agg_cov)
    ze = jnp.zeros_like(agg_edge)
    uc, ue, _ = _merge_core(agg_cov, agg_edge, cov_in, edge_in, zc, ze)
    allu = lax.all_gather(jnp.concatenate([uc, ue]), axis_name)
    sidx = lax.axis_index(axis_name)
    nshards = allu.shape[0]
    lower = jnp.where((jnp.arange(nshards) < sidx)[:, None], allu, 0)
    prev = jnp.bitwise_or.reduce(lower, axis=0)
    union = jnp.bitwise_or.reduce(allu, axis=0)
    _, _, new_lane = _merge_core(
        agg_cov, agg_edge, cov_in, edge_in, prev[:wc], prev[wc:])
    new_cov_words = union[:wc] & ~agg_cov
    return (agg_cov | union[:wc], agg_edge | union[wc:],
            new_lane & include, new_cov_words)


def make_mesh_merge(mesh):
    """The batch merge over a lane-sharded machine: per shard, the SAME
    `_merge_core` runs on the local lane block; the cross-shard exclusive
    prefix comes from ONE all_gather of the per-shard unions
    ([shards, cov_w + edge_w] u32 — the only interconnect bytes of the
    merge).  Bit-identical to `merge_coverage` for any lane order (the
    parity the mesh-vs-single-device campaign tests pin).

    Returns a jitted callable (agg_cov, agg_edge, cov, edge, include) ->
    (agg_cov', agg_edge', new_lane, new_cov_words) with agg/new_words
    replicated and new_lane lane-sharded."""
    key = mesh
    cached = _MESH_MERGE_CACHE.get(key)
    if cached is not None:
        return cached
    fn = jax.jit(shard_map(
        mesh_merge_local, mesh=mesh,
        in_specs=(P(), P(), P(LANE_AXIS), P(LANE_AXIS), P(LANE_AXIS)),
        out_specs=(P(), P(), P(LANE_AXIS), P()),
        check_rep=False))
    _MESH_MERGE_CACHE[key] = fn
    return fn
