"""Batched triage: crash minimization, corpus distillation, and
virtual-breakpoint replay as device workloads.

ROADMAP item 5: anything shaped "run thousands of variants of one
testcase" is a mesh dispatch, so triage throughput scales on the same
hardware as fuzzing throughput.  Three workloads share one batch-replay
core (replay.py) that drives the campaign's own dispatch seams — the
Runner/MeshRunner chunk executors, the devmut slab-upload format for
candidate batches, and the `[words, 32]` coverage bit-planes:

  bucket.py      the triage-grade crash key (kind, faulting RIP,
                 top-of-stack hash) — ONE dedup helper shared by the
                 fuzz-loop harvest and the minimizer
  candidates.py  in-graph candidate builds (truncate / block-delete /
                 zero) in the devmut byte-plane idiom; PORTED_LIMB_PATHS
                 puts them under the lint dtype pin
  replay.py      ReplayCore — chunked host-bytes sweeps and device-built
                 batches, per-testcase planes, exact first-hit credit;
                 FuzzLoop.minset runs on it
  minimize.py    bisecting batch minimizer (`triage minimize`)
  distill.py     exact-attribution corpus distillation + greedy set
                 cover (`triage distill`)
  vbreak.py      batched register+memory snapshots at an armed RIP
                 (`triage vbreak`)

All three land as `wtf-tpu triage {minimize,distill,vbreak}` and are
bit-identical under `--mesh-devices N` vs single device at equal seeds.
"""

from wtf_tpu.triage.bucket import bucket_of, crash_kind, make_bucket  # noqa: F401
from wtf_tpu.triage.distill import DistillResult, distill, greedy_cover  # noqa: F401
from wtf_tpu.triage.minimize import MinimizeResult, minimize  # noqa: F401
from wtf_tpu.triage.replay import ReplayCore, ReplaySweep  # noqa: F401
from wtf_tpu.triage.vbreak import (  # noqa: F401
    BreakCapture, oracle_capture, perturbations, vbreak,
)
