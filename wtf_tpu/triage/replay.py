"""The ONE batched replay core all three triage workloads share.

Everything in wtf_tpu/triage is shaped "run N variants of testcases and
look at what each lane did" — exactly the fuzz loop's execute phase with
the mutation stage swapped out.  This module is that shared execution
path, driving the SAME dispatch seams the campaign uses, so triage
throughput scales on the same hardware as fuzzing throughput:

  * host-bytes sweeps (`replay`): chunked through `backend.run_batch`
    (per-lane target.insert_testcase, trailing lanes idle) — corpus
    distillation, minset, vbreak sweeps;
  * device-built batches (`replay_device`): `[lanes, words]` u32 arrays
    (triage/candidates.py builds, devmut slab format) through
    `TpuBackend.run_batch_words` -> `Runner.device_insert` — the
    minimizer's candidate storm, whose bytes never visit the host;
  * per-testcase coverage out of the `[words, 32]` bit-planes: raw
    cov/edge rows for the host set-cover, and the exact first-hit
    attribution (`meshrun/reduce.first_hit_credit`) computed in-graph
    with the revocation rule of the batch merge (timeout/overlay-full
    lanes credit nothing);
  * triage-grade crash buckets per crashed lane (triage/bucket.py).

The core never owns an executor: chunk programs come from the Runner's
`_chunk_callable` seam (step.make_run_chunk — `REPLAY_CHUNK_FACTORY`
below, pinned by `wtf-tpu lint`'s budget family so triage adds ZERO
gather-class kernels beyond the 168 budget), a MeshRunner transparently
swaps in the shard_map executors, and `exec_sig` keeps compile events
honest.  FuzzLoop.minset (the campaign `--runs 0` path) runs on this
same core.
"""

from __future__ import annotations

from typing import Callable, Dict, List, NamedTuple, Optional, Sequence

import jax
import numpy as np

from wtf_tpu.core.results import Crash, OverlayFull, TestcaseResult, Timedout
from wtf_tpu.interp.step import make_run_chunk
from wtf_tpu.meshrun.reduce import first_hit_credit
from wtf_tpu.triage.bucket import bucket_of
from wtf_tpu import telemetry
from wtf_tpu.telemetry import Registry, StatsDict

# The chunk-executor factory this core's dispatches resolve to (through
# Runner._chunk_callable).  `wtf-tpu lint`'s budget family pins the
# identity: triage replays the SAME compiled step ladder the campaign
# runs — re-pointing this at a private executor without re-baselining
# the kernel budget is a lint failure (budget.triage-chunk).
REPLAY_CHUNK_FACTORY = make_run_chunk

PAGE = 4096


class ReplaySweep(NamedTuple):
    """One `replay()` call's harvest, indexed by testcase position."""

    results: List[TestcaseResult]
    new_lane: np.ndarray            # first-hit credit flags (merge order)
    buckets: Dict[int, str]         # index -> triage bucket (crashes only)
    cov: Optional[np.ndarray]       # uint32[N, Wc] per-testcase planes
    edge: Optional[np.ndarray]      # uint32[N, We]
    credit_cov: Optional[np.ndarray]   # uint32[N, Wc] first-hit credit
    credit_edge: Optional[np.ndarray]  # uint32[N, We]


def _include_mask(results: Sequence[TestcaseResult]) -> np.ndarray:
    """The batch merge's revocation rule as a mask: timeout and
    overlay-full lanes contribute no coverage (client.cc:122-125)."""
    return np.array([not isinstance(r, (Timedout, OverlayFull))
                     for r in results])


class ReplayCore:
    """Batched replay over an already-initialized tpu-family backend.

    Shares the backend's registry/events (spans nest exactly like the
    fuzz loop's: execute / harvest / restore), and owns the `triage.*`
    counter namespace the telemetry report's triage section reads."""

    def __init__(self, backend, target, registry: Optional[Registry] = None,
                 events=None, batch_size: Optional[int] = None):
        if not hasattr(backend, "run_batch"):
            raise ValueError(
                "triage replay needs a backend with the batch facade "
                "(run_batch)")
        self.backend = backend
        self.target = target
        self.registry, self.events = telemetry.resolve(
            backend, registry, events)
        # single-lane backends replay through the base-class batch
        # facade (minset keeps working on --backend emu); the plane /
        # attribution / device-candidate paths need the real batch
        self.n_lanes = getattr(backend, "n_lanes", 1)
        self.batch_size = min(batch_size or self.n_lanes, self.n_lanes)
        self.stats = StatsDict(
            self.registry, "triage",
            fields=("candidates", "dispatches", "crashes"))
        self._spec = getattr(target, "device_insert", None)
        self._pfns: Optional[List[int]] = None

    # -- device-candidate seam (the devmut slab-upload scheme) -----------
    def _require_runner(self, what: str):
        runner = getattr(self.backend, "runner", None)
        if runner is None:
            raise ValueError(
                f"{what} requires the initialized batched tpu backend "
                "(--backend=tpu); this backend has no device batch")
        return runner

    def device_spec(self):
        """(DeviceInsertSpec, input-region pfns) for device-built
        batches; translates the region once, exactly like
        DevMangleMutator.bind."""
        self._require_runner("device-built triage batches")
        if self._spec is None:
            raise ValueError(
                f"target {getattr(self.target, 'name', self.target)!r} "
                "has no device_insert spec — device-built triage batches "
                "need the declarative insert seam "
                "(harness.targets.DeviceInsertSpec)")
        if self._pfns is None:
            n_pages = (self._spec.max_len + PAGE - 1) // PAGE
            view = self.backend.runner.view()
            self._pfns = [
                view.translate(0, self._spec.gva + i * PAGE) >> 12
                for i in range(n_pages)]
        return self._spec, self._pfns

    # -- the sweep --------------------------------------------------------
    def replay(self, testcases: Sequence[bytes], *,
               collect_planes: bool = False, attribute: bool = False,
               want_buckets: bool = False,
               on_batch_start: Optional[Callable[[int], None]] = None,
               on_batch: Optional[Callable] = None,
               after_batch: Optional[Callable[[], None]] = None
               ) -> ReplaySweep:
        """Replay host testcases in batches of `batch_size` lanes with a
        full snapshot restore in between (the batched
        RunTestcaseAndRestore).

        collect_planes  pull each testcase's cov/edge bit-plane rows
                        (revoked lanes zeroed — the merge's include rule)
        attribute       also compute the exact first-hit credit planes
                        in-graph (meshrun/reduce.first_hit_credit),
                        carrying the aggregate across batches
        want_buckets    triage bucket per crashed lane
        on_batch_start(start)           before each batch's execution
        on_batch(start, batch, results) harvest callback, inside the
                        `harvest` span, before the restore
        after_batch()   after the restore (heartbeat cadence)
        """
        import jax.numpy as jnp

        backend = self.backend
        spans = self.registry.spans
        results_all: List[TestcaseResult] = []
        new_flags: List[bool] = []
        buckets: Dict[int, str] = {}
        cov_rows: List[np.ndarray] = []
        edge_rows: List[np.ndarray] = []
        credit_cov_rows: List[np.ndarray] = []
        credit_edge_rows: List[np.ndarray] = []
        agg = None
        testcases = list(testcases)
        for start in range(0, len(testcases), self.batch_size):
            batch = testcases[start:start + self.batch_size]
            if on_batch_start is not None:
                on_batch_start(start)
            with spans.span("execute"):
                results = backend.run_batch(batch, self.target)
            self.stats["dispatches"] += 1
            self.stats["candidates"] += len(batch)
            include = _include_mask(results)
            if collect_planes or attribute:
                m = self._require_runner("per-testcase bit-planes").machine
                if attribute:
                    if agg is None:
                        agg = (jnp.zeros_like(m.cov[0]),
                               jnp.zeros_like(m.edge[0]))
                    inc = jnp.asarray(
                        np.pad(include, (0, self.n_lanes - len(batch))))
                    ccov, cedge, agg_cov, agg_edge = first_hit_credit(
                        agg[0], agg[1], m.cov, m.edge, inc)
                    agg = (agg_cov, agg_edge)
                    credit_cov_rows.append(
                        np.asarray(jax.device_get(ccov))[:len(batch)])
                    credit_edge_rows.append(
                        np.asarray(jax.device_get(cedge))[:len(batch)])
                if collect_planes:
                    cov = np.array(jax.device_get(m.cov))[:len(batch)]
                    edge = np.array(jax.device_get(m.edge))[:len(batch)]
                    cov[~include] = 0
                    edge[~include] = 0
                    cov_rows.append(cov)
                    edge_rows.append(edge)
            for lane, result in enumerate(results):
                if isinstance(result, Crash):
                    self.stats["crashes"] += 1
                    if want_buckets:
                        buckets[start + lane] = bucket_of(
                            backend, lane, result)
            new_flags.extend(
                bool(backend.lane_found_new_coverage(lane))
                for lane in range(len(batch)))
            if on_batch is not None:
                with spans.span("harvest"):
                    on_batch(start, batch, results)
            results_all.extend(results)
            self._restore()
            if after_batch is not None:
                after_batch()
        return ReplaySweep(
            results=results_all,
            new_lane=np.array(new_flags, dtype=bool),
            buckets=buckets,
            cov=np.concatenate(cov_rows) if cov_rows else None,
            edge=np.concatenate(edge_rows) if edge_rows else None,
            credit_cov=(np.concatenate(credit_cov_rows)
                        if credit_cov_rows else None),
            credit_edge=(np.concatenate(credit_edge_rows)
                         if credit_edge_rows else None))

    def replay_device(self, words, lens, n_candidates: int,
                      base_kind: Optional[str] = None):
        """Run one device-built candidate batch (`words` u32[L, W] /
        `lens` i32[L] device arrays, every lane active) through the
        fused insert seam.  Returns (results, buckets) for the first
        `n_candidates` lanes; `base_kind` skips bucket computation for
        crashes of a different fault CLASS (the bucket embeds the
        kind, so a kind mismatch is a bucket mismatch).  The kind —
        not the full result name: a read/write crasher's name embeds
        the fault DATA address, which a still-same-bucket candidate
        legitimately changes."""
        from wtf_tpu.triage.bucket import crash_kind

        spec, pfns = self.device_spec()
        spans = self.registry.spans
        with spans.span("execute"):
            results = self.backend.run_batch_words(words, lens, pfns, spec)
        self.stats["dispatches"] += 1
        self.stats["candidates"] += n_candidates
        buckets: Dict[int, str] = {}
        for lane in range(n_candidates):
            result = results[lane]
            if isinstance(result, Crash):
                self.stats["crashes"] += 1
                if base_kind is None or crash_kind(result) == base_kind:
                    buckets[lane] = bucket_of(self.backend, lane, result)
        self._restore()
        return results[:n_candidates], buckets

    def _restore(self) -> None:
        with self.registry.spans.span("restore"):
            self.target.restore()
            self.backend.restore()
