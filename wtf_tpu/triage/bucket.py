"""Triage-grade crash bucketing: the ONE dedup key for "same crash".

The reference buckets crashes by output filename, which here was the
`Crash.name` string — `crash-<kind>-<fault gva>`.  That key both
under-merges (the same bug reached through two corrupted pointers gets
two names) and over-merges (two distinct bugs faulting on the same
wild address get one).  The triage-grade key is the classic tuple:

  (crash kind, faulting RIP, top-of-stack hash)

  kind      the fault class token out of the result name ("execute",
            "read", "write", "de", "int", ...; harness-stopped crashes
            keep their full custom name as the kind)
  rip       the lane's RIP at the fault — the faulting instruction for
            read/write/#DE, the wild fetch target for execute faults
  tos hash  blake2b-64 of the TOS_BYTES bytes at rsp, read through the
            lane's own memory view — distinguishes call paths that
            fault at the same instruction

Every consumer goes through `bucket_of(backend, lane, result)`:
`FuzzLoop`'s harvest dedups found crashes by it, and
`triage/minimize.py`'s bisection accepts a candidate only when its
bucket equals the original crasher's — so "still reproduces" means the
same bug, not merely any crash.  It degrades to the result name on
backends without register/memory introspection, never raises.
"""

from __future__ import annotations

import hashlib

from wtf_tpu.core.results import Crash, TestcaseResult

# stack window hashed into the bucket key.  Small enough that reading it
# costs one page probe per crash lane; large enough to cover the caller
# frame that distinguishes call paths.
TOS_BYTES = 64

# result names shaped `crash-<kind>-<hex>` (backend/tpu._map_result /
# the oracle's equivalents); anything else is a harness-named crash and
# keeps its full name as the kind token
_KINDS = ("execute", "read", "write", "de", "int")


def crash_kind(result: TestcaseResult) -> str:
    """The fault-class token of a Crash result."""
    name = getattr(result, "name", None) or "crash"
    parts = name.split("-")
    if len(parts) >= 3 and parts[0] == "crash" and parts[1] in _KINDS:
        return parts[1]
    return name


def stack_hash(data: bytes) -> str:
    """blake2b-64 of a top-of-stack window ("nostack" for unreadable)."""
    if not data:
        return "nostack"
    return hashlib.blake2b(data, digest_size=8).hexdigest()


def make_bucket(kind: str, rip: int, tos: str) -> str:
    """The canonical bucket string (stable: checkpointed sets and event
    streams carry it verbatim)."""
    return f"{kind}.{rip:#x}.{tos}"


def bucket_of(backend, lane, result: TestcaseResult) -> str:
    """The triage bucket of a crashed lane — shared by the fuzz-loop
    harvest and the triage minimizer so both agree on "same crash".

    `lane` addresses the batched backend's machine state; pass None (or
    any value) for single-lane backends.  Non-Crash results and backends
    without the introspection seams fall back to the result name — the
    filename-grade key, still a valid (coarser) bucket."""
    if not isinstance(result, Crash):
        return getattr(result, "name", None) or type(result).__name__
    kind = crash_kind(result)
    try:
        runner = getattr(backend, "runner", None)
        if runner is not None and hasattr(backend, "_ensure_view"):
            # batched backend: one pooled HostView pull per batch
            # (backend._view caches until restore), page reads lazy
            view = backend._ensure_view()
            lane = int(lane or 0)
            rip = view.get_rip(lane)
            rsp = view.get_reg(lane, 4)
            try:
                tos = stack_hash(view.virt_read(lane, rsp, TOS_BYTES))
            except Exception:
                tos = "nostack"
            return make_bucket(kind, rip, tos)
        cpu = getattr(backend, "cpu", None)
        if cpu is not None:
            rip = int(cpu.rip)
            rsp = int(cpu.gpr[4])
            try:
                tos = stack_hash(backend.virt_read(rsp, TOS_BYTES))
            except Exception:
                tos = "nostack"
            return make_bucket(kind, rip, tos)
    except Exception:
        pass
    return getattr(result, "name", None) or "crash"
