"""Corpus distillation: one batched sweep, exact attribution, set cover.

The campaign's `--runs 0` minset keeps testcases that were FIRST to set
a bit in replay order (the reference master's semantics, server.h:
552-556) — stateless and order-dependent.  This module replaces the
measurement half with an exact-attribution path on the same hardware:

  1. re-execute the whole corpus through the shared replay core
     (triage/replay.py — `FuzzLoop.minset` runs on the identical path);
  2. per-testcase edge attribution comes straight off the `[words, 32]`
     coverage bit-planes: the in-graph first-hit prefix credit
     (meshrun/reduce.first_hit_credit — `_merge_core`'s scan keeping
     the planes), plus each testcase's FULL cov/edge rows;
  3. the greedy set cover runs on host over the full rows, so the kept
     subset provably reproduces the complete corpus' aggregate coverage
     (usually strictly smaller than the prefix-credit keep set, which
     is also returned — it is byte-compatible with the old minset).

Determinism: replay order is the caller's list order; the credit scan,
cover tie-breaks (highest gain, lowest index) and all counters are pure
functions of the sweep — mesh and single-device runs are bit-identical.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from wtf_tpu.telemetry import Registry
from wtf_tpu.triage.replay import ReplayCore, ReplaySweep

# byte -> popcount table (numpy < 2.0 has no bitwise_count ufunc)
_POPCOUNT = np.array([bin(i).count("1") for i in range(256)],
                     dtype=np.uint16)


def popcount_rows(planes: np.ndarray) -> np.ndarray:
    """Per-row set-bit count of a [N, W] u32 bit-plane."""
    return _POPCOUNT[planes.view(np.uint8)].sum(axis=1).astype(np.int64)


def greedy_cover(planes: np.ndarray) -> List[int]:
    """Greedy set cover over [N, W] row bitmaps: repeatedly keep the row
    covering the most still-uncovered bits (ties: lowest index) until
    the union of kept rows equals the union of all rows.  Exact
    coverage preservation by construction; minimality is the usual
    greedy ln(n) approximation."""
    if planes.shape[0] == 0:
        return []
    union = np.bitwise_or.reduce(planes, axis=0)
    covered = np.zeros_like(union)
    keep: List[int] = []
    while not np.array_equal(covered, union):
        gains = popcount_rows(planes & ~covered[None, :])
        best = int(np.argmax(gains))  # argmax returns the first maximum
        if gains[best] == 0:
            break  # defensive: cannot happen while covered != union
        keep.append(best)
        covered |= planes[best]
    return keep


@dataclasses.dataclass
class DistillResult:
    keep: List[int]            # greedy-cover indices (replay order)
    prefix_keep: List[int]     # first-hit credit indices (old minset set)
    credit_bits: np.ndarray    # int64[N] exact per-testcase credit
    total_bits: int            # aggregate corpus coverage (cov+edge bits)
    kept_bits: int             # aggregate coverage of the kept subset
    sweep: ReplaySweep         # the raw sweep (results, planes, buckets)

    def __post_init__(self):
        # a real exception, not `assert`: the RUNBOOK promises this
        # invariant holds unconditionally, python -O included
        if self.kept_bits != self.total_bits:
            raise RuntimeError(
                f"greedy cover lost coverage ({self.kept_bits} of "
                f"{self.total_bits} bits) — set-cover invariant broken")


def distill(backend, target, testcases: Sequence[bytes],
            registry: Optional[Registry] = None, events=None,
            batch_size: Optional[int] = None,
            on_batch=None, after_batch=None) -> DistillResult:
    """Distill `testcases` (replayed in list order) to a minimal subset
    with identical aggregate coverage.  The optional callbacks thread
    straight through to the replay core (accounting / heartbeats)."""
    core = ReplayCore(backend, target, registry=registry, events=events,
                      batch_size=batch_size)
    registry, events = core.registry, core.events
    testcases = list(testcases)
    sweep = core.replay(testcases, collect_planes=True, attribute=True,
                        want_buckets=True, on_batch=on_batch,
                        after_batch=after_batch)
    n = len(testcases)
    if n == 0:
        empty = np.zeros(0, dtype=np.int64)
        return DistillResult([], [], empty, 0, 0, sweep)
    planes = np.concatenate([sweep.cov, sweep.edge], axis=1)
    credit = np.concatenate([sweep.credit_cov, sweep.credit_edge], axis=1)
    credit_bits = popcount_rows(credit)
    prefix_keep = [i for i in range(n) if credit_bits[i] > 0]
    keep = greedy_cover(planes)
    union = np.bitwise_or.reduce(planes, axis=0)
    total_bits = int(popcount_rows(union[None, :])[0])
    kept = (np.bitwise_or.reduce(planes[keep], axis=0)
            if keep else np.zeros_like(union))
    kept_bits = int(popcount_rows(kept[None, :])[0])
    registry.counter("triage.minset_before").inc(n)
    registry.counter("triage.minset_after").inc(len(keep))
    events.emit("triage-distill", corpus=n, kept=len(keep),
                prefix_kept=len(prefix_keep), total_bits=total_bits,
                dispatches=core.stats["dispatches"])
    return DistillResult(keep=keep, prefix_keep=prefix_keep,
                         credit_bits=credit_bits, total_bits=total_bits,
                         kept_bits=kept_bits, sweep=sweep)
