"""Virtual-breakpoint replay: batched root-cause snapshots.

The PAPERS.md "Virtual Breakpoints for x86/64" leg: the overlay/SMC
machinery already detects armed breakpoints per lane pre-execution (the
uop table's bp column — the batched 0xcc analog), so "break at
instruction N across thousands of perturbed replays" is one sweep of
the shared replay core with a capture handler armed:

  * arm a breakpoint at a target RIP (symbol or address);
  * replay a batch of testcases (typically a crasher and its perturbed
    neighborhood — `perturbations()` builds a deterministic one);
  * per lane, on the `hit`-th arrival at that RIP with at least
    `min_icount` instructions retired, snapshot the register file plus
    a guest-memory window (default: the top of stack) and park the
    lane; lanes that never arrive report their natural result.

Captures are exact: the device parks the lane AT the armed instruction
(nothing about it has executed), so a capture equals the EmuCpu
oracle's state at the same arrival — the differential
tests/test_triage.py pins via `oracle_capture`, which runs the
identical handler on the single-step backend.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from wtf_tpu.core.results import Ok, TestcaseResult
from wtf_tpu.telemetry import Registry
from wtf_tpu.triage.bucket import TOS_BYTES
from wtf_tpu.triage.replay import ReplayCore


@dataclasses.dataclass
class BreakCapture:
    """One lane's snapshot at the armed instruction."""

    index: int                  # testcase index in the sweep
    hit: int                    # which arrival triggered the capture
    rip: int
    gpr: Tuple[int, ...]        # rax..r15 (encoding order)
    rflags: int
    icount: int
    mem_gva: int                # window base (rsp when unspecified)
    mem: bytes                  # the captured window (b"" = unreadable)

    def as_dict(self) -> dict:
        return {
            "index": self.index, "hit": self.hit,
            "rip": hex(self.rip),
            "gpr": [hex(v) for v in self.gpr],
            "rflags": hex(self.rflags), "icount": self.icount,
            "mem_gva": hex(self.mem_gva), "mem": self.mem.hex(),
        }


def _capture(backend, index: int, hit: int, mem_gva: Optional[int],
             mem_len: int) -> BreakCapture:
    gva = mem_gva if mem_gva is not None else backend.get_reg(4)
    try:
        mem = backend.virt_read(gva, mem_len)
    except Exception:
        mem = b""
    return BreakCapture(
        index=index, hit=hit, rip=backend.get_rip(),
        gpr=tuple(backend.get_reg(i) for i in range(16)),
        rflags=backend.get_rflags(), icount=backend.get_icount(),
        mem_gva=gva, mem=mem)


def vbreak(backend, target, testcases: Sequence[bytes], break_rip: int,
           *, hit: int = 1, min_icount: int = 0,
           mem_gva: Optional[int] = None, mem_len: int = TOS_BYTES,
           registry: Optional[Registry] = None, events=None
           ) -> Tuple[List[Optional[BreakCapture]], List[TestcaseResult]]:
    """Replay `testcases` with a virtual breakpoint armed at
    `break_rip`; returns (captures, results) index-aligned with the
    input.  A captured lane's result is Ok (parked at the break);
    None in `captures` means that replay never satisfied the break
    condition (crashed/finished/timed out first — its result says
    which)."""
    core = ReplayCore(backend, target, registry=registry, events=events)
    registry, events = core.registry, core.events
    if break_rip in backend.breakpoints:
        raise ValueError(
            f"breakpoint already armed at {break_rip:#x} (target init "
            "owns it) — vbreak needs an unclaimed RIP")
    captures: Dict[int, BreakCapture] = {}
    hits: Dict[int, int] = {}
    base = {"start": 0}

    def handler(b):
        index = base["start"] + b.current_lane
        n = hits.get(index, 0) + 1
        hits[index] = n
        if n < hit or b.get_icount() < min_icount:
            return  # not yet: lane resumes past the bp (bp_skip)
        captures[index] = _capture(b, index, n, mem_gva, mem_len)
        b.stop(Ok())

    backend.set_breakpoint(break_rip, handler)
    try:
        sweep = core.replay(
            testcases,
            on_batch_start=lambda start: base.update(start=start))
    finally:
        backend.breakpoints.pop(break_rip, None)
        runner = getattr(backend, "runner", None)
        if runner is not None:
            runner.cache.clear_breakpoint(break_rip)
    registry.counter("triage.captures").inc(len(captures))
    events.emit("triage-vbreak", rip=hex(break_rip),
                testcases=len(sweep.results), captures=len(captures))
    return ([captures.get(i) for i in range(len(sweep.results))],
            sweep.results)


def oracle_capture(emu_backend, target, data: bytes, break_rip: int,
                   *, hit: int = 1, min_icount: int = 0,
                   mem_gva: Optional[int] = None, mem_len: int = TOS_BYTES,
                   index: int = 0) -> Optional[BreakCapture]:
    """The same capture on the single-step EmuCpu backend — the
    differential oracle for `vbreak` (and a debugging convenience:
    `wtf-tpu triage vbreak --backend emu` routes here).  `index` labels
    the capture with the caller's sweep position, matching the batched
    path's indexing."""
    state: Dict[int, BreakCapture] = {}
    hits = {"n": 0}

    def handler(b):
        hits["n"] += 1
        if hits["n"] < hit or b.get_icount() < min_icount:
            return
        state[0] = _capture(b, index, hits["n"], mem_gva, mem_len)
        b.stop(Ok())

    emu_backend.set_breakpoint(break_rip, handler)
    try:
        target.insert_testcase(emu_backend, data)
        emu_backend.run()
    finally:
        emu_backend.breakpoints.pop(break_rip, None)
        emu_backend.restore()
        target.restore()
    return state.get(0)


def perturbations(data: bytes, count: int) -> List[bytes]:
    """A deterministic perturbed neighborhood of `data` for vbreak
    sweeps: variant k flips byte (k * PHI) mod len by XOR with a
    splitmix-derived value — pure function of (data, count), so sweeps
    replay identically anywhere."""
    from wtf_tpu.utils.hashing import splitmix64

    out = [bytes(data)]
    if not data:
        return out[:max(count, 1)]
    for k in range(1, count):
        x = splitmix64(k)
        pos = x % len(data)
        flip = (x >> 32) & 0xFF or 0xFF
        b = bytearray(data)
        b[pos] ^= flip
        out.append(bytes(b))
    return out
