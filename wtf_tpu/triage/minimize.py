"""Batched crash minimization: thousands of candidate reductions per
dispatch, bisecting to a minimal reproducer in a handful of dispatches.

The reference minimizes host-serially (one emulator, one candidate at a
time); here each round builds a whole batch of candidates IN-GRAPH from
the current best reproducer (triage/candidates.py), lands them through
the fused insert seam (`Runner.device_insert` via
`TpuBackend.run_batch_words`), and keeps the best candidate that still
reproduces the SAME crash bucket (triage/bucket.py — kind + faulting
RIP + top-of-stack hash, so "still reproduces" means the same bug).

Two phases, both greedy and fully deterministic (no RNG — the schedule
is a pure function of the current length, so mesh and single-device
runs are bit-identical):

  structural   rounds of all truncations + a coarse-to-fine grid of
               block deletions; each round keeps the strictly shortest
               surviving candidate (ties: lowest descriptor index) and
               re-derives the schedule from it
  simplify     one sweep of single-byte zeroing candidates; every byte
               whose zeroing individually preserved the bucket is
               applied at once, then the combined reproducer is
               verified in one more dispatch (afl-tmin's scheme) —
               falling back to the unsimplified reproducer when byte
               interactions break the combination

Dispatch math (PERF.md triage round): a round of a length-L reproducer
is ~L truncations + ~2L deletions ≈ ceil(3L / lanes) dispatches, and
the structural phase converges in O(#edits) rounds — at 4096 lanes a
1 KiB crasher minimizes in single-digit dispatches per round.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from wtf_tpu.core.results import Crash
from wtf_tpu.triage.bucket import crash_kind
from wtf_tpu.telemetry import Registry
from wtf_tpu.triage.candidates import (
    OP_DELETE, OP_TRUNCATE, OP_ZERO, make_build, make_zero_counts,
    pack_testcase,
)
from wtf_tpu.triage.replay import ReplayCore

# descriptor ceiling per structural round; the schedule degrades from
# exhaustive to a pow2-spaced grid when a long input would exceed it
MAX_ROUND_CANDIDATES = 1 << 14


@dataclasses.dataclass
class MinimizeResult:
    data: bytes              # the minimized reproducer
    bucket: str              # its (and the original's) crash bucket
    from_len: int            # original crasher length
    rounds: int              # structural rounds executed
    dispatches: int          # device dispatches consumed (all phases)
    candidates: int          # candidates executed (all phases)
    simplified: int          # bytes zeroed by the simplify phase


def _structural_schedule(cur_len: int) -> List[Tuple[int, int, int]]:
    """(op, pos, size) descriptors for one structural round: every
    truncation (pow2-thinned past the candidate ceiling) + block
    deletions from half the length down to 1 byte, positions stepping
    by the block size."""
    descs: List[Tuple[int, int, int]] = []
    if cur_len <= 1:
        return descs
    step = 1
    while (cur_len - 1) // step > MAX_ROUND_CANDIDATES // 3:
        step *= 2
    for ln in range(1, cur_len, step):
        descs.append((OP_TRUNCATE, ln, 0))
    size = max(cur_len // 2, 1)
    while size >= 1:
        for pos in range(0, cur_len, size):
            descs.append((OP_DELETE, pos, size))
            if len(descs) >= MAX_ROUND_CANDIDATES:
                return descs
        if size == 1:
            break
        size //= 2
    return descs


def _run_schedule(core: ReplayCore, cur: bytes, descs, max_len: int,
                  base_kind: str):
    """Execute a descriptor list against the current reproducer in
    n_lanes-sized dispatches.  Returns per-descriptor
    (len, zeros, bucket-or-None); winner bytes are re-built and fetched
    by `_fetch_candidate` — candidates are deterministic functions of
    (cur, descriptor), so dispatches need no retention."""
    build = make_build()
    zcount = make_zero_counts()
    cur_words, cur_len = pack_testcase(cur, max_len)
    cur_dev = jnp.asarray(cur_words)
    cur_len_dev = jnp.uint32(cur_len)
    lanes = core.n_lanes
    out = []
    for start in range(0, len(descs), lanes):
        chunk = descs[start:start + lanes]
        pad = lanes - len(chunk)
        ops = np.array([d[0] for d in chunk] + [OP_ZERO] * pad,
                       dtype=np.int32)
        pos = np.array([d[1] for d in chunk] + [0] * pad, dtype=np.uint32)
        size = np.array([d[2] for d in chunk] + [0] * pad, dtype=np.uint32)
        words, lens = build(cur_dev, cur_len_dev, jnp.asarray(ops),
                            jnp.asarray(pos), jnp.asarray(size))
        zeros = zcount(words, lens)
        results, buckets = core.replay_device(words, lens, len(chunk),
                                              base_kind=base_kind)
        lens_h = np.asarray(jax.device_get(lens))
        zeros_h = np.asarray(jax.device_get(zeros))
        for lane in range(len(chunk)):
            out.append((int(lens_h[lane]), int(zeros_h[lane]),
                        buckets.get(lane)))
    return out


def _fetch_candidate(core: ReplayCore, cur: bytes, descs, max_len: int,
                     index: int) -> bytes:
    """Re-build the dispatch holding descriptor `index` and pull that
    one lane's bytes (ONE row gather + transfer)."""
    build = make_build()
    cur_words, cur_len = pack_testcase(cur, max_len)
    lanes = core.n_lanes
    start = (index // lanes) * lanes
    chunk = descs[start:start + lanes]
    pad = lanes - len(chunk)
    ops = np.array([d[0] for d in chunk] + [OP_ZERO] * pad, dtype=np.int32)
    pos = np.array([d[1] for d in chunk] + [0] * pad, dtype=np.uint32)
    size = np.array([d[2] for d in chunk] + [0] * pad, dtype=np.uint32)
    words, lens = build(jnp.asarray(cur_words), jnp.uint32(cur_len),
                        jnp.asarray(ops), jnp.asarray(pos),
                        jnp.asarray(size))
    lane = index - start
    row = np.asarray(jax.device_get(words[lane]))
    ln = int(np.asarray(jax.device_get(lens[lane])))
    return row.tobytes()[:ln]


def minimize(backend, target, crasher: bytes,
             registry: Optional[Registry] = None, events=None,
             max_rounds: int = 64) -> MinimizeResult:
    """Minimize `crasher` against `target` on an initialized batched
    backend.  Raises ValueError when the input does not reproduce a
    crash under batch replay (the identity dispatch is the baseline)."""
    core = ReplayCore(backend, target, registry=registry, events=events)
    registry, events = core.registry, core.events
    spec, _ = core.device_spec()
    max_len = spec.max_len
    crasher = bytes(crasher[:max_len])
    if not crasher:
        raise ValueError("empty testcase cannot be minimized")
    build = make_build()
    dispatches0 = core.stats["dispatches"]
    candidates0 = core.stats["candidates"]

    # baseline: the identity candidate through the SAME device insert
    # path every later candidate takes — one replay path, one bucket
    def identity_sweep(data: bytes):
        cur_words, cur_len = pack_testcase(data, max_len)
        lanes = core.n_lanes
        ops = np.zeros(lanes, dtype=np.int32) + OP_ZERO
        zeros = np.zeros(lanes, dtype=np.uint32)
        words, lens = build(jnp.asarray(cur_words), jnp.uint32(cur_len),
                            jnp.asarray(ops), jnp.asarray(zeros),
                            jnp.asarray(zeros))
        return core.replay_device(words, lens, 1)

    results, buckets = identity_sweep(crasher)
    if not isinstance(results[0], Crash):
        raise ValueError(
            f"input does not reproduce a crash under batch replay "
            f"(got {results[0]}) — nothing to minimize")
    base_bucket = buckets[0]
    base_kind = crash_kind(results[0])
    events.emit("triage-minimize-start", bytes=len(crasher),
                bucket=base_bucket)

    cur = crasher
    rounds = 0
    # structural phase: shortest surviving candidate per round
    while rounds < max_rounds:
        descs = _structural_schedule(len(cur))
        if not descs:
            break
        outcomes = _run_schedule(core, cur, descs, max_len, base_kind)
        best = None  # (len, -zeros, index)
        for i, (ln, zeros, bucket) in enumerate(outcomes):
            if bucket != base_bucket or ln >= len(cur):
                continue
            key = (ln, -zeros, i)
            if best is None or key < best:
                best = key
        rounds += 1
        # attempted rounds, improving or not — the counter, the CLI
        # line and the minimize-end event must agree on one number
        registry.counter("triage.minimize_rounds").inc()
        if best is None:
            break
        cur = _fetch_candidate(core, cur, descs, max_len, best[2])

    # simplify phase: zero every byte that individually survives, then
    # verify the combination in one dispatch
    simplified = 0
    nonzero = [i for i, byte in enumerate(cur) if byte]
    if nonzero:
        descs = [(OP_ZERO, i, 1) for i in nonzero]
        outcomes = _run_schedule(core, cur, descs, max_len, base_kind)
        good = [pos for (_, _, bucket), (_, pos, _) in
                zip(outcomes, descs) if bucket == base_bucket]
        if good:
            combined = bytearray(cur)
            for pos in good:
                combined[pos] = 0
            combined = bytes(combined)
            _, buckets = identity_sweep(combined)
            if buckets.get(0) == base_bucket:
                cur = combined
                simplified = len(good)
            # else: byte interactions break the union — keep the
            # structurally-minimal reproducer (documented fallback)

    removed = len(crasher) - len(cur)
    registry.counter("triage.bytes_removed").inc(removed)
    registry.counter("triage.minimizations").inc()
    dispatches = core.stats["dispatches"] - dispatches0
    events.emit("triage-minimize-end", from_bytes=len(crasher),
                to_bytes=len(cur), bucket=base_bucket, rounds=rounds,
                dispatches=dispatches, simplified=simplified)
    return MinimizeResult(
        data=cur, bucket=base_bucket, from_len=len(crasher),
        rounds=rounds, dispatches=dispatches,
        candidates=core.stats["candidates"] - candidates0,
        simplified=simplified)
