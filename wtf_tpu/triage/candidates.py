"""In-graph candidate generation for crash minimization.

One `build` dispatch turns the current best reproducer into a whole
batch of candidate reductions — the devmut-engine technique (byte plane
+ clamped gathers, u32 only) applied to the minimizer's three candidate
classes:

  OP_TRUNCATE  keep the first `pos` bytes (tail removal)
  OP_DELETE    remove the block [pos, pos+size) — the tail shifts left
               through ONE clamped gather (engine.take's trick)
  OP_ZERO      overwrite [pos, pos+size) with 0x00 at unchanged length
               (byte simplification; size 0 == identity, the baseline
               replay descriptor)

The reproducer is uploaded once per round as packed u32 words
(zero-padded past its length, the devmut slab contract); descriptors
(op/pos/size per lane) are tiny host arrays.  Output feeds straight
into `Runner.device_insert` via `TpuBackend.run_batch_words`, so the
candidate bytes never visit the host — the harvest pulls only the one
winning lane.

Every path here is exported through `PORTED_LIMB_PATHS` so `wtf-tpu
lint`'s dtype family compiles it under the zero-u64/f64 pin, exactly
like the step's and devmut's ported paths.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from wtf_tpu.devmut.engine import unpack_bytes, pack_words

OP_TRUNCATE = 0
OP_DELETE = 1
OP_ZERO = 2
OP_NAMES = ("truncate", "delete", "zero")


def build_candidates(cur_words, cur_len, ops, pos, size
                     ) -> Tuple[jax.Array, jax.Array]:
    """Build one candidate per lane from the current reproducer.

    cur_words uint32[W]   packed reproducer (zero-padded past cur_len)
    cur_len   uint32[]    reproducer byte length (>= 1)
    ops       int32[L]    OP_* per lane
    pos       uint32[L]   truncate: the new length; delete/zero: offset
    size      uint32[L]   delete/zero block size (clamped in-graph)

    Returns (words uint32[L, W], lens int32[L]).  Candidate lengths stay
    >= 1; bytes past each candidate's length are zero (the padded-slab
    contract device_insert relies on for deterministic page contents).
    """
    n_words = cur_words.shape[0]
    max_len = n_words * 4
    n_lanes = ops.shape[0]
    ml = jnp.uint32(max_len)
    one = jnp.uint32(1)
    idx = lax.broadcasted_iota(jnp.uint32, (n_lanes, max_len), 1)
    lane = lax.broadcasted_iota(jnp.int32, (n_lanes, max_len), 0)
    b = jnp.broadcast_to(unpack_bytes(cur_words)[None, :],
                         (n_lanes, max_len))

    def take(bb, src_u32):
        src = jnp.minimum(src_u32, ml - one).astype(jnp.int32)
        return bb[lane, src]

    # truncate: new length = clamp(pos, 1, cur_len)
    ln_tr = jnp.clip(pos, one, cur_len)

    # delete [pos, pos+size): clamp so at least one byte survives
    dpos = jnp.minimum(pos, cur_len - one)
    dsz = jnp.minimum(jnp.minimum(size, cur_len - dpos),
                      cur_len - one)
    src_del = jnp.where(idx < dpos[:, None], idx, idx + dsz[:, None])
    b_del = take(b, src_del)
    ln_del = cur_len - dsz

    # zero [pos, pos+size) at unchanged length (size 0 == identity)
    zwin = (idx >= pos[:, None]) & (idx < (pos + size)[:, None])
    b_zero = jnp.where(zwin, jnp.uint32(0), b)

    is_tr = (ops == jnp.int32(OP_TRUNCATE))[:, None]
    is_del = (ops == jnp.int32(OP_DELETE))[:, None]
    out_b = jnp.where(is_del, b_del, jnp.where(is_tr, b, b_zero))
    out_ln = jnp.where(is_del[:, 0], ln_del,
                       jnp.where(is_tr[:, 0], ln_tr,
                                 jnp.broadcast_to(cur_len, (n_lanes,))))
    out_b = jnp.where(idx < out_ln[:, None], out_b, jnp.uint32(0))
    return pack_words(out_b), out_ln.astype(jnp.int32)


def zero_counts(words, lens):
    """Per-lane count of zero bytes inside each candidate's length —
    the simplification half of the minimizer's (len, -zeros) score,
    computed device-side so scoring never pulls candidate bytes."""
    n_words = words.shape[1]
    b = unpack_bytes(words)
    idx = lax.broadcasted_iota(jnp.uint32, (words.shape[0], n_words * 4), 1)
    inside = idx < lens.astype(jnp.uint32)[:, None]
    return jnp.sum((inside & (b == jnp.uint32(0))).astype(jnp.uint32),
                   axis=1, dtype=jnp.uint32).astype(jnp.int32)


@lru_cache(maxsize=None)
def make_build():
    """The jitted candidate builder (shape specialization is jit's own:
    one executor per (words, lanes))."""
    return jax.jit(build_candidates)


@lru_cache(maxsize=None)
def make_zero_counts():
    return jax.jit(zero_counts)


def pack_testcase(data: bytes, max_len: int) -> Tuple[np.ndarray, int]:
    """Host helper: bytes -> (packed u32[max_len/4] zero-padded, length).
    The upload format `build_candidates` and the devmut slab share."""
    data = data[:max_len]
    words = (max_len + 3) // 4
    buf = np.zeros(words * 4, dtype=np.uint8)
    buf[:len(data)] = np.frombuffer(data, dtype=np.uint8)
    return buf.view(np.uint32), len(data)


# Export hook for the static analyzer (mirrors step./devmut.
# PORTED_LIMB_PATHS): compiled standalone under the zero-u64/f64 dtype
# rule by `wtf-tpu lint`; argument recipes in analysis/rules.
PORTED_LIMB_PATHS = {
    "triage.build_candidates": build_candidates,
    "triage.zero_counts": zero_counts,
}
