"""tpu-wtf: a TPU-native, distributed, coverage-guided, snapshot-based fuzzer.

Brand-new framework with the capabilities of the reference fuzzer (m4drat/wtf,
see SURVEY.md): where the reference runs one testcase at a time inside
bochscpu/WHV/KVM, this framework executes *batches* of mutated testcases in
lockstep as a vmapped JAX x86-64 interpreter over an HBM-resident snapshot
image, with lane-masked divergent control flow, device-side coverage bitmaps,
and dirty-page restore as O(1) overlay reset.

Layering (mirrors SURVEY.md section 1's layer map, redesigned TPU-first):
  core/     - strong address types, CpuState, NT structs, results    (L1)
  snapshot/ - snapshot loaders: kdmp / raw / synthetic               (L1)
  mem/      - physical memory image, paging, per-lane dirty overlay  (L1/L2)
  cpu/      - decoder, uops, host oracle interpreter                 (L2)
  interp/   - the vmapped fetch-decode-execute x86-64 interpreter    (L2)
  backend/  - Backend contract + EmuBackend / TpuBackend             (L2)
  symbols/  - symbol store + address<->name (debugger layer)         (L3)
  harness/  - target registry, crash detection, guest-fs, demos      (L4)
  fuzz/     - corpus, mutators (python + native), dirwatch, loop     (L5)
  dist/     - master/node wire protocol + reactor                    (L5)
  meshrun/  - device mesh sharding, shard_map executors, mesh merge  (L5)
  resume/   - crash-safe campaign checkpoint/resume                  (L5)
  tenancy/  - multi-tenant batch + campaign scheduler                (L5)
  testing/  - deterministic chaos harness (fault injection)          (aux)
  trace/    - rip/cov/tenet trace writers                            (aux)
  native/   - on-demand-built C++ components (kdmp, mangle)          (aux)
  cli.py    - `master|fuzz|run|campaign|sched` subcommands           (L6)
  config.py - per-subcommand options objects + path conventions      (L6)
"""

import os

import jax

# The guest is an x86-64 machine: 64-bit GPRs, 64-bit linear addresses.
# Enable x64 so uint64 is a real dtype everywhere (XLA lowers 64-bit integer
# ops to 32-bit pairs on TPU; correctness first, the Pallas hot path works on
# packed 32-bit lanes).
jax.config.update("jax_enable_x64", True)

# Honor JAX_PLATFORMS explicitly: some environments pre-register a TPU PJRT
# plugin from sitecustomize and force the platform over the env var, which
# makes `JAX_PLATFORMS=cpu python -m wtf_tpu ...` silently (or hangingly)
# target the chip.  An explicit config update wins as long as no backend
# has been initialized yet.
_platforms = os.environ.get("JAX_PLATFORMS")
if _platforms and _platforms != "axon":
    try:
        jax.config.update("jax_platforms", _platforms)
    except Exception:
        pass

# Persistent XLA compilation cache: the interpreter step function is large
# (~40-90s per compile on a 1-core host) and its shapes recur across
# processes (bench reruns, CLI invocations, the driver's compile checks).
# A user-provided JAX_COMPILATION_CACHE_DIR wins, like JAX_PLATFORMS above.
if not os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.expanduser("~/.cache/wtf_tpu_xla"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception:
        pass

__version__ = "0.1.0"
