"""x86-64 exception delivery through the guest IDT.

In the reference every hardware fault a guest takes is serviced BY THE
GUEST: bochs emulates the IDT/TSS walk internally, and the hypervisor
backends inject the event so the guest kernel runs its handler
(bochscpu_backend.cc:917-999 `PageFaultsMemoryIfNeeded` +
`bochscpu_cpu_set_exception`, kvm_backend.cc:2019-2042,
whv_backend.cc:1218-1247).  That is what makes guard-page stack growth,
SEH dispatch reaching `ntdll!RtlDispatchException`, and harness-forced
page-ins work on real Windows snapshots.

This module is the single delivery implementation both execution engines
share:

  - the oracle (`cpu/emu.py`) delivers synchronously when an instruction
    faults (`EmuBackend.run` catches the fault and injects),
  - the batched device path surfaces faults in the lane status
    (PAGE_FAULT/DIVIDE_ERROR + fault_gva/fault_write) and the host runner
    injects between chunks (`interp/runner.py::Runner._service_exception`),
  - `Backend.page_faults_memory_if_needed` injects a synthetic #PF the way
    the reference does to make the guest page memory in before host writes.

Scope: long-mode (64-bit) interrupt/trap gates, IST and CPL-change stack
switches through the TSS, error-code pushes, CR2 update.  Task gates and
16/32-bit gates raise `DeliveryFailed` and the fault stays terminal —
exactly the pre-delivery behavior (a crash named from the raw fault).

The `ctx` duck type (implemented by `EmuCpu` and the runner's `_LaneCtx`):
  read/write:  read_virt(gva, n) -> bytes, write_u64(gva, v), read_u64(gva)
  registers:   rip, rsp, rflags, cs_sel, ss_sel  (get/set attributes)
  tables:      idt_base, idt_limit, tss_base      (get attributes)
  faults:      set_cr2(v)
Memory accessors raise the engine's fault type on unmapped addresses; the
caller treats any such escape as an undeliverable (double-fault-like)
condition and keeps the lane terminal.
"""

from __future__ import annotations

import struct

MASK64 = (1 << 64) - 1

# vectors
VEC_DE = 0    # #DE divide error
VEC_BP = 3    # #BP int3
VEC_UD = 6    # #UD invalid opcode
VEC_DF = 8    # #DF double fault
VEC_GP = 13   # #GP general protection
VEC_PF = 14   # #PF page fault

# #PF error-code bits (Intel SDM Vol 3A 4.7)
PF_ERR_P = 1 << 0       # 0 = non-present, 1 = protection violation
PF_ERR_W = 1 << 1       # access was a write
PF_ERR_U = 1 << 2       # access from CPL 3

# vectors that push an error code (SDM Vol 3A 6.15)
_HAS_ERROR_CODE = frozenset({8, 10, 11, 12, 13, 14, 17, 21, 29, 30})

_RF_TF = 1 << 8
_RF_IF = 1 << 9
_RF_NT = 1 << 14
_RF_RF = 1 << 16


class DeliveryFailed(Exception):
    """The guest IDT cannot service this vector (absent/bad gate, no IDT,
    unsupported gate type).  Caller keeps the fault terminal."""


def pf_error_code(present: bool, write: bool, user: bool) -> int:
    return ((PF_ERR_P if present else 0)
            | (PF_ERR_W if write else 0)
            | (PF_ERR_U if user else 0))


def has_error_code(vector: int) -> bool:
    return vector in _HAS_ERROR_CODE


def deliver_page_fault(ctx, gva: int, write: bool, read_translates) -> None:
    """Route a memory fault to the architecturally correct vector and
    deliver it.

    Canonical addresses take #PF (error code P/W/U, CR2 = gva); a
    NON-canonical address is #GP(0) on real hardware — no CR2 update —
    and Windows' KiGeneralProtectionFault turns that into an A/V with no
    faulting address, which is exactly what harness hooks then observe.

    One implementation for both engines (the oracle backend and the batch
    runner) so what the guest handler sees can never diverge between
    them.  `read_translates(gva) -> bool` is the engine's presence probe
    (translate ignoring the access direction): P reflects whether the
    page is mapped — a faulting access to a PRESENT page is a protection
    violation (P=1), e.g. a write through a read-only PTE; anything
    unmapped is non-present (P=0), the demand-paging shape a real
    Windows MmAccessFault distinguishes.  U comes from the ctx's CPL.
    """
    if (gva >> 47) not in (0, 0x1FFFF):  # non-canonical: #GP, not #PF
        deliver_exception(ctx, VEC_GP, 0)
        return
    present = read_translates(gva)
    err = pf_error_code(present, write, (ctx.cs_sel & 3) == 3)
    deliver_exception(ctx, VEC_PF, err, cr2=gva)


def deliver_exception(ctx, vector: int, error_code: int = 0,
                      cr2=None) -> None:
    """Push the interrupt frame and vector `ctx` through its IDT.

    Mirrors the hardware event-delivery sequence (SDM Vol 3A 6.14
    "Exception and Interrupt Handling in 64-bit Mode"): 16-byte gate
    fetch, IST / CPL-change stack selection via the TSS, 16-byte stack
    alignment, SS:RSP/RFLAGS/CS:RIP[/error] pushes, IF masking for
    interrupt gates.  Raises DeliveryFailed when the gate cannot service
    the vector; lets the ctx's own fault type escape when the IDT/TSS/
    stack memory itself is unmapped (the double-fault analog).
    """
    if not 0 <= vector <= 255:
        raise DeliveryFailed(f"vector {vector} out of range")
    if ctx.idt_limit < vector * 16 + 15:
        raise DeliveryFailed(
            f"IDT limit {ctx.idt_limit:#x} does not cover vector {vector}")

    gate = ctx.read_virt((ctx.idt_base + vector * 16) & MASK64, 16)
    off_lo, sel, ist_byte, type_byte, off_mid, off_hi = struct.unpack(
        "<HHBBHI", gate[:12])
    if not type_byte & 0x80:
        raise DeliveryFailed(f"gate {vector} not present")
    gate_type = type_byte & 0xF
    if gate_type not in (0xE, 0xF):  # 64-bit interrupt / trap gate
        raise DeliveryFailed(f"gate {vector} type {gate_type:#x} unsupported")
    handler = off_lo | (off_mid << 16) | (off_hi << 32)

    old_cpl = ctx.cs_sel & 3
    new_cpl = sel & 3
    ist = ist_byte & 7
    if ist:
        rsp = ctx.read_u64((ctx.tss_base + 0x24 + (ist - 1) * 8) & MASK64)
    elif old_cpl != new_cpl:
        rsp = ctx.read_u64((ctx.tss_base + 4) & MASK64)  # TSS.RSP0
    else:
        rsp = ctx.rsp
    rsp &= ~0xF  # hardware aligns the frame base to 16 bytes

    frame = [ctx.ss_sel, ctx.rsp, (ctx.rflags | 0x2) & MASK64,
             ctx.cs_sel, ctx.rip]
    if vector in _HAS_ERROR_CODE:
        frame.append(error_code & MASK64)
    for value in frame:
        rsp = (rsp - 8) & MASK64
        ctx.write_u64(rsp, value)

    ctx.rsp = rsp
    ctx.rip = handler & MASK64
    ctx.cs_sel = sel
    if old_cpl != new_cpl:
        # long mode loads SS with the NULL selector (RPL = new CPL) on an
        # inter-privilege delivery; iretq restores the pushed one
        ctx.ss_sel = new_cpl
    rflags = ctx.rflags & ~(_RF_TF | _RF_NT | _RF_RF)
    if gate_type == 0xE:  # interrupt gate masks IF; trap gate leaves it
        rflags &= ~_RF_IF
    ctx.rflags = rflags | 0x2
    if cr2 is not None:
        ctx.set_cr2(cr2 & MASK64)
