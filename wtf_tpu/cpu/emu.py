"""Pure-Python x86-64 oracle executor over decoded Uops.

Role in the system (SURVEY.md §4): the reference's development workflow
validates the fast backends against deterministic bochscpu `rip` traces; we
keep the same methodology with this module as the trace producer.  It shares
the decoder (cpu/decoder.py) with the device path, so a differential test
pins down exactly one thing: that the device executor (interp/step.py)
implements the same *semantics* for each uop.  It also powers the `emu` execution
backend (the "fake backend" seam, reference `Backend_t` §2.2) so the whole
harness/fuzz/distribution plane is testable without a TPU.

Unsupported-instruction policy: raise/flag, never guess — identical to the
device executor's UNSUPPORTED status.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from wtf_tpu.core.cpustate import (
    CpuState,
    RFLAGS_AF, RFLAGS_CF, RFLAGS_DF, RFLAGS_OF, RFLAGS_PF, RFLAGS_SF,
    RFLAGS_ZF,
)
from wtf_tpu.core.gxa import PAGE_SHIFT, PAGE_SIZE
from wtf_tpu.cpu import uops as U
from wtf_tpu.cpu.cpuid import cpuid, splitmix64
from wtf_tpu.cpu.decoder import decode
from wtf_tpu.mem.physmem import PhysMem

MASK64 = (1 << 64) - 1

# MSR number -> EmuCpu attribute for the rdmsr/wrmsr subset the snapshot
# carries (reference: bochs/KVM MSR state, kvm_backend.cc LoadMsrs)
MSR_ATTR = {0x10: "tsc", 0xC0000080: "efer", 0xC0000081: "star",
            0xC0000082: "lstar", 0xC0000084: "sfmask",
            0xC0000100: "fs_base", 0xC0000101: "gs_base",
            0xC0000102: "kernel_gs_base"}

PTE_P = 1
PTE_W = 1 << 1
PTE_PS = 1 << 7
PHYS_MASK = 0x000F_FFFF_FFFF_F000


class MemFault(Exception):
    """Unresolvable guest access (non-present / non-canonical / !W write)."""

    def __init__(self, gva: int, write: bool):
        super().__init__(f"#PF {'write' if write else 'read'} @ {gva:#x}")
        self.gva = gva
        self.write = write


class DivideError(Exception):
    pass


class UnsupportedInsn(Exception):
    def __init__(self, rip: int, raw: bytes):
        super().__init__(f"unsupported instruction @ {rip:#x}: {raw.hex()}")
        self.rip = rip
        self.raw = raw


class EmuMem:
    """Overlay-on-snapshot memory, mirroring mem/overlay.py semantics: the
    base image is immutable; writes copy pages into a dict overlay; reset()
    is O(dirty)."""

    def __init__(self, physmem: PhysMem):
        self.phys = physmem
        self.overlay: Dict[int, bytearray] = {}

    def reset(self) -> None:
        self.overlay.clear()

    def dirty_pfns(self) -> List[int]:
        return sorted(self.overlay)

    def _page(self, pfn: int, for_write: bool) -> bytes:
        if pfn in self.overlay:
            return self.overlay[pfn]
        if for_write:
            base = self.phys.host_read(pfn << PAGE_SHIFT, PAGE_SIZE)
            page = bytearray(base)
            self.overlay[pfn] = page
            return page
        return self.phys.host_read(pfn << PAGE_SHIFT, PAGE_SIZE)

    def phys_read(self, gpa: int, size: int) -> bytes:
        out = bytearray()
        pos = gpa
        while pos < gpa + size:
            pfn = pos >> PAGE_SHIFT
            off = pos & (PAGE_SIZE - 1)
            chunk = min(gpa + size - pos, PAGE_SIZE - off)
            page = self._page(pfn, for_write=False)
            out += page[off : off + chunk]
            pos += chunk
        return bytes(out)

    def phys_write(self, gpa: int, data: bytes) -> None:
        pos = 0
        while pos < len(data):
            addr = gpa + pos
            pfn = addr >> PAGE_SHIFT
            off = addr & (PAGE_SIZE - 1)
            chunk = min(len(data) - pos, PAGE_SIZE - off)
            page = self._page(pfn, for_write=True)
            page[off : off + chunk] = data[pos : pos + chunk]
            pos += chunk

    def phys_read_u64(self, gpa: int) -> int:
        return int.from_bytes(self.phys_read(gpa, 8), "little")


def _f80_to_f64_bits(v80: int) -> int:
    """80-bit x87 extended -> f64 bits (round-to-nearest-even on the
    mantissa; overflow -> inf, tiny -> 0; good enough for reducing a
    snapshot's FPU stack into the double-precision model)."""
    import struct as _struct

    sign = (v80 >> 79) & 1
    exp = (v80 >> 64) & 0x7FFF
    mant = v80 & ((1 << 64) - 1)
    if exp == 0x7FFF:  # inf / nan
        frac = (mant >> 11) & ((1 << 52) - 1)
        if mant & ((1 << 63) - 1):  # nan: keep top payload bits, quiet
            frac |= 1 << 51
        return (sign << 63) | (0x7FF << 52) | frac
    if exp == 0 and mant == 0:
        return sign << 63
    # normalize (pseudo-denormals included: integer bit may be 0)
    e = exp - 16383
    m = mant
    if m == 0:
        return sign << 63
    while not m >> 63:
        m <<= 1
        e -= 1
    import math

    try:
        f = math.ldexp(m / (1 << 63), e)  # m/2^63 rounds the mantissa once
    except OverflowError:
        f = math.inf
    if sign:
        f = -f
    return int.from_bytes(_struct.pack("<d", f), "little")


def _f64_to_f80(bits64: int) -> int:
    """f64 bits -> 80-bit x87 extended (exact; for the fxsave image)."""
    sign = (bits64 >> 63) & 1
    exp = (bits64 >> 52) & 0x7FF
    frac = bits64 & ((1 << 52) - 1)
    if exp == 0x7FF:  # inf / nan
        mant = (1 << 63) | (frac << 11)
        return (sign << 79) | (0x7FFF << 64) | mant
    if exp == 0:
        if frac == 0:
            return sign << 79
        # denormal: normalize into the explicit-integer-bit format
        e = -1022
        m = frac
        while not m >> 52:
            m <<= 1
            e -= 1
        return ((sign << 79) | ((e + 16383) << 64)
                | ((m & ((1 << 52) - 1)) << 11) | (1 << 63))
    return ((sign << 79) | ((exp - 1023 + 16383) << 64)
            | (1 << 63) | (frac << 11))


def _sx(value: int, bits: int) -> int:
    sign = 1 << (bits - 1)
    return ((value ^ sign) - sign)


def _parity(value: int) -> bool:
    return bin(value & 0xFF).count("1") % 2 == 0


class EmuCpu:
    """One guest vCPU interpreting over an EmuMem."""

    def __init__(self, mem: EmuMem, state: CpuState):
        self.mem = mem
        self.snapshot = state
        self.gpr: List[int] = [0] * 16
        self.xmm: List[List[int]] = [[0, 0] for _ in range(16)]
        # Upper YMM halves (2 limbs each): carried for AVX-bearing
        # snapshot round-trip and the xsave AVX component (reference
        # CpuState_t holds 32xZMM, globals.h:1020-1159); no instruction
        # in the executed subset COMPUTES on them — CPUID steers
        # feature-dispatched code onto SSE2 paths
        self.ymmh: List[List[int]] = [[0, 0] for _ in range(16)]
        self.rip = 0
        self.rflags = 0x2
        self.cr3 = 0
        self.cr0 = 0
        self.cr2 = 0
        self.cr4 = 0
        self.cr8 = 0
        self.cs_sel = 0
        self.ss_sel = 0
        # x87 state: values as f64 bits per PHYSICAL slot (see OPC_X87
        # note in cpu/uops.py for the precision model), TOP kept separate
        # and re-packed into fpsw bits 11-13 at observation points
        self.fpst: List[int] = [0] * 8
        self.fptop = 0
        self.fpcw = 0x27F
        self.fpsw = 0
        self.fptw = 0xFFFF
        self.mxcsr = 0x1F80
        self.fs_base = 0
        self.gs_base = 0
        self.kernel_gs_base = 0
        self.lstar = 0
        self.star = 0
        self.sfmask = 0
        self.efer = 0
        self.tsc = 0
        self.icount = 0
        self.rdrand_state = 0
        self.decode_cache: Dict[int, object] = {}
        # pfn -> rips decoded from that physical page (for SMC/restore flush)
        self.decode_pages: Dict[int, List[int]] = {}
        # when a list, virt_read/virt_write append ("mr"/"mw", gva, size) —
        # the tenet trace writer's lin_access-hook analog (SURVEY §5.1)
        self.access_log = None
        self.load_state(state)

    # -- state ----------------------------------------------------------
    def load_state(self, state: CpuState) -> None:
        self.gpr = state.gpr_list()
        self.rip = state.rip
        self.rflags = state.rflags | 0x2
        self.cr3 = state.cr3
        self.cr0 = state.cr0
        self.cr2 = state.cr2
        self.cr4 = state.cr4
        self.cr8 = state.cr8
        self.cs_sel = state.cs.selector
        self.ss_sel = state.ss.selector
        # snapshot fpst entries may be 80-bit extended (real dumps);
        # reduce to the f64 model on load
        self.fpst = [
            (_f80_to_f64_bits(v) if v >> 64 else v & MASK64)
            for v in state.fpst[:8]] + [0] * (8 - len(state.fpst[:8]))
        self.fpcw = state.fpcw & 0xFFFF
        self.fpsw = state.fpsw & 0xFFFF
        self.fptop = (state.fpsw >> 11) & 7
        self.fptw = state.fptw & 0xFFFF
        self.mxcsr = state.mxcsr & 0xFFFFFFFF
        self.fs_base = state.fs.base
        self.gs_base = state.gs.base
        self.kernel_gs_base = state.kernel_gs_base
        self.lstar = state.lstar
        self.star = state.star
        self.sfmask = state.sfmask
        self.efer = state.efer
        self.tsc = state.tsc
        self.icount = 0
        self.rdrand_state = 0
        self.cr3_event = None
        for i in range(16):
            self.xmm[i] = [state.zmm[i][0], state.zmm[i][1]]
            self.ymmh[i] = [state.zmm[i][2], state.zmm[i][3]]

    # -- registers ------------------------------------------------------
    def read_reg(self, idx: int, size: int) -> int:
        if idx >= U.REG_AH_BASE:
            return (self.gpr[idx - U.REG_AH_BASE] >> 8) & 0xFF
        val = self.gpr[idx]
        return val & ((1 << (size * 8)) - 1)

    def write_reg(self, idx: int, size: int, value: int) -> None:
        if idx >= U.REG_AH_BASE:
            base = idx - U.REG_AH_BASE
            self.gpr[base] = (self.gpr[base] & ~0xFF00) | ((value & 0xFF) << 8)
            return
        if size == 8:
            self.gpr[idx] = value & MASK64
        elif size == 4:
            self.gpr[idx] = value & 0xFFFFFFFF  # 32-bit writes zero-extend
        else:
            mask = (1 << (size * 8)) - 1
            self.gpr[idx] = (self.gpr[idx] & ~mask) | (value & mask)

    # -- translation / memory ------------------------------------------
    def translate(self, gva: int, write: bool) -> int:
        """4-level long-mode walk (reference kvm_backend.cc:1937-1998)."""
        gva &= MASK64
        top = gva >> 47
        if top != 0 and top != 0x1FFFF:
            raise MemFault(gva, write)
        table = self.cr3 & PHYS_MASK
        for shift, large_mask in ((39, None), (30, 0x000F_FFFF_C000_0000),
                                  (21, 0x000F_FFFF_FFE0_0000), (12, None)):
            index = (gva >> shift) & 0x1FF
            entry = self.mem.phys_read_u64(table + index * 8)
            if not entry & PTE_P:
                raise MemFault(gva, write)
            if write and not entry & PTE_W:
                raise MemFault(gva, write)
            if large_mask is not None and entry & PTE_PS:
                return (entry & large_mask) | (gva & ((1 << shift) - 1))
            if shift == 12:
                return (entry & PHYS_MASK) | (gva & 0xFFF)
            table = entry & PHYS_MASK
        raise AssertionError("unreachable")

    def virt_read(self, gva: int, size: int) -> bytes:
        out = bytearray()
        pos = gva
        while pos < gva + size:
            off = pos & (PAGE_SIZE - 1)
            chunk = min(gva + size - pos, PAGE_SIZE - off)
            gpa = self.translate(pos, write=False)
            out += self.mem.phys_read(gpa, chunk)
            pos += chunk
        if self.access_log is not None and size > 0:
            self.access_log.append(("mr", gva, size))
        return bytes(out)

    def virt_write(self, gva: int, data: bytes, enforce: bool = True) -> None:
        pos = 0
        while pos < len(data):
            addr = gva + pos
            off = addr & (PAGE_SIZE - 1)
            chunk = min(len(data) - pos, PAGE_SIZE - off)
            gpa = self.translate(addr, write=enforce)
            self.mem.phys_write(gpa, data[pos : pos + chunk])
            pos += chunk
        if self.access_log is not None and data:
            self.access_log.append(("mw", gva, len(data)))

    def read_u(self, gva: int, size: int) -> int:
        return int.from_bytes(self.virt_read(gva, size), "little")

    def write_u(self, gva: int, size: int, value: int) -> None:
        self.virt_write(gva, (value & ((1 << (size * 8)) - 1)).to_bytes(size, "little"))

    # -- exception-delivery ctx surface (cpu/interrupts.py) --------------
    # IDTR/TR come from the snapshot: lidt/ltr are not emulated, so the
    # tables a snapshot was taken with stay authoritative for its lifetime
    # (true of the reference too — bochs loads them once from CpuState_t).
    @property
    def rsp(self) -> int:
        return self.gpr[4]

    @rsp.setter
    def rsp(self, value: int) -> None:
        self.gpr[4] = value & MASK64

    @property
    def idt_base(self) -> int:
        return self.snapshot.idtr.base

    @property
    def idt_limit(self) -> int:
        return self.snapshot.idtr.limit

    @property
    def tss_base(self) -> int:
        return self.snapshot.tr.base

    def read_virt(self, gva: int, size: int) -> bytes:
        return self.virt_read(gva, size)

    def read_u64(self, gva: int) -> int:
        return self.read_u(gva, 8)

    def write_u64(self, gva: int, value: int) -> None:
        self.write_u(gva, 8, value)

    def set_cr2(self, value: int) -> None:
        self.cr2 = value & MASK64

    # -- x87 state observation (lane writeback / fxsave) -----------------
    def fp_state_list(self) -> List[int]:
        return list(self.fpst)

    def fpsw_packed(self) -> int:
        return (self.fpsw & ~0x3800) | ((self.fptop & 7) << 11)

    def deliver_exception(self, vector: int, error_code: int = 0,
                          cr2=None) -> None:
        """Vector a fault through the guest IDT (cpu/interrupts.py)."""
        from wtf_tpu.cpu.interrupts import deliver_exception

        deliver_exception(self, vector, error_code, cr2)

    # -- flags ----------------------------------------------------------
    def get_flag(self, bit: int) -> bool:
        return bool(self.rflags & bit)

    def set_flags(self, **kw) -> None:
        table = {
            "cf": RFLAGS_CF, "pf": RFLAGS_PF, "af": RFLAGS_AF,
            "zf": RFLAGS_ZF, "sf": RFLAGS_SF, "of": RFLAGS_OF,
            "df": RFLAGS_DF,
        }
        for name, val in kw.items():
            bit = table[name]
            if val:
                self.rflags |= bit
            else:
                self.rflags &= ~bit

    def _flags_logic(self, result: int, bits: int) -> None:
        mask = (1 << bits) - 1
        r = result & mask
        self.set_flags(cf=False, of=False, af=False,
                       zf=r == 0, sf=bool(r >> (bits - 1)), pf=_parity(r))

    def _flags_add(self, a: int, b: int, r: int, bits: int, carry_in: int = 0) -> None:
        mask = (1 << bits) - 1
        full = (a & mask) + (b & mask) + carry_in
        rm = r & mask
        self.set_flags(
            cf=full > mask,
            af=bool((a ^ b ^ r) & 0x10),
            zf=rm == 0,
            sf=bool(rm >> (bits - 1)),
            of=bool(((a ^ r) & (b ^ r)) >> (bits - 1) & 1),
            pf=_parity(rm),
        )

    def _flags_sub(self, a: int, b: int, r: int, bits: int, borrow_in: int = 0) -> None:
        mask = (1 << bits) - 1
        self.set_flags(
            cf=(a & mask) < (b & mask) + borrow_in,
            af=bool((a ^ b ^ r) & 0x10),
            zf=(r & mask) == 0,
            sf=bool((r & mask) >> (bits - 1)),
            of=bool(((a ^ b) & (a ^ r)) >> (bits - 1) & 1),
            pf=_parity(r),
        )

    def eval_cond(self, cc: int) -> bool:
        f = self.rflags
        cf, zf = bool(f & RFLAGS_CF), bool(f & RFLAGS_ZF)
        sf, of = bool(f & RFLAGS_SF), bool(f & RFLAGS_OF)
        pf = bool(f & RFLAGS_PF)
        table = [
            of, not of, cf, not cf, zf, not zf, cf or zf, not (cf or zf),
            sf, not sf, pf, not pf, sf != of, sf == of,
            zf or (sf != of), not zf and sf == of,
        ]
        if cc == 16:  # jrcxz
            return self.gpr[1] == 0  # rcx
        if cc == 17:  # jecxz (67h form)
            return self.gpr[1] & 0xFFFFFFFF == 0
        return table[cc]

    # -- addressing -----------------------------------------------------
    def effective_addr(self, uop: U.Uop, next_rip: int) -> int:
        addr = uop.disp
        if uop.base_reg == U.REG_RIP:
            addr += next_rip
        elif uop.base_reg != U.REG_NONE:
            addr += self.gpr[uop.base_reg]
        if uop.idx_reg != U.REG_NONE:
            addr += self.gpr[uop.idx_reg] * uop.scale
        if uop.a32:
            # 67h truncates the un-segmented EA to 32 bits (SDM 64-bit
            # address-size override), BEFORE the segment base applies
            addr &= 0xFFFF_FFFF
        if uop.seg == U.SEG_FS:
            addr += self.fs_base
        elif uop.seg == U.SEG_GS:
            addr += self.gs_base
        return addr & MASK64

    # -- fetch/decode/execute -------------------------------------------
    def restore(self, state: Optional[CpuState] = None) -> None:
        """Per-testcase restore: flush uops decoded from pages this run
        dirtied (their bytes roll back with the overlay), reset memory, and
        reload registers.  The cheap path of the reference's
        `Backend_t::Restore` (SURVEY.md §5.4)."""
        for pfn in self.mem.dirty_pfns():
            for rip in self.decode_pages.pop(pfn, ()):
                self.decode_cache.pop(rip, None)
        self.mem.reset()
        self.load_state(state or self.snapshot)

    def fetch_decode(self) -> U.Uop:
        cached = self.decode_cache.get(self.rip)
        window = b""
        if cached is None:
            window = self._fetch_window()
            uop = decode(window, self.rip)
            self.decode_cache[self.rip] = uop
            try:
                first = self.translate(self.rip, write=False) >> PAGE_SHIFT
                last = self.translate(self.rip + max(uop.length - 1, 0),
                                      write=False) >> PAGE_SHIFT
                for pfn in {first, last}:
                    self.decode_pages.setdefault(pfn, []).append(self.rip)
            except MemFault:
                pass
            cached = uop
        else:
            # self-modifying-code guard: revalidate raw bytes if either page
            # the instruction spans is dirty (mirrors the device SMC check)
            dirty = False
            try:
                span = max(len(cached.raw) - 1, 0)
                for off in {0, span}:
                    pfn = self.translate(self.rip + off, write=False) >> PAGE_SHIFT
                    dirty |= pfn in self.mem.overlay
            except MemFault:
                pass
            if dirty:
                window = self._fetch_window()
                if not window.startswith(cached.raw):
                    cached = decode(window, self.rip)
                    self.decode_cache[self.rip] = cached
        return cached

    def _fetch_window(self) -> bytes:
        try:
            return self.virt_read(self.rip, 15)
        except MemFault:
            # near end of mapped region: fetch what we can, byte at a time
            out = bytearray()
            for i in range(15):
                try:
                    out += self.virt_read(self.rip + i, 1)
                except MemFault:
                    break
            if not out:
                raise
            return bytes(out)

    def step(self) -> None:
        """Execute exactly one instruction (one uop)."""
        # fetches are not data accesses: keep them out of the access log
        # (bochs' lin_access hook fires for data, not fetch)
        log, self.access_log = self.access_log, None
        try:
            uop = self.fetch_decode()
        finally:
            self.access_log = log
        self.execute(uop)
        self.icount += 1

    def execute(self, uop: U.Uop) -> None:  # noqa: C901 - one big dispatcher
        opc = uop.opc
        next_rip = (self.rip + uop.length) & MASK64
        opsize = uop.opsize
        bits = opsize * 8
        mask = (1 << bits) - 1

        if opc == U.OPC_INVALID:
            raise UnsupportedInsn(self.rip, uop.raw)

        if opc in (U.OPC_NOP, U.OPC_FENCE):
            self.rip = next_rip
            return

        # ---- generic source value -------------------------------------
        ea = None
        if uop.mem_operand() or opc == U.OPC_LEA:
            ea = self.effective_addr(uop, next_rip)

        def load_src() -> int:
            srcsize = uop.srcsize or opsize
            if uop.src_kind == U.K_REG:
                val = self.read_reg(uop.src_reg, srcsize)
            elif uop.src_kind == U.K_MEM:
                val = self.read_u(ea, srcsize)
            elif uop.src_kind == U.K_IMM:
                return uop.imm & mask
            else:
                return 0
            if uop.sext == 1:
                val = _sx(val, srcsize * 8) & mask
            else:
                val &= mask
            return val

        def load_dst() -> int:
            if uop.dst_kind == U.K_REG:
                return self.read_reg(uop.dst_reg, opsize)
            if uop.dst_kind == U.K_MEM:
                return self.read_u(ea, opsize)
            return 0

        def store_dst(value: int) -> None:
            if uop.dst_kind == U.K_REG:
                self.write_reg(uop.dst_reg, opsize, value)
            elif uop.dst_kind == U.K_MEM:
                self.write_u(ea, opsize, value)

        # ---- dispatch ---------------------------------------------------
        if opc == U.OPC_MOV:
            store_dst(load_src())
        elif opc == U.OPC_LEA:
            self.write_reg(uop.dst_reg, opsize, ea)
        elif opc == U.OPC_ALU:
            self._exec_alu(uop, load_src(), load_dst, store_dst, bits)
        elif opc == U.OPC_SHIFT:
            self._exec_shift(uop, load_src, load_dst, store_dst, bits)
        elif opc == U.OPC_UNARY:
            self._exec_unary(uop, load_dst, store_dst, bits)
        elif opc == U.OPC_MUL:
            self._exec_mul(uop, load_src(), bits)
        elif opc == U.OPC_DIV:
            self._exec_div(uop, load_src(), bits)
        elif opc == U.OPC_PUSH:
            # store before committing rsp: a faulting push must leave rsp
            # untouched so the #PF-deliver-and-retry path (interrupts.py)
            # re-executes it from pristine state, like the device path
            # which gates all commits on ~page_fault
            val = load_src()
            new_rsp = (self.gpr[4] - opsize) & MASK64
            self.write_u(new_rsp, opsize, val)
            self.gpr[4] = new_rsp
        elif opc == U.OPC_POP:
            val = self.read_u(self.gpr[4], opsize)
            self.gpr[4] = (self.gpr[4] + opsize) & MASK64
            store_dst(val)
        elif opc == U.OPC_CALL:
            target = (next_rip + uop.imm) & MASK64 if uop.src_kind == U.K_IMM \
                else load_src()
            new_rsp = (self.gpr[4] - 8) & MASK64
            self.write_u(new_rsp, 8, next_rip)  # may fault: commit after
            self.gpr[4] = new_rsp
            self.rip = target
            return
        elif opc == U.OPC_RET:
            self.rip = self.read_u(self.gpr[4], 8)
            self.gpr[4] = (self.gpr[4] + 8 + uop.imm) & MASK64
            return
        elif opc == U.OPC_IRET:
            if uop.sub == 1:
                # retf [imm16]: pop rip + cs; an inter-privilege far
                # return also pops SS:RSP (64-bit far forms, SDM RET)
                rsp = self.gpr[4]
                new_rip = self.read_u(rsp, 8)
                new_cs = self.read_u(rsp + 8, 8) & 0xFFFF
                rsp = (rsp + 16 + uop.imm) & MASK64
                if (new_cs & 3) != (self.cs_sel & 3):
                    self.gpr[4] = rsp  # frame continues at adjusted rsp
                    new_rsp = self.read_u(self.gpr[4], 8)
                    self.ss_sel = self.read_u(self.gpr[4] + 8, 8) & 0xFFFF
                    # SDM RET-far: imm16 releases parameter bytes from the
                    # NEW stack as well after popping SS:RSP
                    rsp = (new_rsp + uop.imm) & MASK64
                self.rip = new_rip
                self.cs_sel = new_cs
                self.gpr[4] = rsp
                return
            # iretq: pop rip, cs, rflags, rsp, ss (five qwords).  The
            # selectors track CPL for exception delivery (cpu/interrupts.py)
            # but are not validated against the GDT — flat memory model,
            # protection lives in the page tables.  Reference gets the full
            # check from bochs/KVM.
            if uop.opsize != 8:
                raise UnsupportedInsn(self.rip, uop.raw)  # iretd (no REX.W)
            rsp = self.gpr[4]
            new_rip = self.read_u(rsp, 8)
            new_cs = self.read_u(rsp + 8, 8)
            new_rflags = self.read_u(rsp + 16, 8)
            new_rsp = self.read_u(rsp + 24, 8)
            new_ss = self.read_u(rsp + 32, 8)
            self.rip = new_rip
            self.rflags = (new_rflags | 0x2) & U.RF_WRITABLE
            self.gpr[4] = new_rsp & MASK64
            self.cs_sel = new_cs & 0xFFFF
            self.ss_sel = new_ss & 0xFFFF
            return
        elif opc == U.OPC_JMP:
            self.rip = (next_rip + uop.imm) & MASK64 if uop.src_kind == U.K_IMM \
                else load_src()
            return
        elif opc == U.OPC_JCC:
            if self.eval_cond(uop.cond):
                self.rip = (next_rip + uop.imm) & MASK64
                return
        elif opc == U.OPC_SETCC:
            store_dst(1 if self.eval_cond(uop.cond) else 0)
        elif opc == U.OPC_CMOVCC:
            value = load_src() if self.eval_cond(uop.cond) else load_dst()
            store_dst(value)  # always writes (64-bit mode zero-extension)
        elif opc == U.OPC_STRING:
            if not self._exec_string(uop, opsize):
                return  # rip unchanged: more REP iterations pending
        elif opc == U.OPC_XCHG:
            a = load_dst()
            b = load_src()
            store_dst(b)
            if uop.src_kind == U.K_REG:
                self.write_reg(uop.src_reg, opsize, a)
        elif opc == U.OPC_CONVERT:
            self._exec_convert(uop, bits)
        elif opc == U.OPC_BT:
            self._exec_bt(uop, ea, bits)
        elif opc == U.OPC_BITSCAN:
            self._exec_bitscan(uop, load_src(), bits)
        elif opc == U.OPC_PUSHF:
            new_rsp = (self.gpr[4] - 8) & MASK64
            self.write_u(new_rsp, 8, self.rflags | 0x2)  # may fault
            self.gpr[4] = new_rsp
        elif opc == U.OPC_POPF:
            val = self.read_u(self.gpr[4], 8)
            self.gpr[4] = (self.gpr[4] + 8) & MASK64
            settable = 0xFD5 | RFLAGS_DF | 0x100 | 0x200 | (1 << 18)
            self.rflags = (val & settable) | 0x2
        elif opc == U.OPC_FLAGOP:
            self._exec_flagop(uop)
        elif opc == U.OPC_BSWAP:
            val = self.read_reg(uop.dst_reg, opsize)
            self.write_reg(uop.dst_reg, opsize,
                           int.from_bytes(val.to_bytes(opsize, "little"), "big"))
        elif opc == U.OPC_CMPXCHG:
            dst = load_dst()
            acc = self.read_reg(0, opsize)
            self._flags_sub(acc, dst, (acc - dst) & mask, bits)
            if acc == dst:
                store_dst(self.read_reg(uop.src_reg, opsize))
            else:
                # Intel: on failure the destination is still written back
                store_dst(dst)
                self.write_reg(0, opsize, dst)
        elif opc == U.OPC_XADD:
            dst = load_dst()
            src = self.read_reg(uop.src_reg, opsize)
            r = (dst + src) & mask
            self._flags_add(dst, src, r, bits)
            self.write_reg(uop.src_reg, opsize, dst)
            store_dst(r)
        elif opc == U.OPC_LEAVE:
            if uop.sub == 1:  # enter size, 0: push rbp; rbp = rsp; alloc
                new_rsp = (self.gpr[4] - 8) & MASK64
                self.write_u(new_rsp, 8, self.gpr[5])  # may fault: rsp last
                self.gpr[5] = new_rsp
                self.gpr[4] = (new_rsp - uop.imm) & MASK64
            else:
                self.gpr[4] = self.gpr[5]
                self.gpr[5] = self.read_u(self.gpr[4], 8)
                self.gpr[4] = (self.gpr[4] + 8) & MASK64
        elif opc == U.OPC_RDTSC:
            tsc = (self.tsc + self.icount) & MASK64
            self.write_reg(0, 8, tsc & 0xFFFFFFFF)
            self.write_reg(2, 8, tsc >> 32)
        elif opc == U.OPC_PEXT:
            # BMI1/BMI2 scalar bit ops (VEX-encoded).  Third operand
            # (VEX.vvvv) rides in uop.cond per the decoder's convention.
            src = load_src()                      # the r/m operand
            third = self.read_reg(uop.cond, opsize)
            sub = uop.sub
            if sub == U.BMI_ANDN:                 # dst = ~vvvv & r/m
                res = (~third & src) & mask
                self.set_flags(sf=bool(res >> (bits - 1)), zf=res == 0,
                               cf=False, of=False)
            elif sub == U.BMI_BZHI:               # zero bits >= vvvv[7:0]
                n = third & 0xFF
                res = src & ((1 << n) - 1) if n < bits else src
                self.set_flags(cf=n > bits - 1, zf=res == 0,
                               sf=bool(res >> (bits - 1)), of=False)
            elif sub == U.BMI_BEXTR:              # field extract by vvvv
                start = third & 0xFF
                ln = (third >> 8) & 0xFF
                res = (src >> start) & ((1 << ln) - 1) if start < bits else 0
                res &= mask
                self.set_flags(zf=res == 0, cf=False, of=False)
            elif sub in (U.BMI_SHLX, U.BMI_SHRX, U.BMI_SARX):  # no flags
                cnt = third & (63 if opsize == 8 else 31)
                if sub == U.BMI_SHLX:
                    res = (src << cnt) & mask
                elif sub == U.BMI_SHRX:
                    res = src >> cnt
                else:
                    res = (_sx(src, bits) >> cnt) & mask
            elif sub == U.BMI_PDEP:               # deposit vvvv into r/m mask
                res, k = 0, 0
                for i in range(bits):
                    if (src >> i) & 1:
                        res |= ((third >> k) & 1) << i
                        k += 1
            elif sub == U.BMI_PEXT_:              # extract r/m-mask bits of vvvv
                res, k = 0, 0
                for i in range(bits):
                    if (src >> i) & 1:
                        res |= ((third >> i) & 1) << k
                        k += 1
            elif sub == U.BMI_BLSR:               # clear lowest set bit
                res = src & (src - 1) & mask
                self.set_flags(cf=src == 0, zf=res == 0,
                               sf=bool(res >> (bits - 1)), of=False)
            elif sub == U.BMI_BLSMSK:             # mask up to lowest set bit
                res = (src ^ (src - 1)) & mask
                self.set_flags(cf=src == 0, zf=res == 0,
                               sf=bool(res >> (bits - 1)), of=False)
            elif sub == U.BMI_BLSI:               # isolate lowest set bit
                res = src & (-src & mask) & mask
                self.set_flags(cf=src != 0, zf=res == 0,
                               sf=bool(res >> (bits - 1)), of=False)
            elif sub == U.BMI_RORX:               # rotate right, no flags
                n = uop.imm & (63 if opsize == 8 else 31)
                res = ((src >> n) | (src << (bits - n))) & mask if n else src
            else:
                raise UnsupportedInsn(self.rip, uop.raw)
            self.write_reg(uop.dst_reg, opsize, res)
        elif opc == U.OPC_MSR:
            # rdmsr/wrmsr over the MSR-backed fields the snapshot carries
            # (reference: bochs/KVM MSR state, kvm_backend.cc LoadMsrs)
            msr = self.gpr[1] & 0xFFFFFFFF
            attr = MSR_ATTR.get(msr)
            if attr is None:
                raise UnsupportedInsn(self.rip, uop.raw)
            if uop.sub == 1:  # wrmsr: edx:eax
                value = ((self.gpr[2] & 0xFFFFFFFF) << 32) \
                    | (self.gpr[0] & 0xFFFFFFFF)
                if attr == "tsc":  # keep rdtsc = tsc_base + icount coherent
                    value = (value - self.icount) & MASK64
                setattr(self, attr, value)
            else:             # rdmsr -> edx:eax (32-bit zero-extending)
                value = getattr(self, attr)
                if attr == "tsc":
                    value = (value + self.icount) & MASK64
                self.write_reg(0, 8, value & 0xFFFFFFFF)
                self.write_reg(2, 8, value >> 32)
        elif opc == U.OPC_RDRAND:
            self.rdrand_state = splitmix64(self.rdrand_state)
            store_dst(self.rdrand_state & mask)
            self.set_flags(cf=True, of=False, af=False, zf=False, sf=False, pf=False)
        elif opc == U.OPC_CPUID:
            eax, ebx, ecx, edx = cpuid(self.gpr[0] & 0xFFFFFFFF,
                                       self.gpr[1] & 0xFFFFFFFF)
            self.write_reg(0, 4, eax)
            self.write_reg(3, 4, ebx)
            self.write_reg(1, 4, ecx)
            self.write_reg(2, 4, edx)
        elif opc == U.OPC_XGETBV:
            self.write_reg(0, 4, 0x7)  # x87+SSE+AVX state enabled
            self.write_reg(2, 4, 0)
        elif opc == U.OPC_VZEROALL:
            # sub 0: vzeroall — the full vector registers; sub 1:
            # vzeroupper — only the upper YMM halves
            for i in range(16):
                if uop.sub == 0:
                    self.xmm[i] = [0, 0]
                self.ymmh[i] = [0, 0]
        elif opc == U.OPC_SYSCALL:
            if uop.sub == 0:
                self.gpr[1] = next_rip                       # rcx
                self.gpr[11] = self.rflags & ~0x10000        # r11 (RF clear)
                self.rflags = (self.rflags & ~(self.sfmask | 0x100)) | 0x2
                self.rip = self.lstar
                # CS/SS from IA32_STAR[47:32] (SDM: SYSCALL loads CPL-0
                # selectors; tracked for exception delivery)
                self.cs_sel = (self.star >> 32) & 0xFFFC
                self.ss_sel = ((self.star >> 32) & 0xFFFC) + 8
                return
            else:  # sysret
                self.rip = self.gpr[1]
                self.rflags = (self.gpr[11] & U.RF_WRITABLE) | 0x2
                # CS/SS from IA32_STAR[63:48] (SYSRET 64-bit forms)
                self.cs_sel = (((self.star >> 48) & 0xFFFF) + 16) | 3
                self.ss_sel = (((self.star >> 48) & 0xFFFF) + 8) | 3
                return
        elif opc == U.OPC_RDGSBASE:
            if uop.sub == 4:  # swapgs
                self.gs_base, self.kernel_gs_base = \
                    self.kernel_gs_base, self.gs_base
            elif uop.sub == 0:  # rdfsbase
                self.write_reg(uop.dst_reg, uop.opsize, self.fs_base)
            elif uop.sub == 1:  # rdgsbase
                self.write_reg(uop.dst_reg, uop.opsize, self.gs_base)
            elif uop.sub in (2, 3):  # wrfsbase/wrgsbase (r32 zero-extends)
                value = self.read_reg(uop.dst_reg, uop.opsize)
                if (value >> 47) not in (0, 0x1FFFF):
                    # hardware #GPs on a non-canonical base; MemFault on
                    # the value routes through deliver_page_fault's
                    # non-canonical -> #GP(0) path (cpu/interrupts.py)
                    raise MemFault(value, write=False)
                if uop.sub == 2:
                    self.fs_base = value
                else:
                    self.gs_base = value
            else:
                raise UnsupportedInsn(self.rip, uop.raw)
        elif opc == U.OPC_MOVCR:
            self._exec_movcr(uop)
        elif opc == U.OPC_SSEMOV:
            self._exec_ssemov(uop, ea)
        elif opc == U.OPC_SSEALU:
            self._exec_ssealu(uop, ea)
        elif opc == U.OPC_SSEFP:
            self._exec_ssefp(uop, ea)
        elif opc == U.OPC_X87:
            self._exec_x87(uop, ea)
        elif opc in (U.OPC_INT, U.OPC_HLT, U.OPC_INT1):
            raise GuestCrash(self.rip, uop)
        else:
            raise UnsupportedInsn(self.rip, uop.raw)

        self.rip = next_rip

    # -- op-class helpers ----------------------------------------------
    def _exec_alu(self, uop, b, load_dst, store_dst, bits) -> None:
        mask = (1 << bits) - 1
        a = load_dst()
        sub = uop.sub
        if sub == U.ALU_ADD:
            r = (a + b) & mask
            self._flags_add(a, b, r, bits)
            store_dst(r)
        elif sub == U.ALU_ADC:
            c = int(self.get_flag(RFLAGS_CF))
            r = (a + b + c) & mask
            self._flags_add(a, b, r, bits, carry_in=c)
            store_dst(r)
        elif sub == U.ALU_SUB:
            r = (a - b) & mask
            self._flags_sub(a, b, r, bits)
            store_dst(r)
        elif sub == U.ALU_SBB:
            c = int(self.get_flag(RFLAGS_CF))
            r = (a - b - c) & mask
            self._flags_sub(a, b, r, bits, borrow_in=c)
            store_dst(r)
        elif sub == U.ALU_CMP:
            r = (a - b) & mask
            self._flags_sub(a, b, r, bits)
        elif sub == U.ALU_AND:
            r = a & b
            self._flags_logic(r, bits)
            store_dst(r)
        elif sub == U.ALU_OR:
            r = a | b
            self._flags_logic(r, bits)
            store_dst(r)
        elif sub == U.ALU_XOR:
            r = a ^ b
            self._flags_logic(r, bits)
            store_dst(r)
        elif sub == U.ALU_TEST:
            self._flags_logic(a & b, bits)

    def _exec_shift(self, uop, load_src, load_dst, store_dst, bits) -> None:
        mask = (1 << bits) - 1
        a = load_dst()
        sub = uop.sub
        if sub in (U.SH_SHLD, U.SH_SHRD):
            filler = self.read_reg(uop.src_reg, uop.opsize)
            count = (uop.imm if uop.sext == 3 else self.read_reg(1, 1)) \
                & (0x3F if bits == 64 else 0x1F)
            if count == 0:
                return
            if count > bits:
                count %= bits  # 16-bit forms w/ count>16: arch-undefined
            if sub == U.SH_SHLD:
                wide = (a << bits) | filler
                r = (wide >> (bits - count)) & mask
                cf = bool((a >> (bits - count)) & 1)
            else:
                wide = (filler << bits) | a
                r = (wide >> count) & mask
                cf = bool((a >> (count - 1)) & 1)
            self.set_flags(cf=cf, zf=r == 0, sf=bool(r >> (bits - 1)),
                           pf=_parity(r),
                           of=bool((r ^ a) >> (bits - 1)) if count == 1 else False)
            store_dst(r)
            return

        count_raw = load_src()
        count = count_raw & (0x3F if bits == 64 else 0x1F)
        if sub in (U.SH_RCL, U.SH_RCR):
            count = count % (bits + 1)
        if count == 0:
            return
        cf_in = int(self.get_flag(RFLAGS_CF))
        of = self.get_flag(RFLAGS_OF)

        if sub in (U.SH_SHL, U.SH_SAL):
            r = (a << count) & mask
            cf = bool((a >> (bits - count)) & 1) if count <= bits else False
            of = (bool(r >> (bits - 1)) != cf) if count == 1 else of
        elif sub == U.SH_SHR:
            r = (a >> count) & mask
            cf = bool((a >> (count - 1)) & 1) if count <= bits else False
            of = bool(a >> (bits - 1)) if count == 1 else of
        elif sub == U.SH_SAR:
            sa = _sx(a, bits)
            r = (sa >> count) & mask
            cf = bool((sa >> (count - 1)) & 1)
            of = False if count == 1 else of
        elif sub == U.SH_ROL:
            c = count % bits
            r = ((a << c) | (a >> (bits - c))) & mask if c else a
            cf = bool(r & 1)
            of = (bool(r >> (bits - 1)) != cf) if count == 1 else of
        elif sub == U.SH_ROR:
            c = count % bits
            r = ((a >> c) | (a << (bits - c))) & mask if c else a
            cf = bool(r >> (bits - 1))
            of = (bool(r >> (bits - 1)) != bool((r >> (bits - 2)) & 1)) \
                if count == 1 else of
        elif sub == U.SH_RCL:
            wide = (cf_in << bits) | a
            c = count
            full = bits + 1
            r_wide = ((wide << c) | (wide >> (full - c))) & ((1 << full) - 1)
            r = r_wide & mask
            cf = bool(r_wide >> bits)
            of = (bool(r >> (bits - 1)) != cf) if count == 1 else of
        else:  # RCR
            wide = (cf_in << bits) | a
            c = count
            full = bits + 1
            r_wide = ((wide >> c) | (wide << (full - c))) & ((1 << full) - 1)
            r = r_wide & mask
            cf = bool(r_wide >> bits)
            of = (bool(a >> (bits - 1)) != cf_in) if count == 1 else of

        if sub in (U.SH_RCL, U.SH_RCR):
            self.set_flags(cf=cf, of=of)
        else:
            self.set_flags(cf=cf, of=of, zf=(r & mask) == 0,
                           sf=bool((r & mask) >> (bits - 1)), pf=_parity(r))
        store_dst(r)

    def _exec_unary(self, uop, load_dst, store_dst, bits) -> None:
        mask = (1 << bits) - 1
        a = load_dst()
        sub = uop.sub
        if sub == U.UN_NOT:
            store_dst(~a & mask)
            return
        cf = self.get_flag(RFLAGS_CF)
        if sub == U.UN_INC:
            r = (a + 1) & mask
            self._flags_add(a, 1, r, bits)
            self.set_flags(cf=cf)  # inc/dec preserve CF
        elif sub == U.UN_DEC:
            r = (a - 1) & mask
            self._flags_sub(a, 1, r, bits)
            self.set_flags(cf=cf)
        else:  # NEG
            r = (-a) & mask
            self._flags_sub(0, a, r, bits)
            self.set_flags(cf=a != 0)
        store_dst(r)

    def _exec_mul(self, uop, b, bits) -> None:
        mask = (1 << bits) - 1
        if uop.sub == U.MUL_2OP:
            a = self.read_reg(uop.dst_reg, uop.opsize)
            if uop.sext == 2:  # 3-operand: r = r/m * imm
                a = b
                b = uop.imm & mask
            prod = _sx(a, bits) * _sx(b, bits)
            r = prod & mask
            overflow = prod != _sx(r, bits)
            self.write_reg(uop.dst_reg, uop.opsize, r)
            self.set_flags(cf=overflow, of=overflow, zf=False,
                           sf=bool(r >> (bits - 1)), pf=_parity(r), af=False)
            return
        a = self.read_reg(0, uop.opsize)
        if uop.sub == U.MUL_WIDE_U:
            prod = a * b
            overflow = prod >> bits != 0
        else:
            prod = _sx(a, bits) * _sx(b, bits)
            overflow = prod != _sx(prod & mask, bits)
            prod &= (1 << (bits * 2)) - 1
        lo, hi = prod & mask, (prod >> bits) & mask
        if uop.opsize == 1:
            self.write_reg(0, 2, prod & 0xFFFF)  # ax = al*src
        else:
            self.write_reg(0, uop.opsize, lo)
            self.write_reg(2, uop.opsize, hi)   # rdx
        self.set_flags(cf=overflow, of=overflow)

    def _exec_div(self, uop, b, bits) -> None:
        mask = (1 << bits) - 1
        if b == 0:
            raise DivideError()
        if uop.opsize == 1:
            dividend = self.read_reg(0, 2)  # ax
        else:
            dividend = (self.read_reg(2, uop.opsize) << bits) | \
                self.read_reg(0, uop.opsize)
        if uop.sub == U.DIV_U:
            q, r = divmod(dividend, b)
            if q > mask:
                raise DivideError()
        else:
            sd = _sx(dividend, bits * 2)
            sb = _sx(b, bits)
            q = int(sd / sb)  # truncation toward zero
            r = sd - q * sb
            if q > (mask >> 1) or q < -(mask >> 1) - 1:
                raise DivideError()
        if uop.opsize == 1:
            self.write_reg(0, 1, q & 0xFF)
            self.write_reg(U.REG_AH_BASE, 1, r & 0xFF)  # ah
        else:
            self.write_reg(0, uop.opsize, q & mask)
            self.write_reg(2, uop.opsize, r & mask)

    def _exec_string(self, uop, opsize) -> bool:
        if uop.a32:
            # 67h string forms address via 32-bit rsi/rdi/rcx — not
            # modeled; refuse rather than run with 64-bit registers
            raise UnsupportedInsn(self.rip, uop.raw)
        """One string-op iteration; returns True when rip should advance."""
        if uop.rep != U.REP_NONE and self.gpr[1] == 0:  # rcx
            return True
        delta = -opsize if self.get_flag(RFLAGS_DF) else opsize
        sub = uop.sub
        rsi, rdi = self.gpr[6], self.gpr[7]
        if sub == U.STR_MOVS:
            self.virt_write(rdi, self.virt_read(rsi, opsize))
            self.gpr[6] = (rsi + delta) & MASK64
            self.gpr[7] = (rdi + delta) & MASK64
        elif sub == U.STR_STOS:
            self.write_u(rdi, opsize, self.read_reg(0, opsize))
            self.gpr[7] = (rdi + delta) & MASK64
        elif sub == U.STR_LODS:
            self.write_reg(0, opsize, self.read_u(rsi, opsize))
            self.gpr[6] = (rsi + delta) & MASK64
        elif sub == U.STR_SCAS:
            a = self.read_reg(0, opsize)
            b = self.read_u(rdi, opsize)
            self._flags_sub(a, b, (a - b) & ((1 << (opsize * 8)) - 1), opsize * 8)
            self.gpr[7] = (rdi + delta) & MASK64
        elif sub == U.STR_CMPS:
            a = self.read_u(rsi, opsize)
            b = self.read_u(rdi, opsize)
            self._flags_sub(a, b, (a - b) & ((1 << (opsize * 8)) - 1), opsize * 8)
            self.gpr[6] = (rsi + delta) & MASK64
            self.gpr[7] = (rdi + delta) & MASK64

        if uop.rep == U.REP_NONE:
            return True
        self.gpr[1] = (self.gpr[1] - 1) & MASK64
        if self.gpr[1] == 0:
            return True
        if sub in (U.STR_SCAS, U.STR_CMPS):
            zf = self.get_flag(RFLAGS_ZF)
            if uop.rep == U.REP_REP and not zf:
                return True
            if uop.rep == U.REP_REPNE and zf:
                return True
        return False

    def _exec_convert(self, uop, bits) -> None:
        if uop.sub == 0:  # cbw/cwde/cdqe: widen half-size rax into rax
            half = bits // 2
            val = _sx(self.read_reg(0, uop.opsize) & ((1 << half) - 1), half)
            self.write_reg(0, uop.opsize, val & ((1 << bits) - 1))
        else:  # cwd/cdq/cqo: rdx = sign of rax
            sign = (self.read_reg(0, uop.opsize) >> (bits - 1)) & 1
            self.write_reg(2, uop.opsize, ((1 << bits) - 1) if sign else 0)

    def _exec_bt(self, uop, ea, bits) -> None:
        if uop.src_kind == U.K_IMM:
            offset = uop.imm & (bits - 1)
            bit_base_adjust = 0
        else:
            # register bit index addresses a bit *string* for memory forms:
            # EA moves by opsize for every `bits` of signed offset
            raw = self.read_reg(uop.src_reg, uop.opsize)
            signed = _sx(raw, bits)
            offset = signed & (bits - 1)
            bit_base_adjust = (signed - offset) // bits * uop.opsize
        if uop.dst_kind == U.K_MEM:
            addr = (ea + bit_base_adjust) & MASK64
            val = self.read_u(addr, uop.opsize)
        else:
            val = self.read_reg(uop.dst_reg, uop.opsize)
        bit = (val >> offset) & 1
        self.set_flags(cf=bool(bit))
        sub = uop.sub
        if sub == U.BT_BT:
            return
        if sub == U.BT_BTS:
            val |= 1 << offset
        elif sub == U.BT_BTR:
            val &= ~(1 << offset)
        else:
            val ^= 1 << offset
        if uop.dst_kind == U.K_MEM:
            self.write_u(addr, uop.opsize, val)
        else:
            self.write_reg(uop.dst_reg, uop.opsize, val)

    def _exec_bitscan(self, uop, src, bits) -> None:
        sub = uop.sub
        if sub == U.BS_POPCNT:
            r = bin(src).count("1")
            self.write_reg(uop.dst_reg, uop.opsize, r)
            self.set_flags(cf=False, of=False, af=False, sf=False,
                           pf=False, zf=src == 0)
            return
        if sub in (U.BS_TZCNT, U.BS_LZCNT):
            if src == 0:
                r = bits
            elif sub == U.BS_TZCNT:
                r = (src & -src).bit_length() - 1
            else:
                r = bits - src.bit_length()
            self.write_reg(uop.dst_reg, uop.opsize, r)
            self.set_flags(cf=src == 0, zf=r == 0)
            return
        if src == 0:
            self.set_flags(zf=True)
            return  # dest unmodified (Intel "undefined", hardware keeps it)
        if sub == U.BS_BSF:
            r = (src & -src).bit_length() - 1
        else:
            r = src.bit_length() - 1
        self.write_reg(uop.dst_reg, uop.opsize, r)
        self.set_flags(zf=False)

    def _exec_flagop(self, uop) -> None:
        sub = uop.sub
        if sub == U.FL_CLC:
            self.set_flags(cf=False)
        elif sub == U.FL_STC:
            self.set_flags(cf=True)
        elif sub == U.FL_CMC:
            self.set_flags(cf=not self.get_flag(RFLAGS_CF))
        elif sub == U.FL_CLD:
            self.set_flags(df=False)
        elif sub == U.FL_STD:
            self.set_flags(df=True)
        elif sub == U.FL_CLI:
            self.rflags &= ~0x200
        elif sub == U.FL_STI:
            self.rflags |= 0x200
        elif sub == U.FL_SAHF:
            ah = self.read_reg(U.REG_AH_BASE, 1)
            self.rflags = (self.rflags & ~0xD5) | (ah & 0xD5) | 0x2
        else:  # LAHF
            self.write_reg(U.REG_AH_BASE, 1, (self.rflags & 0xD7) | 0x2)

    def _exec_movcr(self, uop) -> None:
        cr = uop.sub
        if uop.sext == 0:  # read
            val = {0: self.cr0, 2: self.cr2, 3: self.cr3, 4: self.cr4,
                   8: self.cr8}.get(cr)
            if val is None:
                raise UnsupportedInsn(self.rip, uop.raw)
            self.write_reg(uop.dst_reg, 8, val)
        else:
            val = self.read_reg(uop.src_reg, 8)
            if cr == 2:
                self.cr2 = val
            elif cr == 3:
                # recorded, not raised: rip still advances; the backend turns
                # a differing cr3 into Cr3Change after the step (reference
                # tlb_cntrl hook bochscpu_backend.cc:628-657)
                self.cr3 = val
                self.cr3_event = val
            elif cr == 0:
                self.cr0 = val
            elif cr == 4:
                self.cr4 = val
            elif cr == 8:
                self.cr8 = val
            else:
                raise UnsupportedInsn(self.rip, uop.raw)

    # -- SSE -------------------------------------------------------------
    def _read_xmm_bytes(self, idx: int, size: int) -> bytes:
        lo, hi = self.xmm[idx]
        return (lo | (hi << 64)).to_bytes(16, "little")[:size]

    def _write_xmm_bytes(self, idx: int, data: bytes, merge: bool) -> None:
        if merge:
            cur = bytearray(self._read_xmm_bytes(idx, 16))
            cur[: len(data)] = data
            data = bytes(cur)
        else:
            data = data.ljust(16, b"\x00")
        val = int.from_bytes(data, "little")
        self.xmm[idx] = [val & MASK64, val >> 64]

    def _exec_ssemov(self, uop, ea) -> None:
        size = uop.opsize
        if uop.sub == 1:  # gpr -> xmm (zero upper)
            val = self.read_reg(uop.src_reg, size)
            self._write_xmm_bytes(uop.dst_reg, val.to_bytes(size, "little"),
                                  merge=False)
            return
        if uop.sub == 2:  # xmm -> gpr/mem
            data = self._read_xmm_bytes(uop.src_reg, size)
            if uop.dst_kind == U.K_MEM:
                self.virt_write(ea, data)
            else:
                self.write_reg(uop.dst_reg, size,
                               int.from_bytes(data, "little"))
            return
        if uop.sub in (4, 5):  # movlps/movhps family: one qword half
            hi = uop.sub == 5
            if uop.dst_kind == U.K_MEM:  # store the chosen half
                data = self._read_xmm_bytes(uop.src_reg, 16)
                self.virt_write(ea, data[8:] if hi else data[:8])
                return
            if uop.src_kind == U.K_MEM:
                half = self.virt_read(ea, 8)
            else:  # movhlps takes src HIGH; movlhps takes src LOW
                sdata = self._read_xmm_bytes(uop.src_reg, 16)
                half = sdata[:8] if hi else sdata[8:]
            dst = self._read_xmm_bytes(uop.dst_reg, 16)
            out = (dst[:8] + half) if hi else (half + dst[8:])
            self._write_xmm_bytes(uop.dst_reg, out, merge=False)
            return
        # plain moves
        if uop.src_kind == U.K_XMM:
            data = self._read_xmm_bytes(uop.src_reg, size)
        elif uop.src_kind == U.K_MEM:
            data = self.virt_read(ea, size)
        else:
            raise UnsupportedInsn(self.rip, uop.raw)
        if uop.dst_kind == U.K_XMM:
            # movss/movsd xmm,xmm merge low lanes; movq (sub=3) and loads
            # from memory zero the upper lane
            merge = uop.src_kind == U.K_XMM and size < 16 and uop.sub != 3
            self._write_xmm_bytes(uop.dst_reg, data, merge=merge)
        elif uop.dst_kind == U.K_MEM:
            self.virt_write(ea, data)
        else:
            raise UnsupportedInsn(self.rip, uop.raw)

    def _exec_ssealu(self, uop, ea) -> None:
        sub = uop.sub
        if sub == U.SSE_PINSRW:
            # word-granular insert: source is a gpr low word or an m16
            # (only 2 bytes read — a 16-byte load could fault at page end)
            if uop.src_kind == U.K_REG:
                word = self.read_reg(uop.src_reg, 2)
            else:
                word = self.read_u(ea, 2)
            dst = bytearray(self._read_xmm_bytes(uop.dst_reg, 16))
            dst[uop.cond * 2:uop.cond * 2 + 2] = word.to_bytes(2, "little")
            self._write_xmm_bytes(uop.dst_reg, bytes(dst), merge=False)
            return
        if sub == U.SSE_PEXTRW:
            src = self._read_xmm_bytes(uop.src_reg, 16)
            word = int.from_bytes(src[uop.cond * 2:uop.cond * 2 + 2],
                                  "little")
            self.write_reg(uop.dst_reg, 4, word)  # zero-extended to 32/64
            return
        if uop.src_kind == U.K_XMM:
            src = self._read_xmm_bytes(uop.src_reg, 16)
        elif uop.src_kind == U.K_MEM:
            src = self.virt_read(ea, 16)
        elif uop.src_kind == U.K_IMM:
            src = b""
        else:
            src = b"\x00" * 16

        if sub == U.SSE_PMOVMSKB:
            data = self._read_xmm_bytes(uop.src_reg, 16)
            maskbits = 0
            for i, byte in enumerate(data):
                maskbits |= ((byte >> 7) & 1) << i
            self.write_reg(uop.dst_reg, 4, maskbits)
            return

        dst = self._read_xmm_bytes(uop.dst_reg, 16)
        if sub == U.SSE_PTEST:
            d = int.from_bytes(dst, "little")
            s = int.from_bytes(src, "little")
            self.set_flags(zf=(d & s) == 0, cf=(~d & s) & ((1 << 128) - 1) == 0,
                           of=False, af=False, sf=False, pf=False)
            return
        if sub in (U.SSE_PXOR, U.SSE_XORPS):
            out = bytes(a ^ b for a, b in zip(dst, src))
        elif sub == U.SSE_POR:
            out = bytes(a | b for a, b in zip(dst, src))
        elif sub == U.SSE_PAND:
            out = bytes(a & b for a, b in zip(dst, src))
        elif sub == U.SSE_PANDN:
            out = bytes(~a & b & 0xFF for a, b in zip(dst, src))
        elif sub == U.SSE_PCMPEQB:
            out = bytes(0xFF if a == b else 0 for a, b in zip(dst, src))
        elif sub == U.SSE_PCMPEQW:
            out = b"".join(
                (b"\xff\xff" if dst[i : i + 2] == src[i : i + 2] else b"\x00\x00")
                for i in range(0, 16, 2))
        elif sub == U.SSE_PCMPEQD:
            out = b"".join(
                (b"\xff" * 4 if dst[i : i + 4] == src[i : i + 4] else b"\x00" * 4)
                for i in range(0, 16, 4))
        elif sub == U.SSE_PSUBB:
            out = bytes((a - b) & 0xFF for a, b in zip(dst, src))
        elif sub == U.SSE_PADDB:
            out = bytes((a + b) & 0xFF for a, b in zip(dst, src))
        elif sub == U.SSE_PMINUB:
            out = bytes(min(a, b) for a, b in zip(dst, src))
        elif sub == U.SSE_PUNPCKLQDQ:
            out = dst[:8] + src[:8]
        elif sub == U.SSE_PUNPCKLDQ:
            out = dst[:4] + src[:4] + dst[4:8] + src[4:8]
        elif sub == U.SSE_PADDQ:
            out = b"".join(
                ((int.from_bytes(dst[i:i + 8], "little")
                  + int.from_bytes(src[i:i + 8], "little"))
                 & MASK64).to_bytes(8, "little")
                for i in (0, 8))
        elif sub == U.SSE_PSHUFD:
            sel = uop.imm
            out = b"".join(
                src[((sel >> (2 * i)) & 3) * 4 : ((sel >> (2 * i)) & 3) * 4 + 4]
                for i in range(4))
        elif sub == U.SSE_PSLLDQ:
            n = min(uop.imm, 16)
            out = (b"\x00" * n + dst)[:16]
        elif sub == U.SSE_PSRLDQ:
            n = min(uop.imm, 16)
            out = (dst[n:] + b"\x00" * 16)[:16]
        elif sub in (U.SSE_PSLLQ_I, U.SSE_PSRLQ_I):
            n = uop.imm
            if n > 63:
                out = bytes(16)
            else:
                lo = int.from_bytes(dst[:8], "little")
                hi = int.from_bytes(dst[8:], "little")
                if sub == U.SSE_PSLLQ_I:
                    lo, hi = (lo << n) & MASK64, (hi << n) & MASK64
                else:
                    lo, hi = lo >> n, hi >> n
                out = lo.to_bytes(8, "little") + hi.to_bytes(8, "little")
        else:
            raise UnsupportedInsn(self.rip, uop.raw)
        self._write_xmm_bytes(uop.dst_reg, out, merge=False)

    # -- x87 -------------------------------------------------------------
    def _st_phys(self, i: int) -> int:
        return (self.fptop + i) & 7

    def _st_bits(self, i: int) -> int:
        return self.fpst[self._st_phys(i)]

    def _st_f(self, i: int) -> float:
        import struct as _s

        return _s.unpack("<d", self._st_bits(i).to_bytes(8, "little"))[0]

    def _st_set_f(self, i: int, value: float) -> None:
        import struct as _s

        self.fpst[self._st_phys(i)] = int.from_bytes(
            _s.pack("<d", value), "little")

    def _fp_tag(self, phys: int, empty: bool) -> None:
        self.fptw = (self.fptw & ~(3 << (phys * 2))) | (
            (3 if empty else 0) << (phys * 2))

    def _fp_push_bits(self, bits: int) -> None:
        self.fptop = (self.fptop - 1) & 7
        self.fpst[self.fptop] = bits & MASK64
        self._fp_tag(self.fptop, empty=False)

    def _fp_pop(self, count: int = 1) -> None:
        for _ in range(count):
            self._fp_tag(self.fptop, empty=True)
            self.fptop = (self.fptop + 1) & 7

    def _exec_x87(self, uop, ea) -> None:  # noqa: C901 - one dispatcher
        """x87 subset (OPC_X87): double-precision value model — bit-exact
        vs hardware under the PC=53 control word Windows runs with (see
        cpu/uops.py).  No x87 exceptions/faults are modeled beyond the
        memory accesses themselves."""
        import math
        import struct as _s

        sub = uop.sub
        i = uop.imm & 7
        if sub == U.X87_FLD_M:
            raw = self.virt_read(ea, uop.srcsize)
            f = _s.unpack("<f" if uop.srcsize == 4 else "<d", raw)[0]
            self._fp_push_bits(int.from_bytes(_s.pack("<d", f), "little"))
        elif sub == U.X87_FST_M:
            f = self._st_f(0)
            if uop.srcsize == 4:
                import numpy as np

                self.virt_write(ea, np.asarray(f, dtype="<f4").tobytes())
            else:
                self.virt_write(ea, _s.pack("<d", f))
            if uop.sext:
                self._fp_pop()
        elif sub == U.X87_FILD:
            v = _sx(self.read_u(ea, uop.srcsize), uop.srcsize * 8)
            import numpy as np

            f = float(np.asarray(v, dtype=np.int64).astype(np.float64))
            self._fp_push_bits(int.from_bytes(_s.pack("<d", f), "little"))
        elif sub in (U.X87_FIST, U.X87_FIST_T):
            import numpy as np

            bits = uop.srcsize * 8
            f = self._st_f(0)
            indefinite = 1 << (bits - 1)
            if f != f or f in (math.inf, -math.inf):
                r = indefinite
            else:
                # fisttp always chops; fist(p) honors fpcw.RC (bits 10-11:
                # 0 nearest-even, 1 down, 2 up, 3 chop) — the classic
                # pre-SSE truncation idiom rewrites RC around the store
                rc = 3 if sub == U.X87_FIST_T else (self.fpcw >> 10) & 3
                if rc == 0:
                    r = int(np.rint(np.asarray(f)))
                elif rc == 1:
                    r = math.floor(f)
                elif rc == 2:
                    r = math.ceil(f)
                else:
                    r = int(f)
                if not -(1 << (bits - 1)) <= r < (1 << (bits - 1)):
                    r = indefinite
            self.write_u(ea, uop.srcsize, r & ((1 << bits) - 1))
            if uop.sext:
                self._fp_pop()
        elif sub == U.X87_FLD_STI:
            self._fp_push_bits(self._st_bits(i))
        elif sub == U.X87_FST_STI:
            self.fpst[self._st_phys(i)] = self._st_bits(0)
            self._fp_tag(self._st_phys(i), empty=False)
            if uop.sext:
                self._fp_pop()
        elif sub == U.X87_FLD_CONST:
            f = 1.0 if uop.imm == 0 else 0.0
            self._fp_push_bits(int.from_bytes(_s.pack("<d", f), "little"))
        elif sub in (U.X87_ARITH_M, U.X87_ARITH_ST):
            if sub == U.X87_ARITH_M:
                raw = self.virt_read(ea, uop.srcsize)
                b = _s.unpack("<f" if uop.srcsize == 4 else "<d", raw)[0]
                a = self._st_f(0)
                dst = 0
            elif uop.dst_reg:  # DC/DE: st(i) = st(i) OP st(0)
                a, b = self._st_f(i), self._st_f(0)
                dst = i
            else:              # D8: st(0) = st(0) OP st(i)
                a, b = self._st_f(0), self._st_f(i)
                dst = 0
            op = uop.cond
            if op in (U.X87_OP_COM, U.X87_OP_COMP):
                self._x87_compare(a, b, into_rflags=False)
            else:
                import numpy as np

                an, bn = np.float64(a), np.float64(b)
                with np.errstate(all="ignore"):  # IEEE inf/nan semantics
                    if op == U.X87_OP_ADD:
                        r = an + bn
                    elif op == U.X87_OP_MUL:
                        r = an * bn
                    elif op == U.X87_OP_SUB:
                        r = an - bn
                    elif op == U.X87_OP_SUBR:
                        r = bn - an
                    elif op == U.X87_OP_DIV:
                        r = an / bn
                    else:  # X87_OP_DIVR
                        r = bn / an
                self._st_set_f(dst, float(r))
            if uop.sext:
                self._fp_pop()
        elif sub == U.X87_FXCH:
            pa, pb = self._st_phys(0), self._st_phys(i)
            self.fpst[pa], self.fpst[pb] = self.fpst[pb], self.fpst[pa]
        elif sub == U.X87_FCHS:
            self.fpst[self._st_phys(0)] ^= 1 << 63
        elif sub == U.X87_FABS:
            self.fpst[self._st_phys(0)] &= ~(1 << 63)
        elif sub == U.X87_FNSTCW:
            self.write_u(ea, 2, self.fpcw)
        elif sub == U.X87_FLDCW:
            self.fpcw = self.read_u(ea, 2)
        elif sub == U.X87_FNSTSW_AX:
            self.write_reg(0, 2, self.fpsw_packed())
        elif sub == U.X87_FNSTSW_M:
            self.write_u(ea, 2, self.fpsw_packed())
        elif sub == U.X87_COMI:
            a, b = self._st_f(0), self._st_f(i)
            self._x87_compare(a, b, into_rflags=True)
            if uop.sext:
                self._fp_pop(uop.sext)
        elif sub == U.X87_COM:
            a, b = self._st_f(0), self._st_f(i)
            self._x87_compare(a, b, into_rflags=False)
            if uop.sext:
                self._fp_pop(uop.sext)
        elif sub == U.X87_FNINIT:
            self.fpcw, self.fpsw, self.fptw, self.fptop = 0x37F, 0, 0xFFFF, 0
        elif sub == U.X87_FNCLEX:
            self.fpsw &= ~0x80FF
        elif sub == U.X87_FFREE:
            self._fp_tag(self._st_phys(i), empty=True)
        elif sub == U.X87_EMMS:
            self.fptw = 0xFFFF
        elif sub == U.X87_LDMXCSR:
            self.mxcsr = self.read_u(ea, 4)
        elif sub == U.X87_STMXCSR:
            self.write_u(ea, 4, self.mxcsr & 0xFFFFFFFF)
        elif sub == U.X87_FXSAVE:
            self.virt_write(ea, self._fxsave_image())
        elif sub == U.X87_FXRSTOR:
            self._fxrstor_image(self.virt_read(ea, 512))
        elif sub == U.X87_XSAVE:
            # XSAVE64 with RFBM = edx:eax; x87 (bit 0) + SSE (bit 1) +
            # AVX (bit 2, the upper YMM halves at the standard offset
            # 576) are the components this machine model carries — the
            # kernel context-switch path.  The legacy region is the
            # fxsave image; XSTATE_BV in the header records what saved.
            rfbm = ((self.gpr[2] << 32) | (self.gpr[0] & 0xFFFFFFFF)) & 0x7
            img = bytearray(self._fxsave_image())
            header = bytearray(64)
            _s.pack_into("<Q", header, 0, rfbm)  # XSTATE_BV
            out = bytes(img) + bytes(header)
            if rfbm & 4:
                avx = bytearray(256)
                for r in range(16):
                    _s.pack_into("<QQ", avx, 16 * r,
                                 self.ymmh[r][0], self.ymmh[r][1])
                out += bytes(avx)
            self.virt_write(ea, out)
        elif sub == U.X87_XRSTOR:
            rfbm = ((self.gpr[2] << 32) | (self.gpr[0] & 0xFFFFFFFF)) & 0x7
            raw = self.virt_read(ea, 576)
            (xstate_bv,) = _s.unpack_from("<Q", raw, 512)
            use = rfbm & xstate_bv
            if rfbm & 1:
                if use & 1:
                    self._fxrstor_x87_only(raw)
                else:  # component in init state
                    self.fpcw, self.fpsw = 0x37F, 0
                    self.fptw, self.fptop = 0xFFFF, 0
                    self.fpst = [0] * 8
            if rfbm & 2:
                if use & 2:
                    (self.mxcsr,) = _s.unpack_from("<I", raw, 24)
                    for r in range(16):
                        self._write_xmm_bytes(
                            r, raw[160 + 16 * r:176 + 16 * r], merge=False)
                else:
                    self.mxcsr = 0x1F80
                    for r in range(16):
                        self._write_xmm_bytes(r, bytes(16), merge=False)
            if rfbm & 4:
                if use & 4:
                    avx = self.virt_read((ea + 576) & MASK64, 256)
                    for r in range(16):
                        lo, hi = _s.unpack_from("<QQ", avx, 16 * r)
                        self.ymmh[r] = [lo, hi]
                else:
                    for r in range(16):
                        self.ymmh[r] = [0, 0]
        else:
            raise UnsupportedInsn(self.rip, uop.raw)

    def _x87_compare(self, a: float, b: float, into_rflags: bool) -> None:
        unord = a != a or b != b
        zf, pf, cf = (True, True, True) if unord else (
            a == b, False, a < b)
        if into_rflags:  # fcomi/fucomi family
            self.set_flags(zf=zf, pf=pf, cf=cf, of=False, af=False, sf=False)
        else:  # fcom family: C3/C2/C0 in the status word
            self.fpsw = (self.fpsw & ~0x4500) | (
                (0x4000 if zf else 0) | (0x400 if pf else 0)
                | (0x100 if cf else 0))

    def _fxsave_image(self) -> bytes:
        """The 512-byte FXSAVE64 area (SDM vol 1 10.5.1): control words,
        abridged tag, ST0-7 as 80-bit extended, XMM0-15."""
        out = bytearray(512)
        import struct as _s

        _s.pack_into("<HH", out, 0, self.fpcw & 0xFFFF, self.fpsw_packed())
        # abridged tag: bit i = 1 when physical reg i is NOT empty
        abridged = 0
        for phys in range(8):
            if (self.fptw >> (phys * 2)) & 3 != 3:
                abridged |= 1 << phys
        out[4] = abridged
        _s.pack_into("<I", out, 24, self.mxcsr & 0xFFFFFFFF)
        _s.pack_into("<I", out, 28, 0xFFBF)  # mxcsr_mask
        for j in range(8):
            # slots hold st(j) (top-relative), 80-bit value + 6 pad bytes
            v80 = _f64_to_f80(self._st_bits(j))
            out[32 + 16 * j:32 + 16 * j + 10] = v80.to_bytes(10, "little")
        for r in range(16):
            out[160 + 16 * r:176 + 16 * r] = self._read_xmm_bytes(r, 16)
        return bytes(out)

    def _fxrstor_x87_only(self, raw: bytes) -> None:
        import struct as _s

        self.fpcw, fpsw = _s.unpack_from("<HH", raw, 0)
        self.fpsw = fpsw
        self.fptop = (fpsw >> 11) & 7
        abridged = raw[4]
        self.fptw = 0
        for phys in range(8):
            tag = 0 if (abridged >> phys) & 1 else 3
            self.fptw |= tag << (phys * 2)
        for j in range(8):
            v80 = int.from_bytes(raw[32 + 16 * j:32 + 16 * j + 10], "little")
            self.fpst[self._st_phys(j)] = _f80_to_f64_bits(v80)

    def _fxrstor_image(self, raw: bytes) -> None:
        import struct as _s

        self._fxrstor_x87_only(raw)
        (self.mxcsr,) = _s.unpack_from("<I", raw, 24)
        for r in range(16):
            self._write_xmm_bytes(r, raw[160 + 16 * r:176 + 16 * r],
                                  merge=False)

    def _exec_ssefp(self, uop, ea) -> None:
        """SSE/SSE2 floating point (OPC_SSEFP) — semantics in _SseFp."""
        sub = uop.sub
        elem = uop.srcsize
        packed = uop.sext == 1
        fp = _SseFp(elem)
        n = (16 // elem) if packed else 1

        def src_bytes(nbytes):
            if uop.src_kind == U.K_XMM:
                return self._read_xmm_bytes(uop.src_reg, nbytes)
            return self.virt_read(ea, nbytes)

        def split(b, count):
            return [b[i * elem:(i + 1) * elem] for i in range(count)]

        # integer-involved converts first (different operand shapes)
        if sub == U.FP_CVT_I2F:
            if uop.src_kind == U.K_REG:
                ival = self.read_reg(uop.src_reg, uop.opsize)
            else:
                ival = self.read_u(ea, uop.opsize)
            ival = _sx(ival, uop.opsize * 8)
            # int64 -> float32 must round ONCE (cvtsi2ss semantics):
            # numpy's int64.astype(float32) is the direct C cast
            out = fp.np.asarray(ival, dtype=fp.np.int64).astype(
                fp.fdt).tobytes()
            self._write_xmm_bytes(uop.dst_reg, out, merge=True)
            return
        if sub in (U.FP_CVT_F2I, U.FP_CVT_F2I_T):
            b = src_bytes(elem)
            r = fp.to_int(b, uop.opsize * 8, sub == U.FP_CVT_F2I_T)
            self.write_reg(uop.dst_reg, uop.opsize, r)
            return
        if sub in (U.FP_UCOMI, U.FP_COMI):
            a_b = self._read_xmm_bytes(uop.dst_reg, elem)
            b_b = src_bytes(elem)
            if fp.isnan(a_b) or fp.isnan(b_b):
                zf = pf = cf = True
            else:
                a, b = fp.f(a_b), fp.f(b_b)
                zf, pf, cf = a == b, False, a < b
            self.set_flags(zf=zf, pf=pf, cf=cf, of=False, af=False, sf=False)
            return

        dst16 = self._read_xmm_bytes(uop.dst_reg, 16)
        if sub == U.FP_CVT_F2F:
            np = fp.np
            dst_elem = 12 - elem  # 4 <-> 8
            dst_dt = np.dtype("<f4") if dst_elem == 4 else np.dtype("<f8")
            count = 2 if packed else 1
            src = split(src_bytes(elem * count), count)
            vals = [np.frombuffer(b, dtype=fp.fdt)[0] for b in src]
            with np.errstate(all="ignore"):
                out = b"".join(np.asarray(v, dtype=dst_dt).tobytes()
                               for v in vals)
            if packed:
                # cvtps2pd fills 16; cvtpd2ps writes low 8, zeroes high
                out = out.ljust(16, b"\x00")
                self._write_xmm_bytes(uop.dst_reg, out, merge=False)
            else:
                self._write_xmm_bytes(uop.dst_reg, out, merge=True)
            return
        if sub in (U.FP_CVT_DQ2PS, U.FP_CVT_PS2DQ, U.FP_CVT_PS2DQ_T,
                   U.FP_CVT_DQ2PD, U.FP_CVT_PD2DQ, U.FP_CVT_PD2DQ_T):
            np = fp.np
            src = src_bytes(16)
            if sub == U.FP_CVT_DQ2PS:
                ints = np.frombuffer(src, dtype="<i4")
                out = ints.astype("<f4").tobytes()
            elif sub == U.FP_CVT_DQ2PD:
                ints = np.frombuffer(src[:8], dtype="<i4")
                out = ints.astype("<f8").tobytes()
            else:
                fp_in = _SseFp(4 if sub in (U.FP_CVT_PS2DQ,
                                            U.FP_CVT_PS2DQ_T) else 8)
                count = 16 // fp_in.elem
                trunc = sub in (U.FP_CVT_PS2DQ_T, U.FP_CVT_PD2DQ_T)
                pieces = [fp_in.to_int(b, 32, trunc).to_bytes(4, "little")
                          for b in (src[i * fp_in.elem:(i + 1) * fp_in.elem]
                                    for i in range(count))]
                out = b"".join(pieces).ljust(16, b"\x00")
            self._write_xmm_bytes(uop.dst_reg, out, merge=False)
            return

        # element-wise forms over the common (dst, src) vector shape
        src_v = split(src_bytes(16 if packed else elem), n)
        dst_v = split(dst16, n)
        if sub in (U.FP_ADD, U.FP_SUB, U.FP_MUL, U.FP_DIV):
            out_v = [fp.arith(sub, d, s) for d, s in zip(dst_v, src_v)]
        elif sub in (U.FP_MIN, U.FP_MAX):
            out_v = [fp.minmax(sub, d, s) for d, s in zip(dst_v, src_v)]
        elif sub == U.FP_SQRT:
            out_v = [fp.sqrt(s) for s in src_v]
        elif sub == U.FP_CMP:
            mask = (b"\xFF" * elem, b"\x00" * elem)
            out_v = [mask[0] if fp.cmp(uop.imm & 7, d, s) else mask[1]
                     for d, s in zip(dst_v, src_v)]
        elif sub == U.FP_SHUF:
            src16 = src_bytes(16)
            sel = uop.imm
            if elem == 4:
                picks = [dst16, dst16, src16, src16]
                out_v = [picks[i][((sel >> (2 * i)) & 3) * 4:
                                  ((sel >> (2 * i)) & 3) * 4 + 4]
                         for i in range(4)]
            else:
                out_v = [dst16[(sel & 1) * 8:(sel & 1) * 8 + 8],
                         src16[((sel >> 1) & 1) * 8:((sel >> 1) & 1) * 8 + 8]]
        elif sub in (U.FP_UNPCKL, U.FP_UNPCKH):
            src16 = src_bytes(16)
            d_v, s_v = split(dst16, 16 // elem), split(src16, 16 // elem)
            half = len(d_v) // 2
            base = 0 if sub == U.FP_UNPCKL else half
            out_v = []
            for i in range(half):
                out_v += [d_v[base + i], s_v[base + i]]
        else:
            raise UnsupportedInsn(self.rip, uop.raw)
        out = b"".join(out_v)
        self._write_xmm_bytes(uop.dst_reg, out, merge=not packed)
        return


class _SseFp:
    """SSE/SSE2 floating-point semantics for the oracle (OPC_SSEFP).

    Exact IEEE-754 via numpy (single-precision ops computed in float32 —
    no double rounding) with the x86 rules handled at the bit level: NaN
    payloads preserved, SNaNs quieted, the dst-operand NaN wins for
    arithmetic, min/max/cmp forward the SECOND operand on NaN/equality,
    out-of-range converts produce the integer indefinite.  Oracle-only by
    design: the census over real Windows PEs (tools/decode_census.py)
    shows FP dominates the decode gap, but snapshot-fuzzing guests run
    integer-heavy paths, so trapping FP to the host costs little.
    """

    def __init__(self, elem: int):
        import numpy as np

        self.np = np
        self.elem = elem
        self.fdt = np.dtype("<f4") if elem == 4 else np.dtype("<f8")

    def f(self, b: bytes):
        return self.np.frombuffer(b[:self.elem], dtype=self.fdt)[0]

    def bits(self, x) -> bytes:
        return self.np.asarray(x, dtype=self.fdt).tobytes()

    def isnan(self, b: bytes) -> bool:
        return bool(self.np.isnan(self.f(b)))

    def quiet(self, b: bytes) -> bytes:
        out = bytearray(b[:self.elem])
        if self.elem == 4:
            out[2] |= 0x40  # f32 QNaN bit 22
        else:
            out[6] |= 0x08  # f64 QNaN bit 51
        return bytes(out)

    @property
    def indefinite(self) -> bytes:
        return (b"\x00\x00\xC0\xFF" if self.elem == 4
                else b"\x00\x00\x00\x00\x00\x00\xF8\xFF")

    def arith(self, sub: int, a_b: bytes, b_b: bytes) -> bytes:
        import wtf_tpu.cpu.uops as U

        np = self.np
        if self.isnan(a_b):
            return self.quiet(a_b)
        if self.isnan(b_b):
            return self.quiet(b_b)
        a, b = self.f(a_b), self.f(b_b)
        with np.errstate(all="ignore"):
            if sub == U.FP_ADD:
                r = a + b
            elif sub == U.FP_SUB:
                r = a - b
            elif sub == U.FP_MUL:
                r = a * b
            else:  # FP_DIV
                r = a / b
        if np.isnan(r):  # invalid operation (inf-inf, 0*inf, 0/0, inf/inf)
            return self.indefinite
        return self.bits(r)

    def minmax(self, sub: int, a_b: bytes, b_b: bytes) -> bytes:
        import wtf_tpu.cpu.uops as U

        # SDM MINSS: NaN (either), or equal values (incl. ±0): the SECOND
        # operand is returned unchanged
        if self.isnan(a_b) or self.isnan(b_b):
            return b_b[:self.elem]
        a, b = self.f(a_b), self.f(b_b)
        if a == b:
            return b_b[:self.elem]
        take_a = a < b if sub == U.FP_MIN else a > b
        return a_b[:self.elem] if take_a else b_b[:self.elem]

    def sqrt(self, b_b: bytes) -> bytes:
        np = self.np
        if self.isnan(b_b):
            return self.quiet(b_b)
        v = self.f(b_b)
        if v < 0:
            return self.indefinite  # sqrt(-x) -> real indefinite
        with np.errstate(all="ignore"):
            return self.bits(np.sqrt(v))

    def cmp(self, pred: int, a_b: bytes, b_b: bytes) -> bool:
        unord = self.isnan(a_b) or self.isnan(b_b)
        a, b = self.f(a_b), self.f(b_b)
        if pred == 0:
            return not unord and a == b
        if pred == 1:
            return not unord and a < b
        if pred == 2:
            return not unord and a <= b
        if pred == 3:
            return unord
        if pred == 4:
            return unord or a != b
        if pred == 5:
            return unord or not a < b
        if pred == 6:
            return unord or not a <= b
        return not unord  # 7: ord

    def to_int(self, b_b: bytes, int_bits: int, truncate: bool) -> int:
        """cvt(t)ss/sd2si: rounded (half-even) or truncated, with the
        integer-indefinite on NaN/overflow."""
        np = self.np
        indefinite = 1 << (int_bits - 1)
        if self.isnan(b_b):
            return indefinite
        v = float(self.f(b_b))
        if v != v or v in (float("inf"), float("-inf")):
            return indefinite
        r = int(v) if truncate else int(np.rint(np.asarray(v)))
        if not -(1 << (int_bits - 1)) <= r < (1 << (int_bits - 1)):
            return indefinite
        return r & ((1 << int_bits) - 1)


class GuestCrash(Exception):
    """int3/int n/ud2/hlt executed — surfaced as a Crash result (matching the
    reference's interrupt/hlt handling, bochscpu_backend.cc:595-619,690-697)."""

    def __init__(self, rip: int, uop: U.Uop):
        super().__init__(f"guest fault at {rip:#x} (opc={uop.opc} sub={uop.sub})")
        self.rip = rip
        self.uop = uop


