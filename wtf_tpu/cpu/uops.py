"""Decoded-instruction ("uop") encoding shared by host decoder and executors.

TPU-first design note: the reference interprets x86-64 by switching on raw
opcode bytes inside the emulator's hot loop (bochscpu's fetch-decode-execute;
reference src/libs/bochscpu-bins/include/bochscpu.hpp).  On TPU that per-byte
decode would be branchy, scalar work that maps terribly onto the VPU, so we
split the job the way a JIT does:

  - the HOST decodes each instruction ONCE (per unique guest address) into a
    fixed-width record — the "uop" — stored in device-resident parallel
    arrays (wtf_tpu/cpu/machine.py);
  - the DEVICE executes uops with a uniform pipeline (effective address →
    masked load → ALU select over op classes → masked store → writeback),
    fully vectorized over lanes, with no data-dependent shapes.

Every instruction becomes exactly one uop.  Complex x86 semantics (REP string
ops, partial-register merges, flag updates) are folded into the uop's class
semantics rather than expanded into multi-uop sequences, so `rip` advance
stays trivially per-instruction.

The encoding below is the contract between:
  decoder.py  (host: bytes -> Uop)
  emu.py      (host oracle: executes Uops in pure Python; the differential-
               testing reference, standing in for the role bochscpu rip
               traces play in the reference workflow, SURVEY.md §4)
  exec.py     (device: executes the same Uops in JAX)
"""

from __future__ import annotations

import dataclasses

MASK64 = (1 << 64) - 1

# ---------------------------------------------------------------------------
# Operation classes (Uop.opc).  Each is one branch of the device ALU select.
# ---------------------------------------------------------------------------
OPC_INVALID = 0    # undecodable -> lane status UNSUPPORTED
OPC_NOP = 1
OPC_MOV = 2        # mov / movzx / movsx / movsxd (extension via srcsize+sext)
OPC_LEA = 3
OPC_ALU = 4        # sub-op in ALU_*
OPC_SHIFT = 5      # sub-op in SH_*
OPC_UNARY = 6      # sub-op in UN_*
OPC_MUL = 7        # widening mul/imul (one-operand) and 2/3-operand imul
OPC_DIV = 8        # div/idiv
OPC_PUSH = 9
OPC_POP = 10
OPC_CALL = 11
OPC_RET = 12       # + imm16 stack adjustment
OPC_JMP = 13
OPC_JCC = 14
OPC_SETCC = 15
OPC_CMOVCC = 16
OPC_STRING = 17    # movs/stos/lods/scas/cmps, optionally REP — one iteration
                   # per uop execution; rip only advances when done
OPC_XCHG = 18
OPC_CONVERT = 19   # sub 0: cbw/cwde/cdqe ; sub 1: cwd/cdq/cqo
OPC_BT = 20        # sub-op BT_*
OPC_BITSCAN = 21   # sub-op BS_*
OPC_SYSCALL = 22   # traps to harness (lane pauses)
OPC_INT = 23       # int3 / int n / ud2 / into -> crash path
OPC_HLT = 24
OPC_RDTSC = 25
OPC_RDRAND = 26    # deterministic per-lane chain (reference
                   # bochscpu_backend.cc:874-885 uses a Blake3 chain)
OPC_CPUID = 27
OPC_LEAVE = 28
OPC_PUSHF = 29
OPC_POPF = 30
OPC_FLAGOP = 31    # sub-op FL_*: clc/stc/cmc/cld/std/cli/sti/sahf/lahf
OPC_BSWAP = 32
OPC_CMPXCHG = 33
OPC_XADD = 34
OPC_SSEMOV = 35    # vector-register moves/loads/stores (XMM only)
OPC_SSEALU = 36    # sub-op SSE_*: bitwise/compare XMM ops
OPC_FENCE = 37     # lfence/sfence/mfence/pause -> nop
OPC_XGETBV = 38
OPC_RDGSBASE = 39  # rd/wr fs/gs base (sub: 0 rdfs,1 rdgs,2 wrfs,3 wrgs)
OPC_MOVCR = 40     # mov to/from control register (cr3 writes -> Cr3Change)
OPC_INT1 = 41      # icebp/int1 -> crash
OPC_IRET = 42      # unsupported-class kernel returns (flagged)
OPC_SSECVT = 43    # scalar int<->float converts [minimal]
OPC_PCLMUL = 44    # reserved
OPC_PEXT = 45      # bmi: sub-op BMI_*
OPC_STACKSTR = 46  # push/pop of segment etc (rare; unsupported)
OPC_MSR = 47       # rdmsr/wrmsr (sub: 0 read, 1 write); oracle-serviced
OPC_VZEROALL = 48  # sub 0: vzeroall (whole vector file); sub 1:
                   # vzeroupper (upper YMM halves only) — both execute
                   # on the device as whole-file writes
OPC_SSEFP = 49     # SSE/SSE2 floating point (sub FP_*; srcsize = element
                   # width 4/8, sext = 1 for packed forms).  The dominant
                   # decode gap measured on real Windows-PE codegen
                   # (tools/decode_census.py); oracle-serviced — guests in
                   # the snapshot-fuzzing domain run integer-heavy paths,
                   # so FP trapping to the host costs little
OPC_X87 = 50       # x87 FPU subset (sub X87_*).  Values held in double
                   # precision — Windows runs the FPU with PC=53-bit
                   # (fpcw 0x27F), where add/sub/mul/div round
                   # identically to f64, so the model is bit-exact for
                   # the codegen that actually appears; 80-bit-extended
                   # corner cases (PC=64 + huge exponents) diverge.
                   # Executes on the DEVICE except the FXSAVE-class
                   # state movers (512+ byte images), which stay
                   # oracle-serviced

N_OPC = 51

# OPC_X87 sub-operations.  Field conventions: srcsize = memory operand
# width, sext = number of stack pops (0/1/2), imm = st(i) index or
# constant id, cond = arithmetic op digit, dst_reg = 1 when st(i) is the
# destination (DC/DE forms).
(X87_FLD_M, X87_FST_M, X87_FILD, X87_FIST, X87_FIST_T, X87_FLD_STI,
 X87_FST_STI, X87_FLD_CONST, X87_ARITH_M, X87_ARITH_ST, X87_FXCH,
 X87_FCHS, X87_FABS, X87_FNSTCW, X87_FLDCW, X87_FNSTSW_AX, X87_FNSTSW_M,
 X87_COMI, X87_COM, X87_FNINIT, X87_FNCLEX, X87_FFREE, X87_LDMXCSR,
 X87_STMXCSR, X87_FXSAVE, X87_FXRSTOR, X87_EMMS,
 X87_XSAVE, X87_XRSTOR) = range(29)

# X87_ARITH_* op digits (x87 /r encoding)
X87_OP_ADD, X87_OP_MUL, X87_OP_COM, X87_OP_COMP, X87_OP_SUB, \
    X87_OP_SUBR, X87_OP_DIV, X87_OP_DIVR = range(8)

# OPC_SSEFP sub-operations
FP_ADD = 0
FP_SUB = 1
FP_MUL = 2
FP_DIV = 3
FP_MIN = 4
FP_MAX = 5
FP_SQRT = 6
FP_UCOMI = 7      # ucomiss/ucomisd: rflags only
FP_COMI = 8       # comiss/comisd (same flag image; #IA differences N/A)
FP_CMP = 9        # cmpps/ss/pd/sd imm8 predicate -> all-ones/zeros mask
FP_CVT_I2F = 10   # cvtsi2ss/sd (gpr/mem int -> fp scalar)
FP_CVT_F2I = 11   # cvtss2si/cvtsd2si (rounded)
FP_CVT_F2I_T = 12 # cvttss2si/cvttsd2si (truncated)
FP_CVT_F2F = 13   # cvtss2sd/cvtsd2ss/cvtps2pd/cvtpd2ps
FP_CVT_DQ2PS = 14 # cvtdq2ps
FP_CVT_PS2DQ = 15 # cvtps2dq (rounded)
FP_CVT_PS2DQ_T = 16  # cvttps2dq
FP_SHUF = 17      # shufps/shufpd imm8
FP_UNPCKL = 18    # unpcklps/unpcklpd
FP_UNPCKH = 19    # unpckhps/unpckhpd
FP_CVT_DQ2PD = 20 # cvtdq2pd (F3 0F E6 is pd->dq; E6/5A family)
FP_CVT_PD2DQ = 21 # cvtpd2dq (F2 0F E6, rounded)
FP_CVT_PD2DQ_T = 22  # cvttpd2dq (66 0F E6)

# RFLAGS bits writable by flag-image restores (sysret r11, iretq frame):
# CF PF AF ZF SF TF IF DF OF IOPL NT AC VIF VIP ID.  RF (bit 16) and VM
# (bit 17) are intentionally masked — this is sysret's architectural
# 0x3C7FD7 mask, which we also apply to iretq (hardware iretq restores
# RF; this framework never single-steps via RF, so the difference is
# unobservable to guests and keeps one shared mask).
RF_WRITABLE = 0x3C7FD7

# ALU sub-ops (match x86 /r group encoding order, reference has the same
# ordering baked into its emulator tables)
ALU_ADD, ALU_OR, ALU_ADC, ALU_SBB, ALU_AND, ALU_SUB, ALU_XOR, ALU_CMP = range(8)
ALU_TEST = 8

# SHIFT sub-ops (group 2 /r order)
SH_ROL, SH_ROR, SH_RCL, SH_RCR, SH_SHL, SH_SHR, SH_SAL, SH_SAR = range(8)
SH_SHLD, SH_SHRD = 8, 9

# UNARY sub-ops
UN_INC, UN_DEC, UN_NOT, UN_NEG = range(4)

# MUL sub-ops
MUL_WIDE_U = 0     # mul r/m : rdx:rax = rax * r/m
MUL_WIDE_S = 1     # imul r/m
MUL_2OP = 2        # imul r, r/m (and 3-op imul r, r/m, imm via src=imm path)

# DIV sub-ops
DIV_U, DIV_S = 0, 1

# STRING sub-ops
STR_MOVS, STR_STOS, STR_LODS, STR_SCAS, STR_CMPS = range(5)
REP_NONE, REP_REP, REP_REPNE = 0, 1, 2

# BT sub-ops
BT_BT, BT_BTS, BT_BTR, BT_BTC = range(4)

# BITSCAN sub-ops
BS_BSF, BS_BSR, BS_POPCNT, BS_TZCNT, BS_LZCNT = range(5)

# FLAGOP sub-ops
FL_CLC, FL_STC, FL_CMC, FL_CLD, FL_STD, FL_CLI, FL_STI, FL_SAHF, FL_LAHF = range(9)

# SSEALU sub-ops
SSE_PXOR, SSE_POR, SSE_PAND, SSE_PANDN, SSE_XORPS, SSE_PCMPEQB, SSE_PMOVMSKB, \
    SSE_PSUBB, SSE_PADDB, SSE_PUNPCKLQDQ, SSE_PCMPEQW, SSE_PCMPEQD, SSE_PTEST, \
    SSE_PSHUFD, SSE_PSLLDQ, SSE_PSRLDQ, SSE_PMINUB, SSE_PUNPCKLDQ, \
    SSE_PADDQ, SSE_PSLLQ_I, SSE_PSRLQ_I, SSE_PINSRW, SSE_PEXTRW = range(23)

# BMI sub-ops
BMI_ANDN, BMI_BZHI, BMI_PEXT_, BMI_PDEP, BMI_BLSR, BMI_BLSMSK, BMI_BLSI, \
    BMI_BEXTR, BMI_SHLX, BMI_SHRX, BMI_SARX, BMI_RORX = range(12)

# Operand kinds
K_NONE, K_REG, K_MEM, K_IMM, K_XMM = range(5)

# Register indices: 0-15 = rax..r15 (x86 encoding order,
# core.cpustate.GPR_NAMES); 16-19 = ah/ch/dh/bh (high-byte views);
# REG_RIP used as mem base marker for RIP-relative addressing.
REG_AH_BASE = 16
REG_RIP = 24
REG_NONE = -1

# Segment override (only FS/GS matter in long mode)
SEG_NONE, SEG_FS, SEG_GS = 0, 1, 2

# Condition codes (x86 cc encoding 0x0-0xF: o,no,b,ae,e,ne,be,a,s,ns,p,np,l,ge,le,g)
CC_O, CC_NO, CC_B, CC_AE, CC_E, CC_NE, CC_BE, CC_A, CC_S, CC_NS, CC_P, CC_NP, \
    CC_L, CC_GE, CC_LE, CC_G = range(16)


@dataclasses.dataclass
class Uop:
    """One decoded instruction.  All fields are plain ints so the record can
    be packed into device int32/uint64 parallel arrays verbatim."""

    opc: int = OPC_INVALID
    sub: int = 0          # sub-operation within the class
    cond: int = 0         # condition code for JCC/SETCC/CMOVCC
    length: int = 1       # instruction length in bytes (rip advance)
    opsize: int = 8       # operation size in bytes: 1/2/4/8/16
    srcsize: int = 0      # source load size when != opsize (movzx/movsx); 0 = opsize
    sext: int = 0         # 1: sign-extend src from srcsize to opsize
    dst_kind: int = K_NONE
    dst_reg: int = 0
    src_kind: int = K_NONE
    src_reg: int = 0
    base_reg: int = REG_NONE   # memory operand base (REG_RIP = rip-relative)
    idx_reg: int = REG_NONE    # memory operand index
    scale: int = 1
    disp: int = 0              # sign-extended displacement
    imm: int = 0               # immediate, already sign/zero-extended to 64
    seg: int = SEG_NONE
    rep: int = REP_NONE
    lock: int = 0
    a32: int = 0               # 67h: effective address truncated to 32 bits
    raw: bytes = b""           # original bytes (debug / SMC verification)

    def mem_operand(self) -> bool:
        return self.dst_kind == K_MEM or self.src_kind == K_MEM


# Field order for array packing (machine.py / exec.py rely on this).
INT_FIELDS = (
    "opc", "sub", "cond", "length", "opsize", "srcsize", "sext",
    "dst_kind", "dst_reg", "src_kind", "src_reg",
    "base_reg", "idx_reg", "scale", "seg", "rep", "lock", "a32",
)
U64_FIELDS = ("disp", "imm")
