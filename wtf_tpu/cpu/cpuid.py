"""Deterministic CPUID model shared by the Python oracle and the device
executor.

The reference gets CPUID behavior from its virtualization layer (bochs' model
or the host CPU via KVM/WHV, kvm_backend.cc:436-465 loads the host CPUID into
the VM).  For determinism across backends and chips we pin one synthetic CPU
identity: a generic x86-64 with SSE2/POPCNT and no AVX/XSAVE-dependent
features, so guests stay on code paths the interpreter supports.  Both
executors consult this exact table, keeping differential traces aligned.
"""

from __future__ import annotations

from typing import Dict, Tuple

# (leaf, subleaf) -> (eax, ebx, ecx, edx).  Missing subleaf falls back to
# subleaf 0; missing leaf falls back to highest basic leaf (Intel behavior).
_GENU = 0x756E6547  # "Genu"
_INEI = 0x49656E69  # "ineI"
_NTEL = 0x6C65746E  # "ntel"

# Feature bits, leaf 1 EDX: FPU|TSC|MSR|PAE|CX8|SEP|PGE|CMOV|CLFSH|MMX|FXSR|SSE|SSE2
_L1_EDX = (1 << 0) | (1 << 4) | (1 << 5) | (1 << 6) | (1 << 8) | (1 << 11) \
    | (1 << 13) | (1 << 15) | (1 << 19) | (1 << 23) | (1 << 24) | (1 << 25) \
    | (1 << 26)
# Leaf 1 ECX: POPCNT only.  SSE3/SSSE3/SSE4.x are NOT advertised — their
# instruction sets (movddup, palignr, pcmpistri, ...) are outside the
# implemented subset, so feature-dispatched guests (glibc ifunc etc.) must
# take the SSE2 paths both executors cover.  No OSXSAVE/AVX/RDRAND either
# (RDRAND still executes deterministically if code probes it blindly).
_L1_ECX = 1 << 23

CPUID_TABLE: Dict[Tuple[int, int], Tuple[int, int, int, int]] = {
    (0x0, 0): (0x0000000D, _GENU, _NTEL, _INEI),
    (0x1, 0): (0x000506E3, 0x00100800, _L1_ECX, _L1_EDX),
    (0x2, 0): (0x76036301, 0x00F0B5FF, 0x00000000, 0x00C30000),
    (0x4, 0): (0, 0, 0, 0),
    (0x7, 0): (0, 0, 0, 0),           # no BMI/AVX2 advertised
    (0xB, 0): (0, 0, 0, 0),           # no x2APIC topology
    (0xD, 0): (0, 0, 0, 0),
    (0x80000000, 0): (0x80000008, 0, 0, 0),
    (0x80000001, 0): (0, 0, 0x00000121, 0x2C100800),  # LAHF64|LZCNT|PREFETCHW; NX|PDPE1GB|RDTSCP|LM
    (0x80000002, 0): (0x20555054, 0x2D667477, 0x75706320, 0x00000000),  # "TPU wtf-cpu"
    (0x80000003, 0): (0, 0, 0, 0),
    (0x80000004, 0): (0, 0, 0, 0),
    (0x80000006, 0): (0, 0, 0x01006040, 0),
    (0x80000008, 0): (0x00003030, 0, 0, 0),  # 48-bit phys/virt
}

MAX_BASIC_LEAF = 0xD

# Single definition of the RDRAND-chain / edge-hash mixer lives in
# utils.hashing; re-exported here for executor convenience.
from wtf_tpu.utils.hashing import splitmix64  # noqa: E402,F401


def cpuid(leaf: int, subleaf: int) -> Tuple[int, int, int, int]:
    """Architectural CPUID lookup with out-of-range fallback."""
    leaf &= 0xFFFFFFFF
    if (leaf, subleaf) in CPUID_TABLE:
        return CPUID_TABLE[(leaf, subleaf)]
    if (leaf, 0) in CPUID_TABLE:
        return CPUID_TABLE[(leaf, 0)]
    if leaf < 0x80000000 and leaf > MAX_BASIC_LEAF:
        return CPUID_TABLE[(MAX_BASIC_LEAF, 0)]
    return (0, 0, 0, 0)
