"""Host-side x86-64 instruction decoder: bytes -> Uop.

Runs ONCE per unique guest code address (the decode cache in machine.py keeps
the result), so it is cold-path and written for clarity, not speed.  Covers
the long-mode integer subset that compiled Windows/Linux user and kernel code
actually executes, plus the XMM moves/bitops that show up in memcpy/strlen
paths; anything outside the subset decodes to OPC_INVALID and surfaces as a
per-lane UNSUPPORTED status instead of silently corrupting state (mirroring
how the reference's backends surface unknown situations as explicit results,
reference src/wtf/backend.h:12-31).

Decoding model: legacy prefixes -> REX -> opcode (1-byte map, 0F map,
0F 38 map) -> ModRM/SIB/disp -> immediate.  67h address-size overrides
decode (EA truncates to 32 bits; jecxz tests ECX) except on string ops,
whose 32-bit rsi/rdi/rcx semantics neither engine models — those refuse
loudly as OPC_INVALID.  Far/segment-load forms are out of scope (never
emitted by 64-bit compilers) and decode invalid.
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

from wtf_tpu.cpu.uops import (
    ALU_ADC, ALU_ADD, ALU_AND, ALU_CMP, ALU_OR, ALU_SBB, ALU_SUB, ALU_TEST,
    ALU_XOR, BMI_ANDN, BMI_BEXTR, BMI_BLSI, BMI_BLSMSK, BMI_BLSR, BMI_BZHI,
    BMI_PDEP, BMI_PEXT_, BMI_RORX, BMI_SARX, BMI_SHLX, BMI_SHRX, BS_BSF,
    BS_BSR, BS_LZCNT, BS_POPCNT, BS_TZCNT, BT_BT, BT_BTC, BT_BTR, BT_BTS,
    DIV_S, DIV_U, FL_CLC, FL_CLD, FL_CLI, FL_CMC, FL_LAHF, FL_SAHF, FL_STC,
    FL_STD, FL_STI, K_IMM, K_MEM, K_NONE, K_REG, K_XMM, MUL_2OP, MUL_WIDE_S,
    MUL_WIDE_U, OPC_ALU, OPC_BITSCAN, OPC_BSWAP, OPC_BT, OPC_CALL,
    OPC_CMOVCC, OPC_CMPXCHG, OPC_CONVERT, OPC_CPUID, OPC_DIV, OPC_FENCE,
    OPC_FLAGOP, OPC_HLT, OPC_INT, OPC_INT1, OPC_INVALID, OPC_IRET, OPC_JCC,
    OPC_JMP,
    OPC_LEA, OPC_LEAVE, OPC_MOV, OPC_MOVCR, OPC_MUL, OPC_NOP, OPC_PEXT,
    OPC_POP, OPC_RDGSBASE,
    OPC_MSR, OPC_POPF, OPC_PUSH, OPC_PUSHF, OPC_RDRAND, OPC_RDTSC, OPC_RET,
    OPC_SETCC, OPC_SHIFT, OPC_SSEALU, OPC_SSEMOV, OPC_STRING, OPC_SYSCALL,
    OPC_SSEFP, OPC_UNARY, OPC_VZEROALL, OPC_XADD, OPC_XCHG, OPC_XGETBV,
    FP_ADD, FP_SUB, FP_MUL, FP_DIV, FP_MIN, FP_MAX, FP_SQRT, FP_UCOMI,
    FP_COMI, FP_CMP, FP_CVT_I2F, FP_CVT_F2I, FP_CVT_F2I_T, FP_CVT_F2F,
    FP_CVT_DQ2PS, FP_CVT_PS2DQ, FP_CVT_PS2DQ_T, FP_SHUF, FP_UNPCKL,
    FP_UNPCKH, FP_CVT_DQ2PD, FP_CVT_PD2DQ, FP_CVT_PD2DQ_T,
    OPC_X87, X87_ARITH_M, X87_ARITH_ST, X87_COM, X87_COMI, X87_EMMS,
    X87_FABS, X87_FCHS, X87_FFREE, X87_FILD, X87_FIST, X87_FIST_T,
    X87_FLDCW, X87_FLD_CONST, X87_FLD_M, X87_FLD_STI, X87_FNCLEX,
    X87_FNINIT, X87_FNSTCW, X87_FNSTSW_AX, X87_FNSTSW_M, X87_FST_M,
    X87_FST_STI, X87_FXCH, X87_FXRSTOR, X87_FXSAVE, X87_LDMXCSR,
    X87_XRSTOR, X87_XSAVE,
    X87_STMXCSR, X87_OP_ADD, X87_OP_COM, X87_OP_COMP, X87_OP_DIV,
    X87_OP_DIVR, X87_OP_MUL, X87_OP_SUB, X87_OP_SUBR,
    REG_AH_BASE, REG_NONE,
    REG_RIP, REP_NONE, REP_REP, REP_REPNE, SEG_FS, SEG_GS, SEG_NONE,
    SH_SHL, SH_SHLD, SH_SHRD, SSE_PADDB, SSE_PAND, SSE_PANDN, SSE_PCMPEQB,
    SSE_PCMPEQD,
    SSE_PCMPEQW, SSE_PMINUB, SSE_PMOVMSKB, SSE_PADDQ, SSE_POR, SSE_PSHUFD,
    SSE_PSLLDQ,
    SSE_PSLLQ_I, SSE_PSRLQ_I, SSE_PINSRW, SSE_PEXTRW,
    SSE_PSRLDQ, SSE_PSUBB, SSE_PTEST, SSE_PUNPCKLDQ, SSE_PUNPCKLQDQ, SSE_PXOR,
    SSE_XORPS, STR_CMPS,
    STR_LODS, STR_MOVS, STR_SCAS, STR_STOS, UN_DEC, UN_INC, UN_NEG, UN_NOT,
    Uop,
)

MASK64 = (1 << 64) - 1
MAX_INSN_LEN = 15


class _Cursor:
    """Byte cursor over the instruction window."""

    def __init__(self, code: bytes):
        self.code = code
        self.pos = 0

    def peek(self) -> int:
        if self.pos >= len(self.code):
            raise _Truncated()
        return self.code[self.pos]

    def u8(self) -> int:
        b = self.peek()
        self.pos += 1
        return b

    def bytes(self, n: int) -> bytes:
        if self.pos + n > len(self.code):
            raise _Truncated()
        out = self.code[self.pos : self.pos + n]
        self.pos += n
        return out

    def i8(self) -> int:
        return struct.unpack("<b", self.bytes(1))[0]

    def i16(self) -> int:
        return struct.unpack("<h", self.bytes(2))[0]

    def i32(self) -> int:
        return struct.unpack("<i", self.bytes(4))[0]

    def u16(self) -> int:
        return struct.unpack("<H", self.bytes(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self.bytes(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self.bytes(8))[0]


class _Truncated(Exception):
    pass


class _Prefixes:
    def __init__(self):
        self.osize = False   # 66
        self.asize = False   # 67
        self.lock = False    # F0
        self.repne = False   # F2
        self.rep = False     # F3
        self.seg = SEG_NONE
        self.rex = 0         # 0 = no REX
        self.any_legacy = False   # any legacy prefix seen (VEX validity)
        self.rex_present = False  # a REX byte seen, even 0x40

    @property
    def rex_w(self) -> bool:
        return bool(self.rex & 8)

    @property
    def rex_r(self) -> int:
        return (self.rex >> 2) & 1

    @property
    def rex_x(self) -> int:
        return (self.rex >> 1) & 1

    @property
    def rex_b(self) -> int:
        return self.rex & 1

    def opsize(self) -> int:
        if self.rex_w:
            return 8
        if self.osize:
            return 2
        return 4


def _sx(value: int, bits: int) -> int:
    """Sign-extend `value` from `bits` to a Python int, then mask to 64."""
    sign = 1 << (bits - 1)
    return ((value ^ sign) - sign) & MASK64


def _gpr8(idx: int, pfx: _Prefixes) -> int:
    """8-bit register index: without REX, 4-7 encode ah/ch/dh/bh."""
    if pfx.rex == 0 and 4 <= idx <= 7:
        return REG_AH_BASE + (idx - 4)
    return idx


class _ModRM:
    """Parsed ModRM + SIB + displacement."""

    def __init__(self, cur: _Cursor, pfx: _Prefixes):
        byte = cur.u8()
        self.mod = byte >> 6
        self.reg = ((byte >> 3) & 7) | (pfx.rex_r << 3)
        rm = byte & 7
        self.is_mem = self.mod != 3
        self.rm_reg = rm | (pfx.rex_b << 3)
        self.base = REG_NONE
        self.index = REG_NONE
        self.scale = 1
        self.disp = 0

        if not self.is_mem:
            return

        if rm == 4:  # SIB
            sib = cur.u8()
            scale_bits = sib >> 6
            index = ((sib >> 3) & 7) | (pfx.rex_x << 3)
            base = (sib & 7) | (pfx.rex_b << 3)
            self.scale = 1 << scale_bits
            if index != 4:  # rsp can never be an index
                self.index = index
            if (base & 7) == 5 and self.mod == 0:
                self.disp = _sx(cur.u32(), 32)
            else:
                self.base = base
        elif rm == 5 and self.mod == 0:
            # RIP-relative
            self.base = REG_RIP
            self.disp = _sx(cur.u32(), 32)
            return
        else:
            self.base = rm | (pfx.rex_b << 3)

        if self.mod == 1:
            self.disp = _sx(cur.i8() & 0xFF, 8)
        elif self.mod == 2:
            self.disp = _sx(cur.u32(), 32)


def _apply_mem(uop: Uop, modrm: _ModRM, pfx: _Prefixes) -> None:
    uop.base_reg = modrm.base
    uop.idx_reg = modrm.index
    uop.scale = modrm.scale
    uop.disp = modrm.disp
    uop.seg = pfx.seg


def _rm_operand(uop: Uop, modrm: _ModRM, pfx: _Prefixes, is_dst: bool,
                size8: bool = False) -> None:
    """Set the r/m side (reg or mem) as dst or src."""
    if modrm.is_mem:
        _apply_mem(uop, modrm, pfx)
        if is_dst:
            uop.dst_kind = K_MEM
        else:
            uop.src_kind = K_MEM
    else:
        reg = _gpr8(modrm.rm_reg, pfx) if size8 else modrm.rm_reg
        if is_dst:
            uop.dst_kind, uop.dst_reg = K_REG, reg
        else:
            uop.src_kind, uop.src_reg = K_REG, reg


def _reg_operand(uop: Uop, modrm: _ModRM, pfx: _Prefixes, is_dst: bool,
                 size8: bool = False) -> None:
    reg = _gpr8(modrm.reg, pfx) if size8 else modrm.reg
    if is_dst:
        uop.dst_kind, uop.dst_reg = K_REG, reg
    else:
        uop.src_kind, uop.src_reg = K_REG, reg


def _imm_for(uop: Uop, cur: _Cursor, opsize: int, imm8: bool = False) -> None:
    """Standard immediate: imm8 sign-extended, else imm16/imm32 (imm32
    sign-extends to 64-bit opsize)."""
    uop.src_kind = K_IMM
    if imm8:
        uop.imm = _sx(cur.u8(), 8)
    elif opsize == 2:
        uop.imm = _sx(cur.u16(), 16)
    else:
        uop.imm = _sx(cur.u32(), 32)


def decode(code: bytes, gva: int = 0) -> Uop:
    """Decode one instruction from `code` (a window of up to 15 bytes at
    `gva`).  Always returns a Uop; undecodable input returns OPC_INVALID with
    length 1 so the executor can flag the lane rather than diverge."""
    try:
        uop = _decode_inner(code)
    except _Truncated:
        uop = Uop(opc=OPC_INVALID, length=1)
    except Exception:  # pragma: no cover - decoder bug guard
        uop = Uop(opc=OPC_INVALID, length=1)
    uop.raw = code[: uop.length]
    return uop


def _decode_prefixes(cur: _Cursor) -> _Prefixes:
    pfx = _Prefixes()
    while True:
        b = cur.peek()
        if b == 0x66:
            pfx.osize = True
        elif b == 0x67:
            pfx.asize = True
        elif b == 0xF0:
            pfx.lock = True
        elif b == 0xF2:
            pfx.repne = True
        elif b == 0xF3:
            pfx.rep = True
        elif b == 0x64:
            pfx.seg = SEG_FS
        elif b == 0x65:
            pfx.seg = SEG_GS
        elif b in (0x26, 0x2E, 0x36, 0x3E):
            pass  # es/cs/ss/ds overrides are no-ops in long mode
        else:
            break
        # only LOCK/66/F2/F3 #UD a following VEX; segment overrides are
        # legal before VEX (they scope its memory operand)
        if b in (0x66, 0xF0, 0xF2, 0xF3):
            pfx.any_legacy = True
        cur.pos += 1
    b = cur.peek()
    if 0x40 <= b <= 0x4F:
        pfx.rex = b & 0xF
        pfx.rex_present = True
        cur.pos += 1
    return pfx


def _decode_inner(code: bytes) -> Uop:
    cur = _Cursor(code[:MAX_INSN_LEN])
    pfx = _decode_prefixes(cur)
    op = cur.u8()
    uop = Uop()
    uop.a32 = int(pfx.asize)  # 67h: EA truncated to 32 bits (both engines)
    uop.lock = int(pfx.lock)

    if op in (0xC4, 0xC5) and not pfx.any_legacy and not pfx.rex_present:
        # VEX prefix (in long mode C4/C5 are always VEX; LES/LDS invalid).
        # Any legacy or REX prefix before VEX #UDs on hardware, so such
        # sequences fall through and decode invalid.
        _decode_vex(op, cur, pfx, uop)
    elif op == 0x0F:
        _decode_0f(cur, pfx, uop)
    else:
        _decode_primary(op, cur, pfx, uop)

    uop.length = cur.pos
    return uop


# ---------------------------------------------------------------------------
# VEX map — the BMI1/BMI2 scalar subset (AVX forms stay OPC_INVALID).
# Three-operand encoding convention: dst_reg = destination, the r/m goes
# through the normal src machinery (register or memory), and the VEX.vvvv
# register rides in `uop.cond` (unused by this opcode class otherwise).
# ---------------------------------------------------------------------------

def _decode_vex(op: int, cur: _Cursor, pfx: _Prefixes, uop: Uop) -> None:
    if op == 0xC5:  # 2-byte form: R.vvvv.L.pp, map = 0F
        b1 = cur.u8()
        r = (~b1 >> 7) & 1
        x = b = w = 0
        vvvv = (~b1 >> 3) & 0xF
        l_bit = (b1 >> 2) & 1
        pp = b1 & 3
        mmmmm = 1
    else:           # 3-byte form: RXB.mmmmm, W.vvvv.L.pp
        b1 = cur.u8()
        b2 = cur.u8()
        r = (~b1 >> 7) & 1
        x = (~b1 >> 6) & 1
        b = (~b1 >> 5) & 1
        mmmmm = b1 & 0x1F
        w = (b2 >> 7) & 1
        vvvv = (~b2 >> 3) & 0xF
        l_bit = (b2 >> 2) & 1
        pp = b2 & 3
    opc = cur.u8()
    # reuse the legacy ModRM machinery: VEX.RXB/W are REX-equivalent
    pfx.rex = (w << 3) | (r << 2) | (x << 1) | b
    opsize = 8 if w else 4

    if mmmmm == 1 and opc == 0x77 and pp == 0 and vvvv == 0:
        # pp/vvvv must be 0 — hardware #UDs otherwise.
        # L=1: vzeroall — zeroes the full registers (sub 0).
        # L=0: vzeroupper — zeroes only the upper YMM halves (sub 1);
        #      compilers emit it at AVX/SSE transition points.
        # Both execute on the device step (whole-file xmm limb writes,
        # step.py OPC_VZEROALL block).
        uop.opc, uop.sub = OPC_VZEROALL, (0 if l_bit else 1)
        return

    if l_bit:  # VEX.256 (AVX) — not in the scalar subset
        uop.opc = OPC_INVALID
        return

    if mmmmm == 1:
        # VEX.128 forms of the 0F map: delegate to the legacy decoder
        # with pp mapped onto the prefix flags.  Two-operand forms
        # (moves, packed converts, ucomis) require VEX.vvvv == 1111b
        # exactly like hardware; three-operand forms are accepted when
        # vvvv names the destination — src1 == dst degenerates to the
        # legacy read-modify-write semantics this pipeline models.  A
        # genuinely three-operand encoding (vvvv != dst) stays INVALID.
        pfx.osize = pp == 1
        pfx.rep = pp == 2
        pfx.repne = pp == 3
        _decode_0f_sse(opc, cur, pfx, uop)
        if uop.opc == OPC_INVALID:
            return
        mem = uop.mem_operand()
        three_op = opc in (0x51, 0x58, 0x59, 0x5C, 0x5D, 0x5E, 0x5F,
                           0xC2, 0x54, 0x55, 0x56, 0x57, 0x14, 0x15,
                           0xC6, 0x2A, 0xEF, 0xEB, 0xDB, 0xDF, 0x74,
                           0x75, 0x76, 0xF8, 0xFC, 0xDA, 0x6C, 0x62,
                           0xD4,
                           # 0x73: vpslldq/vpsrldq — VEX dst rides in vvvv,
                           # degenerate when it names the same register
                           0x73)
        scalar_regmov = opc in (0x10, 0x11) and pp in (2, 3) and not mem
        # scalar converts merge into vvvv (vcvtsd2ss etc.); packed 0x5A
        # forms are 2-operand
        scalar_cvt = opc == 0x5A and pp in (2, 3)
        # vmovlps/vmovhps: both the load (mem) and the hl/lh reg forms
        # merge into vvvv; the stores 0x13/0x17 are plain 2-operand
        half_mov = opc in (0x12, 0x16)
        if three_op or scalar_regmov or scalar_cvt or half_mov:
            ok = vvvv == uop.dst_reg
        else:
            ok = vvvv == 0
        if not ok:
            uop.opc = OPC_INVALID
        return

    if mmmmm == 2:  # 0F38 map
        if opc == 0xF2 and pp == 0:  # andn r, vvvv, r/m
            uop.opc, uop.sub, uop.opsize = OPC_PEXT, BMI_ANDN, opsize
            modrm = _ModRM(cur, pfx)
            _reg_operand(uop, modrm, pfx, is_dst=True)
            _rm_operand(uop, modrm, pfx, is_dst=False)
            uop.cond = vvvv
            return
        if opc == 0xF3 and pp == 0:  # blsr/blsmsk/blsi vvvv, r/m
            modrm = _ModRM(cur, pfx)
            group = {1: BMI_BLSR, 2: BMI_BLSMSK, 3: BMI_BLSI}
            digit = modrm.reg & 7  # opcode extension, not a register
            if digit not in group:
                uop.opc = OPC_INVALID
                return
            uop.opc, uop.sub, uop.opsize = OPC_PEXT, group[digit], opsize
            uop.dst_kind, uop.dst_reg = K_REG, vvvv
            _rm_operand(uop, modrm, pfx, is_dst=False)
            return
        if opc == 0xF5:  # bzhi (pp=0) / pext (F3) / pdep (F2): r, r/m, vvvv
            sub = {0: BMI_BZHI, 2: BMI_PEXT_, 3: BMI_PDEP}.get(pp)
            if sub is None:
                uop.opc = OPC_INVALID
                return
            uop.opc, uop.sub, uop.opsize = OPC_PEXT, sub, opsize
            modrm = _ModRM(cur, pfx)
            _reg_operand(uop, modrm, pfx, is_dst=True)
            _rm_operand(uop, modrm, pfx, is_dst=False)
            uop.cond = vvvv
            return
        if opc == 0xF7:  # bextr (pp=0) / shlx (66) / sarx (F3) / shrx (F2)
            sub = {0: BMI_BEXTR, 1: BMI_SHLX, 2: BMI_SARX, 3: BMI_SHRX}[pp]
            uop.opc, uop.sub, uop.opsize = OPC_PEXT, sub, opsize
            modrm = _ModRM(cur, pfx)
            _reg_operand(uop, modrm, pfx, is_dst=True)
            _rm_operand(uop, modrm, pfx, is_dst=False)
            uop.cond = vvvv
            return
        uop.opc = OPC_INVALID
        return
    if mmmmm == 3 and opc == 0xF0 and pp == 3:  # rorx r, r/m, imm8
        if vvvv != 0:  # encoded VEX.vvvv must be 1111b (hardware #UD)
            uop.opc = OPC_INVALID
            return
        uop.opc, uop.sub, uop.opsize = OPC_PEXT, BMI_RORX, opsize
        modrm = _ModRM(cur, pfx)
        _reg_operand(uop, modrm, pfx, is_dst=True)
        _rm_operand(uop, modrm, pfx, is_dst=False)
        uop.imm = cur.u8()
        return
    uop.opc = OPC_INVALID


# ---------------------------------------------------------------------------
# Primary (1-byte) opcode map
# ---------------------------------------------------------------------------

def _decode_primary(op: int, cur: _Cursor, pfx: _Prefixes, uop: Uop) -> None:
    opsize = pfx.opsize()

    # ALU block: 00-3D in groups of 8 per operation
    if op <= 0x3D and (op & 7) <= 5 and (op >> 3) <= 7:
        sub = op >> 3
        form = op & 7
        uop.opc, uop.sub = OPC_ALU, sub
        if form == 0:    # op r/m8, r8
            uop.opsize = 1
            modrm = _ModRM(cur, pfx)
            _rm_operand(uop, modrm, pfx, is_dst=True, size8=True)
            _reg_operand(uop, modrm, pfx, is_dst=False, size8=True)
        elif form == 1:  # op r/m, r
            uop.opsize = opsize
            modrm = _ModRM(cur, pfx)
            _rm_operand(uop, modrm, pfx, is_dst=True)
            _reg_operand(uop, modrm, pfx, is_dst=False)
        elif form == 2:  # op r8, r/m8
            uop.opsize = 1
            modrm = _ModRM(cur, pfx)
            _reg_operand(uop, modrm, pfx, is_dst=True, size8=True)
            _rm_operand(uop, modrm, pfx, is_dst=False, size8=True)
        elif form == 3:  # op r, r/m
            uop.opsize = opsize
            modrm = _ModRM(cur, pfx)
            _reg_operand(uop, modrm, pfx, is_dst=True)
            _rm_operand(uop, modrm, pfx, is_dst=False)
        elif form == 4:  # op al, imm8
            uop.opsize = 1
            uop.dst_kind, uop.dst_reg = K_REG, 0
            uop.src_kind, uop.imm = K_IMM, _sx(cur.u8(), 8)
        else:            # op rAX, imm
            uop.opsize = opsize
            uop.dst_kind, uop.dst_reg = K_REG, 0
            _imm_for(uop, cur, opsize)
        return

    if 0x50 <= op <= 0x57:  # push r64
        uop.opc = OPC_PUSH
        uop.opsize = 2 if pfx.osize else 8
        uop.src_kind, uop.src_reg = K_REG, (op & 7) | (pfx.rex_b << 3)
        return
    if 0x58 <= op <= 0x5F:  # pop r64
        uop.opc = OPC_POP
        uop.opsize = 2 if pfx.osize else 8
        uop.dst_kind, uop.dst_reg = K_REG, (op & 7) | (pfx.rex_b << 3)
        return

    if op == 0x63:  # movsxd r, r/m32
        uop.opc = OPC_MOV
        uop.opsize = opsize
        uop.srcsize, uop.sext = 4, 1
        modrm = _ModRM(cur, pfx)
        _reg_operand(uop, modrm, pfx, is_dst=True)
        _rm_operand(uop, modrm, pfx, is_dst=False)
        return

    if op == 0x68:  # push imm32 (sx to 64)
        uop.opc = OPC_PUSH
        uop.opsize = 8
        uop.src_kind, uop.imm = K_IMM, _sx(cur.u32(), 32)
        return
    if op == 0x6A:  # push imm8
        uop.opc = OPC_PUSH
        uop.opsize = 8
        uop.src_kind, uop.imm = K_IMM, _sx(cur.u8(), 8)
        return
    if op in (0x69, 0x6B):  # imul r, r/m, imm
        uop.opc, uop.sub = OPC_MUL, MUL_2OP
        uop.opsize = opsize
        modrm = _ModRM(cur, pfx)
        _reg_operand(uop, modrm, pfx, is_dst=True)
        _rm_operand(uop, modrm, pfx, is_dst=False)
        # the r/m is the multiplicand; the immediate is the multiplier
        if op == 0x69:
            uop.imm = _sx(cur.u32() if opsize != 2 else cur.u16(),
                          32 if opsize != 2 else 16)
        else:
            uop.imm = _sx(cur.u8(), 8)
        # mark the 3-operand form: src2 = imm (exec checks sub+has imm flag)
        uop.sext = 2  # sentinel: "imm is second source"
        return

    if 0x70 <= op <= 0x7F:  # jcc rel8
        uop.opc, uop.cond = OPC_JCC, op & 0xF
        uop.opsize = 8
        uop.imm = _sx(cur.u8(), 8)
        return

    if op in (0x80, 0x81, 0x83):  # group 1
        modrm = _ModRM(cur, pfx)
        uop.opc, uop.sub = OPC_ALU, modrm.reg & 7
        if op == 0x80:
            uop.opsize = 1
            _rm_operand(uop, modrm, pfx, is_dst=True, size8=True)
            uop.src_kind, uop.imm = K_IMM, _sx(cur.u8(), 8)
        else:
            uop.opsize = opsize
            _rm_operand(uop, modrm, pfx, is_dst=True)
            _imm_for(uop, cur, opsize, imm8=(op == 0x83))
        return

    if op in (0x84, 0x85):  # test r/m, r
        uop.opc, uop.sub = OPC_ALU, ALU_TEST
        size8 = op == 0x84
        uop.opsize = 1 if size8 else opsize
        modrm = _ModRM(cur, pfx)
        _rm_operand(uop, modrm, pfx, is_dst=True, size8=size8)
        _reg_operand(uop, modrm, pfx, is_dst=False, size8=size8)
        return

    if op in (0x86, 0x87):  # xchg r/m, r
        uop.opc = OPC_XCHG
        size8 = op == 0x86
        uop.opsize = 1 if size8 else opsize
        modrm = _ModRM(cur, pfx)
        _rm_operand(uop, modrm, pfx, is_dst=True, size8=size8)
        _reg_operand(uop, modrm, pfx, is_dst=False, size8=size8)
        return

    if op in (0x88, 0x89, 0x8A, 0x8B):  # mov
        uop.opc = OPC_MOV
        size8 = op in (0x88, 0x8A)
        to_rm = op in (0x88, 0x89)
        uop.opsize = 1 if size8 else opsize
        modrm = _ModRM(cur, pfx)
        if to_rm:
            _rm_operand(uop, modrm, pfx, is_dst=True, size8=size8)
            _reg_operand(uop, modrm, pfx, is_dst=False, size8=size8)
        else:
            _reg_operand(uop, modrm, pfx, is_dst=True, size8=size8)
            _rm_operand(uop, modrm, pfx, is_dst=False, size8=size8)
        return

    if op == 0x8D:  # lea
        uop.opc = OPC_LEA
        uop.opsize = opsize
        modrm = _ModRM(cur, pfx)
        if not modrm.is_mem:
            uop.opc = OPC_INVALID
            return
        _reg_operand(uop, modrm, pfx, is_dst=True)
        _apply_mem(uop, modrm, pfx)
        uop.seg = SEG_NONE  # lea ignores segment bases
        return

    if op == 0x8F:  # pop r/m
        uop.opc = OPC_POP
        uop.opsize = 2 if pfx.osize else 8
        modrm = _ModRM(cur, pfx)
        _rm_operand(uop, modrm, pfx, is_dst=True)
        return

    if op == 0x90:
        # nop (also F3 90 = pause)
        uop.opc = OPC_NOP
        return
    if 0x91 <= op <= 0x97:  # xchg rAX, r
        uop.opc = OPC_XCHG
        uop.opsize = opsize
        uop.dst_kind, uop.dst_reg = K_REG, (op & 7) | (pfx.rex_b << 3)
        uop.src_kind, uop.src_reg = K_REG, 0
        return

    if op == 0x98:  # cbw/cwde/cdqe
        uop.opc, uop.sub = OPC_CONVERT, 0
        uop.opsize = opsize
        return
    if op == 0x99:  # cwd/cdq/cqo
        uop.opc, uop.sub = OPC_CONVERT, 1
        uop.opsize = opsize
        return

    if op == 0x9C:
        uop.opc, uop.opsize = OPC_PUSHF, 8
        return
    if op == 0x9D:
        uop.opc, uop.opsize = OPC_POPF, 8
        return
    if op == 0x9E:
        uop.opc, uop.sub = OPC_FLAGOP, FL_SAHF
        return
    if op == 0x9F:
        uop.opc, uop.sub = OPC_FLAGOP, FL_LAHF
        return

    if op in (0xA8, 0xA9):  # test al/rAX, imm
        uop.opc, uop.sub = OPC_ALU, ALU_TEST
        uop.dst_kind, uop.dst_reg = K_REG, 0
        if op == 0xA8:
            uop.opsize = 1
            uop.src_kind, uop.imm = K_IMM, _sx(cur.u8(), 8)
        else:
            uop.opsize = opsize
            _imm_for(uop, cur, opsize)
        return

    if op in (0xA4, 0xA5, 0xA6, 0xA7, 0xAA, 0xAB, 0xAC, 0xAD, 0xAE, 0xAF):
        table = {
            0xA4: (STR_MOVS, 1), 0xA5: (STR_MOVS, opsize),
            0xA6: (STR_CMPS, 1), 0xA7: (STR_CMPS, opsize),
            0xAA: (STR_STOS, 1), 0xAB: (STR_STOS, opsize),
            0xAC: (STR_LODS, 1), 0xAD: (STR_LODS, opsize),
            0xAE: (STR_SCAS, 1), 0xAF: (STR_SCAS, opsize),
        }
        uop.opc = OPC_STRING
        uop.sub, uop.opsize = table[op]
        if pfx.rep:
            uop.rep = REP_REP
        elif pfx.repne:
            uop.rep = REP_REPNE
        return

    if 0xB0 <= op <= 0xB7:  # mov r8, imm8
        uop.opc = OPC_MOV
        uop.opsize = 1
        uop.dst_kind = K_REG
        uop.dst_reg = _gpr8((op & 7) | (pfx.rex_b << 3), pfx) \
            if pfx.rex == 0 else (op & 7) | (pfx.rex_b << 3)
        uop.src_kind, uop.imm = K_IMM, cur.u8()
        return
    if 0xB8 <= op <= 0xBF:  # mov r, imm(16/32/64)
        uop.opc = OPC_MOV
        uop.opsize = opsize
        uop.dst_kind, uop.dst_reg = K_REG, (op & 7) | (pfx.rex_b << 3)
        uop.src_kind = K_IMM
        if opsize == 8:
            uop.imm = cur.u64()
        elif opsize == 2:
            uop.imm = cur.u16()
        else:
            uop.imm = cur.u32()
        return

    if op in (0xC0, 0xC1, 0xD0, 0xD1, 0xD2, 0xD3):  # shift group 2
        modrm = _ModRM(cur, pfx)
        uop.opc, uop.sub = OPC_SHIFT, modrm.reg & 7
        size8 = op in (0xC0, 0xD0, 0xD2)
        uop.opsize = 1 if size8 else opsize
        _rm_operand(uop, modrm, pfx, is_dst=True, size8=size8)
        if op in (0xC0, 0xC1):
            uop.src_kind, uop.imm = K_IMM, cur.u8()
        elif op in (0xD0, 0xD1):
            uop.src_kind, uop.imm = K_IMM, 1
        else:  # D2/D3: count in cl
            uop.src_kind, uop.src_reg = K_REG, 1
            uop.srcsize = 1
        return

    if op == 0xC2:  # ret imm16
        uop.opc, uop.opsize = OPC_RET, 8
        uop.imm = cur.u16()
        return
    if op == 0xC3:
        uop.opc, uop.opsize = OPC_RET, 8
        return

    if op in (0xC6, 0xC7):  # mov r/m, imm
        modrm = _ModRM(cur, pfx)
        if modrm.reg & 7 != 0:
            uop.opc = OPC_INVALID
            return
        uop.opc = OPC_MOV
        if op == 0xC6:
            uop.opsize = 1
            _rm_operand(uop, modrm, pfx, is_dst=True, size8=True)
            uop.src_kind, uop.imm = K_IMM, cur.u8()
        else:
            uop.opsize = opsize
            _rm_operand(uop, modrm, pfx, is_dst=True)
            _imm_for(uop, cur, opsize)
        return

    if op == 0xC8:  # enter imm16, imm8 — level 0 only (nested frames are
        # a pre-386 idiom no 64-bit compiler emits); OPC_LEAVE sub 1
        size = cur.u16()
        level = cur.u8()
        if level != 0:
            uop.opc = OPC_INVALID
            return
        uop.opc, uop.sub, uop.opsize = OPC_LEAVE, 1, 8
        uop.imm = size
        return
    if op == 0xC9:
        uop.opc, uop.opsize = OPC_LEAVE, 8
        return

    if op == 0xCC:  # int3
        uop.opc, uop.sub = OPC_INT, 3
        return
    if op == 0xCD:  # int imm8
        uop.opc, uop.sub = OPC_INT, cur.u8()
        return
    if op in (0xCA, 0xCB):  # retf [imm16]: far return (sub 1)
        uop.opc, uop.sub = OPC_IRET, 1
        uop.opsize = 8  # 64-bit far returns pop qword rip + qword cs
        uop.imm = cur.u16() if op == 0xCA else 0
        return
    if op == 0xCF:  # iret / iretq (REX.W): kernel-mode interrupt return
        uop.opc = OPC_IRET
        uop.opsize = 8 if pfx.rex_w else 4
        return

    if op == 0xE3:  # jrcxz (67h: jecxz tests ECX — special cond 17)
        uop.opc, uop.cond = OPC_JCC, (17 if pfx.asize else 16)
        uop.opsize = 8
        uop.imm = _sx(cur.u8(), 8)
        return

    if op == 0xE8:  # call rel32
        uop.opc, uop.opsize = OPC_CALL, 8
        uop.src_kind, uop.imm = K_IMM, _sx(cur.u32(), 32)
        return
    if op == 0xE9:
        uop.opc, uop.opsize = OPC_JMP, 8
        uop.src_kind, uop.imm = K_IMM, _sx(cur.u32(), 32)
        return
    if op == 0xEB:
        uop.opc, uop.opsize = OPC_JMP, 8
        uop.src_kind, uop.imm = K_IMM, _sx(cur.u8(), 8)
        return

    if op == 0xF4:
        uop.opc = OPC_HLT
        return
    if op == 0xF5:
        uop.opc, uop.sub = OPC_FLAGOP, FL_CMC
        return

    if op in (0xF6, 0xF7):  # group 3
        modrm = _ModRM(cur, pfx)
        sub = modrm.reg & 7
        size8 = op == 0xF6
        size = 1 if size8 else pfx.opsize()
        if sub in (0, 1):  # test r/m, imm
            uop.opc, uop.sub = OPC_ALU, ALU_TEST
            uop.opsize = size
            _rm_operand(uop, modrm, pfx, is_dst=True, size8=size8)
            if size8:
                uop.src_kind, uop.imm = K_IMM, _sx(cur.u8(), 8)
            else:
                _imm_for(uop, cur, size)
        elif sub in (2, 3):  # not / neg
            uop.opc = OPC_UNARY
            uop.sub = UN_NOT if sub == 2 else UN_NEG
            uop.opsize = size
            _rm_operand(uop, modrm, pfx, is_dst=True, size8=size8)
        elif sub in (4, 5):  # mul / imul (widening)
            uop.opc = OPC_MUL
            uop.sub = MUL_WIDE_U if sub == 4 else MUL_WIDE_S
            uop.opsize = size
            _rm_operand(uop, modrm, pfx, is_dst=False, size8=size8)
        else:  # div / idiv
            uop.opc = OPC_DIV
            uop.sub = DIV_U if sub == 6 else DIV_S
            uop.opsize = size
            _rm_operand(uop, modrm, pfx, is_dst=False, size8=size8)
        return

    if op == 0xF8:
        uop.opc, uop.sub = OPC_FLAGOP, FL_CLC
        return
    if op == 0xF9:
        uop.opc, uop.sub = OPC_FLAGOP, FL_STC
        return
    if op == 0xFA:
        uop.opc, uop.sub = OPC_FLAGOP, FL_CLI
        return
    if op == 0xFB:
        uop.opc, uop.sub = OPC_FLAGOP, FL_STI
        return
    if op == 0xFC:
        uop.opc, uop.sub = OPC_FLAGOP, FL_CLD
        return
    if op == 0xFD:
        uop.opc, uop.sub = OPC_FLAGOP, FL_STD
        return

    if op == 0x9B:  # fwait: exception-check only; no deferred faults here
        uop.opc = OPC_NOP
        return

    if 0xD8 <= op <= 0xDF:  # x87 escape block
        _decode_x87(op, cur, pfx, uop)
        return

    if op == 0xFE:  # group 4: inc/dec r/m8
        modrm = _ModRM(cur, pfx)
        sub = modrm.reg & 7
        if sub > 1:
            uop.opc = OPC_INVALID
            return
        uop.opc = OPC_UNARY
        uop.sub = UN_INC if sub == 0 else UN_DEC
        uop.opsize = 1
        _rm_operand(uop, modrm, pfx, is_dst=True, size8=True)
        return

    if op == 0xFF:  # group 5
        modrm = _ModRM(cur, pfx)
        sub = modrm.reg & 7
        if sub == 0 or sub == 1:
            uop.opc = OPC_UNARY
            uop.sub = UN_INC if sub == 0 else UN_DEC
            uop.opsize = pfx.opsize()
            _rm_operand(uop, modrm, pfx, is_dst=True)
        elif sub == 2:  # call r/m64
            uop.opc, uop.opsize = OPC_CALL, 8
            _rm_operand(uop, modrm, pfx, is_dst=False)
        elif sub == 4:  # jmp r/m64
            uop.opc, uop.opsize = OPC_JMP, 8
            _rm_operand(uop, modrm, pfx, is_dst=False)
        elif sub == 6:  # push r/m64
            uop.opc = OPC_PUSH
            uop.opsize = 2 if pfx.osize else 8
            _rm_operand(uop, modrm, pfx, is_dst=False)
        else:
            uop.opc = OPC_INVALID
        return

    uop.opc = OPC_INVALID


# ---------------------------------------------------------------------------
# 0F (two-byte) opcode map
# ---------------------------------------------------------------------------

def _decode_0f(cur: _Cursor, pfx: _Prefixes, uop: Uop) -> None:
    op = cur.u8()
    opsize = pfx.opsize()

    if op == 0x38:
        _decode_0f38(cur, pfx, uop)
        return

    if op == 0x05:
        uop.opc = OPC_SYSCALL
        return
    if op == 0x0B:  # ud2
        uop.opc, uop.sub = OPC_INT, 6  # #UD
        return
    if op == 0x01:
        b = cur.u8()
        if b == 0xD0:       # xgetbv
            uop.opc = OPC_XGETBV
        elif b == 0xF8:     # swapgs
            uop.opc, uop.sub = OPC_RDGSBASE, 4
        else:
            uop.opc = OPC_INVALID
        return
    if op == 0x07:  # sysret
        uop.opc, uop.sub = OPC_SYSCALL, 1
        return
    if op in (0x20, 0x22):  # mov r64, crN / mov crN, r64
        modrm = _ModRM(cur, pfx)
        uop.opc = OPC_MOVCR
        uop.opsize = 8
        uop.sub = modrm.reg  # control register number (incl. REX.R for cr8)
        if op == 0x20:
            uop.dst_kind, uop.dst_reg = K_REG, modrm.rm_reg
            uop.sext = 0  # read from cr
        else:
            uop.src_kind, uop.src_reg = K_REG, modrm.rm_reg
            uop.sext = 1  # write to cr
        return
    if op == 0x0D:  # prefetchw
        _ModRM(cur, pfx)
        uop.opc = OPC_NOP
        return
    if op in (0x18, 0x19, 0x1A, 0x1B, 0x1C, 0x1D, 0x1E, 0x1F):
        # hint nop / multi-byte nop with modrm
        _ModRM(cur, pfx)
        uop.opc = OPC_NOP
        return

    if op == 0x31:
        uop.opc = OPC_RDTSC
        return
    if op == 0x30:  # wrmsr
        uop.opc, uop.sub = OPC_MSR, 1
        return
    if op == 0x32:  # rdmsr
        uop.opc, uop.sub = OPC_MSR, 0
        return
    if op == 0xA2:
        uop.opc = OPC_CPUID
        return

    if 0x40 <= op <= 0x4F:  # cmovcc
        uop.opc, uop.cond = OPC_CMOVCC, op & 0xF
        uop.opsize = opsize
        modrm = _ModRM(cur, pfx)
        _reg_operand(uop, modrm, pfx, is_dst=True)
        _rm_operand(uop, modrm, pfx, is_dst=False)
        return

    if 0x80 <= op <= 0x8F:  # jcc rel32
        uop.opc, uop.cond = OPC_JCC, op & 0xF
        uop.opsize = 8
        uop.imm = _sx(cur.u32(), 32)
        return

    if 0x90 <= op <= 0x9F:  # setcc r/m8
        uop.opc, uop.cond = OPC_SETCC, op & 0xF
        uop.opsize = 1
        modrm = _ModRM(cur, pfx)
        _rm_operand(uop, modrm, pfx, is_dst=True, size8=True)
        return

    if op in (0xA3, 0xAB, 0xB3, 0xBB):  # bt/bts/btr/btc r/m, r
        subs = {0xA3: BT_BT, 0xAB: BT_BTS, 0xB3: BT_BTR, 0xBB: BT_BTC}
        uop.opc, uop.sub = OPC_BT, subs[op]
        uop.opsize = opsize
        modrm = _ModRM(cur, pfx)
        _rm_operand(uop, modrm, pfx, is_dst=True)
        _reg_operand(uop, modrm, pfx, is_dst=False)
        return
    if op == 0xBA:  # group 8: bt r/m, imm8
        modrm = _ModRM(cur, pfx)
        sub = modrm.reg & 7
        if sub < 4:
            uop.opc = OPC_INVALID
            return
        uop.opc, uop.sub = OPC_BT, sub - 4
        uop.opsize = opsize
        _rm_operand(uop, modrm, pfx, is_dst=True)
        uop.src_kind, uop.imm = K_IMM, cur.u8()
        return

    if op in (0xA4, 0xA5, 0xAC, 0xAD):  # shld/shrd
        uop.opc = OPC_SHIFT
        uop.sub = SH_SHLD if op in (0xA4, 0xA5) else SH_SHRD
        uop.opsize = opsize
        modrm = _ModRM(cur, pfx)
        _rm_operand(uop, modrm, pfx, is_dst=True)
        _reg_operand(uop, modrm, pfx, is_dst=False)
        if op in (0xA4, 0xAC):
            uop.imm = cur.u8()
            uop.sext = 3  # sentinel: count in imm
        else:
            uop.sext = 4  # sentinel: count in cl
        return

    if op == 0xAE:
        # group 15: fences; ldmxcsr/stmxcsr and fxsave/fxrstor are real
        # state movers (oracle-serviced via OPC_X87); F3-prefixed
        # register forms are rd/wrfsbase+rd/wrgsbase
        modrm = _ModRM(cur, pfx)
        sub = modrm.reg & 7
        if pfx.rep and not modrm.is_mem and sub in (0, 1, 2, 3):
            # rdfsbase/rdgsbase/wrfsbase/wrgsbase r32/r64
            uop.opc, uop.sub = OPC_RDGSBASE, sub
            uop.opsize = 8 if pfx.rex_w else 4
            uop.dst_kind, uop.dst_reg = K_REG, modrm.rm_reg
        elif not modrm.is_mem and sub in (5, 6, 7):  # l/m/sfence
            uop.opc = OPC_FENCE
        elif modrm.is_mem and sub in (0, 1, 2, 3, 4, 5):
            uop.opc = OPC_X87
            uop.sub = {0: X87_FXSAVE, 1: X87_FXRSTOR,
                       2: X87_LDMXCSR, 3: X87_STMXCSR,
                       4: X87_XSAVE, 5: X87_XRSTOR}[sub]
            _apply_mem(uop, modrm, pfx)
            uop.src_kind = K_MEM  # address carrier
            if sub in (2, 3):
                uop.srcsize = 4  # mxcsr dword (device load/store width)
        else:
            uop.opc = OPC_INVALID  # clflush/clwb out of subset
        return

    if op == 0xAF:  # imul r, r/m
        uop.opc, uop.sub = OPC_MUL, MUL_2OP
        uop.opsize = opsize
        modrm = _ModRM(cur, pfx)
        _reg_operand(uop, modrm, pfx, is_dst=True)
        _rm_operand(uop, modrm, pfx, is_dst=False)
        return

    if op in (0xB0, 0xB1):  # cmpxchg
        uop.opc = OPC_CMPXCHG
        size8 = op == 0xB0
        uop.opsize = 1 if size8 else opsize
        modrm = _ModRM(cur, pfx)
        _rm_operand(uop, modrm, pfx, is_dst=True, size8=size8)
        _reg_operand(uop, modrm, pfx, is_dst=False, size8=size8)
        return

    if op in (0xB6, 0xB7, 0xBE, 0xBF):  # movzx / movsx
        uop.opc = OPC_MOV
        uop.opsize = opsize
        uop.srcsize = 1 if op in (0xB6, 0xBE) else 2
        uop.sext = 1 if op in (0xBE, 0xBF) else 0
        modrm = _ModRM(cur, pfx)
        _reg_operand(uop, modrm, pfx, is_dst=True)
        _rm_operand(uop, modrm, pfx, is_dst=False, size8=(uop.srcsize == 1))
        return

    if op in (0xBC, 0xBD):  # bsf/bsr (F3: tzcnt/lzcnt)
        uop.opc = OPC_BITSCAN
        if pfx.rep:
            uop.sub = BS_TZCNT if op == 0xBC else BS_LZCNT
        else:
            uop.sub = BS_BSF if op == 0xBC else BS_BSR
        uop.opsize = opsize
        modrm = _ModRM(cur, pfx)
        _reg_operand(uop, modrm, pfx, is_dst=True)
        _rm_operand(uop, modrm, pfx, is_dst=False)
        return

    if op == 0xB8 and pfx.rep:  # popcnt
        uop.opc, uop.sub = OPC_BITSCAN, BS_POPCNT
        uop.opsize = opsize
        modrm = _ModRM(cur, pfx)
        _reg_operand(uop, modrm, pfx, is_dst=True)
        _rm_operand(uop, modrm, pfx, is_dst=False)
        return

    if op in (0xC0, 0xC1):  # xadd
        uop.opc = OPC_XADD
        size8 = op == 0xC0
        uop.opsize = 1 if size8 else opsize
        modrm = _ModRM(cur, pfx)
        _rm_operand(uop, modrm, pfx, is_dst=True, size8=size8)
        _reg_operand(uop, modrm, pfx, is_dst=False, size8=size8)
        return

    if op == 0xC7:  # group 9: rdrand / rdseed (/6, /7 reg forms)
        modrm = _ModRM(cur, pfx)
        sub = modrm.reg & 7
        if not modrm.is_mem and sub in (6, 7):
            uop.opc = OPC_RDRAND
            uop.opsize = opsize
            uop.dst_kind, uop.dst_reg = K_REG, modrm.rm_reg
        else:
            uop.opc = OPC_INVALID  # cmpxchg16b unsupported for now
        return

    if 0xC8 <= op <= 0xCF:  # bswap
        uop.opc = OPC_BSWAP
        uop.opsize = 8 if pfx.rex_w else 4
        uop.dst_kind, uop.dst_reg = K_REG, (op & 7) | (pfx.rex_b << 3)
        return

    _decode_0f_sse(op, cur, pfx, uop)


def _decode_x87(op: int, cur: _Cursor, pfx: _Prefixes, uop: Uop) -> None:
    """x87 escape block D8-DF (OPC_X87; executes on the device except
    the FXSAVE-class state movers, interp/step.py).

    Covers the load/store/arith/compare/control subset MSVC and CRT
    helpers emit around `long double` and legacy math paths; the
    transcendental/BCD/env instructions stay INVALID -> oracle
    UnsupportedInsn.  Census note (tools/decode_census.py): x87 is ~0% of
    modern Windows x64 .text — the x64 ABI is SSE-based and kernel code
    may not use the FPU at all — so this subset is about not
    false-crashing the stragglers, not about throughput."""
    uop.opc = OPC_X87
    modbyte = cur.peek()
    if modbyte < 0xC0:  # memory form: reg digit selects the operation
        modrm = _ModRM(cur, pfx)
        digit = modrm.reg & 7
        _apply_mem(uop, modrm, pfx)
        uop.src_kind = K_MEM  # address carrier for exec
        if op in (0xD8, 0xDC):  # fadd/fmul/fcom(p)/fsub(r)/fdiv(r) m32/m64
            uop.sub = X87_ARITH_M
            uop.cond = digit
            uop.srcsize = 4 if op == 0xD8 else 8
            if digit == X87_OP_COMP:
                uop.sext = 1  # fcomp pops
            return
        table = {
            (0xD9, 0): (X87_FLD_M, 4, 0), (0xD9, 2): (X87_FST_M, 4, 0),
            (0xD9, 3): (X87_FST_M, 4, 1), (0xD9, 5): (X87_FLDCW, 2, 0),
            (0xD9, 7): (X87_FNSTCW, 2, 0),
            (0xDD, 0): (X87_FLD_M, 8, 0), (0xDD, 2): (X87_FST_M, 8, 0),
            (0xDD, 3): (X87_FST_M, 8, 1), (0xDD, 7): (X87_FNSTSW_M, 2, 0),
            (0xDB, 0): (X87_FILD, 4, 0), (0xDB, 1): (X87_FIST_T, 4, 1),
            (0xDB, 2): (X87_FIST, 4, 0), (0xDB, 3): (X87_FIST, 4, 1),
            (0xDD, 1): (X87_FIST_T, 8, 1),
            (0xDF, 0): (X87_FILD, 2, 0), (0xDF, 2): (X87_FIST, 2, 0),
            (0xDF, 3): (X87_FIST, 2, 1), (0xDF, 5): (X87_FILD, 8, 0),
            (0xDF, 7): (X87_FIST, 8, 1),
        }
        entry = table.get((op, digit))
        if entry is None:  # m80, fldenv/fstenv, fbld... out of subset
            uop.opc = OPC_INVALID
            return
        uop.sub, uop.srcsize, uop.sext = entry
        return

    # register form
    cur.u8()  # consume the modrm byte
    i = modbyte & 7
    uop.imm = i
    _DSTI_SWAP = {X87_OP_SUB: X87_OP_SUBR, X87_OP_SUBR: X87_OP_SUB,
                  X87_OP_DIV: X87_OP_DIVR, X87_OP_DIVR: X87_OP_DIV}
    if op in (0xD8, 0xDC):  # arith st/st(i); DC: st(i) is the destination
        uop.sub = X87_ARITH_ST
        uop.cond = (modbyte >> 3) & 7
        uop.dst_reg = 1 if op == 0xDC else 0
        if op == 0xD8 and uop.cond == X87_OP_COMP:
            uop.sext = 1
        if op == 0xDC and uop.cond in (X87_OP_COM, X87_OP_COMP):
            uop.opc = OPC_INVALID  # DC D0+ forms are reserved
        if op == 0xDC:
            # the SDM's famous reversal: with st(i) as destination the
            # encoded digit means the OPPOSITE sub/div direction
            uop.cond = _DSTI_SWAP.get(uop.cond, uop.cond)
        return
    if op == 0xDE:
        if modbyte == 0xD9:  # fcompp
            uop.sub, uop.cond, uop.sext = X87_COM, 0, 2
            return
        if (modbyte >> 3) & 7 in (X87_OP_COM, X87_OP_COMP):
            uop.opc = OPC_INVALID
            return
        uop.sub = X87_ARITH_ST  # faddp/fmulp/fsub(r)p/fdiv(r)p st(i), st
        uop.cond = _DSTI_SWAP.get((modbyte >> 3) & 7, (modbyte >> 3) & 7)
        uop.dst_reg = 1
        uop.sext = 1
        return
    if op == 0xD9:
        if modbyte <= 0xC7:
            uop.sub = X87_FLD_STI
        elif modbyte <= 0xCF:
            uop.sub = X87_FXCH
        elif modbyte == 0xD0:
            uop.opc = OPC_NOP  # fnop
        elif modbyte == 0xE0:
            uop.sub = X87_FCHS
        elif modbyte == 0xE1:
            uop.sub = X87_FABS
        elif modbyte == 0xE8:
            uop.sub, uop.imm = X87_FLD_CONST, 0  # fld1
        elif modbyte == 0xEE:
            uop.sub, uop.imm = X87_FLD_CONST, 1  # fldz
        else:  # fptan/fsin/f2xm1... out of subset
            uop.opc = OPC_INVALID
        return
    if op == 0xDD:
        if 0xC0 <= modbyte <= 0xC7:
            uop.sub = X87_FFREE
        elif 0xD0 <= modbyte <= 0xD7:
            uop.sub = X87_FST_STI
        elif 0xD8 <= modbyte <= 0xDF:
            uop.sub, uop.sext = X87_FST_STI, 1
        elif 0xE0 <= modbyte <= 0xE7:
            uop.sub, uop.cond = X87_COM, 0  # fucom
        elif 0xE8 <= modbyte <= 0xEF:
            uop.sub, uop.cond, uop.sext = X87_COM, 0, 1  # fucomp
        else:
            uop.opc = OPC_INVALID
        return
    if op == 0xDB:
        if modbyte == 0xE2:
            uop.sub = X87_FNCLEX
        elif modbyte == 0xE3:
            uop.sub = X87_FNINIT
        elif 0xE8 <= modbyte <= 0xF7:  # fucomi / fcomi
            uop.sub = X87_COMI
        else:  # fcmovcc out of subset
            uop.opc = OPC_INVALID
        return
    if op == 0xDF:
        if modbyte == 0xE0:
            uop.sub = X87_FNSTSW_AX
        elif 0xE8 <= modbyte <= 0xF7:  # fucomip / fcomip
            uop.sub, uop.sext = X87_COMI, 1
        else:
            uop.opc = OPC_INVALID
        return
    if op == 0xDA:
        if modbyte == 0xE9:  # fucompp
            uop.sub, uop.cond, uop.sext = X87_COM, 0, 2
            return
        uop.opc = OPC_INVALID  # fcmovcc out of subset
        return
    uop.opc = OPC_INVALID


def _decode_0f_sse(op: int, cur: _Cursor, pfx: _Prefixes, uop: Uop) -> None:
    """XMM data movement + bitwise ops (the subset memcpy/strcmp-style code
    uses).  dst/src kind K_XMM means the register index refers to xmm0-15."""

    def xmm_rm(modrm: _ModRM, is_dst: bool) -> None:
        if modrm.is_mem:
            _apply_mem(uop, modrm, pfx)
            if is_dst:
                uop.dst_kind = K_MEM
            else:
                uop.src_kind = K_MEM
        else:
            if is_dst:
                uop.dst_kind, uop.dst_reg = K_XMM, modrm.rm_reg
            else:
                uop.src_kind, uop.src_reg = K_XMM, modrm.rm_reg

    def xmm_reg(modrm: _ModRM, is_dst: bool) -> None:
        if is_dst:
            uop.dst_kind, uop.dst_reg = K_XMM, modrm.reg
        else:
            uop.src_kind, uop.src_reg = K_XMM, modrm.reg

    if op == 0x77 and not (pfx.osize or pfx.rep or pfx.repne):
        uop.opc, uop.sub = OPC_X87, X87_EMMS  # clears the x87 tag word
        return

    # movlps/movhps family (66 = movlpd/movhpd, integer-identical; the
    # F3/F2 forms movsldup/movddup are out of the subset).  sub 4 = low
    # qword, sub 5 = high qword; reg forms are movhlps (src HIGH -> dst
    # low) and movlhps (src LOW -> dst high).
    if op in (0x12, 0x13, 0x16, 0x17):
        if pfx.rep or pfx.repne:
            uop.opc = OPC_INVALID
            return
        uop.opc = OPC_SSEMOV
        uop.opsize = 8
        uop.sub = 4 if op in (0x12, 0x13) else 5
        modrm = _ModRM(cur, pfx)
        if op in (0x12, 0x16):  # load (or reg-to-reg half move)
            if not modrm.is_mem and pfx.osize:
                uop.opc = OPC_INVALID  # movlpd/movhpd require memory
                return
            xmm_reg(modrm, is_dst=True)
            xmm_rm(modrm, is_dst=False)
        else:                   # store: memory only
            if not modrm.is_mem:
                uop.opc = OPC_INVALID
                return
            xmm_rm(modrm, is_dst=True)
            xmm_reg(modrm, is_dst=False)
        return

    # movups/movupd/movss/movsd and movaps/movapd (alignment not enforced)
    if op in (0x10, 0x28):
        uop.opc = OPC_SSEMOV
        uop.opsize = 16
        if op == 0x10 and pfx.rep:
            uop.opsize = 4    # movss
        elif op == 0x10 and pfx.repne:
            uop.opsize = 8    # movsd
        modrm = _ModRM(cur, pfx)
        xmm_reg(modrm, is_dst=True)
        xmm_rm(modrm, is_dst=False)
        return
    if op in (0x11, 0x29):
        uop.opc = OPC_SSEMOV
        uop.opsize = 16
        if op == 0x11 and pfx.rep:
            uop.opsize = 4
        elif op == 0x11 and pfx.repne:
            uop.opsize = 8
        modrm = _ModRM(cur, pfx)
        xmm_rm(modrm, is_dst=True)
        xmm_reg(modrm, is_dst=False)
        return

    if op in (0x6F, 0x7F):  # movdqa/movdqu (66 / F3)
        uop.opc = OPC_SSEMOV
        uop.opsize = 16
        modrm = _ModRM(cur, pfx)
        if op == 0x6F:
            xmm_reg(modrm, is_dst=True)
            xmm_rm(modrm, is_dst=False)
        else:
            xmm_rm(modrm, is_dst=True)
            xmm_reg(modrm, is_dst=False)
        return

    if op == 0x6E:  # movd/movq xmm, r/m
        uop.opc = OPC_SSEMOV
        uop.opsize = 8 if pfx.rex_w else 4
        uop.sub = 1  # gpr->xmm (zero upper)
        modrm = _ModRM(cur, pfx)
        xmm_reg(modrm, is_dst=True)
        _rm_operand(uop, modrm, pfx, is_dst=False)
        return
    if op == 0x7E:
        uop.opc = OPC_SSEMOV
        modrm = _ModRM(cur, pfx)
        if pfx.rep:  # movq xmm, xmm/m64 (zeroes the upper lane, unlike movsd)
            uop.opsize = 8
            uop.sub = 3
            xmm_reg(modrm, is_dst=True)
            xmm_rm(modrm, is_dst=False)
        else:  # movd/movq r/m, xmm
            uop.opsize = 8 if pfx.rex_w else 4
            uop.sub = 2  # xmm->gpr
            _rm_operand(uop, modrm, pfx, is_dst=True)
            xmm_reg(modrm, is_dst=False)
        return
    if op == 0xD6:  # movq xmm/m64, xmm (zeroes upper when dst is a register)
        uop.opc = OPC_SSEMOV
        uop.opsize = 8
        uop.sub = 3
        modrm = _ModRM(cur, pfx)
        xmm_rm(modrm, is_dst=True)
        xmm_reg(modrm, is_dst=False)
        return

    # ---- SSE/SSE2 floating point (OPC_SSEFP; oracle-serviced) ----------
    # The dominant decode gap measured on real Windows-PE codegen (VERDICT
    # r3 item 3; tools/decode_census.py).  Element width + packedness from
    # the prefix: F2 = sd, F3 = ss, 66 = pd, none = ps — stored in
    # srcsize (4/8) and sext (1 = packed).
    def fp_elem():
        if pfx.repne:
            return 8, 0   # scalar double
        if pfx.rep:
            return 4, 0   # scalar single
        if pfx.osize:
            return 8, 1   # packed double
        return 4, 1       # packed single

    _FP_ARITH = {0x51: FP_SQRT, 0x58: FP_ADD, 0x59: FP_MUL, 0x5C: FP_SUB,
                 0x5D: FP_MIN, 0x5E: FP_DIV, 0x5F: FP_MAX}
    if op in _FP_ARITH:
        uop.opc, uop.sub = OPC_SSEFP, _FP_ARITH[op]
        uop.srcsize, uop.sext = fp_elem()
        uop.opsize = 16
        modrm = _ModRM(cur, pfx)
        xmm_reg(modrm, is_dst=True)
        xmm_rm(modrm, is_dst=False)
        return

    if op in (0x2E, 0x2F):  # ucomiss/sd, comiss/sd: rflags only
        if pfx.rep or pfx.repne:
            uop.opc = OPC_INVALID
            return
        uop.opc = OPC_SSEFP
        uop.sub = FP_UCOMI if op == 0x2E else FP_COMI
        uop.srcsize, uop.sext = (8 if pfx.osize else 4), 0
        uop.opsize = 16
        modrm = _ModRM(cur, pfx)
        xmm_reg(modrm, is_dst=True)  # compared reg; no writeback
        xmm_rm(modrm, is_dst=False)
        return

    if op == 0xC2:  # cmpps/ss/pd/sd imm8 predicate -> mask
        uop.opc, uop.sub = OPC_SSEFP, FP_CMP
        uop.srcsize, uop.sext = fp_elem()
        uop.opsize = 16
        modrm = _ModRM(cur, pfx)
        xmm_reg(modrm, is_dst=True)
        xmm_rm(modrm, is_dst=False)
        uop.imm = cur.u8()
        return

    if op == 0x2A:  # cvtsi2ss/sd (gpr/mem int -> fp scalar)
        if not (pfx.rep or pfx.repne):
            uop.opc = OPC_INVALID  # MMX cvtpi2ps out of scope
            return
        uop.opc, uop.sub = OPC_SSEFP, FP_CVT_I2F
        uop.srcsize, uop.sext = (8 if pfx.repne else 4), 0
        uop.opsize = 8 if pfx.rex_w else 4  # integer operand width
        modrm = _ModRM(cur, pfx)
        xmm_reg(modrm, is_dst=True)
        _rm_operand(uop, modrm, pfx, is_dst=False)
        return

    if op in (0x2C, 0x2D):  # cvtt/cvt ss/sd -> gpr
        if not (pfx.rep or pfx.repne):
            uop.opc = OPC_INVALID
            return
        uop.opc = OPC_SSEFP
        uop.sub = FP_CVT_F2I_T if op == 0x2C else FP_CVT_F2I
        uop.srcsize, uop.sext = (8 if pfx.repne else 4), 0
        uop.opsize = 8 if pfx.rex_w else 4
        modrm = _ModRM(cur, pfx)
        _reg_operand(uop, modrm, pfx, is_dst=True)
        xmm_rm(modrm, is_dst=False)
        return

    if op == 0x5A:  # cvtss2sd/cvtsd2ss/cvtps2pd/cvtpd2ps
        uop.opc, uop.sub = OPC_SSEFP, FP_CVT_F2F
        uop.srcsize, uop.sext = fp_elem()  # SOURCE element type
        uop.opsize = 16
        modrm = _ModRM(cur, pfx)
        xmm_reg(modrm, is_dst=True)
        xmm_rm(modrm, is_dst=False)
        return

    if op == 0x5B:  # cvtdq2ps / cvtps2dq (66) / cvttps2dq (F3)
        if pfx.repne:
            uop.opc = OPC_INVALID
            return
        uop.opc = OPC_SSEFP
        uop.sub = (FP_CVT_PS2DQ_T if pfx.rep
                   else FP_CVT_PS2DQ if pfx.osize else FP_CVT_DQ2PS)
        uop.srcsize, uop.sext = 4, 1
        uop.opsize = 16
        modrm = _ModRM(cur, pfx)
        xmm_reg(modrm, is_dst=True)
        xmm_rm(modrm, is_dst=False)
        return

    if op == 0xE6:  # cvtdq2pd (F3) / cvtpd2dq (F2) / cvttpd2dq (66)
        if pfx.rep:
            sub = FP_CVT_DQ2PD
        elif pfx.repne:
            sub = FP_CVT_PD2DQ
        elif pfx.osize:
            sub = FP_CVT_PD2DQ_T
        else:
            uop.opc = OPC_INVALID  # bare E6 is MMX-era invalid
            return
        uop.opc, uop.sub = OPC_SSEFP, sub
        uop.srcsize, uop.sext = 8, 1
        uop.opsize = 16
        modrm = _ModRM(cur, pfx)
        xmm_reg(modrm, is_dst=True)
        xmm_rm(modrm, is_dst=False)
        return

    if op in (0x14, 0x15):  # unpcklps/pd, unpckhps/pd
        if pfx.rep or pfx.repne:
            uop.opc = OPC_INVALID
            return
        uop.opc = OPC_SSEFP
        uop.sub = FP_UNPCKL if op == 0x14 else FP_UNPCKH
        uop.srcsize, uop.sext = (8 if pfx.osize else 4), 1
        uop.opsize = 16
        modrm = _ModRM(cur, pfx)
        xmm_reg(modrm, is_dst=True)
        xmm_rm(modrm, is_dst=False)
        return

    if op == 0xC6:  # shufps/shufpd imm8
        if pfx.rep or pfx.repne:
            uop.opc = OPC_INVALID
            return
        uop.opc, uop.sub = OPC_SSEFP, FP_SHUF
        uop.srcsize, uop.sext = (8 if pfx.osize else 4), 1
        uop.opsize = 16
        modrm = _ModRM(cur, pfx)
        xmm_reg(modrm, is_dst=True)
        xmm_rm(modrm, is_dst=False)
        uop.imm = cur.u8()
        return

    sse_table = {
        0x57: SSE_XORPS, 0xEF: SSE_PXOR, 0xEB: SSE_POR, 0xDB: SSE_PAND,
        0xDF: SSE_PANDN, 0x74: SSE_PCMPEQB, 0x75: SSE_PCMPEQW,
        0x76: SSE_PCMPEQD, 0xF8: SSE_PSUBB, 0xFC: SSE_PADDB,
        0xDA: SSE_PMINUB, 0x6C: SSE_PUNPCKLQDQ,
        # andps/andnps/orps and the pd forms: bitwise-identical to the
        # integer logicals for every prefix variant (like 0x57 above)
        0x54: SSE_PAND, 0x55: SSE_PANDN, 0x56: SSE_POR,
    }
    if op in (0x62, 0xD4):  # punpckldq / paddq: 66-prefixed only (no MMX)
        if not pfx.osize:
            uop.opc = OPC_INVALID
            return
        sse_table[0x62] = SSE_PUNPCKLDQ
        sse_table[0xD4] = SSE_PADDQ
    if op in sse_table:
        uop.opc, uop.sub = OPC_SSEALU, sse_table[op]
        uop.opsize = 16
        modrm = _ModRM(cur, pfx)
        xmm_reg(modrm, is_dst=True)
        xmm_rm(modrm, is_dst=False)
        return

    if op == 0xC4 and pfx.osize:  # pinsrw xmm, r32/m16, imm8
        uop.opc, uop.sub = OPC_SSEALU, SSE_PINSRW
        uop.opsize = 16
        modrm = _ModRM(cur, pfx)
        uop.dst_kind, uop.dst_reg = K_XMM, modrm.reg
        if modrm.is_mem:
            _apply_mem(uop, modrm, pfx)
            uop.src_kind = K_MEM
            uop.srcsize = 2
        else:
            uop.src_kind, uop.src_reg = K_REG, modrm.rm_reg
        uop.cond = cur.u8() & 7  # word index rides in cond (imm is data)
        return
    if op == 0xC5 and pfx.osize:  # pextrw r32, xmm, imm8
        modrm = _ModRM(cur, pfx)
        if modrm.is_mem:
            uop.opc = OPC_INVALID  # mem form is SSE4.1 (0F 3A 15)
            return
        uop.opc, uop.sub = OPC_SSEALU, SSE_PEXTRW
        uop.opsize = 4
        uop.dst_kind, uop.dst_reg = K_REG, modrm.reg
        uop.src_kind, uop.src_reg = K_XMM, modrm.rm_reg
        uop.cond = cur.u8() & 7
        return

    if op == 0xD7:  # pmovmskb r, xmm
        uop.opc, uop.sub = OPC_SSEALU, SSE_PMOVMSKB
        uop.opsize = 4
        modrm = _ModRM(cur, pfx)
        _reg_operand(uop, modrm, pfx, is_dst=True)
        if modrm.is_mem:
            uop.opc = OPC_INVALID
            return
        uop.src_kind, uop.src_reg = K_XMM, modrm.rm_reg
        return

    if op == 0x70 and pfx.osize:  # pshufd xmm, xmm/m128, imm8
        uop.opc, uop.sub = OPC_SSEALU, SSE_PSHUFD
        uop.opsize = 16
        modrm = _ModRM(cur, pfx)
        xmm_reg(modrm, is_dst=True)
        xmm_rm(modrm, is_dst=False)
        uop.imm = cur.u8()
        return

    if op == 0x73 and pfx.osize:  # group 14: psrlq/psllq/psrldq/pslldq imm8
        modrm = _ModRM(cur, pfx)
        sub = modrm.reg & 7
        if modrm.is_mem or sub not in (2, 3, 6, 7):
            uop.opc = OPC_INVALID
            return
        uop.opc = OPC_SSEALU
        uop.sub = {2: SSE_PSRLQ_I, 3: SSE_PSRLDQ,
                   6: SSE_PSLLQ_I, 7: SSE_PSLLDQ}[sub]
        uop.opsize = 16
        uop.dst_kind, uop.dst_reg = K_XMM, modrm.rm_reg
        uop.src_kind, uop.imm = K_IMM, cur.u8()
        return

    uop.opc = OPC_INVALID


def _decode_0f38(cur: _Cursor, pfx: _Prefixes, uop: Uop) -> None:
    op = cur.u8()
    if op == 0x17 and pfx.osize:  # ptest
        uop.opc, uop.sub = OPC_SSEALU, SSE_PTEST
        uop.opsize = 16
        modrm = _ModRM(cur, pfx)
        if modrm.is_mem:
            _apply_mem(uop, modrm, pfx)
            uop.src_kind = K_MEM
        else:
            uop.src_kind, uop.src_reg = K_XMM, modrm.rm_reg
        uop.dst_kind, uop.dst_reg = K_XMM, modrm.reg
        uop.sext = 5  # sentinel: flag-only (no writeback)
        return
    uop.opc = OPC_INVALID
