"""Interpreter core: uop encoding, host decoder, executors.

The TPU-native replacement for the reference's bochscpu emulator layer
(SURVEY.md §2.6): decode once on host, execute batched on device.
"""
