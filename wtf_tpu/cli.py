"""CLI entry point: `python -m wtf_tpu {master|fuzz|run|campaign}`.

Mirror of the reference's wtf.cc:33-371 (CLI11 subcommands + path
defaulting) and subcommands.cc:16-101 (drivers):

  run       replay input file/dir on a backend, optional rip/cov trace
            (RunSubcommand, subcommands.cc:16-92)
  fuzz      node loop: dial the master, execute, report
            (FuzzSubcommand -> Client_t::Run, subcommands.cc:94-97)
  master    testcase server: corpus, mutation, coverage aggregation
            (MasterSubcommand -> Server_t::Run, subcommands.cc:99-101)
  campaign  single-process fused master+node over one device batch
            (this framework's native mode; no reference equivalent)
  triage    batched crash triage on the device batch (wtf_tpu/triage):
            minimize (crash bisection), distill (exact-attribution
            corpus minset), vbreak (virtual-breakpoint replay) — the
            reference's host-serial `run`-mode workflows as mesh
            dispatches
  lint      graph-invariant static analysis of the hot-path contracts
            (wtf_tpu/analysis; CPU-only, no reference equivalent)

Target selection is by --name over the self-registering target registry;
--target-module imports additional harness modules first (the reference
compiles fuzzer_*.cc in; here any importable module registering a Target
works, wtf.cc:378-383).
"""

from __future__ import annotations

import argparse
import importlib
import logging
import random
import sys
import time
from contextlib import contextmanager
from pathlib import Path
from typing import List, Optional

from wtf_tpu.config import (
    BACKENDS, CampaignOptions, DEFAULT_ADDRESS, FuzzOptions, MasterOptions,
    RunOptions, TargetPaths, TRACE_TYPES, TriageOptions,
)
from wtf_tpu.core.results import Crash
from wtf_tpu.harness.targets import Targets, load_builtin_targets
from wtf_tpu.telemetry import Registry, open_event_log


def _add_paths(p: argparse.ArgumentParser) -> None:
    p.add_argument("--target", type=Path, default=None,
                   help="target root dir (defaults inputs/outputs/crashes/"
                        "state underneath, wtf.cc:48-68)")
    p.add_argument("--inputs", type=Path, default=None)
    p.add_argument("--outputs", type=Path, default=None)
    p.add_argument("--crashes", type=Path, default=None)
    p.add_argument("--state", type=Path, default=None)
    p.add_argument("--telemetry-dir", type=Path, default=None,
                   help="write machine-readable telemetry (events.jsonl: "
                        "run-start/heartbeat/new-coverage/crash/timeout/"
                        "compile/run-end records with a full metrics dump; "
                        "summarize with tools/telemetry_report.py)")
    p.add_argument("--trace-out", type=Path, default=None,
                   help="write a Chrome-trace-event timeline "
                        "(chrome://tracing / Perfetto JSON) of every "
                        "span — fenced device dispatches, compiles, "
                        "megachunk windows — plus instant marks for "
                        "point events (crash/new-coverage/checkpoint/"
                        "recovery)")


def _add_target_selection(p: argparse.ArgumentParser) -> None:
    p.add_argument("--name", required=True, help="registered target name")
    p.add_argument("--target-module", action="append", default=[],
                   help="extra python module(s) to import for target "
                        "registration")


def _add_backend_tuning(p: argparse.ArgumentParser, mesh: bool = False
                        ) -> None:
    """Execution-engine knobs of the tpu backend (ignored by emu)."""
    if mesh:
        p.add_argument("--mesh-devices", type=int, default=None,
                       metavar="N",
                       help="shard the lane batch over a device mesh "
                            "(wtf_tpu/meshrun): N devices, 0 = every "
                            "local device.  --lanes is the TOTAL lane "
                            "count (lanes/N per chip) and must divide "
                            "by N; coverage OR-reduces on-chip, so the "
                            "fuzz loop sees one logical backend")
    p.add_argument("--fused-step", choices=("off", "auto", "on"),
                   default="off",
                   help="fused Pallas fast path (interp/pstep.py): one "
                        "kernel per chunk for the hot integer core, with "
                        "parked lanes resuming on the XLA step.  auto = "
                        "on only where the per-kernel dispatch win exists "
                        "(a real TPU backend)")
    p.add_argument("--burst-any-tier", choices=("auto", "on", "off"),
                   default="auto",
                   help="the oracle burst's any-instruction tier for "
                        "chronically diverting lanes.  auto = platform "
                        "default (on off-CPU); on/off force it, e.g. to "
                        "run or bench the tier on the CPU platform")
    p.add_argument("--device-decode", action="store_true",
                   help="device-resident x86 decode (interp/devdec.py): "
                        "megachunk windows service decode-cache misses "
                        "in-graph (page-walked fetch + batched decode + "
                        "publish-order slot reservation), parking only "
                        "unsupported encodings for the host; the host "
                        "decoder cross-checks every device-published "
                        "entry at harvest")
    p.add_argument("--supervise", action="store_true",
                   help="self-healing device runtime (wtf_tpu/supervise): "
                        "watchdogged dispatches, rebuild-and-replay "
                        "recovery, the degradation ladder, per-batch "
                        "integrity checks + lane quarantine")
    p.add_argument("--dispatch-timeout", type=float, default=0.0,
                   metavar="SECS",
                   help="watchdog bound for ONE base-chunk dispatch "
                        "(scaled by chunk steps and megachunk window); "
                        "0 = no watchdog.  Implies --supervise")


def _backend_tuning_kwargs(args) -> dict:
    kwargs = {"fused_step": getattr(args, "fused_step", "off")}
    tier = getattr(args, "burst_any_tier", "auto")
    if tier != "auto":
        kwargs["burst_any_tier"] = tier == "on"
    mesh = getattr(args, "mesh_devices", None)
    if mesh is not None:
        kwargs["mesh_devices"] = mesh
    timeout = getattr(args, "dispatch_timeout", 0.0) or 0.0
    if getattr(args, "supervise", False) or timeout:
        kwargs["supervise"] = True
        kwargs["dispatch_timeout"] = timeout
    if getattr(args, "device_decode", False):
        kwargs["device_decode"] = True
    return kwargs


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="wtf_tpu",
        description="TPU-native distributed snapshot fuzzer")
    sub = parser.add_subparsers(dest="subcommand", required=True)

    run = sub.add_parser("run", help="replay testcases / write traces")
    _add_target_selection(run)
    _add_paths(run)
    run.add_argument("--backend", choices=BACKENDS, default="emu")
    run.add_argument("--input", type=Path, required=True,
                     help="testcase file or directory")
    run.add_argument("--limit", type=int, default=0,
                     help="instruction budget per testcase (0 = none)")
    run.add_argument("--runs", type=int, default=1,
                     help="times to run each testcase")
    run.add_argument("--trace-path", type=Path, default=None,
                     help="file (single input) or dir to write traces")
    run.add_argument("--trace-type", choices=TRACE_TYPES, default="rip")
    run.add_argument("--coverage", type=Path, default=None,
                     help="dir of .cov files (IDA/Binja/Ghidra exports); "
                          "prints covered/total per run set")
    run.add_argument("--lanes", type=int, default=4)
    _add_backend_tuning(run)

    fuzz = sub.add_parser("fuzz", help="fuzz node (dials the master)")
    _add_target_selection(fuzz)
    _add_paths(fuzz)
    fuzz.add_argument("--backend", choices=BACKENDS, default="tpu")
    fuzz.add_argument("--limit", type=int, default=0)
    fuzz.add_argument("--address", default=DEFAULT_ADDRESS)
    fuzz.add_argument("--seed", type=int, default=0)
    fuzz.add_argument("--lanes", type=int, default=64)
    fuzz.add_argument("--mux", action="store_true",
                      help="one multiplexed master connection for the whole"
                           " lane batch instead of one per lane (scales a"
                           " wide node past the master's fd budget)")
    fuzz.add_argument("--max-retry-secs", type=float, default=60.0,
                      help="survive mid-campaign socket loss: reconnect "
                           "with jittered exponential backoff for this "
                           "long before giving up (0 = reference "
                           "behavior: first loss ends the node)")
    fuzz.add_argument("--wire-v1", action="store_true",
                      help="speak the legacy (pre-WTF2) hello to a "
                           "not-yet-upgraded master: raw downstream "
                           "frames, no BYE, and therefore no reconnect "
                           "(rolling-upgrade escape hatch)")
    fuzz.add_argument("--no-cov-delta", action="store_true",
                      help="ship whole coverage sets per result (the "
                           "pre-fleet WTF2 wire) instead of streaming "
                           "coverage deltas against the master's ack "
                           "cursor — the escape hatch for masters that "
                           "predate WTF3 (--wire-v1 implies it)")
    _add_backend_tuning(fuzz, mesh=True)

    master = sub.add_parser("master", help="master node (serves testcases)")
    _add_target_selection(master)
    _add_paths(master)
    master.add_argument("--address", default=DEFAULT_ADDRESS)
    master.add_argument("--runs", type=int, default=0,
                        help="mutation budget; 0 = minset over inputs/")
    master.add_argument("--max_len", type=int, default=1024 * 1024)
    master.add_argument("--seed", type=int, default=0)
    master.add_argument("--reclaim-timeout", type=float, default=0.0,
                        help="reclaim in-flight testcases from a node "
                             "silent this long (presumed dead); 0 = off. "
                             "Reclaim-on-disconnect is always on; SIGTERM "
                             "drains gracefully either way")
    master.add_argument("--store", type=Path, default=None, metavar="DIR",
                        help="content-addressed corpus/crash store root "
                             "(wtf_tpu/fleet/store): digest-named blobs "
                             "in sharded fanout dirs with a manifest "
                             "journal; outputs//crashes/ become flat "
                             "views of it")

    snap = sub.add_parser(
        "snapshot", help="convert snapshots between formats")
    snap.add_argument("--state", type=Path, required=True,
                      help="input state dir (mem.npz or mem.dmp + regs.json)")
    snap.add_argument("--out", type=Path, required=True,
                      help="output state dir")
    snap.add_argument("--format", choices=("npz", "dmp-bmp", "dmp-full"),
                      default="npz")

    camp = sub.add_parser(
        "campaign", help="single-process fused master+node fuzz loop")
    _add_target_selection(camp)
    _add_paths(camp)
    camp.add_argument("--backend", choices=BACKENDS, default="tpu")
    camp.add_argument("--limit", type=int, default=0)
    camp.add_argument("--runs", type=int, default=0,
                      help="testcase budget; 0 = minset: replay inputs/ "
                           "once and write the coverage-minimal subset to "
                           "outputs/ (reference --runs=0, server.h:552-556)")
    camp.add_argument("--max_len", type=int, default=1024 * 1024)
    camp.add_argument("--seed", type=int, default=0)
    camp.add_argument("--lanes", type=int, default=64)
    camp.add_argument("--mutator",
                      choices=("auto", "byte", "mangle", "tlv", "devmangle"),
                      default="auto",
                      help="mutation engine: auto = the target's custom "
                           "mutator, else the best host mangle engine. "
                           "devmangle = the device-resident engine "
                           "(wtf_tpu/devmut): the whole batch is "
                           "generated in-graph from the HBM corpus slab "
                           "(tpu backend + a target with a "
                           "DeviceInsertSpec only)")
    camp.add_argument("--stop-on-crash", action="store_true")
    camp.add_argument("--megachunk", type=int, default=0, metavar="N",
                      help="one-dispatch multi-batch windows (wtf_tpu/"
                           "fuzz/megachunk): fold up to N whole batches "
                           "— restore, devmut generation, insert, the "
                           "run ladder, the coverage merge — into ONE "
                           "compiled program per dispatch, so per-batch "
                           "host work collapses to the status pull and "
                           "find harvest.  Needs --mutator devmangle "
                           "and a nonzero --limit; 0 = off")
    camp.add_argument("--checkpoint-every", type=int, default=0,
                      metavar="N",
                      help="crash-safe checkpointing (wtf_tpu/resume): "
                           "persist the resumable campaign state every N "
                           "batches (atomic tmp+fsync+rename; previous "
                           "generation kept as .prev).  A kill at any "
                           "point costs at most one interval")
    camp.add_argument("--checkpoint-dir", type=Path, default=None,
                      help="checkpoint directory (default: "
                           "<target>/checkpoint when --target is given; "
                           "a --resume dir is reused)")
    camp.add_argument("--resume", type=Path, default=None, metavar="DIR",
                      help="resume from a checkpoint dir: coverage, crash "
                           "set, corpus, RNG and devmut streams restore "
                           "bit-identically to the uninterrupted run")
    camp.add_argument("--store", type=Path, default=None, metavar="DIR",
                      help="content-addressed corpus/crash store root "
                           "(wtf_tpu/fleet/store); outputs//crashes/ "
                           "become flat views of it")
    camp.add_argument("--xprof-dir", type=Path, default=None,
                      help="capture ONE jax.profiler device trace over "
                           "--xprof-batches steady-state batches (the "
                           "first batches are compile/warmup and are "
                           "skipped); open with xprof/tensorboard for "
                           "kernel-level truth under the span timeline")
    camp.add_argument("--xprof-batches", type=int, default=4,
                      metavar="N",
                      help="batches inside the --xprof-dir window")
    camp.add_argument("--coordinator", default=None,
                      help="jax.distributed coordinator address for a"
                           " multi-host launch (host:port)")
    camp.add_argument("--num-processes", type=int, default=None)
    camp.add_argument("--process-id", type=int, default=None)
    _add_backend_tuning(camp, mesh=True)

    sched = sub.add_parser(
        "sched", help="multi-tenant campaign scheduler (wtf_tpu/tenancy):"
                      " a jobs table placed onto ONE batched device "
                      "program by priority and lane quota, preempted "
                      "via per-tenant checkpoints")
    sched.add_argument("--jobs", type=Path, required=True,
                       help='jobs table: {"jobs": [{"name", "target", '
                            '"lanes", "runs", "priority", "seed", '
                            '"mutator", "max_len", "inputs", '
                            '"checkpoint_every"}, ...]}')
    sched.add_argument("--workdir", type=Path, required=True,
                       help="per-job state root: <workdir>/<job>/"
                            "{checkpoint,crashes}.  Checkpoints carry "
                            "every bit a job needs across placements, "
                            "so a killed sched run resumes from here")
    sched.add_argument("--lanes", type=int, default=64,
                       help="total lane budget of the shared batch")
    sched.add_argument("--limit", type=int, default=0,
                       help="instruction budget per testcase (applies "
                            "to every job: the limit is one operand of "
                            "the shared compiled program)")
    sched.add_argument("--quantum", type=int, default=4,
                       help="batches per scheduling round; at each "
                            "quantum boundary unfinished placed jobs "
                            "checkpoint, and jobs left waiting preempt "
                            "them in the next placement")
    sched.add_argument("--max-rounds", type=int, default=1 << 12)
    sched.add_argument("--target-module", action="append", default=[],
                       help="extra python module(s) to import for "
                            "target registration")
    sched.add_argument("--telemetry-dir", type=Path, default=None,
                       help="events.jsonl with tenant-tagged records + "
                            "sched-round/sched-preempt/sched-complete; "
                            "summarize with tools/telemetry_report.py")
    sched.add_argument("--store", type=Path, default=None, metavar="DIR",
                       help="content-addressed store root; each job "
                            "gets its own tenant-<name> namespace "
                            "(wtf_tpu/fleet/store)")
    _add_backend_tuning(sched, mesh=True)

    triage = sub.add_parser(
        "triage", help="batched crash triage on the device batch "
                       "(wtf_tpu/triage): minimize / distill / vbreak")
    tsub = triage.add_subparsers(dest="triage_cmd", required=True)

    tmin = tsub.add_parser(
        "minimize", help="bisect a crasher to a minimal reproducer of "
                         "the SAME crash bucket — thousands of in-graph "
                         "candidate reductions per dispatch")
    _add_target_selection(tmin)
    _add_paths(tmin)
    tmin.add_argument("--backend", choices=("tpu",), default="tpu")
    tmin.add_argument("--input", type=Path, required=True,
                      help="the crashing testcase")
    tmin.add_argument("--output", type=Path, default=None,
                      help="where the minimized reproducer lands "
                           "(default: <input>.min)")
    tmin.add_argument("--limit", type=int, default=0)
    tmin.add_argument("--lanes", type=int, default=64,
                      help="candidates per dispatch")
    tmin.add_argument("--max-rounds", type=int, default=64)
    _add_backend_tuning(tmin, mesh=True)

    tdis = tsub.add_parser(
        "distill", help="re-execute the corpus in one batched sweep, "
                        "compute exact per-testcase edge attribution "
                        "from the coverage bit-planes, and keep a "
                        "set-cover subset with identical aggregate "
                        "coverage (the exact-attribution minset)")
    _add_target_selection(tdis)
    _add_paths(tdis)
    tdis.add_argument("--backend", choices=("tpu",), default="tpu")
    tdis.add_argument("--from-checkpoint", type=Path, default=None,
                      metavar="DIR",
                      help="distill a campaign checkpoint's corpus "
                           "(wtf_tpu/resume dir) instead of inputs/")
    tdis.add_argument("--limit", type=int, default=0)
    tdis.add_argument("--lanes", type=int, default=64)
    _add_backend_tuning(tdis, mesh=True)

    tvb = tsub.add_parser(
        "vbreak", help="virtual-breakpoint replay: arm a breakpoint at "
                       "a RIP/icount and capture a register+memory "
                       "window per lane across (perturbed) replays")
    _add_target_selection(tvb)
    _add_paths(tvb)
    tvb.add_argument("--backend", choices=BACKENDS, default="tpu",
                     help="emu = the single-step oracle (debugging "
                          "convenience; one replay at a time)")
    tvb.add_argument("--input", type=Path, required=True,
                     help="testcase file or directory")
    tvb.add_argument("--break-at", required=True,
                     help="capture point: hex address, symbol, or "
                          "symbol+0xOFF")
    tvb.add_argument("--hit", type=int, default=1,
                     help="capture on the Nth arrival at the RIP")
    tvb.add_argument("--min-icount", type=int, default=0,
                     help="only capture once this many instructions "
                          "retired (arrivals before resume past the bp)")
    tvb.add_argument("--mem", default="",
                     help="memory window GVA:LEN (hex ok; default: 64 "
                          "bytes at rsp)")
    tvb.add_argument("--variants", type=int, default=0,
                     help="add N deterministic single-byte perturbations "
                          "per input to the sweep")
    tvb.add_argument("--out", type=Path, default=None,
                     help="write captures as JSON")
    tvb.add_argument("--limit", type=int, default=0)
    tvb.add_argument("--lanes", type=int, default=64)
    _add_backend_tuning(tvb, mesh=True)

    fleet = sub.add_parser(
        "fleet", help="fleet tier (wtf_tpu/fleet): elastic resharding, "
                      "the corpus/crash store, the thousand-client soak")
    fsub = fleet.add_subparsers(dest="fleet_cmd", required=True)

    fre = fsub.add_parser(
        "reshard", help="resume a checkpointed campaign under a "
                        "DIFFERENT --mesh-devices placement: coverage, "
                        "crash buckets, corpus and devmut streams are "
                        "placement-free, so the resumed run is "
                        "bit-identical to never having moved")
    _add_target_selection(fre)
    _add_paths(fre)
    fre.add_argument("--checkpoint", type=Path, required=True,
                     metavar="DIR",
                     help="the campaign checkpoint dir (PR-8 format) to "
                          "re-place; a running campaign writes one at "
                          "every batch boundary under --checkpoint-every")
    fre.add_argument("--runs", type=int, required=True,
                     help="total testcase budget to finish (the budget "
                          "is not part of the checkpoint)")
    fre.add_argument("--limit", type=int, default=0)
    fre.add_argument("--lanes", type=int, default=64,
                     help="must equal the checkpoint's lane count (the "
                          "lane count is the stream identity; "
                          "lanes-per-chip is what resharding changes)")
    fre.add_argument("--mutator",
                     choices=("auto", "byte", "mangle", "tlv",
                              "devmangle"), default="auto")
    fre.add_argument("--max_len", type=int, default=1024 * 1024)
    fre.add_argument("--seed", type=int, default=0)
    _add_backend_tuning(fre, mesh=True)

    fso = fsub.add_parser(
        "soak", help="the chaos soak (wtf_tpu/fleet/soak): N simulated "
                     "clients over the real wire with injected "
                     "resets/reclaims/frame drops; zero-lost + "
                     "serial-replay-parity + delta-ratio assertions")
    fso.add_argument("--clients", type=int, default=256)
    fso.add_argument("--runs-per-client", type=int, default=60)
    fso.add_argument("--seed", type=int, default=0xF1EE7)
    fso.add_argument("--threads", type=int, default=16)
    fso.add_argument("--min-ratio", type=float, default=10.0)

    ffs = fsub.add_parser(
        "fsck", help="verify (and with --repair, recover) a fleet "
                     "store: quarantine torn blobs, drop journal "
                     "entries whose blob vanished, journal orphans")
    ffs.add_argument("--store", type=Path, required=True, metavar="DIR")
    ffs.add_argument("--namespace", default="default")
    ffs.add_argument("--repair", action="store_true")

    status = sub.add_parser(
        "status", help="live campaign/fleet status: render the "
                       "atomically-refreshed status.json a running "
                       "campaign (--telemetry-dir) or master "
                       "(--telemetry-dir exports) maintains")
    status.add_argument("dir", type=Path,
                        help="the telemetry/export dir holding "
                             "status.json (a campaign's --telemetry-dir "
                             "or a master's)")
    status.add_argument("--json", action="store_true",
                        help="print the raw status document")
    status.add_argument("--watch", type=float, default=0.0, metavar="SECS",
                        help="re-render every SECS seconds until ^C "
                             "(0 = render once)")

    lint = sub.add_parser(
        "lint", help="graph-invariant static analysis of the hot-path "
                     "contracts (wtf_tpu/analysis; CPU-only, no chip)")
    lint.add_argument("--json", action="store_true",
                      help="machine-readable output (one JSON object)")
    lint.add_argument("--families", default=None,
                      help="comma list: dtype,budget,recompile,parity,"
                           "mesh,supervise,telemetry,state,transfer,"
                           "thread,contracts (default: all)")
    lint.add_argument("--budgets", type=Path, default=None,
                      help="alternate budgets.json")
    lint.add_argument("--rebaseline", action="store_true",
                      help="rewrite the kernel-count budget file from the "
                           "current tree (record why in PERF.md).  "
                           "Ratcheted: refuses a total increase without "
                           "--allow-regression")
    lint.add_argument("--allow-regression", action="store_true",
                      help="let --rebaseline record a budget INCREASE "
                           "(conscious perf giveback; name it in PERF.md)")
    lint.add_argument("--telemetry-dir", type=Path, default=None,
                      help="write lint findings into events.jsonl")
    lint.add_argument("--deep", action="store_true",
                      help="run the transfer family's jaxpr host-transfer"
                           " census even without the budget family")
    lint.add_argument("--sarif", type=Path, default=None,
                      metavar="OUT.json",
                      help="also write findings as SARIF 2.1.0 (file:line"
                           " provenance as physical locations)")
    return parser


def _paths_from(args) -> TargetPaths:
    return TargetPaths(target=args.target, inputs=args.inputs,
                       outputs=args.outputs, crashes=args.crashes,
                       state=args.state).resolve()


def _lookup_target(args):
    load_builtin_targets()
    for module in args.target_module:
        importlib.import_module(module)
    return Targets.instance().get(args.name)


@contextmanager
def _telemetry_for(args):
    """One registry + one event sink per CLI invocation, wired into the
    backend, the campaign driver, and the heartbeat — the 'unified'
    in unified telemetry.  A fresh Registry (not the process-global one)
    so repeated in-process invocations don't bleed counters.  Context
    manager so the `JSONL always ends with run-end` invariant is
    structural: run-start on entry, run-end + close on ANY exit —
    including a failed backend build."""
    registry = Registry()
    events = open_event_log(getattr(args, "telemetry_dir", None))
    trace_out = getattr(args, "trace_out", None)
    collector = None
    if trace_out is not None:
        # --trace-out: every span becomes a Chrome-trace complete event
        # via the registry's span collector, and every point event (the
        # JSONL records minus the bulky heartbeat/run-start/run-end)
        # becomes an instant mark on the same timeline
        from wtf_tpu.telemetry import TapEventLog, TraceCollector

        collector = TraceCollector()
        registry.spans.collector = collector

        def _instant(type_, fields):
            if type_ in ("heartbeat", "run-start", "run-end"):
                return
            collector.instant(type_, {
                k: v for k, v in fields.items()
                if isinstance(v, (str, int, float, bool))})

        events = TapEventLog(events, _instant)
    events.emit("run-start", subcommand=args.subcommand,
                name=getattr(args, "name", None),
                backend=getattr(args, "backend", None),
                argv=getattr(args, "_argv", None))
    try:
        yield registry, events
    finally:
        events.emit("run-end", metrics=registry.dump())
        if collector is not None:
            try:
                n = collector.write(trace_out)
                print(f"trace: {n} events -> {trace_out}")
            except OSError as e:
                logging.getLogger("wtf_tpu").warning(
                    "trace write failed: %s", e)
        events.close()


def _build_backend(target, backend_name: str, paths: TargetPaths,
                   limit: int, lanes: int, registry=None, events=None,
                   tuning: Optional[dict] = None):
    from wtf_tpu.backend import create_backend
    from wtf_tpu.snapshot.loader import load_snapshot

    registry = registry if registry is not None else Registry()
    with registry.spans.span("snapshot-load"):
        if paths.state and Path(paths.state).exists():
            snapshot = load_snapshot(paths.state)
        elif target.snapshot is not None:
            snapshot = target.snapshot()
        else:
            raise SystemExit(
                f"target {target.name!r} has no snapshot factory and no "
                f"--state dir was given")
    # engine tuning (--fused-step/--burst-any-tier) applies to the batched
    # tpu backend only; the oracle backend has no runner underneath
    kwargs = ({"n_lanes": lanes, **(tuning or {})}
              if backend_name == "tpu" else {})
    backend = create_backend(backend_name, snapshot, limit=limit,
                             registry=registry, events=events, **kwargs)
    with registry.spans.span("init"):
        backend.initialize()
    return backend


def _minset_seed_walk(paths: TargetPaths, corpus):
    """The ONE minset measurement walk shared by `campaign --runs 0`
    and `triage distill`: a single scan over inputs/ AND any prior
    outputs/ feeds `corpus` (shared size-sorted replay ordering;
    add_digested dedups) and snapshots outputs/ (pre-dedup census) so
    it can end as exactly the kept subset of what was measured.
    Returns the [(path, digest)] outputs snapshot."""
    from wtf_tpu.fuzz.corpus import seed_paths

    out_dir = Path(paths.outputs) if paths.outputs else None
    outputs_snapshot = []
    for p, digest, data in seed_paths([paths.inputs, paths.outputs],
                                      with_data=True, keep_dups=True):
        corpus.add_digested(data, digest)
        if out_dir and p.parent == out_dir:
            outputs_snapshot.append((p, digest))
    return outputs_snapshot


def _prune_outputs(outputs_snapshot, kept) -> None:
    """outputs/ ends as exactly the kept subset of what was measured:
    every snapshot file's content was replayed (directly or via a
    content-identical twin), so prune by content digest.  Files that
    appeared after the walk were never measured and stay untouched."""
    for p, digest in outputs_snapshot:
        if not (digest in kept.digests and p.name == digest):
            p.unlink(missing_ok=True)


def _mutator_for(target, rng: random.Random, max_len: int):
    from wtf_tpu.fuzz.native_mutator import best_mangle_mutator

    if target.create_mutator is not None:
        return target.create_mutator(rng, max_len)
    return best_mangle_mutator(rng, max_len)


# ---------------------------------------------------------------------------
# subcommand drivers (subcommands.cc:16-101)
# ---------------------------------------------------------------------------

def cmd_run(args) -> int:
    from wtf_tpu.dist.client import run_testcase_and_restore

    opts = RunOptions(name=args.name, backend=args.backend,
                      input=args.input, limit=args.limit, runs=args.runs,
                      trace_path=args.trace_path,
                      trace_type=args.trace_type, lanes=args.lanes,
                      paths=_paths_from(args))
    target = _lookup_target(args)
    crashes = 0
    with _telemetry_for(args) as (registry, events):
        backend = _build_backend(target, opts.backend, opts.paths,
                                 opts.limit, opts.lanes,
                                 registry=registry, events=events,
                                 tuning=_backend_tuning_kwargs(args))
        target.init(backend)

        inputs: List[Path] = (
            sorted(p for p in opts.input.iterdir() if p.is_file())
            if opts.input.is_dir() else [opts.input])
        trace_dir = (opts.trace_path
                     if opts.trace_path and len(inputs) > 1 else None)
        if trace_dir:
            trace_dir.mkdir(parents=True, exist_ok=True)

        for path in inputs:
            data = path.read_bytes()
            for _ in range(max(opts.runs, 1)):
                if opts.trace_path:
                    trace_file = (trace_dir / f"{path.name}.trace"
                                  if trace_dir else opts.trace_path)
                    backend.set_trace_file(trace_file, opts.trace_type)
                result, coverage = run_testcase_and_restore(
                    backend, target, data)
                if isinstance(result, Crash):
                    crashes += 1
                    events.emit("crash", name=result.name,
                                input=path.name)
                print(f"{path.name}: {result} (|cov| = {len(coverage)})")
        backend.print_run_stats()
        if args.coverage is not None:
            from wtf_tpu.utils.covfiles import parse_cov_files

            wanted = parse_cov_files(args.coverage)
            covered = backend.aggregate_coverage() & wanted
            print(f"coverage: {len(covered)}/{len(wanted)} "
                  f"listed basic blocks hit")
    return 0 if crashes == 0 else 2


def cmd_fuzz(args) -> int:
    from wtf_tpu.dist.client import BatchClient, Client

    opts = FuzzOptions(name=args.name, backend=args.backend,
                       limit=args.limit, address=args.address,
                       seed=args.seed, lanes=args.lanes,
                       mesh_devices=args.mesh_devices,
                       max_retry_secs=args.max_retry_secs,
                       cov_delta=not args.no_cov_delta,
                       paths=_paths_from(args))
    target = _lookup_target(args)
    with _telemetry_for(args) as (registry, events):
        backend = _build_backend(target, opts.backend, opts.paths,
                                 opts.limit, opts.lanes,
                                 registry=registry, events=events,
                                 tuning=_backend_tuning_kwargs(args))
        if opts.backend == "tpu":
            node = BatchClient(backend, target, opts.address, mux=args.mux,
                               registry=registry, events=events,
                               print_stats=True,
                               max_retry_secs=opts.max_retry_secs,
                               wire_v1=args.wire_v1,
                               cov_delta=opts.cov_delta)
        else:
            node = Client(backend, target, opts.address,
                          registry=registry, events=events,
                          print_stats=True,
                          max_retry_secs=opts.max_retry_secs,
                          wire_v1=args.wire_v1,
                          cov_delta=opts.cov_delta)
        served = node.run()
    print(f"node served {served} testcases")
    return 0


def cmd_master(args) -> int:
    from wtf_tpu.dist.server import Server
    from wtf_tpu.fuzz.corpus import Corpus

    opts = MasterOptions(name=args.name, address=args.address,
                         runs=args.runs, max_len=args.max_len,
                         seed=args.seed,
                         reclaim_timeout=args.reclaim_timeout,
                         store=args.store, paths=_paths_from(args))
    target = _lookup_target(args)
    with _telemetry_for(args) as (registry, events):
        rng = random.Random(opts.seed or None)
        store = None
        if opts.store:
            from wtf_tpu.fleet.store import FleetStore

            store = FleetStore(opts.store, registry=registry,
                               events=events)
        corpus = Corpus(outputs_dir=opts.paths.outputs, rng=rng,
                        store=store)
        coverage_path = (Path(opts.paths.target) / "coverage.cov"
                         if opts.paths.target else None)
        server = Server(opts.address,
                        _mutator_for(target, rng, opts.max_len),
                        corpus, inputs_dir=opts.paths.inputs,
                        crashes_dir=opts.paths.crashes, runs=opts.runs,
                        max_len=opts.max_len, print_stats=True,
                        coverage_path=coverage_path,
                        registry=registry, events=events,
                        reclaim_timeout=opts.reclaim_timeout,
                        store=store, telemetry_dir=args.telemetry_dir)
        stats = server.run()
    print(server.stats.line(len(server.coverage), len(corpus), 0))
    if server.drained:
        # SIGTERM drain: state persisted, nodes notified — a supervisor
        # restarting the master must read this as a clean stop
        print("master drained (state persisted)")
        return 0
    return 0 if stats.crashes == 0 else 2


def cmd_campaign(args) -> int:
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.loop import FuzzLoop

    opts = CampaignOptions(name=args.name, backend=args.backend,
                           limit=args.limit, runs=args.runs,
                           max_len=args.max_len, seed=args.seed,
                           lanes=args.lanes, mutator=args.mutator,
                           mesh_devices=args.mesh_devices,
                           stop_on_crash=args.stop_on_crash,
                           checkpoint_every=args.checkpoint_every,
                           checkpoint_dir=args.checkpoint_dir,
                           resume=args.resume, store=args.store,
                           megachunk=args.megachunk,
                           paths=_paths_from(args))
    # checkpoint dir defaulting: explicit flag > the resume dir (a
    # resumed campaign keeps checkpointing in place) > <target>/checkpoint
    ckpt_dir = opts.checkpoint_dir or opts.resume
    if ckpt_dir is None and opts.checkpoint_every and opts.paths.target:
        ckpt_dir = Path(opts.paths.target) / "checkpoint"
    if opts.checkpoint_every and ckpt_dir is None:
        raise SystemExit("--checkpoint-every needs --checkpoint-dir "
                         "(or --target to default one under)")
    if opts.resume and opts.runs == 0:
        raise SystemExit("--resume applies to fuzz campaigns "
                         "(--runs > 0); minset replays are stateless")
    if args.coordinator or args.num_processes:
        # multi-host launch: join the jax distributed runtime first (DCN
        # coordination; tests/test_parallel.py exercises the same path on
        # 2 CPU processes).  Each host then drives its local chips; the
        # global mesh is available to sharded execution paths
        # (wtf_tpu/meshrun), and cross-host work distribution rides the
        # TCP master plane exactly like separate pods.
        from wtf_tpu.meshrun.mesh import init_multihost

        init_multihost(coordinator=args.coordinator,
                       num_processes=args.num_processes,
                       process_id=args.process_id)
    target = _lookup_target(args)
    with _telemetry_for(args) as (registry, events):
        backend = _build_backend(target, opts.backend, opts.paths,
                                 opts.limit, opts.lanes,
                                 registry=registry, events=events,
                                 tuning=_backend_tuning_kwargs(args))
        target.init(backend)
        rng = random.Random(opts.seed or None)
        store = None
        if opts.store:
            from wtf_tpu.fleet.store import FleetStore

            store = FleetStore(opts.store, registry=registry,
                               events=events)
        # minset (--runs=0) fills its corpus from ONE merged scan below
        # (no double read of inputs/); fuzz mode loads inputs and
        # persists coverage-increasing finds into outputs/
        if opts.runs == 0:
            corpus = Corpus(rng=rng)
        elif opts.paths.inputs and Path(opts.paths.inputs).is_dir():
            corpus = Corpus.load_dir(opts.paths.inputs, rng=rng,
                                     outputs_dir=opts.paths.outputs)
            corpus.store = store
        else:
            corpus = Corpus(outputs_dir=opts.paths.outputs, rng=rng,
                            store=store)
        from wtf_tpu.fuzz.mutator import create_mutator

        mutator = (_mutator_for(target, rng, opts.max_len)
                   if opts.mutator == "auto"
                   else create_mutator(opts.mutator, rng, opts.max_len))
        loop = FuzzLoop(backend, target, mutator,
                        corpus, crashes_dir=opts.paths.crashes,
                        registry=registry, events=events,
                        checkpoint_dir=ckpt_dir,
                        checkpoint_every=opts.checkpoint_every,
                        store=store, megachunk=opts.megachunk,
                        xprof_dir=args.xprof_dir,
                        xprof_batches=args.xprof_batches)
        if opts.resume:
            from wtf_tpu.resume import load_campaign, restore_campaign

            state, fell_back = load_campaign(opts.resume)
            batch = restore_campaign(loop, state, opts.resume)
            note = " (newest torn; resumed from .prev)" if fell_back else ""
            print(f"resumed at batch {batch}: "
                  f"{loop.stats.testcases} testcases, "
                  f"{len(corpus)} corpus entries{note}")
        if opts.runs == 0:
            # reference semantics (server.h:552-556): replay the seeds —
            # plus any prior campaign's outputs/, so a corpus can minimize
            # itself — and leave outputs/ holding exactly the
            # coverage-minimal subset (walk + prune shared with
            # `triage distill`)
            outputs_snapshot = _minset_seed_walk(opts.paths, corpus)
            kept = loop.minset(opts.paths.outputs, print_stats=True)
            _prune_outputs(outputs_snapshot, kept)
            print(loop.stats.line(len(corpus), loop._coverage()))
            print(f"minset: kept {len(kept)}/{len(corpus)} seeds")
            return 0 if loop.stats.crashes == 0 else 2
        stats = loop.fuzz(runs=opts.runs, print_stats=True,
                          stop_on_crash=opts.stop_on_crash)
        print(stats.line(len(corpus), loop._coverage()))
        return 0 if stats.crashes == 0 else 2


def cmd_sched(args) -> int:
    from wtf_tpu.tenancy.sched import Scheduler, load_jobs

    load_builtin_targets()
    for module in args.target_module:
        importlib.import_module(module)
    jobs = load_jobs(args.jobs)
    tuning = _backend_tuning_kwargs(args)
    mesh_devices = tuning.pop("mesh_devices", None)
    with _telemetry_for(args) as (registry, events):
        store = None
        if args.store:
            from wtf_tpu.fleet.store import FleetStore

            store = FleetStore(args.store, registry=registry,
                               events=events)
        sched = Scheduler(jobs, n_lanes=args.lanes, workdir=args.workdir,
                          limit=args.limit, quantum=args.quantum,
                          mesh_devices=mesh_devices,
                          registry=registry, events=events,
                          backend_tuning=tuning, store=store)
        summary = sched.run(max_rounds=args.max_rounds)
    crashes = 0
    for name, s in summary.items():
        crashes += s["crashes"]
        state = ("done" if s["done"]
                 else f"stopped at batch {s['batches']}")
        print(f"[sched] {name}: {state}, {s['testcases']} testcases, "
              f"{s['crashes']} crashes, {s['preemptions']} preemptions")
    print(f"[sched] {sched.rounds} rounds over {args.lanes} lanes")
    return 0 if crashes == 0 else 2


def _parse_break_at(spec: str, symbols: dict) -> int:
    """hex address, symbol, or symbol+0xOFF over the snapshot's symbol
    store (the reference resolves bp sites the same way, backend.cc:
    214-239)."""
    base, _, off = spec.partition("+")
    offset = int(off, 0) if off else 0
    try:
        return int(base, 0) + offset
    except ValueError:
        pass
    if base in symbols:
        return int(symbols[base]) + offset
    raise SystemExit(
        f"--break-at {spec!r}: not an address and not in the symbol "
        f"store ({len(symbols)} symbols; e.g. {sorted(symbols)[:4]})")


def _triage_inputs(path: Path) -> List[tuple]:
    """[(name, bytes)] for a testcase file or directory."""
    if path.is_dir():
        return [(p.name, p.read_bytes())
                for p in sorted(p for p in path.iterdir() if p.is_file())]
    return [(path.name, path.read_bytes())]


def cmd_triage(args) -> int:
    """`wtf-tpu triage {minimize,distill,vbreak}` — the batched triage
    engine (wtf_tpu/triage): replay variants at campaign throughput on
    the same hardware, mesh-sharded under --mesh-devices."""
    opts = TriageOptions(
        name=args.name, cmd=args.triage_cmd, backend=args.backend,
        input=getattr(args, "input", None),
        output=getattr(args, "output", None),
        limit=args.limit, lanes=args.lanes,
        mesh_devices=getattr(args, "mesh_devices", None),
        max_rounds=getattr(args, "max_rounds", 64),
        from_checkpoint=getattr(args, "from_checkpoint", None),
        break_at=getattr(args, "break_at", ""),
        hit=getattr(args, "hit", 1),
        min_icount=getattr(args, "min_icount", 0),
        mem=getattr(args, "mem", ""), variants=getattr(args, "variants", 0),
        out=getattr(args, "out", None), paths=_paths_from(args))
    target = _lookup_target(args)
    with _telemetry_for(args) as (registry, events):
        backend = _build_backend(target, opts.backend, opts.paths,
                                 opts.limit, opts.lanes,
                                 registry=registry, events=events,
                                 tuning=_backend_tuning_kwargs(args))
        target.init(backend)
        driver = {"minimize": _triage_minimize, "distill": _triage_distill,
                  "vbreak": _triage_vbreak}[opts.cmd]
        return driver(opts, backend, target, registry, events)


def _triage_minimize(opts, backend, target, registry, events) -> int:
    from wtf_tpu.triage import minimize

    crasher = opts.input.read_bytes()
    try:
        result = minimize(backend, target, crasher,
                          registry=registry, events=events,
                          max_rounds=opts.max_rounds)
    except ValueError as e:
        print(f"minimize: {e}")
        return 1
    out = opts.output or opts.input.with_name(opts.input.name + ".min")
    from wtf_tpu.utils.atomicio import atomic_write_bytes

    atomic_write_bytes(out, result.data)
    print(f"minimize: {result.from_len} -> {len(result.data)} bytes "
          f"(bucket {result.bucket}; {result.rounds} rounds, "
          f"{result.dispatches} dispatches, {result.candidates} "
          f"candidates, {result.simplified} bytes zeroed)")
    print(f"wrote {out}")
    return 0


def _triage_distill(opts, backend, target, registry, events) -> int:
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.triage import distill

    out_dir = Path(opts.paths.outputs) if opts.paths.outputs else None
    outputs_snapshot: List[tuple] = []
    if opts.from_checkpoint:
        # checkpoint-aware input: the campaign checkpoint's corpus, in
        # manifest order with digests verified (wtf_tpu/resume).  The
        # checkpoint is the measurement domain — pre-existing outputs/
        # files were not measured and stay untouched.
        from wtf_tpu.resume import load_campaign
        from wtf_tpu.resume.checkpoint import restore_corpus

        state, _ = load_campaign(opts.from_checkpoint)
        source = Corpus()
        restore_corpus(source, state, opts.from_checkpoint)
    else:
        # the campaign --runs 0 measurement walk + prune, shared with
        # cmd_campaign so minset and distill can never drift on which
        # outputs/ files they delete
        source = Corpus()
        outputs_snapshot = _minset_seed_walk(opts.paths, source)
    if not len(source):
        raise SystemExit("distill found no seeds (--inputs/--target "
                         "dirs, or --from-checkpoint)")
    testcases = list(source)
    result = distill(backend, target, testcases,
                     registry=registry, events=events)
    kept = Corpus(outputs_dir=out_dir)
    for idx in result.keep:
        kept.add(testcases[idx])
    _prune_outputs(outputs_snapshot, kept)
    crashes = registry.counter("triage.crashes").value
    print(f"distill: kept {len(result.keep)}/{len(testcases)} seeds "
          f"(exact cover, {result.kept_bits}/{result.total_bits} bits; "
          f"prefix minset would keep {len(result.prefix_keep)}; "
          f"{registry.counter('triage.dispatches').value} dispatches, "
          f"{crashes} crashes)")
    if out_dir:
        print(f"wrote minset to {out_dir}")
    return 0


def _triage_vbreak(opts, backend, target, registry, events) -> int:
    import json

    from wtf_tpu.triage import oracle_capture, perturbations, vbreak
    from wtf_tpu.triage.bucket import TOS_BYTES

    rip = _parse_break_at(opts.break_at, getattr(backend, "symbols", {}))
    mem_gva, mem_len = None, TOS_BYTES
    if opts.mem:
        try:
            gva_s, _, len_s = opts.mem.partition(":")
            mem_gva = int(gva_s, 0)
            mem_len = int(len_s, 0) if len_s else TOS_BYTES
        except ValueError:
            raise SystemExit(f"--mem {opts.mem!r}: expected GVA[:LEN] "
                             "(hex ok, e.g. 0x7fffe000:128)")
    named = _triage_inputs(opts.input)
    testcases = []
    for _, data in named:
        testcases.extend(perturbations(data, opts.variants + 1))
    try:
        if opts.backend == "emu":
            captures = [
                oracle_capture(backend, target, data, rip, index=i,
                               hit=opts.hit, min_icount=opts.min_icount,
                               mem_gva=mem_gva, mem_len=mem_len)
                for i, data in enumerate(testcases)]
        else:
            captures, _results = vbreak(
                backend, target, testcases, rip, hit=opts.hit,
                min_icount=opts.min_icount, mem_gva=mem_gva,
                mem_len=mem_len, registry=registry, events=events)
    except ValueError as e:
        # e.g. the target's init already owns the breakpoint — the
        # same clean one-liner the minimize subcommand gives
        print(f"vbreak: {e}")
        return 1
    got = [c for c in captures if c is not None]
    print(f"vbreak: {len(got)}/{len(testcases)} replays captured at "
          f"{rip:#x} (hit {opts.hit})")
    for c in got:
        print(f"  #{c.index} icount={c.icount} rip={c.rip:#x} "
              f"rsp={c.gpr[4]:#x} rax={c.gpr[0]:#x} "
              f"mem[{len(c.mem)}]@{c.mem_gva:#x}={c.mem[:16].hex()}")
    if opts.out:
        opts.out.write_text(json.dumps(
            [c.as_dict() if c else None for c in captures], indent=1))
        print(f"wrote {opts.out}")
    return 0


def cmd_fleet(args) -> int:
    """`wtf-tpu fleet {reshard,soak,fsck}` — the fleet tier
    (wtf_tpu/fleet)."""
    if args.fleet_cmd == "soak":
        import tempfile

        from wtf_tpu.fleet.soak import run_soak

        logging.getLogger("wtf_tpu").setLevel(logging.ERROR)
        with tempfile.TemporaryDirectory() as tmp:
            report = run_soak(tmp, clients=args.clients,
                              runs_per_client=args.runs_per_client,
                              seed=args.seed, threads=args.threads,
                              min_ratio=args.min_ratio)
        import json

        print(json.dumps(report, indent=1))
        print(f"fleet soak PASS ({report['clients']} clients, zero "
              f"lost, delta {report['delta_ratio']}x smaller)")
        return 0
    if args.fleet_cmd == "fsck":
        from wtf_tpu.fleet.store import FleetStore

        store = FleetStore(args.store, namespace=args.namespace)
        report = store.verify(repair=args.repair)
        print(f"fsck {args.store}/{args.namespace}: "
              f"{report['ok']}/{report['blobs']} blobs ok, "
              f"{len(report['torn'])} torn, "
              f"{len(report['missing'])} missing, "
              f"{len(report['orphans'])} orphan(s)"
              + (" — repaired" if args.repair else ""))
        broken = report["torn"] or report["missing"] or report["orphans"]
        return 0 if (args.repair or not broken) else 1
    return _fleet_reshard(args)


def _fleet_reshard(args) -> int:
    """Resume a checkpointed campaign under a different --mesh-devices
    placement (wtf_tpu/fleet/elastic).  Checkpoints are placement-free
    and devmut streams are shard-count invariant, so the resumed run is
    bit-identical to one that never moved."""
    import random as _random

    from wtf_tpu.config import FleetOptions
    from wtf_tpu.fleet.elastic import describe_checkpoint, run_elastic, \
        validate_placement
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.loop import FuzzLoop
    from wtf_tpu.fuzz.mutator import create_mutator
    from wtf_tpu.resume import CheckpointError, load_campaign

    opts = FleetOptions(name=args.name, checkpoint=args.checkpoint,
                        mesh_devices=args.mesh_devices, runs=args.runs,
                        limit=args.limit, lanes=args.lanes,
                        mutator=args.mutator, max_len=args.max_len,
                        seed=args.seed, paths=_paths_from(args))
    try:
        info = describe_checkpoint(opts.checkpoint)
        state, _ = load_campaign(opts.checkpoint)
        validate_placement(state, opts.mesh_devices)
    except (CheckpointError, ValueError) as e:
        print(f"reshard: {e}")
        return 1
    cfg = info["config"]
    print(f"reshard: checkpoint at batch {info['batches']} "
          f"({cfg.get('lanes')} lanes on "
          f"{cfg.get('mesh_devices') or 1} device(s), "
          f"{info['corpus']} corpus entries) -> "
          f"{opts.mesh_devices or 1} device(s)")
    target = _lookup_target(args)
    with _telemetry_for(args) as (registry, events):
        tuning = _backend_tuning_kwargs(args)
        tuning.pop("mesh_devices", None)

        def build_loop(mesh_devices):
            build = dict(tuning)
            if mesh_devices is not None:
                build["mesh_devices"] = mesh_devices
            backend = _build_backend(target, "tpu", opts.paths,
                                     opts.limit, opts.lanes,
                                     registry=registry, events=events,
                                     tuning=build)
            target.init(backend)
            rng = _random.Random(opts.seed or None)
            corpus = Corpus(outputs_dir=opts.paths.outputs, rng=rng)
            mutator = (_mutator_for(target, rng, opts.max_len)
                       if opts.mutator == "auto"
                       else create_mutator(opts.mutator, rng,
                                           opts.max_len))
            return FuzzLoop(backend, target, mutator, corpus,
                            crashes_dir=opts.paths.crashes,
                            registry=registry, events=events,
                            checkpoint_dir=opts.checkpoint,
                            checkpoint_every=1)
        loop = run_elastic(build_loop, opts.runs, opts.checkpoint,
                           start_devices=opts.mesh_devices, resume=True,
                           print_stats=True)
        print(loop.stats.line(len(loop.corpus), loop._coverage()))
        return 0 if loop.stats.crashes == 0 else 2


def cmd_lint(args) -> int:
    """`wtf-tpu lint`: the graph-invariant linter (wtf_tpu/analysis),
    telemetry-wired like every other subcommand — findings land in the
    registry (`analysis.*`) and the JSONL stream."""
    from wtf_tpu.analysis import lint_main

    families = args.families.split(",") if args.families else None
    with _telemetry_for(args) as (registry, events):
        return lint_main(families=families, budgets=args.budgets,
                         rebaseline=args.rebaseline,
                         allow_regression=args.allow_regression,
                         as_json=args.json, deep=args.deep,
                         sarif=str(args.sarif) if args.sarif else None,
                         registry=registry, events=events)


def _derived_status_rows(metrics: dict) -> List[str]:
    """The operator-facing derived lines shared by campaign and fleet
    status: each row appears only when its subsystem actually ran, so a
    plain campaign renders just the heartbeat line."""
    rows: List[str] = []

    def val(name, default=0):
        v = metrics.get(name, default)
        return v if isinstance(v, (int, float)) else default

    instr = val("device.instructions")
    fused = val("device.fused_steps")
    if fused and instr:
        rows.append(f"fused occupancy: {fused / instr:.1%}")
    windows = val("megachunk.windows")
    if windows:
        zh = val("devdec.zero_host_windows")
        rows.append(f"zero-host windows: {zh}/{windows} "
                    f"({zh / windows:.0%})")
        # fused-window share: what fraction of in-window quiesce
        # dispatches were Pallas kernel rounds vs XLA ladder sweeps
        rounds = val("device.fused_window_rounds")
        sweeps = val("device.fused_window_xla_steps")
        if rounds:
            rows.append(f"fused windows: "
                        f"{rounds / (rounds + sweeps):.1%} of "
                        f"{rounds + sweeps} quiesce dispatches "
                        f"in-kernel")
            saved = val("device.fused_window_bytes_saved")
            if saved:
                rows.append(f"donation: {saved / (1 << 20):.1f} MiB "
                            f"copy-through saved "
                            f"({saved // max(rounds, 1)} B/dispatch)")
        prelaunched = val("megachunk.prelaunched")
        if prelaunched:
            rows.append(f"prelaunch: "
                        f"{val('megachunk.prelaunch_hits')}/{prelaunched}"
                        f" adopted, {val('megachunk.prelaunch_dropped')}"
                        f" dropped")
    phase = metrics.get("phase.seconds") or {}
    if isinstance(phase, dict) and phase:
        from wtf_tpu.telemetry.spans import DEVICE_SPAN_LEAVES

        top = sum(s for p, s in phase.items() if "/" not in p)
        dev = sum(s for p, s in phase.items()
                  if "/" in p and p.split("/")[-1] in DEVICE_SPAN_LEAVES)
        if top:
            rows.append(f"host share: "
                        f"{max(top - dev, 0.0) / top:.1%} of "
                        f"accounted wall")
    if val("supervise.dispatches"):
        rows.append(f"supervisor: rung {val('supervise.rung')}, "
                    f"{val('supervise.rebuilds')} rebuilds, "
                    f"{val('supervise.quarantined_lanes')} lanes "
                    f"quarantined")
    delta = val("dist.cov_bytes_delta")
    bitmap = val("dist.cov_bytes_bitmap")
    if delta and bitmap:
        rows.append(f"delta frames: {bitmap - delta} cov bytes saved "
                    f"({bitmap / delta:.1f}x smaller)")
    tenants = sorted({name.split(".")[1] for name in metrics
                      if name.startswith("tenant.")
                      and len(name.split(".")) >= 3})
    for t in tenants:
        rows.append(f"tenant {t}: "
                    f"execs={metrics.get(f'tenant.{t}.testcases', 0) or 0}"
                    f" newcov="
                    f"{metrics.get(f'tenant.{t}.new_coverage', 0) or 0}"
                    f" crashes="
                    f"{metrics.get(f'tenant.{t}.crashes', 0) or 0}")
    return rows


def _render_status(doc: dict) -> None:
    age = max(time.time() - float(doc.get("ts", 0) or 0), 0.0)
    if doc.get("kind") == "fleet":
        print(f"fleet: {doc.get('nodes', 0)} node(s), "
              f"{doc.get('frames', 0)} telem frames "
              f"({doc.get('duplicates_dropped', 0)} duplicates dropped), "
              f"as of {age:.0f}s ago")
        for row in doc.get("per_node", []):
            print(f"  {row.get('node', '?')[:12]:<12} "
                  f"seq={row.get('seq')}/e{row.get('epoch')} "
                  f"execs={row.get('testcases')} "
                  f"({row.get('execs_per_s')}/s) "
                  f"crash={row.get('crashes')} "
                  f"newcov={row.get('new_coverage')}")
    else:
        print(f"campaign: batch {doc.get('batches', 0)}, "
              f"as of {age:.0f}s ago")
        if doc.get("line"):
            print(f"  {doc['line']}")
    for row in _derived_status_rows(doc.get("metrics") or {}):
        print(f"  {row}")


def cmd_status(args) -> int:
    """`wtf-tpu status <dir>`: render the status.json a running campaign
    (FuzzLoop._write_status, every heartbeat) or fleet master
    (FleetTelemetry.write_exports, every persistence interval) refreshes
    atomically — readers always see a complete document."""
    import json

    path = args.dir / "status.json"
    while True:
        try:
            doc = json.loads(path.read_text())
        except FileNotFoundError:
            print(f"status: no status.json under {args.dir} — is the "
                  f"campaign/master running with --telemetry-dir?")
            return 1
        except ValueError:
            doc = None  # mid-rotation torn read: keep the last render
        if doc is not None:
            if args.json:
                print(json.dumps(doc))
            else:
                if args.watch:
                    print("\x1b[2J\x1b[H", end="")
                _render_status(doc)
        if not args.watch:
            return 0
        try:
            time.sleep(args.watch)
        except KeyboardInterrupt:
            return 0


def cmd_snapshot(args) -> int:
    """Format conversion: the bdump-side tooling the reference leaves to
    external scripts.  npz <-> Windows crash dump both ways."""
    import json

    import numpy as np

    from wtf_tpu.snapshot.kdmp import write_kdmp
    from wtf_tpu.snapshot.loader import dump_cpu_state_json, load_snapshot

    snap = load_snapshot(args.state)
    args.out.mkdir(parents=True, exist_ok=True)
    if args.format == "npz":
        snap.save_raw(args.out)
    else:
        table = np.asarray(snap.physmem.image.frame_table)[0]
        page_data = np.asarray(snap.physmem.image.pages).view(np.uint8)
        pages = {int(pfn): page_data[int(table[pfn])].tobytes()
                 for pfn in np.nonzero(table)[0]}
        write_kdmp(args.out / "mem.dmp", pages,
                   dump_type="bmp" if args.format == "dmp-bmp" else "full",
                   dtb=snap.cpu.cr3, cpu=snap.cpu)
        (args.out / "regs.json").write_text(dump_cpu_state_json(snap.cpu))
        (args.out / "symbol-store.json").write_text(json.dumps(
            {k: hex(v) for k, v in snap.symbols.items()}, indent=1))
    n_pages = int((np.asarray(snap.physmem.image.frame_table) != 0).sum())
    print(f"wrote {args.format} snapshot ({n_pages} pages) to {args.out}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    # Operational failures (crash-save, coverage-write, malformed-frame —
    # the bare-print replacements) go through `logging`; a message-only
    # handler on stdout keeps them stream-stable with the prints they
    # replaced.  Scoped to the wtf_tpu logger, NOT the root logger:
    # third-party WARNINGs (jax/absl) must not leak bare into the
    # parseable stdout stream.  Heartbeat lines themselves stay print()
    # (CampaignStats.maybe_heartbeat) so they reach stdout even without
    # this config.  Handlers are rebound to the CURRENT stdout on every
    # invocation (pytest capture swaps streams between in-process calls).
    handler = logging.StreamHandler(sys.stdout)
    handler.setFormatter(logging.Formatter("%(message)s"))
    wlog = logging.getLogger("wtf_tpu")
    wlog.handlers[:] = [handler]
    wlog.setLevel(logging.INFO)
    wlog.propagate = False
    args = build_parser().parse_args(argv)
    # the argv actually parsed (programmatic main(argv) included) — the
    # provenance recorded in the run-start telemetry event
    args._argv = list(argv) if argv is not None else sys.argv[1:]
    driver = {
        "run": cmd_run,
        "fuzz": cmd_fuzz,
        "master": cmd_master,
        "campaign": cmd_campaign,
        "sched": cmd_sched,
        "snapshot": cmd_snapshot,
        "triage": cmd_triage,
        "fleet": cmd_fleet,
        "lint": cmd_lint,
        "status": cmd_status,
    }[args.subcommand]
    return driver(args)


def console_main() -> None:
    """setuptools console-script entry (`wtf-tpu ...`)."""
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `wtf-tpu status --json | head` closed the pipe: normal
        # operator usage, not an error
        sys.exit(0)


if __name__ == "__main__":
    console_main()
