"""Multi-chip scaling: shard the lane axis over a device mesh.

Promoted to the first-class `wtf_tpu.meshrun` subsystem in PR 7 (mesh
campaign driver: shard_map executors, MeshRunner/MeshBackend, the
shard-aware coverage reduce).  This package remains as a back-compat
import surface only.
"""

from wtf_tpu.parallel.mesh import (  # noqa: F401
    make_mesh, merged_coverage, shard_machine,
)
