"""Multi-chip scaling: shard the lane axis over a device mesh.

The reference scales by running N independent client *processes* against one
master over TCP (SURVEY.md §2.7); the TPU-native equivalent keeps ONE batch
whose lane axis is sharded across chips with `jax.sharding` — XLA inserts
the ICI collectives (the coverage OR-reduce becomes an all-reduce) and the
host runner stays oblivious.
"""

from wtf_tpu.parallel.mesh import (  # noqa: F401
    make_mesh, merged_coverage, shard_machine,
)
