"""Back-compat shim: lane-axis sharding moved to wtf_tpu/meshrun/.

PR 7 promoted this module into the `meshrun` subsystem (mesh
construction in meshrun/mesh.py, the coverage OR-reduce family in
meshrun/reduce.py, plus the shard_map executors / MeshRunner /
MeshBackend that did not exist here).  The old import surface keeps
working for existing tests and tools; new code should import from
wtf_tpu.meshrun directly.
"""

from wtf_tpu.meshrun.mesh import (  # noqa: F401
    LANE_AXIS, init_multihost, lane_sharding, make_mesh, replicate,
    replicated_sharding, shard_machine,
)
from wtf_tpu.meshrun.reduce import (  # noqa: F401
    merge_coverage, merged_coverage, or_reduce_lanes,
)

# pre-promotion private name, kept for any out-of-tree caller
_or_reduce_lanes = or_reduce_lanes
