"""Lane-axis sharding over a jax.sharding.Mesh.

Design (SURVEY.md §2.7.3): the fuzzer's only parallel axis is *testcases*
(lanes) — the analog of data parallelism.  Machine state is SoA arrays with
a leading lane axis, so sharding is one PartitionSpec over that axis; the
snapshot image and uop table are replicated (every chip interprets against
the same read-only memory image); coverage aggregation is an OR-reduce over
the lane axis, which XLA turns into an ICI all-reduce when lanes span chips.

Multi-host: the same mesh spans processes (jax distributed runtime); the
corpus/crash plane stays host-side and distributes over the reference's TCP
protocol (dist/), which needs no device awareness.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from wtf_tpu.interp.machine import Machine

LANE_AXIS = "lanes"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (LANE_AXIS,))


def init_multihost(coordinator: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> Mesh:
    """Multi-host campaign entry point: join the jax distributed runtime
    (DCN coordination; args default from the cluster environment) and
    return the global lane mesh over every chip of every host.

    This replaces the reference's process-per-core fan-out INSIDE the
    pod: one mesh, lanes sharded across all chips, coverage OR-reduce
    riding ICI within hosts and DCN across (XLA picks the collectives).
    Across independent pods, the TCP master/node plane (wtf_tpu.dist)
    still applies unchanged — a whole pod is one BatchClient."""
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if not jax.distributed.is_initialized():
        jax.distributed.initialize(**kwargs)  # raises on a bad coordinator
    return make_mesh()


def _is_multiprocess(mesh: Mesh) -> bool:
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def _place(leaf, sharding, mesh: Mesh):
    """device_put within one process; across processes every host holds
    the same global value (machines broadcast from one snapshot, images
    and uop tables are replicated by construction), so each process
    donates its addressable shards of that value via the callback form."""
    if not _is_multiprocess(mesh):
        return jax.device_put(leaf, sharding)
    arr = np.asarray(leaf)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])


def shard_machine(machine: Machine, mesh: Mesh) -> Machine:
    """Place every per-lane leaf with its leading axis split over the mesh.

    n_lanes must divide by mesh size.  Returns the same pytree with
    device-sharded arrays; everything downstream (run_chunk, coverage
    merge) is shape-identical, so jit compiles SPMD executables with XLA
    inserting the cross-chip collectives.  On a multi-host mesh every
    process must call this with the SAME host value (true for machines
    built from one snapshot) and the array becomes global."""
    sharding = NamedSharding(mesh, P(LANE_AXIS))
    return jax.tree.map(lambda leaf: _place(leaf, sharding, mesh), machine)


def replicate(tree, mesh: Mesh):
    """Replicate snapshot image / uop table on every mesh device."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda leaf: _place(leaf, sharding, mesh), tree)


def _or_reduce_lanes(words, groups: Optional[int]):
    """OR-reduce u32 bitmaps over the (possibly sharded) lane axis.

    XLA's cross-device reduction set covers sum/min/max but not u32
    bitwise-or, so a plain `bitwise_or.reduce` over a sharded axis fails
    to partition.  Split the reduction instead: the expensive [L, W] part
    is a shard-local bitwise OR (no collective, no expansion), and only
    the small [g, W, 32] per-bit view crosses devices via `jnp.any`'s
    boolean all-reduce.  (The former formulation expanded the full
    [L, W, 32] bit tensor — 32x the bitmap bytes — before reducing.)

    The group count must be a multiple of the lane-mesh size or the
    "local" OR itself crosses shards; callers that hold the mesh pass
    `groups` (merged_coverage's static arg).  The default — the largest
    power-of-two divisor of n_lanes, capped at 256 — stays shard-local
    for any power-of-two mesh up to 256 devices."""
    n = words.shape[0]
    g = groups if groups else min(n & -n, 256)
    grouped = words.reshape(g, n // g, -1)
    local = jnp.bitwise_or.reduce(grouped, axis=1)        # [g, W]
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = jnp.any((local[..., None] >> shifts) & jnp.uint32(1) != 0,
                   axis=0)                                # [W, 32]
    return jnp.sum(bits.astype(jnp.uint32) << shifts, axis=-1)


@partial(jax.jit, static_argnames=("groups",))
def merged_coverage(machine: Machine, groups: Optional[int] = None):
    """Batch-wide coverage union: OR-reduce the per-lane cov/edge bitmaps
    over the lane axis.  Under a sharded lane axis this lowers to an
    all-reduce over ICI — the device-side replacement for the reference
    master's set-union merge (server.h:816-854).

    Pass `groups` = a multiple of the lane-mesh device count (e.g.
    `mesh.size`) on meshes wider than 256 or with non-power-of-two
    device counts; see `_or_reduce_lanes`."""
    return (_or_reduce_lanes(machine.cov, groups),
            _or_reduce_lanes(machine.edge, groups))
