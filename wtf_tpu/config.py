"""Per-subcommand options objects (the reference's Options_t layer,
src/wtf/globals.h:1190-1385) + the targets/<name>/ path conventions
(wtf.cc:48-68; README.md:27-33).

The CLI (wtf_tpu/cli.py) parses argv into these; library users can build
them directly — they are plain dataclasses with no argparse dependency.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Optional

BACKENDS = ("emu", "tpu")
TRACE_TYPES = ("rip", "cov", "tenet")
DEFAULT_ADDRESS = "tcp://localhost:31337/"  # wtf.cc:79,369


@dataclasses.dataclass
class TargetPaths:
    """targets/<t>/{inputs,outputs,crashes,state} conventions."""

    target: Optional[Path] = None
    inputs: Optional[Path] = None
    outputs: Optional[Path] = None
    crashes: Optional[Path] = None
    state: Optional[Path] = None

    def resolve(self) -> "TargetPaths":
        """Default unset dirs from the target root (wtf.cc:48-68)."""
        if self.target is not None:
            root = Path(self.target)
            self.inputs = self.inputs or root / "inputs"
            self.outputs = self.outputs or root / "outputs"
            self.crashes = self.crashes or root / "crashes"
            self.state = self.state or root / "state"
        return self


@dataclasses.dataclass
class RunOptions:
    """`wtf run` options (globals.h Run*Options role)."""

    name: str = ""
    backend: str = "emu"
    input: Optional[Path] = None
    limit: int = 0
    runs: int = 1
    trace_path: Optional[Path] = None
    trace_type: str = "rip"
    lanes: int = 4
    paths: TargetPaths = dataclasses.field(default_factory=TargetPaths)


@dataclasses.dataclass
class FuzzOptions:
    """`wtf fuzz` node options."""

    name: str = ""
    backend: str = "tpu"
    limit: int = 0
    address: str = DEFAULT_ADDRESS
    seed: int = 0
    lanes: int = 64
    # None = single device; 0 = every local device; N = first N devices.
    # The node becomes ONE logical backend of `lanes` total lanes sharded
    # lanes/N per chip (wtf_tpu/meshrun).
    mesh_devices: Optional[int] = None
    # mid-campaign socket-loss budget: reconnect with jittered backoff
    # for this long before the node gives up (0 = reference behavior:
    # first loss ends the node)
    max_retry_secs: float = 60.0
    # streaming coverage deltas (wtf_tpu/fleet/delta, WTF3): results
    # carry only newly-set coverage bits against the master's ack
    # cursor.  Needs a delta-capable master; `fuzz --no-cov-delta` is
    # the rolling-upgrade escape hatch (--wire-v1 implies it)
    cov_delta: bool = True
    paths: TargetPaths = dataclasses.field(default_factory=TargetPaths)


@dataclasses.dataclass
class MasterOptions:
    """`wtf master` options."""

    name: str = ""
    address: str = DEFAULT_ADDRESS
    runs: int = 0
    max_len: int = 1024 * 1024
    seed: int = 0
    # reclaim in-flight testcases from a node that has been silent this
    # long (presumed dead: wedged chip, half-open TCP); 0 = off —
    # drop-detection reclaim is always on regardless
    reclaim_timeout: float = 0.0
    # content-addressed corpus/crash store root (wtf_tpu/fleet/store);
    # None keeps the flat outputs//crashes/ directories as the system
    # of record instead of as views
    store: Optional[Path] = None
    paths: TargetPaths = dataclasses.field(default_factory=TargetPaths)


@dataclasses.dataclass
class TriageOptions:
    """`wtf-tpu triage {minimize,distill,vbreak}` (wtf_tpu/triage — the
    batched triage engine; no reference equivalent, the reference
    triages host-serially through `run`)."""

    name: str = ""
    cmd: str = "minimize"        # minimize | distill | vbreak
    backend: str = "tpu"
    input: Optional[Path] = None     # minimize/vbreak testcase (or dir)
    output: Optional[Path] = None    # minimize: minimized reproducer
    limit: int = 0
    lanes: int = 64
    mesh_devices: Optional[int] = None
    max_rounds: int = 64             # minimize: structural round cap
    from_checkpoint: Optional[Path] = None  # distill: campaign ckpt dir
    break_at: str = ""               # vbreak: symbol | hex | sym+0xOFF
    hit: int = 1                     # vbreak: capture on Nth arrival
    min_icount: int = 0              # vbreak: icount floor for capture
    mem: str = ""                    # vbreak: GVA:LEN window (hex ok)
    variants: int = 0                # vbreak: perturbed replicas/input
    out: Optional[Path] = None       # vbreak: JSON capture dump
    paths: TargetPaths = dataclasses.field(default_factory=TargetPaths)


@dataclasses.dataclass
class CampaignOptions:
    """`wtf campaign` (single-process master+node fused loop — the batch
    framework's native mode; no reference equivalent)."""

    name: str = ""
    backend: str = "tpu"
    limit: int = 0
    runs: int = 0
    max_len: int = 1024 * 1024
    seed: int = 0
    lanes: int = 64
    mutator: str = "auto"   # auto | byte | mangle | tlv | devmangle
    # None = single device; 0 = every local device; N = first N devices
    # (wtf_tpu/meshrun: lanes shard over the mesh, coverage reduces
    # on-chip, the loop sees one logical backend)
    mesh_devices: Optional[int] = None
    stop_on_crash: bool = False
    # crash-safe checkpoint/resume (wtf_tpu/resume): checkpoint the
    # resumable campaign state every N batches (0 = off) into
    # checkpoint_dir (defaults under the target root); resume replays a
    # checkpoint dir bit-identically to the uninterrupted run
    checkpoint_every: int = 0
    checkpoint_dir: Optional[Path] = None
    resume: Optional[Path] = None
    # content-addressed corpus/crash store root (wtf_tpu/fleet/store)
    store: Optional[Path] = None
    # one-dispatch multi-batch windows (wtf_tpu/fuzz/megachunk): up to N
    # whole batches — restore/mutate/insert/execute/reduce — per
    # compiled dispatch (0 = off; needs --mutator devmangle + --limit)
    megachunk: int = 0
    # self-healing device runtime (wtf_tpu/supervise): watchdogged
    # dispatches, rebuild-and-replay recovery, the degradation ladder,
    # per-batch integrity checks + lane quarantine.  dispatch_timeout is
    # the watchdog bound for ONE base-chunk dispatch (scaled by chunk
    # steps / megachunk window); nonzero implies supervise
    supervise: bool = False
    dispatch_timeout: float = 0.0
    paths: TargetPaths = dataclasses.field(default_factory=TargetPaths)


@dataclasses.dataclass
class FleetOptions:
    """`wtf-tpu fleet reshard` (wtf_tpu/fleet/elastic): resume a
    checkpointed campaign under a different device placement."""

    name: str = ""
    checkpoint: Optional[Path] = None
    mesh_devices: Optional[int] = None
    runs: int = 0
    limit: int = 0
    lanes: int = 64
    mutator: str = "auto"
    max_len: int = 1024 * 1024
    seed: int = 0
    paths: TargetPaths = dataclasses.field(default_factory=TargetPaths)
