"""Execution-trace writers (reference TraceType_t surface, SURVEY §5.1)."""

from wtf_tpu.trace.writers import (
    CovTraceWriter, RipTraceWriter, TenetTraceWriter, TraceWriter,
)

__all__ = ["CovTraceWriter", "RipTraceWriter", "TenetTraceWriter",
           "TraceWriter"]
