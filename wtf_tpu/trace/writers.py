"""Trace writers: rip, cov (unique rip), tenet.

Formats match the reference so its downstream tooling works unchanged:
  rip    one hex RIP per executed instruction
         (bochscpu_backend.cc:507-519; fed to the external `symbolizer`)
  cov    one hex RIP per FIRST execution (unique rips)
  tenet  per-instruction register deltas + memory accesses for the Tenet
         trace explorer (DumpTenetDelta, bochscpu_backend.cc:1215-1323):
         'reg=0x..,reg=0x..' changed registers (full set on the first
         line), ',mr=0xADDR:HEXBYTES' / ',mw=...' per access.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional, Tuple

# reference dump order (DumpTenetDelta): note rbx/rcx swapped vs x86
# encoding order, rip last
_TENET_REGS = ("rax", "rbx", "rcx", "rdx", "rbp", "rsp", "rsi", "rdi",
               "r8", "r9", "r10", "r11", "r12", "r13", "r14", "r15", "rip")


class TraceWriter:
    """Base: owns the file handle, context-manager lifetime, and explicit
    flush.  A crashed run's trace is usually the one that matters —
    `flush()` lets long-running drivers checkpoint buffered lines, and
    `with` guarantees the tail reaches disk even when the run raises."""

    def __init__(self, path):
        self._fh = open(Path(path), "w")

    def flush(self) -> None:
        if not self._fh.closed:
            self._fh.flush()

    def close(self) -> None:
        if not self._fh.closed:
            self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


class RipTraceWriter(TraceWriter):
    def on_step(self, rip: int) -> None:
        self._fh.write(f"{rip:#x}\n")


class CovTraceWriter(TraceWriter):
    def __init__(self, path):
        super().__init__(path)
        self._seen = set()

    def on_step(self, rip: int) -> None:
        if rip not in self._seen:
            self._seen.add(rip)
            self._fh.write(f"{rip:#x}\n")


class TenetTraceWriter(TraceWriter):
    """Register+memory delta lines.  Call on_step AFTER each instruction
    with the post-state registers and that instruction's accesses."""

    def __init__(self, path):
        super().__init__(path)
        self._prev: Optional[Dict[str, int]] = None

    def on_step(self, regs: Dict[str, int],
                accesses: List[Tuple[str, int, bytes]] = ()) -> None:
        parts = []
        force = self._prev is None
        for name in _TENET_REGS:
            value = regs[name]
            if force or value != self._prev.get(name):
                parts.append(f"{name}={value:#x}")
        line = ",".join(parts)
        for kind, addr, data in accesses:
            line += f",{kind}={addr:#x}:{data.hex().upper()}"
        if line:
            self._fh.write(line + "\n")
        self._prev = dict(regs)
