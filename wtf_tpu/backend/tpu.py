"""TpuBackend: the batched device interpreter behind the Backend contract.

Where every reference backend runs ONE testcase per `Run()` inside one
VM/emulator, this backend runs a whole *batch* — one testcase per device
lane — per `run_batch()`.  The single-testcase `run()` facade (lane 0) keeps
the reference's `Backend_t` calling convention for the run/trace subcommands
and for harness code that doesn't care about batching.

Lane binding: register/memory accessors operate on the backend's *current*
lane.  During `run_batch` insertion and breakpoint dispatch the backend is
bound to the lane being serviced, so unmodified target modules
(`insert_testcase(backend, data)`, `handler(backend)`) work per-lane exactly
like the reference's globals-based harness code (fuzzer_hevd.cc:20-59).

Coverage: per-lane device bitmaps OR-merged into device-resident aggregate
bitmaps after each batch; a lane "found new coverage" iff its bitmap has a
bit outside the aggregate (reference semantics: set-union merge on the
master, server.h:816-854).  Timeout lanes are excluded from the merge — the
reference client revokes their coverage before reporting (client.cc:122-125).
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Set

import jax
import jax.numpy as jnp
import numpy as np

from wtf_tpu.backend.base import Backend, BreakpointHandler
from wtf_tpu.core.results import (
    Cr3Change, Crash, Ok, OverlayFull, TestcaseResult, Timedout,
)
from wtf_tpu.core.results import StatusCode
from wtf_tpu.interp.machine import CTR_INSTR
from wtf_tpu.interp.runner import HostView, Runner
# the ONE coverage merge (reference master's set-union semantics,
# server.h:816-854) — shared with the mesh backend, which swaps in the
# shard-aware variant of the same core (meshrun/reduce.py)
from wtf_tpu.meshrun.reduce import merge_coverage
from wtf_tpu.snapshot.loader import Snapshot
from wtf_tpu import telemetry
from wtf_tpu.telemetry import Registry, StatsDict
from wtf_tpu.utils.hashing import splitmix64

MASK64 = (1 << 64) - 1


class TpuBackend(Backend):
    def __init__(self, snapshot: Snapshot, n_lanes: int = 64,
                 limit: int = 0, registry: Optional[Registry] = None,
                 events=None, **runner_kwargs):
        self.snapshot = snapshot
        self.symbols = snapshot.symbols
        self.n_lanes = n_lanes
        self.limit = limit
        # Telemetry: ONE registry shared with the Runner (and, when the
        # campaign driver defaults to it, the fuzz loop) so phase spans
        # nest and the heartbeat dump carries everything
        self.registry, self.events = telemetry.resolve(
            registry=registry, events=events)
        # Self-healing device runtime (wtf_tpu/supervise): the supervisor
        # outlives the Runner it guards — initialize() hands it to every
        # (re)built Runner so dispatch indices, ladder state and the
        # quarantine set survive recovery rebuilds.
        from wtf_tpu.supervise import Supervisor

        self.supervisor = Supervisor(
            registry=self.registry, events=self.events,
            enabled=bool(runner_kwargs.pop("supervise", False)),
            dispatch_timeout=float(
                runner_kwargs.pop("dispatch_timeout", 0.0) or 0.0),
            promote_after=int(runner_kwargs.pop("promote_after", 8)),
            max_batch_retries=int(
                runner_kwargs.pop("max_batch_retries", 4)),
            quarantine_threshold=int(
                runner_kwargs.pop("quarantine_threshold", 3)))
        self.supervisor._backend = self
        self._runner_kwargs = runner_kwargs
        self.runner: Optional[Runner] = None
        self.breakpoints: Dict[int, BreakpointHandler] = {}
        self._view: Optional[HostView] = None
        self._lane = 0
        self._lane_results: Dict[int, TestcaseResult] = {}
        self._agg_cov = None
        self._agg_edge = None
        # pipelined harvest: a speculatively dispatched next megachunk
        # window (out, signature) — adopted by the next run_megachunk
        # call when its parameters match, dropped (unread, side-effect
        # free) otherwise
        self._mega_inflight = None
        # the batch coverage merge — the mesh backend swaps in the
        # shard-aware variant (same semantics, one all_gather)
        self._merge = merge_coverage
        self._last_new_words: Optional[np.ndarray] = None
        self._trace_request = None
        # per-campaign counters (reference BochscpuRunStats_t role,
        # bochscpu_backend.h:17-45) — registry-backed dict facade
        self.stats = StatsDict(self.registry, "backend",
                               fields=("batches", "testcases",
                                       "instructions"))

    # -- lifecycle ---------------------------------------------------------
    def initialize(self) -> None:
        self.runner = Runner(self.snapshot, self.n_lanes,
                             registry=self.registry, events=self.events,
                             supervisor=self.supervisor,
                             **self._runner_kwargs)
        m = self.runner.machine
        self._agg_cov = jnp.zeros_like(m.cov[0])
        self._agg_edge = jnp.zeros_like(m.edge[0])

    # -- lane binding ------------------------------------------------------
    @contextmanager
    def _bound(self, view: HostView, lane: int):
        old = (self._view, self._lane)
        self._view, self._lane = view, lane
        try:
            yield
        finally:
            self._view, self._lane = old

    def _ensure_view(self) -> HostView:
        if self._view is None:
            self._view = self.runner.view()
        return self._view

    # -- batch execution ---------------------------------------------------
    def run_batch(
        self,
        insert: Optional[Sequence] = None,
        target=None,
    ) -> List[TestcaseResult]:
        """Run a batch of testcases (one per lane; lists longer than
        n_lanes run as several device rounds with a restore in between;
        shorter lists leave trailing lanes idle)."""
        if insert is not None and len(insert) > self.n_lanes:
            results: List[TestcaseResult] = []
            flags: List[bool] = []
            for start in range(0, len(insert), self.n_lanes):
                if start > 0:
                    target.restore()
                    self.restore()
                chunk = insert[start:start + self.n_lanes]
                results.extend(self.run_batch(chunk, target))
                flags.extend(self._new_lane[:len(chunk)])
            self._new_lane = np.array(flags)
            return results
        runner = self.runner
        runner.limit = self.limit
        self._lane_results = {}
        spans = self.registry.spans
        with spans.span("insert"):
            view = self._ensure_view()
            n_active = self.n_lanes
            if insert is not None:
                n_active = len(insert)
                quarantined = runner.supervisor.quarantined
                for lane, data in enumerate(insert):
                    if lane in quarantined:
                        # poisoned lane parked idle (tenancy mask idiom):
                        # no insert, terminal status, and _finish_batch's
                        # include mask keeps it out of the coverage merge
                        view.set_status(lane, StatusCode.OK)
                        continue
                    with self._bound(view, lane):
                        target.insert_testcase(self, data)
                for lane in range(n_active, self.n_lanes):
                    view.set_status(lane, StatusCode.OK)  # idle lanes
            runner.push(view)
            self._view = None
        statuses = runner.run(bp_handler=self._dispatch_bp)
        self._finish_batch(statuses, n_active)
        return [self._map_result(lane, statuses[lane])
                for lane in range(n_active)]

    def _finish_batch(self, statuses, n_active: int) -> None:
        """Post-run batch accounting shared by run_batch and
        run_batch_device: coverage merge on device (timeouts revoked like
        the reference client, and OVERLAY_FULL lanes excluded — they ran
        on truncated memory, their coverage is not trustworthy), backend
        counters, and the once-per-burst device-counter fold."""
        runner = self.runner
        # integrity gate BEFORE anything consumes the machine: a poisoned
        # status would crash StatusCode() in result mapping, poisoned
        # planes would credit coverage.  Raises LanePoisoned (the fuzz
        # loop's supervision wrapper replays the batch); inert when the
        # supervisor is disabled.
        runner.supervisor.raise_if_poisoned(runner, "batch")
        qmask = runner.supervisor.quarantine_mask()
        with self.registry.spans.span("cov-readback") as sp:
            m = runner.machine
            keep = ((statuses != int(StatusCode.TIMEDOUT))
                    & (statuses != int(StatusCode.OVERLAY_FULL))
                    & (np.arange(self.n_lanes) < n_active))
            if qmask is not None:
                keep &= ~qmask  # quarantined lanes never credit coverage
            include = jnp.asarray(keep)
            (self._agg_cov, self._agg_edge, new_lane,
             new_words) = self._merge(
                self._agg_cov, self._agg_edge, m.cov, m.edge, include)
            self._new_lane = np.asarray(new_lane)
            self._last_new_words = np.asarray(new_words)
            self.stats["batches"] += 1
            self.stats["testcases"] += n_active
            self.stats["instructions"] += int(
                np.asarray(m.icount)[:n_active].sum())
            # fold the device telemetry block exactly once per burst
            runner.fold_device_counters()
            sp.fence(self._agg_cov)

    def run_batch_device(self, mutator, target) -> List[TestcaseResult]:
        """One batch whose testcases were generated ON DEVICE (wtf_tpu/
        devmut): insertion is a single in-graph overlay/register update
        (Runner.device_insert) instead of per-lane target.insert_testcase
        calls — mutate→insert→execute with no host round-trip for the
        testcase bytes.  `mutator` is a bound DevMangleMutator whose
        take_batch() already ran; every lane is active."""
        words, lens = mutator.current_batch()
        spec = mutator.spec
        return self.run_batch_words(words, lens, mutator.pfns, spec)

    def run_batch_words(self, words, lens, pfns,
                        spec) -> List[TestcaseResult]:
        """The device-generated batch driver shared by the devmangle fuzz
        path and the triage replay core (wtf_tpu/triage): `words`
        (u32[L, W]) / `lens` (i32[L]) device arrays — a devmut generate
        output, or triage's in-graph candidate builds — land in every
        lane's overlay through Runner.device_insert and the batch runs
        with every lane active.  `spec` is the target's
        DeviceInsertSpec, `pfns` the input region's page frames."""
        runner = self.runner
        runner.limit = self.limit
        self._lane_results = {}
        spans = self.registry.spans
        with spans.span("insert"):
            # host state staged through the backend view (e.g. init-time
            # register/memory writes a target made before the first
            # batch) must land, exactly as run_batch's push does —
            # BEFORE device_insert so the testcase wins any overlap
            if self._view is not None:
                runner.push(self._view)
                self._view = None
            qmask = runner.supervisor.quarantine_mask()
            with spans.span("device") as sp:
                if qmask is None:
                    runner.device_insert(words, lens, pfns, spec.gva,
                                         spec.len_gpr, spec.ptr_gpr)
                else:
                    # masked insert (tenancy idiom) + park the poisoned
                    # lanes terminal so the run loop never steps them
                    from wtf_tpu.supervise import integrity

                    runner.device_insert(words, lens, pfns, spec.gva,
                                         spec.len_gpr, spec.ptr_gpr,
                                         active=~qmask)
                    runner.machine = integrity.mask_idle(
                        runner.machine, qmask)
                sp.fence(runner.machine.status)
        statuses = runner.run(bp_handler=self._dispatch_bp)
        self._finish_batch(statuses, self.n_lanes)
        return [self._map_result(lane, statuses[lane])
                for lane in range(self.n_lanes)]

    def run_megachunk(self, mutator, target, max_batches: int,
                      n_batches: int):
        """ONE megachunk window (wtf_tpu/fuzz/megachunk.py): up to
        `n_batches` whole fuzz batches — restore, devmut generation,
        insert, the run ladder, the finish-breakpoint rewrite and the
        coverage merge — in one compiled dispatch; the host touches the
        window only for the status pull and the crash/new-coverage
        harvest.  `max_batches` is the compiled buffer size (stable
        across calls so the program compiles once); `n_batches <=
        max_batches` is this window's effective budget (checkpoint
        cadence / runs-budget capping).

        Returns a list of (results, new_flags, datas) per PROCESSED
        batch, in batch order: `results` the per-lane TestcaseResults,
        `new_flags` the prefix-credit new-coverage flags, `datas` the
        fetched bytes of crash/new-coverage lanes.  A batch that needed
        host servicing is finished through the ordinary Runner.run loop
        before being returned — the cold-start path IS the legacy loop.
        """
        import jax

        runner = self.runner
        if not self.limit:
            raise ValueError(
                "megachunk windows need a nonzero --limit: the in-graph "
                "run ladder quiesces on the instruction budget")
        runner.limit = self.limit
        self._lane_results = {}
        spans = self.registry.spans
        spec = mutator.spec
        n_pages = len(mutator.pfns)
        fn = runner.megachunk_callable(max_batches, n_pages,
                                       spec.len_gpr, spec.ptr_gpr,
                                       mutator.rounds)
        key = ("megachunk", max_batches, n_pages, self.n_lanes,
               mutator.rounds, runner.exec_sig,
               bool(runner.fused_enabled), runner._donate)
        from wtf_tpu.interp.runner import _DISPATCHED_EXECUTORS

        if key not in _DISPATCHED_EXECUTORS:
            _DISPATCHED_EXECUTORS.add(key)
            self.events.emit("compile", kind="megachunk",
                             batches=max_batches, lanes=self.n_lanes)
        # host state staged through the backend view (init-time target
        # writes) must land BEFORE the window, like run_batch_words
        view_was_clean = self._view is None
        if self._view is not None:
            runner.push(self._view)
            self._view = None
        self.registry.counter("megachunk.windows").inc()
        # pipelined harvest, adopt side: if the previous call prelaunched
        # this exact window, its execution has been overlapping that
        # call's harvest accounting — fence the (mostly elapsed) wait
        # instead of dispatching
        out = None
        if self._mega_inflight is not None:
            p_out, p_sig = self._mega_inflight
            self._mega_inflight = None
            sig = self._mega_signature(mutator, max_batches, n_batches,
                                       n_pages)
            if view_was_clean and p_sig == sig:
                out = p_out
                self.registry.counter("megachunk.prelaunch_hits").inc()
            else:
                # the speculation missed (window size changed, host state
                # intervened): the dispatch is pure, dropping its outputs
                # unread discards it completely
                self.registry.counter("megachunk.prelaunch_dropped").inc()
        if out is None:
            out = self._dispatch_window(fn, mutator, spec, n_pages,
                                        max_batches, n_batches,
                                        runner.machine, self._agg_cov,
                                        self._agg_edge, wait=True)
        else:
            with spans.span("device") as sp:
                sp.fence(out.batches)
        runner.machine = out.machine
        self._agg_cov = out.agg_cov
        self._agg_edge = out.agg_edge
        # integrity gate before the harvest and before the mutator cursor
        # advances: a LanePoisoned raise here leaves the window fully
        # replayable (consume_window not yet called)
        runner.supervisor.raise_if_poisoned(runner, "megachunk")
        # devdec harvest: back-fill device-published decode entries into
        # the host cache BEFORE anything can re-service those rips (the
        # incomplete path's Runner.run below rebuilds the dispatch table
        # from the cache — missing rows would re-publish at new indices
        # and corrupt the coverage-bit mapping)
        published = 0
        if runner.device_decode:
            published = self._harvest_device_decode(out)
        self._last_new_words = np.asarray(jax.device_get(out.new_words))
        b_done = int(jax.device_get(out.batches))
        incomplete = bool(jax.device_get(out.incomplete))
        statuses = np.asarray(jax.device_get(out.statuses))
        flags = np.asarray(jax.device_get(out.new_flags))
        ctr_sums = np.asarray(jax.device_get(out.ctr_sums))
        # engine-round census: [XLA step_v sweeps, Pallas dispatches]
        # over the whole window.  Every Pallas dispatch with aliased
        # overlay/machine planes is one avoided copy-through of those
        # buffers — the donation win the status/telemetry rows surface.
        er = np.asarray(jax.device_get(out.engine_rounds))
        self.registry.counter("device.fused_window_xla_steps").inc(
            int(er[0]))
        if int(er[1]):
            self.registry.counter("device.fused_window_rounds").inc(
                int(er[1]))
            self.registry.counter("device.fused_window_bytes_saved").inc(
                int(er[1]) * self._fused_alias_bytes())
        processed = b_done + (1 if incomplete else 0)
        mutator.consume_window(processed)
        if runner.device_decode and not incomplete:
            # a complete window needed ZERO host decode services — the
            # zero-host steady state PERF.md round 18 measures; length =
            # batches the window carried without coming up for air
            self.registry.counter("devdec.zero_host_windows").inc()
            self.registry.counter("devdec.zero_host_batches").inc(b_done)
        # pipelined harvest, launch side: a complete window with no finds
        # and no freshly published decode entries leaves every operand of
        # the next window already determined (slab unchanged — crashes
        # never enter the corpus — and machine/aggregates device-
        # resident), so dispatch it NOW and let it execute under the
        # harvest accounting below.  Finds must NOT prelaunch: the next
        # window's first batch is entitled to them, and its slab view is
        # only pinned during the loop's harvest.  Supervised or mesh
        # campaigns keep the synchronous schedule (recovery rebuilds and
        # multi-chip placement interact badly with in-flight windows),
        # and so do DONATED windows: a dropped prelaunch discards its
        # outputs, but donation has already consumed its input buffers —
        # adopting nothing would leave the live machine invalidated.
        if (not incomplete and published == 0
                and not flags[:b_done].any()
                and not runner.supervisor.enabled
                and not runner._donate
                and runner.exec_sig == ()):
            n_out = self._dispatch_window(
                fn, mutator, spec, n_pages, max_batches, n_batches,
                out.machine, out.agg_cov, out.agg_edge, wait=False)
            self._mega_inflight = (n_out, self._mega_signature(
                mutator, max_batches, n_batches, n_pages))
            self.registry.counter("megachunk.prelaunched").inc()

        batches = []
        for b in range(b_done):
            row = statuses[b]
            frow = flags[b]
            runner.fold_counter_totals(ctr_sums[b])
            if b == b_done - 1 and not incomplete:
                # the live machine IS this batch's final state (the
                # window stops on any non-clean terminal), so crash
                # naming reads it exactly like run_batch's path
                results = [self._map_result(lane, row[lane])
                           for lane in range(self.n_lanes)]
            else:
                # interior batches are clean by the stop rule
                results = [self._result_from_fields(
                    StatusCode(int(row[lane])), 0, 0, 0, "")
                    for lane in range(self.n_lanes)]
            snap = out.cur if b == processed - 1 else out.prev
            datas = {}
            wanted = [lane for lane in range(self.n_lanes)
                      if frow[lane] or isinstance(results[lane], Crash)]
            if wanted:
                mutator.set_current(snap.words, snap.lens)
                datas = mutator.fetch(wanted)
            self._new_lane = frow
            self.stats["batches"] += 1
            self.stats["testcases"] += self.n_lanes
            self.stats["instructions"] += int(ctr_sums[b][CTR_INSTR])
            batches.append((results, frow, datas))

        if incomplete:
            # finish the in-flight batch through the ordinary servicing
            # loop (decode/SMC/oracle/breakpoints), then account it the
            # host way — this IS the batch-at-a-time path
            statuses_fin = runner.run(bp_handler=self._dispatch_bp)
            self._finish_batch(statuses_fin, self.n_lanes)
            results = [self._map_result(lane, statuses_fin[lane])
                       for lane in range(self.n_lanes)]
            frow = np.asarray(self._new_lane)
            mutator.set_current(out.cur.words, out.cur.lens)
            wanted = [lane for lane in range(self.n_lanes)
                      if frow[lane] or isinstance(results[lane], Crash)]
            datas = mutator.fetch(wanted) if wanted else {}
            batches.append((results, frow, datas))
        return batches

    def _dispatch_window(self, fn, mutator, spec, n_pages: int,
                         max_batches: int, n_batches: int, machine,
                         agg_cov, agg_edge, wait: bool):
        """Dispatch one megachunk window against explicit machine/
        aggregate operands — shared by the synchronous path and the
        pipelined-harvest prelaunch (which passes the JUST-finished
        window's device-side outputs and wait=False so the dispatch
        queues behind nothing)."""
        from wtf_tpu.fuzz.megachunk import NO_FINISH

        runner = self.runner
        finish = spec.finish_gva if spec.finish_gva is not None \
            else NO_FINISH
        slab_first, slab_rest = mutator.window_slabs()
        seeds = mutator.window_seeds(max_batches)
        slab_first, slab_rest, seeds = runner.megachunk_place(
            slab_first, slab_rest, seeds)
        pfns = jnp.asarray(np.asarray(mutator.pfns, dtype=np.int32))
        gva_l = jnp.asarray(np.array(
            [spec.gva & 0xFFFF_FFFF, (spec.gva >> 32) & 0xFFFF_FFFF],
            dtype=np.uint32))
        with self.registry.spans.span("device") as sp:
            out = runner.supervisor.dispatch(
                "megachunk", fn,
                runner.device_tab(), runner.image, machine,
                runner.template, slab_first, slab_rest, seeds, pfns,
                gva_l, jnp.uint64(finish), jnp.uint64(self.limit),
                jnp.int32(n_batches), agg_cov, agg_edge,
                *runner.devdec_operands(),
                window=n_batches, wait=wait, sync=lambda o: o.batches)
            if wait:
                sp.fence(out.batches)
        return out

    def _mega_signature(self, mutator, max_batches: int, n_batches: int,
                        n_pages: int):
        """Everything a speculative window's operands were derived from:
        a prelaunched window is adopted only when the next call's
        signature is identical (same window size, same stream cursor,
        same decode cache, same breakpoint set, same limit, same step
        engine — a degradation-ladder rung flip mid-campaign must drop
        the speculative window, not adopt one built by the other
        engine)."""
        cache = self.runner.cache
        return (max_batches, n_batches, n_pages, mutator._batch,
                self.limit, cache.count, frozenset(cache.pending_bps),
                bool(self.runner.fused_enabled))

    def _fused_alias_bytes(self) -> int:
        """Bytes of the 13 machine/overlay planes the fused kernel
        aliases in place (input_output_aliases) — the per-dispatch
        copy-through the donation leg eliminates, dominated by the
        `[lanes, slots, words]` overlay data slab."""
        m = self.runner.machine
        ov = m.overlay
        leaves = (m.gpr_l, m.rip_l, m.rflags_l, m.status, m.icount,
                  m.bp_skip, m.ctr, m.cov, m.edge, ov.pfn, ov.data,
                  ov.valid, ov.count)
        return int(sum(x.size * x.dtype.itemsize for x in leaves))

    def _harvest_device_decode(self, out) -> int:
        """Adopt the window's device-published decode entries into the
        host cache (publish order preserved — coverage bit i IS entry
        index i) with the host decoder as cross-checking oracle, and
        fold the in-graph service stats.  Returns the number of adopted
        entries."""
        runner = self.runner
        cache = runner.cache
        dd = np.asarray(jax.device_get(out.dd_stats))
        reg = self.registry
        reg.counter("devdec.serviced_lanes").inc(int(dd[0]))
        reg.counter("devdec.published").inc(int(dd[1]))
        reg.counter("devdec.parked_lanes").inc(int(dd[2]))
        reg.counter("devdec.service_rounds").inc(int(dd[3]))
        new_count = int(jax.device_get(out.count))
        start = cache.count
        if new_count < start:
            raise RuntimeError(
                f"device decode count went backwards: {new_count} < "
                f"host cache {start}")
        if new_count == start:
            return 0
        rip_rows, mi_rows, mu_rows = jax.device_get(
            (out.tab.rip_l[start:new_count],
             out.tab.meta_i32[start:new_count],
             out.tab.meta_u64[start:new_count]))
        mismatches = cache.adopt_device_entries(
            rip_rows, mi_rows, mu_rows, start, new_count)
        reg.counter("devdec.crosscheck_mismatches").inc(mismatches)
        if mismatches:
            self.events.emit("devdec-mismatch", entries=new_count - start,
                             mismatches=mismatches)
        return new_count - start

    # -- checkpoint/resume (wtf_tpu/resume) --------------------------------
    def coverage_state(self):
        """(cov words, edge words) aggregate bitmaps as host arrays — the
        coverage half of a campaign checkpoint.  Bit indices are decode-
        cache entry indices; the checkpoint carries the cache alongside
        (Runner.checkpoint_state) so they stay meaningful."""
        return (np.asarray(jax.device_get(self._agg_cov)),
                np.asarray(jax.device_get(self._agg_edge)))

    def restore_coverage_state(self, cov: np.ndarray,
                               edge: np.ndarray) -> None:
        """Install checkpointed aggregate bitmaps.  The mesh backend
        overrides placement (aggregates live replicated on every chip).

        Drops any pipelined-harvest prelaunch in flight: a window
        dispatched against pre-restore mutator/cache state could
        otherwise be adopted after the restore if its signature happens
        to match (the signature pins batch cursor and cache count, not
        the restored slab/aggregate contents)."""
        self._agg_cov = jnp.asarray(cov)
        self._agg_edge = jnp.asarray(edge)
        self._mega_inflight = None

    def lane_found_new_coverage(self, lane: int) -> bool:
        return bool(self._new_lane[lane])

    def lane_coverage(self, lane: int) -> Set[int]:
        """This lane's executed-RIP set from its device bitmap (valid after
        run_batch, before restore).  Edge-hash coverage stays device-side;
        the wire protocol reports RIP coverage like the reference's
        robin_set<Gva_t> (client.cc:187-200).  Indexed on device first so
        only the wanted lane's row transfers — on a mesh the [lanes,
        words] plane spans shards and a full gather per harvested lane
        would dominate the crash-fetch path."""
        cov = np.asarray(jax.device_get(self.runner.machine.cov[lane]))
        return set(self.runner.cache.rips_of_bits(cov))

    def lane_cov_words(self, lane: int) -> np.ndarray:
        """This lane's raw coverage bitmap words (device-indexed pull,
        no address decode) — what the WTF3 delta path ships instead of
        the decoded RIP set: bit i is decode-cache entry i, so the
        fleet cursor's XOR against the last-acked aggregate is the whole
        delta extraction (wtf_tpu/fleet/delta.BitmapDeltaCursor)."""
        return np.asarray(jax.device_get(self.runner.machine.cov[lane]))

    def lane_result_detail(self, lane: int) -> str:
        return self.runner.lane_errors.get(lane, "")

    def _bp_handler(self, lane: int, rip: int):
        """Handler lookup for a lane stopped at `rip` — the seam the
        multi-tenant backend re-keys by (tenant, rip) so two base images
        sharing a virtual address dispatch to their own targets."""
        return self.breakpoints.get(rip)

    def _dispatch_bp(self, runner: Runner, view: HostView, lane: int) -> None:
        rip = view.get_rip(lane)
        handler = self._bp_handler(lane, rip)
        if handler is None:
            runner.lane_errors[lane] = f"unexpected breakpoint @ {rip:#x}"
            view.set_status(lane, StatusCode.HARD_ERROR)
            return
        with self._bound(view, lane):
            handler(self)
            if lane in self._lane_results:
                # handler called stop(): park the lane terminally
                result = self._lane_results[lane]
                view.set_status(lane, _result_status(result))

    def _result_from_fields(self, status: StatusCode, gva: int, write: int,
                            rip: int, detail: str) -> TestcaseResult:
        """Terminal status + crash-naming fields -> TestcaseResult — the
        ONE mapping shared by the per-lane machine read (_map_result) and
        the megachunk window's batch rows, so the two dispatch paths name
        crashes identically."""
        if status == StatusCode.OK:
            return Ok()
        if status == StatusCode.TIMEDOUT:
            return Timedout()
        if status == StatusCode.CR3_CHANGE:
            return Cr3Change()
        if status == StatusCode.CRASH:
            return Crash(f"crash-int-{gva:#x}")
        if status == StatusCode.PAGE_FAULT:
            if gva == rip and not write:
                kind = "execute"  # fetch-address fault (A/V-execute analog)
            else:
                kind = "write" if write else "read"
            return Crash(f"crash-{kind}-{gva:#x}")
        if status == StatusCode.DIVIDE_ERROR:
            return Crash(f"crash-de-{rip:#x}")
        if status == StatusCode.OVERLAY_FULL:
            return OverlayFull()
        if status == StatusCode.HARD_ERROR:
            return Crash(f"crash-{detail.split()[0]}")
        raise AssertionError(f"unmapped terminal status {status!r}")

    def _map_result(self, lane: int, status_val: int) -> TestcaseResult:
        if lane in self._lane_results:
            return self._lane_results[lane]
        status = StatusCode(int(status_val))
        if status in (StatusCode.OK, StatusCode.TIMEDOUT,
                      StatusCode.CR3_CHANGE):
            return self._result_from_fields(status, 0, 0, 0, "")
        m = self.runner.machine
        return self._result_from_fields(
            status,
            int(np.asarray(m.fault_gva)[lane]),
            int(np.asarray(m.fault_write)[lane]),
            int(np.asarray(m.rip)[lane]),
            self.runner.lane_errors.get(lane, "hard-error"))

    # -- Backend facade (single testcase == lane 0) ------------------------
    def run(self) -> TestcaseResult:
        if self._trace_request is not None:
            return self._run_traced()
        view = self._ensure_view()
        for lane in range(1, self.n_lanes):
            view.set_status(lane, StatusCode.OK)
        self.runner.limit = self.limit
        self._lane_results = {}
        runner = self.runner
        runner.push(view)
        self._view = None
        statuses = runner.run(bp_handler=self._dispatch_bp)
        m = runner.machine
        include = jnp.asarray(
            (statuses != int(StatusCode.TIMEDOUT))
            & (statuses != int(StatusCode.OVERLAY_FULL))
            & (np.arange(self.n_lanes) == 0))
        self._agg_cov, self._agg_edge, new_lane, new_words = self._merge(
            self._agg_cov, self._agg_edge, m.cov, m.edge, include)
        self._new_lane = np.asarray(new_lane)
        self._last_new_words = np.asarray(new_words)
        self.stats["batches"] += 1
        self.stats["testcases"] += 1
        self.stats["instructions"] += int(np.asarray(m.icount)[0])
        self.runner.fold_device_counters()
        return self._map_result(0, statuses[0])

    def _run_traced(self) -> TestcaseResult:
        """rip/cov trace runs go through the oracle for exact per-step
        ordering (the reference's rip traces are bochscpu-only the same way,
        wtf.cc:180-185); device state is untouched."""
        from wtf_tpu.backend.emu import EmuBackend

        path, trace_type = self._trace_request
        self._trace_request = None
        emu = EmuBackend(self.snapshot, limit=self.limit)
        emu.initialize()
        emu.breakpoints = dict(self.breakpoints)
        # replay lane-0 pending state (testcase insertion) onto the oracle:
        # memory writes plus the FULL device-resident register set, so the
        # trace follows the same path the run it reproduces would take
        view = self._ensure_view()
        for (lane, pfn), page in sorted(view.pending.items()):
            if lane == 0:
                emu.cpu.mem.phys_write(pfn << 12, bytes(page))
        cpu = emu.cpu
        cpu.gpr = [int(v) for v in view.r["gpr"][0]]
        cpu.rip = int(view.r["rip"][0])
        cpu.rflags = int(view.r["rflags"][0])
        for name in ("fs_base", "gs_base", "kernel_gs_base", "cr0", "cr2",
                     "cr3", "cr4", "cr8", "lstar", "star", "sfmask", "efer",
                     "tsc", "fpcw", "fpsw", "fptw", "mxcsr"):
            setattr(cpu, name, int(view.r[name][0]))
        cpu.cs_sel = int(view.r["cs"][0])
        cpu.ss_sel = int(view.r["ss"][0])
        cpu.fpst = [int(v) for v in view.r["fpst"][0]]
        cpu.fptop = (int(view.r["fpsw"][0]) >> 11) & 7
        for i in range(16):
            cpu.xmm[i][0] = int(view.r["xmm"][0, i, 0])
            cpu.xmm[i][1] = int(view.r["xmm"][0, i, 1])
            cpu.ymmh[i][0] = int(view.r["xmm"][0, i, 2])
            cpu.ymmh[i][1] = int(view.r["xmm"][0, i, 3])
        cpu.icount = int(view.r["icount"][0])
        cpu.rdrand_state = int(view.r["rdrand"][0])
        self._view = None
        emu.set_trace_file(path, trace_type)
        return emu.run()

    def restore(self) -> None:
        self._view = None
        self.runner.restore()

    def stop(self, result: TestcaseResult) -> None:
        self._lane_results[self._lane] = result

    # -- registers / memory (current lane) ---------------------------------
    @property
    def current_lane(self) -> int:
        return self._lane

    def get_reg(self, idx: int) -> int:
        return self._ensure_view().get_reg(self._lane, idx)

    def set_reg(self, idx: int, value: int) -> None:
        self._ensure_view().set_reg(self._lane, idx, value)

    def get_xmm(self, idx: int) -> int:
        r = self._ensure_view().r["xmm"]
        return int(r[self._lane, idx, 0]) | (int(r[self._lane, idx, 1]) << 64)

    def set_xmm(self, idx: int, value: int) -> None:
        r = self._ensure_view().r["xmm"]
        r[self._lane, idx, 0] = np.uint64(value & (1 << 64) - 1)
        r[self._lane, idx, 1] = np.uint64((value >> 64) & (1 << 64) - 1)

    def get_rip(self) -> int:
        return self._ensure_view().get_rip(self._lane)

    def set_rip(self, value: int) -> None:
        self._ensure_view().set_rip(self._lane, value)

    def get_rflags(self) -> int:
        return int(self._ensure_view().r["rflags"][self._lane])

    def get_icount(self) -> int:
        return int(self._ensure_view().r["icount"][self._lane])

    def virt_translate(self, gva: int, write: bool = False) -> int:
        return self._ensure_view().translate(self._lane, gva, write)

    def inject_exception(self, vector: int, error_code: int = 0,
                         cr2: Optional[int] = None) -> None:
        from wtf_tpu.cpu.interrupts import deliver_exception
        from wtf_tpu.interp.runner import _LaneCtx

        ctx = _LaneCtx(self._ensure_view(), self._lane,
                       self.runner.cpu0_of(self._lane))
        deliver_exception(ctx, vector, error_code, cr2)

    def virt_read(self, gva: int, size: int) -> bytes:
        return self._ensure_view().virt_read(self._lane, gva, size)

    def virt_write(self, gva: int, data: bytes) -> None:
        self._ensure_view().virt_write(self._lane, gva, data)

    # -- breakpoints -------------------------------------------------------
    def set_breakpoint(self, gva: int, handler: BreakpointHandler) -> None:
        self.breakpoints[gva] = handler
        self.runner.cache.set_breakpoint(gva)

    # -- coverage ----------------------------------------------------------
    def last_new_coverage(self) -> Set[int]:
        if self._last_new_words is None:
            return set()
        return set(self.runner.cache.rips_of_bits(self._last_new_words))

    def aggregate_coverage(self) -> Set[int]:
        """All RIPs covered so far this campaign (decoded from the device
        aggregate bitmap)."""
        return set(self.runner.cache.rips_of_bits(np.asarray(self._agg_cov)))

    def revoke_last_new_coverage(self) -> None:
        if self._last_new_words is not None:
            self._agg_cov = self._agg_cov & ~jnp.asarray(self._last_new_words)
            self._last_new_words = None

    # -- misc ---------------------------------------------------------------
    def rdrand(self) -> int:
        view = self._ensure_view()
        nxt = splitmix64(int(view.r["rdrand"][self._lane]))
        view.r["rdrand"][self._lane] = np.uint64(nxt)
        return nxt

    def set_trace_file(self, path, trace_type: str) -> None:
        if trace_type in ("rip", "cov", "tenet"):
            self._trace_request = (path, trace_type)
        else:
            raise ValueError(f"unsupported trace type {trace_type!r}")

    def print_run_stats(self) -> None:
        s = self.runner.stats
        from wtf_tpu.utils.human import number_to_human as h

        print(f"[tpu] lanes={self.n_lanes} "
              f"testcases={h(self.stats['testcases'])} "
              f"batches={self.stats['batches']} "
              f"instructions={h(self.stats['instructions'])} "
              f"chunks={s['chunks']} decodes={s['decodes']} "
              f"fallbacks={s['fallbacks']} "
              f"smc={s['smc_updates']} bp_dispatches={s['bp_dispatches']}")
        # fused-step occupancy: what fraction of retired instructions ran
        # inside the Pallas kernel.  Printed whenever the fast path is
        # enabled, so 0% occupancy (every lane parks — the hot subset
        # misses this target) stays distinguishable from "off"
        fused = self.registry.counter("device.fused_steps").value
        if fused or getattr(self.runner, "fused_enabled", False):
            instr = max(self.registry.counter("device.instructions").value, 1)
            # the park split answers WHY lanes left the kernel: a cold
            # opclass (subset) vs a memory fault / overlay exhaustion
            ps = self.registry.counter("device.fused_park_subset").value
            pm = self.registry.counter("device.fused_park_mem").value
            print(f"[tpu] fused steps: {h(fused)} "
                  f"({fused / instr:.1%} of instructions in-kernel; "
                  f"parks: subset={h(ps)} mem={h(pm)})")
        by_class = s.get("fallbacks_by_opclass", {})
        if by_class:
            # attribution for the fallback total (VERDICT r5 item 3):
            # which instruction classes keep leaving the device path
            top = ", ".join(
                f"{name}={count}" for name, count in sorted(
                    by_class.items(), key=lambda kv: -kv[1])[:10])
            print(f"[tpu] fallbacks by opclass: {top}")


def _result_status(result: TestcaseResult) -> StatusCode:
    if isinstance(result, Ok):
        return StatusCode.OK
    if isinstance(result, Timedout):
        return StatusCode.TIMEDOUT
    if isinstance(result, Cr3Change):
        return StatusCode.CR3_CHANGE
    if isinstance(result, OverlayFull):
        return StatusCode.OVERLAY_FULL
    return StatusCode.CRASH
