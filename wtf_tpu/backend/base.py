"""The Backend contract (reference `Backend_t`, src/wtf/backend.h:161-596).

Pure-virtual surface the reference defines: Initialize / Run / Restore /
Stop / SetLimit / GetReg / SetReg / Rdrand / PrintRunStats / SetTraceFile /
SetBreakpoint / VirtTranslate / VirtRead / VirtWrite(Dirty) /
LastNewCoverage / RevokeLastNewCoverage — plus the non-virtual conveniences
implemented once over those (backend.cc:129-332): register shortcuts,
Windows-x64 argument accessors, SimulateReturnFromFunction, SaveCrash.

Semantic deltas from the reference, by design:
  - `run()` here takes no buffer: testcase insertion is the target's job
    (targets.insert_testcase writes guest memory through this API before
    run), matching the actual call order in RunTestcaseAndRestore
    (client.cc:88-180) while keeping the batch backend free to insert a
    whole batch at once.
  - breakpoint handlers receive the backend positionally (`handler(backend)`)
    exactly like the reference's `BreakpointHandler_t` (backend.h:110);
    on the batch backend the backend object is temporarily *lane-bound*
    during dispatch.
"""

from __future__ import annotations

import abc
from typing import Callable, Dict, List, Optional, Set

from wtf_tpu.core.results import Crash, TestcaseResult

BreakpointHandler = Callable[["Backend"], None]

# x86 register indices in encoding order (core.cpustate.GPR_NAMES):
# rax rcx rdx rbx rsp rbp rsi rdi r8..r15
_REG_IDX = {
    "rax": 0, "rcx": 1, "rdx": 2, "rbx": 3, "rsp": 4, "rbp": 5,
    "rsi": 6, "rdi": 7, "r8": 8, "r9": 9, "r10": 10, "r11": 11,
    "r12": 12, "r13": 13, "r14": 14, "r15": 15,
}


class Backend(abc.ABC):
    """One guest execution engine.  Register accessors operate on the
    *current* lane (the only lane for EmuBackend; the bound lane during
    batch dispatch for TpuBackend)."""

    # -- lifecycle (backend.h:171-199) -----------------------------------
    @abc.abstractmethod
    def initialize(self) -> None:
        """Build the execution engine around the snapshot (VM construction
        in the reference; device upload + machine allocation here)."""

    @abc.abstractmethod
    def run(self) -> TestcaseResult:
        """Execute until a stop condition; testcase already inserted."""

    @abc.abstractmethod
    def restore(self) -> None:
        """Roll back registers + dirty memory to the snapshot."""

    @abc.abstractmethod
    def stop(self, result: TestcaseResult) -> None:
        """Terminate the current testcase with `result` (callable from
        breakpoint handlers, like backend.h:191)."""

    def set_limit(self, limit: int) -> None:
        self.limit = limit

    # -- registers (backend.h:205-206 + shortcuts backend.cc:241-307) ----
    @abc.abstractmethod
    def get_reg(self, idx: int) -> int: ...

    def get_xmm(self, idx: int) -> int:
        """128-bit XMM read (reference GetReg covers vector regs too,
        bochscpu_backend.cc:1124-1190)."""
        raise NotImplementedError

    def set_xmm(self, idx: int, value: int) -> None:
        raise NotImplementedError

    @abc.abstractmethod
    def set_reg(self, idx: int, value: int) -> None: ...

    @abc.abstractmethod
    def get_rip(self) -> int: ...

    @abc.abstractmethod
    def set_rip(self, value: int) -> None: ...

    def get_rflags(self) -> int:
        """Current-lane RFLAGS (triage introspection: the vbreak capture
        snapshots it alongside the GPR file)."""
        raise NotImplementedError

    def get_icount(self) -> int:
        """Instructions retired by the current lane this run (triage
        introspection; 0-based at insert time)."""
        raise NotImplementedError

    def __getattr__(self, name):
        # rax()/rcx()/... accessor-mutator shortcuts (backend.cc:241-307)
        if name in _REG_IDX:
            idx = _REG_IDX[name]

            def accessor(value: Optional[int] = None):
                if value is None:
                    return self.get_reg(idx)
                self.set_reg(idx, value)

            return accessor
        raise AttributeError(name)

    def rip(self, value: Optional[int] = None):
        if value is None:
            return self.get_rip()
        self.set_rip(value)

    @property
    def current_lane(self) -> int:
        """The lane this backend's accessors currently address (always 0
        for single-lane backends; the bound lane during batch dispatch).
        Harness state that is per-guest (file tables, handle tables) must
        be keyed by this."""
        return 0

    # -- memory (backend.h:248-261, backend.cc:30-127) --------------------
    @abc.abstractmethod
    def virt_read(self, gva: int, size: int) -> bytes: ...

    @abc.abstractmethod
    def virt_write(self, gva: int, data: bytes) -> None:
        """Host-initiated guest write; always dirty-tracked (the overlay
        design makes every write dirty by construction, preserving the
        reference's VirtWriteDirty contract, backend.cc:91-127)."""

    def virt_write_dirty(self, gva: int, data: bytes) -> None:
        self.virt_write(gva, data)

    def virt_translate(self, gva: int, write: bool = False) -> int:
        """GVA -> GPA through the current lane's page tables (reference
        backend.h:248; harnesses use it for page-boundary placement).
        Raises the backend's fault type on non-present/non-writable."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement virt_translate")

    def phys_translate(self, gpa: int) -> int:
        """GPA -> backing offset (the reference returns a host pointer,
        backend.h:255; page-granular identity here)."""
        return gpa

    def inject_exception(self, vector: int, error_code: int = 0,
                         cr2: Optional[int] = None) -> None:
        """Vector an exception through the guest IDT on the current lane
        (reference `bochscpu_cpu_set_exception`, bochscpu_backend.cc:995-998
        / KVM event injection, kvm_backend.cc:2019-2042).  Raises the
        delivery error when the snapshot's IDT cannot service it."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement inject_exception")

    def page_faults_memory_if_needed(self, gva: int, size: int) -> bool:
        """Reference PageFaultsMemoryIfNeeded (backend.h:261,
        bochscpu_backend.cc:917-999): when part of [gva, gva+size) is not
        yet paged in (lazy VirtualAlloc-style PTEs), inject a #PF so the
        GUEST kernel pages it in, and return True — the calling breakpoint
        handler must then return and let the guest run; the breakpoint
        re-fires at the retried instruction and the range is probed again
        (one page faulted in per round, exactly the reference's dance).
        Returns False when the whole range is mapped and the host may
        write it directly."""
        from wtf_tpu.cpu.emu import MemFault
        from wtf_tpu.cpu.interrupts import PF_ERR_U, PF_ERR_W
        from wtf_tpu.interp.runner import HostFault

        page = 0x1000
        gva_end = gva + max(size, 1)
        pos = gva & ~(page - 1)
        page_to_fault = None
        while pos < gva_end:
            try:
                self.virt_translate(pos, write=True)
            except (MemFault, HostFault):
                page_to_fault = pos
                break
            pos += page
        if page_to_fault is None:
            return False
        # ErrorWrite | ErrorUser, like the reference's synthetic fault
        # (bochscpu_backend.cc:993-998)
        self.inject_exception(14, PF_ERR_W | PF_ERR_U, cr2=page_to_fault)
        return True

    def virt_read_u64(self, gva: int) -> int:
        return int.from_bytes(self.virt_read(gva, 8), "little")

    def virt_read_u32(self, gva: int) -> int:
        return int.from_bytes(self.virt_read(gva, 4), "little")

    def virt_write_u64(self, gva: int, value: int) -> None:
        self.virt_write(gva, (value & (1 << 64) - 1).to_bytes(8, "little"))

    def virt_read_string(self, gva: int, max_len: int = 1024) -> str:
        """NUL-terminated ASCII read (helper for harness logging)."""
        out = bytearray()
        while len(out) < max_len:
            byte = self.virt_read(gva + len(out), 1)
            if byte == b"\x00":
                break
            out += byte
        return out.decode("latin-1")

    # -- breakpoints (backend.h:231, backend.cc:214-239) ------------------
    @abc.abstractmethod
    def set_breakpoint(self, gva: int, handler: BreakpointHandler) -> None: ...

    def set_breakpoint_by_symbol(self, symbol: str,
                                 handler: BreakpointHandler) -> None:
        """Resolve `module!symbol` through the snapshot's symbol store
        (reference SetBreakpoint(const char*), backend.cc:214-239)."""
        addr = self.symbols.get(symbol)
        if addr is None:
            raise KeyError(f"symbol {symbol!r} not in symbol store")
        self.set_breakpoint(addr, handler)

    def set_breakpoint_if_symbol(self, symbol: str,
                                 handler: BreakpointHandler) -> bool:
        """set_breakpoint_by_symbol, but skip-on-missing: hook sets
        register detections only for symbols the snapshot carries (the
        reference behaves the same for e.g. verifier hooks on targets
        without app verifier, crash_detection_umode.cc:154-164)."""
        addr = self.symbols.get(symbol)
        if addr is None:
            return False
        self.set_breakpoint(addr, handler)
        return True

    # -- coverage (backend.h:583-589) --------------------------------------
    @abc.abstractmethod
    def last_new_coverage(self) -> Set[int]: ...

    @abc.abstractmethod
    def revoke_last_new_coverage(self) -> None: ...

    # -- determinism (backend.h:212) ---------------------------------------
    @abc.abstractmethod
    def rdrand(self) -> int:
        """Next value of the deterministic rdrand chain (reference keeps a
        Blake3-chained seed, bochscpu_backend.cc:874-885)."""

    # -- conveniences (backend.cc:129-212) ---------------------------------
    def simulate_return_from_function(self, return_value: int = 0) -> bool:
        """Pop the saved return address and return `return_value` in rax
        (backend.cc:129-147) — the NOP-a-function harness primitive."""
        self.set_reg(0, return_value)
        stack = self.get_reg(4)
        saved = self.virt_read_u64(stack)
        self.set_reg(4, stack + 8)
        self.set_rip(saved)
        return True

    def get_arg_address(self, idx: int) -> int:
        if idx <= 3:
            raise ValueError(
                "args 0-3 live in rcx/rdx/r8/r9; they have no address")
        return self.get_reg(4) + 8 + idx * 8

    def get_arg(self, idx: int) -> int:
        """Windows-x64 calling convention argument (backend.cc:178-192)."""
        if idx == 0:
            return self.get_reg(1)
        if idx == 1:
            return self.get_reg(2)
        if idx == 2:
            return self.get_reg(8)
        if idx == 3:
            return self.get_reg(9)
        return self.virt_read_u64(self.get_arg_address(idx))

    def save_crash(self, exception_address: int, exception_kind: str) -> None:
        """Name + stop like the reference's SaveCrash (backend.cc:204-212):
        the name becomes the on-disk filename under crashes/."""
        self.stop(Crash(f"crash-{exception_kind}-{exception_address:#x}"))

    def print_registers(self) -> None:
        """Windbg-style register dump of the current lane (reference
        PrintRegisters, backend.cc:309-332) — the harness-debugging aid
        breakpoint handlers reach for."""
        rows = (("rax", "rbx", "rcx"), ("rdx", "rsi", "rdi"),
                ("rip", "rsp", "rbp"), ("r8", "r9", "r10"),
                ("r11", "r12", "r13"), ("r14", "r15"))
        for row in rows:
            print(" ".join(
                f"{name:>3}={(self.rip() if name == 'rip' else getattr(self, name)()):016x}"
                for name in row))


    # -- batch facade ------------------------------------------------------
    def run_batch(self, insert: List[bytes], target) -> List[TestcaseResult]:
        """Run a list of testcases; returns one result each.

        Single-lane backends iterate the reference's canonical per-testcase
        sequence (client.cc:88-180: InsertTestcase -> Run -> Restore),
        restoring between testcases; the batch backend overrides this with
        one device dispatch for the whole list.  The final restore is the
        caller's (fuzz loop's) responsibility either way."""
        results: List[TestcaseResult] = []
        self._batch_new: List[bool] = []
        for i, data in enumerate(insert):
            if i > 0:
                target.restore()
                self.restore()
            target.insert_testcase(self, data)
            result = self.run()
            if isinstance(result, type(None)):
                raise AssertionError("run() returned None")
            from wtf_tpu.core.results import Timedout
            if isinstance(result, Timedout):
                self.revoke_last_new_coverage()
                self._batch_new.append(False)
            else:
                self._batch_new.append(bool(self.last_new_coverage()))
            results.append(result)
        return results

    def lane_found_new_coverage(self, lane: int) -> bool:
        return self._batch_new[lane]

    # -- misc --------------------------------------------------------------
    def set_trace_file(self, path, trace_type: str) -> None:
        """Arrange for a rip/cov trace of the next run (reference
        backend.h:224); implemented by backends that support it."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement tracing")

    def print_run_stats(self) -> None:
        pass


def guard_guest_faults(handler: BreakpointHandler) -> BreakpointHandler:
    """Wrap a breakpoint handler that dereferences guest-controlled
    pointers: a bad pointer must fail the TESTCASE (as the real kernel
    would A/V probing a syscall argument), not escape and abort the
    campaign."""
    from wtf_tpu.cpu.emu import MemFault
    from wtf_tpu.interp.runner import HostFault

    def wrapped(backend):
        try:
            handler(backend)
        except (MemFault, HostFault) as e:
            kind = "write" if getattr(e, "write", False) else "read"
            backend.save_crash(getattr(e, "gva", 0), kind)
    return wrapped
