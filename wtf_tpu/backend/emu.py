"""EmuBackend: the oracle CPU behind the Backend contract.

Plays the role bochscpu plays in the reference (slowest, fully
deterministic, precise — README.md:7) *and* the fake-backend test seam
SURVEY.md §4 calls for: the whole harness/fuzz/distribution plane runs on
it without a TPU in sight.  One guest, one lane.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, Optional, Set

from wtf_tpu.backend.base import Backend, BreakpointHandler
from wtf_tpu.core.results import (
    Cr3Change, Crash, Ok, TestcaseResult, Timedout,
)
from wtf_tpu.cpu.emu import (
    DivideError, EmuCpu, EmuMem, GuestCrash, MemFault, UnsupportedInsn,
)
from wtf_tpu.cpu.interrupts import (
    VEC_DE, VEC_PF, DeliveryFailed, deliver_page_fault,
)
from wtf_tpu.snapshot.loader import Snapshot
from wtf_tpu import telemetry
from wtf_tpu.telemetry import Registry, StatsDict
from wtf_tpu.utils.hashing import splitmix64


class EmuBackend(Backend):
    def __init__(self, snapshot: Snapshot, limit: int = 0,
                 deliver_exceptions: Optional[bool] = None,
                 registry: Optional[Registry] = None, events=None):
        self.snapshot = snapshot
        self.symbols = snapshot.symbols
        self.limit = limit
        self.registry, self.events = telemetry.resolve(
            registry=registry, events=events)
        # Guest exception delivery through the snapshot's IDT (auto: on
        # exactly when the snapshot carries one) — see cpu/interrupts.py.
        if deliver_exceptions is None:
            deliver_exceptions = snapshot.cpu.idtr.limit > 0
        self.deliver_exceptions = deliver_exceptions
        self.breakpoints: Dict[int, BreakpointHandler] = {}
        self.cpu: Optional[EmuCpu] = None
        self._stop_result: Optional[TestcaseResult] = None
        self._run_cov: Set[int] = set()
        self._aggregate_cov: Set[int] = set()
        self._last_new: Set[int] = set()
        self._trace_file = None
        self._trace_type = None
        self.stats = StatsDict(self.registry, "backend",
                               fields=("runs", "instructions"))

    # -- lifecycle ---------------------------------------------------------
    def initialize(self) -> None:
        self.cpu = EmuCpu(EmuMem(self.snapshot.physmem), self.snapshot.cpu)

    def run(self) -> TestcaseResult:
        assert self.cpu is not None, "initialize() first"
        cpu = self.cpu
        self._stop_result = None
        self._run_cov = set()
        skip_rip = None  # one-shot bp suppression after handler resume
        result: TestcaseResult
        writer = None
        tenet = False
        if self._trace_file is not None:
            from wtf_tpu.trace import (
                CovTraceWriter, RipTraceWriter, TenetTraceWriter,
            )

            cls = {"rip": RipTraceWriter, "cov": CovTraceWriter,
                   "tenet": TenetTraceWriter}[self._trace_type]
            writer = cls(self._trace_file)
            tenet = self._trace_type == "tenet"
        try:
            while True:
                if self.limit and cpu.icount >= self.limit:
                    result = Timedout()
                    break
                rip = cpu.rip
                if rip in self.breakpoints and rip != skip_rip:
                    skip_rip = rip
                    self.breakpoints[rip](self)
                    if self._stop_result is not None:
                        result = self._stop_result
                        break
                    if cpu.rip != rip:
                        skip_rip = None
                    continue
                skip_rip = None
                self._run_cov.add(rip)
                if writer is not None and not tenet:
                    writer.on_step(rip)
                if tenet:
                    cpu.access_log = []
                try:
                    cpu.step()
                except GuestCrash as e:
                    result = Crash(f"crash-int-{e.rip:#x}")
                    break
                except MemFault as e:
                    if self._deliver(VEC_PF, fault=e):
                        continue  # guest services the fault and retries
                    # execute-refinement: a fault on the fetch address is an
                    # exec A/V (reference refines A/Vs into read/write/
                    # execute, crash_detection_umode.cc:104-121)
                    if e.gva == rip and not e.write:
                        kind = "execute"
                    else:
                        kind = "write" if e.write else "read"
                    result = Crash(f"crash-{kind}-{e.gva:#x}")
                    break
                except DivideError:
                    if self._deliver(VEC_DE):
                        continue
                    result = Crash(f"crash-de-{rip:#x}")
                    break
                except UnsupportedInsn as e:
                    result = Crash(f"crash-unsupported-{e.rip:#x}")
                    break
                if tenet:
                    self._tenet_step(writer)
                if cpu.cr3_event is not None:
                    if cpu.cr3_event != self.snapshot.cpu.cr3:
                        result = Cr3Change()
                        break
                    cpu.cr3_event = None
        finally:
            if writer is not None:
                writer.close()
            cpu.access_log = None
            self._trace_file = None
        self.stats["runs"] += 1
        self.stats["instructions"] += cpu.icount
        # coverage merge (reference: per-run set union into the aggregate,
        # LastNewCoverage = the delta, bochscpu_backend.cc:497-505)
        self._last_new = self._run_cov - self._aggregate_cov
        self._aggregate_cov |= self._last_new
        return result

    def _deliver(self, vector: int, fault: Optional[MemFault] = None) -> bool:
        """Try to vector a hardware fault through the guest IDT
        (cpu/interrupts.py); False keeps the pre-delivery terminal-crash
        behavior (no IDT, absent gate, or the delivery itself faulted —
        the double-fault analog)."""
        if not self.deliver_exceptions:
            return False
        cpu = self.cpu
        try:
            if vector == VEC_PF:
                def reads(g):
                    try:
                        cpu.translate(g, write=False)
                        return True
                    except MemFault:
                        return False

                deliver_page_fault(cpu, fault.gva, fault.write, reads)
            else:
                cpu.deliver_exception(vector)
        except (DeliveryFailed, MemFault):
            return False
        return True

    def inject_exception(self, vector: int, error_code: int = 0,
                         cr2: Optional[int] = None) -> None:
        self.cpu.deliver_exception(vector, error_code, cr2)

    def _tenet_step(self, writer) -> None:
        """Post-instruction tenet delta: registers + the step's accesses
        (data fetched post-insn like the reference, bochscpu:1276-1289)."""
        cpu = self.cpu
        accesses, cpu.access_log = cpu.access_log, None
        regs = {name: cpu.gpr[i] for i, name in enumerate(
            ("rax", "rcx", "rdx", "rbx", "rsp", "rbp", "rsi", "rdi",
             "r8", "r9", "r10", "r11", "r12", "r13", "r14", "r15"))}
        regs["rip"] = cpu.rip
        resolved = []
        for kind, gva, size in accesses or ():
            try:
                data = cpu.virt_read(gva, min(size, 64))
            except MemFault:
                continue  # e.g. the faulting access of a crashing insn
            resolved.append((kind, gva, data))
        writer.on_step(regs, resolved)

    def restore(self) -> None:
        self.cpu.restore()

    def stop(self, result: TestcaseResult) -> None:
        self._stop_result = result

    # -- registers ---------------------------------------------------------
    def get_reg(self, idx: int) -> int:
        return self.cpu.gpr[idx]

    def set_reg(self, idx: int, value: int) -> None:
        self.cpu.gpr[idx] = value & (1 << 64) - 1

    def get_xmm(self, idx: int) -> int:
        lo, hi = self.cpu.xmm[idx]
        return lo | (hi << 64)

    def set_xmm(self, idx: int, value: int) -> None:
        self.cpu.xmm[idx] = [value & (1 << 64) - 1, (value >> 64) & (1 << 64) - 1]

    def get_rip(self) -> int:
        return self.cpu.rip

    def set_rip(self, value: int) -> None:
        self.cpu.rip = value & (1 << 64) - 1

    def get_rflags(self) -> int:
        return self.cpu.rflags

    def get_icount(self) -> int:
        return self.cpu.icount

    # -- memory ------------------------------------------------------------
    def virt_translate(self, gva: int, write: bool = False) -> int:
        return self.cpu.translate(gva, write)

    def virt_read(self, gva: int, size: int) -> bytes:
        return self.cpu.virt_read(gva, size)

    def virt_write(self, gva: int, data: bytes) -> None:
        self.cpu.virt_write(gva, data, enforce=False)

    # -- breakpoints -------------------------------------------------------
    def set_breakpoint(self, gva: int, handler: BreakpointHandler) -> None:
        self.breakpoints[gva] = handler

    # -- coverage ----------------------------------------------------------
    def last_new_coverage(self) -> Set[int]:
        return set(self._last_new)

    def aggregate_coverage(self) -> Set[int]:
        """All RIPs covered so far this campaign (feeds the .cov-file
        coverage report, reference coverage.cov aggregate README.md:166)."""
        return set(self._aggregate_cov)

    def revoke_last_new_coverage(self) -> None:
        # reference client revokes after a timeout so flaky paths don't
        # enter the corpus (client.cc:122-125)
        self._aggregate_cov -= self._last_new
        self._last_new = set()

    # -- misc ---------------------------------------------------------------
    def rdrand(self) -> int:
        self.cpu.rdrand_state = splitmix64(self.cpu.rdrand_state)
        return self.cpu.rdrand_state

    def set_trace_file(self, path, trace_type: str) -> None:
        if trace_type not in ("rip", "cov", "tenet"):
            raise ValueError(f"unsupported trace type {trace_type!r}")
        self._trace_file = Path(path)
        self._trace_type = trace_type

    def print_run_stats(self) -> None:
        print(f"[emu] runs={self.stats['runs']} "
              f"instructions={self.stats['instructions']}")
