"""EmuBackend: the oracle CPU behind the Backend contract.

Plays the role bochscpu plays in the reference (slowest, fully
deterministic, precise — README.md:7) *and* the fake-backend test seam
SURVEY.md §4 calls for: the whole harness/fuzz/distribution plane runs on
it without a TPU in sight.  One guest, one lane.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, Optional, Set

from wtf_tpu.backend.base import Backend, BreakpointHandler
from wtf_tpu.core.results import (
    Cr3Change, Crash, Ok, TestcaseResult, Timedout,
)
from wtf_tpu.cpu.emu import (
    DivideError, EmuCpu, EmuMem, GuestCrash, MemFault, UnsupportedInsn,
)
from wtf_tpu.snapshot.loader import Snapshot
from wtf_tpu.utils.hashing import splitmix64


class EmuBackend(Backend):
    def __init__(self, snapshot: Snapshot, limit: int = 0):
        self.snapshot = snapshot
        self.symbols = snapshot.symbols
        self.limit = limit
        self.breakpoints: Dict[int, BreakpointHandler] = {}
        self.cpu: Optional[EmuCpu] = None
        self._stop_result: Optional[TestcaseResult] = None
        self._run_cov: Set[int] = set()
        self._aggregate_cov: Set[int] = set()
        self._last_new: Set[int] = set()
        self._trace_file = None
        self._trace_type = None
        self.stats = {"runs": 0, "instructions": 0}

    # -- lifecycle ---------------------------------------------------------
    def initialize(self) -> None:
        self.cpu = EmuCpu(EmuMem(self.snapshot.physmem), self.snapshot.cpu)

    def run(self) -> TestcaseResult:
        assert self.cpu is not None, "initialize() first"
        cpu = self.cpu
        self._stop_result = None
        self._run_cov = set()
        skip_rip = None  # one-shot bp suppression after handler resume
        result: TestcaseResult
        trace = None
        if self._trace_file is not None:
            trace = open(self._trace_file, "w")
        try:
            while True:
                if self.limit and cpu.icount >= self.limit:
                    result = Timedout()
                    break
                rip = cpu.rip
                if rip in self.breakpoints and rip != skip_rip:
                    skip_rip = rip
                    self.breakpoints[rip](self)
                    if self._stop_result is not None:
                        result = self._stop_result
                        break
                    if cpu.rip != rip:
                        skip_rip = None
                    continue
                skip_rip = None
                if rip not in self._run_cov:
                    self._run_cov.add(rip)
                    if trace is not None and self._trace_type == "cov":
                        trace.write(f"{rip:#x}\n")
                if trace is not None and self._trace_type == "rip":
                    trace.write(f"{rip:#x}\n")
                try:
                    cpu.step()
                except GuestCrash as e:
                    result = Crash(f"crash-int-{e.rip:#x}")
                    break
                except MemFault as e:
                    # execute-refinement: a fault on the fetch address is an
                    # exec A/V (reference refines A/Vs into read/write/
                    # execute, crash_detection_umode.cc:104-121)
                    if e.gva == rip and not e.write:
                        kind = "execute"
                    else:
                        kind = "write" if e.write else "read"
                    result = Crash(f"crash-{kind}-{e.gva:#x}")
                    break
                except DivideError:
                    result = Crash(f"crash-de-{rip:#x}")
                    break
                except UnsupportedInsn as e:
                    result = Crash(f"crash-unsupported-{e.rip:#x}")
                    break
                if cpu.cr3_event is not None:
                    if cpu.cr3_event != self.snapshot.cpu.cr3:
                        result = Cr3Change()
                        break
                    cpu.cr3_event = None
        finally:
            if trace is not None:
                trace.close()
            self._trace_file = None
        self.stats["runs"] += 1
        self.stats["instructions"] += cpu.icount
        # coverage merge (reference: per-run set union into the aggregate,
        # LastNewCoverage = the delta, bochscpu_backend.cc:497-505)
        self._last_new = self._run_cov - self._aggregate_cov
        self._aggregate_cov |= self._last_new
        return result

    def restore(self) -> None:
        self.cpu.restore()

    def stop(self, result: TestcaseResult) -> None:
        self._stop_result = result

    # -- registers ---------------------------------------------------------
    def get_reg(self, idx: int) -> int:
        return self.cpu.gpr[idx]

    def set_reg(self, idx: int, value: int) -> None:
        self.cpu.gpr[idx] = value & (1 << 64) - 1

    def get_rip(self) -> int:
        return self.cpu.rip

    def set_rip(self, value: int) -> None:
        self.cpu.rip = value & (1 << 64) - 1

    # -- memory ------------------------------------------------------------
    def virt_read(self, gva: int, size: int) -> bytes:
        return self.cpu.virt_read(gva, size)

    def virt_write(self, gva: int, data: bytes) -> None:
        self.cpu.virt_write(gva, data, enforce=False)

    # -- breakpoints -------------------------------------------------------
    def set_breakpoint(self, gva: int, handler: BreakpointHandler) -> None:
        self.breakpoints[gva] = handler

    # -- coverage ----------------------------------------------------------
    def last_new_coverage(self) -> Set[int]:
        return set(self._last_new)

    def revoke_last_new_coverage(self) -> None:
        # reference client revokes after a timeout so flaky paths don't
        # enter the corpus (client.cc:122-125)
        self._aggregate_cov -= self._last_new
        self._last_new = set()

    # -- misc ---------------------------------------------------------------
    def rdrand(self) -> int:
        self.cpu.rdrand_state = splitmix64(self.cpu.rdrand_state)
        return self.cpu.rdrand_state

    def set_trace_file(self, path, trace_type: str) -> None:
        if trace_type not in ("rip", "cov"):
            raise ValueError(f"unsupported trace type {trace_type!r}")
        self._trace_file = Path(path)
        self._trace_type = trace_type

    def print_run_stats(self) -> None:
        print(f"[emu] runs={self.stats['runs']} "
              f"instructions={self.stats['instructions']}")
