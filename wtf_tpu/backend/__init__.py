"""Execution backends: the pluggable layer the whole system runs on.

Mirror of the reference's `Backend_t` plugin architecture (reference
src/wtf/backend.h:161-596, SURVEY.md §2.2): everything above this layer
(targets, crash detection, fuzz loop, CLI) talks only to the `Backend`
contract, so backends are interchangeable:

  base.py - the contract + derived conveniences (GetArg, SimulateReturn...)
  emu.py  - EmuBackend over the pure-Python oracle CPU: the deterministic
            reference backend (role of bochscpu) and the TPU-less test seam
  tpu.py  - TpuBackend over the batched device interpreter: N testcase
            lanes per Run, the reason this framework exists

A `mesh_devices` kwarg on the tpu backend upgrades it to the mesh
campaign driver (wtf_tpu/meshrun): the same contract, lane count =
lanes_per_chip x chips over a jax.sharding.Mesh.

Selected by name like the reference's --backend flag (wtf.cc:208-225).
"""

from wtf_tpu.backend.base import Backend, BreakpointHandler  # noqa: F401
from wtf_tpu.backend.emu import EmuBackend  # noqa: F401
from wtf_tpu.backend.tpu import TpuBackend  # noqa: F401


def create_backend(name: str, snapshot, **kwargs) -> Backend:
    """Instantiate a backend by CLI name (reference wtf.cc:403-415)."""
    if name == "emu":
        kwargs.pop("n_lanes", None)
        kwargs.pop("mesh_devices", None)
        # supervision guards DEVICE dispatch seams; the pure-host oracle
        # backend has none
        for key in ("supervise", "dispatch_timeout", "promote_after",
                    "max_batch_retries", "quarantine_threshold",
                    "device_decode"):
            kwargs.pop(key, None)
        return EmuBackend(snapshot, **kwargs)
    if name == "tpu":
        if kwargs.get("mesh_devices") is not None:
            from wtf_tpu.meshrun.backend import MeshBackend

            return MeshBackend(snapshot, **kwargs)
        kwargs.pop("mesh_devices", None)
        return TpuBackend(snapshot, **kwargs)
    raise ValueError(f"unknown backend {name!r} (expected emu|tpu)")
