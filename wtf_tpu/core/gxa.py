"""Strong-ish guest address helpers.

The reference wraps guest virtual/physical addresses in strong C++ types
(`Gva_t` / `Gpa_t`, reference src/wtf/gxa.h:10-88) so the two can't be mixed.
In Python we keep them as plain ints at the API boundary, but give them named
aliases + the same Align/Offset helpers so call sites read the same.  Inside
jitted interpreter code addresses are uint64 jax arrays.
"""

from __future__ import annotations

PAGE_SHIFT = 12
PAGE_SIZE = 1 << PAGE_SHIFT  # reference src/wtf/ram.h:10-17 (Page::Size)

# Type aliases for documentation purposes.
Gva = int  # guest virtual address
Gpa = int  # guest physical address


def page_align(addr: int) -> int:
    """Align an address down to its page base (gxa.h Align())."""
    return addr & ~(PAGE_SIZE - 1)


def page_offset(addr: int) -> int:
    """Offset of an address within its page (gxa.h Offset())."""
    return addr & (PAGE_SIZE - 1)


def page_number(addr: int) -> int:
    """Page frame number of an address."""
    return addr >> PAGE_SHIFT


def is_canonical(gva: int) -> bool:
    """True if `gva` is a canonical 48-bit x86-64 virtual address."""
    upper = gva >> 47
    return upper == 0 or upper == (1 << 17) - 1
