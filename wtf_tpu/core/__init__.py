"""Core types: strong addresses, CPU state, testcase results, options."""

from wtf_tpu.core.gxa import Gva, Gpa, PAGE_SIZE, PAGE_SHIFT, page_align, page_offset
from wtf_tpu.core.cpustate import (
    CpuState,
    Seg,
    GlobalSeg,
    load_cpu_state_json,
    sanitize_cpu_state,
)
from wtf_tpu.core.results import (
    TestcaseResult,
    Ok,
    Timedout,
    Cr3Change,
    Crash,
)
