"""Full x86-64 CPU state: the register half of a snapshot.

Equivalent of the reference's `CpuState_t` (reference src/wtf/globals.h:1020-1159)
plus its JSON loader `LoadCpuStateFromJSON` (src/wtf/utils.cc:57-193) and
`SanitizeCpuState` (src/wtf/utils.cc:195-258).  The on-disk format is the
`regs.json` emitted by the external bdump.js windbg script, so snapshots taken
for the reference load here unchanged.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Union

MASK64 = (1 << 64) - 1

# Canonical GPR order used across the whole framework (index into the
# interpreter's gpr array).  Matches x86-64 encoding order (reg field).
GPR_NAMES = (
    "rax", "rcx", "rdx", "rbx", "rsp", "rbp", "rsi", "rdi",
    "r8", "r9", "r10", "r11", "r12", "r13", "r14", "r15",
)

# RFLAGS bit positions (reference src/wtf/globals.h Rflags_t bitfield union).
RFLAGS_CF = 1 << 0
RFLAGS_RESERVED1 = 1 << 1  # always set
RFLAGS_PF = 1 << 2
RFLAGS_AF = 1 << 4
RFLAGS_ZF = 1 << 6
RFLAGS_SF = 1 << 7
RFLAGS_TF = 1 << 8
RFLAGS_IF = 1 << 9
RFLAGS_DF = 1 << 10
RFLAGS_OF = 1 << 11

# CR0 / CR4 / EFER bits we care about (globals.h Cr0_t/Cr4_t/Efer_t).
CR0_PE = 1 << 0
CR0_PG = 1 << 31
CR4_PAE = 1 << 5
CR4_LA57 = 1 << 12
EFER_LME = 1 << 8
EFER_LMA = 1 << 10
EFER_NXE = 1 << 11


@dataclasses.dataclass
class Seg:
    """Segment register (reference globals.h:33-64 `Seg_t`)."""

    present: bool = False
    selector: int = 0
    base: int = 0
    limit: int = 0
    attr: int = 0

    @property
    def reserved_bits(self) -> int:
        # Seg_t stores limit[16:20] in a Reserved attr subfield; bdump packs
        # them into attr bits 8..11 on the wtf side.  We only need them for the
        # sanitize-time validity check.
        return (self.attr >> 8) & 0xF


@dataclasses.dataclass
class GlobalSeg:
    """GDTR/IDTR (reference globals.h:66-76 `GlobalSeg_t`)."""

    base: int = 0
    limit: int = 0


def _zmm_default() -> list:
    # 32 ZMM registers x 64 bytes, stored as 8 u64 limbs each.
    return [[0] * 8 for _ in range(32)]


@dataclasses.dataclass
class CpuState:
    """Complete architectural state captured in `regs.json`.

    Field set mirrors reference `CpuState_t` (globals.h:1020-1159): 16 GPRs,
    rip/rflags, 8 segment registers, gdtr/idtr, control registers, debug
    registers, 13 MSRs, x87/SSE state, 32 ZMM registers.
    """

    # GPRs
    rax: int = 0
    rbx: int = 0
    rcx: int = 0
    rdx: int = 0
    rsi: int = 0
    rdi: int = 0
    rip: int = 0
    rsp: int = 0
    rbp: int = 0
    r8: int = 0
    r9: int = 0
    r10: int = 0
    r11: int = 0
    r12: int = 0
    r13: int = 0
    r14: int = 0
    r15: int = 0
    rflags: int = 0x2

    # Segments
    es: Seg = dataclasses.field(default_factory=Seg)
    cs: Seg = dataclasses.field(default_factory=Seg)
    ss: Seg = dataclasses.field(default_factory=Seg)
    ds: Seg = dataclasses.field(default_factory=Seg)
    fs: Seg = dataclasses.field(default_factory=Seg)
    gs: Seg = dataclasses.field(default_factory=Seg)
    tr: Seg = dataclasses.field(default_factory=Seg)
    ldtr: Seg = dataclasses.field(default_factory=Seg)
    gdtr: GlobalSeg = dataclasses.field(default_factory=GlobalSeg)
    idtr: GlobalSeg = dataclasses.field(default_factory=GlobalSeg)

    # Control / debug registers
    cr0: int = 0
    cr2: int = 0
    cr3: int = 0
    cr4: int = 0
    cr8: int = 0
    xcr0: int = 0
    dr0: int = 0
    dr1: int = 0
    dr2: int = 0
    dr3: int = 0
    dr6: int = 0
    dr7: int = 0

    # MSRs
    tsc: int = 0
    apic_base: int = 0
    sysenter_cs: int = 0
    sysenter_esp: int = 0
    sysenter_eip: int = 0
    pat: int = 0
    efer: int = 0
    star: int = 0
    lstar: int = 0
    cstar: int = 0
    sfmask: int = 0
    kernel_gs_base: int = 0
    tsc_aux: int = 0

    # x87 / SSE
    fpcw: int = 0x27F
    fpsw: int = 0
    fptw: int = 0xFFFF
    fpop: int = 0
    fpst: list = dataclasses.field(default_factory=lambda: [0] * 8)
    mxcsr: int = 0x1F80
    mxcsr_mask: int = 0xFFBF

    # Vector state: 32 regs x 8 u64 limbs (low 2 limbs = XMM, 4 = YMM).
    zmm: list = dataclasses.field(default_factory=_zmm_default)

    def gpr_list(self) -> list:
        """GPRs in x86 encoding order (GPR_NAMES)."""
        return [getattr(self, name) & MASK64 for name in GPR_NAMES]

    def set_gpr_list(self, values) -> None:
        for name, value in zip(GPR_NAMES, values):
            setattr(self, name, int(value) & MASK64)

    def long_mode(self) -> bool:
        return bool(self.efer & EFER_LMA)

    def paging_enabled(self) -> bool:
        return bool(self.cr0 & CR0_PG)

    def copy(self) -> "CpuState":
        new = dataclasses.replace(self)
        new.fpst = list(self.fpst)
        new.zmm = [list(limbs) for limbs in self.zmm]
        for seg in ("es", "cs", "ss", "ds", "fs", "gs", "tr", "ldtr"):
            setattr(new, seg, dataclasses.replace(getattr(self, seg)))
        new.gdtr = dataclasses.replace(self.gdtr)
        new.idtr = dataclasses.replace(self.idtr)
        return new


def _parse_u64(value: Union[str, int]) -> int:
    if isinstance(value, int):
        return value & MASK64
    return int(value, 0) & MASK64


_REG_KEYS = [
    "rax", "rbx", "rcx", "rdx", "rsi", "rdi", "rip", "rsp", "rbp",
    "r8", "r9", "r10", "r11", "r12", "r13", "r14", "r15", "rflags",
    "tsc", "apic_base", "sysenter_cs", "sysenter_esp", "sysenter_eip",
    "pat", "efer", "star", "lstar", "cstar", "sfmask", "kernel_gs_base",
    "tsc_aux", "fpcw", "fpsw", "fptw", "cr0", "cr2", "cr3", "cr4", "cr8",
    "xcr0", "dr0", "dr1", "dr2", "dr3", "dr6", "dr7", "mxcsr",
    "mxcsr_mask", "fpop",
]

_SEG_KEYS = ["es", "cs", "ss", "ds", "fs", "gs", "tr", "ldtr"]


def load_cpu_state_json(path) -> CpuState:
    """Load a bdump.js `regs.json` into a CpuState.

    Format compatibility with reference `LoadCpuStateFromJSON`
    (src/wtf/utils.cc:57-193): every scalar register is a hex string; segments
    are objects with present/selector/base/limit/attr; gdtr/idtr have
    base/limit; fpst is 8 entries that may be "Infinity"-style strings for an
    uninitialized x87 stack (in which case fptw is forced to 0xffff, matching
    the reference's windbg-fptw workaround at utils.cc:156-191).
    """
    data = json.loads(Path(path).read_text())
    state = CpuState()

    for key in _REG_KEYS:
        if key in data:
            setattr(state, key, _parse_u64(data[key]))

    for key in _SEG_KEYS:
        if key not in data:
            continue
        seg_json = data[key]
        seg = Seg(
            present=bool(seg_json.get("present", False)),
            selector=_parse_u64(seg_json.get("selector", 0)),
            base=_parse_u64(seg_json.get("base", 0)),
            limit=_parse_u64(seg_json.get("limit", 0)),
            attr=_parse_u64(seg_json.get("attr", 0)),
        )
        setattr(state, key, seg)

    for key, attr in (("gdtr", "gdtr"), ("idtr", "idtr")):
        if key in data:
            setattr(
                state,
                attr,
                GlobalSeg(
                    base=_parse_u64(data[key].get("base", 0)),
                    limit=_parse_u64(data[key].get("limit", 0)),
                ),
            )

    # x87 stack slots: bdump emits "0xInfinity"-ish strings when the FPU
    # state was never materialized; treat those as zero and force an empty
    # tag word if everything was empty (utils.cc:156-191).  NOT masked to
    # 64 bits: live entries are 80-bit extended values — consumers reduce
    # them to the double model (cpu/emu.py _f80_to_f64_bits).
    all_slots_zero = True
    if "fpst" in data:
        for idx, value in enumerate(data["fpst"][:8]):
            if isinstance(value, str) and "Infinity" in value:
                state.fpst[idx] = 0
            else:
                state.fpst[idx] = (int(value, 0) if isinstance(value, str)
                                   else int(value))
                all_slots_zero = False
    if state.fptw == 0 and all_slots_zero:
        state.fptw = 0xFFFF

    if "zmm" in data:
        for idx, reg in enumerate(data["zmm"][:32]):
            if isinstance(reg, dict):
                # bdump format: {"q": ["0x..", ...]} or flat hex string
                limbs = reg.get("q", [])
            else:
                limbs = reg
            if isinstance(limbs, str):
                raw = int(limbs, 0)
                parsed = [(raw >> (64 * i)) & MASK64 for i in range(8)]
            else:
                parsed = [_parse_u64(v) for v in limbs][:8]
            parsed += [0] * (8 - len(parsed))
            state.zmm[idx] = parsed

    return state


def sanitize_cpu_state(state: CpuState) -> bool:
    """Apply the reference's snapshot-state fixups (utils.cc:195-258).

    - cr8 forced to 0 when rip is user-mode,
    - hardware breakpoints (dr0-dr3, dr6, dr7) cleared,
    - segment attr sanity check (limit[16:20] must match the attr copy),
    - mxcsr_mask defaulted to 0xffbf when the dump recorded 0.

    Returns False when the state is unusable (bad segment attributes).
    """
    if state.rip < 0x7FFF_FFFF_0000 and state.cr8 != 0:
        state.cr8 = 0

    for name in ("dr0", "dr1", "dr2", "dr3", "dr6", "dr7"):
        if getattr(state, name) != 0:
            setattr(state, name, 0)

    for name in ("es", "fs", "cs", "gs", "ss", "ds"):
        seg: Seg = getattr(state, name)
        if seg.reserved_bits != ((seg.limit >> 16) & 0xF):
            return False

    if state.mxcsr_mask == 0:
        state.mxcsr_mask = 0xFFBF

    return True
