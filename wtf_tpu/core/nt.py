"""NT guest-structure definitions and status codes.

Role of the reference's nt.h (src/wtf/nt.h, 342 LoC): the Windows-shaped
constants and struct layouts harness code needs to introspect a guest —
EXCEPTION_RECORD parsing for user-mode crash detection
(crash_detection_umode.cc:53-129), NTSTATUS codes for guest-fs hook
returns (fshooks.cc), IO_STATUS_BLOCK/OBJECT_ATTRIBUTES shapes, and the
exception-code pretty printer (utils.cc:416-472).
"""

from __future__ import annotations

import dataclasses
import struct
from typing import List

# -- NTSTATUS ----------------------------------------------------------------

STATUS_SUCCESS = 0x00000000
STATUS_PENDING = 0x00000103
STATUS_BUFFER_OVERFLOW = 0x80000005
STATUS_UNSUCCESSFUL = 0xC0000001
STATUS_NOT_IMPLEMENTED = 0xC0000002
STATUS_INVALID_HANDLE = 0xC0000008
STATUS_INVALID_PARAMETER = 0xC000000D
STATUS_NO_SUCH_FILE = 0xC000000F
STATUS_END_OF_FILE = 0xC0000011
STATUS_ACCESS_DENIED = 0xC0000022
STATUS_OBJECT_NAME_NOT_FOUND = 0xC0000034
STATUS_OBJECT_PATH_NOT_FOUND = 0xC000003A
STATUS_MEMORY_NOT_ALLOCATED = 0xC00000A0

# -- exception codes ---------------------------------------------------------

EXCEPTION_ACCESS_VIOLATION = 0xC0000005
EXCEPTION_DATATYPE_MISALIGNMENT = 0x80000002
EXCEPTION_BREAKPOINT = 0x80000003
EXCEPTION_SINGLE_STEP = 0x80000004
EXCEPTION_ARRAY_BOUNDS_EXCEEDED = 0xC000008C
EXCEPTION_FLT_DIVIDE_BY_ZERO = 0xC000008E
EXCEPTION_INT_DIVIDE_BY_ZERO = 0xC0000094
EXCEPTION_INT_OVERFLOW = 0xC0000095
EXCEPTION_PRIV_INSTRUCTION = 0xC0000096
EXCEPTION_ILLEGAL_INSTRUCTION = 0xC000001D
EXCEPTION_STACK_OVERFLOW = 0xC00000FD
EXCEPTION_STACK_BUFFER_OVERRUN = 0xC0000409
EXCEPTION_GUARD_PAGE = 0x80000001
EXCEPTION_HEAP_CORRUPTION = 0xC0000374
DBG_PRINTEXCEPTION_C = 0x40010006
DBG_PRINTEXCEPTION_WIDE_C = 0x4001000A
CPP_EH_EXCEPTION = 0xE06D7363  # msvc c++ throw ('msc'|0xE0)

_EXCEPTION_NAMES = {
    EXCEPTION_ACCESS_VIOLATION: "access-violation",
    EXCEPTION_BREAKPOINT: "breakpoint",
    EXCEPTION_SINGLE_STEP: "single-step",
    EXCEPTION_INT_DIVIDE_BY_ZERO: "divide-by-zero",
    EXCEPTION_INT_OVERFLOW: "integer-overflow",
    EXCEPTION_ILLEGAL_INSTRUCTION: "illegal-instruction",
    EXCEPTION_PRIV_INSTRUCTION: "privileged-instruction",
    EXCEPTION_STACK_OVERFLOW: "stack-overflow",
    EXCEPTION_STACK_BUFFER_OVERRUN: "stack-buffer-overrun",
    EXCEPTION_GUARD_PAGE: "guard-page",
    EXCEPTION_HEAP_CORRUPTION: "heap-corruption",
    DBG_PRINTEXCEPTION_C: "dbg-print",
    DBG_PRINTEXCEPTION_WIDE_C: "dbg-print-wide",
    CPP_EH_EXCEPTION: "cpp-exception",
}


def exception_code_to_str(code: int) -> str:
    """Pretty name for crash filenames (reference ExceptionCodeToStr,
    utils.cc:416-472)."""
    return _EXCEPTION_NAMES.get(code, f"exception-{code:#x}")


# -- ntdll pointer encoding --------------------------------------------------

_M64 = (1 << 64) - 1


def decode_pointer(cookie: int, value: int) -> int:
    """ntdll's DecodePointer: ror64(value, 0x40 - (cookie & 0x3F)) ^ cookie
    (reference utils.cc:302-304).  Harnesses need it to walk encoded
    handler lists (PEB fast-fail handlers, KernelCallbackTable, etc.);
    the cookie comes from the guest (e.g. ntdll!RtlpProcessCookie or a
    NtQueryInformationProcess(ProcessCookie) result read at init)."""
    rot = 0x40 - (cookie & 0x3F)
    value &= _M64
    rotated = ((value >> rot) | (value << (64 - rot))) & _M64
    return rotated ^ cookie


def encode_pointer(cookie: int, value: int) -> int:
    """Inverse of decode_pointer (ntdll EncodePointer): xor first, then
    rotate left by 0x40 - (cookie & 0x3F)."""
    rot = 0x40 - (cookie & 0x3F)
    mixed = (value ^ cookie) & _M64
    return ((mixed << rot) | (mixed >> (64 - rot))) & _M64


# -- EXCEPTION_RECORD64 ------------------------------------------------------

@dataclasses.dataclass
class ExceptionRecord:
    """EXCEPTION_RECORD64 (the same wire layout nt.h declares and the
    crash dump header embeds):
      u32 ExceptionCode; u32 ExceptionFlags; u64 ExceptionRecord;
      u64 ExceptionAddress; u32 NumberParameters; u32 pad;
      u64 ExceptionInformation[15];"""

    code: int
    flags: int
    nested: int
    address: int
    parameters: List[int]

    SIZE = 0x98

    @classmethod
    def parse(cls, raw: bytes) -> "ExceptionRecord":
        code, flags = struct.unpack_from("<II", raw, 0)
        nested, address = struct.unpack_from("<QQ", raw, 8)
        (n_params,) = struct.unpack_from("<I", raw, 0x18)
        params = list(struct.unpack_from("<15Q", raw, 0x20))
        return cls(code=code, flags=flags, nested=nested, address=address,
                   parameters=params[:min(n_params, 15)])

    def av_kind(self) -> str:
        """Refine an access violation into read/write/execute via
        ExceptionInformation[0] (0=read, 1=write, 8=DEP/execute) — the
        reference's refinement in crash_detection_umode.cc:104-121."""
        if self.code != EXCEPTION_ACCESS_VIOLATION or not self.parameters:
            return ""
        kind = self.parameters[0]
        return {0: "read", 1: "write", 8: "execute"}.get(kind, f"av{kind}")


# -- OBJECT_ATTRIBUTES / IO_STATUS_BLOCK (guest-fs hook surface) ------------

@dataclasses.dataclass
class IoStatusBlock:
    """u64 Status (union w/ Pointer); u64 Information."""

    status: int
    information: int

    SIZE = 0x10

    @classmethod
    def parse(cls, raw: bytes) -> "IoStatusBlock":
        status, info = struct.unpack_from("<QQ", raw, 0)
        return cls(status=status, information=info)

    def pack(self) -> bytes:
        return struct.pack("<QQ", self.status, self.information)


@dataclasses.dataclass
class ObjectAttributes:
    """OBJECT_ATTRIBUTES (x64): Length, RootDirectory, ObjectName(PUNICODE),
    Attributes, SecurityDescriptor, SecurityQualityOfService."""

    length: int
    root_directory: int
    object_name_ptr: int
    attributes: int

    SIZE = 0x30

    @classmethod
    def parse(cls, raw: bytes) -> "ObjectAttributes":
        length, root, name_ptr, attrs = struct.unpack_from("<QQQQ", raw, 0)
        return cls(length=length & 0xFFFFFFFF, root_directory=root,
                   object_name_ptr=name_ptr, attributes=attrs & 0xFFFFFFFF)


def read_unicode_string(virt_read, ptr: int) -> str:
    """UNICODE_STRING {u16 Length; u16 Max; pad; u64 Buffer} -> str
    (reference HostObjectAttributes_t reader, utils.h:55-224)."""
    hdr = virt_read(ptr, 16)
    length, _maxlen = struct.unpack_from("<HH", hdr, 0)
    (buffer_ptr,) = struct.unpack_from("<Q", hdr, 8)
    if length == 0 or buffer_ptr == 0:
        return ""
    return virt_read(buffer_ptr, length).decode("utf-16-le", "replace")
