"""Testcase result variants.

Equivalent of the reference's `TestcaseResult_t = std::variant<Ok_t, Timedout_t,
Cr3Change_t, Crash_t>` (reference src/wtf/backend.h:12-31).  A crash carries a
name used as the on-disk filename under crashes/ (server.h:861-877).

These also define the integer status codes the interpreter keeps per lane on
device; `StatusCode` is the device-side encoding, the dataclasses are the
host-side API objects.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Union


class StatusCode(enum.IntEnum):
    """Per-lane execution status, kept as int32 on device."""

    RUNNING = 0
    OK = 1          # a stop breakpoint ended the testcase cleanly
    TIMEDOUT = 2    # instruction limit reached
    CR3_CHANGE = 3  # context switch detected (cr3 write != snapshot cr3)
    CRASH = 4       # guest crashed (fault, bugcheck, harness-detected)
    BREAKPOINT = 5  # paused at a breakpoint awaiting host servicing
    UNSUPPORTED = 6 # interpreter hit an unimplemented instruction
    PAGE_FAULT = 7  # translation fault.  When the snapshot carries an IDT
                    # the host delivers it through the guest kernel
                    # (cpu/interrupts.py) and the lane resumes; otherwise
                    # (or on delivery failure) it is terminal and surfaces
                    # as a memory-access crash
    NEED_DECODE = 8   # rip not in the uop table; host must decode + resume
    SMC = 9           # lane's code bytes diverge from the shared decode cache
    OVERLAY_FULL = 10 # lane ran out of dirty-page overlay slots
    DIVIDE_ERROR = 11 # #DE (div by zero / quotient overflow)
    HARD_ERROR = 12   # terminal: instruction unsupported even by the host
                      # oracle, or other unrecoverable servicing failure
                      # (details in Runner.lane_errors)
    NEEDS_XLA = 13    # fused Pallas fast path parked the lane BEFORE
                      # executing (instruction outside the hot integer
                      # subset, armed breakpoint, or dirty/diverged code
                      # bytes); state is untouched and the runner resumes
                      # it on the XLA chunk path — never escapes the
                      # runner's fused ladder (interp/pstep.py)


# Statuses the device can set that the host run loop must service before the
# lane can make further progress (vs. terminal testcase outcomes).
# PAGE_FAULT/DIVIDE_ERROR are conditionally serviceable on top of these:
# with guest exception delivery enabled they resume through the IDT
# (interp/runner.py), otherwise they are terminal.
SERVICEABLE = (
    StatusCode.NEED_DECODE,
    StatusCode.BREAKPOINT,
    StatusCode.SMC,
    StatusCode.UNSUPPORTED,
)


@dataclasses.dataclass(frozen=True)
class Ok:
    def __str__(self) -> str:
        return "ok"


@dataclasses.dataclass(frozen=True)
class Timedout:
    def __str__(self) -> str:
        return "timedout"


@dataclasses.dataclass(frozen=True)
class Cr3Change:
    def __str__(self) -> str:
        return "cr3change"


@dataclasses.dataclass(frozen=True)
class Crash:
    """A crash with an optional name; named crashes get saved to disk
    (reference backend.cc:204-212 SaveCrash / server.h:861-877)."""

    name: Optional[str] = None

    def __str__(self) -> str:
        return f"crash({self.name or '?'})"


@dataclasses.dataclass(frozen=True)
class OverlayFull:
    """The lane ran out of dirty-page overlay slots — a resource limit of
    THIS framework (no reference analog: its VMs have all of guest RAM).
    Not a finding: excluded from crashes/ and from the coverage merge (the
    run executed on truncated memory); campaign drivers requeue the
    testcase so it still gets an honest execution."""

    def __str__(self) -> str:
        return "overlay-full"


TestcaseResult = Union[Ok, Timedout, Cr3Change, Crash, OverlayFull]


def is_crash(result: TestcaseResult) -> bool:
    return isinstance(result, Crash)
