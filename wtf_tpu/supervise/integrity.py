"""In-graph machine-state integrity: invariant predicates + digest fold.

A corrupted lane (HBM bit flip, a miscompiled executor, scripted chaos)
must be caught BEFORE its batch is harvested — a poisoned status would
crash `StatusCode(int(...))` in result mapping, and poisoned coverage
planes would credit edges that were never executed.  The check is one
jitted function over the live machine pytree (lane-parallel elementwise
work plus two tiny reductions — noise next to a chunk dispatch, and it
pipelines behind the batch's own async dispatch):

  status    in [RUNNING .. NEEDS_XLA] — every value StatusCode can map
  rip       canonical: the u32 hi limb's bits 63..47 all-zero or all-one
            (the u64 rip is stored as two u32 limbs; no u64 on device)
  overlay   0 <= count <= capacity AND count == #allocated slots
            (pfn >= 0) — a corrupt count would tear COW restore
  ctr       fused-retired <= total-retired (CTR_FUSED counts a subset of
            CTR_INSTR by construction)

The digest is a lane-mixed wraparound-sum fold over the same planes — a cheap
whole-state fingerprint for the poisoned-lane event (two occurrences of
one corruption correlate by digest across the fleet's JSONL streams).

`poison_machine` / `mask_idle` are the write-side helpers: scripted
corruption for the chaos harness, and the tenancy-style idle mask that
parks quarantined lanes (status=OK: never stepped, never harvested).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from wtf_tpu.core.results import StatusCode
from wtf_tpu.interp.machine import CTR_FUSED, CTR_INSTR, Machine

_STATUS_MAX = max(int(s) for s in StatusCode)

# the status value scripted poison writes: far outside StatusCode, and
# recognizable in a debugger dump
POISON_STATUS = 77
POISON_RIP_HI = 0x00DEAD00


@jax.jit
def _check(machine: Machine) -> Tuple[jax.Array, jax.Array]:
    status = machine.status
    ok = (status >= 0) & (status <= _STATUS_MAX)
    # rip canonicality on the hi limb: bits 63..47 of the u64 rip are
    # bits 31..15 of rip_l[:, 1] — all zero (user) or all one (kernel)
    hi = machine.rip_l[:, 1] >> 15
    ok &= (hi == 0) | (hi == jnp.uint32(0x1FFFF))
    ov = machine.overlay
    capacity = ov.pfn.shape[1]
    allocated = jnp.sum((ov.pfn >= 0).astype(jnp.int32), axis=1)
    ok &= (ov.count >= 0) & (ov.count <= capacity) & (allocated == ov.count)
    ok &= machine.ctr[:, CTR_FUSED] <= machine.ctr[:, CTR_INSTR]
    # lane-mixed fingerprint folded with wraparound add (order-free, and
    # unlike a custom XOR lax.reduce it lowers to the stock add-reduction
    # every backend — including sharded host CPU — implements)
    mix = (machine.rip_l[:, 0]
           ^ (machine.rip_l[:, 1] * jnp.uint32(0x9E3779B9))
           ^ (status.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B))
           ^ (machine.ctr[:, CTR_INSTR] * jnp.uint32(0xC2B2AE35)))
    digest = jnp.sum(mix, dtype=jnp.uint32)
    return ~ok, digest


def check_machine(machine: Machine) -> Tuple[jax.Array, jax.Array]:
    """(bad bool[L], digest u32) for the live machine — async device
    values; the caller fences and reads back."""
    return _check(machine)


@partial(jax.jit, static_argnums=1)
def _poison(machine: Machine, lane: int) -> Machine:
    return machine._replace(
        status=machine.status.at[lane].set(POISON_STATUS),
        rip_l=machine.rip_l.at[lane, 1].set(jnp.uint32(POISON_RIP_HI)))


def poison_machine(machine: Machine, lane: int) -> Machine:
    """Scripted corruption (chaos harness): out-of-range status AND a
    non-canonical rip on one lane — either predicate alone catches it."""
    return _poison(machine, int(lane))


def poison_output(out, lane: int):
    """Apply scripted poison to a dispatch output: the Machine itself, or
    a result carrying one under `.machine` (megachunk window out)."""
    if isinstance(out, Machine):
        return poison_machine(out, lane)
    machine = getattr(out, "machine", None)
    if isinstance(machine, Machine):
        return out._replace(machine=poison_machine(machine, lane))
    return out  # non-machine seam: faultinject slides poison off these


_MASK_CACHE: Dict[Tuple[int, ...], object] = {}


def mask_idle(machine: Machine, mask) -> Machine:
    """Park `mask` lanes idle the way the batch paths already treat
    untasked lanes: status=OK (terminal — never stepped by the chunk
    loop, excluded from harvest by the caller's include mask)."""
    fn = _MASK_CACHE.get(machine.status.shape)
    if fn is None:
        @jax.jit
        def fn(machine, mask):
            return machine._replace(status=jnp.where(
                mask, jnp.int32(int(StatusCode.OK)), machine.status))

        _MASK_CACHE[machine.status.shape] = fn
    return fn(machine, jnp.asarray(mask))
