"""Self-healing device runtime (supervise/).

  supervisor.Supervisor  the per-backend dispatch guard: watchdog,
                         batch-boundary snapshot + rebuild/replay
                         recovery, per-batch integrity + quarantine
  ladder.DegradationLadder
                         megachunk -> batch -> fused-off -> fixed-chunk
                         step-down with hysteresis re-promotion
  integrity              the jitted invariant/digest fold and the
                         poison/mask write-side helpers

See supervisor.py's module docstring for the full contract; SEAM_SITES
is the lint-pinned enumeration of every dispatch entry point that must
route through Supervisor.dispatch.
"""

from wtf_tpu.supervise.ladder import DegradationLadder  # noqa: F401
from wtf_tpu.supervise.supervisor import (  # noqa: F401
    DEVICE_ERROR, DEVICE_HANG, DEVICE_POISON, MACHINE_SEAMS, SEAM_SITES,
    SUPERVISED_SEAMS, DispatchError, DispatchFailure, DispatchHang,
    LanePoisoned, Supervisor,
)
