"""The device-dispatch supervisor: watchdog, recovery, quarantine.

The device plane was the last part of the fuzzer that trusted its
substrate completely: a hung XLA dispatch, an `XlaRuntimeError`, or a
silently corrupted lane either aborted the campaign or — worse —
credited poisoned coverage.  The Supervisor closes that gap by owning
every device dispatch seam (Runner chunk/fused/insert, the megachunk
window, devmut generation — enumerated in `SEAM_SITES`, pinned by the
lint `supervise` family) with four capabilities:

  watchdog     `dispatch()` bounds the wait on a dispatch's results with
               a host timer thread (`--dispatch-timeout`, scaled by the
               dispatch's step budget and megachunk window).  A hang is
               abandoned — the waiter thread is left parked on the dead
               dispatch, never joined — and surfaces as DispatchHang.
  recovery     `recover()` rebuilds the backend from live host-side
               state (decode cache + SMC counters are host dicts, the
               coverage aggregates and mutator cursor were mirrored at
               the batch boundary by `pre_batch()`) and the batch
               replays bit-identically: the failed attempt only ever
               decoded a prefix of the same deterministic stream.
  degradation  repeated failures step down the `DegradationLadder`
               (megachunk -> batch-at-a-time -> fused off -> fixed
               chunks); N clean batches re-promote.  Every rung is
               pinned bit-identical at equal seeds elsewhere in the
               tree, so rungs trade wall-clock, never results.
  quarantine   a cheap jitted integrity fold over the machine planes
               runs once per batch (supervise/integrity.py); violating
               lanes raise LanePoisoned (the batch replays from restore
               state) and repeat offenders enter the persistent
               quarantine mask — masked idle via the tenancy lane-mask
               idiom, excluded from the coverage merge, never harvested.

Fault injection: `wtf_tpu.testing.faultinject.chaos_device(plan)` arms
the module-global `_DEVICE_FAULT` hook (the atomicio `_WRITE_FAULT`
pattern) so scripted hang/error/poison faults fire on exact dispatch
indices — no wall clock, provable in CI (`make device-chaos-smoke`).

An inert Supervisor (enabled=False, no hook armed) adds one attribute
load and one `is None` test per dispatch — standalone Runners pay
nothing for the seam routing.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Set

import numpy as np

from wtf_tpu.telemetry import NULL, Registry

# Seam names -> the code location that must route through
# Supervisor.dispatch.  The lint `supervise` family resolves each site
# and asserts the literal routing call is present (analysis/rules.py
# check_supervised_seams) — a future dispatch seam that bypasses the
# supervisor is a lint failure, not a silent recovery hole.
SEAM_SITES: Dict[str, str] = {
    "chunk": "wtf_tpu.interp.runner:Runner.run",
    "fused": "wtf_tpu.interp.runner:Runner._fused_dispatch",
    "fused-resume": "wtf_tpu.interp.runner:Runner._fused_dispatch",
    "device-insert": "wtf_tpu.interp.runner:Runner.device_insert",
    "devmut-generate": "wtf_tpu.devmut.mutator:DevMangleMutator.generate",
    "megachunk": "wtf_tpu.backend.tpu:TpuBackend._dispatch_window",
    "device-decode": "wtf_tpu.interp.runner:Runner._gather_code_windows",
}
SUPERVISED_SEAMS = tuple(sorted(SEAM_SITES))

# seams whose dispatch output carries machine state — the only ones a
# scripted poison fault can corrupt (faultinject slides poison scheduled
# on other seams to the next dispatch index)
MACHINE_SEAMS = frozenset(
    ("chunk", "fused", "fused-resume", "device-insert", "megachunk"))

# scripted device-fault kinds (testing/faultinject.FaultPlan.device_faults)
DEVICE_HANG = "device-hang"
DEVICE_ERROR = "device-error"
DEVICE_POISON = "device-poison"

# armed by testing.faultinject.chaos_device: callable(seam, index) ->
# Optional[(kind, arg)].  Module global like utils/atomicio._WRITE_FAULT
# so production code never imports the chaos harness.
_DEVICE_FAULT = None


class DispatchFailure(RuntimeError):
    """Base of every supervised-dispatch failure.  Carries the seam name
    and the global dispatch index so recovery events are attributable."""

    kind = "failure"

    def __init__(self, seam: str, index: int, detail: str):
        super().__init__(f"{seam} dispatch #{index}: {detail}")
        self.seam = seam
        self.index = index
        self.detail = detail


class DispatchHang(DispatchFailure):
    """The watchdog expired waiting on a dispatch (real or injected)."""

    kind = "hang"


class DispatchError(DispatchFailure):
    """The dispatch raised (XlaRuntimeError and friends, or injected)."""

    kind = "error"


class LanePoisoned(DispatchFailure):
    """The per-batch integrity check found lanes violating machine-state
    invariants; `lanes` are the violators."""

    kind = "poison"

    def __init__(self, seam: str, index: int, lanes, detail: str):
        super().__init__(seam, index, detail)
        self.lanes = tuple(int(x) for x in lanes)


def _wait_ready(value) -> None:
    """The blocking wait the watchdog thread runs — a module function so
    tests can substitute a slow waiter without touching jax."""
    import jax

    jax.block_until_ready(value)


class Supervisor:
    """One per backend; shared with the Runner it rebuilds (the global
    dispatch index and telemetry survive rebuilds by construction)."""

    def __init__(self, registry: Optional[Registry] = None, events=None,
                 enabled: bool = False, dispatch_timeout: float = 0.0,
                 promote_after: int = 8, max_batch_retries: int = 4,
                 quarantine_threshold: int = 3):
        self.registry = registry if registry is not None else Registry()
        self.events = events if events is not None else NULL
        self.enabled = bool(enabled)
        self.dispatch_timeout = float(dispatch_timeout)
        self.promote_after = int(promote_after)
        self.max_batch_retries = int(max_batch_retries)
        self.quarantine_threshold = int(quarantine_threshold)
        self.ladder = None          # built by attach_loop
        self.quarantined: Set[int] = set()
        self._violations: Dict[int, int] = {}
        self._op_index = 0          # global supervised-dispatch counter
        self._snap: Optional[dict] = None
        self._base_steps = 256      # refined by attach_runner
        self.n_lanes = 0

    # -- wiring ------------------------------------------------------------
    def attach_runner(self, runner) -> None:
        """Called from Runner.__init__ — including the rebuilt Runner
        after a recovery, which shares THIS supervisor."""
        self._base_steps = max(int(runner.chunk_steps), 1)
        self.n_lanes = runner.n_lanes

    def attach_loop(self, loop) -> None:
        """Called from FuzzLoop.__init__ when supervision is enabled:
        builds the degradation ladder against the loop's configuration."""
        from wtf_tpu.supervise.ladder import DegradationLadder

        self.ladder = DegradationLadder(loop, self.promote_after)
        self.apply_rung(loop)
        # bottom-of-ladder escape hatch on a mesh with checkpointing:
        # persistent failures hand the campaign to the elastic driver at
        # half the device count (PR-11 reshard, placement-free resume)
        if (loop.reshard_policy is None
                and loop.checkpoint_dir is not None
                and getattr(loop.backend, "mesh", None) is not None):
            loop.reshard_policy = self.reshard_request

    @property
    def megachunk_disabled(self) -> bool:
        """Megachunk windows are off when the ladder stepped below them
        OR any lane is persistently quarantined (the in-graph window
        cannot mask lanes; the batch-at-a-time path can)."""
        if self.quarantined:
            return True
        return bool(self.ladder is not None and self.ladder.megachunk_off)

    def _active(self) -> bool:
        return self.enabled or _DEVICE_FAULT is not None

    # -- the dispatch guard --------------------------------------------------
    def dispatch(self, seam: str, fn, *args, steps: int = 0,
                 window: int = 1, wait: bool = True, sync=None):
        """Route one device dispatch: scripted-fault check, the call,
        then (when a timeout is configured) the bounded wait on
        `sync(out)` (or `out` itself).  `steps`/`window` scale the
        timeout; `wait=False` marks async dispatches (devmut prelaunch)
        whose hang surfaces at the next synchronizing seam instead."""
        if not self._active():
            return fn(*args)
        index = self._op_index
        self._op_index += 1
        self.registry.counter("supervise.dispatches").inc()
        hook = _DEVICE_FAULT
        fault = hook(seam, index) if hook is not None else None
        if fault is not None:
            kind = fault[0]
            if kind == DEVICE_HANG:
                # scripted hangs never wait wall-clock: the watchdog
                # outcome (abandon + rebuild) is identical either way
                self._note_watchdog(seam, index, injected=True)
                raise DispatchHang(seam, index,
                                   "injected hung dispatch (watchdog)")
            if kind == DEVICE_ERROR:
                self._note_error(seam, index, "injected device error")
                raise DispatchError(seam, index, "injected device error")
        try:
            out = fn(*args)
            if wait and self.dispatch_timeout > 0:
                self._bounded_wait(seam, index,
                                   sync(out) if sync is not None else out,
                                   steps, window)
        except DispatchFailure:
            raise
        except Exception as exc:
            if not self.enabled:
                raise
            self._note_error(seam, index, repr(exc))
            raise DispatchError(seam, index, repr(exc)) from exc
        if fault is not None and fault[0] == DEVICE_POISON:
            from wtf_tpu.supervise import integrity

            out = integrity.poison_output(out, int(fault[1] or 0))
        return out

    def timeout_for(self, steps: int, window: int) -> float:
        """--dispatch-timeout is calibrated to ONE base chunk; bigger
        dispatches (adaptive chunk rungs, the instruction-budget-bound
        megachunk window) get proportionally longer before the watchdog
        calls them hung."""
        scale = max(1.0, steps / self._base_steps) if steps else 1.0
        return self.dispatch_timeout * scale * max(1, window)

    def _bounded_wait(self, seam: str, index: int, value,
                      steps: int, window: int) -> None:
        timeout = self.timeout_for(steps, window)
        done = threading.Event()
        raised = []

        def waiter():
            try:
                _wait_ready(value)
            except Exception as exc:  # surfaces as DispatchError above
                raised.append(exc)
            finally:
                done.set()

        thread = threading.Thread(
            target=waiter, daemon=True, name=f"wtf-watchdog-{seam}-{index}")
        thread.start()
        if not done.wait(timeout):
            # abandon, don't join: the thread stays parked on the dead
            # dispatch and dies with the process; recovery rebuilds the
            # runner so nothing ever consumes the wedged buffers
            self._note_watchdog(seam, index, injected=False)
            raise DispatchHang(
                seam, index,
                f"no completion within {timeout:.1f}s (watchdog)")
        if raised:
            raise raised[0]

    def _note_watchdog(self, seam: str, index: int, injected: bool) -> None:
        self.registry.counter("supervise.watchdog_fires").inc()
        self.events.emit("watchdog", seam=seam, index=index,
                         injected=injected)

    def _note_error(self, seam: str, index: int, detail: str) -> None:
        self.registry.counter("supervise.device_errors").inc()
        self.events.emit("device-error", seam=seam, index=index,
                         detail=detail[:200])

    # -- per-batch integrity + quarantine ------------------------------------
    def check_batch_integrity(self, runner) -> Optional[np.ndarray]:
        """Run the jitted invariant fold over the live machine (called by
        the backend BEFORE the coverage merge and result mapping — a
        poisoned status must never reach StatusCode() or the aggregate
        bitmaps).  Returns the violation mask, or None when inert."""
        if not self.enabled:
            return None
        import jax

        from wtf_tpu.supervise import integrity

        with self.registry.spans.span("integrity") as sp:
            bad_dev, digest = integrity.check_machine(runner.machine)
            sp.fence(bad_dev)
        self.registry.counter("supervise.integrity_checks").inc()
        bad = np.asarray(jax.device_get(bad_dev))
        if bad.any():
            lanes = [int(x) for x in np.nonzero(bad)[0]]
            for lane in lanes:
                self._violations[lane] = self._violations.get(lane, 0) + 1
                self.registry.counter("device.quarantined").inc()
                if self._violations[lane] >= self.quarantine_threshold:
                    self.quarantined.add(lane)
            self.events.emit("poisoned-lane", lanes=lanes,
                             digest=int(jax.device_get(digest)),
                             quarantined=sorted(self.quarantined))
            self.registry.counter("supervise.poisoned_lanes").inc(len(lanes))
            self.registry.gauge("supervise.quarantined_lanes").set(
                len(self.quarantined))
        return bad

    def raise_if_poisoned(self, runner, seam: str) -> None:
        """Integrity gate the backend drops before every harvest: run the
        check and raise LanePoisoned on any violating lane, so the batch
        is replayed (fuzz-loop supervision wrapper) instead of harvested.
        Inert when supervision is disabled."""
        bad = self.check_batch_integrity(runner)
        if bad is not None and bad.any():
            lanes = np.nonzero(bad)[0]
            raise LanePoisoned(
                seam, self._op_index, lanes,
                f"machine-state invariants violated on lanes "
                f"{[int(x) for x in lanes]}")

    def quarantine_mask(self) -> Optional[np.ndarray]:
        """bool[L] — True for persistently quarantined lanes (masked
        idle: skipped at insert, excluded from the coverage merge).
        None while the set is empty (the common case costs nothing)."""
        if not self.quarantined or not self.n_lanes:
            return None
        mask = np.zeros(self.n_lanes, dtype=bool)
        mask[sorted(self.quarantined)] = True
        return mask

    # -- batch-boundary snapshot + recovery ----------------------------------
    def pre_batch(self, loop) -> None:
        """Mirror the batch-boundary state a replay needs: the coverage
        aggregates, the FULL mutator checkpoint, the campaign RNG and
        the overlay-full requeue.  The mutator snapshot must be the full
        checkpoint (slab included), not just the cursor: the prelaunch
        seam SYNCS the slab's as-uploaded view before its generate
        dispatch can fail, so a cursor-only snapshot would regenerate
        the pending batch from a newer slab than the original sampled.
        Everything else is either host-side and monotone (decode cache,
        SMC counters — captured live at recovery time) or derived
        deterministically from these."""
        backend = loop.backend
        mutator = loop.mutator
        with self.registry.spans.span("supervise-snapshot"):
            cov, edge = backend.coverage_state()
            if hasattr(mutator, "checkpoint_state"):
                mut = mutator.checkpoint_state()
            else:
                mut = None
            corpus_rng = getattr(loop.corpus, "rng", None)
            mut_rng = getattr(mutator, "rng", None)
            self._snap = {
                "coverage": (cov, edge),
                "mutator": mut,
                "rng_corpus": (corpus_rng.getstate()
                               if corpus_rng is not None else None),
                # most drivers share ONE rng between corpus and mutator
                # (resume/checkpoint.py's "shared" idiom)
                "rng_mutator": ("shared" if mut_rng is corpus_rng else
                                (mut_rng.getstate()
                                 if mut_rng is not None else None)),
                "requeue": list(loop._requeue),
                "requeue_digests": set(loop._requeue_digests),
            }

    def post_batch(self, loop) -> None:
        """A clean batch: drop the snapshot and feed the ladder's
        hysteresis — `promote_after` consecutive clean batches win one
        rung back."""
        self._snap = None
        if self.ladder is not None and self.ladder.on_clean():
            self.registry.counter("supervise.promotions").inc()
            self.events.emit("promote", rung=self.ladder.rung_name,
                             level=self.ladder.level)
            self.apply_rung(loop)
        if self.ladder is not None:
            self.registry.gauge("supervise.rung").set(self.ladder.level)

    def recover(self, loop, failure: DispatchFailure) -> None:
        """Abandon the failed dispatch, rebuild the device plane from
        host-side state, and leave the loop ready to replay the batch
        bit-identically.

        Why the replay is exact: the failed attempt consumed no host
        randomness (RNG/requeue restored from the snapshot), its decode
        work is a PREFIX of the same deterministic stream (cache entries
        keep their insertion indices — captured live, they are host
        state), and the mutator byte stream is a pure function of
        (seed, batch cursor, slab-as-uploaded) — all three restored from
        the pre_batch snapshot, including the slab's as-uploaded view
        (which the failing dispatch itself may have re-synced)."""
        if self._snap is None:
            raise RuntimeError(
                "supervised recovery without a pre_batch snapshot") \
                from failure
        backend = loop.backend
        mutator = loop.mutator
        self.registry.counter("supervise.batch_retries").inc()
        with self.registry.spans.span("supervise-recover"):
            runner_state = backend.runner.checkpoint_state()
            device_mut = bool(getattr(mutator, "is_device", False))
            backend.initialize()  # fresh Runner (shares this supervisor)
            runner = backend.runner
            # re-arm breakpoints directly from the backend's table —
            # target.init already ran once and must not run twice
            for gva in getattr(backend, "breakpoints", {}):
                runner.cache.set_breakpoint(gva)
            runner.restore_state(runner_state)
            cov, edge = self._snap["coverage"]
            backend.restore_coverage_state(cov, edge)
            if device_mut:
                mutator.bind(backend, loop.target,
                             registry=loop.registry, events=loop.events)
                # regenerate=True: even a megachunk-boundary snapshot
                # (pending=False) must re-prelaunch from the entitled
                # as-uploaded slab view, because the replay runs
                # batch-at-a-time (the ladder stepped below megachunk)
                mutator.restore_state(self._snap["mutator"],
                                      regenerate=True)
            elif (self._snap["mutator"] is not None
                    and hasattr(mutator, "restore_state")):
                mutator.restore_state(self._snap["mutator"])
            corpus_rng = getattr(loop.corpus, "rng", None)
            if corpus_rng is not None and self._snap["rng_corpus"]:
                corpus_rng.setstate(self._snap["rng_corpus"])
            mut_rng_state = self._snap["rng_mutator"]
            if mut_rng_state not in (None, "shared"):
                getattr(mutator, "rng").setstate(mut_rng_state)
            loop._requeue = list(self._snap["requeue"])
            loop._requeue_digests = set(self._snap["requeue_digests"])
            backend._view = None
            loop.target.restore()
        self.registry.counter("supervise.rebuilds").inc()
        self.events.emit("rebuild", seam=failure.seam, index=failure.index,
                         kind=failure.kind)
        if self.ladder is not None:
            if self.ladder.on_failure():
                self.registry.counter("supervise.degradations").inc()
                self.events.emit("degrade", rung=self.ladder.rung_name,
                                 level=self.ladder.level,
                                 kind=failure.kind)
            self.registry.gauge("supervise.rung").set(self.ladder.level)
        # the NEW runner needs the current rung's flags re-applied
        self.apply_rung(loop)

    def apply_rung(self, loop) -> None:
        if self.ladder is not None:
            self.ladder.apply(loop)

    # -- elastic mesh rung (wtf_tpu/fleet/elastic) ----------------------------
    def reshard_request(self, loop) -> Optional[int]:
        """A reshard_policy-shaped hook (callable(loop) -> Optional[int]):
        when the ladder is already at its bottom rung and failures keep
        coming, ask the elastic driver to re-place the campaign on half
        the mesh (PR-11 primitive; placement-free checkpoints make the
        shrink bit-identical)."""
        del loop
        if self.ladder is None or not self.ladder.wants_reshard:
            return None
        backend = getattr(self, "_backend", None)
        mesh = getattr(backend, "mesh", None) if backend else None
        if mesh is None or mesh.size <= 1:
            return None
        self.ladder.wants_reshard = False
        return max(1, mesh.size // 2)

    # -- heartbeat -----------------------------------------------------------
    def heartbeat_fields(self) -> dict:
        """Extra JSONL heartbeat fields (the full supervise.* counter set
        rides in the registry dump already)."""
        return {
            "supervise_rung": (self.ladder.rung_name
                               if self.ladder is not None else "full"),
            "supervise_quarantined": len(self.quarantined),
        }
