"""The graceful-degradation ladder: trade wall-clock for stability.

Every rung below "full" turns OFF one throughput feature whose output is
already pinned bit-identical to the plain path at equal seeds (megachunk
parity, fused-step parity, and the adaptive-chunk schedule are all
tier-1 parity tests) — so stepping down after repeated device failures
changes how fast the campaign runs, never what it finds:

  level 0  full            megachunk windows, fused step, adaptive chunks
  level 1  no-megachunk    batch-at-a-time dispatch (smallest abandonable
                           unit shrinks from a window to one batch)
  level 2  no-fused        fused mutate->execute off; plain chunk executor
  level 3  fixed-chunk     adaptive chunk growth off; base chunk_steps
                           only (the minimal XLA surface: one executor)

Rungs that don't apply to the campaign (no megachunk configured, fused
step already off) are skipped at construction, so `level` always indexes
a real change.  Hysteresis: one failure steps down one rung immediately;
`promote_after` CONSECUTIVE clean batches step back up one rung — a
flapping device ratchets down and stays down.

Below the bottom rung there is nothing left to turn off on this backend;
further failures set `wants_reshard`, which the supervisor's
reshard_policy adapter converts into an elastic mesh shrink (PR-11
primitive) when the campaign runs on a mesh with checkpointing enabled.
"""

from __future__ import annotations

FULL = "full"
NO_MEGACHUNK = "no-megachunk"
NO_FUSED = "no-fused"
FIXED_CHUNK = "fixed-chunk"


class DegradationLadder:
    def __init__(self, loop, promote_after: int = 8):
        runner = loop.backend.runner
        self._orig_fused = bool(getattr(runner, "fused_enabled", False))
        self._orig_adaptive = bool(getattr(runner, "adaptive_chunks", True))
        self.rungs = [FULL]
        if getattr(loop, "megachunk", 0):
            self.rungs.append(NO_MEGACHUNK)
        if self._orig_fused:
            self.rungs.append(NO_FUSED)
        if self._orig_adaptive:
            self.rungs.append(FIXED_CHUNK)
        self.level = 0
        self.promote_after = max(1, int(promote_after))
        self.clean_streak = 0
        self.wants_reshard = False

    @property
    def rung_name(self) -> str:
        return self.rungs[self.level]

    def _active(self, rung: str) -> bool:
        """Level k activates every degradation in rungs[1..k]."""
        try:
            return self.rungs.index(rung) <= self.level
        except ValueError:
            return False

    @property
    def megachunk_off(self) -> bool:
        return self._active(NO_MEGACHUNK)

    def on_failure(self) -> bool:
        """Step down one rung; returns True when the rung changed.  At
        the bottom, flag the elastic-reshard escape hatch instead."""
        self.clean_streak = 0
        if self.level + 1 < len(self.rungs):
            self.level += 1
            return True
        self.wants_reshard = True
        return False

    def on_clean(self) -> bool:
        """One clean batch; returns True when the streak re-promotes a
        rung (hysteresis: promote_after consecutive cleans per rung)."""
        if self.level == 0:
            return False
        self.clean_streak += 1
        if self.clean_streak >= self.promote_after:
            self.clean_streak = 0
            self.level -= 1
            return True
        return False

    def apply(self, loop) -> None:
        """Install this rung's flags on the CURRENT runner.  Called after
        every rung change and after every rebuild (the fresh Runner comes
        up with its construction-time defaults, not the rung's)."""
        runner = loop.backend.runner
        runner.fused_enabled = self._orig_fused and not self._active(NO_FUSED)
        runner.adaptive_chunks = (self._orig_adaptive
                                  and not self._active(FIXED_CHUNK))
