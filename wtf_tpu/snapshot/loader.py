"""Unified snapshot loading.

A snapshot is the checkpoint the whole system revolves around (reference
README.md:168-240): guest memory (`mem.dmp`) + registers (`regs.json`) +
symbols (`symbol-store.json`) living in a target's `state/` directory
(wtf.cc:127-129).  This module loads any of:

  - `mem.dmp`   — Windows kernel crash-dump, parsed by wtf_tpu.snapshot.kdmp
                  (kdmp-parser equivalent; see native/ for the C++ fast path),
  - `mem.npz`   — the raw packed format used by synthetic snapshots/tests,

into a `Snapshot{PhysMem, CpuState, symbols}` ready for device upload.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Dict, Optional

import numpy as np

from wtf_tpu.core.cpustate import CpuState, load_cpu_state_json, sanitize_cpu_state
from wtf_tpu.core.gxa import PAGE_SIZE
from wtf_tpu.mem.physmem import PhysMem


@dataclasses.dataclass
class Snapshot:
    physmem: PhysMem
    cpu: CpuState
    symbols: Dict[str, int] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_pages(
        cls, pages: Dict[int, bytes], cpu: CpuState, symbols: Optional[Dict[str, int]] = None
    ) -> "Snapshot":
        return cls(physmem=PhysMem.from_pages(pages), cpu=cpu, symbols=symbols or {})

    def save_raw(self, state_dir) -> None:
        """Persist in the raw format (mem.npz + regs.json + symbol-store.json)."""
        state_dir = Path(state_dir)
        state_dir.mkdir(parents=True, exist_ok=True)
        image = self.physmem
        pages_np = np.asarray(image.image.pages)
        table_np = np.asarray(image.image.frame_table)[0]
        pfns = np.nonzero(table_np)[0]
        slots = table_np[pfns]
        np.savez_compressed(
            state_dir / "mem.npz",
            pfns=pfns.astype(np.int64),
            pages=pages_np[slots],
        )
        (state_dir / "regs.json").write_text(dump_cpu_state_json(self.cpu))
        (state_dir / "symbol-store.json").write_text(
            json.dumps({k: hex(v) for k, v in self.symbols.items()}, indent=1)
        )


def load_snapshot(state_dir, sanitize: bool = True) -> Snapshot:
    """Load a snapshot directory (reference startup path wtf.cc:378-465:
    LoadCpuStateFromJSON -> backend init -> SanitizeCpuState)."""
    state_dir = Path(state_dir)
    cpu = load_cpu_state_json(state_dir / "regs.json")
    if sanitize and not sanitize_cpu_state(cpu):
        raise ValueError(f"unusable CPU state in {state_dir}")

    symbols: Dict[str, int] = {}
    symbol_path = state_dir / "symbol-store.json"
    if symbol_path.exists():
        raw = json.loads(symbol_path.read_text())
        symbols = {k: (int(v, 0) if isinstance(v, str) else int(v)) for k, v in raw.items()}

    npz_path = state_dir / "mem.npz"
    dmp_path = state_dir / "mem.dmp"
    if npz_path.exists():
        data = np.load(npz_path)
        pages = {
            int(pfn): bytes(page.tobytes())
            for pfn, page in zip(data["pfns"], data["pages"])
        }
    elif dmp_path.exists():
        from wtf_tpu.snapshot.kdmp import parse_kdmp

        pages = parse_kdmp(dmp_path)
    else:
        raise FileNotFoundError(f"no mem.npz or mem.dmp under {state_dir}")

    return Snapshot(physmem=PhysMem.from_pages(pages), cpu=cpu, symbols=symbols)


def dump_cpu_state_json(cpu: CpuState) -> str:
    """Serialize a CpuState back to the bdump.js regs.json format, so
    synthetic snapshots round-trip through the same loader as real ones."""
    from wtf_tpu.core.cpustate import _REG_KEYS, _SEG_KEYS  # noqa: SLF001

    data = {}
    for key in _REG_KEYS:
        data[key] = hex(getattr(cpu, key))
    for key in _SEG_KEYS:
        seg = getattr(cpu, key)
        data[key] = {
            "present": seg.present,
            "selector": hex(seg.selector),
            "base": hex(seg.base),
            "limit": hex(seg.limit),
            "attr": hex(seg.attr),
        }
    data["gdtr"] = {"base": hex(cpu.gdtr.base), "limit": hex(cpu.gdtr.limit)}
    data["idtr"] = {"base": hex(cpu.idtr.base), "limit": hex(cpu.idtr.limit)}
    data["fpst"] = [hex(v) for v in cpu.fpst]
    data["zmm"] = [{"q": [hex(limb) for limb in reg]} for reg in cpu.zmm]
    return json.dumps(data, indent=1)
