from wtf_tpu.snapshot.synthetic import SyntheticSnapshotBuilder
from wtf_tpu.snapshot.loader import Snapshot, load_snapshot
