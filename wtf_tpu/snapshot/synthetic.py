"""Synthetic snapshot builder: tiny long-mode guests for tests and benches.

The reference's snapshots are Windows kernel crash-dumps taken with bdump.js
(reference README.md:168-240); none ship with the tree (targets/ is empty).
For unit tests, demo targets, and benchmarks we synthesize minimal but
architecturally real snapshots: 4-level page tables, long-mode CpuState, code
and data mapped at arbitrary GVAs.  The result loads through the same
`Snapshot` path as a parsed crash-dump, so everything downstream is exercised
identically.
"""

from __future__ import annotations

from typing import Dict, Optional

from wtf_tpu.core.cpustate import (
    CR0_PE,
    CR0_PG,
    CR4_PAE,
    CpuState,
    EFER_LMA,
    EFER_LME,
    Seg,
)
from wtf_tpu.core.gxa import PAGE_SHIFT, PAGE_SIZE

_PTE_P = 1
_PTE_W = 1 << 1
_PTE_U = 1 << 2


class SyntheticSnapshotBuilder:
    """Builds {pfn: page bytes} + a long-mode CpuState with real page tables.

    Guest-physical layout: page tables from `table_base`, mapped data pages
    allocated by a bump allocator above them.
    """

    def __init__(self, table_base: int = 0x10000):
        self._phys: Dict[int, bytearray] = {}
        self._mappings: Dict[int, int] = {}  # gva pfn -> gpa pfn
        self._writable: Dict[int, bool] = {}
        self._next_pfn = (table_base >> PAGE_SHIFT) + 0x100
        self._table_base = table_base
        self._large = []  # (gva, gpa, size_shift) large-page mappings
        self.cpu = CpuState()

    def _phys_page(self, pfn: int) -> bytearray:
        page = self._phys.get(pfn)
        if page is None:
            page = bytearray(PAGE_SIZE)
            self._phys[pfn] = page
        return page

    def alloc_phys(self) -> int:
        pfn = self._next_pfn
        self._next_pfn += 1
        self._phys_page(pfn)
        return pfn

    def map(self, gva: int, size: int, writable: bool = True) -> None:
        """Map [gva, gva+size) to freshly allocated physical pages."""
        start = gva >> PAGE_SHIFT
        end = (gva + size + PAGE_SIZE - 1) >> PAGE_SHIFT
        for vpn in range(start, end):
            if vpn not in self._mappings:
                self._mappings[vpn] = self.alloc_phys()
                self._writable[vpn] = writable

    def write(self, gva: int, data: bytes, map_if_needed: bool = True) -> None:
        """Write snapshot contents at a GVA (mapping pages on demand)."""
        if map_if_needed:
            self.map(gva, len(data))
        pos = 0
        while pos < len(data):
            vpn = (gva + pos) >> PAGE_SHIFT
            off = (gva + pos) & (PAGE_SIZE - 1)
            chunk = min(len(data) - pos, PAGE_SIZE - off)
            gpa_pfn = self._mappings[vpn]
            self._phys_page(gpa_pfn)[off : off + chunk] = data[pos : pos + chunk]
            pos += chunk

    def map_discontiguous_pair(self, gva: int) -> None:
        """Map two virtually-adjacent pages to non-adjacent frames (for
        page-crossing tests)."""
        vpn = gva >> PAGE_SHIFT
        self._mappings[vpn] = self.alloc_phys()
        self._writable[vpn] = True
        self.alloc_phys()  # hole
        self._mappings[vpn + 1] = self.alloc_phys()
        self._writable[vpn + 1] = True

    def _build_tables(self) -> int:
        """Materialize 4-level page tables; returns cr3."""
        import struct

        next_table = [self._table_base >> PAGE_SHIFT]

        def alloc_table() -> int:
            pfn = next_table[0]
            next_table[0] += 1
            if pfn >= (self._table_base >> PAGE_SHIFT) + 0x100:
                raise RuntimeError("page-table arena exhausted")
            self._phys_page(pfn)
            return pfn

        pml4_pfn = alloc_table()
        # level maps: {table pfn: {index: child pfn}}
        tables: Dict[int, Dict[int, int]] = {pml4_pfn: {}}

        def get_child(table_pfn: int, index: int) -> int:
            children = tables.setdefault(table_pfn, {})
            if index not in children:
                child = alloc_table()
                children[index] = child
                page = self._phys_page(table_pfn)
                entry = (child << PAGE_SHIFT) | _PTE_P | _PTE_W | _PTE_U
                page[index * 8 : index * 8 + 8] = struct.pack("<Q", entry)
            return children[index]

        for vpn, gpa_pfn in self._mappings.items():
            gva = vpn << PAGE_SHIFT
            i4 = (gva >> 39) & 0x1FF
            i3 = (gva >> 30) & 0x1FF
            i2 = (gva >> 21) & 0x1FF
            i1 = (gva >> 12) & 0x1FF
            pdpt = get_child(pml4_pfn, i4)
            pd = get_child(pdpt, i3)
            pt = get_child(pd, i2)
            flags = _PTE_P | _PTE_U | (_PTE_W if self._writable.get(vpn, True) else 0)
            entry = (gpa_pfn << PAGE_SHIFT) | flags
            self._phys_page(pt)[i1 * 8 : i1 * 8 + 8] = struct.pack("<Q", entry)

        return pml4_pfn << PAGE_SHIFT

    def add_large_page_mapping(self, gva: int, gpa: int, size_shift: int) -> None:
        """Map a 2MiB (size_shift=21) or 1GiB (30) large page (PS entries)."""
        assert size_shift in (21, 30)
        self._large.append((gva, gpa, size_shift))

    def build(self, rip: int = 0, rsp: int = 0):
        """Finalize -> (pages dict, CpuState in long mode)."""
        import struct

        cr3 = self._build_tables()
        # Splice in large-page mappings after regular tables exist.
        for gva, gpa, size_shift in self._large:
            i4 = (gva >> 39) & 0x1FF
            i3 = (gva >> 30) & 0x1FF
            i2 = (gva >> 21) & 0x1FF
            pml4_pfn = cr3 >> PAGE_SHIFT
            pml4 = self._phys_page(pml4_pfn)
            pdpt_entry = struct.unpack("<Q", pml4[i4 * 8 : i4 * 8 + 8])[0]
            if not pdpt_entry & _PTE_P:
                raise RuntimeError("large-page parent PML4E missing; map() a sibling first")
            pdpt_pfn = (pdpt_entry >> PAGE_SHIFT) & ((1 << 40) - 1)
            if size_shift == 30:
                entry = gpa | _PTE_P | _PTE_W | _PTE_U | (1 << 7)
                self._phys_page(pdpt_pfn)[i3 * 8 : i3 * 8 + 8] = struct.pack("<Q", entry)
            else:
                pdpt = self._phys_page(pdpt_pfn)
                pd_entry = struct.unpack("<Q", pdpt[i3 * 8 : i3 * 8 + 8])[0]
                if not pd_entry & _PTE_P:
                    raise RuntimeError("large-page parent PDPTE missing; map() a sibling first")
                pd_pfn = (pd_entry >> PAGE_SHIFT) & ((1 << 40) - 1)
                entry = gpa | _PTE_P | _PTE_W | _PTE_U | (1 << 7)
                self._phys_page(pd_pfn)[i2 * 8 : i2 * 8 + 8] = struct.pack("<Q", entry)

        cpu = self.cpu
        cpu.cr3 = cr3
        cpu.cr0 = CR0_PE | CR0_PG | 0x50030  # PE+PG plus typical NE/ET/MP bits
        cpu.cr4 = CR4_PAE | 0x668
        cpu.efer = EFER_LME | EFER_LMA | 0x1  # long mode + SCE
        cpu.rip = rip
        cpu.rsp = rsp
        cpu.rflags = 0x202
        # attr bits 8..11 mirror limit[16:19] (see core.cpustate.Seg).
        cpu.cs = Seg(present=True, selector=0x33, base=0, limit=0xFFFFFFFF, attr=0xAFFB)
        cpu.ss = Seg(present=True, selector=0x2B, base=0, limit=0xFFFFFFFF, attr=0xCFF3)
        pages = {pfn: bytes(page) for pfn, page in self._phys.items()}
        return pages, cpu
