"""Windows kernel crash-dump (`mem.dmp`) parsing and writing.

The reference consumes dumps through the vendored C++ kdmp-parser
(src/libs/kdmp-parser/src/lib/kdmp-parser.h, used by src/wtf/ram.h:96-152
and bochscpu_backend.cc:276-279); SURVEY.md §2.6 keeps that component
native.  Here:

  - the FAST path is wtf_tpu/native/kdmp.cc (C++, mmap + run/bitmap walk)
    loaded over ctypes, built on demand by wtf_tpu.native.build_library;
  - the FALLBACK is a pure-Python parser of the same format, so dumps load
    even without a toolchain;
  - `write_kdmp` produces valid full/BMP dumps — the test-fixture
    generator and the synthetic-snapshot -> dmp migration path (the
    reference has no writer; its dumps come from bdump.js).

Format notes (64-bit dumps; layout documented in the reference headers and
originally reverse-engineered by the rekall project):

  HEADER64: 'PAGE'+'DU64' magic, DirectoryTableBase @0x10, BugCheckCode
  @0x38, CONTEXT @0x348 (Rax @+0x78, Rip @+0xf8, Xmm0 @+0x1a0), DumpType
  @0xf98 (1=full, 5=bmp), data @0x2000.
  Full dump: PHYSMEM_DESC @0x88 {NumberOfRuns, NumberOfPages} with
  PHYSMEM_RUN[{BasePage, PageCount}] @0x98; page data packed back-to-back
  from 0x2000 in run order (PFN holes exist in the run list, not the file).
  BMP dump: BMP_HEADER64 @0x2000 {'SDMP'/'FDMP'+'DUMP', FirstPage @+0x20,
  TotalPresentPages @+0x28, Pages @+0x30, Bitmap @+0x38}; page data packed
  from FirstPage in ascending-PFN bitmap order.
"""

from __future__ import annotations

import ctypes
import dataclasses
import mmap
import struct
from pathlib import Path
from typing import Dict, Optional

PAGE_SIZE = 0x1000

SIG_PAGE = 0x45474150  # 'PAGE'
SIG_DU64 = 0x34365544  # 'DU64'
BMP_SDMP = 0x504D4453  # 'SDMP'
BMP_FDMP = 0x504D4446  # 'FDMP'
BMP_DUMP = 0x504D5544  # 'DUMP'

FULL_DUMP = 1
KERNEL_DUMP = 2
BMP_DUMP_TYPE = 5

_OFF_DTB = 0x10
_OFF_BUGCHECK = 0x38
_OFF_PHYSMEM_DESC = 0x88
_OFF_PHYSMEM_RUNS = 0x98
_OFF_CONTEXT = 0x348
_OFF_DUMPTYPE = 0xF98
_OFF_DATA = 0x2000
_CTX_SIZE = 0xF00 - 0x348

# CONTEXT-relative offsets
_CTX_MXCSR = 0x34
_CTX_SEGCS = 0x38
_CTX_EFLAGS = 0x44
_CTX_RAX = 0x78       # Rax,Rcx,Rdx,Rbx,Rsp,Rbp,Rsi,Rdi,R8..R15
_CTX_RIP = 0xF8
_CTX_MXCSR2 = 0x118
_CTX_XMM0 = 0x1A0


class KdmpError(ValueError):
    pass


@dataclasses.dataclass
class KdmpInfo:
    dump_type: int
    dtb: int
    bugcheck_code: int
    n_pages: int
    context_raw: bytes

    def context_registers(self) -> Dict[str, int]:
        """Decode the useful registers out of the raw CONTEXT record (the
        reference takes CPU state from regs.json instead; this is for
        inspection and for dumps captured without bdump)."""
        ctx = self.context_raw
        names = ("rax", "rcx", "rdx", "rbx", "rsp", "rbp", "rsi", "rdi",
                 "r8", "r9", "r10", "r11", "r12", "r13", "r14", "r15")
        regs = {name: struct.unpack_from("<Q", ctx, _CTX_RAX + i * 8)[0]
                for i, name in enumerate(names)}
        regs["rip"] = struct.unpack_from("<Q", ctx, _CTX_RIP)[0]
        regs["rflags"] = struct.unpack_from("<I", ctx, _CTX_EFLAGS)[0]
        regs["mxcsr"] = struct.unpack_from("<I", ctx, _CTX_MXCSR)[0]
        for i, seg in enumerate(("cs", "ds", "es", "fs", "gs", "ss")):
            regs[seg] = struct.unpack_from("<H", ctx, _CTX_SEGCS + i * 2)[0]
        return regs


# ---------------------------------------------------------------------------
# native fast path
# ---------------------------------------------------------------------------

_NATIVE: Optional[ctypes.CDLL] = None
_NATIVE_TRIED = False


def _native_lib() -> Optional[ctypes.CDLL]:
    global _NATIVE, _NATIVE_TRIED
    if _NATIVE_TRIED:
        return _NATIVE
    _NATIVE_TRIED = True
    from wtf_tpu.native import build_library

    path = build_library("wtfkdmp", ["kdmp.cc"])
    if path is None:
        return None
    lib = ctypes.CDLL(str(path))
    lib.wtf_kdmp_open.restype = ctypes.c_void_p
    lib.wtf_kdmp_open.argtypes = [ctypes.c_char_p]
    lib.wtf_kdmp_close.argtypes = [ctypes.c_void_p]
    lib.wtf_kdmp_dump_type.restype = ctypes.c_uint32
    lib.wtf_kdmp_dump_type.argtypes = [ctypes.c_void_p]
    lib.wtf_kdmp_n_pages.restype = ctypes.c_uint64
    lib.wtf_kdmp_n_pages.argtypes = [ctypes.c_void_p]
    lib.wtf_kdmp_pages.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64)]
    lib.wtf_kdmp_dtb.restype = ctypes.c_uint64
    lib.wtf_kdmp_dtb.argtypes = [ctypes.c_void_p]
    lib.wtf_kdmp_bugcheck_code.restype = ctypes.c_uint32
    lib.wtf_kdmp_bugcheck_code.argtypes = [ctypes.c_void_p]
    lib.wtf_kdmp_context.restype = ctypes.c_int
    lib.wtf_kdmp_context.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64]
    _NATIVE = lib
    return lib


def _parse_native(path: Path):
    lib = _native_lib()
    if lib is None:
        return None
    handle = lib.wtf_kdmp_open(str(path).encode())
    if not handle:
        return None  # let the python path produce the precise error
    try:
        n = lib.wtf_kdmp_n_pages(handle)
        pfns = (ctypes.c_uint64 * n)()
        offsets = (ctypes.c_uint64 * n)()
        lib.wtf_kdmp_pages(handle, pfns, offsets)
        ctx = (ctypes.c_uint8 * _CTX_SIZE)()
        lib.wtf_kdmp_context(handle, ctx, _CTX_SIZE)
        info = KdmpInfo(
            dump_type=lib.wtf_kdmp_dump_type(handle),
            dtb=lib.wtf_kdmp_dtb(handle),
            bugcheck_code=lib.wtf_kdmp_bugcheck_code(handle),
            n_pages=n,
            context_raw=bytes(ctx),
        )
        index = [(int(pfns[i]), int(offsets[i])) for i in range(n)]
        return info, index
    finally:
        lib.wtf_kdmp_close(handle)


# ---------------------------------------------------------------------------
# pure-python fallback
# ---------------------------------------------------------------------------

def _parse_python(data) -> tuple:
    try:
        return _parse_python_inner(data)
    except (IndexError, struct.error) as e:
        # corrupt headers pointing outside the file surface as the module's
        # declared error type, matching the native parser's bounds checks
        raise KdmpError(f"corrupt dump header: {e}") from e


def _parse_python_inner(data) -> tuple:
    def u32(off):
        return struct.unpack_from("<I", data, off)[0]

    def u64(off):
        return struct.unpack_from("<Q", data, off)[0]

    if len(data) < _OFF_DATA:
        raise KdmpError("file too small for a 64-bit dump header")
    if u32(0) != SIG_PAGE or u32(4) != SIG_DU64:
        raise KdmpError("bad signature (not a 64-bit kernel crash dump)")
    dump_type = u32(_OFF_DUMPTYPE)
    index = []
    if dump_type == FULL_DUMP:
        nruns = u32(_OFF_PHYSMEM_DESC)
        if nruns == SIG_PAGE or nruns > 4096:
            raise KdmpError("invalid physmem descriptor")
        file_off = _OFF_DATA
        for i in range(nruns):
            base = u64(_OFF_PHYSMEM_RUNS + i * 16)
            count = u64(_OFF_PHYSMEM_RUNS + i * 16 + 8)
            for p in range(count):
                if file_off + PAGE_SIZE > len(data):
                    raise KdmpError("truncated full dump")
                index.append((base + p, file_off))
                file_off += PAGE_SIZE
    elif dump_type == BMP_DUMP_TYPE:
        sig = u32(_OFF_DATA)
        if sig not in (BMP_SDMP, BMP_FDMP) or u32(_OFF_DATA + 4) != BMP_DUMP:
            raise KdmpError("bad BMP dump header")
        first_page = u64(_OFF_DATA + 0x20)
        total_present = u64(_OFF_DATA + 0x28)
        bitmap_pages = u64(_OFF_DATA + 0x30)
        bitmap_off = _OFF_DATA + 0x38
        if bitmap_off + bitmap_pages // 8 > len(data):
            raise KdmpError("bitmap extends past end of file")
        file_off = first_page
        for byte_idx in range(bitmap_pages // 8):
            byte = data[bitmap_off + byte_idx]
            if not byte:
                continue
            for bit in range(8):
                if not (byte >> bit) & 1:
                    continue
                if file_off + PAGE_SIZE > len(data):
                    raise KdmpError("truncated BMP dump")
                index.append((byte_idx * 8 + bit, file_off))
                file_off += PAGE_SIZE
        if len(index) != total_present:
            raise KdmpError(
                f"bitmap/total mismatch ({len(index)} != {total_present})")
    elif dump_type == KERNEL_DUMP:
        raise KdmpError("partial kernel dumps are not supported "
                        "(use full or active/BMP dumps, as the reference)")
    else:
        raise KdmpError(f"unknown dump type {dump_type}")
    info = KdmpInfo(
        dump_type=dump_type,
        dtb=u64(_OFF_DTB),
        bugcheck_code=u32(_OFF_BUGCHECK),
        n_pages=len(index),
        context_raw=bytes(data[_OFF_CONTEXT:_OFF_CONTEXT + _CTX_SIZE]),
    )
    return info, index


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def parse_kdmp_info(path) -> KdmpInfo:
    """Header-only parse (dump type, DTB, bugcheck, context, page count)."""
    return _parse(Path(path))[0]


def parse_kdmp(path) -> Dict[int, bytes]:
    """Parse a dump into {pfn: 4KiB page bytes} (the shape
    snapshot.loader/PhysMem.from_pages consume).  One mmap serves both the
    (fallback) header parse and the page slicing."""
    path = Path(path)
    native = _parse_native(path)
    pages: Dict[int, bytes] = {}
    with open(path, "rb") as f:
        with mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ) as m:
            _, index = native if native is not None else _parse_python(m)
            for pfn, off in index:
                pages[pfn] = bytes(m[off:off + PAGE_SIZE])
    return pages


def _parse(path: Path):
    native = _parse_native(path)
    if native is not None:
        return native
    with open(path, "rb") as f:
        with mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ) as m:
            return _parse_python(m)


# ---------------------------------------------------------------------------
# writer (fixtures + synthetic -> dmp migration)
# ---------------------------------------------------------------------------

def write_kdmp(path, pages: Dict[int, bytes], dump_type: str = "bmp",
               dtb: int = 0, cpu=None, bugcheck_code: int = 0) -> None:
    """Write a valid 64-bit dump.  `pages` maps pfn -> 4KiB bytes;
    `dump_type` is 'full' or 'bmp'; `cpu` (a CpuState) fills the CONTEXT
    record when given."""
    header = bytearray(_OFF_DATA)
    struct.pack_into("<II", header, 0, SIG_PAGE, SIG_DU64)
    struct.pack_into("<II", header, 8, 15, 19041)  # plausible major/minor
    struct.pack_into("<Q", header, _OFF_DTB, dtb)
    struct.pack_into("<I", header, _OFF_BUGCHECK, bugcheck_code)
    _write_context(header, cpu)

    pfns = sorted(pages)
    for pfn in pfns:
        if len(pages[pfn]) != PAGE_SIZE:
            raise ValueError(f"page {pfn:#x} is not 4KiB")

    if dump_type == "full":
        struct.pack_into("<I", header, _OFF_DUMPTYPE, FULL_DUMP)
        runs = _runs_of(pfns)
        if _OFF_PHYSMEM_RUNS + len(runs) * 16 > _OFF_CONTEXT:
            raise ValueError(f"too many physmem runs ({len(runs)})")
        struct.pack_into("<IIQ", header, _OFF_PHYSMEM_DESC,
                         len(runs), 0, len(pfns))
        for i, (base, count) in enumerate(runs):
            struct.pack_into("<QQ", header, _OFF_PHYSMEM_RUNS + i * 16,
                             base, count)
        with open(path, "wb") as f:
            f.write(header)
            for pfn in pfns:
                f.write(pages[pfn])
    elif dump_type == "bmp":
        struct.pack_into("<I", header, _OFF_DUMPTYPE, BMP_DUMP_TYPE)
        bitmap_pages = ((pfns[-1] + 8) // 8 * 8) if pfns else 0
        bitmap = bytearray(bitmap_pages // 8)
        for pfn in pfns:
            bitmap[pfn // 8] |= 1 << (pfn % 8)
        # page data starts page-aligned after the bitmap
        first_page = (_OFF_DATA + 0x38 + len(bitmap) + PAGE_SIZE - 1) \
            // PAGE_SIZE * PAGE_SIZE
        bmp = bytearray(first_page - _OFF_DATA)
        struct.pack_into("<II", bmp, 0, BMP_SDMP, BMP_DUMP)
        struct.pack_into("<QQQ", bmp, 0x20,
                         first_page, len(pfns), bitmap_pages)
        bmp[0x38:0x38 + len(bitmap)] = bitmap
        with open(path, "wb") as f:
            f.write(header)
            f.write(bmp)
            for pfn in pfns:
                f.write(pages[pfn])
    else:
        raise ValueError(f"dump_type must be 'full' or 'bmp', not "
                         f"{dump_type!r}")


def _write_context(header: bytearray, cpu) -> None:
    """Fill the CONTEXT record (MxCsr mirrored into MxCsr2 — parsers
    integrity-check that, reference CONTEXT::LooksGood)."""
    base = _OFF_CONTEXT
    mxcsr = 0x1F80
    if cpu is not None:
        order = ("rax", "rcx", "rdx", "rbx", "rsp", "rbp", "rsi", "rdi",
                 "r8", "r9", "r10", "r11", "r12", "r13", "r14", "r15")
        for i, name in enumerate(order):
            struct.pack_into("<Q", header, base + _CTX_RAX + i * 8,
                             getattr(cpu, name))
        struct.pack_into("<Q", header, base + _CTX_RIP, cpu.rip)
        struct.pack_into("<I", header, base + _CTX_EFLAGS,
                         cpu.rflags & 0xFFFFFFFF)
        # segment selectors (CONTEXT order: cs ds es fs gs ss) — found
        # missing by the reference-parser differential (test_kdmp.py)
        for i, seg in enumerate(("cs", "ds", "es", "fs", "gs", "ss")):
            struct.pack_into("<H", header, base + _CTX_SEGCS + i * 2,
                             getattr(cpu, seg).selector & 0xFFFF)
        mxcsr = getattr(cpu, "mxcsr", mxcsr)
        for i in range(16):
            struct.pack_into("<QQ", header, base + _CTX_XMM0 + i * 16,
                             cpu.zmm[i][0] & ((1 << 64) - 1),
                             cpu.zmm[i][1] & ((1 << 64) - 1))
    struct.pack_into("<I", header, base + _CTX_MXCSR, mxcsr)
    struct.pack_into("<I", header, base + _CTX_MXCSR2, mxcsr)


def _runs_of(pfns):
    """Consecutive-PFN ranges -> [(base, count)]."""
    runs = []
    for pfn in pfns:
        if runs and runs[-1][0] + runs[-1][1] == pfn:
            runs[-1][1] += 1
        else:
            runs.append([pfn, 1])
    return [tuple(r) for r in runs]
