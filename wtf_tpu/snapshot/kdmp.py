"""Windows kernel crash-dump (mem.dmp) parsing.

Equivalent of the reference's vendored kdmp-parser (reference
src/libs/kdmp-parser/src/lib/kdmp-parser.h): parses 64-bit full and BMP
crash dumps into a {pfn: page bytes} mapping.  The fast path is the native
C++ parser under native/ (ctypes-loaded); this module holds the pure-Python
fallback and the shared format structs.

Status: implemented by `parse_kdmp` once the native/python parsers land
(build plan task: native components).  Until then, loading a real mem.dmp
raises a clear error instead of ModuleNotFoundError.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict


def parse_kdmp(path) -> Dict[int, bytes]:
    """Parse a Windows kernel crash dump into {pfn: 4KiB page}."""
    header = Path(path).open("rb").read(8)
    if header != b"PAGEDU64":
        raise ValueError(f"{path}: not a 64-bit kernel crash dump (bad signature {header!r})")
    raise NotImplementedError(
        "mem.dmp parsing is not wired up yet in this build; convert the dump "
        "with tools to the raw mem.npz format, or wait for the native kdmp "
        "parser (native/kdmp) to land"
    )
