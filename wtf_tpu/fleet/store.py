"""Append-only content-addressed corpus/crash store.

The flat `outputs/` and `crashes/` directories scale poorly past a few
thousand entries (one directory, one file per testcase, no journal to
recover from) and give the master no dedup memory beyond what it holds
in RAM.  The fleet store is the durable half of the corpus/crash
service:

  blobs       <root>/<namespace>/blobs/<d0d1>/<digest> — content-
              addressed (utils.hashing.hex_digest, the ONE digest that
              also names flat outputs/ files) in 256-way fanout dirs,
              written atomically; a blob is immutable once written
  journal     <root>/<namespace>/manifest.jsonl — one JSON line per
              ACCEPTED blob in arrival order: digest, size, kind
              (corpus/crash), the reported name and triage bucket for
              crashes.  Append-only with a torn-tail-tolerant loader
              (same contract as the telemetry JSONL)
  dedup       content dedup on write (digest already journaled = a
              `fleet.store_dedup` hit, no I/O); crash intake
              additionally dedups by the PR-9 triage bucket — only
              novel buckets are persisted and announced
  namespaces  `namespace(name)` opens a sibling store under the same
              root — the per-tenant isolation seam (wtf_tpu/tenancy)
  fsck        `verify(repair=True)` recovers after torn writes or a
              lost journal: blobs failing their digest name are
              quarantined (.torn suffix), journal entries whose blob
              vanished are dropped, orphan blobs are re-journaled

Flat views: `link_into(dir, digest)` materializes a blob in a flat
directory (hardlink when the filesystem allows, copy otherwise) — how
`outputs/` and `crashes/` remain byte-compatible views for the seed
replay scan, minset pruning, and operators' eyeballs while the store is
the system of record.
"""

from __future__ import annotations

import json
import logging
import os
import re
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple

from wtf_tpu.utils.atomicio import atomic_write_bytes
from wtf_tpu.utils.hashing import hex_digest

log = logging.getLogger(__name__)

_NS_RE = re.compile(r"^[A-Za-z0-9_.-]+$")


class StoreError(RuntimeError):
    pass


class FleetStore:
    def __init__(self, root, namespace: str = "default",
                 registry=None, events=None):
        if not _NS_RE.match(namespace):
            raise StoreError(f"bad store namespace {namespace!r}")
        self.root = Path(root)
        self.ns = namespace
        self.dir = self.root / namespace
        self.blob_dir = self.dir / "blobs"
        self.journal_path = self.dir / "manifest.jsonl"
        self.registry = registry
        self.events = events
        self._digests: Dict[str, dict] = {}
        self._buckets: Dict[str, str] = {}  # bucket -> first digest
        self._load_journal()

    # -- journal ---------------------------------------------------------
    def _load_journal(self) -> None:
        if not self.journal_path.exists():
            return
        for line in self.journal_path.read_text(
                encoding="utf-8", errors="replace").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                # torn tail from a kill mid-append: everything before it
                # is intact (one record per line), the partial line is
                # simply re-earned on the next put
                log.warning("store %s: torn journal tail ignored", self.ns)
                break
            self._index(rec)

    def _index(self, rec: dict) -> None:
        digest = rec.get("digest", "")
        if digest:
            self._digests.setdefault(digest, rec)
        bucket = rec.get("bucket")
        if bucket:
            self._buckets.setdefault(bucket, digest)

    def _append_journal(self, rec: dict) -> None:
        self.dir.mkdir(parents=True, exist_ok=True)
        with open(self.journal_path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(rec, sort_keys=True) + "\n")
            fh.flush()
            os.fsync(fh.fileno())

    # -- blobs -----------------------------------------------------------
    def blob_path(self, digest: str) -> Path:
        return self.blob_dir / digest[:2] / digest

    def _count(self, name: str, n: int = 1) -> None:
        if self.registry is not None:
            self.registry.counter(name).inc(n)

    def put(self, data: bytes, kind: str = "corpus",
            name: Optional[str] = None,
            bucket: Optional[str] = None) -> Tuple[str, bool]:
        """Store one blob; returns (digest, accepted).  Content
        duplicates cost nothing (`fleet.store_dedup`); crash blobs with
        a known triage bucket are dropped entirely
        (`fleet.bucket_dedup`) — only novel buckets persist."""
        digest = hex_digest(data)
        if digest in self._digests:
            self._count("fleet.store_dedup")
            return digest, False
        if kind == "crash" and bucket and bucket in self._buckets:
            self._count("fleet.bucket_dedup")
            return digest, False
        path = self.blob_path(digest)
        if not path.exists():
            atomic_write_bytes(path, data)
        rec = {"digest": digest, "size": len(data), "kind": kind}
        if name:
            rec["name"] = name
        if bucket:
            rec["bucket"] = bucket
        self._append_journal(rec)
        self._index(rec)
        self._count("fleet.store_puts")
        if self.events is not None:
            self.events.emit("store-put", store=self.ns, kind=kind,
                             digest=digest, size=len(data),
                             bucket=bucket or None)
        return digest, True

    def get(self, digest: str) -> bytes:
        data = self.blob_path(digest).read_bytes()
        if hex_digest(data) != digest:
            raise StoreError(f"blob {digest[:16]}… fails its digest "
                             "(torn write?)")
        return data

    def has(self, digest: str) -> bool:
        return digest in self._digests

    def has_bucket(self, bucket: str) -> bool:
        return bucket in self._buckets

    def __len__(self) -> int:
        return len(self._digests)

    def records(self, kind: Optional[str] = None) -> Iterator[dict]:
        """Journal records in arrival order (optionally one kind)."""
        for rec in self._digests.values():
            if kind is None or rec.get("kind") == kind:
                yield rec

    @property
    def buckets(self) -> Dict[str, str]:
        return dict(self._buckets)

    # -- flat views ------------------------------------------------------
    def link_into(self, directory, digest: str,
                  name: Optional[str] = None) -> Path:
        """Materialize a blob as `<directory>/<name or digest>` — the
        flat-view seam that keeps outputs//crashes/ byte-compatible.
        Hardlink when possible (no data copied), atomic copy otherwise."""
        directory = Path(directory)
        directory.mkdir(parents=True, exist_ok=True)
        dest = directory / (name or digest)
        if dest.exists():
            return dest
        try:
            os.link(self.blob_path(digest), dest)
        except OSError:
            atomic_write_bytes(dest, self.get(digest))
        return dest

    # -- namespaces (tenancy) --------------------------------------------
    def namespace(self, name: str) -> "FleetStore":
        """A sibling store under the same root — per-tenant corpus and
        crash spaces share the fanout tree layout but nothing else."""
        return FleetStore(self.root, namespace=name,
                          registry=self.registry, events=self.events)

    # -- recovery --------------------------------------------------------
    def verify(self, repair: bool = False) -> dict:
        """fsck: walk every blob, check content against its digest name,
        and reconcile with the journal.  With `repair`: quarantine torn
        blobs (renamed `<digest>.torn`), drop journal entries whose blob
        is missing or torn, journal orphan blobs (valid content, no
        record — e.g. the journal itself was lost).  The journal is then
        rewritten atomically.  Returns the report dict the RUNBOOK drill
        prints."""
        report = {"blobs": 0, "ok": 0, "torn": [], "missing": [],
                  "orphans": [], "repaired": repair}
        on_disk = {}
        if self.blob_dir.exists():
            for sub in sorted(self.blob_dir.iterdir()):
                if not sub.is_dir():
                    continue
                for p in sorted(sub.iterdir()):
                    if p.suffix == ".torn" or not p.is_file():
                        continue
                    report["blobs"] += 1
                    try:
                        data = p.read_bytes()
                    except OSError:
                        continue
                    if hex_digest(data) != p.name:
                        report["torn"].append(p.name)
                        if repair:
                            p.replace(p.with_name(p.name + ".torn"))
                        continue
                    on_disk[p.name] = len(data)
                    report["ok"] += 1
        for digest in list(self._digests):
            if digest not in on_disk:
                report["missing"].append(digest)
                if repair:
                    del self._digests[digest]
        for digest, size in on_disk.items():
            if digest not in self._digests:
                report["orphans"].append(digest)
                if repair:
                    self._index({"digest": digest, "size": size,
                                 "kind": "corpus", "recovered": True})
        if repair:
            self._buckets = {}
            lines = []
            for rec in self._digests.values():
                self._index(rec)
                lines.append(json.dumps(rec, sort_keys=True))
            from wtf_tpu.utils.atomicio import atomic_write_text

            atomic_write_text(self.journal_path,
                              "\n".join(lines) + ("\n" if lines else ""))
        return report
