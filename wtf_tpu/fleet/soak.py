"""Fleet soak: hundreds-to-1000 simulated clients over the REAL wire.

The proof harness for the fleet tier.  Everything on the wire is
production code — `dist.server.Server` reactor, `MasterLink` reconnect
machinery, the WTF3 delta cursors, the content-addressed store — only
the *execution engine* is simulated: a deterministic testcase->coverage
model (`CoverageModel`) stands in for the device, which is what makes a
1000-client campaign runnable on one box AND makes the ground truth
exact: the union of the model over every testcase the master ever
served IS the aggregate a serial replay would compute, regardless of
thread scheduling, resets or reclaims.

Injected faults (deterministic per client, keyed on run index):

  drop    the client computes a result, then its socket dies BEFORE the
          send — the delta frame is lost, the master reclaims the
          testcase; the reconnected client's next frame must repair the
          lost bits by re-extraction against the ack cursor
  reset   the socket dies AFTER the send — a pure reconnect (master
          kept the result; no reclaim)

Assertions (`run_soak` raises on any failure):
  - zero lost testcases: the master accounts exactly seeds + runs
  - aggregate coverage == the serial-replay union, byte-identical, and
    the persisted coverage.cov agrees
  - >= the scripted number of reconnects; >= 1 reclaim when drops are
    scripted
  - coverage wire bytes: delta <= bitmap-equivalent / `min_ratio`
    (the >=10x bar of the acceptance soak)
"""

from __future__ import annotations

import argparse
import json
import random
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Set

from wtf_tpu.core.results import Ok
from wtf_tpu.dist import wire
from wtf_tpu.dist.client import MasterLink
from wtf_tpu.fleet.delta import AddressDeltaCursor
from wtf_tpu.utils.hashing import mix64


class CoverageModel:
    """Deterministic testcase -> address-set model.  A large `common`
    block set every execution hits (what makes whole-bitmap exchange
    expensive) plus content-derived rare addresses (what makes coverage
    grow under mutation)."""

    BASE = 0x1_4000_0000

    def __init__(self, common: int = 1500, rare_rate: int = 8,
                 space: int = 1 << 20):
        self.common = frozenset(self.BASE + 16 * i for i in range(common))
        self.rare_rate = rare_rate
        self.space = space

    def cover(self, data: bytes) -> Set[int]:
        out = set(self.common)
        for i in range(0, max(len(data) - 3, 0), 4):
            h = mix64(int.from_bytes(data[i:i + 4], "little") ^ (i << 32))
            if h % self.rare_rate == 0:
                out.add(self.BASE + 0x10_0000 + (h % self.space) * 8)
        out.add(self.BASE + 0x20_0000 + min(len(data), 512))
        return out


class SimClient:
    """One simulated node: real MasterLink (reconnect/backoff/cursor),
    simulated execution.  `mode` selects the wire dialect — the soak can
    mix WTF3 delta speakers with whole-bitmap WTF2 and raw v1 nodes
    against the same master."""

    def __init__(self, address: str, model: CoverageModel, mode: str,
                 seed: int, registry, max_retry_secs: float = 30.0,
                 faults: Optional[Dict[int, str]] = None,
                 telem_every: int = 0, telem_dup_every: int = 0):
        assert mode in ("delta", "v2", "v1")
        self.model = model
        self.mode = mode
        self.faults = dict(faults or {})
        self.registry = registry
        cursor = (AddressDeltaCursor(registry=registry)
                  if mode == "delta" else None)
        self.link = MasterLink(address, 1, max_retry_secs,
                               registry=registry,
                               rng=random.Random(seed),
                               tagged=(mode != "v1"), cursor=cursor)
        self.local: Set[int] = set()
        self.runs = 0
        self.drops = 0
        self.resets = 0
        # TAG_TELEM emission (obs_smoke): every `telem_every` runs send
        # the client registry's cumulative snapshot; every
        # `telem_dup_every`-th frame is sent TWICE verbatim — the
        # scripted duplicate the master must drop by sequence number
        self.telem_every = telem_every
        self.telem_dup_every = telem_dup_every
        self._telem_seq = 0
        self.telem_dups_sent = 0
        self.last_telem: Optional[dict] = None

    def send_telem(self) -> None:
        """One cumulative snapshot frame on the live work connection
        (plus a scripted verbatim duplicate when dialed)."""
        if self.link.cursor is None:
            return
        self._telem_seq += 1
        snapshot = self.registry.snapshot()
        body = wire.encode_telem(self._telem_seq, snapshot)
        if not self.link.send_telem(body):
            self._telem_seq -= 1
            return
        self.last_telem = snapshot
        if (self.telem_dup_every
                and self._telem_seq % self.telem_dup_every == 0):
            if self.link.send_telem(body):
                self.telem_dups_sent += 1

    def connect(self) -> None:
        self.link.connect(retry_for=30.0)

    def step(self) -> bool:
        """One lock-step exchange; False when the campaign is over for
        this client (BYE, or the retry budget is spent)."""
        tc = self.link.recv_work()
        if tc is None:
            return False
        self.registry.counter("campaign.testcases").inc()
        if self.telem_every and (self.runs + 1) % self.telem_every == 0:
            # BEFORE the result send: the lock-step master always reads
            # up to the next result frame, so a telem frame that
            # precedes one is never stranded behind the final BYE
            self.send_telem()
        coverage = self.model.cover(tc)
        new = coverage - self.local
        self.local |= coverage
        result = Ok()
        fault = self.faults.pop(self.runs, None)
        if fault == "drop":
            # lose the result frame: the master reclaims the testcase
            self.drops += 1
            self.link._drop_socket()
        if self.link.cursor is not None:
            self.link.send_delta(self.link.cursor.encode_result(
                tc, result, coverage if new else None))
        else:
            self.link.send(wire.encode_result(
                tc, coverage if new else set(), result))
        if fault == "reset":
            # lose the connection after the send: pure reconnect
            self.resets += 1
            self.link._drop_socket()
        self.runs += 1
        return True

    def close(self) -> None:
        self.link.close()


def _drive(clients: List[SimClient]) -> None:
    """Round-robin a worker thread's client group until all retire."""
    for client in clients:
        client.connect()
    active = list(clients)
    while active:
        still = []
        for client in active:
            try:
                alive = client.step()
            except OSError:
                alive = False
            if alive:
                still.append(client)
            else:
                client.close()
        active = still


def run_soak(workdir, clients: int = 64, runs_per_client: int = 60,
             seed: int = 0xF1EE7, threads: int = 16,
             v1_clients: int = 2, v2_clients: int = 2,
             drop_every: int = 8, reset_every: int = 16,
             min_ratio: float = 10.0, use_store: bool = True,
             reclaim_timeout: float = 0.0,
             max_seconds: float = 900.0) -> dict:
    """The soak.  Returns the report dict; raises AssertionError when
    any fleet invariant breaks.  Faults are scripted: every
    `drop_every`-th delta client loses one result frame, every
    `reset_every`-th takes one post-send reset (0 disables either).
    v1/v2 clients run fault-free — re-extraction repair is a WTF3
    property; the legacy dialects prove interop, not loss recovery."""
    from wtf_tpu.dist.server import Server
    from wtf_tpu.fleet.store import FleetStore
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.mutator import ByteMutator
    from wtf_tpu.telemetry import Registry

    workdir = Path(workdir)
    workdir.mkdir(parents=True, exist_ok=True)
    address = f"unix://{workdir}/soak.sock"
    model = CoverageModel()
    rng = random.Random(seed)
    seeds = [bytes(rng.randrange(256) for _ in range(32)),
             bytes(rng.randrange(256) for _ in range(48))]
    runs = clients * runs_per_client
    store = (FleetStore(workdir / "store", registry=Registry())
             if use_store else None)
    corpus = Corpus(outputs_dir=workdir / "outputs", rng=rng,
                    store=store)
    server = Server(address, ByteMutator(rng, 64), corpus,
                    crashes_dir=workdir / "crashes", runs=runs,
                    coverage_path=workdir / "coverage.cov",
                    stats_every=5.0, reclaim_timeout=reclaim_timeout,
                    store=store)
    server.paths = list(seeds)

    # serial-replay ground truth: every testcase the master ever served
    # (re-serves after a reclaim repeat an entry; the union is a set)
    served_log: List[bytes] = []
    original_get = server.get_testcase

    def logged_get():
        tc = original_get()
        if tc is not None:
            served_log.append(tc)
        return tc

    server.get_testcase = logged_get

    server_thread = threading.Thread(
        target=server.run, kwargs={"max_seconds": max_seconds})
    server_thread.start()

    registry = Registry()  # shared by all sim clients
    sims: List[SimClient] = []
    scripted_drops = scripted_resets = 0
    for i in range(clients):
        if i < v1_clients:
            mode = "v1"
        elif i < v1_clients + v2_clients:
            mode = "v2"
        else:
            mode = "delta"
        faults: Dict[int, str] = {}
        if mode == "delta":
            idx = i - v1_clients - v2_clients
            if drop_every and idx % drop_every == 0:
                faults[2 + idx % 3] = "drop"
                scripted_drops += 1
            if reset_every and idx % reset_every == 3:
                faults[4 + idx % 3] = "reset"
                scripted_resets += 1
        sims.append(SimClient(address, model, mode, seed ^ (i << 8),
                              registry, faults=faults))

    t0 = time.time()
    groups = [sims[i::threads] for i in range(threads)]
    workers = [threading.Thread(target=_drive, args=(group,))
               for group in groups if group]
    for w in workers:
        w.start()
    for w in workers:
        w.join(timeout=max_seconds)
    server_thread.join(timeout=max_seconds)
    wall = time.time() - t0
    assert not server_thread.is_alive(), "master did not finish"

    # -- zero lost testcases --------------------------------------------
    expected = len(seeds) + runs
    accounted = server.stats.testcases
    assert accounted == expected, \
        f"lost testcases: accounted {accounted}, expected {expected}"

    # -- aggregate coverage == serial replay, byte-identical ------------
    serial: Set[int] = set()
    for tc in served_log:
        serial |= model.cover(tc)
    got = sorted(server.coverage)
    want = sorted(serial)
    assert got == want, \
        (f"aggregate coverage diverged from serial replay: "
         f"{len(got)} vs {len(want)} addresses, "
         f"missing={len(serial - server.coverage)}, "
         f"extra={len(server.coverage - serial)}")
    persisted = json.loads((workdir / "coverage.cov").read_text())
    assert persisted["addresses"] == want, "persisted coverage diverged"

    # -- fault accounting ------------------------------------------------
    retries = registry.counter("dist.retries").value
    reclaimed = server.registry.counter("dist.reclaimed").value
    if scripted_drops:
        assert reclaimed >= 1, "scripted drops produced no reclaim"
    if scripted_drops + scripted_resets:
        assert retries >= scripted_drops + scripted_resets, \
            f"retries {retries} < scripted faults"

    # -- delta wire-byte ratio ------------------------------------------
    delta_bytes = registry.counter("dist.cov_bytes_delta").value
    bitmap_bytes = registry.counter("dist.cov_bytes_bitmap").value
    ratio = bitmap_bytes / delta_bytes if delta_bytes else float("inf")
    assert ratio >= min_ratio, \
        (f"coverage wire bytes only {ratio:.1f}x smaller than "
         f"whole-bitmap exchange (bar {min_ratio}x): "
         f"{delta_bytes} vs {bitmap_bytes}")

    report = {
        "clients": clients, "runs": runs, "accounted": accounted,
        "wall_s": round(wall, 1),
        "results_per_s": round(accounted / wall, 1) if wall else None,
        "coverage": len(server.coverage), "corpus": len(corpus),
        "retries": retries, "reclaimed": reclaimed,
        "scripted_drops": scripted_drops,
        "scripted_resets": scripted_resets,
        "delta_cov_bytes": delta_bytes,
        "bitmap_equiv_bytes": bitmap_bytes,
        "delta_ratio": round(ratio, 1),
        "full_resyncs": server.registry.counter(
            "fleet.full_resyncs").value,
        "coverage_writes": server.registry.counter(
            "fleet.coverage_writes").value,
    }
    if store is not None:
        report["store_puts"] = store.registry.counter(
            "fleet.store_puts").value
        report["store_dedup"] = store.registry.counter(
            "fleet.store_dedup").value
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="wtf_tpu.fleet.soak",
        description="fleet soak: N simulated clients over the real "
                    "WTF2/WTF3 wire with injected resets/reclaims")
    parser.add_argument("--clients", type=int, default=256)
    parser.add_argument("--runs-per-client", type=int, default=60)
    parser.add_argument("--seed", type=int, default=0xF1EE7)
    parser.add_argument("--threads", type=int, default=16)
    parser.add_argument("--v1", type=int, default=2)
    parser.add_argument("--v2", type=int, default=2)
    parser.add_argument("--min-ratio", type=float, default=10.0)
    parser.add_argument("--no-store", action="store_true")
    parser.add_argument("--workdir", type=Path, default=None)
    args = parser.parse_args(argv)

    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        report = run_soak(
            args.workdir or tmp, clients=args.clients,
            runs_per_client=args.runs_per_client, seed=args.seed,
            threads=args.threads, v1_clients=args.v1,
            v2_clients=args.v2, min_ratio=args.min_ratio,
            use_store=not args.no_store)
    print(json.dumps(report, indent=1))
    print(f"fleet-soak PASS ({report['clients']} clients, zero lost, "
          f"aggregate == serial replay, delta {report['delta_ratio']}x "
          f"smaller)")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
