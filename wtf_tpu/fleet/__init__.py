"""Fleet tier: the distribution layer that scales the master/node plane
(ROADMAP item 4) from a handful of ad-hoc clients to a serving system.

Three legs, each usable on its own:

  delta.py    streaming coverage deltas over the WTF2 wire (WTF3 hello /
              TAG_COVDELTA frames): results carry only newly-set
              coverage bits as sparse word+mask pairs against the
              master's per-client ack cursor, with whole-bitmap resync
              on first contact and cursor loss
  store.py    append-only content-addressed corpus/crash store: Blake-
              digested blobs in sharded fanout dirs, dedup on write, a
              manifest journal, crash intake deduped by the PR-9 triage
              bucket, per-tenant namespaces
  elastic.py  elastic campaigns: checkpoint a running campaign at a
              batch boundary (PR-8 format) and resume it bit-identically
              under a different --mesh-devices placement
  soak.py     the proof harness: hundreds-to-1000 simulated clients over
              the real wire protocol with injected resets/reclaims,
              asserting zero lost testcases and exact aggregate-coverage
              agreement with a serial replay
"""

from wtf_tpu.fleet.delta import (
    AddressDeltaCursor, BitmapDeltaCursor, DeltaCursor, ServerCursor,
    cursor_digest,
)
from wtf_tpu.fleet.store import FleetStore

__all__ = [
    "AddressDeltaCursor", "BitmapDeltaCursor", "DeltaCursor",
    "FleetStore", "ServerCursor", "cursor_digest",
]
