"""Streaming coverage deltas: the cursor state machines behind WTF3.

The v1/v2 wire ships each new-coverage result's WHOLE coverage set —
O(covered blocks) u64 addresses per result, forever.  The `[words, 32]`
bit-plane formulation of coverage makes the delta trivially cheap to
extract instead: a lane's newly-set bits are one XOR/AND against the
client's last-acked aggregate, and popcount tells how many.  A WTF3
connection therefore sends, per result, only the bits the master has
not acked — as sparse (word index, u32 mask) pairs over the CLIENT's
own bit space — plus incremental bit->address table registrations so
the master can map them into its global address set.

Cursor protocol (all state machines in this module):

  client side   `DeltaCursor` tracks the acked aggregate + the one
                in-flight (pending) delta of a lock-step link.  A WORK
                frame is the implicit ack (the master only serves after
                accounting); a TAG_CURSOR frame after (re)connect is
                the explicit resync point: the master names the cursor
                it holds for this client identity, the client compares
                against its acked state (with and without the pending
                fold) and either resumes sparse deltas or resets to a
                whole-bitmap resync.
  server side   `ServerCursor` holds the per-client bit->address table
                + acked bitmap, maps incoming delta frames to address
                sets (idempotent under re-sends — the merge is a set
                union), and is what the master persists alongside its
                coverage file so a RESTARTED master can resume client
                cursors instead of forcing whole-bitmap resyncs.

Loss recovery never needs retransmission bookkeeping beyond the acked
bitmap: the next delta is always extracted against *acked*, so anything
lost in flight is simply re-extracted — the OR-merge makes duplicates
free.
"""

from __future__ import annotations

import hashlib
import os
import struct
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from wtf_tpu.dist import wire

MASK32 = 0xFFFFFFFF


def cursor_digest(table: Sequence[int], words: np.ndarray,
                  n_table: int) -> bytes:
    """8-byte digest of an ack-cursor state: the first `n_table` table
    addresses plus the acked bitmap canonicalized to ceil(n_table/32)
    words (zero-padded — client and server arrays may differ in
    allocation length but never in set bits)."""
    h = hashlib.blake2b(digest_size=8)
    h.update(struct.pack("<I", n_table))
    h.update(struct.pack(f"<{n_table}Q", *[int(a) for a in
                                           table[:n_table]]))
    n_words = (n_table + 31) // 32
    canon = np.zeros(n_words, np.uint32)
    src = np.asarray(words[:n_words], np.uint32)
    canon[:len(src)] = src
    h.update(canon.tobytes())
    return h.digest()


def _grow(words: np.ndarray, n: int) -> np.ndarray:
    if len(words) >= n:
        return words
    out = np.zeros(n, np.uint32)
    out[:len(words)] = words
    return out


def _or_words(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    out = _grow(a.copy(), len(b))
    out[:len(b)] |= b
    return out


def pairs_of(words: np.ndarray) -> List[Tuple[int, int]]:
    """Sparse (word index, mask) encoding of a bitmap's nonzero words."""
    idx = np.nonzero(words)[0]
    return [(int(i), int(words[i])) for i in idx]


def popcount(words) -> int:
    if hasattr(np, "bitwise_count"):
        return int(np.bitwise_count(np.asarray(words, np.uint32)).sum())
    return sum(bin(int(w)).count("1") for w in np.asarray(words).ravel())


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------

class DeltaCursor:
    """Client-side ack-cursor for one master link (lock-step framing).

    Subclasses own the bit space and feed per-result deltas through
    `_emit`; this base holds the acked/pending bookkeeping, the cursor
    handshake, and the wire-byte accounting (`dist.cov_bytes_delta` vs
    `dist.cov_bytes_bitmap` — the measured delta-vs-whole-bitmap ratio
    the soak asserts on)."""

    def __init__(self, client_id: Optional[bytes] = None, registry=None):
        self.client_id = client_id or os.urandom(wire.CLIENT_ID_LEN)
        self.registry = registry
        self._acked_table = 0
        self._acked = np.zeros(0, np.uint32)
        # the one in-flight delta of a lock-step link: (words, table_len)
        self._pending: Optional[Tuple[np.ndarray, int]] = None
        # whole-bitmap resync owed (first contact / cursor mismatch)
        self._force_full = True
        self.full_resyncs = 0

    # -- the bit space (subclass) ---------------------------------------
    def _table(self) -> Sequence[int]:
        raise NotImplementedError

    # -- link callbacks (MasterLink drives these) -----------------------
    def on_cursor(self, n_table: int, digest: bytes) -> None:
        """TAG_CURSOR arrived after (re)connect: resolve our state
        against the cursor the master holds.  Three outcomes: the master
        saw our pending frame (fold it), it did not (drop pending — the
        bits stay unacked and re-extract into the next delta), or it
        holds something else entirely (fresh/older master: reset to a
        whole-bitmap resync)."""
        table = self._table()
        if self._pending is not None:
            words, tlen = self._pending
            folded = _or_words(self._acked, words)
            n = max(self._acked_table, tlen)
            if n == n_table and cursor_digest(table, folded, n) == digest:
                self._acked, self._acked_table = folded, n
                self._pending = None
                self._force_full = False
                return
        self._pending = None
        if (self._acked_table == n_table
                and cursor_digest(table, self._acked,
                                  self._acked_table) == digest):
            self._force_full = False
            return
        # cursor lost (restarted master without persisted cursors, or a
        # different master): whole-bitmap resync on the next frame
        self._acked_table = 0
        self._acked = np.zeros(0, np.uint32)
        self._force_full = True

    def on_ack(self) -> None:
        """A WORK frame landed: the master accounted everything we sent
        on this connection (it only serves after handling the result)."""
        if self._pending is not None:
            words, tlen = self._pending
            self._acked = _or_words(self._acked, words)
            self._acked_table = max(self._acked_table, tlen)
            self._pending = None

    # -- delta extraction ------------------------------------------------
    def unacked(self, current: np.ndarray) -> np.ndarray:
        """`current & ~acked`: every bit the master has not acked —
        including bits lost with a dropped frame, which is the whole
        loss-recovery story (re-extraction, not retransmission)."""
        out = np.array(current, np.uint32, copy=True)
        n = min(len(out), len(self._acked))
        out[:n] &= ~self._acked[:n]
        return out

    def _emit(self, testcase: bytes, result, delta_words: np.ndarray,
              table_len: int, bucket: str = "",
              full_equiv_bits: int = 0, first: bool = True) -> bytes:
        """Encode one delta-result body and note it as pending.  `first`
        is False for the 2nd..Nth bodies of one mux batch frame (they
        share the first body's full flag + table registration watermark).
        `full_equiv_bits` is what a v1/v2 client would have shipped for
        this result (|whole coverage set|), for the byte accounting."""
        full = self._force_full and first
        pairs = pairs_of(delta_words)
        if pairs or full:
            base = self._acked_table if not full else 0
            if self._pending is not None:
                base = max(base, self._pending[1])
            addrs = [int(a) for a in self._table()[base:table_len]]
        else:
            base, addrs, table_len = self._acked_table, [], self._acked_table
        frame = wire.DeltaFrame(full, base, addrs, pairs)
        body = wire.encode_result_delta(testcase, result, frame, bucket)
        if pairs or full:
            prev_words = (self._pending[0] if self._pending is not None
                          else np.zeros(0, np.uint32))
            prev_tlen = (self._pending[1] if self._pending is not None
                         else 0)
            self._pending = (_or_words(prev_words, delta_words),
                             max(prev_tlen, table_len))
        if full:
            self.full_resyncs += 1
            self._force_full = False
        if self.registry is not None:
            self.registry.counter("dist.cov_bytes_delta").inc(
                frame.cov_bytes())
            # what the v1/v2 coverage section would have cost for this
            # exact result: u32 n_cov + 8 bytes per address of the
            # whole set (0 addresses for revoked/no-new results)
            self.registry.counter("dist.cov_bytes_bitmap").inc(
                4 + 8 * full_equiv_bits)
        return body

    @property
    def wants_full(self) -> bool:
        return self._force_full

    def encode_empty(self, testcase: bytes, result,
                     bucket: str = "") -> bytes:
        """A zero-coverage body that carries NO delta bits and touches
        no cursor state — for results whose coverage is revoked
        (timeouts, overlay-full): unacked repair must never ride them,
        or the master would credit a hang-inducing testcase with lost
        coverage and admit it to the corpus."""
        frame = wire.DeltaFrame(False, self._acked_table, [], [])
        if self.registry is not None:
            self.registry.counter("dist.cov_bytes_delta").inc(
                frame.cov_bytes())
            self.registry.counter("dist.cov_bytes_bitmap").inc(4)
        return wire.encode_result_delta(testcase, result, frame, bucket)


class AddressDeltaCursor(DeltaCursor):
    """Delta cursor over an address-set coverage source (the emu/oracle
    backends, and the per-lane links of a non-mux batch node): bit
    indices are assigned in first-seen order, the client-side analog of
    the decode cache's insertion order."""

    def __init__(self, client_id: Optional[bytes] = None, registry=None):
        super().__init__(client_id, registry)
        self._addr_index: Dict[int, int] = {}
        self._table_list: List[int] = []
        self._current = np.zeros(0, np.uint32)

    def _table(self) -> Sequence[int]:
        return self._table_list

    def feed(self, coverage: Set[int]) -> None:
        """Record a result's coverage set into the client bit space."""
        for addr in coverage:
            idx = self._addr_index.get(addr)
            if idx is None:
                idx = len(self._table_list)
                self._addr_index[addr] = idx
                self._table_list.append(int(addr))
            self._current = _grow(self._current, idx // 32 + 1)
            self._current[idx // 32] |= np.uint32(1 << (idx % 32))

    def encode_result(self, testcase: bytes, result,
                      coverage: Optional[Set[int]] = None,
                      bucket: str = "") -> bytes:
        """One result -> one delta body.  `coverage` is the result's
        whole coverage set (None/empty for results with nothing new to
        report — the frame still repairs any unacked bits)."""
        full_bits = len(coverage) if coverage else 0
        if coverage:
            self.feed(coverage)
        delta = self.unacked(self._current)
        return self._emit(testcase, result, delta, len(self._table_list),
                          bucket=bucket, full_equiv_bits=full_bits)

    def has_unacked(self) -> bool:
        return self.wants_full or bool(np.any(self.unacked(self._current)))


class BitmapDeltaCursor(DeltaCursor):
    """Delta cursor over the batched backend's native bit space: bit i
    IS decode-cache entry i, so delta extraction is exactly the
    XOR/popcount the `[words, 32]` formulation promises — no address-set
    decode on the hot path.  One cursor per mux link."""

    def __init__(self, backend, client_id: Optional[bytes] = None,
                 registry=None):
        super().__init__(client_id, registry)
        self._backend = backend
        self._rips: List[int] = []

    def _table(self) -> Sequence[int]:
        cache = self._backend.runner.cache
        while len(self._rips) < cache.count:
            self._rips.append(int(cache.rip_of(len(self._rips))))
        return self._rips

    def table_len(self) -> int:
        return len(self._table())

    def encode_lane(self, testcase: bytes, result,
                    lane_words: Optional[np.ndarray], claimed: np.ndarray,
                    bucket: str = "", first: bool = True) -> bytes:
        """One lane's body within a batch frame.  `lane_words` is the
        lane's coverage bitmap (None for lanes with nothing to report);
        `claimed` accumulates the bits earlier lanes of this batch
        already carry, so each new bit rides exactly one body."""
        if lane_words is None:
            delta = np.zeros(0, np.uint32)
            full_bits = 0
        else:
            delta = self.unacked(lane_words)
            n = min(len(delta), len(claimed))
            delta[:n] &= ~claimed[:n]
            claimed[:len(delta)] |= delta
            full_bits = popcount(lane_words)
        return self._emit(testcase, result, delta, self.table_len(),
                          bucket=bucket, full_equiv_bits=full_bits,
                          first=first)


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------

class ServerCursor:
    """Master-side per-client ack cursor: the client's bit->address
    table plus the acked bitmap.  `apply` maps a delta frame to the
    address set the rest of the master already understands; re-applied
    frames are free (set-union merge).  `state`/`from_state` are the
    persistence hooks the master's coverage file uses so client cursors
    survive a master restart."""

    def __init__(self):
        self.table: List[int] = []
        self.words = np.zeros(0, np.uint32)
        # LRU bookkeeping for the master's eviction policy (a cursor is
        # a near-copy of the address table per client identity, and
        # identities are fresh per node process — dead ones must not
        # accumulate forever).  Not part of the digest.
        import time

        self.last_seen = time.time()

    def touch(self) -> None:
        import time

        self.last_seen = time.time()

    def summary(self) -> Tuple[int, bytes]:
        n = len(self.table)
        return n, cursor_digest(self.table, self.words, n)

    def apply(self, frame: wire.DeltaFrame) -> Set[int]:
        """Merge one delta frame; returns the addresses its bits name.
        Raises ValueError on protocol violations (table gaps, conflicting
        re-registrations, bits beyond the table) — the master treats
        that like any malformed frame: drop the node, reclaim its work."""
        self.touch()
        if frame.full:
            self.table = []
            self.words = np.zeros(0, np.uint32)
        base = frame.table_base
        if base > len(self.table):
            raise ValueError(
                f"delta table gap (base {base}, have {len(self.table)})")
        for i, addr in enumerate(frame.addrs):
            idx = base + i
            if idx < len(self.table):
                if self.table[idx] != addr:
                    raise ValueError(f"delta table conflict at bit {idx}")
            else:
                self.table.append(int(addr))
        out: Set[int] = set()
        if frame.pairs:
            self.words = _grow(self.words,
                               max(w for w, _ in frame.pairs) + 1)
            for word_idx, mask in frame.pairs:
                mask = int(mask) & MASK32
                base_bit = word_idx * 32
                self.words[word_idx] |= np.uint32(mask)
                while mask:
                    low = mask & -mask
                    idx = base_bit + low.bit_length() - 1
                    if idx >= len(self.table):
                        raise ValueError(
                            f"delta bit {idx} beyond table "
                            f"({len(self.table)} entries)")
                    out.add(self.table[idx])
                    mask ^= low
        return out

    # -- persistence (the master's coverage file) ------------------------
    def state(self) -> dict:
        return {"table": list(self.table),
                "words": self.words.tobytes().hex()}

    @classmethod
    def from_state(cls, state: dict) -> "ServerCursor":
        cur = cls()
        cur.table = [int(a) for a in state.get("table", [])]
        raw = bytes.fromhex(state.get("words", ""))
        cur.words = np.frombuffer(raw, np.uint32).copy()
        return cur
