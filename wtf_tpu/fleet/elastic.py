"""Elastic campaigns: reshard a live campaign onto a different device
count without losing a bit.

Why this is nearly free (the Concordia posture, PAPERS.md): PR-8
checkpoints are *placement-free* — coverage bitmaps, decode cache,
devmut slab views and RNG state none of which mention a mesh — and PR-7
mesh programs are byte-stable per device with shard-count-invariant
devmut streams.  So "autoscale a running campaign from 1 chip to 8"
decomposes into machinery that already exists:

  1. the in-master policy hook (`FuzzLoop.reshard_policy`) fires at a
     batch boundary: the loop checkpoints (PR-8 format) and returns
     with `reshard_to` set
  2. the driver rebuilds the campaign against the new `--mesh-devices`
     count and restores the checkpoint — bit-identical resume is the
     PR-8 parity bar, which never pinned a placement
  3. the campaign continues; coverage/crash-bucket/corpus state ends
     byte-identical to the uninterrupted run

`run_elastic` is the in-process driver (the soak/test harness and the
scheduler tier use it); `wtf-tpu fleet reshard` is the operator-facing
one-step version: validate a checkpoint, re-place, resume.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Callable, Dict, Optional

log = logging.getLogger(__name__)


class ScheduledReshard:
    """Reshard policy from a fixed plan {batch_index: device_count} —
    the deterministic driver the parity tests and the soak use.  A
    production autoscaler is the same shape: any callable(loop) ->
    Optional[int] consulted at batch boundaries."""

    def __init__(self, plan: Dict[int, int]):
        self.plan = dict(plan)
        self.fired = []

    def __call__(self, loop) -> Optional[int]:
        want = self.plan.pop(loop.batches_done, None)
        if want is not None:
            self.fired.append((loop.batches_done, want))
        return want


def placement_of(loop) -> Optional[int]:
    """The device count a loop currently runs on (None = single)."""
    mesh = getattr(loop.backend, "mesh", None)
    return getattr(mesh, "size", None)


def validate_placement(state: dict, mesh_devices: Optional[int]) -> None:
    """A checkpoint re-places onto `mesh_devices` iff the TOTAL lane
    count divides: lanes are the stream identity (devmut seeds key on
    lane index), lanes-per-chip is the free variable."""
    lanes = state.get("config", {}).get("lanes")
    if mesh_devices and lanes and lanes % mesh_devices:
        raise ValueError(
            f"cannot reshard: checkpoint has {lanes} lanes, not divisible "
            f"by --mesh-devices {mesh_devices} (the lane count is the "
            f"stream identity and must stay fixed; lanes-per-chip is what "
            f"resharding changes)")


def run_elastic(build_loop: Callable, runs: int, checkpoint_dir,
                policy=None, start_devices: Optional[int] = None,
                resume: bool = False, print_stats: bool = False):
    """Drive one campaign across placements until its run budget is
    spent.  `build_loop(mesh_devices)` must return a FRESH FuzzLoop
    (backend initialized, target init, seeds loaded) for that placement;
    everything that matters restores from the checkpoint.  Returns the
    final loop (stats, corpus, coverage all live on it)."""
    from wtf_tpu.resume import load_campaign, restore_campaign

    checkpoint_dir = Path(checkpoint_dir)
    devices = start_devices
    restoring = resume
    loop = None
    while True:
        loop = build_loop(devices)
        loop.checkpoint_dir = checkpoint_dir
        loop.reshard_policy = policy
        if restoring:
            state, _ = load_campaign(checkpoint_dir)
            validate_placement(state, devices)
            batch = restore_campaign(loop, state, checkpoint_dir)
            log.info("resharded onto %s device(s) at batch %d",
                     devices or 1, batch)
        loop.fuzz(runs, print_stats=print_stats)
        if loop.reshard_to is None:
            return loop
        devices = loop.reshard_to
        restoring = True


def describe_checkpoint(directory) -> dict:
    """Operator summary of a checkpoint dir (the `fleet reshard`
    preflight): config, progress, corpus size — raises CheckpointError
    on a torn/unusable pair like any resume would."""
    from wtf_tpu.resume import load_campaign

    state, fell_back = load_campaign(directory)
    return {
        "config": state.get("config", {}),
        "batches": state.get("batches", 0),
        "corpus": len(state.get("corpus_manifest", [])),
        "crash_buckets": len(state.get("crash_buckets", [])),
        "fell_back": fell_back,
    }
