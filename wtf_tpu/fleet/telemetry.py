"""Fleet telemetry aggregation: per-node registry snapshots -> one view.

WTF3 nodes piggyback TAG_TELEM frames on their existing work connection
(dist/wire.py): a sequence-numbered CUMULATIVE Registry.snapshot() plus
a digest of recent events, once per node heartbeat.  This module is the
master side — it merges those per-node snapshots into a single fleet
registry keyed by client identity, with three properties the wire makes
easy to get wrong:

  idempotent   snapshots are cumulative and the aggregator keeps only
               the LATEST (seq, state) per client identity, so a frame
               replayed across a reconnect — or a whole node re-sending
               its running totals after a reclaim — never double-counts
  exact        the merged registry equals the serial sum of the latest
               per-node registries (counters/gauges add per label,
               histograms combine count/sum and extremize min/max) —
               fleet_smoke/obs_smoke assert byte-equality against a
               serial replay
  namespaced   tenant.<name>.* / sched.* metric names pass through
               untouched, so per-tenant rows survive aggregation

Exports: a Prometheus-style text endpoint file (`telemetry.prom`,
atomically replaced), a `fleet-telem.jsonl` stream (one record per
applied snapshot — the fleet-wide analogue of the campaign event log),
and `fleet_registry()` — a real Registry holding the merged state, so
`wtf-tpu status` and tools/telemetry_report.py render it with the same
code that renders a local campaign.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from wtf_tpu.telemetry.metrics import Registry, merge_snapshots


class NodeTelemetry:
    """Latest snapshot state for one client identity."""

    __slots__ = ("client_id", "seq", "snapshot", "last_seen", "epoch",
                 "execs_per_s")

    def __init__(self, client_id: str):
        self.client_id = client_id
        self.seq = -1
        self.snapshot: dict = {}
        self.last_seen = 0.0
        self.epoch = 0
        # instantaneous rate between the last two applied frames — the
        # per-node execs/s column of `wtf-tpu status`
        self.execs_per_s = 0.0

    @staticmethod
    def _execs(snapshot: dict) -> float:
        entry = snapshot.get("campaign.testcases") or {}
        try:
            return float(entry.get("value", 0))
        except (TypeError, ValueError):
            return 0.0

    def apply(self, seq: int, snapshot: dict, now: float) -> bool:
        """Install a frame if it advances this node's sequence.  A
        RECONNECT restarts the client's seq at 0 (per connection epoch);
        the cumulative snapshot makes that safe — whatever the new epoch
        sends supersedes the old totals — so the only frames dropped are
        true duplicates within one epoch (seq <= last seen there)."""
        if seq <= self.seq and seq != 0:
            return False
        if seq == 0 and self.seq >= 0:
            self.epoch += 1  # reconnect: fresh connection epoch
        if self.last_seen and now > self.last_seen:
            delta = self._execs(snapshot) - self._execs(self.snapshot)
            if delta >= 0:
                self.execs_per_s = delta / (now - self.last_seen)
        self.seq = seq
        self.snapshot = snapshot
        self.last_seen = now
        return True


class FleetTelemetry:
    """The master's aggregator.  `apply()` from the reactor on every
    TAG_TELEM frame; `write_exports()` on the same cadence as coverage
    persistence (dirty-flag guarded, atomic replace)."""

    def __init__(self, export_dir=None, clock=time.time,
                 stream_max_bytes: int = 8 * 1024 * 1024):
        self.nodes: Dict[str, NodeTelemetry] = {}
        self._clock = clock
        self._dirty = False
        self.export_dir = Path(export_dir) if export_dir else None
        self.frames = 0
        self.duplicates = 0
        self._stream_fh = None
        self._stream_max = stream_max_bytes

    # -- intake ------------------------------------------------------------

    def apply(self, client_id: bytes, seq: int, snapshot: dict,
              events: Optional[list] = None) -> bool:
        """One decoded TAG_TELEM frame.  Returns True when it advanced
        the fleet state (False = duplicate/stale, dropped)."""
        key = client_id.hex() if isinstance(client_id, (bytes, bytearray)) \
            else str(client_id)
        node = self.nodes.get(key)
        if node is None:
            node = self.nodes[key] = NodeTelemetry(key)
        now = self._clock()
        if not node.apply(seq, snapshot, now):
            self.duplicates += 1
            return False
        self.frames += 1
        self._dirty = True
        self._stream({"ts": now, "node": key, "seq": seq,
                      "epoch": node.epoch,
                      "events": events or [],
                      "snapshot": snapshot})
        return True

    # -- aggregate views ---------------------------------------------------

    def fleet_snapshot(self) -> dict:
        """The merged snapshot: serial sum of every node's latest."""
        return merge_snapshots(n.snapshot for n in self.nodes.values())

    def fleet_registry(self) -> Registry:
        """The merged state as a real Registry (dump()/report-compatible)."""
        registry = Registry()
        registry.restore_snapshot(self.fleet_snapshot())
        return registry

    def per_node(self) -> List[Tuple[str, dict]]:
        """[(client_id_hex, latest snapshot)] sorted by identity."""
        return sorted((k, n.snapshot) for k, n in self.nodes.items())

    def status(self) -> dict:
        """The `wtf-tpu status` document for a fleet master."""
        def _val(snap, name, default=0):
            entry = snap.get(name) or {}
            return entry.get("value", default)

        per_node = []
        for key in sorted(self.nodes):
            node = self.nodes[key]
            per_node.append({
                "node": key,
                "seq": node.seq,
                "epoch": node.epoch,
                "last_seen": node.last_seen,
                "execs_per_s": round(node.execs_per_s, 1),
                "testcases": _val(node.snapshot, "campaign.testcases"),
                "crashes": _val(node.snapshot, "campaign.crashes"),
                "new_coverage": _val(node.snapshot,
                                     "campaign.new_coverage"),
            })
        return {
            "kind": "fleet",
            "ts": self._clock(),
            "nodes": len(self.nodes),
            "frames": self.frames,
            "duplicates_dropped": self.duplicates,
            "node_ids": sorted(self.nodes),
            "per_node": per_node,
            "metrics": self.fleet_registry().dump(),
        }

    # -- exports -----------------------------------------------------------

    def write_exports(self, force: bool = False) -> bool:
        """Refresh `telemetry.prom` + `status.json` under export_dir when
        dirty (atomic replace, same posture as coverage persistence).
        Returns True when files were written."""
        if self.export_dir is None or not (self._dirty or force):
            return False
        from wtf_tpu.utils.atomicio import atomic_write_text

        self.export_dir.mkdir(parents=True, exist_ok=True)
        atomic_write_text(self.export_dir / "telemetry.prom",
                          render_prometheus(self.fleet_snapshot()))
        atomic_write_text(self.export_dir / "status.json",
                          json.dumps(self.status(), default=str))
        self._dirty = False
        return True

    def _stream(self, record: dict) -> None:
        """Append one applied snapshot to fleet-telem.jsonl (best-effort:
        a full disk degrades the stream, never the master)."""
        if self.export_dir is None:
            return
        try:
            if self._stream_fh is None:
                self.export_dir.mkdir(parents=True, exist_ok=True)
                self._stream_fh = open(
                    self.export_dir / "fleet-telem.jsonl", "a",
                    encoding="utf-8")
            self._stream_fh.write(json.dumps(record, default=str) + "\n")
            self._stream_fh.flush()
            if self._stream_fh.tell() >= self._stream_max:
                self._stream_fh.close()
                path = self.export_dir / "fleet-telem.jsonl"
                path.replace(path.with_name(path.name + ".1"))
                self._stream_fh = open(path, "a", encoding="utf-8")
        except OSError:
            self._stream_fh = None

    def close(self) -> None:
        self.write_exports(force=bool(self.nodes))
        if self._stream_fh is not None:
            try:
                self._stream_fh.close()
            except OSError:
                pass
            self._stream_fh = None


def _prom_name(name: str) -> str:
    """Metric name -> Prometheus identifier (dots/dashes -> underscores)."""
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    ident = "".join(out)
    return "wtf_" + ident


def render_prometheus(snapshot: dict) -> str:
    """A Registry.snapshot()-shaped dict as Prometheus text exposition
    (counters -> counter, gauges -> gauge, histograms -> the _count/_sum
    + min/max gauge pair summary form)."""
    lines: List[str] = []
    for name, entry in sorted(snapshot.items()):
        kind = entry.get("kind")
        pname = _prom_name(name)
        if kind == "h":
            lines.append(f"# TYPE {pname} summary")
            lines.append(f"{pname}_count {entry.get('count', 0)}")
            lines.append(f"{pname}_sum {entry.get('sum', 0.0)}")
            for field in ("min", "max"):
                value = entry.get(field)
                if value is not None:
                    lines.append(f"{pname}_{field} {value}")
            continue
        prom_type = "gauge" if kind == "g" else "counter"
        lines.append(f"# TYPE {pname} {prom_type}")
        if "labels" in entry:
            for label, value in sorted(entry["labels"].items()):
                escaped = str(label).replace("\\", "\\\\").replace(
                    '"', '\\"')
                lines.append(f'{pname}{{label="{escaped}"}} {value}')
        else:
            lines.append(f"{pname} {entry.get('value', 0)}")
    return "\n".join(lines) + "\n"
