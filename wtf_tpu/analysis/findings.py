"""Finding: one named lint violation with actionable provenance.

Every rule in the graph-invariant linter (wtf_tpu/analysis/rules.py)
reports violations as Finding records — rule name + entry point +
offending primitive — so a regression shows up in CI as e.g.

    dtype.no-u64 @ step.alu_limb [u64[] add]: 64-bit integer op in ported path

instead of a 2x wall-clock surprise on real hardware five PRs later.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass
from typing import Optional


@dataclass
class Finding:
    rule: str                      # e.g. "dtype.no-u64", "budget.kernel-count"
    entry: str                     # traced entry point (function / executor)
    message: str                   # one-line human explanation
    primitive: Optional[str] = None  # offending HLO op / dtype / opclass
    count: Optional[int] = None      # measured value (budget rules)
    budget: Optional[int] = None     # pinned value  (budget rules)

    def as_dict(self) -> dict:
        return {k: v for k, v in asdict(self).items() if v is not None}

    def __str__(self) -> str:
        extra = f" [{self.primitive}]" if self.primitive else ""
        vs = (f" (measured {self.count} vs budget {self.budget})"
              if self.count is not None and self.budget is not None else "")
        return f"{self.rule} @ {self.entry}{extra}: {self.message}{vs}"
