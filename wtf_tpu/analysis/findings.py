"""Finding: one named lint violation with actionable provenance.

Every rule in the graph-invariant linter (wtf_tpu/analysis/rules.py)
reports violations as Finding records — rule name + entry point +
offending primitive — so a regression shows up in CI as e.g.

    dtype.no-u64 @ step.alu_limb [u64[] add]: 64-bit integer op in ported path

instead of a 2x wall-clock surprise on real hardware five PRs later.
The dataflow families (state/transfer/thread, wtf_tpu/analysis/
contracts.py) additionally carry file:line provenance, which the SARIF
output mode maps to physical locations for review annotation.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass
from typing import List, Optional


@dataclass
class Finding:
    rule: str                      # e.g. "dtype.no-u64", "budget.kernel-count"
    entry: str                     # traced entry point (function / executor)
    message: str                   # one-line human explanation
    primitive: Optional[str] = None  # offending HLO op / dtype / opclass
    count: Optional[int] = None      # measured value (budget rules)
    budget: Optional[int] = None     # pinned value  (budget rules)
    file: Optional[str] = None       # source file (dataflow families)
    line: Optional[int] = None       # 1-based line in `file`

    def as_dict(self) -> dict:
        return {k: v for k, v in asdict(self).items() if v is not None}

    def __str__(self) -> str:
        extra = f" [{self.primitive}]" if self.primitive else ""
        vs = (f" (measured {self.count} vs budget {self.budget})"
              if self.count is not None and self.budget is not None else "")
        loc = f" ({self.file}:{self.line})" if self.file else ""
        return f"{self.rule} @ {self.entry}{extra}: {self.message}{vs}{loc}"


def to_sarif(findings: List[Finding], tool_version: str = "0") -> dict:
    """SARIF 2.1.0 document for review-annotation pipelines — one result
    per finding, physical location attached when the rule carries
    file:line provenance."""
    results = []
    for f in findings:
        result: dict = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": str(f)},
        }
        if f.file:
            result["locations"] = [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.file},
                    "region": {"startLine": int(f.line or 1)},
                },
            }]
        results.append(result)
    rules = sorted({f.rule for f in findings})
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "wtf-tpu-lint",
                "version": tool_version,
                "rules": [{"id": r} for r in rules],
            }},
            "results": results,
        }],
    }
