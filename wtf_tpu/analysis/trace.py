"""Trace capture: build the real entry points and lower/compile them.

Two jobs in one module:

  * the warm-runner + chunk-timing recipe `ablate.py` and `bench.py`
    both used to hand-roll (build a demo_tlv Runner, warm the decode
    cache through the oracle, write the payload into every lane, time a
    cold and a warm chunk dispatch) — extracted here so the benches and
    the linter share one trace-capture path;
  * HLO/StableHLO text capture for the rule engine
    (wtf_tpu/analysis/rules.py): lower a jitted entry point, compile it,
    hand the text to the rules.

Heavy imports (jax, the interpreter stack) stay inside functions so
importing this module never initializes a backend — the benches pick
their platform after import, exactly like before.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

DEFAULT_PAYLOAD = b"\x01\x08AAAAAAAA" * 200


def insert_payload(runner, payload: bytes) -> None:
    """Write `payload` (demo_tlv calling convention: bytes at INPUT_GVA,
    length in rdx) into every lane and push."""
    from wtf_tpu.harness import demo_tlv

    view = runner.view()
    for lane in range(runner.n_lanes):
        view.virt_write(lane, demo_tlv.INPUT_GVA, payload)
        view.r["gpr"][lane, 2] = np.uint64(len(payload))
    runner.push(view)


def build_tlv_runner(n_lanes: int = 1024, chunk_steps: int = 512,
                     payload: Optional[bytes] = DEFAULT_PAYLOAD,
                     snapshot=None, warm: bool = True, limit: int = 0,
                     **runner_kwargs):
    """A demo_tlv Runner ready to dispatch: decode cache warmed through
    the host oracle (no device compile), payload inserted in every lane.
    `payload=None` (the linter's shape-only path) skips both."""
    from wtf_tpu.harness import demo_tlv
    from wtf_tpu.interp.runner import Runner, warm_decode_cache

    if snapshot is None:
        snapshot = demo_tlv.build_snapshot()
    runner = Runner(snapshot, n_lanes=n_lanes, chunk_steps=chunk_steps,
                    **runner_kwargs)
    runner.limit = limit
    if payload is not None:
        if warm:
            warm_decode_cache(runner, demo_tlv.TARGET, payload)
        insert_payload(runner, payload)
    return runner


def timed_chunk(runner, limit: int = 1 << 40) -> dict:
    """Dispatch the runner's chunk executor cold then warm; returns
    {"compile_s", "warm_wall_s", "instr"}.  Leaves runner.machine at the
    post-dispatch state (donation-safe: icount is copied, never viewed)."""
    import jax.numpy as jnp

    tab = runner.cache.device()
    run_chunk = runner.chunk_executor()
    image = runner.physmem.image
    t0 = time.time()
    m = run_chunk(tab, image, runner.machine, jnp.uint64(limit))
    m.status.block_until_ready()
    compile_s = time.time() - t0
    ic0 = np.asarray(m.icount).copy()  # m is donated into the next call
    t0 = time.time()
    m2 = run_chunk(tab, image, m, jnp.uint64(limit))
    m2.status.block_until_ready()
    warm_s = time.time() - t0
    runner.machine = m2
    return {"compile_s": compile_s, "warm_wall_s": warm_s,
            "instr": int((np.asarray(m2.icount) - ic0).sum())}


def build_tenant_runner(quotas=(2, 2), order=("demo_tlv", "demo_kernel"),
                        chunk_steps: int = 16, **runner_kwargs):
    """A heterogeneous two-tenant Runner (wtf_tpu/tenancy) in the
    linter's shape-only configuration: demo_tlv + demo_kernel lanes
    behind ONE stacked image table, no decode warmup, no payload.
    `order` permutes the tenant table — the budget family lowers the
    chunk under both orders and pins the programs byte-identical (tenant
    identity is DATA; the compiled program depends only on shapes)."""
    from wtf_tpu.harness.targets import Targets, load_builtin_targets
    from wtf_tpu.interp.runner import Runner
    from wtf_tpu.tenancy.backend import TenantSpec

    load_builtin_targets()
    targets = Targets.instance()
    specs = []
    for name, lanes in zip(order, quotas):
        target = targets.get(name)
        specs.append(TenantSpec(name=name, target=target,
                                snapshot=target.snapshot(), lanes=lanes))
    runner = Runner(specs[0].snapshot, n_lanes=sum(quotas),
                    chunk_steps=chunk_steps, tenants=specs,
                    **runner_kwargs)
    return runner


def build_tlv_campaign(n_lanes: int = 64, mutator: str = "mangle",
                       limit: int = 100_000, seed: int = 0x77F,
                       max_len: int = 0x400, registry=None,
                       megachunk: int = 0, **backend_kwargs):
    """A demo_tlv FuzzLoop ready to run_one_batch(): tpu backend built
    and initialized, target init, one TLV seed in the corpus, and the
    mutation engine picked by name ("mangle" = best host engine;
    "devmangle" = the device-resident engine, wtf_tpu/devmut) — the
    A/B harness `ablate.py devmut` and the devmut tests share."""
    import random

    from wtf_tpu.backend import create_backend
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.loop import FuzzLoop
    from wtf_tpu.fuzz.mutator import create_mutator
    from wtf_tpu.fuzz.native_mutator import best_mangle_mutator
    from wtf_tpu.harness import demo_tlv
    from wtf_tpu.telemetry import Registry

    registry = registry if registry is not None else Registry()
    backend = create_backend("tpu", demo_tlv.build_snapshot(),
                             n_lanes=n_lanes, limit=limit,
                             registry=registry, **backend_kwargs)
    backend.initialize()
    demo_tlv.TARGET.init(backend)
    rng = random.Random(seed)
    corpus = Corpus(rng=rng)
    corpus.add(b"\x01\x04AAAA\x02\x08BBBBBBBB")
    mut = (best_mangle_mutator(rng, max_len) if mutator == "mangle"
           else create_mutator(mutator, rng, max_len))
    return FuzzLoop(backend, demo_tlv.TARGET, mut, corpus,
                    registry=registry, megachunk=megachunk)


# ---------------------------------------------------------------------------
# HLO / StableHLO capture for the rule engine
# ---------------------------------------------------------------------------

def lower_jit(fn, *args, donate_argnums=()):
    """jax.jit(fn, donate_argnums=...).lower(*args) — the pre-optimization
    StableHLO handle (`.as_text()` is the retrace-stability fingerprint;
    `.compile()` yields the optimized HLO the budget/dtype rules scan)."""
    import jax

    return jax.jit(fn, donate_argnums=donate_argnums).lower(*args)


def compiled_hlo(fn, *args, donate_argnums=()):
    """Optimized (post-XLA-pipeline) HLO text of fn(*args)."""
    return lower_jit(fn, *args,
                     donate_argnums=donate_argnums).compile().as_text()


def step_executor_lowering(runner, n_steps: int = 64, donate: bool = True,
                           perturb: bool = False):
    """Lowered handle of the chunked XLA step ladder on this runner's
    operands.  `perturb=True` re-traces under perturbed-but-same-shape
    inputs (register values bumped, a different limit) — the
    signature-stability probe: both lowerings must produce identical
    StableHLO or something value-dependent leaked into the trace.

    Each call traces FRESH (make_run_chunk(jit=False) + a new jit
    wrapper): jax's trace cache keys on function identity, so lowering
    the memoized executor twice would compare a cache hit against
    itself."""
    import jax
    import jax.numpy as jnp

    from wtf_tpu.interp.step import make_run_chunk

    tab = runner.cache.device()
    machine = runner.machine
    limit = jnp.uint64(0)
    if perturb:
        machine = machine._replace(
            gpr_l=machine.gpr_l + np.uint32(1),
            icount=machine.icount + np.uint64(7))
        limit = jnp.uint64(12345)
    run_chunk = make_run_chunk(n_steps, donate=donate, jit=False)
    jitted = jax.jit(run_chunk, donate_argnums=(2,) if donate else ())
    return jitted.lower(tab, runner.physmem.image, machine, limit)


def tenant_executor_lowering(runner, n_steps: int = 16,
                             donate: bool = False):
    """Lowered handle of the chunked step ladder on a heterogeneous
    runner's operands — `runner.image` (the stacked table + per-lane
    tenant selector), not `runner.physmem.image` (tenant 0's plain
    image).  Fresh trace per call, same reasoning as
    step_executor_lowering."""
    import jax
    import jax.numpy as jnp

    from wtf_tpu.interp.step import make_run_chunk

    run_chunk = make_run_chunk(n_steps, donate=donate, jit=False)
    jitted = jax.jit(run_chunk, donate_argnums=(2,) if donate else ())
    return jitted.lower(runner.cache.device(), runner.image,
                        runner.machine, jnp.uint64(0))


def megachunk_window_lowering(max_batches: int = 2, n_lanes: int = 4,
                              fused: bool = True, donate: bool = True,
                              limit: int = 10_000):
    """Lower (without executing) ONE megachunk window program at the
    canonical budget shapes: a demo_tlv devmangle campaign's window with
    the requested step engine and donation policy.  Returns
    (lowered, args, fn): the jax .lower() handle of the window
    executable, the operand tuple it was lowered against (the donation
    rules index its pytree structure), and the window callable itself
    (the jaxpr census re-traces it).

    Lowering WITH donation is safe on the CPU backend — only EXECUTION
    of a donated program is unsound there (the PR-2 finding) — which is
    why the runtime policy gates on the backend while this helper pins
    the hardware posture statically."""
    import jax.numpy as jnp
    import numpy as np

    from wtf_tpu.fuzz.megachunk import NO_FINISH, make_megachunk

    loop = build_tlv_campaign(n_lanes=n_lanes, mutator="devmangle",
                              limit=limit, megachunk=max_batches,
                              fused_step="on" if fused else "off")
    backend = loop.backend
    runner = backend.runner
    mutator = loop.mutator
    spec = mutator.spec
    n_pages = len(mutator.pfns)
    fn = make_megachunk(max_batches, n_pages, spec.len_gpr,
                        spec.ptr_gpr, mutator.rounds,
                        deliver=runner.deliver_exceptions,
                        devdec=runner.device_decode, fused=fused,
                        fused_k=runner.fused_k,
                        fused_resume_steps=runner.fused_resume_steps,
                        donate=donate)
    finish = spec.finish_gva if spec.finish_gva is not None else NO_FINISH
    slab_first, slab_rest = mutator.window_slabs()
    seeds = mutator.window_seeds(max_batches)
    pfns = jnp.asarray(np.asarray(mutator.pfns, dtype=np.int32))
    gva_l = jnp.asarray(np.array(
        [spec.gva & 0xFFFF_FFFF, (spec.gva >> 32) & 0xFFFF_FFFF],
        dtype=np.uint32))
    args = (runner.device_tab(), runner.image, runner.machine,
            runner.template, slab_first, slab_rest, seeds, pfns, gva_l,
            jnp.uint64(finish), jnp.uint64(backend.limit),
            jnp.int32(max_batches), backend._agg_cov, backend._agg_edge,
            *runner.devdec_operands())
    return fn.lower(*args), args, fn
