"""`python -m wtf_tpu.analysis` -> the graph-invariant linter CLI."""

import sys

from wtf_tpu.analysis import main

sys.exit(main())
