"""Whole-program contract families: state, transfer, thread, contracts.

Three dynamic guarantees of the stack are pinned statically here, on the
shared dataflow engine (wtf_tpu/analysis/flow.py):

  * **state** — bit-identical checkpoint/resume (PR 8/13) requires that
    every mutable attribute on the campaign objects is either carried by
    the checkpoint field sets or consciously declared derived/transient.
    The family enumerates `self.X = ...` writes outside `__init__` per
    class, subtracts what the checkpoint/restore/recovery-snapshot
    extractors touch, and demands a disposition in `contracts.json` for
    the rest (`state.uncheckpointed`).
  * **transfer** — the zero-host steady state (PR 14/19) requires that
    no dispatch seam grows a hidden device→host sync.  AST rule: every
    `.item()` / `float()` / `bool()` / `np.asarray()` /
    `jax.device_get()` call inside a supervise.SEAM_SITES function must
    match an allowlist row (`transfer.hidden-sync`), and the jaxpr-level
    host-callback census of the steady-state programs is pinned in
    budgets.json (`transfer.census-drift`).
  * **thread** — the watchdog/prelaunch/reactor/reconnect paths run on
    real host threads.  Attributes shared across declared thread roots
    (written by one root, written or read by another) must appear in an
    ownership/lock table (`thread.unlocked-shared-write`).
  * **contracts** — the tables themselves are audited: entries naming
    deleted attributes or unmatched allowlist rows are
    `contracts.stale-entry`, entries without a reason are
    `contracts.undocumented` — an allowlist you can't grow silently and
    can't let rot.

`contracts.json` is a RATCHET with budgets.json semantics (PR 12):
`--rebaseline` regenerates the tables but REFUSES to add entries unless
`--allow-regression` is passed, and new entries land with an empty
reason — which the contracts family flags until a human documents them.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from wtf_tpu.analysis import flow
from wtf_tpu.analysis.findings import Finding

CONTRACTS_PATH = Path(__file__).with_name("contracts.json")

# contracts.json section names / valid state dispositions
SECTIONS = ("state", "transfer", "thread")
STATE_KINDS = ("derived", "transient", "config", "rebind")

# ---------------------------------------------------------------------------
# the analyzed surfaces
# ---------------------------------------------------------------------------

_CKPT = "wtf_tpu.resume.checkpoint"
_SUP = "wtf_tpu.supervise.supervisor"

# class site -> the checkpoint/restore/recovery extractors whose
# attribute accesses (through the named parameter) count as coverage.
# checkpoint_state READS what it saves; restore_state WRITES what it
# reinstates; the recovery snapshot (Supervisor.pre_batch/recover) and
# the campaign checkpoint (_campaign_state/restore_campaign) reach into
# the loop/backend from outside — both directions count.
STATE_SURFACE: Dict[str, List[Tuple[str, str, str]]] = {
    "wtf_tpu.interp.runner:Runner": [
        ("wtf_tpu.interp.runner", "Runner.checkpoint_state", "self"),
        ("wtf_tpu.interp.runner", "Runner.restore_state", "self"),
    ],
    "wtf_tpu.meshrun.runner:MeshRunner": [
        ("wtf_tpu.interp.runner", "Runner.checkpoint_state", "self"),
        ("wtf_tpu.interp.runner", "Runner.restore_state", "self"),
    ],
    "wtf_tpu.fuzz.loop:FuzzLoop": [
        (_CKPT, "_campaign_state", "loop"),
        (_CKPT, "restore_campaign", "loop"),
        (_SUP, "Supervisor.pre_batch", "loop"),
        (_SUP, "Supervisor.recover", "loop"),
    ],
    "wtf_tpu.fuzz.mutator:ByteMutator": [
        ("wtf_tpu.fuzz.mutator", "Mutator.checkpoint_state", "self"),
        ("wtf_tpu.fuzz.mutator", "Mutator.restore_state", "self"),
    ],
    "wtf_tpu.fuzz.mutator:MangleMutator": [
        ("wtf_tpu.fuzz.mutator", "Mutator.checkpoint_state", "self"),
        ("wtf_tpu.fuzz.mutator", "Mutator.restore_state", "self"),
    ],
    "wtf_tpu.fuzz.mutator:TlvStructureMutator": [
        ("wtf_tpu.fuzz.mutator", "Mutator.checkpoint_state", "self"),
        ("wtf_tpu.fuzz.mutator", "Mutator.restore_state", "self"),
    ],
    "wtf_tpu.devmut.mutator:DevMangleMutator": [
        ("wtf_tpu.devmut.mutator",
         "DevMangleMutator.checkpoint_state", "self"),
        ("wtf_tpu.devmut.mutator",
         "DevMangleMutator.restore_state", "self"),
    ],
    "wtf_tpu.devmut.corpus:DeviceCorpus": [
        ("wtf_tpu.devmut.corpus", "DeviceCorpus.checkpoint_state", "self"),
        ("wtf_tpu.devmut.corpus", "DeviceCorpus.uploaded_state", "self"),
        ("wtf_tpu.devmut.corpus", "DeviceCorpus.restore", "self"),
    ],
    "wtf_tpu.backend.tpu:TpuBackend": [
        ("wtf_tpu.backend.tpu", "TpuBackend.coverage_state", "self"),
        ("wtf_tpu.backend.tpu",
         "TpuBackend.restore_coverage_state", "self"),
    ],
    "wtf_tpu.meshrun.backend:MeshBackend": [
        ("wtf_tpu.backend.tpu", "TpuBackend.coverage_state", "self"),
        ("wtf_tpu.backend.tpu",
         "TpuBackend.restore_coverage_state", "self"),
        ("wtf_tpu.meshrun.backend",
         "MeshBackend.restore_coverage_state", "self"),
    ],
    f"{_SUP}:Supervisor": [
        (_SUP, "Supervisor.pre_batch", "self"),
        (_SUP, "Supervisor.recover", "self"),
    ],
    # the PR-18 node-telemetry mixin checkpoints NOTHING by design —
    # every mutable attribute needs an explicit disposition
    "wtf_tpu.dist.client:_NodeTelemetry": [],
}

# class site -> thread roots: each root is one real host-thread entry
# point (the function a thread starts in, or the surface another thread
# calls into), closed over self.method() calls but never into another
# root's entry functions.
THREAD_SURFACE: Dict[str, Dict[str, Sequence[str]]] = {
    # dispatcher thread vs the bounded-wait watchdog waiter thread
    f"{_SUP}:Supervisor": {
        "dispatcher": ("dispatch",),
        "watchdog": ("_bounded_wait.waiter",),
    },
    # single-threaded selector reactor vs the drain surface, which the
    # SIGTERM handler or any embedding thread may call
    "wtf_tpu.dist.server:Server": {
        "reactor": ("run",),
        "control": ("request_drain",),
    },
    # a soak worker thread owns its links; the reconnect path re-enters
    # the socket state from inside the serve loop
    "wtf_tpu.dist.client:MasterLink": {
        "serve": ("connect", "recv_work", "send", "send_delta",
                  "send_telem", "close"),
        "reconnect": ("_reconnect",),
    },
    # megachunk window driver vs the pipelined-harvest prelaunch seam
    "wtf_tpu.backend.tpu:TpuBackend": {
        "window": ("run_megachunk",),
        "prelaunch": ("_dispatch_window",),
    },
}

# transfer census subjects: steady-state programs whose jaxpr-level
# host-callback count is pinned in budgets.json under `host_transfer`
TRANSFER_ENTRY = "host_transfer"
TRANSFER_CENSUS_ENTRY = ("jaxpr host-transfer census (callback/infeed/"
                         "outfeed/device_put) over steady-state programs"
                         " / demo_tlv / n_lanes=4")
TRANSFER_PROGRAMS = ("megachunk_window_fused", "devmut_generate",
                     "device_insert", "decode_service")


# ---------------------------------------------------------------------------
# contracts.json I/O + ratchet
# ---------------------------------------------------------------------------

def load_contracts(path: Optional[Path] = None) -> Dict:
    p = Path(path) if path else CONTRACTS_PATH
    if not p.exists():
        return {s: {} for s in SECTIONS}
    doc = json.loads(p.read_text())
    for s in SECTIONS:
        doc.setdefault(s, {})
    return doc


def save_contracts(contracts: Dict, path: Optional[Path] = None) -> Path:
    p = Path(path) if path else CONTRACTS_PATH
    p.write_text(json.dumps(contracts, indent=2, sort_keys=True) + "\n")
    return p


def _entry_keys(contracts: Dict) -> set:
    """Flat (section, owner, entry) key set — the ratchet's unit of
    growth.  Transfer rows key on their call kind."""
    keys = set()
    for cls, attrs in contracts.get("state", {}).items():
        for attr in attrs:
            keys.add(("state", cls, attr))
    for site, rows in contracts.get("transfer", {}).items():
        for row in rows:
            keys.add(("transfer", site, row.get("call")))
    for cls, attrs in contracts.get("thread", {}).items():
        for attr in attrs:
            keys.add(("thread", cls, attr))
    return keys


def apply_contracts_rebaseline(contracts: Dict, needed: Dict,
                               allow_regression: bool = False) -> Dict:
    """Merge regenerated contract tables over the checked-in ones — a
    RATCHET: entries that are no longer needed drop silently (every
    drop is a contract getting stronger), but a NEW entry is allowlist
    growth — a new undispositioned attribute, hidden coercion, or
    shared write — and is refused unless `allow_regression` names the
    act.  Existing reasons/dispositions are carried over; genuinely new
    entries land with whatever skeleton `needed` carries (empty reasons,
    which the contracts family keeps flagging until documented)."""
    grown = sorted(_entry_keys(needed) - _entry_keys(contracts))
    if grown and not allow_regression:
        what = ", ".join(f"{s}:{owner}.{entry}"
                         for s, owner, entry in grown[:6])
        more = f" (+{len(grown) - 6} more)" if len(grown) > 6 else ""
        raise ValueError(
            f"rebaseline would GROW the contract allowlist ({what}{more})"
            " — each new entry is a new undispositioned mutable "
            "attribute, hidden host coercion, or unlocked shared write; "
            "fix the code or document the disposition and re-run with "
            "--allow-regression")
    merged: Dict = {s: {} for s in SECTIONS}
    for cls, attrs in needed.get("state", {}).items():
        old = contracts.get("state", {}).get(cls, {})
        merged["state"][cls] = {
            attr: old.get(attr, skel) for attr, skel in attrs.items()}
    for site, rows in needed.get("transfer", {}).items():
        old_rows = {r.get("call"): r
                    for r in contracts.get("transfer", {}).get(site, [])}
        out = []
        for row in rows:
            kept = dict(old_rows.get(row["call"], row))
            kept["call"] = row["call"]
            kept["count"] = row["count"]
            out.append(kept)
        merged["transfer"][site] = out
    for cls, attrs in needed.get("thread", {}).items():
        old = contracts.get("thread", {}).get(cls, {})
        merged["thread"][cls] = {
            attr: old.get(attr, skel) for attr, skel in attrs.items()}
    return merged


# ---------------------------------------------------------------------------
# tree analysis (pure AST — shared by all four families)
# ---------------------------------------------------------------------------

def _split_site(site: str) -> Tuple[str, str]:
    mod, _, cls = site.partition(":")
    return mod, cls


def analyze_state(surface: Optional[Dict] = None) -> Dict[str, Dict]:
    """Per class: the full write surface, the mutable subset (written
    outside __init__) with first-write provenance, and the covered set
    the extractors reach."""
    surface = STATE_SURFACE if surface is None else surface
    out: Dict[str, Dict] = {}
    for cls_site, extractors in surface.items():
        mod, cls = _split_site(cls_site)
        writes = flow.class_attribute_writes(mod, cls)
        mutable: Dict[str, Tuple[str, int]] = {}
        for attr, sites in writes.items():
            outside = [(m, ln) for m, ln in sites
                       if m not in ("__init__", "__post_init__")]
            if outside:
                mutable[attr] = min(outside, key=lambda s: s[1])
        covered = set()
        for ex_mod, ex_qual, ex_param in extractors:
            info = flow.function_index(ex_mod).get(ex_qual)
            if info is None:
                raise KeyError(
                    f"state extractor {ex_mod}:{ex_qual} not found "
                    f"(STATE_SURFACE for {cls_site})")
            covered |= flow.function_param_accesses(info, ex_param)
        out[cls_site] = {"writes": writes, "mutable": mutable,
                         "covered": covered,
                         "file": flow.module_file(mod)}
    return out


def analyze_transfer(sites: Optional[Dict[str, str]] = None) -> Dict:
    """Per seam site: measured coercion calls {kind: [lineno…]} plus
    file provenance.  Unresolvable sites are skipped — the supervise
    family owns that finding."""
    if sites is None:
        from wtf_tpu.supervise import SEAM_SITES

        sites = SEAM_SITES
    out: Dict[str, Dict] = {}
    for site in sorted(set(sites.values())):
        try:
            info = flow.resolve_site(site)
        except Exception:
            continue
        calls: Dict[str, List[int]] = {}
        for kind, lineno in flow.coercion_calls(info.node):
            calls.setdefault(kind, []).append(lineno)
        out[site] = {"calls": calls, "file": info.file,
                     "lineno": info.lineno}
    return out


def analyze_thread(surface: Optional[Dict] = None) -> Dict[str, Dict]:
    """Per class: per-root access sets plus the shared-attribute set
    (written by one root, written or read by another)."""
    surface = THREAD_SURFACE if surface is None else surface
    out: Dict[str, Dict] = {}
    for cls_site, roots in surface.items():
        mod, cls = _split_site(cls_site)
        accesses = flow.thread_root_accesses(
            mod, cls, {r: list(q) for r, q in roots.items()})
        shared: Dict[str, Dict] = {}
        for root, acc in accesses.items():
            for attr, lines in acc["writes"].items():
                for other, oacc in accesses.items():
                    if other == root:
                        continue
                    if (attr in oacc["writes"]
                            or attr in oacc["reads"]):
                        entry = shared.setdefault(
                            attr, {"writers": {}, "line": min(lines)})
                        entry["writers"][root] = min(lines)
        out[cls_site] = {"accesses": accesses, "shared": shared,
                         "file": flow.module_file(mod)}
    return out


# ---------------------------------------------------------------------------
# the state family
# ---------------------------------------------------------------------------

def check_state_contracts(contracts: Optional[Dict] = None,
                          surface: Optional[Dict] = None,
                          analysis: Optional[Dict] = None
                          ) -> List[Finding]:
    """`state.uncheckpointed`: a mutable attribute with neither
    checkpoint coverage nor a declared disposition."""
    contracts = load_contracts() if contracts is None else contracts
    analysis = analyze_state(surface) if analysis is None else analysis
    table = contracts.get("state", {})
    findings: List[Finding] = []
    for cls_site in sorted(analysis):
        a = analysis[cls_site]
        declared = table.get(cls_site, {})
        for attr in sorted(a["mutable"]):
            if attr in a["covered"]:
                continue
            disp = declared.get(attr)
            if disp and disp.get("kind") in STATE_KINDS:
                continue
            method, lineno = a["mutable"][attr]
            findings.append(Finding(
                rule="state.uncheckpointed", entry=cls_site,
                primitive=attr, file=a["file"], line=lineno,
                message=(f"mutable attribute `{attr}` (written in "
                         f"{method}) is neither carried by the "
                         "checkpoint/restore/recovery field sets nor "
                         "declared derived/transient in contracts.json "
                         "— a resumed campaign would silently diverge; "
                         "checkpoint it or document the disposition")))
    return findings


# ---------------------------------------------------------------------------
# the transfer family
# ---------------------------------------------------------------------------

def check_transfer_seams(contracts: Optional[Dict] = None,
                         sites: Optional[Dict[str, str]] = None,
                         analysis: Optional[Dict] = None
                         ) -> List[Finding]:
    """`transfer.hidden-sync`: a host-coercion call inside a dispatch
    seam beyond what the harvest/readback allowlist declares."""
    contracts = load_contracts() if contracts is None else contracts
    analysis = analyze_transfer(sites) if analysis is None else analysis
    table = contracts.get("transfer", {})
    findings: List[Finding] = []
    for site in sorted(analysis):
        a = analysis[site]
        allowed = {row.get("call"): int(row.get("count", 0))
                   for row in table.get(site, [])}
        for kind in sorted(a["calls"]):
            lines = a["calls"][kind]
            if len(lines) <= allowed.get(kind, 0):
                continue
            over = sorted(lines)[allowed.get(kind, 0):]
            findings.append(Finding(
                rule="transfer.hidden-sync", entry=site, primitive=kind,
                count=len(lines), budget=allowed.get(kind, 0),
                file=a["file"], line=over[0],
                message=(f"{kind} coercion inside a dispatch seam "
                         "beyond the harvest/readback allowlist — a "
                         "hidden device->host sync here re-serializes "
                         "the zero-host steady state; batch the "
                         "readback through the documented harvest or "
                         "allowlist it with a reason")))
    return findings


def count_host_transfers(jaxpr) -> int:
    """Host-callback-class primitives in a jaxpr (sub-jaxprs included,
    pallas_call atomic): pure/io/debug callbacks, infeed/outfeed, and
    explicit device_put — everything that moves data across the
    host/device boundary inside a steady-state program."""
    from wtf_tpu.analysis.rules import _iter_eqns

    jxp = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    n = 0
    for eqn in _iter_eqns(jxp):
        name = eqn.primitive.name
        if ("callback" in name or name in ("infeed", "outfeed")
                or name == "device_put"):
            n += 1
    return n


def measure_transfer_census(runner=None, mega_jaxpr=None) -> Dict[str, int]:
    """The device->host transfer census of the steady-state programs.
    `mega_jaxpr` reuses the budget family's fused-window trace when both
    families run; `runner` reuses its demo_tlv runner."""
    import jax
    import jax.numpy as jnp

    from wtf_tpu.analysis import trace
    from wtf_tpu.analysis.rules import DECODE_BP_SLOTS, MEGA_CONFIG

    counts: Dict[str, int] = {}

    if mega_jaxpr is None:
        cfg = MEGA_CONFIG
        lowered, args, fn = trace.megachunk_window_lowering(
            max_batches=cfg["max_batches"], n_lanes=cfg["n_lanes"],
            fused=True, donate=True, limit=cfg["limit"])
        mega_jaxpr = jax.make_jaxpr(fn)(*args)
    counts["megachunk_window_fused"] = count_host_transfers(mega_jaxpr)

    from wtf_tpu.devmut import engine as DM

    dm_data = jnp.zeros((4, 8), jnp.uint32)
    dm_lens = jnp.ones((4,), jnp.int32)
    dm_cumw = jnp.asarray([1, 2, 3, 4], jnp.uint32)
    dm_seeds = jnp.zeros((2, 2), jnp.uint32)
    gen_jaxpr = jax.make_jaxpr(
        lambda d, ln, c, s: DM.generate(d, ln, c, s, rounds=1))(
        dm_data, dm_lens, dm_cumw, dm_seeds)
    counts["devmut_generate"] = count_host_transfers(gen_jaxpr)

    if runner is None:
        runner = trace.build_tlv_runner(n_lanes=4, chunk_steps=16,
                                        payload=None)

    from wtf_tpu.interp.runner import _make_device_insert

    n_pages, width = 2, 8
    ins = _make_device_insert(n_pages, width, 7, 6, False, masked=False)
    ins_jaxpr = jax.make_jaxpr(ins)(
        runner.machine,
        jnp.zeros((runner.n_lanes, width), jnp.uint32),
        jnp.ones((runner.n_lanes,), jnp.int32),
        jnp.zeros((n_pages,), jnp.int32),
        jnp.zeros((2,), jnp.uint32))
    counts["device_insert"] = count_host_transfers(ins_jaxpr)

    from wtf_tpu.interp import devdec
    from wtf_tpu.mem.physmem import lane_image

    capacity = runner.cache.capacity

    def service(tab, image, machine, count, bp_keys, n_bp):
        blocks = devdec.compute_blocks(tab, image, machine, bp_keys, n_bp)
        return devdec.commit_blocks(tab, count, blocks, machine.status,
                                    capacity)

    dec_jaxpr = jax.make_jaxpr(service)(
        runner.cache.device(),
        lane_image(runner.physmem.image, runner.n_lanes),
        runner.machine, jnp.int32(0),
        jnp.zeros(DECODE_BP_SLOTS, jnp.uint64), jnp.int32(0))
    counts["decode_service"] = count_host_transfers(dec_jaxpr)

    counts["total"] = sum(counts.values())
    return counts


def check_transfer_census(measured: Dict[str, int],
                          budget: Dict,
                          budgets_file: str = "budgets.json"
                          ) -> List[Finding]:
    """`transfer.census-drift`: a steady-state program's host-callback
    count exceeds the pin.  The pin is EXACT downward too via
    --rebaseline + bench_guard; lint only fails on growth."""
    findings: List[Finding] = []
    for prog in list(TRANSFER_PROGRAMS) + ["total"]:
        if prog not in measured:
            continue
        pinned = budget.get(prog)
        if pinned is None or measured[prog] <= int(pinned):
            continue
        findings.append(Finding(
            rule="transfer.census-drift", entry=TRANSFER_ENTRY,
            primitive=prog, count=measured[prog], budget=int(pinned),
            file=budgets_file, line=1,
            message=("host-callback/transfer ops appeared in a "
                     "steady-state program's jaxpr — the zero-host "
                     "loop now syncs per window; remove the callback "
                     "or re-baseline with the regression documented")))
    return findings


# ---------------------------------------------------------------------------
# the thread family
# ---------------------------------------------------------------------------

def check_thread_contracts(contracts: Optional[Dict] = None,
                           surface: Optional[Dict] = None,
                           analysis: Optional[Dict] = None
                           ) -> List[Finding]:
    """`thread.unlocked-shared-write`: an attribute written by one
    thread root and touched by another with no declared owner/lock."""
    contracts = load_contracts() if contracts is None else contracts
    analysis = analyze_thread(surface) if analysis is None else analysis
    table = contracts.get("thread", {})
    findings: List[Finding] = []
    for cls_site in sorted(analysis):
        a = analysis[cls_site]
        declared = table.get(cls_site, {})
        for attr in sorted(a["shared"]):
            entry = declared.get(attr)
            if entry and (entry.get("owner") or entry.get("lock")):
                continue
            writers = a["shared"][attr]["writers"]
            findings.append(Finding(
                rule="thread.unlocked-shared-write", entry=cls_site,
                primitive=attr, file=a["file"],
                line=a["shared"][attr]["line"],
                message=(f"`{attr}` is written from thread root(s) "
                         f"{sorted(writers)} and touched from another "
                         "root with no declared ownership/lock in "
                         "contracts.json — an unlocked cross-thread "
                         "write; serialize it or declare the owner "
                         "and discipline")))
    return findings


# ---------------------------------------------------------------------------
# the contracts family (table hygiene)
# ---------------------------------------------------------------------------

def check_contract_hygiene(contracts: Optional[Dict] = None,
                           state_analysis: Optional[Dict] = None,
                           transfer_analysis: Optional[Dict] = None,
                           thread_analysis: Optional[Dict] = None
                           ) -> List[Finding]:
    """The tables themselves under lint: `contracts.stale-entry` for
    rows naming deleted attributes/calls, `contracts.undocumented` for
    rows without a reason, `contracts.unknown-kind` for dispositions
    outside the vocabulary."""
    contracts = load_contracts() if contracts is None else contracts
    state_analysis = (analyze_state() if state_analysis is None
                      else state_analysis)
    transfer_analysis = (analyze_transfer() if transfer_analysis is None
                         else transfer_analysis)
    thread_analysis = (analyze_thread() if thread_analysis is None
                       else thread_analysis)
    findings: List[Finding] = []

    for cls_site in sorted(contracts.get("state", {})):
        entries = contracts["state"][cls_site]
        known = state_analysis.get(cls_site)
        for attr in sorted(entries):
            disp = entries[attr] or {}
            if known is None or attr not in known["writes"]:
                findings.append(Finding(
                    rule="contracts.stale-entry", entry=cls_site,
                    primitive=attr,
                    message=("contracts.json state entry names an "
                             "attribute no longer assigned on the class"
                             " — delete the row (stale allowlist rows "
                             "hide future regressions under a familiar "
                             "name)")))
                continue
            if disp.get("kind") not in STATE_KINDS:
                findings.append(Finding(
                    rule="contracts.unknown-kind", entry=cls_site,
                    primitive=attr,
                    message=(f"state disposition kind "
                             f"{disp.get('kind')!r} is not one of "
                             f"{list(STATE_KINDS)}")))
            if not str(disp.get("reason") or "").strip():
                findings.append(Finding(
                    rule="contracts.undocumented", entry=cls_site,
                    primitive=attr,
                    message=("state disposition has no reason — every "
                             "allowlist row must say WHY the attribute "
                             "may skip the checkpoint")))

    for site in sorted(contracts.get("transfer", {})):
        rows = contracts["transfer"][site]
        measured = transfer_analysis.get(site, {}).get("calls", {})
        for row in rows:
            kind = row.get("call")
            n = len(measured.get(kind, []))
            if site not in transfer_analysis or n == 0:
                findings.append(Finding(
                    rule="contracts.stale-entry", entry=site,
                    primitive=kind,
                    message=("transfer allowlist row matches no call in "
                             "the seam anymore — delete it")))
            elif n < int(row.get("count", 0)):
                findings.append(Finding(
                    rule="contracts.stale-entry", entry=site,
                    primitive=kind, count=n,
                    budget=int(row.get("count", 0)),
                    message=("transfer allowlist row allows more "
                             f"{kind} calls than the seam contains — "
                             "tighten the count (the ratchet only "
                             "tightens itself on --rebaseline)")))
            if not str(row.get("reason") or "").strip():
                findings.append(Finding(
                    rule="contracts.undocumented", entry=site,
                    primitive=kind,
                    message=("transfer allowlist row has no reason — "
                             "every allowed coercion must name its "
                             "harvest/readback purpose")))

    for cls_site in sorted(contracts.get("thread", {})):
        entries = contracts["thread"][cls_site]
        known = thread_analysis.get(cls_site)
        for attr in sorted(entries):
            row = entries[attr] or {}
            touched = known is not None and any(
                attr in acc["writes"] or attr in acc["reads"]
                for acc in known["accesses"].values())
            if not touched:
                findings.append(Finding(
                    rule="contracts.stale-entry", entry=cls_site,
                    primitive=attr,
                    message=("thread ownership row names an attribute "
                             "no thread root touches anymore — delete "
                             "it")))
                continue
            roots = set(known["accesses"]) | {"any"}
            if row.get("owner") not in roots:
                findings.append(Finding(
                    rule="contracts.unknown-kind", entry=cls_site,
                    primitive=attr,
                    message=(f"thread owner {row.get('owner')!r} is not "
                             f"a declared root of the class "
                             f"({sorted(set(known['accesses']))}) or "
                             "'any'")))
            if not str(row.get("reason") or "").strip():
                findings.append(Finding(
                    rule="contracts.undocumented", entry=cls_site,
                    primitive=attr,
                    message=("thread ownership row has no reason — "
                             "declare the lock/discipline that makes "
                             "the sharing safe")))
    return findings


# ---------------------------------------------------------------------------
# rebaseline skeleton generation
# ---------------------------------------------------------------------------

def needed_contracts(state_analysis: Optional[Dict] = None,
                     transfer_analysis: Optional[Dict] = None,
                     thread_analysis: Optional[Dict] = None) -> Dict:
    """The minimal tables the current tree requires — what
    `--rebaseline` merges (under the growth ratchet) over the
    checked-in file.  New entries carry empty reasons on purpose."""
    state_analysis = (analyze_state() if state_analysis is None
                      else state_analysis)
    transfer_analysis = (analyze_transfer() if transfer_analysis is None
                         else transfer_analysis)
    thread_analysis = (analyze_thread() if thread_analysis is None
                       else thread_analysis)
    needed: Dict = {"state": {}, "transfer": {}, "thread": {}}
    for cls_site, a in state_analysis.items():
        attrs = {attr: {"kind": "transient", "reason": ""}
                 for attr in sorted(a["mutable"])
                 if attr not in a["covered"]}
        if attrs:
            needed["state"][cls_site] = attrs
    for site, a in transfer_analysis.items():
        rows = [{"call": kind, "count": len(lines), "reason": ""}
                for kind, lines in sorted(a["calls"].items())]
        if rows:
            needed["transfer"][site] = rows
    for cls_site, a in thread_analysis.items():
        attrs = {attr: {"owner": "", "reason": ""}
                 for attr in sorted(a["shared"])}
        if attrs:
            needed["thread"][cls_site] = attrs
    return needed
