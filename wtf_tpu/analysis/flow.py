"""Shared static-analysis engine: module/AST walking and dataflow.

Every source-inspection lint family rides this one engine instead of
bespoke importlib+regex paths:

  * site resolution — `"module:Class.method"` strings (the
    supervise.SEAM_SITES idiom) resolve to a `FunctionInfo` carrying the
    AST node, file and line, so findings get provenance for free;
  * per-function dataflow — attribute-assignment/read extraction over a
    base name (`self`, or a named parameter like `loop`), the raw
    material of the state and thread families;
  * transitive name resolution — the PR-5 parity resolver's
    worklist-over-local-bindings algorithm, generalized so parity.py and
    any future value-set rule share one implementation;
  * call classification — supervisor.dispatch routing, telemetry
    serialization, and host-coercion (`.item()` / `float()` / `bool()` /
    `np.asarray` / `jax.device_get`) call sites with line numbers;
  * thread-entry discovery — `threading.Thread(target=...)` call sites
    resolved to the qualname of the function the thread will run.

Pure AST: nothing here imports the analyzed modules beyond locating
their source (importlib for the file path only), so the engine runs in
milliseconds and never trips device initialization.
"""

from __future__ import annotations

import ast
import importlib
import inspect
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

# ---------------------------------------------------------------------------
# module walking
# ---------------------------------------------------------------------------

_SRC_CACHE: Dict[str, str] = {}
_AST_CACHE: Dict[str, ast.Module] = {}
_FILE_CACHE: Dict[str, str] = {}


def module_source(modname: str) -> str:
    """Source text of an importable module (cached)."""
    if modname not in _SRC_CACHE:
        mod = importlib.import_module(modname)
        _SRC_CACHE[modname] = inspect.getsource(mod)
        _FILE_CACHE[modname] = inspect.getsourcefile(mod) or modname
    return _SRC_CACHE[modname]


def module_file(modname: str) -> str:
    module_source(modname)
    return _FILE_CACHE[modname]


def module_ast(modname: str) -> ast.Module:
    if modname not in _AST_CACHE:
        _AST_CACHE[modname] = ast.parse(module_source(modname))
    return _AST_CACHE[modname]


@dataclass
class FunctionInfo:
    """A resolved function/method: AST node plus file:line provenance."""

    module: str
    qualname: str  # "Class.method", "func", "Class.method.inner"
    file: str
    lineno: int
    node: ast.AST  # FunctionDef / AsyncFunctionDef


def function_index(modname: str) -> Dict[str, FunctionInfo]:
    """Every function/method in a module keyed by dotted qualname,
    including nested defs ("Class.method.inner")."""
    index: Dict[str, FunctionInfo] = {}
    fname = module_file(modname)

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}" if prefix else child.name
                index[qual] = FunctionInfo(
                    module=modname, qualname=qual, file=fname,
                    lineno=child.lineno, node=child)
                visit(child, qual + ".")
            elif isinstance(child, ast.ClassDef):
                qual = f"{prefix}{child.name}" if prefix else child.name
                visit(child, qual + ".")

    visit(module_ast(modname), "")
    return index


def resolve_site(site: str) -> FunctionInfo:
    """Resolve a `"module:Qual.name"` site string to a FunctionInfo.
    Raises (ImportError / KeyError / OSError) when unresolvable — the
    caller decides whether that is itself a finding (supervise family)
    or someone else's (telemetry family)."""
    mod_name, _, qual = site.partition(":")
    index = function_index(mod_name)  # raises on bad module
    if qual in index:
        return index[qual]
    # runtime fallback: re-exported or dynamically attached callables
    obj = importlib.import_module(mod_name)
    for part in qual.split("."):
        obj = getattr(obj, part)  # raises AttributeError: the finding
    src = inspect.getsource(obj)
    node = ast.parse(inspect.cleandoc("\n" + src) if src[0] in " \t"
                     else src).body[0]
    _, lineno = inspect.getsourcelines(obj)
    return FunctionInfo(module=mod_name, qualname=qual,
                        file=inspect.getsourcefile(obj) or mod_name,
                        lineno=lineno, node=node)


def class_functions(modname: str, classname: str) -> Dict[str, FunctionInfo]:
    """The methods (and their nested defs) of one class, keyed by the
    qualname RELATIVE to the class ("run", "_bounded_wait.waiter")."""
    prefix = classname + "."
    out: Dict[str, FunctionInfo] = {}
    for qual, info in function_index(modname).items():
        if qual.startswith(prefix):
            out[qual[len(prefix):]] = info
    if not out:
        raise KeyError(f"no class {classname!r} in module {modname!r}")
    return out


# ---------------------------------------------------------------------------
# per-function dataflow: attribute writes/reads over a base name
# ---------------------------------------------------------------------------

def _target_attrs(target: ast.AST, base: str) -> List[Tuple[str, int]]:
    """(attr, lineno) pairs assigned under one assignment target."""
    if isinstance(target, ast.Attribute) and \
            isinstance(target.value, ast.Name) and target.value.id == base:
        return [(target.attr, target.lineno)]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[Tuple[str, int]] = []
        for elt in target.elts:
            out.extend(_target_attrs(elt, base))
        return out
    if isinstance(target, ast.Starred):
        return _target_attrs(target.value, base)
    return []


def _walk_scope(node: ast.AST, include_nested: bool):
    """Child statements of a function body; descends into nested defs
    only when asked (the thread family keeps closures separate)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and not include_nested:
            continue
        yield child
        yield from _walk_scope(child, include_nested)


def attribute_writes(node: ast.AST, base: str = "self",
                     include_nested: bool = True) -> List[Tuple[str, int]]:
    """Every `<base>.attr = ...` (Assign/AugAssign/AnnAssign, tuple
    targets included) in a function body, as (attr, lineno)."""
    writes: List[Tuple[str, int]] = []
    for sub in _walk_scope(node, include_nested):
        if isinstance(sub, ast.Assign):
            for t in sub.targets:
                writes.extend(_target_attrs(t, base))
        elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
            writes.extend(_target_attrs(sub.target, base))
        elif isinstance(sub, (ast.For, ast.AsyncFor)):
            writes.extend(_target_attrs(sub.target, base))
        elif isinstance(sub, (ast.With, ast.AsyncWith)):
            for item in sub.items:
                if item.optional_vars is not None:
                    writes.extend(_target_attrs(item.optional_vars, base))
    return writes


def attribute_reads(node: ast.AST, base: str = "self",
                    include_nested: bool = True) -> List[Tuple[str, int]]:
    """Every `<base>.attr` load in a function body, as (attr, lineno)."""
    reads: List[Tuple[str, int]] = []
    for sub in _walk_scope(node, include_nested):
        if (isinstance(sub, ast.Attribute)
                and isinstance(sub.ctx, ast.Load)
                and isinstance(sub.value, ast.Name)
                and sub.value.id == base):
            reads.append((sub.attr, sub.lineno))
    return reads


def class_attribute_writes(modname: str, classname: str
                           ) -> Dict[str, List[Tuple[str, int]]]:
    """attr -> [(method_qualname, lineno), ...] over every method of a
    class — the raw mutable-attribute surface of the state family."""
    surface: Dict[str, List[Tuple[str, int]]] = {}
    for qual, info in class_functions(modname, classname).items():
        if "." in qual:
            continue  # nested defs are walked within their method
        self_name = _self_param(info.node)
        if self_name is None:
            continue  # staticmethod: no instance surface
        for attr, lineno in attribute_writes(info.node, self_name):
            surface.setdefault(attr, []).append((qual, lineno))
    return surface


def _self_param(node: ast.AST) -> Optional[str]:
    args = getattr(node, "args", None)
    if args is None or not args.args:
        return None
    return args.args[0].arg


def function_param_accesses(info: FunctionInfo, param: str
                            ) -> Set[str]:
    """Attributes of `param` a function reads OR writes — the coverage
    extractor of the state family (what `checkpoint_state(self)` reads
    is checkpointed; what `restore_state(self)` writes is restored)."""
    accessed = {a for a, _ in attribute_writes(info.node, param)}
    accessed |= {a for a, _ in attribute_reads(info.node, param)}
    return accessed


# ---------------------------------------------------------------------------
# transitive name resolution (the PR-5 parity resolver, generalized)
# ---------------------------------------------------------------------------

def name_bindings(tree: ast.AST) -> Dict[str, List[ast.AST]]:
    """Name -> [RHS value nodes] over every Assign/AugAssign in a tree
    (the house style routes predicate sets through locals and builds
    with `|=`; a literal-only walk of one RHS would be blind to both)."""
    defs: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    defs.setdefault(t.id, []).append(node.value)
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name):
                defs.setdefault(node.target.id, []).append(node.value)
    return defs


def resolve_transitive(src: str, target: str,
                       extract: Callable[[ast.AST], Set[str]]) -> Set[str]:
    """Values `extract` finds under every assignment reachable from
    `target`, resolving intermediate Name bindings transitively.
    Raises ValueError when `target` is never assigned."""
    defs = name_bindings(ast.parse(src))
    if target not in defs:
        raise ValueError(f"no `{target} = ...` assignment found in source")
    names: Set[str] = set()
    seen = {target}
    work = [target]
    while work:
        for rhs in defs[work.pop()]:
            names |= extract(rhs)
            for sub in ast.walk(rhs):
                if (isinstance(sub, ast.Name) and sub.id in defs
                        and sub.id not in seen):
                    seen.add(sub.id)
                    work.append(sub.id)
    return names


# ---------------------------------------------------------------------------
# call classification
# ---------------------------------------------------------------------------

def dispatch_seams(node: ast.AST) -> Set[str]:
    """String literals dispatched through `*.dispatch("<seam>", ...)` —
    the supervise routing contract, AST-level (no regex false hits on
    comments or docstrings)."""
    seams: Set[str] = set()
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "dispatch"
                and _attr_tail_is(sub.func.value, "supervisor")
                and sub.args
                and isinstance(sub.args[0], ast.Constant)
                and isinstance(sub.args[0].value, str)):
            seams.add(sub.args[0].value)
    return seams


def _attr_tail_is(node: ast.AST, name: str) -> bool:
    """True for `supervisor`, `self.supervisor`, `runner.supervisor`…"""
    if isinstance(node, ast.Name):
        return node.id == name
    if isinstance(node, ast.Attribute):
        return node.attr == name
    return False


# serialization surface: building a wire/export payload from the metric
# registry.  The pattern strings mirror the retired regex exactly —
# tests pin them in Finding.primitive.
def serialization_calls(node: ast.AST) -> List[Tuple[str, int]]:
    hits: List[Tuple[str, int]] = []
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        f = sub.func
        if isinstance(f, ast.Attribute):
            if f.attr == "snapshot":
                hits.append((".snapshot(", sub.lineno))
            elif f.attr in ("encode_telem", "render_prometheus"):
                hits.append((f"{f.attr}(", sub.lineno))
            elif (f.attr == "dumps" and isinstance(f.value, ast.Name)
                    and f.value.id == "json"):
                hits.append(("json.dumps(", sub.lineno))
        elif isinstance(f, ast.Name) and \
                f.id in ("encode_telem", "render_prometheus"):
            hits.append((f"{f.id}(", sub.lineno))
    return hits


# host-coercion calls: the device->host sync surface the transfer
# family audits inside dispatch seams.  Kind strings appear verbatim in
# contracts.json allowlist rows and in Finding.primitive.
def coercion_calls(node: ast.AST) -> List[Tuple[str, int]]:
    hits: List[Tuple[str, int]] = []
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        f = sub.func
        if isinstance(f, ast.Attribute):
            if f.attr == "item" and not sub.args:
                hits.append((".item()", sub.lineno))
            elif (f.attr == "asarray"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "np"):
                hits.append(("np.asarray()", sub.lineno))
            elif (f.attr == "device_get"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "jax"):
                hits.append(("jax.device_get()", sub.lineno))
        elif isinstance(f, ast.Name) and f.id in ("float", "bool"):
            if sub.args and not isinstance(sub.args[0], ast.Constant):
                hits.append((f"{f.id}()", sub.lineno))
    return hits


# ---------------------------------------------------------------------------
# thread-entry discovery + per-root access closure
# ---------------------------------------------------------------------------

def thread_targets(modname: str) -> List[Tuple[str, int]]:
    """(qualname, lineno) of every function handed to
    `threading.Thread(target=...)` in a module — the real host-thread
    entry points the thread family audits."""
    out: List[Tuple[str, int]] = []
    index = function_index(modname)
    for qual, info in index.items():
        for sub in ast.walk(info.node):
            if not (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "Thread"
                    and isinstance(sub.func.value, ast.Name)
                    and sub.func.value.id == "threading"):
                continue
            for kw in sub.keywords:
                if kw.arg != "target":
                    continue
                if isinstance(kw.value, ast.Name):
                    # nearest enclosing scope first: a nested def named
                    # X inside this function wins over a module-level X
                    nested = f"{qual}.{kw.value.id}"
                    target = nested if nested in index else kw.value.id
                    out.append((target, sub.lineno))
                elif isinstance(kw.value, ast.Attribute):
                    out.append((kw.value.attr, sub.lineno))
    return sorted(set(out))


def _called_methods(node: ast.AST, self_name: str) -> Set[str]:
    calls: Set[str] = set()
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id == self_name):
            calls.add(sub.func.attr)
    return calls


def thread_root_accesses(modname: str, classname: str,
                         roots: Dict[str, Sequence[str]]
                         ) -> Dict[str, Dict[str, Dict[str, List[int]]]]:
    """Per-root attribute access sets for a class.

    `roots` maps a root name (one thread entry point: "reactor",
    "watchdog", "control"…) to the class-relative qualnames it starts
    from.  Each root's closure expands through `self.method()` calls —
    but never INTO another root's entry functions (the watchdog closure
    nested inside `_bounded_wait` stays the watchdog's even though the
    dispatcher defines it).

    Returns {root: {"writes": {attr: [lineno…]}, "reads": {…}}}.
    """
    funcs = class_functions(modname, classname)
    out: Dict[str, Dict[str, Dict[str, List[int]]]] = {}
    all_entries = {q for quals in roots.values() for q in quals}
    for root, entries in roots.items():
        other = {q for q in all_entries if q not in set(entries)}
        closure: Set[str] = set()
        work = [q for q in entries if q in funcs]
        missing = [q for q in entries if q not in funcs]
        if missing:
            raise KeyError(
                f"thread root {root!r} of {modname}:{classname} names "
                f"unknown functions {missing!r}")
        writes: Dict[str, List[int]] = {}
        reads: Dict[str, List[int]] = {}
        while work:
            qual = work.pop()
            if qual in closure:
                continue
            closure.add(qual)
            info = funcs[qual]
            # the method owning a nested entry ("m" for "m.inner")
            # resolves self through ITS first parameter
            owner = qual.split(".")[0]
            self_name = _self_param(funcs[owner].node) or "self"
            for attr, ln in attribute_writes(info.node, self_name,
                                             include_nested=False):
                writes.setdefault(attr, []).append(ln)
            for attr, ln in attribute_reads(info.node, self_name,
                                            include_nested=False):
                reads.setdefault(attr, []).append(ln)
            for callee in _called_methods(info.node, self_name):
                if callee in funcs and callee not in other:
                    work.append(callee)
            # nested defs run on this root's thread unless they are
            # another root's entry point
            for sub_qual in funcs:
                if (sub_qual.startswith(qual + ".")
                        and sub_qual not in other):
                    work.append(sub_qual)
        out[root] = {"writes": writes, "reads": reads}
    return out
