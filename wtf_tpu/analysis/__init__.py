"""Graph-invariant linter: static analysis of the hot-path contracts.

The snapshot→execute→restore loop's fast path is defined by graph-shape
invariants (zero u64 in the ported integer core, a pinned count of
data-dependent gather kernels per step, no recompile hazards, donation
aliasing, fused-subset parity between pstep and step).  This package
traces the real entry points into jaxpr/StableHLO/optimized HLO on the
CPU backend — statically, no chip — and walks them with a rule engine,
so a regression shows up as a named lint failure with provenance.

Entry points:
    python -m wtf_tpu.analysis [--json] [--families ...] [--rebaseline]
    python -m wtf_tpu lint ...          (same flags, telemetry-wired)
    wtf-tpu lint ...                    (installed console script)

Rule families (wtf_tpu/analysis/rules.py): dtype, budget, recompile,
parity, mesh, supervise, telemetry, plus the dataflow contract families
(wtf_tpu/analysis/contracts.py on the shared engine in flow.py): state,
transfer, thread, contracts.  Kernel/collective/transfer budgets live in
wtf_tpu/analysis/budgets.json and the state/transfer/thread allowlists
in wtf_tpu/analysis/contracts.json; re-baseline with `--rebaseline` when
a PR legitimately changes them (PERF.md rounds 9 and 21 document the
procedure — both files are ratchets: growth needs --allow-regression).
`--deep` adds the jaxpr host-transfer census to a transfer-family run
that skips the budget family; `--sarif OUT.json` additionally writes
the findings as SARIF 2.1.0 for review-annotation pipelines.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional

from wtf_tpu.analysis.findings import Finding  # noqa: F401
from wtf_tpu.analysis.parity import check_fused_parity  # noqa: F401
from wtf_tpu.analysis.rules import (  # noqa: F401
    FAMILIES, apply_rebaseline, check_budget, check_mesh_collectives,
    check_no_u64,
    check_seam_bitcast_only, check_seam_enumeration, check_shard_stability,
    check_signature_stable,
    check_strong_inputs, check_supervised_seams, count_collective_ops,
    count_data_dependent_ops,
    run_dtype_family, run_lint, run_mesh_family,
)


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="wtf_tpu.analysis",
        description="graph-invariant linter (hot-path contracts, CPU-only)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (one JSON object)")
    p.add_argument("--families", default=None,
                   help=f"comma list from {','.join(FAMILIES)} "
                        "(default: all)")
    p.add_argument("--budgets", default=None,
                   help="alternate budgets.json (default: the checked-in "
                        "wtf_tpu/analysis/budgets.json)")
    p.add_argument("--rebaseline", action="store_true",
                   help="measure the kernel-count budget and REWRITE the "
                        "budget file instead of checking it (record why "
                        "in PERF.md).  Ratcheted: refuses to record a "
                        "total INCREASE without --allow-regression")
    p.add_argument("--allow-regression", action="store_true",
                   help="let --rebaseline record a kernel/collective "
                        "budget increase (a conscious perf giveback — "
                        "name the reason in PERF.md)")
    p.add_argument("--telemetry-dir", default=None,
                   help="write lint findings as events.jsonl records")
    p.add_argument("--deep", action="store_true",
                   help="run the transfer family's jaxpr host-transfer "
                        "census even without the budget family (whose "
                        "fused-window trace it would otherwise reuse)")
    p.add_argument("--sarif", default=None, metavar="OUT.json",
                   help="also write the findings as a SARIF 2.1.0 "
                        "document (file:line provenance mapped to "
                        "physical locations)")
    return p


def lint_main(families=None, budgets=None, rebaseline: bool = False,
              allow_regression: bool = False,
              as_json: bool = False, deep: bool = False,
              sarif: Optional[str] = None,
              registry=None, events=None,
              out=None) -> int:
    """Run the lint and print results; returns the process exit code
    (0 clean, 1 findings).  Shared by `python -m wtf_tpu.analysis` and
    the `wtf-tpu lint` subcommand (which supplies telemetry wiring)."""
    out = out or sys.stdout
    # The lint's contracts are CPU-platform facts (HLO counts, donation
    # policy), so the default pins the CPU backend.  WTF_LINT_PLATFORM
    # overrides: "native" leaves the ambient jax platform untouched, any
    # other value is passed to jax verbatim (e.g. "tpu").
    import os

    platform = os.environ.get("WTF_LINT_PLATFORM", "cpu")
    if platform != "native":
        import jax

        try:
            jax.config.update("jax_platforms", platform)
        except Exception:  # noqa: BLE001 - backend already initialized
            pass
    t0 = time.time()
    try:
        findings, info = run_lint(families=families, budgets_path=budgets,
                                  rebaseline=rebaseline,
                                  allow_regression=allow_regression,
                                  deep=deep,
                                  registry=registry, events=events)
    except ValueError as e:
        # operator-facing refusals (the rebaseline ratchet, bad family
        # lists) print as clean one-liners, not tracebacks
        print(f"wtf-tpu lint: {e}", file=out)
        return 1
    wall = round(time.time() - t0, 1)
    if sarif:
        from pathlib import Path

        from wtf_tpu.analysis.findings import to_sarif

        Path(sarif).write_text(
            json.dumps(to_sarif(findings), indent=2) + "\n")
    if as_json:
        print(json.dumps({
            "clean": not findings, "wall_seconds": wall,
            "findings": [f.as_dict() for f in findings], **info,
        }), file=out)
    else:
        for f in findings:
            print(f"FAIL {f}", file=out)
        counts = info.get("kernel_counts")
        if counts:
            print("kernel counts: " + " ".join(
                f"{k}={v}" for k, v in counts.items()), file=out)
        collectives = info.get("collective_counts")
        if collectives:
            print("mesh collectives: " + " ".join(
                f"{k}={v}" for k, v in collectives.items()), file=out)
        census = info.get("transfer_census")
        if census:
            print("transfer census: " + " ".join(
                f"{k}={v}" for k, v in census.items()), file=out)
        if "budgets_written" in info:
            print(f"re-baselined -> {info['budgets_written']}", file=out)
        if "contracts_written" in info:
            print(f"re-baselined -> {info['contracts_written']}",
                  file=out)
        state = ("CLEAN" if not findings
                 else f"{len(findings)} finding(s)")
        print(f"wtf-tpu lint: {state} "
              f"({','.join(info['families'])}; {wall}s)", file=out)
    return 0 if not findings else 1


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    families = args.families.split(",") if args.families else None
    from wtf_tpu.telemetry import Registry, open_event_log

    registry = Registry()
    events = open_event_log(args.telemetry_dir)
    events.emit("run-start", subcommand="lint",
                argv=list(argv) if argv is not None else sys.argv[1:])
    try:
        return lint_main(families=families, budgets=args.budgets,
                         rebaseline=args.rebaseline,
                         allow_regression=args.allow_regression,
                         as_json=args.json, deep=args.deep,
                         sarif=args.sarif,
                         registry=registry, events=events)
    finally:
        events.emit("run-end", metrics=registry.dump())
        events.close()
