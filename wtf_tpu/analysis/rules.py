"""The graph-invariant rule engine: four families of static checks.

Each rule traces a *real* entry point (the chunked XLA step ladder, the
ported u32-limb hot paths, the pack/unpack seams, overlay restore) into
jaxpr/StableHLO/optimized-HLO on the CPU backend — no chip — and walks
the result:

  dtype     zero u64/s64/f64/f32 primitives in the ported integer-core
            paths (the PR-2 contract, formerly ad-hoc string greps in
            tests/test_limbs.py); the pack/unpack seam may hold 64-bit
            values but only through free bitcasts; every path step.py
            exports as ported must have an argument recipe here or the
            lint fails (a newly ported path cannot dodge the pin)
  budget    data-dependent gather/dynamic-slice/dynamic-update-slice/
            scatter ops surviving in the compiled step ladder, pinned
            against analysis/budgets.json (the PERF.md round-8 "168
            surviving kernels" math as a regression gate); plus the
            triage-chunk identity pin — wtf_tpu/triage's replay core
            must dispatch this same ladder (zero new kernels) — and the
            tenancy pins (wtf_tpu/tenancy): the heterogeneous chunk's
            kernel census against the `tenant_chunk` budget entry, and
            program byte-stability across tenant permutations ("one
            compiled program per lane count regardless of tenant mix")
  recompile re-trace the executor under perturbed-but-same-shape inputs
            and flag signature instability; weak-typed executor operands
            (a python scalar passed where a committed dtype belongs —
            the jit-cache-split hazard); donation verification (every
            donated machine leaf actually aliased in the compiled
            output, and the Runner's CPU-donation gate intact — the
            PR-2 corruption class caught statically)
  parity    the fused-subset contract between pstep.py and step.py
            (wtf_tpu/analysis/parity.py)
  mesh      the sharded chunk executor (wtf_tpu/meshrun) on a forced
            multi-device CPU mesh: cross-device collectives pinned to
            exactly the coverage all-reduce (no accidental resharding
            of machine state — zero all-gather/all-to-all/permute), and
            the compiled per-device program byte-stable across shard
            counts at equal lanes-per-shard.  When the ambient process
            has too few devices (plain `make lint`), the family re-runs
            itself in a subprocess with
            XLA_FLAGS=--xla_force_host_platform_device_count=8.
  supervise every device dispatch entry point routes through the
            Supervisor (wtf_tpu/supervise) — seam routing + enumeration
            completeness, by source inspection over SEAM_SITES
  telemetry no dispatch seam serializes the metric registry inline
            (snapshot / encode_telem / json.dumps in a per-chunk path) —
            the <1% observability-overhead bar holds because
            serialization rides the heartbeat/TAG_TELEM cadence; same
            SEAM_SITES enumeration as the supervise family
  state     every mutable attribute on the campaign objects is either
            carried by the checkpoint/restore/recovery field sets or
            declared derived/transient in analysis/contracts.json
            (wtf_tpu/analysis/contracts.py, on the shared dataflow
            engine in wtf_tpu/analysis/flow.py)
  transfer  no dispatch seam grows a hidden device->host sync: AST
            coercion census over SEAM_SITES against the contracts.json
            allowlist, plus (with --deep, or for free when the budget
            family runs) the jaxpr host-callback census of the
            steady-state programs pinned under budgets.json's
            `host_transfer` entry
  thread    attributes shared across declared host-thread roots
            (watchdog, prelaunch, reactor, reconnect…) must appear in
            the contracts.json ownership/lock table
  contracts the contract tables themselves: stale rows, undocumented
            reasons, unknown dispositions — the allowlist cannot rot

`run_lint` orchestrates all families and reports Findings; helpers are
public so tests can seed violations directly.
"""

from __future__ import annotations

import json
import re
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from wtf_tpu.analysis.findings import Finding
from wtf_tpu.analysis.parity import check_fused_parity
from wtf_tpu.analysis.trace import (
    build_tenant_runner, build_tlv_runner, compiled_hlo,
    step_executor_lowering, tenant_executor_lowering,
)

BUDGETS_PATH = Path(__file__).with_name("budgets.json")

# the data-dependent-index HLO ops TPU XLA cannot fuse across — the unit
# of the PERF.md performance model ("step wall is proportional to the
# number of gather-class kernels, not FLOPs")
DATA_DEP_OPS = ("gather", "dynamic-slice", "dynamic-update-slice", "scatter")

# canonical budget-trace configuration: op counts are static code sites
# (independent of n_lanes / n_steps — the chunk is a while_loop, not an
# unroll), but the pin is only meaningful against one fixed entry shape
BUDGET_ENTRY = "xla_step"
BUDGET_CONFIG = dict(n_lanes=4, chunk_steps=64, n_steps=64, donate=True)

# canonical heterogeneous-batch configuration (wtf_tpu/tenancy): the
# budget family lowers the SAME step ladder over a two-tenant stacked
# image table, counts its gather-class kernels against the
# `tenant_chunk` budget entry, and pins the compiled program
# byte-identical under a permuted tenant table — "one program per lane
# count regardless of tenant mix"
TENANT_ENTRY = "tenant_chunk"
TENANT_CONFIG = dict(n_steps=16, quotas=(2, 2),
                     order=("demo_tlv", "demo_kernel"),
                     uop_capacity=1 << 10, overlay_slots=8, edge_bits=12)

# the cross-device collective HLO ops the mesh family censuses: on the
# lane mesh the compiled chunk may hold exactly ONE — the coverage
# all-reduce; any gather-class op means machine state is being resharded
COLLECTIVE_OPS = ("all-reduce", "all-gather", "all-to-all",
                  "collective-permute", "collective-broadcast",
                  "reduce-scatter")

# canonical mesh-trace configuration: the census is pinned against the
# 8-device arm; the 4-device arm (same lanes-per-shard) feeds the
# shard-count stability rule.  donate=False matches the real CPU
# dispatch policy (donation is unsound on XLA CPU — make_run_chunk).
MESH_ENTRY = "mesh_chunk"
MESH_DEVICES = 8
MESH_CONFIG = dict(n_steps=16, lanes_per_shard=2,
                   uop_capacity=1 << 10, overlay_slots=8, edge_bits=12)

# canonical fused-megachunk window configuration (fuzz/megachunk.py with
# the Pallas step engine): the whole window program's data-dependent
# JAXPR census — the Pallas dispatch counted ATOMICALLY as one
# "pallas-call" (on hardware it IS one kernel; in interpret mode the
# lowering would inline it and pollute an HLO census) — pinned as the
# `megachunk_window_fused` entry, plus the two donation rules: every
# pallas_call output aliased to its operand, and every machine/aggregate
# leaf of the donate-lowered window executable aliased in the compiled
# output (zero copy-through end to end).
MEGA_ENTRY = "megachunk_window_fused"
MEGA_CONFIG = dict(n_lanes=4, max_batches=2, limit=10_000)

# canonical device-decode service configuration (wtf_tpu/interp/devdec):
# ONE in-graph service round — the vmapped per-lane decode blocks plus
# the sequential publish-order commit — lowered at the budget runner's
# shapes and pinned as its own entry.  The decode graph rides inside
# the megachunk window, so this census is the marginal gather-class
# kernel cost a `--device-decode` campaign pays per service round.
DECODE_ENTRY = "decode_service"
DECODE_BP_SLOTS = 8

FAMILIES = ("dtype", "budget", "recompile", "parity", "mesh", "supervise",
            "telemetry", "state", "transfer", "thread", "contracts")

# families that (re)write budgets.json vs analysis/contracts.json on
# --rebaseline — the guard below demands at least one of them
_BUDGET_FAMILIES = frozenset(("budget", "mesh", "transfer"))
_CONTRACT_FAMILIES = frozenset(("state", "transfer", "thread"))

_FORBID_64 = re.compile(r"\b(u64|s64|f64|f32)\[")
# jaxpr primitives that move/reshape bits without computing on them (the
# pack/unpack seam allowance; on CPU the width-changing bitcast itself
# legitimately LOWERS to shift/or arithmetic, so the contract is checked
# at the jaxpr level, before XLA expands it)
_SEAM_OK = frozenset((
    "bitcast_convert_type", "reshape", "transpose", "squeeze",
    "broadcast_in_dim", "convert_element_type",
))
_ALIAS_ENTRY = re.compile(r"\((\d+), \{[^)]*?\}(?:, [a-z\-]+)?\)")


# ---------------------------------------------------------------------------
# dtype family
# ---------------------------------------------------------------------------

def check_no_u64(fn, *args, entry: str) -> List[Finding]:
    """Compile fn(*args); any 64-bit (or float) typed op is a finding —
    the ported integer-core paths are u32/bool/i32-only by contract."""
    text = compiled_hlo(fn, *args)
    found: Dict[str, int] = {}
    for m in _FORBID_64.finditer(text):
        found[m.group(1)] = found.get(m.group(1), 0) + 1
    return [
        Finding(rule="dtype.no-u64", entry=entry, primitive=dtype,
                count=n,
                message=("64-bit/float op reintroduced in a ported "
                         "u32-limb path (XLA lowers it to a carry-chained "
                         "u32 pair on TPU; Pallas cannot hold it at all)"))
        for dtype, n in sorted(found.items())
    ]


def check_seam_bitcast_only(fn, *args, entry: str) -> List[Finding]:
    """The pack/unpack seam may *hold* 64-bit values but must not compute
    on them: its jaxpr may contain only bitcast / data-movement
    primitives (the "free bitcast" contract XLA then lowers per
    platform)."""
    import jax

    jaxpr = jax.make_jaxpr(fn)(*args).jaxpr
    bad: Dict[str, int] = {}
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name not in _SEAM_OK:
            bad[name] = bad.get(name, 0) + 1
    return [
        Finding(rule="dtype.seam-bitcast-only", entry=entry, primitive=op,
                count=n,
                message=("pack/unpack seam performs arithmetic — the "
                         "seam contract is a free bitcast (data movement "
                         "only), nothing more"))
        for op, n in sorted(bad.items())
    ]


def _dtype_arg_recipes() -> Dict[str, Tuple]:
    """name -> (fn, args) for every ported path the dtype family pins.
    The step-level names must cover step.PORTED_LIMB_PATHS (and the
    devmut engine's devmut.PORTED_LIMB_PATHS) exactly; run_dtype_family
    fails the lint on any export without a recipe."""
    import jax.numpy as jnp

    from wtf_tpu.devmut import engine as DM
    from wtf_tpu.interp import limbs as L
    from wtf_tpu.interp import step as S
    from wtf_tpu.interp.uoptable import UopTable

    p = (jnp.uint32(0x55667788), jnp.uint32(0x11223344))
    q = (jnp.uint32(0xDEADBEEF), jnp.uint32(0x12345678))
    cin = jnp.bool_(True)
    n4 = jnp.int32(4)
    n8 = jnp.int32(8)
    s = jnp.uint32(33)
    rf = jnp.uint32(0x246)
    cap = 8
    tab = UopTable(
        rip_l=jnp.zeros((cap, 2), jnp.uint32),
        meta_i32=jnp.zeros((cap, 4), jnp.int32),
        meta_u64=jnp.zeros((cap, 4), jnp.uint32),
        hash_tab=jnp.full((cap * 4, 3), -1, jnp.int32),
    )
    gl = jnp.zeros((16, 2), jnp.uint32)
    recipes: Dict[str, Tuple] = {
        # limb library (interp/limbs.py public helpers)
        "limbs.adc64": (L.adc64, (p, q, cin)),
        "limbs.sbb64": (L.sbb64, (p, q, cin)),
        "limbs.shl64": (L.shl64, (p, s)),
        "limbs.shr64": (L.shr64, (p, s)),
        "limbs.sar64": (L.sar64, (p, s)),
        "limbs.rol64": (L.rol64, (p, s)),
        "limbs.mul64_lo": (L.mul64_lo, (p, q)),
        "limbs.umulhi64": (L.umulhi64, (p, q)),
        "limbs.smulhi64": (L.smulhi64, (p, q)),
        "limbs.splitmix64": (L.splitmix64, (p,)),
        "limbs.sext": (L.sext, (p, n4)),
        "limbs.flags_add": (L.flags_add, (p, q, p, n4, cin)),
        "limbs.flags_sub": (L.flags_sub, (p, q, p, n4, cin)),
        "limbs.eval_cond": (L.eval_cond, (rf, p, jnp.int32(5))),
        # step-level ported paths (step.PORTED_LIMB_PATHS)
        "step.alu_limb": (S.alu_limb, (jnp.int32(0), p, q, cin, n8, rf)),
        "step.unary_limb": (S.unary_limb,
                            (jnp.int32(0), p, jnp.bool_(False), n4, rf)),
        "step.shift_limb": (S.shift_limb,
                            (jnp.int32(4), jnp.int32(0), p, q, jnp.uint32(7),
                             jnp.uint32(3), jnp.uint32(2), cin, n8, rf)),
        "step.mul_limb": (S.mul_limb,
                          (jnp.int32(2), jnp.int32(0), p, q, p, q, n8, rf)),
        "step.ea_limb": (
            lambda d, b, i, sc, a32: S.ea_limb(
                d, b, S._scale_idx_l(i, sc), (jnp.uint32(0x1000),
                                              jnp.uint32(0)), a32),
            (p, q, p, n4, jnp.int32(0))),
        "step.scale_idx_l": (S._scale_idx_l, (p, n4)),
        "step.uop_lookup": (S.uop_lookup,
                            (tab, (jnp.uint32(0x1000), jnp.uint32(0x14)))),
        "step.gpr_write_l": (S._gpr_write_l,
                             (gl, jnp.bool_(True), jnp.int32(3), p, n4)),
    }
    # devmut engine paths (devmut.PORTED_LIMB_PATHS): tiny shapes — the
    # pin is about dtypes, not scale
    dm_data = jnp.zeros((4, 8), jnp.uint32)
    dm_lens = jnp.ones((4,), jnp.int32)
    dm_cumw = jnp.asarray([1, 2, 3, 4], jnp.uint32)
    dm_seeds = jnp.zeros((2, 2), jnp.uint32)
    recipes.update({
        "devmut.prng_next": (DM.prng_next, (p,)),
        "devmut.pick_slot": (DM.pick_slot,
                             (dm_cumw, jnp.asarray([5, 7], jnp.uint32))),
        "devmut.unpack_bytes": (DM.unpack_bytes, (dm_data,)),
        "devmut.pack_words": (DM.pack_words,
                              (jnp.zeros((2, 32), jnp.uint32),)),
        "devmut.generate": (
            lambda d, ln, c, s: DM.generate(d, ln, c, s, rounds=1),
            (dm_data, dm_lens, dm_cumw, dm_seeds)),
    })
    # triage candidate builds (triage.PORTED_LIMB_PATHS): the in-graph
    # minimizer ops run under the same pin as the devmut engine
    from wtf_tpu.triage import candidates as TC

    tc_words = jnp.zeros((8,), jnp.uint32)
    tc_ops = jnp.zeros((2,), jnp.int32)
    tc_u = jnp.zeros((2,), jnp.uint32)
    recipes.update({
        "triage.build_candidates": (
            TC.build_candidates,
            (tc_words, jnp.uint32(7), tc_ops, tc_u, tc_u)),
        "triage.zero_counts": (
            TC.zero_counts,
            (jnp.zeros((2, 8), jnp.uint32), jnp.ones((2,), jnp.int32))),
    })
    return recipes


def run_dtype_family(exports: Optional[Dict] = None,
                     compile_paths: bool = True) -> List[Finding]:
    """All dtype rules: no-u64 over every enumerated ported path, the
    seam bitcast-only check, and the completeness check that every path
    step.py exports (`exports`, default step.PORTED_LIMB_PATHS) has a
    recipe here — the mechanism that forces a newly ported path under
    the pin.  compile_paths=False runs only the completeness check (the
    compiles are the expensive part; tests that seed an unpinned export
    don't need them)."""
    import jax.numpy as jnp

    from wtf_tpu.devmut import engine as DM
    from wtf_tpu.interp import limbs as L
    from wtf_tpu.interp import step as S
    from wtf_tpu.triage import candidates as TC

    if exports is None:
        exports = {**S.PORTED_LIMB_PATHS, **DM.PORTED_LIMB_PATHS,
                   **TC.PORTED_LIMB_PATHS}
    recipes = _dtype_arg_recipes()
    findings: List[Finding] = []
    for name in sorted(exports):
        if name not in recipes:
            findings.append(Finding(
                rule="dtype.unpinned", entry=name,
                message=("step.PORTED_LIMB_PATHS exports a ported path "
                         "with no argument recipe in "
                         "analysis.rules._dtype_arg_recipes — add one so "
                         "the zero-u64 pin covers it")))
    if not compile_paths:
        return findings
    # Fast path: ONE compile of every recipe bundled into a tuple-valued
    # module (tuple outputs keep each path live, so a u64 in any entry
    # survives into the scanned text).  Only when that sweep finds a
    # violation do the entries recompile individually, to attach the
    # exact entry point to the finding — clean runs (CI, tier-1) pay a
    # single XLA pipeline instead of ~20.
    names = sorted(recipes)
    fns = [recipes[n][0] for n in names]

    def combined(argsets):
        return tuple(fn(*a) for fn, a in zip(fns, argsets))

    quick = check_no_u64(combined, [recipes[n][1] for n in names],
                         entry="ported-paths(combined)")
    if quick:
        localized: List[Finding] = []
        for name in names:
            fn, args = recipes[name]
            localized.extend(check_no_u64(fn, *args, entry=name))
        findings.extend(localized if localized else quick)
    # the seam itself: free bitcasts only
    v64 = jnp.arange(4, dtype=jnp.uint64)
    v32 = jnp.zeros((4, 2), jnp.uint32)
    findings.extend(check_seam_bitcast_only(
        L.pack_u64, v32, entry="limbs.pack_u64"))
    findings.extend(check_seam_bitcast_only(
        L.unpack_u64, v64, entry="limbs.unpack_u64"))
    return findings


# ---------------------------------------------------------------------------
# budget family
# ---------------------------------------------------------------------------

def count_data_dependent_ops(hlo_text: str) -> Dict[str, int]:
    """Occurrences of each gather-class op in optimized HLO text (plus
    "total") — the kernel-count currency of PERF.md's model."""
    counts = {}
    for name in DATA_DEP_OPS:
        pat = re.compile(r"(?<![\w\-])" + re.escape(name) + r"\(")
        counts[name] = len(pat.findall(hlo_text))
    counts["total"] = sum(counts.values())
    return counts


def check_budget(counts: Dict[str, int], budget: Dict[str, int],
                 entry: str, ops: Optional[Sequence[str]] = None
                 ) -> List[Finding]:
    """Exact pin: any drift (up OR down) is a finding — an improvement
    must be re-baselined consciously (see PERF.md round 9), a regression
    must be explained or fixed.  `ops` extends the censused op set
    (the fused-window entry adds "pallas-call")."""
    findings = []
    for name in list(ops if ops is not None else DATA_DEP_OPS) + ["total"]:
        got = counts.get(name, 0)
        want = budget.get(name)
        if want is None or got == want:
            continue
        direction = "over" if got > want else "under"
        findings.append(Finding(
            rule="budget.kernel-count", entry=entry, primitive=name,
            count=got, budget=want,
            message=(f"data-dependent `{name}` kernel count {direction} "
                     "the checked-in budget — if the change is "
                     "intentional, re-baseline with `python -m "
                     "wtf_tpu.analysis --rebaseline` and record why in "
                     "PERF.md")))
    return findings


def decode_service_lowering(runner):
    """Lower one devdec service round (compute_blocks + commit_blocks)
    at the budget runner's shapes — the `decode_service` census
    subject."""
    import jax
    import jax.numpy as jnp

    from wtf_tpu.interp import devdec
    from wtf_tpu.mem.physmem import lane_image

    capacity = runner.cache.capacity

    def service(tab, image, machine, count, bp_keys, n_bp):
        blocks = devdec.compute_blocks(tab, image, machine, bp_keys, n_bp)
        return devdec.commit_blocks(tab, count, blocks, machine.status,
                                    capacity)

    return jax.jit(service).lower(
        runner.cache.device(),
        lane_image(runner.physmem.image, runner.n_lanes),
        runner.machine, jnp.int32(0),
        jnp.zeros(DECODE_BP_SLOTS, jnp.uint64), jnp.int32(0))


def run_decode_rules(runner, budgets_path: Optional[Path] = None,
                     rebaseline: bool = False):
    """The `decode_service` kernel-count pin (budget family)."""
    text = decode_service_lowering(runner).compile().as_text()
    counts = count_data_dependent_ops(text)
    info = {"decode_counts": counts,
            "entry": f"decode_service(1 round) / demo_tlv / "
                     f"n_lanes={runner.n_lanes}"}
    findings: List[Finding] = []
    if not rebaseline:
        budget = load_budgets(budgets_path).get(DECODE_ENTRY, {})
        findings = check_budget(counts, budget, entry=info["entry"])
    return findings, info


def _iter_eqns(jxp):
    """Depth-first over a jaxpr's equations, descending into every
    sub-jaxpr carried in params (while/cond/scan/pjit/custom calls) —
    EXCEPT under pallas_call, which is atomic: on hardware it is ONE
    kernel dispatch, so its internal jaxpr must not leak into a
    kernel-count census."""
    from jax.core import ClosedJaxpr, Jaxpr

    def sub_jaxprs(v):
        if isinstance(v, ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for x in v:
                yield from sub_jaxprs(x)

    for eqn in jxp.eqns:
        yield eqn
        if eqn.primitive.name == "pallas_call":
            continue
        for v in eqn.params.values():
            for sub in sub_jaxprs(v):
                yield from _iter_eqns(sub)


def count_data_dependent_eqns(jaxpr) -> Dict[str, int]:
    """JAXPR-level analogue of count_data_dependent_ops for programs
    that embed a Pallas kernel: gather-class primitives counted across
    every sub-jaxpr, each pallas_call counted as ONE "pallas-call"
    (the fused window's per-round dispatch cost on hardware).  The HLO
    census can't serve here — interpret-mode lowering inlines the
    kernel body, which only exists on the CPU stand-in."""
    jxp = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    counts = {name: 0 for name in DATA_DEP_OPS}
    counts["pallas-call"] = 0
    for eqn in _iter_eqns(jxp):
        name = eqn.primitive.name
        if name == "pallas_call":
            counts["pallas-call"] += 1
        elif name == "gather":
            counts["gather"] += 1
        elif name == "dynamic_slice":
            counts["dynamic-slice"] += 1
        elif name == "dynamic_update_slice":
            counts["dynamic-update-slice"] += 1
        elif name.startswith("scatter"):
            counts["scatter"] += 1
    counts["total"] = sum(counts.values())
    return counts


def check_pallas_aliasing(jaxpr, entry: str) -> List[Finding]:
    """Every pallas_call in the fused window program must alias EVERY
    output to an input operand (input_output_aliases) — an unaliased
    output means the machine/overlay plane copies through the kernel on
    each dispatch, the exact copy-through the fused-megachunk donation
    leg eliminates.  A window with NO pallas_call is also a finding: the
    census subject isn't actually running the kernel."""
    jxp = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    findings: List[Finding] = []
    n_calls = 0
    for eqn in _iter_eqns(jxp):
        if eqn.primitive.name != "pallas_call":
            continue
        n_calls += 1
        ioa = eqn.params.get("input_output_aliases") or ()
        covered = {int(o) for (_i, o) in ioa}
        missing = sorted(set(range(len(eqn.outvars))) - covered)
        if missing:
            findings.append(Finding(
                rule="recompile.pallas-unaliased", entry=entry,
                primitive=f"pallas_call outputs {missing}",
                count=len(missing),
                message=("fused-kernel output not aliased to its "
                         "operand — the plane copies through the kernel "
                         "every dispatch; extend input_output_aliases "
                         "in pstep.fused_call_impl")))
    if n_calls == 0:
        findings.append(Finding(
            rule="recompile.pallas-unaliased", entry=entry,
            primitive="pallas_call",
            message=("no pallas_call in the fused window program — the "
                     "fused megachunk is not running the Pallas step "
                     "engine; the pin's census subject is wrong")))
    return findings


def check_window_donation_aliasing(compiled, args,
                                   donated: Sequence[int],
                                   entry: str) -> List[Finding]:
    """check_donation_aliasing generalized to the megachunk window
    executable: every leaf of every donated operand position must appear
    in the compiled module's input_output_alias map.  `args` is the full
    operand tuple the window was lowered against; `donated` the
    positional donate_argnums (megachunk.WINDOW_DONATE_ARGNUMS).  A
    donated leaf jit's DCE pruned outright is still a finding — the
    buffer is invalidated with no in-place reuse."""
    import jax

    text = compiled.as_text()
    header = text[:text.index("\n")]
    m = re.search(r"input_output_alias=\{(.*?)\}, entry_computation",
                  header)
    aliased = ({int(g.group(1)) for g in _ALIAS_ENTRY.finditer(m.group(1))}
               if m else set())
    kept = getattr(getattr(compiled, "_executable", None),
                   "_kept_var_idx", None)
    findings: List[Finding] = []
    base = 0
    names = ("tab", "image", "machine", "template", "slab_first",
             "slab_rest", "seeds", "pfns", "gva_l", "finish", "limit",
             "n_batches", "agg_cov", "agg_edge", "count", "bp_keys",
             "n_bp")
    for pos, arg in enumerate(args):
        flat = jax.tree_util.tree_flatten_with_path(arg)[0]
        for i, (path, _leaf) in enumerate(flat):
            param = base + i
            if pos in donated:
                if kept is not None and param not in kept:
                    shifted = -1  # pruned outright: never aliased
                elif kept is not None:
                    shifted = sum(1 for k in kept if k < param)
                else:
                    shifted = param
                if shifted not in aliased:
                    arg_name = (names[pos] if pos < len(names)
                                else f"arg{pos}")
                    findings.append(Finding(
                        rule="recompile.window-donation-unaliased",
                        entry=entry,
                        primitive=(f"{arg_name}"
                                   f"{jax.tree_util.keystr(path)} "
                                   f"(param {param})"),
                        message=("donated window operand leaf not "
                                 "aliased in the compiled megachunk — "
                                 "the buffer is invalidated without the "
                                 "in-place reuse; the overlay/machine "
                                 "planes would copy through the window "
                                 "executable")))
        base += len(flat)
    return findings


def run_megachunk_rules(budgets_path: Optional[Path] = None,
                        rebaseline: bool = False
                        ) -> Tuple[List[Finding], Dict]:
    """The fused-window pins, one trace for all three:

      1. the `megachunk_window_fused` kernel census (jaxpr-level,
         pallas_call atomic) against budgets.json;
      2. every pallas_call aliases all its machine-state outputs;
      3. the window executable, LOWERED with donation (safe on CPU —
         only execution is unsound there), aliases every donated
         machine/aggregate leaf in its compiled output.

    Returns (findings, info) with the measured counts for run_lint's
    rebaseline merge."""
    import jax

    from wtf_tpu.analysis.trace import megachunk_window_lowering
    from wtf_tpu.fuzz.megachunk import WINDOW_DONATE_ARGNUMS

    cfg = MEGA_CONFIG
    entry = (f"megachunk(max_batches={cfg['max_batches']}, fused=True, "
             f"donate=True) / demo_tlv / n_lanes={cfg['n_lanes']}")
    lowered, args, fn = megachunk_window_lowering(
        max_batches=cfg["max_batches"], n_lanes=cfg["n_lanes"],
        fused=True, donate=True, limit=cfg["limit"])
    jaxpr = jax.make_jaxpr(fn)(*args)
    counts = count_data_dependent_eqns(jaxpr)
    findings: List[Finding] = []
    if not rebaseline:
        budget = load_budgets(budgets_path).get(MEGA_ENTRY, {})
        findings = check_budget(counts, budget, entry=entry,
                                ops=list(DATA_DEP_OPS) + ["pallas-call"])
    findings.extend(check_pallas_aliasing(jaxpr, entry=entry))
    findings.extend(check_window_donation_aliasing(
        lowered.compile(), args, WINDOW_DONATE_ARGNUMS, entry=entry))
    # the raw jaxpr rides along (popped before JSON) so the transfer
    # family's host-callback census never re-traces the window
    return findings, {"mega_counts": counts, "entry": entry,
                      "jaxpr": jaxpr}


def check_triage_chunk() -> List[Finding]:
    """The triage replay core must dispatch the SAME compiled step
    ladder the campaign runs — zero new gather/DS/DUS kernels beyond the
    pinned budget.  Statically: its declared chunk-executor factory is
    step.make_run_chunk by identity (ReplayCore drives Runner.run, whose
    `_chunk_callable` memoizes that factory), and the core defines no
    private executor seam.  Re-pointing either is a real kernel-budget
    event and must be re-baselined consciously."""
    from wtf_tpu.interp.step import make_run_chunk
    from wtf_tpu.triage import replay as TR

    findings = []
    if TR.REPLAY_CHUNK_FACTORY is not make_run_chunk:
        findings.append(Finding(
            rule="budget.triage-chunk", entry="triage.replay",
            primitive="REPLAY_CHUNK_FACTORY",
            message=("triage's replay chunk no longer resolves to "
                     "step.make_run_chunk — the triage path would "
                     "compile its own step program outside the pinned "
                     "168-kernel budget; route it through the Runner "
                     "dispatch seam or re-baseline")))
    private = [name for name in ("_chunk_callable", "chunk_executor",
                                 "device_tab")
               if name in vars(TR.ReplayCore)]
    if private:
        findings.append(Finding(
            rule="budget.triage-chunk", entry="triage.replay.ReplayCore",
            primitive=", ".join(private),
            message=("ReplayCore overrides the Runner dispatch seam — "
                     "triage batches must run the campaign's own chunk "
                     "executors (budget + mesh census coverage), not a "
                     "private program")))
    return findings


# ---------------------------------------------------------------------------
# supervise family
# ---------------------------------------------------------------------------

def check_supervised_seams(sites: Optional[Dict[str, str]] = None
                           ) -> List[Finding]:
    """Every device dispatch entry point must route through the
    supervisor (wtf_tpu/supervise) — the recovery/watchdog/chaos
    contract is only as strong as its seam coverage, so the enumeration
    is an export hook (supervise.SEAM_SITES, the PORTED_LIMB_PATHS
    mechanism): a new dispatch seam must be listed there AND its listed
    site must contain a `supervisor.dispatch("<seam>"...)` routing
    call.  AST-level on the shared dataflow engine (analysis/flow.py) —
    the seams include paths (mesh, fused) a CPU lint run never
    executes, and the AST walk cannot false-hit on comments or
    docstrings the way the retired regex could.  `sites` parameterizes
    the enumeration for rule tests."""
    from wtf_tpu.analysis import flow

    if sites is None:
        from wtf_tpu.supervise import SEAM_SITES

        sites = SEAM_SITES
    findings: List[Finding] = []
    for seam, site in sorted(sites.items()):
        try:
            info = flow.resolve_site(site)
        except Exception as e:  # unresolvable site IS the finding
            findings.append(Finding(
                rule="supervise.seam-routing", entry=site, primitive=seam,
                message=(f"supervised seam site unresolvable ({e}) — "
                         "supervise.SEAM_SITES must name the live "
                         "module:Class.method of every dispatch seam")))
            continue
        if seam not in flow.dispatch_seams(info.node):
            findings.append(Finding(
                rule="supervise.seam-routing", entry=site, primitive=seam,
                file=info.file, line=info.lineno,
                message=(f"dispatch seam {seam!r} does not route through "
                         "Supervisor.dispatch — a hang/error/poison here "
                         "would bypass watchdog + rebuild-and-replay "
                         "recovery; route the call or update "
                         "supervise.SEAM_SITES")))
    return findings


def check_telemetry_seams(sites: Optional[Dict[str, str]] = None
                          ) -> List[Finding]:
    """No supervised dispatch seam may serialize the metric registry
    inline: `.snapshot()` walks every metric, `encode_telem`/`json.dumps`
    pay JSON, and the seams run once per chunk — the <1% overhead bar
    (PERF.md) holds because serialization rides the heartbeat/TAG_TELEM
    cadence (seconds) instead.  AST-level (flow.serialization_calls —
    counter bumps like `.inc()`/`.set()` are O(1) dict ops and welcome
    anywhere; registry snapshots / telem encoding / json.dumps are
    O(registry)+JSON and are not), over the same supervise.SEAM_SITES
    enumeration the routing rule walks, so a new dispatch seam is
    covered the moment it is enumerated.  `sites` parameterizes the
    enumeration for rule tests."""
    from wtf_tpu.analysis import flow

    if sites is None:
        from wtf_tpu.supervise import SEAM_SITES

        sites = SEAM_SITES
    findings: List[Finding] = []
    for seam, site in sorted(sites.items()):
        try:
            info = flow.resolve_site(site)
        except Exception:
            # unresolvable sites are the supervise family's finding;
            # double-reporting here would just duplicate the signal
            continue
        calls = flow.serialization_calls(info.node)
        if calls:
            hits = sorted({pat for pat, _ in calls})
            findings.append(Finding(
                rule="telemetry.seam-serialization", entry=site,
                primitive=f"{seam}: {', '.join(hits)}",
                count=len(hits), file=info.file,
                line=min(ln for _, ln in calls),
                message=("dispatch seam serializes telemetry inline — "
                         "registry snapshots / telem encoding are "
                         "O(registry)+JSON and this seam runs per chunk; "
                         "move the serialization to the heartbeat or "
                         "TAG_TELEM cadence (counter bumps stay, "
                         "serialization goes)")))
    return findings


def check_seam_enumeration() -> List[Finding]:
    """Completeness of the export hook itself: the known dispatch-seam
    surface (the Runner seam methods MeshRunner re-points, the megachunk
    window, devmut generate) must each be claimed by some SEAM_SITES
    entry — deleting a seam's enumeration to dodge the routing rule is
    itself a finding."""
    from wtf_tpu.supervise import SEAM_SITES

    claimed = set(SEAM_SITES)
    required = {"chunk", "fused", "fused-resume", "device-insert",
                "devmut-generate", "megachunk", "device-decode"}
    missing = sorted(required - claimed)
    return [Finding(
        rule="supervise.seam-enumeration", entry="supervise.SEAM_SITES",
        primitive=seam,
        message=(f"dispatch seam {seam!r} dropped from "
                 "supervise.SEAM_SITES — the routing rule no longer "
                 "covers it"))
        for seam in missing]


def _first_diff_line(text_a: str, text_b: str) -> Tuple[int, str]:
    """(0-based line index, detail) of the first differing line between
    two lowerings; (-1, "length mismatch") when one is a prefix of the
    other.  Shared by the byte-stability rules."""
    for i, (la, lb) in enumerate(zip(text_a.splitlines(),
                                     text_b.splitlines())):
        if la != lb:
            return i, la.strip()[:80]
    return -1, "length mismatch"


def check_tenant_mix_stability(text_a: str, text_b: str,
                               entry: str) -> List[Finding]:
    """The heterogeneous batch's serving contract, statically: at a
    given lane count the chunk executor must lower to the SAME program
    bytes for any tenant mix — tenant identity is pure data (the
    per-lane selector + stacked table contents), never a traced value.
    The probe permutes the tenant TABLE (demo_tlv+demo_kernel vs
    demo_kernel+demo_tlv: same shapes, different contents and lane
    assignment); a diff means a tenant-mix-dependent value is baked into
    the trace and every mix would compile its own program."""
    if text_a == text_b:
        return []
    i, detail = _first_diff_line(text_a, text_b)
    return [Finding(
        rule="budget.tenant-mix", entry=entry,
        primitive=f"line {i + 1}: {detail}",
        message=("the compiled step ladder differs across tenant "
                 "permutations at equal lane count — tenant identity "
                 "leaked into the traced program; heterogeneous batches "
                 "must share ONE compiled program per lane count"))]


def run_tenant_rules(budgets_path: Optional[Path] = None,
                     rebaseline: bool = False) -> Tuple[List[Finding],
                                                        Dict]:
    """The tenancy half of the budget family: image-table kernel census
    + tenant-mix program stability.  Returns (findings, info) with the
    measured counts for run_lint's rebaseline merge."""
    cfg = TENANT_CONFIG
    entry = (f"make_run_chunk({cfg['n_steps']}, donate=False) / "
             f"{'+'.join(cfg['order'])} / quotas={list(cfg['quotas'])}")
    kwargs = dict(chunk_steps=cfg["n_steps"],
                  uop_capacity=cfg["uop_capacity"],
                  overlay_slots=cfg["overlay_slots"],
                  edge_bits=cfg["edge_bits"])
    runner = build_tenant_runner(quotas=cfg["quotas"], order=cfg["order"],
                                 **kwargs)
    lowered = tenant_executor_lowering(runner, n_steps=cfg["n_steps"])
    permuted = build_tenant_runner(quotas=cfg["quotas"],
                                   order=cfg["order"][::-1], **kwargs)
    lowered_p = tenant_executor_lowering(permuted, n_steps=cfg["n_steps"])
    findings = check_tenant_mix_stability(
        lowered.as_text(), lowered_p.as_text(), entry=entry)
    counts = count_data_dependent_ops(lowered.compile().as_text())
    if not rebaseline:
        budget = load_budgets(budgets_path).get(TENANT_ENTRY, {})
        findings.extend(check_budget(counts, budget, entry=entry))
    return findings, {"tenant_counts": counts, "entry": entry}


def apply_rebaseline(budgets: Dict, measured: Dict,
                     allow_regression: bool = False) -> Dict:
    """Merge freshly measured budget entries over the checked-in ones —
    RATCHETED: an entry whose `total` INCREASED over the checked-in
    value is refused (ValueError naming every offender) unless
    `allow_regression`.  Every decrement is a wall-clock win on TPU
    (step cost tracks kernel count), so a giveback must be a conscious,
    named act.  Returns the merged dict; pure, so tests can pin the
    ratchet without paying a trace."""
    regressions = [
        (name, budgets[name].get("total"), entry.get("total"))
        for name, entry in measured.items()
        if name in budgets
        and entry.get("total", 0) > budgets[name].get("total", 0)]
    if regressions and not allow_regression:
        detail = ", ".join(f"{n}: {old} -> {new}"
                           for n, old, new in regressions)
        raise ValueError(
            f"--rebaseline would RAISE a kernel/collective budget "
            f"({detail}); the pin is a ratchet — re-run with "
            f"--allow-regression and record why in PERF.md, or fix "
            f"the regression")
    merged = dict(budgets)
    merged.update(measured)
    return merged


def load_budgets(path: Optional[Path] = None) -> Dict:
    path = Path(path) if path else BUDGETS_PATH
    return json.loads(path.read_text())


def save_budgets(budgets: Dict, path: Optional[Path] = None) -> Path:
    path = Path(path) if path else BUDGETS_PATH
    path.write_text(json.dumps(budgets, indent=2, sort_keys=True) + "\n")
    return path


# ---------------------------------------------------------------------------
# recompile family
# ---------------------------------------------------------------------------

def weak_typed_leaves(args) -> List[Tuple[str, str]]:
    """(path, dtype) for every weak-typed leaf in an argument pytree —
    each is a python scalar crossing the jit boundary where a committed
    dtype belongs, and a second call site with the strong dtype splits
    the jit cache (one executor shape, two compiles)."""
    import jax

    out = []
    flat = jax.tree_util.tree_flatten_with_path(args)[0]
    for path, leaf in flat:
        aval = jax.core.get_aval(leaf)
        if getattr(aval, "weak_type", False):
            out.append((jax.tree_util.keystr(path), str(aval.dtype)))
    return out


def check_strong_inputs(args, entry: str) -> List[Finding]:
    return [
        Finding(rule="recompile.weak-type", entry=entry,
                primitive=f"{path}: {dtype} (weak)",
                message=("weak-typed executor operand — a python scalar "
                         "leaked to the dispatch seam; pass a committed "
                         "dtype (jnp.uint64(...) etc.) or the jit cache "
                         "splits per caller convention"))
        for path, dtype in weak_typed_leaves(args)
    ]


def check_signature_stable(text_a: str, text_b: str,
                           entry: str) -> List[Finding]:
    """Two lowerings of the same executor under perturbed-but-same-shape
    inputs must be byte-identical StableHLO; a diff means a traced VALUE
    (python int capture, host-dependent constant) entered the graph —
    every such value is a silent retrace per distinct value."""
    if text_a == text_b:
        return []
    i, detail = _first_diff_line(text_a, text_b)
    return [Finding(
        rule="recompile.signature-unstable", entry=entry,
        primitive=f"line {i + 1}: {detail}",
        message=("re-tracing under perturbed same-shape inputs changed "
                 "the lowered module — a runtime value is captured in "
                 "the trace and will force a recompile per value"))]


def check_runner_donation_policy(runner, entry: str = "interp.runner"
                                 ) -> List[Finding]:
    """The PR-2 CPU-donation-corruption class, statically: on the CPU
    backend the Runner must not request donation (XLA CPU's buffer reuse
    corrupts live machine leaves on this graph); off-CPU it must (HBM
    in-place updates are the point)."""
    import jax

    expected = jax.default_backend() != "cpu"
    if bool(runner._donate) == expected:
        return []
    return [Finding(
        rule="recompile.donation-policy", entry=entry,
        primitive=f"_donate={runner._donate} on {jax.default_backend()}",
        message=("Runner donation gate violated: donation must be OFF on "
                 "the CPU backend (donated machine buffers corrupt there "
                 "— the PR-2 failure class) and ON elsewhere"))]


def check_donation_aliasing(compiled_text: str, machine,
                            n_prefix_params: int, entry: str,
                            dropped_args=frozenset()) -> List[Finding]:
    """Every leaf of the donated machine argument must appear in the
    compiled module's input_output_alias map; an unaliased donated
    buffer is invalidated without the in-place win, and any host view of
    it reads garbage.

    `dropped_args`: original flat-argument indices jit's dead-code
    elimination pruned from the compiled module (kept_var_idx's
    complement) — compiled param numbering skips them, so every dropped
    index below a machine leaf shifts its param down by one.  A donated
    machine leaf that is ITSELF dropped is still a finding (the buffer
    is invalidated with no reuse)."""
    import jax

    header = compiled_text[:compiled_text.index("\n")]
    m = re.search(r"input_output_alias=\{(.*?)\}, entry_computation", header)
    aliased = ({int(g.group(1)) for g in _ALIAS_ENTRY.finditer(m.group(1))}
               if m else set())
    flat = jax.tree_util.tree_flatten_with_path(machine)[0]
    findings = []
    for i, (path, _leaf) in enumerate(flat):
        param = n_prefix_params + i
        if param in dropped_args:
            shifted = -1  # pruned outright: never aliased
        else:
            shifted = param - sum(1 for d in dropped_args if d < param)
        if shifted not in aliased:
            findings.append(Finding(
                rule="recompile.donation-unaliased", entry=entry,
                primitive=f"machine{jax.tree_util.keystr(path)} "
                          f"(param {param})",
                message=("donated machine leaf not aliased in the "
                         "compiled output — donation invalidates the "
                         "buffer with no in-place reuse; host code "
                         "holding a view of it reads poison")))
    return findings


# ---------------------------------------------------------------------------
# mesh family
# ---------------------------------------------------------------------------

def count_collective_ops(hlo_text: str) -> Dict[str, int]:
    """Occurrences of each cross-device collective in partitioned HLO
    text (plus "total") — the interconnect-traffic currency of the mesh
    cost model (PERF.md round 11)."""
    counts = {}
    for name in COLLECTIVE_OPS:
        pat = re.compile(r"(?<![\w\-])" + re.escape(name) + r"[\.\w]*\(")
        counts[name] = len(pat.findall(hlo_text))
    counts["total"] = sum(counts.values())
    return counts


def check_mesh_collectives(counts: Dict[str, int], budget: Dict[str, int],
                           entry: str) -> List[Finding]:
    """Exact pin against budgets.json's `mesh_chunk` entry: the sharded
    chunk's only collective is the coverage all-reduce.  A gather-class
    op appearing means machine state is crossing the interconnect —
    an accidental reshard, the regression this family exists to catch."""
    findings = []
    for name in list(COLLECTIVE_OPS) + ["total"]:
        got = counts.get(name, 0)
        want = budget.get(name)
        if want is None or got == want:
            continue
        direction = "over" if got > want else "under"
        findings.append(Finding(
            rule="mesh.collectives", entry=entry, primitive=name,
            count=got, budget=want,
            message=(f"cross-device `{name}` count {direction} the "
                     "checked-in mesh budget — the compiled chunk's only "
                     "collective is the coverage all-reduce; anything "
                     "else reshards machine state over the interconnect. "
                     "If intentional, re-baseline with `python -m "
                     "wtf_tpu.analysis --rebaseline` and record why in "
                     "PERF.md")))
    return findings


# partitioned-HLO details that legitimately vary with the mesh size
# (device lists in sharding annotations / replica groups) — stripped
# before the shard-count stability comparison
_MESH_NORMALIZE = (
    (re.compile(r"sharding=\{[^{}]*\}"), "sharding={...}"),
    (re.compile(r"replica_groups=\{\{[^{}]*\}(,\{[^{}]*\})*\}"),
     "replica_groups={...}"),
    (re.compile(r"replica_groups=\{[^{}]*\}"), "replica_groups={...}"),
    (re.compile(r"replica_groups=\[[^\]]*\]<=\[\d+\]"),
     "replica_groups=[...]"),
    (re.compile(r"num_partitions=\d+"), "num_partitions=N"),
)


def normalize_partitioned_hlo(text: str) -> str:
    for pat, repl in _MESH_NORMALIZE:
        text = pat.sub(repl, text)
    return text


def check_shard_stability(text_a: str, text_b: str,
                          entry: str) -> List[Finding]:
    """Two compiled mesh chunks at EQUAL lanes-per-shard but different
    shard counts must be byte-identical per-device programs once the
    device-list annotations are normalized; a diff means a shard-count-
    dependent value leaked into the trace and every mesh resize pays a
    silent recompile of a *different* program."""
    na, nb = normalize_partitioned_hlo(text_a), normalize_partitioned_hlo(
        text_b)
    if na == nb:
        return []
    for i, (la, lb) in enumerate(zip(na.splitlines(), nb.splitlines())):
        if la != lb:
            detail = la.strip()[:80]
            break
    else:
        detail, i = "length mismatch", -1
    return [Finding(
        rule="mesh.shard-unstable", entry=entry,
        primitive=f"line {i + 1}: {detail}",
        message=("the compiled per-device chunk differs across shard "
                 "counts at equal lanes-per-shard — a mesh-size-dependent "
                 "value is baked into the traced program"))]


def _mesh_chunk_compiled(n_shards: int) -> str:
    """Compiled partitioned HLO of the mesh chunk executor at
    MESH_CONFIG's lanes-per-shard over `n_shards` devices."""
    import jax
    import jax.numpy as jnp

    from wtf_tpu.meshrun.executor import make_mesh_chunk
    from wtf_tpu.meshrun.mesh import make_mesh, replicate, shard_machine

    cfg = MESH_CONFIG
    runner = build_tlv_runner(
        n_lanes=cfg["lanes_per_shard"] * n_shards,
        chunk_steps=cfg["n_steps"], payload=None,
        uop_capacity=cfg["uop_capacity"],
        overlay_slots=cfg["overlay_slots"], edge_bits=cfg["edge_bits"])
    mesh = make_mesh(n_shards)
    machine = shard_machine(runner.machine, mesh)
    tab = replicate(runner.cache.device(), mesh)
    image = replicate(runner.physmem.image, mesh)
    # jit=False: a fresh shard_map closure per lowering, same reasoning
    # as step_executor_lowering's fresh-trace requirement
    fn = jax.jit(make_mesh_chunk(cfg["n_steps"], mesh, donate=False,
                                 jit=False))
    return fn.lower(tab, image, machine,
                    jnp.uint64(0)).compile().as_text()


def _mesh_family_subprocess(budgets_path: Optional[Path],
                            rebaseline: bool) -> Tuple[List[Finding], Dict]:
    """Re-run ONLY the mesh family in a child interpreter with the
    forced 8-device CPU platform (the ambient process has too few
    devices and jax device topology is fixed at backend init)."""
    import os
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # force EXACTLY MESH_DEVICES: an ambient flag pinning a smaller
    # count must be overridden, not preserved, or the child is just as
    # device-poor as the parent and the family reports unavailable
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count"
                f"={MESH_DEVICES}").strip()
    if env.get("WTF_LINT_MESH_SUBPROC"):
        return [Finding(
            rule="mesh.unavailable", entry=MESH_ENTRY,
            message=(f"mesh family needs >= {MESH_DEVICES} devices but "
                     "the forced-device subprocess still sees too few — "
                     "platform cannot host a virtual mesh"))], {}
    env["WTF_LINT_MESH_SUBPROC"] = "1"
    cmd = [sys.executable, "-m", "wtf_tpu.analysis", "--families", "mesh",
           "--json"]
    if budgets_path is not None:
        cmd += ["--budgets", str(budgets_path)]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=1800)
    line = next((ln for ln in reversed(proc.stdout.splitlines())
                 if ln.startswith("{")), None)
    if line is None:
        return [Finding(
            rule="mesh.unavailable", entry=MESH_ENTRY,
            message=("forced-8-device mesh subprocess produced no JSON "
                     f"(rc={proc.returncode}): "
                     f"{(proc.stderr or proc.stdout)[-200:]}"))], {}
    out = json.loads(line)
    findings = [Finding(**{k: f.get(k) for k in
                           ("rule", "entry", "message", "primitive",
                            "count", "budget", "file", "line")})
                for f in out.get("findings", [])]
    if rebaseline:
        # parent is re-pinning: the measured counts matter, drift
        # findings against the OLD budget don't
        findings = [f for f in findings if f.rule != "mesh.collectives"]
    return findings, {"collective_counts": out.get("collective_counts"),
                      "entry": out.get("mesh_entry")}


def run_mesh_family(budgets_path: Optional[Path] = None,
                    rebaseline: bool = False) -> Tuple[List[Finding], Dict]:
    """All mesh rules.  Returns (findings, info) where info carries the
    measured collective census (for run_lint's rebaseline merge and the
    `analysis.mesh_collectives` telemetry gauges)."""
    import jax

    if len(jax.devices()) < MESH_DEVICES:
        return _mesh_family_subprocess(budgets_path, rebaseline)
    entry = (f"make_mesh_chunk({MESH_CONFIG['n_steps']}, donate=False) / "
             f"demo_tlv / {MESH_DEVICES} shards x "
             f"{MESH_CONFIG['lanes_per_shard']} lanes")
    text_full = _mesh_chunk_compiled(MESH_DEVICES)
    text_half = _mesh_chunk_compiled(MESH_DEVICES // 2)
    counts = count_collective_ops(text_full)
    findings = check_shard_stability(text_full, text_half, entry=entry)
    if not rebaseline:
        budget = load_budgets(budgets_path).get(MESH_ENTRY, {})
        findings.extend(check_mesh_collectives(counts, budget, entry=entry))
    return findings, {"collective_counts": counts, "entry": entry}


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------

def run_lint(families: Optional[Sequence[str]] = None,
             budgets_path: Optional[Path] = None,
             rebaseline: bool = False,
             allow_regression: bool = False,
             deep: bool = False,
             contracts_path: Optional[Path] = None,
             registry=None, events=None) -> Tuple[List[Finding], Dict]:
    """Run the requested rule families (default: all) against the real
    tree on the current (CPU) backend.  Returns (findings, info); wires
    results into the telemetry registry under `analysis.*` and emits one
    `lint-finding` event per finding when an event sink is given.

    `deep` forces the transfer family's jaxpr host-callback census even
    when the budget family (whose fused-window trace it would reuse) is
    not co-selected; without either, the transfer family runs its cheap
    AST rule only.

    The kernel-count pin is a RATCHET: `rebaseline` re-pins measured
    counts as usual, but REFUSES to record a budget whose `total`
    INCREASED over the checked-in value unless `allow_regression` is
    set — every decrement is a wall-clock win on TPU (step cost tracks
    kernel count), so giving one back must be a conscious, named act
    (`--allow-regression`, recorded in PERF.md).  contracts.json gets
    the same treatment through apply_contracts_rebaseline: entry GROWTH
    (a new undispositioned attribute / hidden coercion / shared write)
    is refused without `allow_regression`."""
    from wtf_tpu.telemetry import NULL, Registry

    registry = registry if registry is not None else Registry()
    events = events if events is not None else NULL
    families = list(families) if families else list(FAMILIES)
    unknown = set(families) - set(FAMILIES)
    if unknown:
        raise ValueError(f"unknown lint families: {sorted(unknown)} "
                         f"(known: {list(FAMILIES)})")
    if rebaseline and not (_BUDGET_FAMILIES
                           | _CONTRACT_FAMILIES) & set(families):
        raise ValueError(
            "--rebaseline rewrites the kernel-count/collective budgets "
            "and the contract tables, which only the budget/mesh/"
            "transfer and state/transfer/thread families measure — drop "
            "the families filter or include one of them")
    findings: List[Finding] = []
    info: Dict = {"families": families, "seconds": {}, "entries": []}
    # entries re-measured this run; merged over the checked-in file on
    # --rebaseline so a partial family filter never drops the others
    measured_budgets: Dict[str, Dict] = {}

    needs_trace = {"budget", "recompile"} & set(families)
    runner = None
    if needs_trace:
        t0 = time.time()
        runner = build_tlv_runner(
            n_lanes=BUDGET_CONFIG["n_lanes"],
            chunk_steps=BUDGET_CONFIG["chunk_steps"], payload=None)
        lowered = step_executor_lowering(
            runner, n_steps=BUDGET_CONFIG["n_steps"],
            donate=BUDGET_CONFIG["donate"])
        info["seconds"]["trace"] = round(time.time() - t0, 1)
        info["entries"].append(
            f"make_run_chunk({BUDGET_CONFIG['n_steps']}, "
            f"donate={BUDGET_CONFIG['donate']}) / demo_tlv / "
            f"n_lanes={BUDGET_CONFIG['n_lanes']}")

    if "dtype" in families:
        t0 = time.time()
        findings.extend(run_dtype_family())
        info["seconds"]["dtype"] = round(time.time() - t0, 1)

    compiled = None
    compiled_text = None
    mega_jaxpr = None
    if "budget" in families:
        t0 = time.time()
        compiled = lowered.compile()
        compiled_text = compiled.as_text()
        counts = count_data_dependent_ops(compiled_text)
        info["kernel_counts"] = counts
        if rebaseline:
            measured_budgets[BUDGET_ENTRY] = {
                "entry": info["entries"][0], **counts}
        else:
            budget = load_budgets(budgets_path).get(BUDGET_ENTRY, {})
            findings.extend(check_budget(counts, budget,
                                         entry=info["entries"][0]))
        for name, value in counts.items():
            registry.gauge("analysis.kernel_count").labels(name).set(value)
        # the triage replay core rides the same compiled ladder: its
        # kernel contribution is ZERO by identity, checked statically
        findings.extend(check_triage_chunk())
        # heterogeneous batches (wtf_tpu/tenancy): image-table kernel
        # census + one-program-per-lane-count across tenant mixes
        tenant_findings, tenant_info = run_tenant_rules(
            budgets_path=budgets_path, rebaseline=rebaseline)
        findings.extend(tenant_findings)
        counts_t = tenant_info["tenant_counts"]
        info["tenant_kernel_counts"] = counts_t
        info["entries"].append(tenant_info["entry"])
        if rebaseline:
            measured_budgets[TENANT_ENTRY] = {
                "entry": tenant_info["entry"], **counts_t}
        for name, value in counts_t.items():
            registry.gauge("analysis.tenant_kernel_count").labels(
                name).set(value)
        # device-decode service graph (interp/devdec): its own pin —
        # the marginal kernel cost of a `--device-decode` service round
        decode_findings, decode_info = run_decode_rules(
            runner, budgets_path=budgets_path, rebaseline=rebaseline)
        findings.extend(decode_findings)
        counts_d = decode_info["decode_counts"]
        info["decode_kernel_counts"] = counts_d
        info["entries"].append(decode_info["entry"])
        if rebaseline:
            measured_budgets[DECODE_ENTRY] = {
                "entry": decode_info["entry"], **counts_d}
        for name, value in counts_d.items():
            registry.gauge("analysis.decode_kernel_count").labels(
                name).set(value)
        # fused megachunk window (fuzz/megachunk.py fused=True): jaxpr
        # census with pallas_call atomic, plus the two donation rules —
        # kernel output aliasing and window-executable donation aliasing
        mega_findings, mega_info = run_megachunk_rules(
            budgets_path=budgets_path, rebaseline=rebaseline)
        findings.extend(mega_findings)
        mega_jaxpr = mega_info.pop("jaxpr", None)
        counts_m = mega_info["mega_counts"]
        info["mega_kernel_counts"] = counts_m
        info["entries"].append(mega_info["entry"])
        if rebaseline:
            measured_budgets[MEGA_ENTRY] = {
                "entry": mega_info["entry"], **counts_m}
        for name, value in counts_m.items():
            registry.gauge("analysis.mega_kernel_count").labels(
                name).set(value)
        info["seconds"]["budget"] = round(time.time() - t0, 1)

    if "recompile" in families:
        t0 = time.time()
        entry = info["entries"][0]
        # weak-typed operands at the dispatch seam (what Runner.run passes)
        operands = runner.executor_operands()
        findings.extend(check_strong_inputs(operands, entry=entry))
        # retrace under perturbed same-shape inputs
        perturbed = step_executor_lowering(
            runner, n_steps=BUDGET_CONFIG["n_steps"],
            donate=BUDGET_CONFIG["donate"], perturb=True)
        findings.extend(check_signature_stable(
            lowered.as_text(), perturbed.as_text(), entry=entry))
        # overlay restore: same stability contract, cheap trace (fresh
        # jit wrappers — the memoized executor would hit the trace cache)
        from wtf_tpu.interp.machine import _machine_restore_impl
        from wtf_tpu.analysis.trace import lower_jit

        ra = lower_jit(lambda m, t: _machine_restore_impl(m, t),
                       runner.machine, runner.template).as_text()
        rb = lower_jit(lambda m, t: _machine_restore_impl(m, t),
                       runner.machine._replace(
                           icount=runner.machine.icount + 3),
                       runner.template).as_text()
        findings.extend(check_signature_stable(
            ra, rb, entry="machine_restore"))
        info["entries"].append("machine_restore")
        # donation: policy gate + alias coverage of the donated executor
        findings.extend(check_runner_donation_policy(runner))
        if compiled_text is None:
            compiled = lowered.compile()
            compiled_text = compiled.as_text()
        import jax

        n_prefix = len(jax.tree_util.tree_leaves(runner.cache.device())) \
            + len(jax.tree_util.tree_leaves(runner.physmem.image))
        n_machine = len(jax.tree_util.tree_leaves(runner.machine))
        # jit DCEs unused flat args (e.g. tab.rip_l once uop_lookup's
        # probe verifies against the hash rows' own key limbs), shifting
        # the compiled param numbering the alias map indexes
        kept = getattr(getattr(compiled, "_executable", None),
                       "_kept_var_idx", None)
        dropped = (frozenset(i for i in range(n_prefix + n_machine)
                             if i not in kept)
                   if kept is not None else frozenset())
        findings.extend(check_donation_aliasing(
            compiled_text, runner.machine, n_prefix, entry=entry,
            dropped_args=dropped))
        info["seconds"]["recompile"] = round(time.time() - t0, 1)

    if "parity" in families:
        t0 = time.time()
        findings.extend(check_fused_parity())
        info["seconds"]["parity"] = round(time.time() - t0, 1)
        info["entries"].append("pstep.hot_class vs step.unsupported")

    if "mesh" in families:
        t0 = time.time()
        mesh_findings, mesh_info = run_mesh_family(
            budgets_path=budgets_path, rebaseline=rebaseline)
        findings.extend(mesh_findings)
        counts = mesh_info.get("collective_counts")
        if counts:
            info["collective_counts"] = counts
            info["mesh_entry"] = mesh_info.get("entry")
            for name, value in counts.items():
                registry.gauge("analysis.mesh_collectives").labels(
                    name).set(value)
            if rebaseline:
                measured_budgets[MESH_ENTRY] = {
                    "entry": mesh_info.get("entry"), **counts}
        if mesh_info.get("entry"):
            info["entries"].append(mesh_info["entry"])
        info["seconds"]["mesh"] = round(time.time() - t0, 1)

    if "supervise" in families:
        t0 = time.time()
        findings.extend(check_supervised_seams())
        findings.extend(check_seam_enumeration())
        from wtf_tpu.supervise import SEAM_SITES

        info["entries"].append(
            f"supervise.SEAM_SITES ({len(SEAM_SITES)} seams)")
        info["seconds"]["supervise"] = round(time.time() - t0, 1)

    if "telemetry" in families:
        t0 = time.time()
        findings.extend(check_telemetry_seams())
        from wtf_tpu.supervise import SEAM_SITES

        info["entries"].append(
            f"telemetry over SEAM_SITES ({len(SEAM_SITES)} seams)")
        info["seconds"]["telemetry"] = round(time.time() - t0, 1)

    # contract families (state/transfer/thread/contracts) share ONE
    # pure-AST analysis pass over the tree (analysis/contracts.py on
    # the flow.py engine) — milliseconds, no device work
    contract_fams = ({"state", "transfer", "thread", "contracts"}
                     & set(families))
    if contract_fams:
        from wtf_tpu.analysis import contracts as CT

        t0 = time.time()
        con = CT.load_contracts(contracts_path)
        state_a = CT.analyze_state()
        transfer_a = CT.analyze_transfer()
        thread_a = CT.analyze_thread()
        info["seconds"]["contract-analysis"] = round(time.time() - t0, 1)

        if "state" in families:
            t0 = time.time()
            if not rebaseline:
                findings.extend(CT.check_state_contracts(
                    con, analysis=state_a))
            n_mut = sum(len(a["mutable"]) for a in state_a.values())
            n_cov = sum(len(set(a["mutable"]) & a["covered"])
                        for a in state_a.values())
            registry.gauge("analysis.state_attrs").labels(
                "mutable").set(n_mut)
            registry.gauge("analysis.state_attrs").labels(
                "covered").set(n_cov)
            info["entries"].append(
                f"state surface ({len(state_a)} classes, "
                f"{n_mut} mutable attrs)")
            info["seconds"]["state"] = round(time.time() - t0, 1)

        if "transfer" in families:
            t0 = time.time()
            if not rebaseline:
                findings.extend(CT.check_transfer_seams(
                    con, analysis=transfer_a))
            # the jaxpr census re-traces nothing when the budget family
            # already ran (mega_jaxpr + runner ride along); standalone
            # it is the expensive part, so it hides behind --deep
            census = None
            if deep or rebaseline or mega_jaxpr is not None:
                census = CT.measure_transfer_census(
                    runner=runner, mega_jaxpr=mega_jaxpr)
                info["transfer_census"] = census
                for name, value in census.items():
                    registry.gauge("analysis.transfer_census").labels(
                        name).set(value)
                if rebaseline:
                    measured_budgets[CT.TRANSFER_ENTRY] = {
                        "entry": CT.TRANSFER_CENSUS_ENTRY, **census}
                else:
                    budget = load_budgets(budgets_path).get(
                        CT.TRANSFER_ENTRY, {})
                    findings.extend(CT.check_transfer_census(
                        census, budget,
                        budgets_file=str(budgets_path or BUDGETS_PATH)))
            info["entries"].append(
                "transfer over SEAM_SITES"
                + (" + jaxpr census" if census is not None else ""))
            info["seconds"]["transfer"] = round(time.time() - t0, 1)

        if "thread" in families:
            t0 = time.time()
            if not rebaseline:
                findings.extend(CT.check_thread_contracts(
                    con, analysis=thread_a))
            n_shared = sum(len(a["shared"]) for a in thread_a.values())
            registry.gauge("analysis.thread_shared_attrs").set(n_shared)
            info["entries"].append(
                f"thread roots ({len(thread_a)} classes, "
                f"{n_shared} shared attrs)")
            info["seconds"]["thread"] = round(time.time() - t0, 1)

        if "contracts" in families:
            t0 = time.time()
            if not rebaseline:
                findings.extend(CT.check_contract_hygiene(
                    con, state_a, transfer_a, thread_a))
            for section in CT.SECTIONS:
                n = sum(len(v) for v in con.get(section, {}).values())
                registry.gauge("analysis.contract_entries").labels(
                    section).set(n)
            info["entries"].append("contracts.json hygiene")
            info["seconds"]["contracts"] = round(time.time() - t0, 1)

        if rebaseline and _CONTRACT_FAMILIES & set(families):
            needed = CT.needed_contracts(state_a, transfer_a, thread_a)
            merged = CT.apply_contracts_rebaseline(
                con, needed, allow_regression=allow_regression)
            info["contracts_written"] = str(
                CT.save_contracts(merged, contracts_path))

    if rebaseline and measured_budgets:
        budgets = apply_rebaseline(load_budgets(budgets_path),
                                   measured_budgets,
                                   allow_regression=allow_regression)
        info["budgets_written"] = str(save_budgets(budgets, budgets_path))

    # telemetry: analysis.* namespace + one event per finding
    registry.gauge("analysis.families_run").set(len(families))
    for f in findings:
        registry.counter("analysis.findings").labels(f.rule).inc()
        events.emit("lint-finding", **f.as_dict())
    info["n_findings"] = len(findings)
    return findings, info
