"""Fused-subset parity: pstep's in-kernel opclass claim vs reality.

interp/pstep.py (the Pallas fused-step kernel) and interp/step.py (the
XLA transition function) duplicate instruction semantics by design — the
kernel executes a hot subset, everything else parks to the XLA leg.
Nothing in the runtime keeps the two in sync: a class added to the
kernel's `hot_class` predicate but dropped from (or never present in)
step.py's dispatch would make parked/unparked lanes diverge silently.

This module makes the contract machine-checked, statically:

  1. `FUSED_OPCLASSES` (pstep.py) is the *claim* — the opclass set the
     kernel says it handles in-kernel (subject to per-uop operand
     conditions).
  2. The kernel's actual `hot_class = (...)` expression is AST-parsed
     from the pstep source; its `U.OPC_*` set must equal the claim.
  3. step.py's `unsupported = pre_live & (...)` expression is AST-parsed
     the same way; no claimed class may appear in it, even conditionally
     (conservative: a conditionally-diverting class has no business in
     the always-hot kernel subset).
  4. Every claimed class must be dispatched somewhere in step.py —
     referenced by name — or be a documented implicit no-op (NOP/FENCE
     commit with no writes through step_lane's default paths).

Tests seed violations by passing doctored source text through the
`*_src` parameters; the CLI lint runs against the real files.
"""

from __future__ import annotations

import ast
import inspect
from typing import List, Optional, Set

from wtf_tpu.analysis.findings import Finding

# opclasses step_lane executes through its default no-write commit path
# without ever naming them (hence absent from the source text)
IMPLICIT_NOOPS = frozenset({"NOP", "FENCE"})


def _opc_names(node: ast.AST) -> Set[str]:
    """All `U.OPC_*` attribute references under `node`, without prefix."""
    names: Set[str] = set()
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Attribute)
                and isinstance(sub.value, ast.Name)
                and sub.value.id == "U"
                and sub.attr.startswith("OPC_")):
            names.add(sub.attr[len("OPC_"):])
    return names


def _resolved_opc_names(src: str, target: str) -> Set[str]:
    """OPC_* names reachable from every assignment to `target`, resolving
    intermediate Name bindings transitively (the house style routes
    predicates through locals — `movcr_bad`, `x87_oracle` — and builds
    with `|=` sometimes; a literal-only walk of one RHS would be blind to
    both).  Delegates to the shared dataflow engine (analysis/flow.py),
    where the worklist resolver this family pioneered now lives."""
    from wtf_tpu.analysis import flow

    return flow.resolve_transitive(src, target, _opc_names)


def _module_src(modname: str) -> str:
    import importlib

    return inspect.getsource(importlib.import_module(modname))


def kernel_hot_opclasses(pstep_src: Optional[str] = None) -> Set[str]:
    """Opclasses in pstep's `hot_class` predicate (the kernel's reality),
    intermediate bindings resolved."""
    src = pstep_src or _module_src("wtf_tpu.interp.pstep")
    return _resolved_opc_names(src, "hot_class")


def step_unsupported_opclasses(step_src: Optional[str] = None) -> Set[str]:
    """Opclasses named (even conditionally, even through intermediate
    locals like `movcr_bad`) in step_lane's `unsupported` expression —
    the oracle-diverting set, conservatively."""
    src = step_src or _module_src("wtf_tpu.interp.step")
    return _resolved_opc_names(src, "unsupported")


def step_referenced_opclasses(step_src: Optional[str] = None) -> Set[str]:
    """Every opclass step.py references at all (dispatch superset)."""
    src = step_src or _module_src("wtf_tpu.interp.step")
    return _opc_names(ast.parse(src))


def check_fused_parity(claimed: Optional[Set[str]] = None,
                       pstep_src: Optional[str] = None,
                       step_src: Optional[str] = None) -> List[Finding]:
    """The fused-subset parity rule family.  Returns [] when the claim,
    the kernel predicate, and step.py's dispatch all agree."""
    if claimed is None:
        from wtf_tpu.interp.pstep import FUSED_OPCLASSES

        claimed = set(FUSED_OPCLASSES)
    findings: List[Finding] = []

    kernel = kernel_hot_opclasses(pstep_src)
    for opc in sorted(kernel - claimed):
        findings.append(Finding(
            rule="parity.claim-vs-kernel", entry="interp/pstep.py:hot_class",
            primitive=f"OPC_{opc}",
            message=("kernel hot_class executes an opclass absent from "
                     "FUSED_OPCLASSES — update the claim (and this check's "
                     "step.py cross-checks will vet it)")))
    for opc in sorted(claimed - kernel):
        findings.append(Finding(
            rule="parity.claim-vs-kernel", entry="interp/pstep.py:hot_class",
            primitive=f"OPC_{opc}",
            message=("FUSED_OPCLASSES claims an opclass the kernel "
                     "hot_class predicate never matches — stale claim")))

    unsupported = step_unsupported_opclasses(step_src)
    for opc in sorted(claimed & unsupported):
        findings.append(Finding(
            rule="parity.fused-vs-unsupported",
            entry="interp/step.py:unsupported", primitive=f"OPC_{opc}",
            message=("opclass claimed in-kernel by pstep appears in "
                     "step.py's oracle-diverting `unsupported` expression "
                     "— a parked lane would diverge from the kernel")))

    referenced = step_referenced_opclasses(step_src) | IMPLICIT_NOOPS
    for opc in sorted(claimed - referenced):
        findings.append(Finding(
            rule="parity.fused-vs-dispatch", entry="interp/step.py",
            primitive=f"OPC_{opc}",
            message=("opclass claimed in-kernel by pstep is never "
                     "dispatched by step.py (and is not a documented "
                     "implicit no-op) — the resume leg cannot execute it")))
    return findings
