// Native Windows kernel crash-dump parser (kdmp-parser equivalent).
//
// Role: the reference loads guest physical memory from `mem.dmp` via the
// vendored C++ kdmp-parser (src/libs/kdmp-parser/src/lib/kdmp-parser.h,
// consumed at src/wtf/ram.h:96-152); SURVEY.md §2.6 keeps this component
// native in the rebuild.  This is an original implementation against the
// dump FORMAT (documented by the reference headers and the rekall
// project's reverse engineering): 64-bit full dumps (run list) and BMP
// dumps (present-page bitmap).
//
// C ABI surface (consumed by wtf_tpu/snapshot/kdmp.py over ctypes): the
// parser mmaps the file and returns (pfn, file_offset) pairs; Python
// slices page bytes straight out of its own mmap, so no page data crosses
// the FFI boundary.
//
// Build: g++ -O2 -shared -fPIC kdmp.cc -o libwtfkdmp.so   (see binding).

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr uint64_t kPageSize = 0x1000;

// HEADER64 field offsets (layout fixed by the format; asserts in the
// reference headers pin these same numbers).
constexpr uint64_t kOffSignature = 0x00;       // 'PAGE'
constexpr uint64_t kOffValidDump = 0x04;       // 'DU64'
constexpr uint64_t kOffDirectoryTableBase = 0x10;
constexpr uint64_t kOffBugCheckCode = 0x38;
constexpr uint64_t kOffBugCheckParams = 0x40;  // 4 x u64
constexpr uint64_t kOffPhysmemDesc = 0x88;     // {u32 nruns, pad, u64 npages}
constexpr uint64_t kOffPhysmemRuns = 0x98;     // PHYSMEM_RUN[nruns]
constexpr uint64_t kOffContext = 0x348;        // CONTEXT (0xbb8 bytes)
constexpr uint64_t kOffDumpType = 0xf98;
constexpr uint64_t kOffBmpHeader = 0x2000;     // also: full-dump page data
// BMP_HEADER64 offsets relative to kOffBmpHeader
constexpr uint64_t kOffBmpSignature = 0x00;    // 'SDMP' | 'FDMP'
constexpr uint64_t kOffBmpValidDump = 0x04;    // 'DUMP'
constexpr uint64_t kOffBmpFirstPage = 0x20;
constexpr uint64_t kOffBmpTotalPresent = 0x28;
constexpr uint64_t kOffBmpPages = 0x30;
constexpr uint64_t kOffBmpBitmap = 0x38;

constexpr uint32_t kSigPage = 0x45474150;      // 'PAGE'
constexpr uint32_t kSigDu64 = 0x34365544;      // 'DU64'
constexpr uint32_t kBmpSdmp = 0x504D4453;      // 'SDMP'
constexpr uint32_t kBmpFdmp = 0x504D4446;      // 'FDMP'
constexpr uint32_t kBmpDump = 0x504D5544;      // 'DUMP'

constexpr uint32_t kFullDump = 1;
constexpr uint32_t kBmpDumpType = 5;

struct PagePair {
  uint64_t pfn;
  uint64_t file_offset;
};

struct Parser {
  int fd = -1;
  const uint8_t *map = nullptr;
  uint64_t size = 0;
  uint32_t dump_type = 0;
  std::vector<PagePair> pages;

  ~Parser() {
    if (map) munmap(const_cast<uint8_t *>(map), size);
    if (fd >= 0) close(fd);
  }

  template <typename T> bool read_at(uint64_t off, T *out) const {
    if (off + sizeof(T) > size) return false;
    std::memcpy(out, map + off, sizeof(T));
    return true;
  }

  bool parse() {
    uint32_t sig = 0, valid = 0;
    if (!read_at(kOffSignature, &sig) || !read_at(kOffValidDump, &valid))
      return false;
    if (sig != kSigPage || valid != kSigDu64) return false;
    if (!read_at(kOffDumpType, &dump_type)) return false;
    if (dump_type == kFullDump) return parse_full();
    if (dump_type == kBmpDumpType) return parse_bmp();
    return false;  // KernelDump (partial) not supported, like ram.h's use
  }

  // Full dump: run list; page data packed back-to-back from 0x2000 in run
  // order (holes between runs exist in PFN space, not in the file).
  bool parse_full() {
    uint32_t nruns = 0;
    uint64_t npages = 0;
    if (!read_at(kOffPhysmemDesc, &nruns)) return false;
    if (!read_at(kOffPhysmemDesc + 8, &npages)) return false;
    // 'PAGE'-poisoned descriptor = invalid (reference LooksGood check)
    if (nruns == 0x45474150u || nruns > 4096) return false;
    uint64_t file_off = kOffBmpHeader;
    for (uint32_t i = 0; i < nruns; i++) {
      uint64_t base = 0, count = 0;
      const uint64_t run_off = kOffPhysmemRuns + uint64_t(i) * 16;
      if (!read_at(run_off, &base) || !read_at(run_off + 8, &count))
        return false;
      for (uint64_t p = 0; p < count; p++) {
        if (file_off > size - kPageSize) return false;  // overflow-safe
        pages.push_back({base + p, file_off});
        file_off += kPageSize;
      }
    }
    return true;
  }

  // BMP dump: bitmap of present PFNs; page data packed from FirstPage in
  // ascending PFN order.
  bool parse_bmp() {
    uint32_t sig = 0, valid = 0;
    if (!read_at(kOffBmpHeader + kOffBmpSignature, &sig)) return false;
    if (!read_at(kOffBmpHeader + kOffBmpValidDump, &valid)) return false;
    if ((sig != kBmpSdmp && sig != kBmpFdmp) || valid != kBmpDump)
      return false;
    uint64_t first_page = 0, total_present = 0, bitmap_pages = 0;
    if (!read_at(kOffBmpHeader + kOffBmpFirstPage, &first_page)) return false;
    if (!read_at(kOffBmpHeader + kOffBmpTotalPresent, &total_present))
      return false;
    if (!read_at(kOffBmpHeader + kOffBmpPages, &bitmap_pages)) return false;
    const uint64_t bitmap_bytes = bitmap_pages / 8;
    const uint64_t bitmap_off = kOffBmpHeader + kOffBmpBitmap;
    if (bitmap_bytes > size || bitmap_off > size - bitmap_bytes) return false;
    if (first_page > size) return false;
    uint64_t file_off = first_page;
    for (uint64_t byte_idx = 0; byte_idx < bitmap_bytes; byte_idx++) {
      const uint8_t byte = map[bitmap_off + byte_idx];
      if (!byte) continue;
      for (uint8_t bit = 0; bit < 8; bit++) {
        if (!((byte >> bit) & 1)) continue;
        if (file_off > size - kPageSize) return false;  // overflow-safe
        pages.push_back({byte_idx * 8 + bit, file_off});
        file_off += kPageSize;
      }
    }
    return pages.size() == total_present;
  }
};

}  // namespace

extern "C" {

void *wtf_kdmp_open(const char *path) {
  auto *p = new Parser();
  p->fd = open(path, O_RDONLY);
  if (p->fd < 0) {
    delete p;
    return nullptr;
  }
  struct stat st {};
  if (fstat(p->fd, &st) != 0 || st.st_size < 0x2000) {
    delete p;
    return nullptr;
  }
  p->size = uint64_t(st.st_size);
  p->map = static_cast<const uint8_t *>(
      mmap(nullptr, p->size, PROT_READ, MAP_PRIVATE, p->fd, 0));
  if (p->map == MAP_FAILED) {
    p->map = nullptr;
    delete p;
    return nullptr;
  }
  if (!p->parse()) {
    delete p;
    return nullptr;
  }
  return p;
}

void wtf_kdmp_close(void *h) { delete static_cast<Parser *>(h); }

uint32_t wtf_kdmp_dump_type(void *h) {
  return static_cast<Parser *>(h)->dump_type;
}

uint64_t wtf_kdmp_n_pages(void *h) {
  return static_cast<Parser *>(h)->pages.size();
}

// Fill caller-allocated arrays (n_pages entries each) with the PFN ->
// file-offset index.
void wtf_kdmp_pages(void *h, uint64_t *pfns, uint64_t *offsets) {
  auto *p = static_cast<Parser *>(h);
  for (size_t i = 0; i < p->pages.size(); i++) {
    pfns[i] = p->pages[i].pfn;
    offsets[i] = p->pages[i].file_offset;
  }
}

uint64_t wtf_kdmp_dtb(void *h) {
  uint64_t dtb = 0;
  static_cast<Parser *>(h)->read_at(kOffDirectoryTableBase, &dtb);
  return dtb;
}

uint32_t wtf_kdmp_bugcheck_code(void *h) {
  uint32_t code = 0;
  static_cast<Parser *>(h)->read_at(kOffBugCheckCode, &code);
  return code;
}

// Copy the raw 0xbb8-byte CONTEXT record (register layout is decoded on
// the Python side).
int wtf_kdmp_context(void *h, uint8_t *out, uint64_t out_size) {
  auto *p = static_cast<Parser *>(h);
  const uint64_t ctx_size = 0xf00 - 0x348;
  if (out_size < ctx_size || kOffContext + ctx_size > p->size) return 0;
  std::memcpy(out, p->map + kOffContext, ctx_size);
  return 1;
}

}  // extern "C"
