// Native mutation engine (the honggfuzz-mangle role, SURVEY §2.6: the
// reference's mutator engines are compiled C++ — libFuzzer's
// MutationDispatcher and the vendored honggfuzz mangle port — because at
// fuzzing throughput a per-testcase interpreter-language mutation call
// dominates the host plane).
//
// Original implementation: a deterministic splitmix64-driven op table
// mutating a buffer in place.  The op set mirrors the roles of the
// honggfuzz mangle functions (bit/byte corruption, magic values, block
// shift/expand/shrink, ASCII digits, cross-over splice); it is NOT a port
// of their code.
//
// C ABI (ctypes): wtf_mangle mutates data[0..len) within capacity,
// returns the new length.

#include <cstdint>
#include <cstring>

namespace {

struct Rng {
  uint64_t state;
  explicit Rng(uint64_t seed) : state(seed) {}
  uint64_t next() {
    // splitmix64 (public domain algorithm), matching utils.hashing
    uint64_t z = (state += 0x9E3779B97F4A7C15ull);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
  }
  uint64_t below(uint64_t bound) { return bound ? next() % bound : 0; }
};

const uint8_t kMagic1[] = {0x00, 0x01, 0x7F, 0x80, 0xFF};
const uint16_t kMagic2[] = {0x0000, 0x0001, 0x7FFF, 0x8000, 0xFFFF};
const uint32_t kMagic4[] = {0u, 1u, 0x7FFFFFFFu, 0x80000000u, 0xFFFFFFFFu};
const uint64_t kMagic8[] = {0ull, 1ull, 0x7FFFFFFFFFFFFFFFull,
                            0x8000000000000000ull, 0xFFFFFFFFFFFFFFFFull};

uint64_t mangle_once(uint8_t *data, uint64_t len, uint64_t cap, Rng &rng,
                     const uint8_t *cross, uint64_t cross_len) {
  const uint64_t op = rng.below(11);
  switch (op) {
  case 0: {  // bit flip
    if (!len) break;
    const uint64_t pos = rng.below(len);
    data[pos] ^= uint8_t(1u << rng.below(8));
    break;
  }
  case 1: {  // random byte
    if (!len) break;
    data[rng.below(len)] = uint8_t(rng.next());
    break;
  }
  case 2: {  // increment/decrement
    if (!len) break;
    const uint64_t pos = rng.below(len);
    data[pos] = uint8_t(data[pos] + (rng.below(2) ? 1 : 0xFF));
    break;
  }
  case 3: {  // magic value splice (1/2/4/8 bytes)
    if (!len) break;
    const uint64_t width = 1ull << rng.below(4);
    if (len < width) break;
    const uint64_t pos = rng.below(len - width + 1);
    const uint64_t pick = rng.below(5);
    switch (width) {
    case 1: data[pos] = kMagic1[pick]; break;
    case 2: std::memcpy(data + pos, &kMagic2[pick], 2); break;
    case 4: std::memcpy(data + pos, &kMagic4[pick], 4); break;
    default: std::memcpy(data + pos, &kMagic8[pick], 8); break;
    }
    break;
  }
  case 4: {  // copy block within
    if (len < 2) break;
    const uint64_t src = rng.below(len);
    const uint64_t count = 1 + rng.below(len - src > 32 ? 32 : len - src);
    const uint64_t dst = rng.below(len);
    const uint64_t n = (dst + count > len) ? len - dst : count;
    std::memmove(data + dst, data + src, n);
    break;
  }
  case 5: {  // insert (duplicate) block
    if (!len || len >= cap) break;
    const uint64_t count0 = 1 + rng.below(16);
    const uint64_t count = (len + count0 > cap) ? cap - len : count0;
    const uint64_t pos = rng.below(len);
    std::memmove(data + pos + count, data + pos, len - pos);
    const uint64_t src = rng.below(len);
    for (uint64_t i = 0; i < count; i++) {
      data[pos + i] = data[(src + i) % len];
    }
    len += count;
    break;
  }
  case 6: {  // shrink
    if (len < 2) break;
    const uint64_t start = rng.below(len);
    const uint64_t avail = len - start;
    const uint64_t count = 1 + rng.below(avail > 2 ? avail / 2 : 1);
    std::memmove(data + start, data + start + count, len - start - count);
    len -= count;
    break;
  }
  case 7: {  // ASCII digit rewrite
    if (!len) break;
    const uint64_t pos = rng.below(len);
    data[pos] = uint8_t('0' + rng.below(10));
    break;
  }
  case 8: {  // swap two bytes
    if (len < 2) break;
    const uint64_t a = rng.below(len), b = rng.below(len);
    const uint8_t t = data[a];
    data[a] = data[b];
    data[b] = t;
    break;
  }
  case 9: {  // printable ascii byte
    if (!len) break;
    data[rng.below(len)] = uint8_t(0x20 + rng.below(95));
    break;
  }
  default: {  // cross-over splice from the last coverage-finding input
    if (!cross || !cross_len || !len) break;
    const uint64_t pos = rng.below(len);
    const uint64_t room = cap - pos;
    uint64_t take = rng.below(cross_len + 1);
    if (take > room) take = room;
    std::memcpy(data + pos, cross, take);
    if (pos + take > len) len = pos + take;
    break;
  }
  }
  return len;
}

}  // namespace

extern "C" {

uint64_t wtf_mangle(uint8_t *data, uint64_t len, uint64_t capacity,
                    uint64_t seed, uint32_t n_mutations,
                    const uint8_t *cross, uint64_t cross_len) {
  Rng rng(seed);
  for (uint32_t i = 0; i < n_mutations; i++) {
    len = mangle_once(data, len, capacity, rng, cross, cross_len);
    if (len == 0) {
      data[0] = uint8_t(rng.next());
      len = 1;
    }
  }
  return len;
}

// Batch variant: one call mutates `count` buffers laid out in a flat
// arena (stride = capacity), cutting Python->C transition cost to one
// per DEVICE BATCH instead of one per testcase.  Each item draws its own
// mutation count in [1, max_mutations] so the batch output matches the
// distribution of `count` single calls.
void wtf_mangle_batch(uint8_t *arena, uint64_t *lens, uint64_t capacity,
                      uint64_t count, uint64_t seed, uint32_t max_mutations,
                      const uint8_t *cross, uint64_t cross_len) {
  for (uint64_t i = 0; i < count; i++) {
    Rng seeder(seed + i);
    const uint32_t n =
        1 + uint32_t(seeder.below(max_mutations ? max_mutations : 1));
    lens[i] = wtf_mangle(arena + i * capacity, lens[i], capacity,
                         seeder.next(), n, cross, cross_len);
  }
}

}  // extern "C"
