"""Native (C++) components and their build-on-demand loader.

SURVEY.md §2.6 marks the snapshot parser (and later: hot host-plane pieces)
as native in the rebuild.  Sources live next to this file; binaries build
into `_build/` on first use with the in-image toolchain (g++).  Every
native component has a pure-Python fallback so the framework still works
without a compiler — the native path is the fast path, not a hard
dependency.
"""

from __future__ import annotations

import subprocess
from pathlib import Path
from typing import Optional

_DIR = Path(__file__).parent
_BUILD = _DIR / "_build"


def build_library(name: str, sources: list[str],
                  extra_flags: Optional[list[str]] = None) -> Optional[Path]:
    """Compile `sources` (relative to native/) into _build/lib<name>.so;
    returns the path, a cached build, or None when no compiler exists.
    Rebuilds when any source is newer than the binary."""
    out = _BUILD / f"lib{name}.so"
    srcs = [_DIR / s for s in sources]
    if out.exists() and all(
            s.stat().st_mtime <= out.stat().st_mtime for s in srcs):
        return out
    _BUILD.mkdir(exist_ok=True)
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
           *(extra_flags or []),
           *[str(s) for s in srcs], "-o", str(out)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return None
    return out
