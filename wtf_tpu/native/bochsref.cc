// Measured stand-in for the bochs-role denominator (VERDICT r4 item 6).
//
// The reference's slowest backend is bochscpu: a single-threaded C++
// fetch-decode-execute interpreter whose hot loop pays, per instruction,
// a coverage-set insert and hook dispatch (reference
// bochscpu_backend.cc:476-548), and per testcase a dirty-page restore.
// That library is a PREBUILT Rust/C++ artifact the reference downloads at
// build time — it cannot be built in this zero-egress environment, so
// `bench.py`'s vs_baseline was a modeled constant for four rounds.
//
// This file replaces the model with a measurement: a minimal C++
// interpreter of the demo_tlv guest running the SAME snapshot bytes, the
// same per-instruction coverage insert (open-addressed set, robin-map
// class), the same per-exec byte-exact restore.  It is deliberately
// FASTER than real bochs — tiny decoder, flat span memory instead of
// paging+TLB, no hook chain — so the exec/s it measures is an UPPER
// bound on the bochs role and the vs_baseline computed from it is
// conservative for the TPU side.
//
// Instruction coverage: the x86-64 subset MSVC-ish codegen and the
// demo_tlv parser use (REX, ModRM+SIB, mov/movzx/lea/add/sub/cmp/test/
// xor/inc/dec/push/pop/jcc/jmp/ret, AL-imm forms).  Unknown opcodes and
// unmapped fetches end the testcase as a crash — exactly what the
// fuzzed workload does when the planted stack smash fires.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Span {
  uint64_t base;
  uint64_t size;
  uint8_t *data;
};

struct DirtyByte {
  uint8_t *p;
  uint8_t old;
};

// open-addressed coverage set (the robin-map role): pow2 table, linear
// probe, epoch-tagged slots so the per-testcase clear (LastNewCoverage)
// is O(1) like clearing a small robin_map, not a full-table memset
struct CovSet {
  std::vector<uint64_t> slots;   // rip per slot
  std::vector<uint32_t> epochs;  // slot valid iff epochs[i] == epoch
  uint32_t epoch = 1;
  size_t mask;
  explicit CovSet(size_t pow2)
      : slots(pow2, 0), epochs(pow2, 0), mask(pow2 - 1) {}
  inline void insert(uint64_t rip) {
    size_t h = (rip * 0x9E3779B97F4A7C15ull) >> 40 & mask;
    while (true) {
      if (epochs[h] != epoch) {
        epochs[h] = epoch;
        slots[h] = rip;
        return;
      }
      if (slots[h] == rip) return;
      h = (h + 1) & mask;
    }
  }
  inline void clear() { epoch++; }
};

struct Vm {
  std::vector<Span> spans;
  std::vector<uint8_t> backing;
  std::vector<DirtyByte> dirty;
  CovSet cov{1 << 16};
  uint64_t gpr[16];
  uint64_t rip;
  bool zf, cf, sf, of;

  uint8_t *ptr(uint64_t gva, size_t len) {
    for (auto &s : spans)
      if (gva >= s.base && gva + len <= s.base + s.size)
        return s.data + (gva - s.base);
    return nullptr;
  }
};

inline uint64_t rd(Vm &vm, uint8_t *p, int size) {
  uint64_t v = 0;
  std::memcpy(&v, p, size);
  return v;
}

inline void wr(Vm &vm, uint8_t *p, int size, uint64_t v) {
  for (int i = 0; i < size; i++) vm.dirty.push_back({p + i, p[i]});
  std::memcpy(p, &v, size);
}

struct Mod {
  uint64_t gva;     // effective address (mod != 3)
  int reg;          // ModRM.reg (REX.R applied)
  int rm;           // ModRM.rm (REX.B applied); -1 when memory form
  int len;          // bytes consumed (modrm+sib+disp)
};

// decode ModRM+SIB+disp at code[0]; rex bits already split out
bool modrm(Vm &vm, const uint8_t *code, int rexr, int rexx, int rexb,
           Mod *out) {
  uint8_t m = code[0];
  int mod = m >> 6, reg = ((m >> 3) & 7) | (rexr << 3), rm = m & 7;
  int len = 1;
  uint64_t addr = 0;
  if (mod == 3) {
    *out = {0, reg, rm | (rexb << 3), 1};
    return true;
  }
  if (rm == 4) {  // SIB
    uint8_t sib = code[1];
    len = 2;
    int scale = sib >> 6, idx = ((sib >> 3) & 7) | (rexx << 3),
        base = (sib & 7) | (rexb << 3);
    if (idx != 4) addr += vm.gpr[idx] << scale;
    if ((sib & 7) == 5 && mod == 0) {
      addr += (int32_t)rd(vm, (uint8_t *)code + 2, 4);
      len += 4;
    } else {
      addr += vm.gpr[base];
    }
  } else if (rm == 5 && mod == 0) {  // rip-relative (disp applied later)
    addr = (int32_t)rd(vm, (uint8_t *)code + 1, 4);
    len = 5;  // caller adds rip-after
    *out = {addr, reg, -2, len};
    return true;
  } else {
    addr = vm.gpr[rm | (rexb << 3)];
  }
  if (mod == 1) {
    addr += (int8_t)code[len];
    len += 1;
  } else if (mod == 2) {
    addr += (int32_t)rd(vm, (uint8_t *)code + len, 4);
    len += 4;
  }
  *out = {addr, reg, -1, len};
  return true;
}

inline void flags_sub(Vm &vm, uint64_t a, uint64_t b, uint64_t r, int bits) {
  uint64_t msb = 1ull << (bits - 1);
  uint64_t mask = bits == 64 ? ~0ull : (1ull << bits) - 1;
  a &= mask; b &= mask; r &= mask;
  vm.zf = r == 0;
  vm.cf = a < b;
  vm.sf = (r & msb) != 0;
  vm.of = (((a ^ b) & (a ^ r)) & msb) != 0;
}

inline void flags_add(Vm &vm, uint64_t a, uint64_t b, uint64_t r, int bits) {
  uint64_t msb = 1ull << (bits - 1);
  uint64_t mask = bits == 64 ? ~0ull : (1ull << bits) - 1;
  a &= mask; b &= mask; r &= mask;
  vm.zf = r == 0;
  vm.cf = r < a;
  vm.sf = (r & msb) != 0;
  vm.of = (((a ^ r) & (b ^ r)) & msb) != 0;
}

inline void flags_logic(Vm &vm, uint64_t r, int bits) {
  uint64_t msb = 1ull << (bits - 1);
  uint64_t mask = bits == 64 ? ~0ull : (1ull << bits) - 1;
  r &= mask;
  vm.zf = r == 0;
  vm.cf = false;
  vm.sf = (r & msb) != 0;
  vm.of = false;
}

enum Result { RUNNING = 0, FINISHED = 1, CRASHED = 2, TIMEDOUT = 3 };

// one instruction; returns RUNNING/terminal
int step(Vm &vm, uint64_t finish) {
  if (vm.rip == finish) return FINISHED;
  uint8_t *code = vm.ptr(vm.rip, 16);
  if (!code) return CRASHED;
  vm.cov.insert(vm.rip);  // the per-instruction hook cost (bochs :479-505)

  const uint8_t *c = code;
  int rexw = 0, rexr = 0, rexx = 0, rexb = 0;
  if ((*c & 0xF0) == 0x40) {
    rexw = (*c >> 3) & 1; rexr = (*c >> 2) & 1;
    rexx = (*c >> 1) & 1; rexb = *c & 1;
    c++;
  }
  int osz = rexw ? 64 : 32;
  int osz_b = osz / 8;
  Mod m;
  uint8_t op = *c++;
  auto finish_len = [&](int extra) {
    vm.rip += (c - code) + extra;
  };
  auto mem = [&](int sz) -> uint8_t * {
    return vm.ptr(m.gva, sz);
  };

  switch (op) {
    case 0x50: case 0x51: case 0x52: case 0x53:
    case 0x54: case 0x55: case 0x56: case 0x57: {  // push r64
      int r = (op - 0x50) | (rexb << 3);
      vm.gpr[4] -= 8;
      uint8_t *p = vm.ptr(vm.gpr[4], 8);
      if (!p) return CRASHED;
      wr(vm, p, 8, vm.gpr[r]);
      finish_len(0);
      return RUNNING;
    }
    case 0x58: case 0x59: case 0x5A: case 0x5B:
    case 0x5C: case 0x5D: case 0x5E: case 0x5F: {  // pop r64
      int r = (op - 0x58) | (rexb << 3);
      uint8_t *p = vm.ptr(vm.gpr[4], 8);
      if (!p) return CRASHED;
      vm.gpr[r] = rd(vm, p, 8);
      vm.gpr[4] += 8;
      finish_len(0);
      return RUNNING;
    }
    case 0x01: case 0x29: case 0x31: case 0x39: case 0x85: {  // op r/m,r
      if (!modrm(vm, c, rexr, rexx, rexb, &m)) return CRASHED;
      c += m.len;
      uint64_t b = vm.gpr[m.reg];
      uint64_t a;
      uint8_t *p = nullptr;
      if (m.rm >= 0) {
        a = vm.gpr[m.rm];
      } else {
        p = mem(osz_b);
        if (!p) return CRASHED;
        a = rd(vm, p, osz_b);
      }
      uint64_t r;
      if (op == 0x01) { r = a + b; flags_add(vm, a, b, r, osz); }
      else if (op == 0x29) { r = a - b; flags_sub(vm, a, b, r, osz); }
      else if (op == 0x31) { r = a ^ b; flags_logic(vm, r, osz); }
      else if (op == 0x39) { r = a - b; flags_sub(vm, a, b, r, osz);
                             finish_len(0); return RUNNING; }
      else { r = a & b; flags_logic(vm, r, osz);
             finish_len(0); return RUNNING; }
      if (osz == 32) r &= 0xFFFFFFFFull;
      if (m.rm >= 0) vm.gpr[m.rm] = r;
      else wr(vm, p, osz_b, r);
      finish_len(0);
      return RUNNING;
    }
    case 0x83: {  // grp1 r/m, imm8
      if (!modrm(vm, c, rexr, rexx, rexb, &m)) return CRASHED;
      c += m.len;
      int64_t imm = (int8_t)*c;
      c++;
      uint64_t a;
      uint8_t *p = nullptr;
      if (m.rm >= 0) a = vm.gpr[m.rm];
      else { p = mem(osz_b); if (!p) return CRASHED; a = rd(vm, p, osz_b); }
      uint64_t r = a;
      switch (m.reg & 7) {
        case 0: r = a + imm; flags_add(vm, a, imm, r, osz); break;
        case 5: r = a - imm; flags_sub(vm, a, imm, r, osz); break;
        case 7: flags_sub(vm, a, imm, a - imm, osz);
                finish_len(0); return RUNNING;
        case 4: r = a & imm; flags_logic(vm, r, osz); break;
        case 1: r = a | imm; flags_logic(vm, r, osz); break;
        case 6: r = a ^ imm; flags_logic(vm, r, osz); break;
        default: return CRASHED;
      }
      if (osz == 32) r &= 0xFFFFFFFFull;
      if (m.rm >= 0) vm.gpr[m.rm] = r;
      else wr(vm, p, osz_b, r);
      finish_len(0);
      return RUNNING;
    }
    case 0x89: case 0x8B: {  // mov r/m,r / mov r,r/m
      if (!modrm(vm, c, rexr, rexx, rexb, &m)) return CRASHED;
      c += m.len;
      if (op == 0x89) {
        if (m.rm >= 0) {
          vm.gpr[m.rm] = osz == 64 ? vm.gpr[m.reg]
                                   : (vm.gpr[m.reg] & 0xFFFFFFFFull);
        } else {
          uint8_t *p = mem(osz_b);
          if (!p) return CRASHED;
          wr(vm, p, osz_b, vm.gpr[m.reg]);
        }
      } else {
        uint64_t v;
        if (m.rm >= 0) v = vm.gpr[m.rm];
        else {
          uint8_t *p = mem(osz_b);
          if (!p) return CRASHED;
          v = rd(vm, p, osz_b);
        }
        vm.gpr[m.reg] = osz == 64 ? v : (v & 0xFFFFFFFFull);
      }
      finish_len(0);
      return RUNNING;
    }
    case 0x88: case 0x8A: {  // mov r/m8, r8 / mov r8, r/m8 (low bytes)
      if (!modrm(vm, c, rexr, rexx, rexb, &m)) return CRASHED;
      c += m.len;
      if (op == 0x88) {
        uint8_t v = vm.gpr[m.reg] & 0xFF;
        if (m.rm >= 0) vm.gpr[m.rm] = (vm.gpr[m.rm] & ~0xFFull) | v;
        else { uint8_t *p = mem(1); if (!p) return CRASHED; wr(vm, p, 1, v); }
      } else {
        uint8_t v;
        if (m.rm >= 0) v = vm.gpr[m.rm] & 0xFF;
        else { uint8_t *p = mem(1); if (!p) return CRASHED; v = *p; }
        vm.gpr[m.reg] = (vm.gpr[m.reg] & ~0xFFull) | v;
      }
      finish_len(0);
      return RUNNING;
    }
    case 0x8D: {  // lea
      if (!modrm(vm, c, rexr, rexx, rexb, &m)) return CRASHED;
      c += m.len;
      if (m.rm >= 0) return CRASHED;
      vm.gpr[m.reg] = osz == 64 ? m.gva : (m.gva & 0xFFFFFFFFull);
      finish_len(0);
      return RUNNING;
    }
    case 0x0F: {
      uint8_t op2 = *c++;
      if (op2 == 0xB6) {  // movzx r, r/m8
        if (!modrm(vm, c, rexr, rexx, rexb, &m)) return CRASHED;
        c += m.len;
        uint8_t v;
        if (m.rm >= 0) v = vm.gpr[m.rm] & 0xFF;
        else { uint8_t *p = mem(1); if (!p) return CRASHED; v = *p; }
        vm.gpr[m.reg] = v;
        finish_len(0);
        return RUNNING;
      }
      return CRASHED;
    }
    case 0x3C: {  // cmp al, imm8
      uint8_t imm = *c++;
      flags_sub(vm, vm.gpr[0] & 0xFF, imm, (vm.gpr[0] & 0xFF) - imm, 8);
      finish_len(0);
      return RUNNING;
    }
    case 0xFF: {  // grp5: inc/dec r/m
      if (!modrm(vm, c, rexr, rexx, rexb, &m)) return CRASHED;
      c += m.len;
      if (m.rm < 0) return CRASHED;
      uint64_t a = vm.gpr[m.rm];
      if ((m.reg & 7) == 0) {
        uint64_t r = a + 1;
        bool keep_cf = vm.cf;
        flags_add(vm, a, 1, r, osz);
        vm.cf = keep_cf;
        vm.gpr[m.rm] = osz == 64 ? r : (r & 0xFFFFFFFFull);
      } else if ((m.reg & 7) == 1) {
        uint64_t r = a - 1;
        bool keep_cf = vm.cf;
        flags_sub(vm, a, 1, r, osz);
        vm.cf = keep_cf;
        vm.gpr[m.rm] = osz == 64 ? r : (r & 0xFFFFFFFFull);
      } else {
        return CRASHED;
      }
      finish_len(0);
      return RUNNING;
    }
    case 0xEB: {  // jmp rel8
      int8_t d = (int8_t)*c++;
      finish_len(0);
      vm.rip += d;
      return RUNNING;
    }
    case 0x72: case 0x73: case 0x74: case 0x75:
    case 0x76: case 0x77: case 0x78: case 0x79: {  // jcc rel8
      int8_t d = (int8_t)*c++;
      bool take = false;
      switch (op) {
        case 0x72: take = vm.cf; break;
        case 0x73: take = !vm.cf; break;
        case 0x74: take = vm.zf; break;
        case 0x75: take = !vm.zf; break;
        case 0x76: take = vm.cf || vm.zf; break;
        case 0x77: take = !(vm.cf || vm.zf); break;
        case 0x78: take = vm.sf; break;
        case 0x79: take = !vm.sf; break;
      }
      finish_len(0);
      if (take) vm.rip += d;
      return RUNNING;
    }
    case 0xC3: {  // ret
      uint8_t *p = vm.ptr(vm.gpr[4], 8);
      if (!p) return CRASHED;
      vm.rip = rd(vm, p, 8);
      vm.gpr[4] += 8;
      return RUNNING;
    }
    default:
      return CRASHED;  // outside the workload subset = the crash path
  }
}

}  // namespace

extern "C" {

// spans: n flat guest-memory windows (copied; the vm owns its backing)
void *bochsref_create(const uint64_t *bases, const uint64_t *sizes,
                      const uint8_t *const *datas, int n) {
  Vm *vm = new Vm();
  size_t total = 0;
  for (int i = 0; i < n; i++) total += sizes[i];
  vm->backing.resize(total);
  size_t off = 0;
  for (int i = 0; i < n; i++) {
    std::memcpy(vm->backing.data() + off, datas[i], sizes[i]);
    vm->spans.push_back({bases[i], sizes[i], vm->backing.data() + off});
    off += sizes[i];
  }
  return vm;
}

void bochsref_destroy(void *p) { delete (Vm *)p; }

// The per-testcase loop mirrors RunTestcaseAndRestore (client.cc:88-180):
// insert testcase -> run to finish/crash/limit (per-instruction coverage
// insert) -> byte-exact restore of every dirty location.  Returns total
// executed testcases; fills instr/crash counters.
void bochsref_campaign(void *p, uint64_t rip0, uint64_t rsp0,
                       uint64_t input_gva, uint64_t finish_gva,
                       uint64_t scratch_gva, const uint8_t *tcs,
                       const uint32_t *lens, int n_tc, uint64_t limit,
                       uint64_t repeat, uint64_t *out_execs,
                       uint64_t *out_instr, uint64_t *out_crashes) {
  Vm &vm = *(Vm *)p;
  uint64_t execs = 0, instr = 0, crashes = 0;
  const uint32_t *off = new uint32_t[n_tc];
  {
    uint32_t *o = (uint32_t *)off;
    uint32_t cur = 0;
    for (int i = 0; i < n_tc; i++) { o[i] = cur; cur += lens[i]; }
  }
  for (uint64_t rep = 0; rep < repeat; rep++) {
    for (int t = 0; t < n_tc; t++) {
      // insert testcase (a dirty write like VirtWriteDirty)
      uint8_t *in = vm.ptr(input_gva, lens[t]);
      if (in) {
        for (uint32_t i = 0; i < lens[t]; i++)
          vm.dirty.push_back({in + i, in[i]});
        std::memcpy(in, tcs + off[t], lens[t]);
      }
      std::memset(vm.gpr, 0, sizeof vm.gpr);
      vm.gpr[4] = rsp0;
      vm.gpr[6] = input_gva;   // rsi
      vm.gpr[2] = lens[t];     // rdx
      vm.gpr[15] = scratch_gva;
      vm.rip = rip0;
      vm.zf = vm.cf = vm.sf = vm.of = false;
      int res = RUNNING;
      uint64_t steps = 0;
      while (res == RUNNING) {
        res = step(vm, finish_gva);
        if (res == RUNNING && ++steps >= limit) res = TIMEDOUT;
      }
      instr += steps;
      if (res == CRASHED) crashes++;
      execs++;
      // restore: undo the dirty log newest-first (bochs rewrites dirty
      // GPAs from the dump, :730-797; byte-exact undo is the same
      // effect and FASTER, keeping the denominator conservative)
      for (size_t i = vm.dirty.size(); i-- > 0;)
        *vm.dirty[i].p = vm.dirty[i].old;
      vm.dirty.clear();
      vm.cov.clear();
    }
  }
  delete[] off;
  *out_execs = execs;
  *out_instr = instr;
  *out_crashes = crashes;
}

}  // extern "C"
